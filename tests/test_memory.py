"""Memory-observability coverage: the live-buffer ledger balances, the
memory plan brackets the measured watermark, M001 OOM forensics name the
top holders in the black box, and the perf/memory regression sentry
(tools/perf_diff.py) gates on injected regressions."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags, profiler
from paddle_tpu.observability import blackbox, memory, telemetry
from paddle_tpu.resilience import chaos, retry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _quiet_memory():
    """Memory/forensics subsystems off and empty around every test; the
    shared executable registry is purged so per-executable one-shots
    (plan registration, kind classification) run inside the test."""
    import paddle_tpu.executor as executor_mod

    executor_mod._shared_executables.clear()
    telemetry.enable(False)
    telemetry.reset(flops=True)
    memory.reset()
    blackbox.disable()
    blackbox.reset()
    chaos.disable()
    yield
    chaos.disable()
    blackbox.disable()
    blackbox.reset()
    telemetry.enable(False)
    telemetry.reset(flops=True)
    memory.reset()
    flags.set_flag("dispatch_retries", 0)


def _mlp_program(seed=13):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [32])
        label = fluid.layers.data("label", [1], dtype="int64")
        h = fluid.layers.fc(x, size=64, act="relu")
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        # Momentum: velocity accumulators exercise the opt_state kind
        fluid.optimizer.Momentum(
            learning_rate=0.1, momentum=0.9).minimize(loss)
    return main, startup, loss


def _feed(bs=8):
    r = np.random.RandomState(7)
    return {"x": r.rand(bs, 32).astype("float32"),
            "label": r.randint(0, 4, (bs, 1)).astype("int64")}


# ---------------------------------------------------------------------------
# ledger mechanics
# ---------------------------------------------------------------------------


def test_ledger_tracks_replaces_and_balances():
    memory.track("w", 1000, "param", "cpu:0")
    memory.track("m", 500, "opt_state", "cpu:0")
    assert memory.live_bytes() == 1500
    # re-tracking the same key REPLACES (donation successor semantics)
    memory.track("w", 2000, "param", "cpu:0")
    assert memory.live_bytes() == 2500
    assert memory.live_by_kind() == {"param": 2000, "opt_state": 500}
    assert memory.take_step_peak() == 2500
    # every byte registered comes back out
    assert memory.drop("w", "param", "cpu:0")
    assert memory.drop("m", "opt_state", "cpu:0")
    assert memory.live_bytes() == 0
    # double-drop is a tolerated no-op, not a negative balance
    assert not memory.drop("w", "param", "cpu:0")
    assert memory.live_bytes() == 0


def test_top_holders_ordered():
    memory.track("big", 300, "activation", "cpu:0")
    memory.track("mid", 200, "feed", "cpu:0")
    memory.track("small", 100, "param", "cpu:0")
    top = memory.top_holders(2)
    assert [h["name"] for h in top] == ["big", "mid"]
    assert top[0] == {"name": "big", "kind": "activation",
                      "device": "cpu:0", "bytes": 300}


def test_executor_ledger_balance_after_steps():
    """After sync steps: feeds and fetched activations are fully
    released; what stays live is exactly the scope's persistable state
    (params + optimizer accumulators), byte for byte."""
    main, startup, loss = _mlp_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    telemetry.enable(True)
    for _ in range(3):
        exe.run(main, feed=_feed(), fetch_list=[loss])
    kinds = memory.live_by_kind()
    assert set(kinds) == {"param", "opt_state"}, kinds
    assert kinds["param"] > 0 and kinds["opt_state"] > 0
    # cross-check against the scope's actual arrays
    scope = fluid.global_scope()
    expected = 0
    for (_dev, _kind, name), b in list(memory._live.items()):
        val = scope.get_value(name)
        assert val is not None, name
        assert b == val.nbytes, (name, b, val.nbytes)
        expected += val.nbytes
    assert memory.live_bytes() == expected
    # per-step record carries the watermark + the plan's prediction
    rec = telemetry.step_records()[-1]
    assert rec["peak_hbm_bytes"] >= memory.live_bytes()
    assert rec["predicted_peak_bytes"] > 0
    assert rec["hbm_top"], "records must name the top holders"


def test_async_fetch_releases_on_result():
    main, startup, loss = _mlp_program(seed=14)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    telemetry.enable(True)
    handle = exe.run_async(main, feed=_feed(), fetch_list=[loss])
    assert "activation" in memory.live_by_kind()
    handle.result()
    assert "activation" not in memory.live_by_kind()


def test_checkpoint_snapshot_enters_and_leaves_ledger(tmp_path):
    from paddle_tpu.resilience.checkpoint import CheckpointManager

    main, startup, loss = _mlp_program(seed=15)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    telemetry.enable(True)
    exe.run(main, feed=_feed(), fetch_list=[loss])
    mgr = CheckpointManager(str(tmp_path), executor=exe,
                            main_program=main)
    mgr.save(step=1)
    # the host snapshot was tracked under 'cache' during the write and
    # released when it completed — the sync save returns after both
    assert "cache" not in memory.live_by_kind()


# ---------------------------------------------------------------------------
# predicted-memory planning
# ---------------------------------------------------------------------------


def test_memory_plan_shape_and_ordering():
    main, _startup, loss = _mlp_program(seed=16)
    plan = main.memory_plan(feed_shapes={"x": (8, 32), "label": (8, 1)},
                            fetch_names=[loss.name])
    assert plan.peak_bytes > 0 and np.isfinite(plan.peak_bytes)
    assert plan.n_ops == len(main.global_block().ops)
    assert 0 <= plan.peak_op_idx < plan.n_ops
    assert plan.peak_bytes == max(plan.per_op_bytes)
    assert all(b >= 0 for b in plan.per_op_bytes)
    top = plan.top(5)
    assert top and all(top[i][1] >= top[i + 1][1]
                       for i in range(len(top) - 1)), "top must be sorted"
    # params are resident the whole step: the peak can't be below them
    param_bytes = sum(
        b for _n, b in top if _n.endswith(".w_0") or _n.endswith(".b_0"))
    assert plan.peak_bytes >= param_bytes
    d = plan.as_dict()
    assert d["peak_bytes"] == plan.peak_bytes and d["top_live"]


def test_memory_plan_within_2x_of_measured():
    """Predicted (liveness-sweep) vs measured (ledger watermark) peak on
    the CPU backend: the plan adds transient activations/grads the
    ledger never sees, the ledger adds buffers XLA already freed — both
    views must still land within 2x of each other or one of them is
    lying."""
    main, startup, loss = _mlp_program(seed=17)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    telemetry.enable(True)
    for _ in range(2):
        exe.run(main, feed=_feed(), fetch_list=[loss])
    ms = profiler.memory_stats()
    assert ms["measured_peak_bytes"] and ms["predicted_peak_bytes"]
    ratio = ms["predicted_peak_bytes"] / ms["measured_peak_bytes"]
    assert 0.5 <= ratio <= 2.0, (
        "predicted/measured peak ratio %.3f outside [0.5, 2]" % ratio)
    assert ms["predicted_plan"]["peak_op_type"]
    assert ms["top_holders"]


def test_every_golden_model_reports_memory():
    """Acceptance: every golden model reports BOTH predicted and
    measured peak HBM through profiler.memory_stats() on the CPU
    backend, and the plan's curve is well-formed."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from golden_models import GOLDEN_MODELS, build_golden
    from paddle_tpu.core.scope import Scope

    for name in sorted(GOLDEN_MODELS):
        telemetry.reset(flops=True)
        memory.reset()
        with fluid.scope_guard(Scope()):
            program, _feed_names, fetch, feed, exe = build_golden(name)
            telemetry.enable(True)
            exe.run(program, feed=feed, fetch_list=[fetch.name])
            ms = profiler.memory_stats()
            telemetry.enable(False)
        assert ms["measured_peak_bytes"], "%s: no measured peak" % name
        assert ms["predicted_peak_bytes"], "%s: no predicted peak" % name
        assert np.isfinite(ms["predicted_peak_bytes"]), name
        plan = ms["predicted_plan"]
        assert plan["peak_bytes"] == ms["predicted_peak_bytes"], name
        assert plan["top_live"], "%s: plan names no live tensors" % name


# ---------------------------------------------------------------------------
# M001 OOM forensics
# ---------------------------------------------------------------------------


def test_oom_classified_never_transient():
    assert not retry.is_transient(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating "
                     "123456 bytes"))
    assert not retry.is_transient(chaos.ChaosOOMError(
        "RESOURCE_EXHAUSTED: chaos: injected out-of-memory at x"))
    assert not retry.is_transient(MemoryError())
    # the transient family still retries
    assert retry.is_transient(RuntimeError("UNAVAILABLE: peer reset"))
    assert retry.is_transient(retry.TransientError("flaky"))


def test_oom_burns_no_retry_budget():
    attempts = []

    def dies_oom():
        attempts.append(1)
        raise chaos.ChaosOOMError(
            "RESOURCE_EXHAUSTED: chaos: injected out-of-memory at t")

    with pytest.raises(chaos.ChaosOOMError):
        retry.call(dies_oom, origin="test", retries=3)
    assert len(attempts) == 1, (
        "a deterministic OOM must die on the FIRST attempt, "
        "ran %d" % len(attempts))


def test_chaos_skip_param_defers_deterministically():
    chaos.configure("oom@site=exec.dispatch,skip=2,n=1")
    chaos.fault("exec.dispatch")  # visit 1: skipped
    chaos.fault("exec.dispatch")  # visit 2: skipped
    with pytest.raises(chaos.ChaosOOMError):
        chaos.fault("exec.dispatch")  # visit 3: fires
    assert chaos.fires("exec.dispatch") == 1
    chaos.fault("exec.dispatch")  # budget n=1 exhausted: quiet


def test_m001_blackbox_dump_names_top_holders(tmp_path):
    """An induced OOM at dispatch produces a black-box dump whose M001
    diagnostic names the top-3 live-buffer holders and the predicted
    peak, and tools/blackbox_dump.py surfaces it with exit code 4."""
    import blackbox_dump

    box = str(tmp_path / "box.json")
    main, startup, loss = _mlp_program(seed=18)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    telemetry.enable(True)
    exe.run(main, feed=_feed(), fetch_list=[loss])  # populate the ledger
    blackbox.enable(box, handlers=False)
    chaos.configure("oom@site=exec.dispatch,n=1")
    with pytest.raises(memory.MemoryExhaustedError) as ei:
        exe.run(main, feed=_feed(), fetch_list=[loss])
    chaos.disable()
    diag = ei.value.diagnostic
    assert diag.rule == "M001" and diag.severity == "error"
    assert "RESOURCE_EXHAUSTED" in str(ei.value)
    with open(box) as f:
        snap = json.load(f)
    d = snap["oom_diagnostic"]
    assert d["rule"] == "M001"
    holders = d["top_holders"]
    assert len(holders) == 3, holders
    assert holders[0]["bytes"] >= holders[1]["bytes"] >= \
        holders[2]["bytes"]
    assert d["predicted_peak_bytes"] > 0
    assert any(e["kind"] == "oom_diagnostic" for e in snap["events"])
    rc = blackbox_dump.main([box])
    assert rc == 4, "blackbox_dump must exit 4 on an M001 dump"


def test_oom_not_enriched_when_not_oom():
    """An ordinary dispatch failure must NOT be rebranded M001."""
    main, startup, loss = _mlp_program(seed=19)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    chaos.configure("compile@site=exec.dispatch,n=1")
    with pytest.raises(chaos.ChaosTransientError):
        exe.run(main, feed=_feed(), fetch_list=[loss])


# ---------------------------------------------------------------------------
# perf/memory regression sentry
# ---------------------------------------------------------------------------


def _bench_artifact(path, fresh_compiles=4, p50=50.0, peak=1000000,
                    predicted=2000000, value=10.0):
    rec = {"models": {"resnet50": {
        "value": value, "unit": "images/sec",
        "step_ms": {"p50": p50, "p95": p50 * 4},
        "compile_seconds_cold": 10.0,
        "exec_cache": {"fresh_compiles": fresh_compiles},
        "peak_hbm_bytes": peak, "predicted_peak_bytes": predicted,
    }}}
    with open(path, "w") as f:
        f.write(json.dumps(rec) + "\n")
    return str(path)


def test_perf_diff_clean_and_fresh_compile_regression(tmp_path):
    import perf_diff

    base = _bench_artifact(tmp_path / "base.json")
    same = _bench_artifact(tmp_path / "same.json")
    # identical artifacts: clean (returns, no SystemExit)
    perf_diff.main([same, "--baseline", base])
    # +30% fresh compiles: deterministic counter, must gate HARD even
    # though it sits inside any noise band
    worse = _bench_artifact(tmp_path / "worse.json",
                            fresh_compiles=int(4 * 1.3) + 1)
    with pytest.raises(SystemExit) as ei:
        perf_diff.main([worse, "--baseline", base])
    assert ei.value.code == 1


def test_perf_diff_timing_noise_band(tmp_path):
    import perf_diff

    base = _bench_artifact(tmp_path / "base.json")
    # +20% p50 sits inside the default 25% band: noise, not regression
    noisy = _bench_artifact(tmp_path / "noisy.json", p50=60.0)
    perf_diff.main([noisy, "--baseline", base])
    # +60% p50 is a regression
    slow = _bench_artifact(tmp_path / "slow.json", p50=80.0)
    with pytest.raises(SystemExit) as ei:
        perf_diff.main([slow, "--baseline", base])
    assert ei.value.code == 1
    # a higher predicted peak is deterministic: gates hard at any size
    fat = _bench_artifact(tmp_path / "fat.json", predicted=2000001)
    with pytest.raises(SystemExit) as ei:
        perf_diff.main([fat, "--baseline", base])
    assert ei.value.code == 1


def test_perf_diff_budget_mode(tmp_path):
    import perf_diff

    cand = _bench_artifact(tmp_path / "cand.json")
    budgets = tmp_path / "budgets.json"
    budgets.write_text(json.dumps({
        "band": 0.5,
        "models": {"resnet50": {
            "fresh_compiles": {"max": 4, "why": "seed"},
            "predicted_peak_bytes": {"max": 2000000, "why": "seed"},
            "step_ms_p50": {"max": 50.0, "why": "seed"},
            "throughput": {"min": 10.0, "why": "seed"},
        }}}))
    perf_diff.main([cand, "--budgets", str(budgets)])
    over = _bench_artifact(tmp_path / "over.json", fresh_compiles=5)
    with pytest.raises(SystemExit) as ei:
        perf_diff.main([over, "--budgets", str(budgets)])
    assert ei.value.code == 1


def test_perf_diff_budget_mode_fails_on_missing_metric(tmp_path):
    """A budgeted metric absent from the candidate is a FAILURE, not a
    silent skip — a PR that breaks the telemetry capture must not turn
    the gate green by shrinking what it checks."""
    import perf_diff

    budgets = tmp_path / "budgets.json"
    budgets.write_text(json.dumps({
        "band": 0.5,
        "models": {"resnet50": {
            "fresh_compiles": {"max": 4, "why": "seed"},
            "throughput": {"min": 10.0, "why": "seed"},
        }}}))
    # a capture that lost its exec-cache counters: throughput survives,
    # fresh_compiles is gone
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps(
        {"models": {"resnet50": {"value": 10.0}}}) + "\n")
    with pytest.raises(SystemExit) as ei:
        perf_diff.main([str(bare), "--budgets", str(budgets)])
    assert ei.value.code == 1


def test_predicted_peak_no_cross_executable_fallback():
    """An explicit fingerprint with no registered plan must report None,
    not another executable's prediction."""
    memory.register_plan("fp_a", {"peak_bytes": 123, "peak_op_idx": 0,
                                  "peak_op_type": "mul", "n_ops": 1,
                                  "top_live": []})
    assert memory.predicted_peak("fp_a") == 123
    assert memory.predicted_peak("fp_unplanned") is None
    assert memory.predicted_peak() == 123  # no fingerprint: last plan


def test_perf_diff_unreadable_exits_2(tmp_path):
    import perf_diff

    bad = tmp_path / "bad.json"
    bad.write_text("not json at all {{{")
    with pytest.raises(SystemExit) as ei:
        perf_diff.main([str(bad), "--baseline", str(bad)])
    assert ei.value.code == 2


def test_committed_budgets_parse_and_cover_the_gate():
    """The checked-in budgets file must parse, carry lineage for every
    number, and budget the deterministic counters the gate exists for."""
    with open(os.path.join(REPO, "benchmark", "budgets.json")) as f:
        budgets = json.load(f)
    assert budgets["models"], "budgets must cover at least one model"
    for model, entries in budgets["models"].items():
        assert "fresh_compiles" in entries, model
        if model not in ("servechaos", "router", "trace", "stepprof"):
            # every bench-leg model budgets its memory plan; the
            # servechaos/router/trace/stepprof smoke captures have no
            # memory_plan surface — their deterministic gate is
            # fresh_compiles == 0 (in the RESTORED process / on the
            # failover survivor / across the tracing-ON wire leg /
            # across the profiled replay)
            assert "predicted_peak_bytes" in entries, model
        for metric, spec in entries.items():
            assert spec.get("why"), (
                "budget %s/%s needs a lineage 'why'" % (model, metric))
            assert "max" in spec or "min" in spec, (model, metric)


# ---------------------------------------------------------------------------
# offline tooling
# ---------------------------------------------------------------------------


def test_step_breakdown_memory_view(tmp_path):
    main, startup, loss = _mlp_program(seed=20)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    telemetry.enable(True)
    for _ in range(3):
        exe.run(main, feed=_feed(), fetch_list=[loss])
    snap = str(tmp_path / "steps.jsonl")
    telemetry.write_steps_jsonl(snap)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "step_breakdown.py"),
         "--from-jsonl", snap, "--memory"],
        capture_output=True, text=True, env=env, timeout=120)
    assert proc.returncode == 0, proc.stderr
    lines = [json.loads(line) for line in proc.stdout.splitlines()
             if line.strip()]
    mem = next(l for l in lines if "peak_hbm_mb" in l)
    assert mem["peak_hbm_mb"]["max"] > 0
    assert mem["predicted_peak_mb"] > 0
    assert mem["top_holders"], "memory view must name the top holders"
