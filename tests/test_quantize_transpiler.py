"""QuantizeTranspiler tests (contrib/quantize/quantize_transpiler.py
capability): QAT graph rewriting, running activation scales, convergence
through the straight-through gradients, and deploy freezing.
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.transpiler import QuantizeTranspiler


def _build_convnet():
    img = fluid.layers.data("img", [1, 8, 8])
    label = fluid.layers.data("label", [1], dtype="int64")
    c = fluid.layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                            act="relu")
    logits = fluid.layers.fc(fluid.layers.flatten(c), size=3)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    return img, label, logits, loss


def _batch(rng, bs=8):
    y = rng.randint(0, 3, (bs, 1)).astype("int64")
    x = np.zeros((bs, 1, 8, 8), "float32")
    for i, l in enumerate(y[:, 0]):
        x[i, 0, int(l) * 2:(int(l) + 1) * 2, :] = 1.0
    x += 0.1 * rng.rand(bs, 1, 8, 8)
    return x.astype("float32"), y


def test_training_transpile_inserts_pairs_and_converges():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    startup.random_seed = 3
    with fluid.program_guard(main, startup):
        img, label, logits, loss = _build_convnet()
        QuantizeTranspiler().training_transpile(main, startup)
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
    types = [op.type for op in main.global_block().ops]
    # conv input + conv weight + two mul inputs (fc) at minimum
    assert types.count("fake_quantize_abs_max") >= 4
    assert types.count("fake_dequantize_max_abs") >= 4
    # the conv now consumes the dequantized tensors
    conv = next(op for op in main.global_block().ops if op.type == "conv2d")
    assert all(n.endswith(".dequantized")
               for n in conv.inputs["Input"] + conv.inputs["Filter"])

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    first = last = None
    for _ in range(60):
        x, y = _batch(rng)
        (lv,) = exe.run(main, feed={"img": x, "label": y},
                        fetch_list=[loss])
        last = float(np.asarray(lv).ravel()[0])
        if first is None:
            first = last
    assert last < first * 0.3, (first, last)


def test_range_abs_max_scale_state_grows():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        y = fluid.layers.fc(x, size=2)
        QuantizeTranspiler(
            activation_quantize_type="range_abs_max"
        ).training_transpile(main, startup)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    state_names = [n for n in main.global_block().vars
                   if n.endswith(".scale.state")]
    assert state_names, "no running-scale state var created"
    exe.run(main, feed={"x": np.full((2, 4), 3.0, "float32")},
            fetch_list=[y])
    s1 = float(np.asarray(
        fluid.global_scope().get_value(state_names[0])).ravel()[0])
    assert abs(s1 - 3.0) < 1e-5  # grew from 1e-3 to the batch abs-max
    # a smaller batch must NOT shrink the running max
    exe.run(main, feed={"x": np.full((2, 4), 1.0, "float32")},
            fetch_list=[y])
    s2 = float(np.asarray(
        fluid.global_scope().get_value(state_names[0])).ravel()[0])
    assert abs(s2 - 3.0) < 1e-5


def test_freeze_program_strips_fakes_and_snaps_weights():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    startup.random_seed = 5
    with fluid.program_guard(main, startup):
        img, label, logits, loss = _build_convnet()
        qt = QuantizeTranspiler()
        qt.training_transpile(main, startup)
        # reference contract: clone(for_test) BEFORE minimize (clone does
        # not prune; framework.py clone docstring)
        test_prog = main.clone(for_test=True)
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(1)
    for _ in range(20):
        x, y = _batch(rng)
        exe.run(main, feed={"img": x, "label": y}, fetch_list=[loss])

    x, y = _batch(rng, bs=4)
    (qat_out,) = exe.run(test_prog, feed={"img": x, "label": y},
                         fetch_list=[logits])

    scales = qt.freeze_program(test_prog)
    assert scales, "no weights were snapped"
    types = [op.type for op in test_prog.global_block().ops]
    assert not any(t.startswith("fake_") for t in types)
    (frozen_out,) = exe.run(test_prog, feed={"img": x, "label": y},
                            fetch_list=[logits])
    # the frozen float program reproduces the QAT activations up to the
    # activation-quantization noise removed by freezing
    np.testing.assert_allclose(np.asarray(frozen_out), np.asarray(qat_out),
                               rtol=0.15, atol=0.15)


def test_preprocessor_in_graph():
    """layers.Preprocessor: reader outputs transformed in-graph."""
    import paddle_tpu.layers as layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        reader = layers.py_reader(
            capacity=4, shapes=[[-1, 4]], dtypes=["float32"],
            use_double_buffer=False)
        pre = layers.Preprocessor(reader=reader)
        with pre.block():
            (x,) = pre.inputs()
            pre.outputs(fluid.layers.scale(x, scale=0.5))
        (scaled,) = pre()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    reader.decorate_paddle_reader(
        lambda: iter([(np.full((2, 4), 8.0, "float32"),)]))
    reader.start()
    (out,) = exe.run(main, feed=reader.next_feed(), fetch_list=[scaled])
    np.testing.assert_allclose(np.asarray(out), np.full((2, 4), 4.0))


def test_freeze_rejects_training_program():
    """Freezing a program that still carries backward/optimizer ops must
    fail loudly (it would sever the gradient chain)."""
    import pytest

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img, label, logits, loss = _build_convnet()
        qt = QuantizeTranspiler()
        qt.training_transpile(main, startup)
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
    with pytest.raises(ValueError, match="backward/optimizer"):
        qt.freeze_program(main)


def test_convert_to_int8_roundtrip(tmp_path):
    """QAT -> freeze -> convert_to_int8 -> save -> serve: the saved model
    stores int8 weights (4x smaller), the dequantize_weight op rehydrates
    the exact grid values freeze snapped to (XLA parity ~float-exact),
    and the C++ interpreter serves the int8 model too (VERDICT r3
    Next #7)."""
    from paddle_tpu import native
    from paddle_tpu.inference import NativeConfig, create_paddle_predictor
    from paddle_tpu.io import prune_program

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 7
    startup.random_seed = 7
    with fluid.program_guard(main, startup):
        img, label, logits, loss = _build_convnet()
        qt = QuantizeTranspiler()
        qt.training_transpile(main, startup)
        test_prog = main.clone(for_test=True)
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(2)
    for _ in range(15):
        x, y = _batch(rng)
        exe.run(main, feed={"img": x, "label": y}, fetch_list=[loss])

    test_prog = prune_program(test_prog, ["img"], [logits.name])
    scales = qt.freeze_program(test_prog)
    assert scales
    x, y = _batch(rng, bs=4)
    (frozen_out,) = exe.run(test_prog, feed={"img": x},
                            fetch_list=[logits])

    converted = qt.convert_to_int8(test_prog, scales=scales)
    assert sorted(converted) == sorted(scales)
    gb = test_prog.global_block()
    assert gb.ops[0].type == "dequantize_weight"
    for name in converted:
        assert str(gb.vars[name + ".int8"].dtype) == "int8"
        assert not gb.vars[name].persistable
    # int8 dequantization reproduces the snapped grid values exactly
    (int8_out,) = exe.run(test_prog, feed={"img": x},
                          fetch_list=[logits])
    np.testing.assert_allclose(np.asarray(int8_out),
                               np.asarray(frozen_out),
                               rtol=1e-5, atol=1e-6)

    # deployment: the saved dir stores int8 tensors
    path = str(tmp_path / "int8_model")
    fluid.io.save_inference_model(path, ["img"], [logits], exe,
                                  main_program=test_prog)
    import os

    saved = {}
    for fn in os.listdir(path):
        if fn.endswith(".npy"):
            saved[fn] = np.load(os.path.join(path, fn))
    int8_files = [fn for fn, a in saved.items() if a.dtype == np.int8]
    assert len(int8_files) == len(converted)
    for name in converted:
        assert not any(fn.startswith(name + ".npy") for fn in saved), \
            "float weight %s must not be persisted" % name

    # serve the int8 model through BOTH engines
    with fluid.scope_guard(fluid.executor.Scope()):
        exe2 = fluid.Executor(fluid.CPUPlace())
        prog2, feeds2, fetches2 = fluid.io.load_inference_model(path, exe2)
        (loaded_out,) = exe2.run(prog2, feed={"img": x},
                                 fetch_list=fetches2)
    np.testing.assert_allclose(np.asarray(loaded_out),
                               np.asarray(frozen_out),
                               rtol=1e-5, atol=1e-6)
    if native.available():
        predictor = create_paddle_predictor(
            NativeConfig(model_dir=path, use_tpu=False))
        got_cpp = predictor.run_native_reference({"img": x})
        np.testing.assert_allclose(np.asarray(got_cpp),
                                   np.asarray(frozen_out),
                                   rtol=1e-4, atol=1e-5)
    # the STANDALONE C++ binary exercises npy::Load on the int8 files
    # (the ctypes path above feeds params through the Python scope)
    from tests.conftest import build_native_binary

    binary = build_native_binary("ptpu_demo_predictor")
    if binary is not None:
        import subprocess

        inp = str(tmp_path / "input.npy")
        outp = str(tmp_path / "output.npy")
        np.save(inp, x)
        res = subprocess.run([binary, path, inp, outp],
                             capture_output=True, text=True, timeout=120)
        assert res.returncode == 0, res.stderr
        np.testing.assert_allclose(np.load(outp),
                                   np.asarray(frozen_out),
                                   rtol=1e-4, atol=1e-5)
