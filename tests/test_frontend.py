"""Network front end: the socket serving plane (serving/frontend.py +
serving/client.py) and the JSON-lines substrate extensions underneath
it (distributed/master.py streaming + connection callbacks).

Covers, in order: the substrate regression surface (dict dispatch,
MasterService and FleetCoordinator behavior UNCHANGED under the
extended serve_json_lines), the wire codec (bit-exact arrays, typed
error round trips), unary predict (parity, deadlines, degradation),
streaming generate (incremental chunks, best-of-N + prefix reuse over
the wire, oracle parity), disconnect-safe reclamation (kill/cancel a
client mid-stream -> slot + page refcounts back to conservation),
the net.* chaos sites with classified-retry coverage (severed
connections are retried or surface typed errors — never a hang), and
the SIGTERM composition with DecodeSnapshotManager (subprocess leg:
the frontend banks its backlog and dies by the signal).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags
from paddle_tpu.distributed.master import (
    JsonLineClient,
    MasterClient,
    MasterService,
    close_json_server,
    serve_json_lines,
)
from paddle_tpu.executor import global_scope
from paddle_tpu.resilience import chaos
from paddle_tpu.serving.client import (
    ServingClient,
    StreamBrokenError,
    decode_array,
    encode_array,
    error_from_wire,
    error_to_wire,
)
from paddle_tpu.serving.degradation import DegradedError
from paddle_tpu.serving.frontend import ServingFrontend
from paddle_tpu.serving.generation import (
    NoFreePageError,
    NoFreeSlotError,
    Sampler,
    SlotDecodeSession,
)
from paddle_tpu.serving.server import (
    BatchingServer,
    DeadlineExceededError,
    QueueFullError,
    ServerClosedError,
    ServingError,
)

VOCAB, SEQ, D, S = 24, 8, 32, 4
CFG = dict(src_vocab_size=VOCAB, trg_vocab_size=VOCAB, n_layer=1,
           n_head=2, d_inner=64)


@pytest.fixture(autouse=True)
def _clean_chaos_and_flags():
    yield
    chaos.disable()
    flags.set_flag("dispatch_retries", 0)


# ---------------------------------------------------------------------------
# substrate: serve_json_lines extensions + regression
# ---------------------------------------------------------------------------

def test_substrate_dict_dispatch_unchanged():
    """The legacy one-request/one-response contract (and the legacy
    dispatch signature) is untouched: MasterService serves its whole
    task protocol through the extended substrate."""
    svc = MasterService(chunks_per_task=1, timeout_s=5.0)
    addr = svc.serve()
    try:
        client = MasterClient(addr)
        client.set_dataset(["a", "b"])
        t1 = client.get_task()
        assert t1 is not None and t1.chunks in (["a"], ["b"])
        assert client.task_finished(t1.task_id)
        st = client.status()
        assert st["done"] == 1 and st["todo"] == 1
        client.close()
    finally:
        svc.close()


def test_substrate_streaming_callbacks_and_byte_accounting():
    opened, closed = [], []

    def dispatch(req, conn):
        assert conn.id >= 1
        if req["m"] == "one":
            conn.state["seen"] = True
            return {"ok": True, "x": req["x"]}

        def gen():
            for i in range(3):
                yield {"ok": True, "i": i}
            yield {"ok": True, "event": "end"}

        return gen()

    srv, addr = serve_json_lines(
        dispatch, pass_conn=True,
        on_open=lambda c: opened.append(c.id),
        on_close=lambda c: closed.append((c.id, c.state.get("seen"))))
    try:
        cl = JsonLineClient(addr)
        assert cl._call(m="one", x=7) == {"ok": True, "x": 7}
        cl._send_line({"m": "stream"})
        msgs = [cl._recv_line() for _ in range(4)]
        assert [m.get("i") for m in msgs[:3]] == [0, 1, 2]
        assert msgs[3]["event"] == "end"
        cl.close()
        deadline = time.monotonic() + 5.0
        while not closed and time.monotonic() < deadline:
            time.sleep(0.01)
        assert opened == [1] and closed == [(1, True)]
        with srv._conn_mu:
            assert srv.bytes_sent > 0 and srv.bytes_received > 0
    finally:
        close_json_server(srv)


def test_substrate_stream_exception_becomes_terminal_error_line():
    cleaned = []

    def dispatch(req):
        def gen():
            try:
                yield {"ok": True, "i": 0}
                raise RuntimeError("mid-stream boom")
            finally:
                cleaned.append(True)

        return gen()

    srv, addr = serve_json_lines(dispatch)
    try:
        cl = JsonLineClient(addr)
        cl._send_line({})
        assert cl._recv_line() == {"ok": True, "i": 0}
        err = cl._recv_line()
        assert err["ok"] is False and "mid-stream boom" in err["error"]
        cl.close()
        assert cleaned == [True]
    finally:
        close_json_server(srv)


def test_fleet_coordinator_behavior_unchanged():
    """The elastic coordinator (the substrate's other production user)
    still registers/heartbeats/deregisters identically."""
    from paddle_tpu.elastic.coordinator import FleetClient, FleetCoordinator

    co = FleetCoordinator(lease_s=2.0, min_workers=1)
    addr = co.serve()
    try:
        fc = FleetClient(addr)
        view = fc.register(worker_id="w0")
        assert (view["world"], view["rank"]) == (1, 0)
        hb = fc.heartbeat("w0")
        assert hb["generation"] == view["generation"]
        assert fc.leave("w0")
        fc.close()
    finally:
        co.close()


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------

def test_array_codec_bit_exact():
    nan_payload = np.array([1.0, np.float32(np.nan), -np.inf, 3e-41],
                           dtype="float32").reshape(2, 2)
    for arr in (nan_payload,
                np.arange(12, dtype="int64").reshape(3, 4),
                np.asarray(2.5, dtype="float64")):
        back = decode_array(encode_array(arr))
        assert back.dtype == arr.dtype and back.shape == arr.shape
        assert np.array_equal(arr.tobytes(), back.tobytes())
        back[...] = 0  # decoded arrays must be writable


def test_typed_errors_round_trip_the_wire():
    for exc in (QueueFullError("q"), DeadlineExceededError("d"),
                ServerClosedError("c"), NoFreeSlotError("s"),
                NoFreePageError("p"), StreamBrokenError("b")):
        back = error_from_wire(error_to_wire(exc))
        assert type(back) is type(exc) and str(exc) in str(back)
    deg = error_from_wire(error_to_wire(
        DegradedError("shed", state="shed", retry_after_s=0.25)))
    assert isinstance(deg, DegradedError)
    assert deg.state == "shed" and deg.retry_after_s == 0.25
    from paddle_tpu.resilience.retry import is_transient

    assert is_transient(deg), "wire DegradedError lost retriability"
    unknown = error_from_wire({"ok": False, "etype": "Weird",
                               "error": "x"})
    assert isinstance(unknown, ServingError) and "Weird" in str(unknown)


# ---------------------------------------------------------------------------
# fixtures: demo predictor (unary) + trained decoder (streaming)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def demo_predictor(tmp_path_factory):
    from paddle_tpu.inference import NativeConfig, create_paddle_predictor
    from paddle_tpu.serving import loadgen

    model_dir = str(tmp_path_factory.mktemp("fe_demo") / "model")
    loadgen.build_demo_model(model_dir, train_steps=5)
    return create_paddle_predictor(
        NativeConfig(model_dir=model_dir, use_tpu=False))


@pytest.fixture(scope="module")
def trained():
    from paddle_tpu.models import transformer

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 41
    startup.random_seed = 41
    scope = global_scope()
    with fluid.program_guard(main, startup):
        transformer.build(dropout=0.0, label_smooth_eps=0.0,
                          max_length=SEQ, d_model=D, **CFG)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(7)
    src = rng.randint(3, VOCAB, (8, SEQ)).astype("int64")
    return {"exe": exe, "scope": scope, "src": src}


def _paged(trained, **kw):
    args = dict(num_slots=S, max_length=SEQ, d_model=D, paged=True,
                page_size=4, steps=2, num_groups=2,
                prefix_cache_pages=8,
                sampler=Sampler(strategy="top_k", top_k=4,
                                temperature=0.9, seed=11),
                scope=trained["scope"].new_scope())
    args.update(CFG)
    args.update(kw)
    return SlotDecodeSession(trained["exe"], **args)


def _drained(sess, timeout=60.0):
    """Wait until every teardown landed: every slot free, no queued
    request, pool at conservation. The free-slot check matters: a
    mid-admission window (request popped, slot popped, dispatch in
    flight) satisfies the weaker live/pending/conservation predicate —
    disconnect reclamation is processed on the decode worker and tests
    must wait for it, not race it."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if (not sess.active_slots and not sess.pending_requests
                and sess.free_slots == sess._S
                and sess.pool_conserved):
            return True
        time.sleep(0.02)
    return False


# ---------------------------------------------------------------------------
# unary predict over the wire
# ---------------------------------------------------------------------------

def test_predict_bit_exact_parity(demo_predictor):
    from paddle_tpu.serving import loadgen

    server = BatchingServer(demo_predictor, max_batch=8, workers=1,
                            batch_linger_s=0.002)
    with server, ServingFrontend(server=server) as fe:
        cl = ServingClient(fe.address)
        for req in loadgen.demo_requests(6, seed=5):
            got = cl.predict(req)
            want = server.run_reference(req)
            assert all(np.array_equal(g, w) for g, w in zip(got, want))
        # list-form inputs (feed order) work too
        req = loadgen.demo_requests(1, seed=9)[0]
        got = cl.predict([req["x"]])
        want = server.run_reference(req)
        assert all(np.array_equal(g, w) for g, w in zip(got, want))
        cl.close()


def test_predict_deadline_maps_to_typed_error(demo_predictor):
    server = BatchingServer(demo_predictor, max_batch=8, workers=1,
                            batch_linger_s=0.2)
    with server, ServingFrontend(server=server) as fe:
        cl = ServingClient(fe.address)
        with pytest.raises(DeadlineExceededError):
            cl.predict({"x": np.zeros((2, 12), dtype="float32")},
                       deadline_s=1e-6)
        cl.close()


def test_predict_shed_reaches_client_typed_then_retries_through(
        demo_predictor):
    server = BatchingServer(
        demo_predictor, max_batch=8, workers=1, max_queue_depth=4,
        batch_linger_s=0.05,
        degradation=dict(brownout_at=0.25, shed_at=0.5,
                         recover_at=0.25, retry_after_s=0.05))
    with server, ServingFrontend(server=server) as fe:
        req = {"x": np.zeros((1, 12), dtype="float32")}

        def flood(n):
            rejects, okays = [], []

            def one():
                cl = ServingClient(fe.address)
                try:
                    cl.predict(req)
                    okays.append(1)
                except DegradedError as exc:
                    rejects.append(exc)
                finally:
                    cl.close()

            threads = [threading.Thread(target=one) for _ in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            return rejects, okays

        # no retries: the typed reject surfaces to the caller
        rejects, okays = flood(16)
        assert rejects, "the flood never tripped shed"
        assert okays, "shed refused everything, including the drain"
        assert all(isinstance(e, DegradedError)
                   and e.retry_after_s > 0 for e in rejects)
        # with the classified budget armed, the SAME flood rides the
        # retry-after hint through the drain instead of surfacing
        flags.set_flag("dispatch_retries", 8)
        rejects, okays = flood(16)
        assert not rejects and len(okays) == 16


def test_unknown_method_is_typed(demo_predictor):
    server = BatchingServer(demo_predictor, max_batch=8, workers=1)
    with server, ServingFrontend(server=server) as fe:
        cl = ServingClient(fe.address)
        with pytest.raises(ServingError, match="unknown method"):
            cl._request(method="nope")
        # a predict-only frontend refuses generate with a typed error
        with pytest.raises(ServingError, match="no decode session"):
            list(cl.generate(np.zeros(SEQ, dtype="int64")))
        cl.close()


# ---------------------------------------------------------------------------
# streaming generate
# ---------------------------------------------------------------------------

def test_generate_streams_incrementally_and_matches_oracle(trained):
    src = trained["src"]
    sess, oracle = _paged(trained), _paged(trained)
    with ServingFrontend(session=sess) as fe:
        cl = ServingClient(fe.address)
        events = list(cl.generate(src[0], src_len=SEQ))
        kinds = [e["event"] for e in events]
        assert kinds[0] == "queued" and kinds[-1] == "end"
        token_events = [e for e in events if e["event"] == "tokens"]
        # SEQ=8, steps=2: the stream must arrive in PER-DISPATCH
        # chunks, not one end-of-generation lump
        assert len(token_events) >= 2
        assert all(len(e["tokens"]) <= 2 for e in token_events)
        wire = cl.generate_full(src[1], src_len=5)
        cl.close()
    want0 = oracle.generate(src[0][None, :], [SEQ])
    want1 = oracle.generate(src[1][None, :], [5])
    row0 = np.full(SEQ, 2, dtype="int64")
    row0[0] = 1
    fill = 1
    for e in token_events:
        row0[fill:fill + len(e["tokens"])] = e["tokens"]
        fill += len(e["tokens"])
    assert np.array_equal(row0, want0[0])
    assert np.array_equal(wire[0], want1[0])


def test_generate_best_of_and_prefix_reuse_over_the_wire(trained):
    src = trained["src"]
    pfx = [int(t) for t in src[0][:5]]
    sess, oracle = _paged(trained), _paged(trained)
    with ServingFrontend(session=sess) as fe:
        cl = ServingClient(fe.address)
        wire = cl.generate_full(src[0], src_len=SEQ, n=2,
                                prefix_tokens=pfx)
        # the same forced prefix again: served from the prefix cache
        wire2 = cl.generate_full(src[0], src_len=SEQ, n=2,
                                 prefix_tokens=pfx)
        stats = sess.prefix_cache_stats()
        cl.close()
    want = oracle.generate_best_of(src[0], 2, src_len=SEQ,
                                   prefix_tokens=pfx)
    want2 = oracle.generate_best_of(src[0], 2, src_len=SEQ,
                                    prefix_tokens=pfx)
    assert np.array_equal(wire, want)
    assert np.array_equal(wire2, want2)
    assert stats["lookups"] >= 2 and stats["hits"] >= 1, stats


def test_generate_beam_over_the_wire_matches_in_process(trained):
    """Beam socket parity (PR 15): the wire grammar — ``admitted`` with
    beam metadata, one ``beam`` survivor chunk per dispatch, a final
    ``beam_end`` n-best — reassembles bit-identical to the in-process
    ``generate_beam``, the client's incremental replay cross-checks the
    chunks against the n-best, and a disconnected beam stream returns
    every lane slot to conservation."""
    src = trained["src"]
    args = dict(num_slots=S, max_length=SEQ, d_model=D, paged=True,
                page_size=4, beam_width=2,
                scope=trained["scope"].new_scope())
    args.update(CFG)
    sess = SlotDecodeSession(trained["exe"], **args)
    with ServingFrontend(session=sess) as fe:
        cl = ServingClient(fe.address)
        events = []
        got_t, got_s = cl.generate_beam(src[0], src_len=SEQ,
                                        on_event=events.append)
        kinds = [e["event"] for e in events]
        assert kinds[0] == "admitted" and kinds[-2:] == ["beam_end",
                                                         "end"]
        adm = events[0]
        assert adm["beam_width"] == 2 and len(adm["slots"]) == 2
        # one survivor chunk PER DISPATCH (parents + tokens + scores),
        # not an end-of-beam lump
        beam_events = [e for e in events if e["event"] == "beam"]
        assert len(beam_events) >= 3
        assert all(len(e["parents"]) == 2 and len(e["tokens"]) == 2
                   for e in beam_events)
        # beam=True composes with nothing: n > 1 is a typed reject
        with pytest.raises(ServingError):
            list(cl.generate(src[0], src_len=SEQ, n=2, beam=True))
        # disconnect mid-beam: the whole lane reclaims
        gen = cl.generate(src[1], src_len=SEQ, beam=True)
        assert next(gen)["event"] == "admitted"
        cl.close()  # severed socket: the close hook cancels the beam
    assert _drained(sess)
    assert sess.free_beams == S // 2 and sess.pool_conserved
    # wire parity: the frontend is closed, the session is drained — the
    # SAME session decoding the SAME source in-process must reproduce
    # the wire n-best bit-for-bit (the greedy lattice is deterministic)
    want_t, want_s = sess.generate_beam(src[0], SEQ)
    np.testing.assert_array_equal(got_t, want_t)
    np.testing.assert_array_equal(got_s, want_s)
    # a beam that finishes with NO attached stream (the
    # preemption-orphan shape) banks its n-best under the claim id —
    # and the wire take_result reaches the beam bank
    lane = sess.admit_beam(src[1], SEQ)
    rid = sess.register_beam_owner(lane)
    while lane in sess.active_beams:
        sess.step()
    with ServingFrontend(session=sess) as fe2:
        cl2 = ServingClient(fe2.address)
        bt, bs = cl2.take_result(rid)
        cl2.close()
    assert sess.take_beam_result(rid) is None  # claimed over the wire
    np.testing.assert_array_equal(bt, sess.generate_beam(src[1], SEQ)[0])
    assert bs.shape == (2,)


def test_beam_len_penalty_rescoring_wire_matches_in_process(trained):
    """GNMT length-penalty rescoring as a wire option: ``len_penalty``
    on a beam request makes the frontend rescore the final n-best
    (``beam_end`` reorders under the penalized scores and carries the
    ``order`` permutation the client replay-check realigns through);
    the wire result is bit-identical to the in-process
    ``generate_beam(len_penalty=...)``, which itself is exactly
    ``gnmt_rescore_nbest`` over the raw n-best. ``len_penalty``
    without ``beam`` is a typed reject."""
    from paddle_tpu.models.transformer import gnmt_rescore_nbest

    src = trained["src"]
    args = dict(num_slots=S, max_length=SEQ, d_model=D, paged=True,
                page_size=4, beam_width=2,
                scope=trained["scope"].new_scope())
    args.update(CFG)
    sess = SlotDecodeSession(trained["exe"], **args)
    with ServingFrontend(session=sess) as fe:
        cl = ServingClient(fe.address)
        events = []
        got_t, got_s = cl.generate_beam(src[0], src_len=SEQ,
                                        len_penalty=2.0,
                                        on_event=events.append)
        end = [e for e in events if e["event"] == "beam_end"][0]
        assert end["len_penalty"] == 2.0
        assert sorted(end["order"]) == [0, 1]
        with pytest.raises(ServingError, match="beam=true"):
            list(cl.generate(src[0], src_len=SEQ, len_penalty=0.6))
        cl.close()
    assert _drained(sess)
    want_t, want_s = sess.generate_beam(src[0], SEQ, len_penalty=2.0)
    np.testing.assert_array_equal(got_t, want_t)
    np.testing.assert_array_equal(got_s, want_s)
    # the in-process rescoring IS gnmt_rescore_nbest over the raw
    # n-best (penalized scores, score-descending reorder)
    raw_t, raw_s = sess.generate_beam(src[0], SEQ)
    order, re_t, re_s = gnmt_rescore_nbest(raw_t, raw_s, sess._eos, 2.0)
    np.testing.assert_array_equal(re_t, want_t)
    np.testing.assert_array_equal(re_s, want_s)
    assert sorted(int(i) for i in order) == [0, 1]
    # len_penalty = 0 divides by 1: identity order, raw scores
    z_t, z_s = sess.generate_beam(src[0], SEQ, len_penalty=0.0)
    np.testing.assert_array_equal(z_t, raw_t)
    np.testing.assert_allclose(z_s, raw_s, rtol=1e-6)


def test_generate_backlog_exceeding_slots_completes_concurrently(
        trained):
    """6 concurrent wire streams over a 4-slot pool: the overflow rides
    the session's persistent queue; every stream completes and matches
    the greedy oracle (greedy decode is slot-independent, so the
    nondeterministic admission order cannot affect the bits)."""
    src = trained["src"]
    sess = _paged(trained, sampler=None, prefix_cache_pages=0)
    oracle = _paged(trained, sampler=None, prefix_cache_pages=0)
    results = {}
    errors = []
    with ServingFrontend(session=sess) as fe:

        def one(i):
            cl = ServingClient(fe.address)
            try:
                results[i] = cl.generate_full(src[i], src_len=SEQ)
            except Exception as exc:  # noqa: BLE001 - collected
                errors.append(exc)
            finally:
                cl.close()

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert _drained(sess)
    assert not errors, errors[:3]
    for i in range(6):
        want = oracle.generate(src[i][None, :], [SEQ])
        assert np.array_equal(results[i][0], want[0]), "row %d" % i


def test_client_disconnect_mid_stream_reclaims_pool(trained):
    src = trained["src"]
    sess = _paged(trained)
    with ServingFrontend(session=sess) as fe:
        cl = ServingClient(fe.address)
        # warm the admit/step executables first: the disconnect scenario
        # must race the decode loop, not a cold XLA compile
        cl.generate_full(src[0], src_len=SEQ)
        gen = cl.generate(src[2], src_len=SEQ)
        next(gen)
        # hard kill: close the socket without a cancel line — only the
        # substrate's close callback can reclaim
        cl.close()
        assert _drained(sess), (
            "disconnect did not reclaim: live=%r pending=%r "
            "conserved=%r" % (sess.active_slots,
                              sess.pending_requests,
                              sess.pool_conserved))
        assert sess.free_slots == S
        assert sess.free_pages == sess._P - 1 - sess.cached_pages
        # a subsequent admission over a fresh connection succeeds
        cl2 = ServingClient(fe.address)
        out = cl2.generate_full(src[2], src_len=SEQ)
        assert out.shape == (1, SEQ)
        cl2.close()


def test_inband_cancel_reclaims_and_connection_stays_usable(trained):
    src = trained["src"]
    sess, oracle = _paged(trained), _paged(trained)
    with ServingFrontend(session=sess) as fe:
        cl = ServingClient(fe.address)
        gen = cl.generate(src[3], src_len=SEQ)
        next(gen)
        gen.close()  # sends the in-band cancel, drains the ack
        assert _drained(sess)
        assert sess.pool_conserved and sess.free_slots == S
        # the SAME connection serves the next request
        wire = cl.generate_full(src[4], src_len=SEQ)
        cl.close()
    # drive the oracle through the same effective history (a cancelled
    # generation admits and releases; slot order is preserved)
    o = _oracle_after_cancel(oracle, src)
    assert np.array_equal(wire[0], o[0])


def _oracle_after_cancel(oracle, src):
    slot = oracle.admit(src[3], SEQ)
    oracle.cancel(slot)
    return oracle.generate(src[4][None, :], [SEQ])


def test_session_cancel_is_conservation_clean(trained):
    """The session-level teardown primitive itself: cancel a live fork
    group member mid-decode, conservation holds, the slot re-admits."""
    src = trained["src"]
    sess = _paged(trained)
    slots = sess.admit_group(src[0], n=2, src_len=SEQ,
                             prefix_tokens=[int(t) for t in src[0][:4]])
    assert sess.cancel(slots[0]) is True
    assert sess.cancel(slots[0]) is False  # idempotent
    assert sess.pool_conserved
    sess.step()  # the surviving member decodes on
    if slots[1] in sess.active_slots:
        assert sess.cancel(slots[1]) is True
    assert sess.pool_conserved and sess.free_slots == S
    assert sess.free_pages == sess._P - 1 - sess.cached_pages


def test_close_drain_false_fails_streams_typed_and_reclaims(trained):
    src = trained["src"]
    sess = _paged(trained)
    fe = ServingFrontend(session=sess)
    cl = ServingClient(fe.address)
    gen = cl.generate(src[5], src_len=SEQ)
    next(gen)
    got = []

    def drain():
        try:
            for _ in gen:
                pass
        except Exception as exc:  # noqa: BLE001 - asserted below
            got.append(exc)

    t = threading.Thread(target=drain)
    t.start()
    fe.close(drain=False)
    t.join(timeout=30)
    assert not t.is_alive(), "stream consumer hung across close"
    if got:  # either the typed close error or the severed connection
        assert isinstance(got[0], (ServerClosedError, StreamBrokenError,
                                   ConnectionError, OSError)), got[0]
    assert _drained(sess)
    cl.close()


def test_bad_request_is_typed_and_worker_survives(trained):
    """A request the session type refuses (forced prefix on a DENSE
    session) surfaces as a typed wire error from the admission path —
    and must NOT kill the decode worker: the next request still
    serves."""
    src = trained["src"]
    sess = SlotDecodeSession(
        trained["exe"], num_slots=S, max_length=SEQ, d_model=D,
        paged=False, scope=trained["scope"].new_scope(), **CFG)
    with ServingFrontend(session=sess) as fe:
        cl = ServingClient(fe.address)
        with pytest.raises(ServingError):
            cl.generate_full(src[0], src_len=SEQ,
                             prefix_tokens=[3, 4])
        # the worker lived through it: a well-formed request serves
        out = cl.generate_full(src[0], src_len=SEQ)
        assert out.shape == (1, SEQ)
        cl.close()


# ---------------------------------------------------------------------------
# ops endpoints
# ---------------------------------------------------------------------------

def test_metrics_health_stats_endpoints(demo_predictor, trained):
    server = BatchingServer(demo_predictor, max_batch=8, workers=1)
    sess = _paged(trained)
    with server, ServingFrontend(server=server, session=sess) as fe:
        cl = ServingClient(fe.address)
        cl.predict({"x": np.zeros((2, 12), dtype="float32")})
        cl.generate_full(trained["src"][6], src_len=SEQ)
        text = cl.metrics()
        assert "paddle_tpu_frontend_request_seconds" in text
        assert "paddle_tpu_frontend_active_connections" in text
        assert "paddle_tpu_frontend_bytes_sent_total" in text
        assert "paddle_tpu_frontend_ttft_seconds" in text
        health = cl.health()
        assert health == {"server": "healthy", "decode": "healthy"}
        stats = cl.stats()
        assert stats["requests"]["predict"]["ok"] >= 1
        assert stats["requests"]["generate"]["ok"] >= 1
        assert stats["active_connections"] >= 1
        assert stats["bytes_sent"] > 0 and stats["bytes_received"] > 0
        assert cl.take_result(10 ** 9) is None
        cl.close()


# ---------------------------------------------------------------------------
# chaos: net.accept / net.send + classified retry — never a hang
# ---------------------------------------------------------------------------

def test_net_accept_fault_is_survived_by_reconnect(demo_predictor):
    server = BatchingServer(demo_predictor, max_batch=8, workers=1)
    with server, ServingFrontend(server=server) as fe:
        flags.set_flag("chaos_spec", "seed=3;io@site=net.accept,n=1")
        chaos.configure()
        cl = ServingClient(fe.address)
        out = cl.predict({"x": np.zeros((2, 12), dtype="float32")})
        assert len(out) == 1
        assert chaos.fires("net.accept") == 1, \
            "the accept fault never fired: the test is vacuous"
        cl.close()


def test_net_send_fault_unary_is_retried(demo_predictor):
    server = BatchingServer(demo_predictor, max_batch=8, workers=1)
    with server, ServingFrontend(server=server) as fe:
        flags.set_flag("chaos_spec", "seed=3;io@site=net.send,n=1")
        chaos.configure()
        cl = ServingClient(fe.address)
        # the response write fails -> severed connection -> the
        # client's reconnect-retry-once re-sends and succeeds
        out = cl.predict({"x": np.zeros((2, 12), dtype="float32")})
        assert len(out) == 1
        assert chaos.fires("net.send") == 1
        cl.close()


def test_net_send_fault_mid_stream_is_typed_never_a_hang(trained):
    src = trained["src"]
    sess = _paged(trained)
    with ServingFrontend(session=sess) as fe:
        # skip the queued/admitted/first-token sends, then sever: the
        # client has consumed tokens, so the break is NOT silently
        # retried — it surfaces as the typed StreamBrokenError
        flags.set_flag("chaos_spec",
                       "seed=3;io@site=net.send,skip=3,n=1")
        chaos.configure()
        cl = ServingClient(fe.address)
        t0 = time.monotonic()
        with pytest.raises(StreamBrokenError):
            cl.generate_full(src[7], src_len=SEQ)
        assert time.monotonic() - t0 < 30.0, "broken stream hung"
        assert chaos.fires("net.send") == 1
        chaos.disable()
        # the severed write tore the stream down server-side too
        assert _drained(sess)
        assert sess.pool_conserved
        cl.close()


def test_client_reads_are_watchdog_armed(demo_predictor, monkeypatch):
    from paddle_tpu.serving import client as client_mod

    armed = []
    monkeypatch.setattr(client_mod._watchdog, "ENABLED", True)
    real_arm = client_mod._watchdog.arm

    def spy_arm(tag="work", scale=1):
        armed.append(tag)
        return real_arm(tag, scale)

    monkeypatch.setattr(client_mod._watchdog, "arm", spy_arm)
    server = BatchingServer(demo_predictor, max_batch=8, workers=1)
    with server, ServingFrontend(server=server) as fe:
        cl = ServingClient(fe.address)
        cl.predict({"x": np.zeros((2, 12), dtype="float32")})
        cl.close()
    assert "net.recv" in armed


def test_client_survives_frontend_restart(demo_predictor):
    server = BatchingServer(demo_predictor, max_batch=8, workers=1)
    req = {"x": np.zeros((2, 12), dtype="float32")}
    with server:
        fe = ServingFrontend(server=server)
        host, port = fe.address
        cl = ServingClient(fe.address)
        want = cl.predict(req)
        fe.close()
        # restart on the SAME port: the established connection is
        # severed; the client's reconnect-retry-once rides through
        fe2 = ServingFrontend(server=server, host=host, port=port)
        got = cl.predict(req)
        assert np.array_equal(got[0], want[0])
        cl.close()
        fe2.close()


# ---------------------------------------------------------------------------
# SIGTERM composition with DecodeSnapshotManager (subprocess)
# ---------------------------------------------------------------------------

_CHILD = r"""
import os, sys, time
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import paddle_tpu as fluid
from paddle_tpu.models import transformer
from paddle_tpu.serving.frontend import ServingFrontend
from paddle_tpu.serving.generation import Sampler, SlotDecodeSession
from paddle_tpu.serving.snapshot import DecodeSnapshotManager

snap_dir = sys.argv[1]
VOCAB, SEQ, D, S = 24, 8, 32, 4
CFG = dict(src_vocab_size=VOCAB, trg_vocab_size=VOCAB, n_layer=1,
           n_head=2, d_inner=64)
main, startup = fluid.Program(), fluid.Program()
main.random_seed = 41; startup.random_seed = 41
with fluid.program_guard(main, startup):
    transformer.build(dropout=0.0, label_smooth_eps=0.0,
                      max_length=SEQ, d_model=D, **CFG)
exe = fluid.Executor(fluid.CPUPlace())
exe.run(startup)
sess = SlotDecodeSession(exe, num_slots=S, max_length=SEQ, d_model=D,
                         paged=True, page_size=4, steps=2,
                         sampler=Sampler(seed=3), **CFG)
# order matters: the manager's handlers first, the frontend's on top —
# a SIGTERM stops the transport, then chains into the snapshot path
mgr = DecodeSnapshotManager(sess, snap_dir,
                            install_signal_handlers=True)
fe = ServingFrontend(session=sess, install_signal_handlers=True)
print("PORT %d" % fe.port, flush=True)
while True:
    time.sleep(0.1)
"""


@pytest.mark.slow
def test_sigterm_frontend_banks_backlog_and_dies_by_signal(tmp_path):
    snap_dir = str(tmp_path / "snap")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("FLAGS_chaos_spec", None)
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD, snap_dir],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env,
        cwd=os.path.join(os.path.dirname(__file__), os.pardir))
    streams_alive = []
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("PORT "), (line, proc.stderr.read())
        port = int(line.split()[1])
        rng = np.random.RandomState(7)
        src = rng.randint(3, VOCAB, (8, SEQ)).astype("int64")

        def streamer(i):
            cl = ServingClient(("127.0.0.1", port), timeout_s=60.0)
            try:
                for _ in cl.generate(src[i], src_len=SEQ):
                    pass
            except Exception:  # noqa: BLE001 - severed by the SIGTERM
                pass
            finally:
                cl.close()

        # a backlog bigger than the pool: some live, some queued
        threads = [threading.Thread(target=streamer, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        streams_alive = threads
        time.sleep(1.0)  # let admissions land
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        proc.kill()
        for t in streams_alive:
            t.join(timeout=30)
    assert proc.returncode == -signal.SIGTERM, (proc.returncode, err)
    from paddle_tpu.resilience.checkpoint import (
        complete_serials,
        read_manifest,
    )

    serials = complete_serials(snap_dir)
    assert serials, "no final snapshot banked on SIGTERM: %s" % err
    manifest = read_manifest(
        os.path.join(snap_dir, "checkpoint_%d" % serials[-1]))
    meta = manifest["extra"]["decode_snapshot"]
    assert meta["live"] or meta["pending"], (
        "SIGTERM'd frontend banked no backlog (live=%r pending=%r)"
        % (meta["live"], meta["pending"]))
