"""Graph-level recordio reader tests: convert_reader_to_recordio_file(s) +
open_recordio_file / open_files feeding a training loop.

Reference: python/paddle/fluid/recordio_writer.py,
operators/reader/create_recordio_file_reader_op.cc, open_files_op.cc,
tests/unittests/test_recordio_reader.py.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import native, recordio_writer

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason="native toolchain unavailable: %s" % native.last_error(),
)


def _sample_reader(n, seed=0):
    def reader():
        rng = np.random.RandomState(seed)
        for i in range(n):
            x = rng.rand(4).astype("float32")
            y = np.array([x.sum()], "float32")
            yield x, y

    return reader


def test_pack_unpack_roundtrip():
    sample = (np.arange(6, dtype="float32").reshape(2, 3),
              np.array([3], "int64"))
    blob = recordio_writer.pack_sample(sample)
    back = recordio_writer.unpack_sample(blob)
    assert len(back) == 2
    np.testing.assert_array_equal(back[0], sample[0])
    np.testing.assert_array_equal(back[1], sample[1])


def test_convert_and_read_back(tmp_path):
    path = str(tmp_path / "data.recordio")
    n = recordio_writer.convert_reader_to_recordio_file(
        path, _sample_reader(10))
    assert n == 10
    with native.RecordIOReader(path) as r:
        rows = [recordio_writer.unpack_sample(b) for b in r]
    assert len(rows) == 10
    expected = list(_sample_reader(10)())
    for got, exp in zip(rows, expected):
        np.testing.assert_allclose(got[0], exp[0], rtol=1e-6)


def test_sharded_files_cover_everything(tmp_path):
    base = str(tmp_path / "shard")
    paths = recordio_writer.convert_reader_to_recordio_files(
        base, 4, _sample_reader(10))
    assert len(paths) == 3  # 4 + 4 + 2
    total = 0
    for p in paths:
        with native.RecordIOReader(p) as r:
            total += sum(1 for _ in r)
    assert total == 10


def test_open_files_trains_a_model(tmp_path):
    base = str(tmp_path / "train")
    # pre-batched records: [8,4] x, [8,1] y per record
    def batched():
        rng = np.random.RandomState(3)
        for _ in range(12):
            x = rng.rand(8, 4).astype("float32")
            yield x, x.sum(1, keepdims=True).astype("float32")

    paths = recordio_writer.convert_reader_to_recordio_files(base, 6, batched)
    assert len(paths) == 2

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        reader = fluid.layers.open_files(
            paths, shapes=[[-1, 4], [-1, 1]],
            dtypes=["float32", "float32"], pass_num=3)
        xv, yv = fluid.layers.read_file(reader)
        xv.stop_gradient = False
        pred = fluid.layers.fc(xv, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, yv))
        fluid.optimizer.SGD(0.05).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    reader.start()
    losses = []
    from paddle_tpu.reader.queue import EOFException

    while True:
        try:
            feed = reader.next_feed()
        except EOFException:
            break
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert len(losses) == 12 * 3  # every record, every pass
    assert np.mean(losses[-6:]) < np.mean(losses[:6])


def test_open_files_multithreaded_covers_all_records(tmp_path):
    base = str(tmp_path / "mt")

    def batched():
        for i in range(9):
            yield (np.full((2, 3), i, "float32"),)

    paths = recordio_writer.convert_reader_to_recordio_files(base, 3, batched)
    assert len(paths) == 3

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        reader = fluid.layers.open_files(
            paths, shapes=[[-1, 3]], dtypes=["float32"], thread_num=3)
    reader.start()
    from paddle_tpu.reader.queue import EOFException

    seen = []
    while True:
        try:
            feed = reader.next_feed()
        except EOFException:
            break
        (arr,) = feed.values()
        seen.append(int(np.asarray(arr)[0, 0]))
    # all 9 records arrive exactly once, any interleaving
    assert sorted(seen) == list(range(9))


def test_open_files_multithreaded_pass_barrier_and_error(tmp_path):
    base = str(tmp_path / "pb")

    def batched():
        for i in range(4):
            yield (np.full((1,), i, "float32"),)

    paths = recordio_writer.convert_reader_to_recordio_files(base, 2, batched)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        reader = fluid.layers.open_files(
            paths, shapes=[[-1]], dtypes=["float32"], thread_num=2,
            pass_num=3)
    reader.start()
    from paddle_tpu.reader.queue import EOFException

    seen = []
    while True:
        try:
            feed = reader.next_feed()
        except EOFException:
            break
        (arr,) = feed.values()
        seen.append(int(np.asarray(arr)[0]))
    assert len(seen) == 4 * 3
    # pass barrier: each contiguous window of 4 records is one full pass
    for k in range(3):
        assert sorted(seen[4 * k:4 * (k + 1)]) == [0, 1, 2, 3]

    # a corrupt shard surfaces as an error, not a quiet partial EOF
    blob = bytearray(open(paths[0], "rb").read())
    blob[4 + 8 + 4 + 1] ^= 0xFF
    open(paths[0], "wb").write(bytes(blob))
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2):
        reader2 = fluid.layers.open_files(
            paths, shapes=[[-1]], dtypes=["float32"], thread_num=2)
    reader2.start()
    with pytest.raises((RuntimeError, EOFException)) as exc_info:
        for _ in range(20):
            reader2.next_feed()
    assert exc_info.type is RuntimeError
