"""Speculative decoding over the paged slot pool (PR 16): the tree
verify kernel, the drafters, and the end-to-end session.

* interpret-mode Pallas ``paged_tree_attention`` == composed reference
  at ragged/non-page-multiple base lengths, branched ancestor masks,
  empty and dead slots, and max-length clipping — and a Pallas failure
  trips the once-per-process reference fallback;
* the ``FLAGS_speculative`` on/off ORACLE: the same session streams
  BIT-identical tokens with speculation on and off, greedy AND seeded
  top-k (the drafter only ever moves throughput, never content), and
  the speculative path matches the dense slot decoder;
* a second batch through the warm speculative session adds ZERO fresh
  compiles — drafting/accept churn stays on the two cached executables;
* ``NgramDrafter`` is deterministic in the history and a state_dict
  round-trip re-proposes identically (the snapshot contract);
* ``chain_tree`` / ``tree_from_parents`` build the visibility masks the
  kernel contract requires (and reject malformed trees loudly).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags
from paddle_tpu.core import exec_cache
from paddle_tpu.kernels import paged_attention as pa
from paddle_tpu.observability import REGISTRY
from paddle_tpu.serving.generation import Sampler, SlotDecodeSession
from paddle_tpu.serving.speculative import (
    NgramDrafter,
    chain_tree,
    tree_from_parents,
)

VOCAB, SEQ, D = 24, 8, 32
CFG = dict(src_vocab_size=VOCAB, trg_vocab_size=VOCAB, n_layer=1,
           n_head=2, d_inner=64)


# -- tree masks --------------------------------------------------------------

def test_chain_tree_and_tree_from_parents():
    parent, anc = chain_tree(3)
    np.testing.assert_array_equal(parent, [-1, 0, 1, 2])
    np.testing.assert_array_equal(anc, np.tril(np.ones((4, 4))))
    # a branched tree: node's root path only, diagonal included
    anc = tree_from_parents([-1, 0, 0, 1])
    np.testing.assert_array_equal(anc, [[1, 0, 0, 0],
                                        [1, 1, 0, 0],
                                        [1, 0, 1, 0],
                                        [1, 1, 0, 1]])
    with pytest.raises(ValueError, match="anchor"):
        tree_from_parents([0, 0])
    with pytest.raises(ValueError, match="precede"):
        tree_from_parents([-1, 2, 1])


# -- kernel ------------------------------------------------------------------

def _pools(rng, S, H, dh, ps, npp, lengths):
    """Random pools + ragged table, page 0 reserved as trash (mirrors
    test_paged_attention)."""
    P = 1 + S * npp
    kp = rng.randn(P, H, ps, dh).astype("float32")
    vp = rng.randn(P, H, ps, dh).astype("float32")
    table = np.zeros((S, npp), np.int32)
    nxt = 1
    for s in range(S):
        n = pa.pages_for(max(int(lengths[s]), 1), ps)
        for p in range(n):
            table[s, p] = nxt
            nxt += 1
        for p in range(n, npp):
            table[s, p] = table[s, max(n - 1, 0)]
    return kp, vp, table


def _tree_case(seed=9):
    """S=5 ragged verify batch: off-grid base, empty slot, a base whose
    tree straddles max_length (tail rows trash-routed), and a DEAD slot
    (base -1); chain and branched ancestor masks mixed."""
    import jax.numpy as jnp

    S, H, dh, ps, npp, N = 5, 2, 16, 4, 8, 4
    base = np.array([7, 0, 25, 30, -1], np.int32)
    rng = np.random.RandomState(seed)
    q = rng.randn(S, H, N, dh).astype("float32")
    kp, vp, table = _pools(rng, S, H, dh, ps, npp,
                           np.minimum(np.maximum(base, 0) + N,
                                      npp * ps))
    anc = np.stack([
        chain_tree(N - 1)[1],
        tree_from_parents([-1, 0, 0, 1]),
        tree_from_parents([-1, 0, 1, 1]),
        chain_tree(N - 1)[1],
        tree_from_parents([-1, 0, 0, 0]),
    ]).astype("int64")
    args = (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(table), jnp.asarray(base), jnp.asarray(anc))
    return args, dict(max_length=npp * ps)


def test_tree_kernel_parity_ragged_lengths():
    args, kw = _tree_case()
    ref = np.asarray(pa.paged_tree_attention_reference(*args, **kw))
    ker = np.asarray(pa.paged_tree_attention(*args, force_pallas=True,
                                             **kw))
    assert np.isfinite(ker).all()
    np.testing.assert_allclose(ker, ref, rtol=2e-6, atol=2e-6)
    # the dead slot is exactly zero from both paths, never NaN bait
    assert np.abs(ker[4]).max() == 0.0 and np.abs(ref[4]).max() == 0.0
    # the empty slot's anchor row sees only itself -> its own V row
    assert np.abs(ker[1]).max() > 0.0


def test_tree_kernel_branch_isolation():
    """Two sibling branches never see each other: zeroing a sibling's
    K/V rows must not change a node's output (only its root path is
    visible), while zeroing an ANCESTOR row must."""
    import jax.numpy as jnp

    args, kw = _tree_case(seed=11)
    q, kp, vp, table, base, anc = args
    out = np.asarray(pa.paged_tree_attention_reference(*args, **kw))
    # slot 1 (base 0, tree [-1,0,0,1]): node 2's sibling branch is
    # nodes 1 and 3; its rows live at storage 1 and 3 of page
    # table[1, 0]
    pg = int(np.asarray(table)[1, 0])
    kp2, vp2 = np.asarray(kp).copy(), np.asarray(vp).copy()
    for row in (1, 3):
        kp2[pg, :, row] = 0.0
        vp2[pg, :, row] = 0.0
    out2 = np.asarray(pa.paged_tree_attention_reference(
        q, jnp.asarray(kp2), jnp.asarray(vp2), table, base, anc, **kw))
    np.testing.assert_allclose(out2[1, :, 2], out[1, :, 2],
                               rtol=1e-6, atol=1e-6)
    # zeroing its ANCHOR (ancestor, row 0) does move node 2
    kp3, vp3 = np.asarray(kp).copy(), np.asarray(vp).copy()
    kp3[pg, :, 0] = 0.0
    vp3[pg, :, 0] = 0.0
    out3 = np.asarray(pa.paged_tree_attention_reference(
        q, jnp.asarray(kp3), jnp.asarray(vp3), table, base, anc, **kw))
    assert np.abs(out3[1, :, 2] - out[1, :, 2]).max() > 1e-4


def test_tree_kernel_falls_back_once_per_process(monkeypatch):
    args, kw = _tree_case()
    want = np.asarray(pa.paged_tree_attention_reference(*args, **kw))
    calls = {"n": 0}

    def boom(*a, **k):
        calls["n"] += 1
        raise RuntimeError("pallas toolchain exploded")

    pa.reset_tree_kernel_fallback()
    monkeypatch.setattr(pa, "_tree_pallas", boom)
    try:
        got = np.asarray(pa.paged_tree_attention(*args,
                                                 force_pallas=True, **kw))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        assert pa.tree_kernel_fallback_tripped()
        np.asarray(pa.paged_tree_attention(*args, force_pallas=True,
                                           **kw))
        assert calls["n"] == 1  # attempted ONCE per process
        count = REGISTRY.counter(
            "paddle_tpu_kernel_fallbacks_total",
            "Pallas kernels abandoned for their reference path this "
            "process (once per kernel)",
            labels=("kernel",)).value(kernel="paged_tree_attention")
        assert count >= 1
    finally:
        pa.reset_tree_kernel_fallback()


# -- drafters ----------------------------------------------------------------

def test_ngram_drafter_is_deterministic_and_restores():
    d = NgramDrafter(num_slots=4, k=3, eos_id=2, order=3)
    states = {
        0: {"trg": np.array([1, 5, 6, 5, 6, 0, 0, 0]), "pos": 4},
        2: {"trg": np.array([1, 3, 3, 3, 3, 0, 0, 0]), "pos": 4},
    }
    a = d.propose(states)
    np.testing.assert_array_equal(a, d.propose(states))  # pure lookup
    # slot 0: suffix (5, 6) recurs at position 1 -> continuation (5, 6)
    np.testing.assert_array_equal(a[0], [5, 6, 2])
    # slot 2: suffix (3, 3, 3) recurs -> continuation (3,), eos-padded
    np.testing.assert_array_equal(a[2], [3, 2, 2])
    # slots not live propose pure eos (a free reject)
    assert (a[1] == 2).all() and (a[3] == 2).all()
    # the snapshot contract: a fresh drafter with the restored state
    # re-proposes identically (the lookup state IS the history)
    d2 = NgramDrafter(num_slots=4, k=3, eos_id=2, order=1)
    d2.load_state_dict(d.state_dict())
    np.testing.assert_array_equal(d2.propose(states), a)
    d.forget(0)  # stateless no-op, must not disturb proposals
    np.testing.assert_array_equal(d.propose(states), a)


# -- session: the on/off oracle ----------------------------------------------

@pytest.fixture(scope="module")
def trained(request):
    """One tiny trained transformer (copy task, so the n-gram drafter
    actually gets acceptances) + the dense slot decoder's greedy tokens
    as the cross-architecture oracle."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 21
    startup.random_seed = 21
    from paddle_tpu.executor import global_scope
    from paddle_tpu.models import transformer

    scope = global_scope()
    with fluid.program_guard(main, startup):
        loss, feeds, extras = transformer.build(
            dropout=0.0, label_smooth_eps=0.0, max_length=SEQ,
            d_model=D, **CFG)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(22)
    for _ in range(30):
        src = rng.randint(3, VOCAB, (16, SEQ)).astype("int64")
        trg = np.full_like(src, 1)
        trg[:, 1:] = src[:, :-1]
        exe.run(main, feed={
            "src_word": src,
            "src_len": np.full((16, 1), SEQ, "int64"),
            "trg_word": trg,
            "trg_len": np.full((16, 1), SEQ, "int64"),
            "label": src,
        }, fetch_list=[loss])
    src = rng.randint(3, VOCAB, (3, SEQ)).astype("int64")
    src_len = np.asarray([[SEQ], [SEQ - 3], [SEQ - 1]], "int64")
    dense = SlotDecodeSession(exe, num_slots=3, max_length=SEQ,
                              d_model=D, scope=scope, **CFG)
    want = dense.generate(src, src_len)
    return {"exe": exe, "scope": scope, "src": src, "src_len": src_len,
            "want": want}


def _spec_session(trained, **kw):
    args = dict(num_slots=3, max_length=SEQ, d_model=D, paged=True,
                page_size=4, steps=1,
                speculative={"k": 3, "drafter": "ngram"},
                scope=trained["scope"])
    args.update(CFG)
    args.update(kw)
    return SlotDecodeSession(trained["exe"], **args)


@pytest.fixture(autouse=True)
def _speculative_flag_restored():
    old = flags.get("speculative")
    yield
    flags.set_flag("speculative", old)


def test_greedy_stream_is_bit_identical_to_off_oracle(trained):
    """THE tentpole contract: the same session decodes the same batch
    with speculation on and off and the streams are BIT-identical —
    and both equal the dense slot decoder (a third architecture)."""
    sess = _spec_session(trained)
    flags.set_flag("speculative", "on")
    on = sess.generate(trained["src"], trained["src_len"])
    assert sess.spec_dispatches > 0 and sess.spec_proposed > 0
    assert sess.spec_accepted > 0, \
        "drafter never landed a token on a trained copy task"
    assert sess.pages_in_use == 0  # spec churn recycled everything
    flags.set_flag("speculative", "off")
    off = sess.generate(trained["src"], trained["src_len"])
    np.testing.assert_array_equal(on, off)
    np.testing.assert_array_equal(on, trained["want"])


def test_sampled_stream_is_bit_identical_to_off_oracle(trained):
    """Seeded top-k sampling under speculation: accepted tokens are
    re-sampled from TARGET logits with (seed, slot, position) keys, so
    the stream is bit-identical to the sequential path's."""
    sess = _spec_session(
        trained, sampler=Sampler(strategy="top_k", top_k=4,
                                 temperature=0.8, seed=11))
    flags.set_flag("speculative", "on")
    on = sess.generate(trained["src"], trained["src_len"])
    flags.set_flag("speculative", "off")
    off = sess.generate(trained["src"], trained["src_len"])
    np.testing.assert_array_equal(on, off)
    assert (on[:, 0] == 1).all()  # bos leads every row


def test_warm_speculative_rerun_compiles_nothing_fresh(trained):
    """A second batch through the warm speculative session — drafting,
    accepts, rejects, admissions, releases — adds ZERO fresh compiles:
    the decode hot path is the ONE cached verify executable (plus the
    warm admit/table programs)."""
    flags.set_flag("speculative", "on")
    sess = _spec_session(trained)
    first = sess.generate(trained["src"], trained["src_len"])
    before = exec_cache.stats()["fresh_compiles"]
    again = sess.generate(trained["src"], trained["src_len"])
    np.testing.assert_array_equal(again, first)
    assert exec_cache.stats()["fresh_compiles"] == before, (
        "warm speculative decode paid fresh compiles")
    assert sess.spec_dispatches > 0


def test_speculative_composes_with_fork_groups(trained):
    """COW isolation under speculation: two forked continuations of one
    admitted prefix decode to the SAME tokens as two independent
    admissions (greedy), with all pages recycled after."""
    sess = _spec_session(trained, num_groups=2)
    flags.set_flag("speculative", "on")
    src = trained["src"][0]
    slots = sess.admit_group(src, n=2, src_len=SEQ)
    done = {}
    for _ in range(40):
        done.update(sess.step())
        if len(done) >= len(slots):
            break
    flags.set_flag("speculative", "off")
    want = sess.generate(trained["src"][:1], trained["src_len"][:1])
    for slot in slots:
        np.testing.assert_array_equal(done[slot], want[0])
    assert sess.pages_in_use == 0
