"""Pass framework tests (framework/ir Pass + pass_builder parity)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core import passes


def _conv_bn_net():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [3, 8, 8])
        c = fluid.layers.conv2d(x, 4, 3, padding=1, bias_attr=True)
        b = fluid.layers.batch_norm(c)
        d = fluid.layers.dropout(b, 0.3,
                                 dropout_implementation="upscale_in_train")
        out = fluid.layers.relu(d)
    return main, startup, out


def test_registry_and_unknown_pass():
    assert "fuse_batch_norm" in passes.list_passes()
    with pytest.raises(KeyError):
        passes.get_pass("nope")
    with pytest.raises(ValueError):
        passes.register_pass("fuse_batch_norm", lambda p, scope=None: p)


def test_inference_strategy_pipeline_preserves_outputs():
    main, startup, out = _conv_bn_net()
    test_prog = main.clone(for_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x = np.random.RandomState(0).rand(2, 3, 8, 8).astype("float32")
    (before,) = exe.run(test_prog, feed={"x": x}, fetch_list=[out])

    pm = fluid.passes.PassManager(strategy="inference",
                                  passes=["delete_dropout"])
    test_prog = pm.apply(test_prog, scope=fluid.global_scope(),
                         feed_names=["x"], fetch_names=[out.name])
    types = [op.type for op in test_prog.global_block().ops]
    assert "batch_norm" not in types
    assert "dropout" not in types
    (after,) = exe.run(test_prog, feed={"x": x}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(after), np.asarray(before),
                               rtol=1e-4, atol=1e-4)


def test_custom_pass_applies_in_order():
    calls = []

    @passes.register_pass("_test_tag_a")
    def tag_a(program, scope=None, **kw):
        calls.append("a")
        return program

    @passes.register_pass("_test_tag_b")
    def tag_b(program, scope=None, **kw):
        calls.append("b")
        return program

    try:
        main = fluid.Program()
        fluid.passes.PassManager(["_test_tag_b", "_test_tag_a"]).apply(main)
        assert calls == ["b", "a"]
    finally:
        passes._PASSES.pop("_test_tag_a", None)
        passes._PASSES.pop("_test_tag_b", None)


def test_amp_strategy_marks_program():
    main, startup, out = _conv_bn_net()
    fluid.passes.PassManager(strategy="amp_bf16").apply(main)
    assert getattr(main, "_amp_dtype", None) == "bfloat16"


def test_delete_dropout_keeps_fetchable_output():
    """Fetching the (former) dropout output must keep working: the pass
    downgrades the op to assign instead of deleting it."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        d = fluid.layers.dropout(x, 0.5,
                                 dropout_implementation="upscale_in_train")
    test_prog = main.clone(for_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    passes.apply_pass(test_prog, "delete_dropout")
    types = [op.type for op in test_prog.global_block().ops]
    assert "dropout" not in types and "assign" in types
    xb = np.ones((2, 4), "float32")
    (out,) = exe.run(test_prog, feed={"x": xb}, fetch_list=[d])
    np.testing.assert_array_equal(np.asarray(out), xb)


def test_pass_kwargs_filtered_per_signature():
    @passes.register_pass("_test_no_kwargs")
    def strict(program, scope=None):
        return program

    try:
        main = fluid.Program()
        # feed/fetch kwargs must not leak into a pass that can't take them
        fluid.passes.PassManager(["_test_no_kwargs"]).apply(
            main, feed_names=["x"], fetch_names=["y"])
    finally:
        passes._PASSES.pop("_test_no_kwargs", None)
