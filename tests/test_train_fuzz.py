"""Differential TRAIN-STEP fuzz: XLA executor vs C++ interpreter.

tests/test_diff_fuzz.py holds the two engines together on INFERENCE
programs; this harness does the same for TRAINING — the C++ grad +
optimizer surface grew large in r5 (conv/pool/LSTM/GRU BPTT,
elementwise broadcast grads, structural grads, sgd/momentum/adam) and
hand-written parity tests only pin the configurations someone thought
of. Each seeded case builds a random small net from a training-safe
layer menu, appends a random optimizer, runs ONE step in both engines
from identical deterministic parameters, and compares loss plus EVERY
updated persistable (params, moments, velocities).

Outcomes per case: parameters match at f32 tolerance, or the C++
engine refuses explicitly (honest boundary). Silent divergence fails
with the seed.

Env knobs: PTPU_TRAIN_FUZZ_N (default 60), PTPU_TRAIN_FUZZ_SEED.
"""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import native

N_CASES = int(os.environ.get("PTPU_TRAIN_FUZZ_N", "60"))
BASE_SEED = int(os.environ.get("PTPU_TRAIN_FUZZ_SEED", "52260801"))

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason="native toolchain unavailable: %s" % native.last_error())


class CppRefusal(Exception):
    pass


def _random_body(rng, x, feed, B):
    """Random trunk over the training-safe layer menu; returns a 2-D
    [B, n] tensor."""
    kind = rng.choice(["mlp", "conv", "gru", "lstm", "embed", "attn",
                       "convbn"])
    if kind == "mlp":
        h = x
        for _ in range(int(rng.randint(1, 3))):
            h = fluid.layers.fc(
                h, int(rng.randint(3, 9)),
                act=str(rng.choice(["relu", "tanh", "sigmoid"])))
        return h
    if kind == "conv":
        hw = int(rng.choice([6, 8]))
        img = fluid.layers.data(name="img", shape=[2, hw, hw],
                                dtype="float32")
        feed["img"] = rng.rand(B, 2, hw, hw).astype("float32")
        v = fluid.layers.conv2d(
            img, num_filters=int(rng.randint(2, 5)),
            filter_size=int(rng.choice([1, 3])),
            padding=int(rng.choice([0, 1])),
            stride=int(rng.choice([1, 2])), act="relu")
        if rng.rand() < 0.5:
            v = fluid.layers.pool2d(
                v, pool_size=2, pool_stride=2,
                pool_type=str(rng.choice(["max", "avg"])),
                ceil_mode=bool(rng.rand() < 0.3))
        return fluid.layers.fc(v, int(rng.randint(3, 7)), act="tanh")
    if kind in ("gru", "lstm"):
        T = int(rng.randint(3, 6))
        D = int(rng.choice([2, 3]))
        mult = 3 if kind == "gru" else 4
        seqv = fluid.layers.data(name="seq", shape=[T, mult * D],
                                 dtype="float32")
        feed["seq"] = (rng.randn(B, T, mult * D) * 0.5).astype("float32")
        kwargs = {}
        if rng.rand() < 0.5:
            length = fluid.layers.data(name="len", shape=[1],
                                       dtype="int64")
            feed["len"] = rng.randint(1, T + 1, (B, 1)).astype("int64")
            kwargs["length"] = length
        if kind == "gru":
            h = fluid.layers.dynamic_gru(
                seqv, size=D, is_reverse=bool(rng.rand() < 0.5),
                **kwargs)
        else:
            h, _c = fluid.layers.dynamic_lstm(
                seqv, size=mult * D,
                use_peepholes=bool(rng.rand() < 0.5),
                is_reverse=bool(rng.rand() < 0.5), **kwargs)
        return fluid.layers.reduce_mean(h, dim=[1])
    if kind == "convbn":
        hw = int(rng.choice([6, 8]))
        img = fluid.layers.data(name="bimg", shape=[2, hw, hw],
                                dtype="float32")
        feed["bimg"] = rng.rand(B, 2, hw, hw).astype("float32")
        v = fluid.layers.conv2d(
            img, num_filters=int(rng.randint(2, 5)), filter_size=3,
            padding=1, bias_attr=False)
        v = fluid.layers.batch_norm(v, act="relu")   # TRAINING mode
        if rng.rand() < 0.5:
            sc = fluid.layers.conv2d(img, num_filters=v.shape[1],
                                     filter_size=1, bias_attr=False)
            v = fluid.layers.elementwise_add(v, sc, act="relu")
        return fluid.layers.fc(v, int(rng.randint(3, 7)), act="tanh")
    if kind == "attn":
        T, H, dh = int(rng.choice([3, 4])), int(rng.choice([2, 4])), 4
        kvg = int(rng.choice([1, 2])) if H == 4 else 1
        D = H * dh
        seqx = fluid.layers.data(name="ax", shape=[T, D],
                                 dtype="float32")
        feed["ax"] = (rng.randn(B, T, D) * 0.5).astype("float32")
        nx = fluid.layers.layer_norm(seqx, begin_norm_axis=2)

        def heads(tv, nh):
            tv = fluid.layers.reshape(tv, [-1, T, nh, dh])
            return fluid.layers.transpose(tv, [0, 2, 1, 3])

        q = heads(fluid.layers.fc(nx, D, num_flatten_dims=2,
                                  bias_attr=False), H)
        k = heads(fluid.layers.fc(nx, (H // kvg) * dh,
                                  num_flatten_dims=2,
                                  bias_attr=False), H // kvg)
        v = heads(fluid.layers.fc(nx, (H // kvg) * dh,
                                  num_flatten_dims=2,
                                  bias_attr=False), H // kvg)
        att = fluid.layers.scaled_dot_product_attention(
            q, k, v, causal=bool(rng.rand() < 0.5),
            window=int(rng.choice([0, 2])), kv_group=kvg,
            impl="reference")
        att = fluid.layers.reshape(
            fluid.layers.transpose(att, [0, 2, 1, 3]), [-1, T, D])
        return fluid.layers.reduce_mean(att, dim=[1])
    vocab = int(rng.randint(8, 20))
    T = int(rng.randint(2, 5))
    ids = fluid.layers.data(name="ids", shape=[T], dtype="int64")
    feed["ids"] = rng.randint(0, vocab, (B, T)).astype("int64")
    emb = fluid.layers.embedding(ids, size=[vocab, int(rng.choice([4, 6]))])
    pooled = fluid.layers.reduce_mean(emb, dim=[1])
    return fluid.layers.fc(pooled, int(rng.randint(3, 7)), act="tanh")


def _run_case(seed):
    rng = np.random.RandomState(seed)
    B = int(rng.randint(2, 5))
    from paddle_tpu.core.program_bin import serialize_program
    from paddle_tpu.testing import set_deterministic_params

    fluid.unique_name.switch()
    feed = {}
    with fluid.scope_guard(fluid.executor.Scope()):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[5], dtype="float32")
            feed["x"] = rng.randn(B, 5).astype("float32")
            trunk = _random_body(rng, x, feed, B)
            nclass = int(rng.randint(2, 5))
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            feed["label"] = rng.randint(0, nclass, (B, 1)).astype("int64")
            logits = fluid.layers.fc(trunk, nclass)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            opt = rng.choice(["sgd", "momentum", "adam"])
            if opt == "sgd":
                fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
            elif opt == "momentum":
                fluid.optimizer.Momentum(
                    learning_rate=0.1, momentum=0.9,
                    use_nesterov=bool(rng.rand() < 0.5)).minimize(loss)
            else:
                fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        scope = fluid.executor.global_scope()
        set_deterministic_params(main, scope)
        params = {n: np.asarray(scope.get_value(n))
                  for n in scope.local_var_names()
                  if scope.get_value(n) is not None}
        (xla_loss,) = exe.run(main, feed=feed, fetch_list=[loss])
        after_xla = {n: np.asarray(scope.get_value(n))
                     for n in scope.local_var_names()
                     if scope.get_value(n) is not None}

    lib = native.get_lib()
    blob = serialize_program(main)
    prog = lib.ptpu_program_parse(bytes(blob), len(blob))
    if not prog:
        raise CppRefusal(native.last_error())
    try:
        ns = native.NativeScope()
        for name, val in params.items():
            arr = np.asarray(val)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            ns.set(name, arr)
        for name, val in feed.items():
            ns.set(name, val)
        rc = lib.ptpu_interp_run(prog, ns._h, 0)
        if rc != 0:
            raise CppRefusal(native.last_error())
        cpp_loss = np.ravel(ns.get(loss.name))[0]
        np.testing.assert_allclose(
            cpp_loss, np.ravel(np.asarray(xla_loss))[0],
            rtol=1e-4, atol=1e-5,
            err_msg="train-step loss diverged (seed %d)" % seed)
        for name, want in sorted(after_xla.items()):
            if want.dtype.kind != "f":
                continue
            got = ns.get(name)
            assert got is not None, (
                "updated var %r missing in C++ scope (seed %d)"
                % (name, seed))
            np.testing.assert_allclose(
                got, want, rtol=2e-3, atol=1e-5,
                err_msg="updated %r diverged (seed %d)" % (name, seed))
    finally:
        lib.ptpu_program_destroy(prog)
    return "match"


# outcomes recorded by the parametrized pass so the vacuity guard
# doesn't pay for a second run of the same seeds
_OUTCOMES = {}


@pytest.mark.parametrize("seed", range(BASE_SEED, BASE_SEED + N_CASES))
def test_train_fuzz(seed):
    try:
        _run_case(seed)
        _OUTCOMES[seed] = ("match", "")
    except CppRefusal as e:
        _OUTCOMES[seed] = ("refused", str(e)[:60])


def test_train_fuzz_mostly_compares():
    """Vacuity guard: most cases must actually compare (a C++ engine
    refusing every training program would pass the per-seed tests).
    Uses the parametrized pass's recorded outcomes; falls back to a
    fresh slice under -k selection."""
    outcomes = dict(_OUTCOMES)
    if len(outcomes) < min(N_CASES, 15):
        for seed in range(BASE_SEED, BASE_SEED + min(N_CASES, 30)):
            if seed in outcomes:
                continue
            try:
                _run_case(seed)
                outcomes[seed] = ("match", "")
            except CppRefusal as e:
                outcomes[seed] = ("refused", str(e)[:60])
    n = len(outcomes)
    matched = sum(1 for k, _ in outcomes.values() if k == "match")
    refusals = [d for k, d in outcomes.values() if k == "refused"]
    assert matched >= int(0.7 * n), (
        "only %d/%d train-fuzz cases compared; refusals: %r"
        % (matched, n, refusals[:8]))
