"""In-program evaluator tests (python/paddle/fluid/evaluator.py parity):
ChunkEvaluator / EditDistance accumulate across minibatches."""

import numpy as np

import paddle_tpu as fluid


def test_chunk_evaluator_accumulates():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        pred = fluid.layers.data("pred", [6], dtype="int64")
        label = fluid.layers.data("label", [6], dtype="int64")
        length = fluid.layers.data("len", [1], dtype="int64")
        ev = fluid.evaluator.ChunkEvaluator(pred, label, "IOB", 2,
                                            length=length)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    ev.reset()
    # tag = chunk_type * 2 + (0=B, 1=I); O = 4 (>= num_types * num_tags)
    # batch 1: perfect prediction; batch 2: all-O prediction (no chunks)
    seq = np.array([[0, 1, 4, 2, 3, 4]], "int64")  # B-0 I-0 O B-1 I-1 O
    none = np.full((1, 6), 4, "int64")
    ln = np.array([[6]], "int64")
    for pred_v, label_v in [(seq, seq), (none, seq)]:
        counts = exe.run(main, feed={"pred": pred_v, "label": label_v,
                                     "len": ln},
                         fetch_list=ev.metrics)
        ev.update(counts)
    precision, recall, f1 = ev.eval()
    # 2 correct of 2 inferred chunks; 2 correct of 4 labeled chunks
    np.testing.assert_allclose(precision, 1.0)
    np.testing.assert_allclose(recall, 0.5)
    np.testing.assert_allclose(f1, 2 / 3, rtol=1e-6)
    ev.reset()
    assert ev.eval() == (0.0, 0.0, 0.0)


def test_edit_distance_evaluator_accumulates():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        hyp = fluid.layers.data("hyp", [4], dtype="int64")
        ref = fluid.layers.data("ref", [4], dtype="int64")
        ev = fluid.evaluator.EditDistance(hyp, ref, normalized=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    ev.reset()
    h = np.array([[1, 2, 3, 4], [1, 2, 3, 4]], "int64")
    r = np.array([[1, 2, 3, 4], [4, 3, 2, 1]], "int64")
    fetched = exe.run(main, feed={"hyp": h, "ref": r},
                      fetch_list=ev.metrics)
    ev.update(fetched)
    avg, err_rate = ev.eval()
    assert err_rate == 0.5  # one of two sequences differs
    assert avg > 0


def test_detection_map_evaluator_accumulates():
    det = np.zeros((1, 2, 6), "float32")
    det[0, 0] = [1, 0.9, 0.1, 0.1, 0.4, 0.4]
    det[0, 1] = [-1, 0, 0, 0, 0, 0]
    gt_label = np.array([[1]], "int32")
    gt_box = np.array([[[0.1, 0.1, 0.4, 0.4]]], "float32")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        dv = fluid.layers.data("d", [2, 6])
        lv = fluid.layers.data("l", [1], dtype="int32")
        bv = fluid.layers.data("b", [1, 4])
        ev = fluid.evaluator.DetectionMAP(dv, lv, bv, class_num=2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"d": det, "l": gt_label, "b": gt_box}
    (batch_map,) = exe.run(main, feed=feed, fetch_list=ev.metrics)
    ev.update(det, gt_label, gt_box)
    np.testing.assert_allclose(float(np.ravel(batch_map)[0]), 1.0, atol=1e-5)
    np.testing.assert_allclose(ev.eval(), 1.0, atol=1e-6)
    ev.reset()
    assert ev.eval() == 0.0
