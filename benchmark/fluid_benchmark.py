"""Model-zoo benchmark CLI.

Reference parity: benchmark/fluid/fluid_benchmark.py + args.py — one driver
over the models zoo with --model / --batch_size / --update_method /
--device, reporting per-pass throughput. TPU-first differences:
  * --update_method local|spmd|multiproc: `spmd` runs GSPMD data-parallel
    over the visible devices via ParallelExecutor (the gpus>1 path);
    `multiproc` expects torchrun-style env (PADDLE_TRAINER_ID/
    PADDLE_TRAINERS) and uses jax.distributed, the nccl2 analog.
  * --device TPU|CPU (GPU has no meaning here).
  * --use_fake_data feeds one synthetic host batch repeatedly;
    --use_reader_op draws input on-device from the in-graph random reader
    (no host link traffic at all, the bench.py configuration).
  * --amp applies the bf16 AMP program rewrite.

Usage:
    python benchmark/fluid_benchmark.py --model resnet --batch_size 32 \
        --iterations 30 --device CPU
"""

import argparse
import os
import sys
import time

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

BENCHMARK_MODELS = [
    "mnist", "resnet", "vgg", "se_resnext", "stacked_lstm",
    "machine_translation", "transformer",
]


def parse_args():
    parser = argparse.ArgumentParser("paddle_tpu model benchmarks.")
    parser.add_argument("--model", type=str, choices=BENCHMARK_MODELS,
                        default="resnet")
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--learning_rate", type=float, default=0.001)
    parser.add_argument("--skip_batch_num", type=int, default=5,
                        help="warmup iterations excluded from timing")
    parser.add_argument("--iterations", type=int, default=80)
    parser.add_argument("--pass_num", type=int, default=1)
    parser.add_argument("--device", type=str, default="TPU",
                        choices=["TPU", "CPU"])
    parser.add_argument("--update_method", type=str, default="local",
                        choices=["local", "spmd", "multiproc"])
    parser.add_argument("--num_devices", type=int, default=0,
                        help="devices for spmd (0 = all visible)")
    parser.add_argument("--infer_only", action="store_true")
    parser.add_argument("--use_fake_data", action="store_true")
    parser.add_argument("--use_reader_op", action="store_true",
                        help="in-graph random reader instead of host feeds")
    parser.add_argument("--amp", action="store_true",
                        help="bf16 AMP program rewrite")
    parser.add_argument("--pallas_rnn", action="store_true",
                        help="route dynamic_lstm/gru through the fused "
                             "Pallas kernels (FLAGS_use_pallas_lstm/gru)")
    parser.add_argument("--memory_optimize", action="store_true")
    parser.add_argument("--gradient_merge", type=int, default=0,
                        metavar="K",
                        help="accumulate K microbatches per optimizer "
                             "step (multi_batch_merge capability)")
    parser.add_argument("--fuse_elewise", action="store_true",
                        help="run the fuse_elewise_add_act pass "
                             "(BuildStrategy.fuse_elewise_add_act_ops)")
    parser.add_argument("--profile", action="store_true",
                        help="profile the timed region (chrome trace)")
    parser.add_argument("--profile_path", type=str,
                        default="/tmp/fluid_benchmark_trace")
    return parser.parse_args()


def _image_inputs(fluid, args, shape, classes):
    """(image var, label var): host-fed data layers, or the in-graph
    random reader when --use_reader_op (no host link traffic)."""
    bs = args.batch_size
    if args.use_reader_op:
        img, label = fluid.layers.random_data_generator(
            shapes=[[bs, *shape], [bs, 1]], dtypes=["float32", "int64"],
            int_high=classes - 1)
        return img, label, {}
    rng = np.random.RandomState(7)
    img = fluid.layers.data("pixel", list(shape))
    label = fluid.layers.data("label", [1], dtype="int64")
    batch = {"pixel": rng.rand(bs, *shape).astype("float32"),
             "label": rng.randint(0, classes, (bs, 1)).astype("int64")}
    return img, label, batch


def _build_model(fluid, args):
    """Returns (loss, feed_fn) — feed_fn() -> feed dict for one batch."""
    bs = args.batch_size
    rng = np.random.RandomState(7)
    name = args.model
    if args.use_reader_op and name not in (
            "mnist", "resnet", "vgg", "se_resnext"):
        raise SystemExit(
            "--use_reader_op is wired for the image models only; "
            "%s feeds from the host" % name)

    if name == "mnist":
        from paddle_tpu import nets

        shape, classes = (1, 28, 28), 10
        img, label, batch = _image_inputs(fluid, args, shape, classes)
        c1 = nets.simple_img_conv_pool(img, filter_size=5, num_filters=20,
                                       pool_size=2, pool_stride=2,
                                       act="relu")
        c2 = nets.simple_img_conv_pool(c1, filter_size=5, num_filters=50,
                                       pool_size=2, pool_stride=2,
                                       act="relu")
        predict = fluid.layers.fc(c2, classes, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(predict, label))
    elif name in ("resnet", "vgg", "se_resnext"):
        shape = (3, 224, 224) if name != "vgg" else (3, 32, 32)
        classes = 1000 if name != "vgg" else 10
        img, label, batch = _image_inputs(fluid, args, shape, classes)
        if name == "resnet":
            from paddle_tpu.models import resnet

            predict = resnet.resnet_imagenet(img, classes)
        elif name == "vgg":
            from paddle_tpu.models.vgg import vgg16_bn_drop

            net = vgg16_bn_drop(img)
            predict = fluid.layers.fc(net, classes, act="softmax")
        else:
            from paddle_tpu.models.se_resnext import se_resnext_imagenet

            predict = se_resnext_imagenet(img, classes)
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(predict, label))
    elif name == "stacked_lstm":
        from paddle_tpu.models import stacked_lstm as m

        seq = 80
        loss, feeds, _ = m.build(seq_len=seq)
        batch = {
            "words": rng.randint(0, 5000, (bs, seq)).astype("int64"),
            "length": np.full((bs, 1), seq, "int64"),
            "label": rng.randint(0, 2, (bs, 1)).astype("int64"),
        }
    elif name == "machine_translation":
        from paddle_tpu.models import machine_translation as m

        loss, feeds, _ = m.build()
        seq = 32
        # build() returns (src, src_len, tgt, label, label_mask) vars; key
        # the batch by their actual names, no positional remapping
        src, src_len, tgt, label, label_mask = feeds
        batch = {
            src.name: rng.randint(1, 1000, (bs, seq)).astype("int64"),
            src_len.name: np.full((bs, 1), seq, "int64"),
            tgt.name: rng.randint(1, 1000, (bs, seq)).astype("int64"),
            label.name: rng.randint(1, 1000, (bs, seq)).astype("int64"),
            label_mask.name: np.ones((bs, seq), "float32"),
        }
    elif name == "transformer":
        from paddle_tpu.models import transformer as m

        seq = 64
        loss, feeds, _ = m.build(max_length=seq)
        batch = {
            "src_word": rng.randint(1, 1000, (bs, seq)).astype("int64"),
            "src_len": np.full((bs, 1), seq, "int64"),
            "trg_word": rng.randint(1, 1000, (bs, seq)).astype("int64"),
            "trg_len": np.full((bs, 1), seq, "int64"),
            "label": rng.randint(1, 1000, (bs, seq)).astype("int64"),
        }
        batch = {k: v for k, v in batch.items()
                 if any(f.name == k for f in feeds)}
    else:
        raise ValueError(name)

    return loss, (lambda: batch)


def main():
    args = parse_args()

    import jax

    if args.device == "CPU":
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid

    if args.update_method == "multiproc":
        from paddle_tpu.parallel import init_distributed

        init_distributed()

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 1
    startup.random_seed = 1
    with fluid.program_guard(main_prog, startup):
        loss, feed_fn = _build_model(fluid, args)
        if not args.infer_only:
            fluid.optimizer.Adam(args.learning_rate).minimize(loss)
    if args.infer_only:
        main_prog = main_prog.clone(for_test=True)
    if args.amp:
        from paddle_tpu.transpiler import rewrite_program_amp

        rewrite_program_amp(main_prog, "bfloat16")
    if args.pallas_rnn:
        from paddle_tpu import flags as _flags

        _flags.set_flag("use_pallas_lstm", True)
        _flags.set_flag("use_pallas_gru", True)
    if args.memory_optimize:
        from paddle_tpu.transpiler import memory_optimize

        memory_optimize(main_prog)
    if args.gradient_merge > 1:
        from paddle_tpu.transpiler import rewrite_program_gradient_merge

        rewrite_program_gradient_merge(
            main_prog, startup, k_steps=args.gradient_merge, avg=True)
    if args.fuse_elewise and args.update_method == "local":
        from paddle_tpu.core.passes import apply_pass

        apply_pass(main_prog, "fuse_elewise_add_act")

    place = fluid.CPUPlace() if args.device == "CPU" else fluid.TPUPlace()

    if args.update_method in ("spmd", "multiproc"):
        build_strategy = fluid.BuildStrategy()
        build_strategy.fuse_elewise_add_act_ops = bool(args.fuse_elewise)
        exe = fluid.Executor(place)
        exe.run(startup)
        pexe = fluid.ParallelExecutor(
            use_tpu=args.device != "CPU",
            loss_name=loss.name,
            main_program=main_prog,
            build_strategy=build_strategy,
            num_devices=args.num_devices or None,
        )
        run = lambda fetch: pexe.run(
            fetch_list=fetch, feed=feed_fn())
    else:
        exe = fluid.Executor(place)
        exe.run(startup)
        run = lambda fetch: exe.run(
            main_prog, feed=feed_fn(), fetch_list=fetch)

    for pass_id in range(args.pass_num):
        for i in range(args.skip_batch_num):
            run([])
        run([loss])  # sync

        if args.profile and pass_id == 0:
            from paddle_tpu import profiler

            prof = profiler.profiler("All", profile_path=args.profile_path)
            prof.__enter__()
        t0 = time.perf_counter()
        for i in range(args.iterations - 1):
            if args.profile and pass_id == 0:
                with profiler.RecordEvent("iter_%d" % i):
                    run([])
            else:
                run([])
        out = run([loss])
        dt = time.perf_counter() - t0
        if args.profile and pass_id == 0:
            prof.__exit__(None, None, None)
            print("chrome trace written to %s" % args.profile_path)

        lv = float(np.ravel(np.asarray(out[0]))[0])
        ips = args.iterations * args.batch_size / dt
        print("pass %d: loss=%.4f, %.2f samples/sec (%.1f ms/iter)"
              % (pass_id, lv, ips, 1000.0 * dt / args.iterations))


if __name__ == "__main__":
    main()
