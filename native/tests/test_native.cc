// Assert-based unit tests for the native runtime (cc_test-style,
// cmake/generic.cmake:303 role). Covers recordio round-trip + corruption
// detection, blocking-queue producer/consumer + close semantics, scope
// parent lookup, and PTPB parse/re-serialize identity.

// Assertions ARE the test; keep them in release builds.
#undef NDEBUG
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "ptpu/c_api.h"
#include "../src/json.h"
#include "../src/master.h"

static void test_recordio() {
  const char* path = "/tmp/ptpu_test.recordio";
  ptpu_recordio_writer* w = ptpu_recordio_writer_open(path);
  assert(w != nullptr);
  std::string a = "hello records";
  std::string b(1 << 16, 'x');  // 64 KiB record
  assert(ptpu_recordio_write(w, a.data(), a.size()) == 0);
  assert(ptpu_recordio_write(w, b.data(), b.size()) == 0);
  assert(ptpu_recordio_write(w, nullptr, 0) == 0);  // empty record
  assert(ptpu_recordio_writer_close(w) == 0);

  ptpu_recordio_reader* r = ptpu_recordio_reader_open(path);
  assert(r != nullptr);
  int64_t n = ptpu_recordio_next(r);
  assert(n == (int64_t)a.size());
  std::vector<char> buf(n);
  assert(ptpu_recordio_read(r, buf.data(), n) == 0);
  assert(std::memcmp(buf.data(), a.data(), n) == 0);
  assert(ptpu_recordio_next(r) == (int64_t)b.size());
  // Next() without Read() discards the previous payload.
  assert(ptpu_recordio_next(r) == 0);   // the empty third record
  assert(ptpu_recordio_next(r) == -1);  // EOF
  ptpu_recordio_reader_close(r);

  // Corrupt a payload byte -> CRC failure.
  std::FILE* f = std::fopen(path, "r+b");
  std::fseek(f, 4 + 8 + 4 + 2, SEEK_SET);  // into record 1's payload
  std::fputc('X', f);
  std::fclose(f);
  r = ptpu_recordio_reader_open(path);
  assert(ptpu_recordio_next(r) == -2);
  ptpu_recordio_reader_close(r);
  std::remove(path);
  std::printf("recordio ok\n");
}

static void test_queue() {
  ptpu_queue* q = ptpu_queue_create(2);
  assert(ptpu_queue_capacity(q) == 2);

  // Producer pushes 50 records; consumer pops them all.
  std::thread producer([q] {
    for (int i = 0; i < 50; ++i) {
      int payload = i * 3;
      int rc = ptpu_queue_push(q, &payload, sizeof(payload), -1);
      assert(rc == 0);
    }
    ptpu_queue_close(q);
  });
  int got = 0, sum = 0;
  for (;;) {
    int payload = 0;
    int64_t n = ptpu_queue_pop(q, &payload, sizeof(payload), -1);
    if (n == 0) break;  // closed and drained
    assert(n == sizeof(payload));
    sum += payload;
    ++got;
  }
  producer.join();
  assert(got == 50);
  assert(sum == 3 * (49 * 50 / 2));
  assert(ptpu_queue_is_closed(q) == 1);

  // Reopen for a new epoch; timeout semantics.
  ptpu_queue_reopen(q);
  int x = 7;
  assert(ptpu_queue_push(q, &x, sizeof(x), 10) == 0);
  assert(ptpu_queue_push(q, &x, sizeof(x), 10) == 0);
  assert(ptpu_queue_push(q, &x, sizeof(x), 10) == -2);  // full -> timeout
  int64_t peek = ptpu_queue_pop(q, nullptr, 0, 10);
  assert(peek == sizeof(x));  // size query leaves the record queued
  assert(ptpu_queue_size(q) == 2);
  ptpu_queue_destroy(q);
  std::printf("queue ok\n");
}

static void test_scope() {
  ptpu_scope* root = ptpu_scope_create();
  float w[6] = {1, 2, 3, 4, 5, 6};
  int64_t dims[2] = {2, 3};
  assert(ptpu_scope_set(root, "w", "float32", dims, 2, w, sizeof(w)) == 0);

  ptpu_scope* child = ptpu_scope_new_child(root);
  // FindVar walks to the parent.
  char dtype[32];
  int64_t got_dims[16];
  int32_t ndim = 0;
  int64_t nbytes =
      ptpu_scope_get_meta(child, "w", dtype, sizeof(dtype), got_dims, &ndim);
  assert(nbytes == (int64_t)sizeof(w));
  assert(std::strcmp(dtype, "float32") == 0);
  assert(ndim == 2 && got_dims[0] == 2 && got_dims[1] == 3);
  float back[6];
  assert(ptpu_scope_get_data(child, "w", back, sizeof(back)) == 0);
  assert(std::memcmp(back, w, sizeof(w)) == 0);

  // Local shadowing: child's own var wins.
  float v = 9;
  int64_t d1[1] = {1};
  ptpu_scope_set(child, "w", "float32", d1, 1, &v, sizeof(v));
  assert(ptpu_scope_get_meta(child, "w", nullptr, 0, nullptr, nullptr) ==
         (int64_t)sizeof(v));
  assert(ptpu_scope_get_meta(root, "w", nullptr, 0, nullptr, nullptr) ==
         (int64_t)sizeof(w));
  assert(ptpu_scope_num_vars(child) == 1);
  assert(ptpu_scope_get_meta(child, "absent", nullptr, 0, nullptr,
                             nullptr) == -1);
  ptpu_scope_destroy(child);  // wrapper only; tree dies with root
  ptpu_scope_destroy(root);
  std::printf("scope ok\n");
}

static void test_program_roundtrip(const char* ptpb_path) {
  // When the Python test wrote a program file, parse + re-serialize and
  // require byte identity (lockstep guarantee with program_bin.py).
  std::FILE* f = std::fopen(ptpb_path, "rb");
  if (f == nullptr) {
    std::printf("program roundtrip skipped (no input file)\n");
    return;
  }
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> data(size);
  assert(std::fread(data.data(), 1, size, f) == (size_t)size);
  std::fclose(f);

  ptpu_program* p = ptpu_program_parse(data.data(), data.size());
  assert(p != nullptr);
  assert(ptpu_program_num_blocks(p) >= 1);
  assert(ptpu_program_num_ops(p, 0) >= 1);
  char op0[128];
  assert(ptpu_program_op_type(p, 0, 0, op0, sizeof(op0)) > 0);
  int64_t need = ptpu_program_serialize(p, nullptr, 0);
  assert(need == (int64_t)data.size());
  std::vector<uint8_t> out(need);
  ptpu_program_serialize(p, out.data(), out.size());
  assert(out == data);
  ptpu_program_destroy(p);
  std::printf("program roundtrip ok (%ld bytes, first op %s)\n", size, op0);
}

static void test_json_codec() {
  using ptpu::json::Value;
  // round-trip the master's wire/snapshot shapes, incl. unicode escapes
  const std::string text =
      "{\"chunks\": [\"a,b\", 3, 2.5, null, true,"
      " \"\\ud83d\\ude00\\u00e9\"], \"cur_pass\": 7}";
  Value v = ptpu::json::parse(text);
  assert(v["cur_pass"].as_int() == 7);
  const auto& arr = v["chunks"].as_array();
  assert(arr.size() == 6);
  assert(arr[0].as_string() == "a,b");
  assert(arr[1].as_int() == 3);
  assert(arr[2].as_double() == 2.5);
  assert(arr[3].is_null());
  assert(arr[4].as_bool());
  assert(arr[5].as_string() == "\xF0\x9F\x98\x80\xC3\xA9");  // UTF-8
  // dump -> parse -> dump is a fixed point
  std::string d1 = v.dump();
  std::string d2 = ptpu::json::parse(d1).dump();
  assert(d1 == d2);
  // malformed inputs raise, never crash
  for (const char* bad : {"{", "[1,", "\"\\u12g4\"", "\"\\ud800\"",
                          "01x", "{\"a\" 1}"}) {
    bool threw = false;
    try {
      ptpu::json::parse(bad);
    } catch (const std::exception&) {
      threw = true;
    }
    assert(threw);
  }
  std::printf("json codec ok\n");
}

static void test_master_service() {
  using ptpu::master::MasterService;
  using ptpu::master::Task;
  MasterService svc(2, /*timeout_s=*/30.0, /*failure_max=*/2, "");
  ptpu::json::Array chunks;
  for (int i = 0; i < 5; ++i) chunks.push_back(ptpu::json::Value(i));
  svc.SetDataset(chunks);  // -> 3 tasks (2+2+1)
  Task t;
  std::string err;
  int got = 0;
  while (svc.GetTask(0, &t, &err)) {
    got += (int)t.chunks.size();
    assert(svc.TaskFinished(t.task_id));
  }
  assert(got == 5);
  assert(err == ptpu::master::kPassBefore ||
         err == ptpu::master::kNoMoreAvailable);
  // pass rolled; old-pass fetches are rejected, new pass serves again
  assert(!svc.GetTask(0, &t, &err) && err == ptpu::master::kPassBefore);
  assert(svc.GetTask(1, &t, &err));
  // stale-epoch failure reports are rejected
  assert(!svc.TaskFailed(t.task_id, ptpu::json::Value((int64_t)0)));
  assert(svc.TaskFailed(t.task_id, ptpu::json::Value(t.epoch)));
  std::printf("master service ok\n");
}

int main(int argc, char** argv) {
  test_recordio();
  test_queue();
  test_scope();
  test_json_codec();
  test_master_service();
  test_program_roundtrip(argc > 1 ? argv[1]
                                  : "/tmp/ptpu_test_program.ptpb");
  std::printf("ALL NATIVE TESTS PASSED\n");
  return 0;
}
