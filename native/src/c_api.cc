// C API surface over the native host runtime (see include/ptpu/c_api.h).
// Single translation unit; consumed from Python via ctypes.

#include "ptpu/c_api.h"

#include <cstdio>
#include <cstring>
#include <string>

#include "interp.h"
#include "program.h"
#include "queue.h"
#include "recordio.h"
#include "scope.h"

namespace {
thread_local std::string g_last_error;
void set_error(const std::string& msg) { g_last_error = msg; }
}  // namespace

extern "C" {

const char* ptpu_last_error(void) { return g_last_error.c_str(); }

// ---------------------------------------------------------------------------
// recordio
// ---------------------------------------------------------------------------

struct ptpu_recordio_writer {
  ptpu::RecordIOWriter impl;
  explicit ptpu_recordio_writer(const char* path) : impl(path) {}
};

struct ptpu_recordio_reader {
  ptpu::RecordIOReader impl;
  explicit ptpu_recordio_reader(const char* path) : impl(path) {}
};

ptpu_recordio_writer* ptpu_recordio_writer_open(const char* path) {
  auto* w = new ptpu_recordio_writer(path);
  if (!w->impl.ok()) {
    set_error(std::string("cannot open for write: ") + path);
    delete w;
    return nullptr;
  }
  return w;
}

int ptpu_recordio_write(ptpu_recordio_writer* w, const void* data,
                        uint64_t len) {
  if (w == nullptr) return -1;
  if (!w->impl.Write(data, len)) {
    set_error("recordio write failed");
    return -1;
  }
  return 0;
}

int ptpu_recordio_writer_close(ptpu_recordio_writer* w) {
  if (w == nullptr) return -1;
  int rc = w->impl.Close() ? 0 : -1;
  delete w;
  return rc;
}

ptpu_recordio_reader* ptpu_recordio_reader_open(const char* path) {
  auto* r = new ptpu_recordio_reader(path);
  if (!r->impl.ok()) {
    set_error(std::string("cannot open recordio file: ") + path);
    delete r;
    return nullptr;
  }
  return r;
}

int64_t ptpu_recordio_next(ptpu_recordio_reader* r) {
  if (r == nullptr) return -1;
  int64_t n = r->impl.Next();
  if (n == -2) set_error("recordio record corrupt (crc/length mismatch)");
  return n;
}

int ptpu_recordio_read(ptpu_recordio_reader* r, void* out, uint64_t len) {
  if (r == nullptr || len < r->impl.buffer().size()) {
    set_error("recordio read buffer too small");
    return -1;
  }
  std::memcpy(out, r->impl.buffer().data(), r->impl.buffer().size());
  return 0;
}

int ptpu_recordio_reader_close(ptpu_recordio_reader* r) {
  if (r == nullptr) return -1;
  r->impl.Close();
  delete r;
  return 0;
}

// ---------------------------------------------------------------------------
// blocking queue
// ---------------------------------------------------------------------------

struct ptpu_queue {
  ptpu::BlockingByteQueue impl;
  explicit ptpu_queue(uint64_t cap) : impl(cap) {}
};

ptpu_queue* ptpu_queue_create(uint64_t capacity) {
  return new ptpu_queue(capacity == 0 ? 1 : capacity);
}

int ptpu_queue_push(ptpu_queue* q, const void* data, uint64_t len,
                    int64_t timeout_ms) {
  return q->impl.Push(data, len, timeout_ms);
}

int64_t ptpu_queue_pop(ptpu_queue* q, void* out, uint64_t max_len,
                       int64_t timeout_ms) {
  return q->impl.Pop(out, max_len, timeout_ms);
}

uint64_t ptpu_queue_size(ptpu_queue* q) { return q->impl.Size(); }
uint64_t ptpu_queue_capacity(ptpu_queue* q) { return q->impl.Capacity(); }
void ptpu_queue_close(ptpu_queue* q) { q->impl.Close(); }
void ptpu_queue_kill(ptpu_queue* q) { q->impl.Kill(); }
int ptpu_queue_is_closed(ptpu_queue* q) { return q->impl.IsClosed() ? 1 : 0; }
void ptpu_queue_reopen(ptpu_queue* q) { q->impl.Reopen(); }
void ptpu_queue_destroy(ptpu_queue* q) { delete q; }

// ---------------------------------------------------------------------------
// scope
// ---------------------------------------------------------------------------

struct ptpu_scope {
  ptpu::Scope* impl;
  bool owned;
};

ptpu_scope* ptpu_scope_create(void) {
  return new ptpu_scope{new ptpu::Scope(), true};
}

ptpu_scope* ptpu_scope_new_child(ptpu_scope* s) {
  return new ptpu_scope{s->impl->NewChild(), false};
}

int ptpu_scope_set(ptpu_scope* s, const char* name, const char* dtype,
                   const int64_t* dims, int32_t ndim, const void* data,
                   uint64_t nbytes) {
  ptpu::HostTensor t;
  t.dtype = dtype;
  t.dims.assign(dims, dims + ndim);
  const uint8_t* p = static_cast<const uint8_t*>(data);
  t.data.assign(p, p + nbytes);
  s->impl->Set(name, std::move(t));
  return 0;
}

int64_t ptpu_scope_get_meta(ptpu_scope* s, const char* name, char* dtype_out,
                            uint64_t dtype_cap, int64_t* dims_out,
                            int32_t* ndim_out) {
  const ptpu::HostTensor* t = s->impl->Find(name);
  if (t == nullptr) return -1;
  if (dtype_out != nullptr && dtype_cap > 0) {
    std::snprintf(dtype_out, dtype_cap, "%s", t->dtype.c_str());
  }
  if (ndim_out != nullptr) *ndim_out = static_cast<int32_t>(t->dims.size());
  if (dims_out != nullptr) {
    for (size_t i = 0; i < t->dims.size() && i < 16; ++i) {
      dims_out[i] = t->dims[i];
    }
  }
  return static_cast<int64_t>(t->data.size());
}

int ptpu_scope_get_data(ptpu_scope* s, const char* name, void* out,
                        uint64_t nbytes) {
  const ptpu::HostTensor* t = s->impl->Find(name);
  if (t == nullptr || nbytes < t->data.size()) {
    set_error("scope var missing or buffer too small");
    return -1;
  }
  std::memcpy(out, t->data.data(), t->data.size());
  return 0;
}

int ptpu_scope_erase(ptpu_scope* s, const char* name) {
  return s->impl->Erase(name) ? 0 : -1;
}

uint64_t ptpu_scope_num_vars(ptpu_scope* s) { return s->impl->NumVars(); }

int64_t ptpu_scope_list(ptpu_scope* s, char* out, uint64_t cap) {
  std::string joined = s->impl->ListJoined();
  if (out != nullptr && cap > joined.size()) {
    std::memcpy(out, joined.c_str(), joined.size() + 1);
  }
  return static_cast<int64_t>(joined.size() + 1);
}

void ptpu_scope_destroy(ptpu_scope* s) {
  if (s->owned) delete s->impl;  // children die with the parent tree
  delete s;
}

// ---------------------------------------------------------------------------
// program
// ---------------------------------------------------------------------------

struct ptpu_program {
  ptpu::ProgramDesc impl;
};

ptpu_program* ptpu_program_parse(const void* data, uint64_t len) {
  auto* p = new ptpu_program();
  if (!ptpu::ParseProgram(static_cast<const uint8_t*>(data), len,
                          &p->impl)) {
    set_error("PTPB parse failed (bad magic/version or truncated stream)");
    delete p;
    return nullptr;
  }
  return p;
}

int32_t ptpu_program_num_blocks(ptpu_program* p) {
  return static_cast<int32_t>(p->impl.blocks.size());
}

int32_t ptpu_program_num_ops(ptpu_program* p, int32_t block) {
  if (block < 0 || block >= ptpu_program_num_blocks(p)) return -1;
  return static_cast<int32_t>(p->impl.blocks[block].ops.size());
}

int32_t ptpu_program_num_vars(ptpu_program* p, int32_t block) {
  if (block < 0 || block >= ptpu_program_num_blocks(p)) return -1;
  return static_cast<int32_t>(p->impl.blocks[block].vars.size());
}

int64_t ptpu_program_op_type(ptpu_program* p, int32_t block, int32_t op,
                             char* out, uint64_t cap) {
  if (block < 0 || block >= ptpu_program_num_blocks(p)) return -1;
  const auto& ops = p->impl.blocks[block].ops;
  if (op < 0 || op >= static_cast<int32_t>(ops.size())) return -1;
  const std::string& t = ops[op].type;
  if (out != nullptr && cap > t.size()) {
    std::memcpy(out, t.c_str(), t.size() + 1);
  }
  return static_cast<int64_t>(t.size() + 1);
}

int64_t ptpu_program_serialize(ptpu_program* p, void* out, uint64_t cap) {
  std::vector<uint8_t> buf;
  ptpu::SerializeProgram(p->impl, &buf);
  if (out != nullptr && cap >= buf.size()) {
    std::memcpy(out, buf.data(), buf.size());
  }
  return static_cast<int64_t>(buf.size());
}

void ptpu_program_destroy(ptpu_program* p) { delete p; }

// ---------------------------------------------------------------------------
// reference interpreter
// ---------------------------------------------------------------------------

int ptpu_interp_run(ptpu_program* p, ptpu_scope* s, int32_t block) {
  ptpu::interp::Interpreter interp(p->impl);
  std::string err = interp.Run(block, s->impl);
  if (!err.empty()) {
    set_error(err);
    return -1;
  }
  return 0;
}

}  // extern "C"
