#pragma once
// PTPB program IR parser/serializer — the C++ twin of
// paddle_tpu/core/program_bin.py (reference role: framework.proto +
// program_desc.h/op_desc.h C++ IR shared by runtime and front-end). The
// writer must produce byte-identical output to the Python writer for the
// same program; the round-trip test in tests/test_native_runtime.py holds
// the two in lockstep.

#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <vector>

namespace ptpu {

struct AttrValue {
  enum Tag : uint8_t {
    kInt = 0,
    kFloat = 1,
    kStr = 2,
    kBool = 3,
    kInts = 4,
    kFloats = 5,
    kStrs = 6,
    kNone = 7,
  };
  Tag tag = kNone;
  int64_t i = 0;
  double f = 0.0;
  bool b = false;
  std::string s;
  std::vector<int64_t> ints;
  std::vector<double> floats;
  std::vector<std::string> strs;
};

struct VarDesc {
  std::string name;
  std::string type;
  bool has_dtype = false;
  std::string dtype;
  bool has_shape = false;
  std::vector<int64_t> shape;
  uint32_t lod_level = 0;
  uint8_t flags = 0;  // 1 persistable, 2 stop_gradient, 4 is_data,
                      // 8 is_parameter, 16 trainable
};

struct OpDesc {
  std::string type;
  // Slot order is the Python writer's sorted() order; std::map matches.
  std::map<std::string, std::vector<std::string>> inputs;
  std::map<std::string, std::vector<std::string>> outputs;
  std::map<std::string, AttrValue> attrs;
};

struct BlockDesc {
  int32_t idx = 0;
  int32_t parent_idx = -1;
  int32_t forward_block_idx = -1;
  // Var order is sorted-by-name in the byte stream.
  std::vector<VarDesc> vars;
  std::vector<OpDesc> ops;
};

struct ProgramDesc {
  uint32_t version = 1;
  uint64_t random_seed = 0;
  std::vector<BlockDesc> blocks;
};

// ---------------------------------------------------------------------------
// Reader
// ---------------------------------------------------------------------------

class BinReader {
 public:
  BinReader(const uint8_t* data, uint64_t len)
      : data_(data), len_(len), off_(0), ok_(true) {}

  bool ok() const { return ok_; }

  template <typename T>
  T Read() {
    T v{};
    if (off_ + sizeof(T) > len_) {
      ok_ = false;
      return v;
    }
    std::memcpy(&v, data_ + off_, sizeof(T));
    off_ += sizeof(T);
    return v;
  }

  std::string ReadStr() {
    uint32_t n = Read<uint32_t>();
    if (!ok_ || off_ + n > len_) {
      ok_ = false;
      return std::string();
    }
    std::string s(reinterpret_cast<const char*>(data_ + off_), n);
    off_ += n;
    return s;
  }

 private:
  const uint8_t* data_;
  uint64_t len_;
  uint64_t off_;
  bool ok_;
};

// ---------------------------------------------------------------------------
// Writer
// ---------------------------------------------------------------------------

class BinWriter {
 public:
  template <typename T>
  void Write(T v) {
    const uint8_t* p = reinterpret_cast<const uint8_t*>(&v);
    buf_.insert(buf_.end(), p, p + sizeof(T));
  }
  void WriteStr(const std::string& s) {
    Write<uint32_t>(static_cast<uint32_t>(s.size()));
    buf_.insert(buf_.end(), s.begin(), s.end());
  }
  void WriteRaw(const void* p, size_t n) {
    const uint8_t* b = static_cast<const uint8_t*>(p);
    buf_.insert(buf_.end(), b, b + n);
  }
  const std::vector<uint8_t>& buffer() const { return buf_; }

 private:
  std::vector<uint8_t> buf_;
};

static bool ReadAttr(BinReader* r, AttrValue* out) {
  out->tag = static_cast<AttrValue::Tag>(r->Read<uint8_t>());
  switch (out->tag) {
    case AttrValue::kNone:
      return r->ok();
    case AttrValue::kBool:
      out->b = r->Read<uint8_t>() != 0;
      return r->ok();
    case AttrValue::kInt:
      out->i = r->Read<int64_t>();
      return r->ok();
    case AttrValue::kFloat:
      out->f = r->Read<double>();
      return r->ok();
    case AttrValue::kStr:
      out->s = r->ReadStr();
      return r->ok();
    case AttrValue::kInts: {
      uint32_t n = r->Read<uint32_t>();
      out->ints.resize(n);
      for (uint32_t i = 0; i < n; ++i) out->ints[i] = r->Read<int64_t>();
      return r->ok();
    }
    case AttrValue::kFloats: {
      uint32_t n = r->Read<uint32_t>();
      out->floats.resize(n);
      for (uint32_t i = 0; i < n; ++i) out->floats[i] = r->Read<double>();
      return r->ok();
    }
    case AttrValue::kStrs: {
      uint32_t n = r->Read<uint32_t>();
      out->strs.resize(n);
      for (uint32_t i = 0; i < n; ++i) out->strs[i] = r->ReadStr();
      return r->ok();
    }
    default:
      return false;
  }
}

static void WriteAttr(BinWriter* w, const AttrValue& a) {
  w->Write<uint8_t>(a.tag);
  switch (a.tag) {
    case AttrValue::kNone:
      break;
    case AttrValue::kBool:
      w->Write<uint8_t>(a.b ? 1 : 0);
      break;
    case AttrValue::kInt:
      w->Write<int64_t>(a.i);
      break;
    case AttrValue::kFloat:
      w->Write<double>(a.f);
      break;
    case AttrValue::kStr:
      w->WriteStr(a.s);
      break;
    case AttrValue::kInts:
      w->Write<uint32_t>(static_cast<uint32_t>(a.ints.size()));
      for (int64_t v : a.ints) w->Write<int64_t>(v);
      break;
    case AttrValue::kFloats:
      w->Write<uint32_t>(static_cast<uint32_t>(a.floats.size()));
      for (double v : a.floats) w->Write<double>(v);
      break;
    case AttrValue::kStrs:
      w->Write<uint32_t>(static_cast<uint32_t>(a.strs.size()));
      for (const std::string& v : a.strs) w->WriteStr(v);
      break;
  }
}

static bool ReadIOMap(BinReader* r,
                      std::map<std::string, std::vector<std::string>>* io) {
  uint32_t nslots = r->Read<uint32_t>();
  for (uint32_t i = 0; i < nslots && r->ok(); ++i) {
    std::string slot = r->ReadStr();
    uint32_t n = r->Read<uint32_t>();
    std::vector<std::string> names(n);
    for (uint32_t j = 0; j < n; ++j) names[j] = r->ReadStr();
    (*io)[slot] = std::move(names);
  }
  return r->ok();
}

static void WriteIOMap(
    BinWriter* w, const std::map<std::string, std::vector<std::string>>& io) {
  w->Write<uint32_t>(static_cast<uint32_t>(io.size()));
  for (const auto& kv : io) {
    w->WriteStr(kv.first);
    w->Write<uint32_t>(static_cast<uint32_t>(kv.second.size()));
    for (const std::string& n : kv.second) w->WriteStr(n);
  }
}

bool ParseProgram(const uint8_t* data, uint64_t len, ProgramDesc* out) {
  if (len < 4 || std::memcmp(data, "PTPB", 4) != 0) return false;
  BinReader r(data + 4, len - 4);
  out->version = r.Read<uint32_t>();
  if (out->version != 1) return false;
  out->random_seed = r.Read<uint64_t>();
  uint32_t nblocks = r.Read<uint32_t>();
  out->blocks.resize(nblocks);
  for (uint32_t b = 0; b < nblocks && r.ok(); ++b) {
    BlockDesc& blk = out->blocks[b];
    blk.idx = r.Read<int32_t>();
    blk.parent_idx = r.Read<int32_t>();
    blk.forward_block_idx = r.Read<int32_t>();
    uint32_t nvars = r.Read<uint32_t>();
    blk.vars.resize(nvars);
    for (uint32_t v = 0; v < nvars && r.ok(); ++v) {
      VarDesc& var = blk.vars[v];
      var.name = r.ReadStr();
      var.type = r.ReadStr();
      var.has_dtype = r.Read<uint8_t>() != 0;
      if (var.has_dtype) var.dtype = r.ReadStr();
      var.has_shape = r.Read<uint8_t>() != 0;
      if (var.has_shape) {
        uint32_t ndim = r.Read<uint32_t>();
        var.shape.resize(ndim);
        for (uint32_t d = 0; d < ndim; ++d) var.shape[d] = r.Read<int64_t>();
      }
      var.lod_level = r.Read<uint32_t>();
      var.flags = r.Read<uint8_t>();
    }
    uint32_t nops = r.Read<uint32_t>();
    blk.ops.resize(nops);
    for (uint32_t o = 0; o < nops && r.ok(); ++o) {
      OpDesc& op = blk.ops[o];
      op.type = r.ReadStr();
      if (!ReadIOMap(&r, &op.inputs)) return false;
      if (!ReadIOMap(&r, &op.outputs)) return false;
      uint32_t nattrs = r.Read<uint32_t>();
      for (uint32_t a = 0; a < nattrs && r.ok(); ++a) {
        std::string name = r.ReadStr();
        AttrValue val;
        if (!ReadAttr(&r, &val)) return false;
        op.attrs[name] = std::move(val);
      }
    }
  }
  return r.ok();
}

void SerializeProgram(const ProgramDesc& prog, std::vector<uint8_t>* out) {
  BinWriter w;
  w.WriteRaw("PTPB", 4);
  w.Write<uint32_t>(prog.version);
  w.Write<uint64_t>(prog.random_seed);
  w.Write<uint32_t>(static_cast<uint32_t>(prog.blocks.size()));
  for (const BlockDesc& blk : prog.blocks) {
    w.Write<int32_t>(blk.idx);
    w.Write<int32_t>(blk.parent_idx);
    w.Write<int32_t>(blk.forward_block_idx);
    w.Write<uint32_t>(static_cast<uint32_t>(blk.vars.size()));
    for (const VarDesc& var : blk.vars) {
      w.WriteStr(var.name);
      w.WriteStr(var.type);
      w.Write<uint8_t>(var.has_dtype ? 1 : 0);
      if (var.has_dtype) w.WriteStr(var.dtype);
      w.Write<uint8_t>(var.has_shape ? 1 : 0);
      if (var.has_shape) {
        w.Write<uint32_t>(static_cast<uint32_t>(var.shape.size()));
        for (int64_t d : var.shape) w.Write<int64_t>(d);
      }
      w.Write<uint32_t>(var.lod_level);
      w.Write<uint8_t>(var.flags);
    }
    w.Write<uint32_t>(static_cast<uint32_t>(blk.ops.size()));
    for (const OpDesc& op : blk.ops) {
      w.WriteStr(op.type);
      WriteIOMap(&w, op.inputs);
      WriteIOMap(&w, op.outputs);
      w.Write<uint32_t>(static_cast<uint32_t>(op.attrs.size()));
      for (const auto& kv : op.attrs) {
        w.WriteStr(kv.first);
        WriteAttr(&w, kv.second);
      }
    }
  }
  *out = w.buffer();
}

}  // namespace ptpu
