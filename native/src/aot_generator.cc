// C++ serving main for the AOT GENERATION artifact.
//
// Reference parity: inference/api/api_impl.cc serving +
// RecurrentGradientMachine's generation role (SURVEY.md §2.8), fused
// the TPU way: transformer.save_compiled_generator compiles the ENTIRE
// KV-cached greedy decode (encoder prepare + lax.scan over the cached
// step) into one serialized XLA executable with the parameters baked
// in. This main embeds CPython (the binding route this project uses
// instead of pybind11) purely to deserialize and execute that
// artifact — io.load_compiled_inference_model performs NO tracing, NO
// program IR interpretation and reads NO parameter files; the artifact
// IS the model. One process, one executable call, token ids out.
//
//   ptpu_aot_generator <artifact_dir> <src.npy> <src_len.npy> <out.npy>
//
// src.npy int32 [B, T], src_len.npy int32 [B, 1] -> out.npy int32
// [B, T] generated token ids. PYTHONPATH must reach the repo root and
// the Python env's site-packages (same contract as
// ptpu_compiled_predictor).

#include <Python.h>

#include <cstdio>
#include <string>

int main(int argc, char** argv) {
  if (argc < 5) {
    std::fprintf(stderr,
                 "usage: %s <artifact_dir> <src.npy> <src_len.npy> "
                 "<out.npy>\n",
                 argv[0]);
    return 2;
  }
  std::string model_dir = argv[1];
  std::string src = argv[2];
  std::string src_len = argv[3];
  std::string output = argv[4];
  // argv is spliced into generated Python source: strings must not
  // break out of the r''' literals
  for (const std::string* s : {&model_dir, &src, &src_len, &output}) {
    if (s->find("'''") != std::string::npos ||
        (!s->empty() && (s->back() == '\\' || s->back() == '\''))) {
      std::fprintf(stderr,
                   "argument %s cannot contain ''' or end in a "
                   "backslash or quote\n",
                   s->c_str());
      return 2;
    }
  }

  Py_Initialize();

  std::string script;
  script += "import jax\n";
  script += "jax.config.update('jax_platforms', 'cpu')\n";
  script += "import numpy as np\n";
  script += "import paddle_tpu as fluid\n";
  script += "model = fluid.io.load_compiled_inference_model(\n";
  script += "    r'''" + model_dir + "''')\n";
  script += "src = np.load(r'''" + src + "''')\n";
  script += "src_len = np.load(r'''" + src_len + "''')\n";
  script += "(tokens,) = model.run("
            "{'src_word': src, 'src_len': src_len})\n";
  script += "np.save(r'''" + output + "''', np.asarray(tokens))\n";
  script += "print('ok aot tokens', np.asarray(tokens).shape)\n";

  int rc = PyRun_SimpleString(script.c_str());
  if (rc != 0) {
    std::fprintf(stderr, "embedded aot generator failed\n");
  }
  if (Py_FinalizeEx() < 0 && rc == 0) rc = 1;
  return rc == 0 ? 0 : 1;
}
