#pragma once
// Minimal C++ CPU reference interpreter over PTPB programs.
//
// Reference parity: the NaiveExecutor + CPU-kernel path that backs the
// reference's C++ predictor (framework/naive_executor.cc,
// inference/api/api_impl.cc) and its "C++-only train/infer demo"
// (train/demo/demo_trainer.cc). On TPU the production inference path is
// the XLA-compiled executable; this interpreter is the host-side reference
// implementation used to (a) prove the C++ runtime can execute the IR end
// to end without Python and (b) cross-check XLA lowerings from C++ parity
// tests (SURVEY.md §2.9 item 7). Float32, core op subset; unsupported ops
// report an error rather than mis-executing.
//
// TRAINING grad table (what the C++ trainer can differentiate — the
// MLP, MNIST-conv, stacked-LSTM book models and a pre-norm
// transformer attention block; every kernel pinned one-step against
// the XLA vjp and the whole surface fuzzed by
// tests/test_train_fuzz.py):
//   mean_grad, relu/tanh/sigmoid/square/exp/log/sqrt grads,
//   softmax_grad, cross_entropy_grad,
//   softmax_with_cross_entropy_grad, elementwise_add_grad and
//   elementwise_{sub,mul,div}_grad (shared ResolveBroadcast geometry,
//   dY reduced), mul_grad, conv2d_grad (strides/paddings/dilations/
//   groups), pool2d_grad (max + avg/exclusive + ceil_mode),
//   reduce_{sum,mean}_grad (shared ResolveReduce geometry),
//   reshape/flatten(+2)/transpose(+2) grads, sum_grad,
//   lookup_table_grad (padding-skipping scatter), sequence_pool_grad
//   (all six pooltypes), dynamic_lstm_grad (BPTT incl. peepholes/
//   reverse/lengths), dynamic_gru_grad (BPTT), layer_norm_grad
//   (shared RowMeanInv stats), scaled_dot_product_attention_grad
//   (shared SdpaValid predicate; causal/window/key-mask/GQA),
//   optimizers sgd / momentum (incl. nesterov) / adam, and the
//   startup initializers (fill_constant, uniform_random,
//   gaussian_random). Anything else errors explicitly.

#include <algorithm>
#include <cctype>
#include <cmath>
#include <cstdint>
#include <functional>
#include <map>
#include <string>
#include <vector>

#include "program.h"
#include "scope.h"

namespace ptpu {

namespace interp {

// One pooled output dim. ceil_mode uses python-style ceil division
// (valid for negative numerators too, matching the XLA lowering's
// -(-num // s) + 1) plus the Caffe/reference clamp: the last window
// must START inside input+low-pad, so no window lies entirely in
// high-side padding (which would read as -inf/0-count).
inline int64_t PoolOutDim(int64_t size, int64_t k, int64_t s, int64_t p,
                          bool ceil_mode) {
  int64_t num = size + 2 * p - k;
  if (!ceil_mode) {
    return num < 0 ? 0 : num / s + 1;
  }
  int64_t q = -num;  // ceil(num/s) = -floor(-num/s)
  int64_t fd = q >= 0 ? q / s : -((-q + s - 1) / s);
  int64_t out = -fd + 1;
  if ((out - 1) * s >= size + p) --out;
  return out;
}

inline int64_t NumElements(const std::vector<int64_t>& dims) {
  int64_t n = 1;
  for (int64_t d : dims) n *= d;
  return n;
}

// xorshift64* stream shared by uniform_random and the C++ demos:
// deterministic for a given seed, no <random> heft.
struct XorShiftRng {
  uint64_t s;
  explicit XorShiftRng(uint64_t seed)
      : s(seed ? seed : 0x9e3779b97f4a7c15ull) {}
  uint64_t next() {
    s ^= s >> 12;
    s ^= s << 25;
    s ^= s >> 27;
    return s * 0x2545f4914f6cdd1dull;
  }
  float uniform() {  // [0, 1)
    return static_cast<float>(next() >> 40) /
           static_cast<float>(1ull << 24);
  }
};

inline const float* F32(const HostTensor& t) {
  return reinterpret_cast<const float*>(t.data.data());
}

inline bool IsF32(const HostTensor& t) { return t.dtype == "float32"; }

inline HostTensor MakeF32(std::vector<int64_t> dims) {
  HostTensor t;
  t.dtype = "float32";
  t.dims = std::move(dims);
  t.data.resize(NumElements(t.dims) * sizeof(float));
  return t;
}

inline float* MutF32(HostTensor* t) {
  return reinterpret_cast<float*>(t->data.data());
}

// Fetches the single input bound to `slot` (empty-name entries skipped).
inline const std::string* OneName(const OpDesc& op, const std::string& slot,
                                  bool input = true) {
  const auto& io = input ? op.inputs : op.outputs;
  auto it = io.find(slot);
  if (it == io.end()) return nullptr;
  for (const std::string& n : it->second) {
    if (!n.empty()) return &n;
  }
  return nullptr;
}

// Paddle axis-aligned broadcast geometry (elementwise_op_function.h):
// default axis from the UNTRIMMED y rank, then trailing 1-dims trimmed,
// y matching x over [ax, ax+y_rank); y's element for flat x index i is
// ya[(i / *inner) % ny]. Shared by the forward and both grad kernels so
// the three can never disagree (a trim-before-axis divergence in the
// grad copy produced silently wrong broadcast gradients).
inline std::string ResolveBroadcast(const OpDesc& op,
                                    const std::vector<int64_t>& xdims,
                                    const std::vector<int64_t>& ydims_in,
                                    int64_t* inner) {
  int64_t ax = -1;
  auto ax_it = op.attrs.find("axis");
  if (ax_it != op.attrs.end() && ax_it->second.tag == AttrValue::kInt) {
    ax = ax_it->second.i;
  }
  if (ax < 0) {
    ax = static_cast<int64_t>(xdims.size()) -
         static_cast<int64_t>(ydims_in.size());
  }
  std::vector<int64_t> ydims = ydims_in;
  while (ydims.size() > 1 && ydims.back() == 1) ydims.pop_back();
  if (ax < 0 || ax + ydims.size() > xdims.size()) {
    return "broadcast axis out of range";
  }
  for (size_t d = 0; d < ydims.size(); ++d) {
    if (ydims[d] != xdims[ax + d]) return "broadcast shape mismatch";
  }
  int64_t nx = 1, ny = 1;
  for (int64_t v : xdims) nx *= v;
  for (int64_t v : ydims_in) ny *= v;
  if (ny == 0 || nx % ny != 0) return "broadcast mismatch";
  *inner = 1;
  for (size_t d = ax + ydims.size(); d < xdims.size(); ++d) {
    *inner *= xdims[d];
  }
  if (*inner <= 0) return "broadcast mismatch";
  return "";
}

// Shared reduce geometry for RunReduce / RunReduceGrad (forward and
// backward must parse dims identically — the ResolveBroadcast lesson):
// fills the reduced mask and the reduced-element count.
inline std::string ResolveReduce(const OpDesc& op,
                                 const std::vector<int64_t>& xdims,
                                 std::vector<bool>* reduced,
                                 int64_t* denom) {
  size_t rank = xdims.size();
  reduced->assign(rank, false);
  std::vector<int64_t> dims;
  auto it = op.attrs.find("dim");
  if (it != op.attrs.end() && it->second.tag == AttrValue::kInts) {
    dims = it->second.ints;
  } else {
    dims = {0};
  }
  bool all = false;
  auto ra = op.attrs.find("reduce_all");
  if (ra != op.attrs.end()) {
    // the attr is serialized as BOOL (missing the kBool arm here is
    // exactly how the MT golden caught a silent reduce_all regression)
    if (ra->second.tag == AttrValue::kInt) {
      all = ra->second.i != 0;
    } else if (ra->second.tag == AttrValue::kBool) {
      all = ra->second.b;
    }
  }
  if (all) {
    reduced->assign(rank, true);
  } else {
    for (int64_t d : dims) {
      if (d < 0) d += rank;
      if (d < 0 || d >= static_cast<int64_t>(rank)) return "bad dim";
      (*reduced)[d] = true;
    }
  }
  *denom = 1;
  for (size_t d = 0; d < rank; ++d) {
    if ((*reduced)[d]) *denom *= xdims[d];
  }
  return "";
}

// Per-row mean + 1/sqrt(var+eps), double accumulation — shared by
// layer_norm forward and backward so the recomputed normalization can
// never drift from what the forward produced.
inline void RowMeanInv(const float* src, int64_t inner, float eps,
                       float* mean_out, float* inv_out) {
  double mean = 0.0;
  for (int64_t i = 0; i < inner; ++i) mean += src[i];
  mean /= inner;
  double var = 0.0;
  for (int64_t i = 0; i < inner; ++i) {
    double dv = src[i] - mean;
    var += dv * dv;
  }
  var /= inner;
  *mean_out = static_cast<float>(mean);
  *inv_out = 1.0f / std::sqrt(static_cast<float>(var) + eps);
}

// SDPA attention validity predicate — shared by RunSDPA and
// RunSDPAGrad (causal, sliding window, optional [B,S] key mask).
inline bool SdpaValid(int64_t t, int64_t j, bool causal, int64_t window,
                      const float* mask_row) {
  if (causal && j > t) return false;
  if (window != 0) {
    if (t - j >= window) return false;
    if (!causal && j - t >= window) return false;
  }
  if (mask_row != nullptr && mask_row[j] <= 0.0f) return false;
  return true;
}

class Interpreter {
 public:
  explicit Interpreter(const ProgramDesc& prog) : prog_(prog) {}

  // Runs every op of `block` against `scope`. Returns "" on success or an
  // error description.
  std::string Run(int32_t block_idx, Scope* scope) {
    if (block_idx < 0 ||
        block_idx >= static_cast<int32_t>(prog_.blocks.size())) {
      return "bad block index";
    }
    for (const OpDesc& op : prog_.blocks[block_idx].ops) {
      std::string err = RunOp(op, scope);
      if (!err.empty()) return "op " + op.type + ": " + err;
    }
    return "";
  }

 private:
  std::string RunOp(const OpDesc& op, Scope* scope) {
    if (op.type == "feed" || op.type == "fetch") return "";  // host-managed
    if (op.type == "mul") return RunMul(op, scope);
    if (op.type == "elementwise_add") return RunAdd(op, scope);
    if (op.type == "elementwise_sub") {
      return RunBinary(op, scope, [](float a, float b) { return a - b; });
    }
    if (op.type == "elementwise_mul") {
      return RunBinary(op, scope, [](float a, float b) { return a * b; });
    }
    if (op.type == "elementwise_div") {
      return RunBinary(op, scope, [](float a, float b) { return a / b; });
    }
    if (op.type == "elementwise_max") {
      return RunBinary(op, scope,
                       [](float a, float b) { return std::max(a, b); });
    }
    if (op.type == "elementwise_min") {
      return RunBinary(op, scope,
                       [](float a, float b) { return std::min(a, b); });
    }
    if (op.type == "elementwise_pow") {
      return RunBinary(op, scope,
                       [](float a, float b) { return std::pow(a, b); });
    }
    if (op.type == "relu") return RunUnary(op, scope, [](float v) {
      return v > 0.0f ? v : 0.0f;
    });
    if (op.type == "sigmoid") return RunUnary(op, scope, [](float v) {
      return 1.0f / (1.0f + std::exp(-v));
    });
    if (op.type == "tanh") return RunUnary(op, scope, [](float v) {
      return std::tanh(v);
    });
    if (op.type == "scale") {
      float s = FloatAttr(op, "scale", 1.0f);
      float b = FloatAttr(op, "bias", 0.0f);
      return RunUnary(op, scope, [s, b](float v) { return s * v + b; });
    }
    if (op.type == "softmax") return RunSoftmax(op, scope);
    if (op.type == "conv2d" || op.type == "depthwise_conv2d") {
      return RunConv2d(op, scope);
    }
    if (op.type == "pool2d") return RunPool2d(op, scope);
    if (op.type == "batch_norm") return RunBatchNorm(op, scope);
    if (op.type == "softmax_with_cross_entropy") return RunSCE(op, scope);
    if (op.type == "reshape" || op.type == "flatten" ||
        op.type == "squeeze" || op.type == "unsqueeze") {
      return RunReshape(op, scope);
    }
    if (op.type == "mean") return RunMean(op, scope);
    if (op.type == "dropout") return RunDropoutTest(op, scope);
    if (op.type == "lookup_table") return RunLookupTable(op, scope);
    if (op.type == "sum") return RunSum(op, scope);
    if (op.type == "sequence_pool") return RunSequencePool(op, scope);
    if (op.type == "dynamic_lstm") return RunDynamicLstm(op, scope);
    // training subset (train/demo/demo_trainer.cc parity): the backward +
    // update ops a minimize()'d MLP program serializes
    if (op.type == "fill_constant") return RunFillConstant(op, scope);
    if (op.type == "uniform_random") return RunUniformRandom(op, scope);
    // transformer serving subset (inference/api_impl.cc parity for the
    // attention-era models): layer_norm + transpose + fused attention
    if (op.type == "layer_norm") return RunLayerNorm(op, scope);
    if (op.type == "transpose" || op.type == "transpose2") {
      return RunTranspose(op, scope);
    }
    if (op.type == "sequence_mask") return RunSequenceMask(op, scope);
    if (op.type == "scaled_dot_product_attention") return RunSDPA(op, scope);
    if (op.type == "reduce_mean") return RunReduceMean(op, scope);
    if (op.type == "reduce_sum") {
      return RunReduce(op, scope, /*mean=*/false);
    }
    // model-zoo breadth (GoogLeNet/SE-ResNeXt/AlexNet/MT/Transformer
    // serving + metric heads)
    if (op.type == "concat") return RunConcat(op, scope);
    if (op.type == "split") return RunSplit(op, scope);
    if (op.type == "lrn") return RunLrn(op, scope);
    if (op.type == "conv2d_transpose") return RunConvTranspose2d(op, scope);
    if (op.type == "dynamic_gru") return RunDynamicGru(op, scope);
    if (op.type == "attention_lstm") return RunAttentionLstm(op, scope);
    if (op.type == "log_softmax") return RunLogSoftmax(op, scope);
    if (op.type == "add_position_encoding") return RunPosEncoding(op, scope);
    if (op.type == "cast") return RunCast(op, scope);
    if (op.type == "dequantize_weight") {
      return RunDequantizeWeight(op, scope);
    }
    if (op.type == "cross_entropy") return RunCrossEntropy(op, scope);
    if (op.type == "top_k") return RunTopK(op, scope);
    if (op.type == "accuracy") return RunAccuracy(op, scope);
    if (op.type == "mean_grad") return RunMeanGrad(op, scope);
    if (op.type == "relu_grad") return RunReluGrad(op, scope);
    if (op.type == "softmax_grad") return RunSoftmaxGrad(op, scope);
    if (op.type == "cross_entropy_grad") return RunXentGrad(op, scope);
    if (op.type == "conv2d_grad" || op.type == "depthwise_conv2d_grad") {
      return RunConv2dGrad(op, scope);
    }
    if (op.type == "pool2d_grad") return RunPool2dGrad(op, scope);
    if (op.type == "gaussian_random") return RunGaussianRandom(op, scope);
    if (op.type == "moe_ffn") return RunMoeFFN(op, scope);
    if (op.type == "expand") return RunExpand(op, scope);
    if (IsUnaryType(op.type)) return RunUnary(op, scope);
    if (op.type == "slice") return RunSlice(op, scope);
    if (op.type == "gather") return RunGather(op, scope);
    if (op.type == "stack") return RunStack(op, scope);
    if (op.type == "pad") return RunPad(op, scope);
    if (op.type == "one_hot") return RunOneHot(op, scope);
    if (op.type == "matmul") return RunMatmul(op, scope);
    if (op.type == "clip") return RunClip(op, scope);
    if (op.type == "cumsum") return RunCumsum(op, scope);
    if (op.type == "scatter") return RunScatter(op, scope);
    if (op.type == "arg_max" || op.type == "arg_min") {
      return RunArgMax(op, scope, op.type == "arg_min");
    }
    if (op.type == "assign") return RunAssign(op, scope);
    if (op.type == "fill_zeros_like") return RunFillZerosLike(op, scope);
    if (op.type == "shape") return RunShapeOp(op, scope);
    if (op.type == "prelu") return RunPrelu(op, scope);
    if (op.type == "group_norm") return RunGroupNorm(op, scope);
    if (op.type == "sequence_softmax") return RunSeqSoftmax(op, scope);
    if (op.type == "norm" || op.type == "l2_normalize") {
      return RunL2Norm(op, scope);
    }
    if (op.type == "huber_loss") return RunHuberLoss(op, scope);
    if (op.type == "log_loss") return RunLogLoss(op, scope);
    if (op.type == "maxout") return RunMaxout(op, scope);
    if (op.type == "softmax_with_cross_entropy_grad") {
      return RunSCEGrad(op, scope);
    }
    if (op.type == "elementwise_add_grad") return RunAddGrad(op, scope);
    if (op.type == "elementwise_sub_grad" ||
        op.type == "elementwise_mul_grad" ||
        op.type == "elementwise_div_grad") {
      return RunEwGrad(op, scope);
    }
    if (op.type == "mul_grad") return RunMulGrad(op, scope);
    if (op.type == "sgd") return RunSgd(op, scope);
    if (op.type == "dynamic_lstm_grad") {
      return RunDynamicLstmGrad(op, scope);
    }
    if (op.type == "dynamic_gru_grad") {
      return RunDynamicGruGrad(op, scope);
    }
    if (op.type == "layer_norm_grad") return RunLayerNormGrad(op, scope);
    if (op.type == "attention_lstm_grad") {
      return RunAttentionLstmGrad(op, scope);
    }
    if (op.type == "batch_norm_grad") {
      return RunBatchNormGrad(op, scope);
    }
    if (op.type == "lrn_grad") return RunLrnGrad(op, scope);
    if (op.type == "scaled_dot_product_attention_grad") {
      return RunSDPAGrad(op, scope);
    }
    if (op.type == "reduce_mean_grad" || op.type == "reduce_sum_grad") {
      return RunReduceGrad(op, scope,
                           op.type == "reduce_mean_grad");
    }
    if (op.type == "lookup_table_grad") {
      return RunLookupTableGrad(op, scope);
    }
    if (op.type == "sequence_pool_grad") {
      return RunSeqPoolGrad(op, scope);
    }
    if (op.type == "sum_grad") return RunSumGrad(op, scope);
    if (op.type == "concat_grad") return RunConcatGrad(op, scope);
    if (op.type == "reshape_grad" || op.type == "flatten_grad" ||
        op.type == "reshape2_grad" || op.type == "flatten2_grad") {
      return RunReshapeGrad(op, scope);
    }
    if (op.type == "transpose_grad" || op.type == "transpose2_grad") {
      return RunTransposeGrad(op, scope);
    }
    if (op.type == "adam") return RunAdam(op, scope);
    if (op.type == "momentum") return RunMomentum(op, scope);
    if (op.type == "tanh_grad") return RunTanhGrad(op, scope);
    if (op.type == "sigmoid_grad") return RunSigmoidGrad(op, scope);
    if (op.type == "square_grad") {
      return RunActGradFromX(
          op, scope, [](float x2, float g) { return 2.0f * x2 * g; });
    }
    if (op.type == "exp_grad") {
      return RunActGradFromOut(
          op, scope, [](float o) { return o; });
    }
    if (op.type == "log_grad") {
      return RunActGradFromX(
          op, scope, [](float x2, float g) { return g / x2; });
    }
    if (op.type == "sqrt_grad") {
      return RunActGradFromOut(
          op, scope, [](float o) { return 0.5f / o; });
    }
    return "unsupported op type";
  }

  static int64_t IntAttr(const OpDesc& op, const std::string& name,
                         int64_t fallback) {
    auto it = op.attrs.find(name);
    if (it == op.attrs.end()) return fallback;
    if (it->second.tag == AttrValue::kInt) return it->second.i;
    if (it->second.tag == AttrValue::kBool) return it->second.b ? 1 : 0;
    return fallback;
  }

  static float FloatAttr(const OpDesc& op, const std::string& name,
                         float fallback) {
    auto it = op.attrs.find(name);
    if (it == op.attrs.end()) return fallback;
    if (it->second.tag == AttrValue::kFloat) {
      return static_cast<float>(it->second.f);
    }
    if (it->second.tag == AttrValue::kInt) {
      return static_cast<float>(it->second.i);
    }
    return fallback;
  }

  static std::vector<int64_t> IntsAttr(const OpDesc& op,
                                       const std::string& name,
                                       std::vector<int64_t> fallback) {
    auto it = op.attrs.find(name);
    if (it == op.attrs.end() || it->second.tag != AttrValue::kInts) {
      return fallback;
    }
    return it->second.ints;
  }

  static std::string StrAttr(const OpDesc& op, const std::string& name,
                             const std::string& fallback) {
    auto it = op.attrs.find(name);
    if (it == op.attrs.end() || it->second.tag != AttrValue::kStr) {
      return fallback;
    }
    return it->second.s;
  }

  // layer_norm_op.cc role: normalize over the trailing dims from
  // begin_norm_axis; Scale/Bias are flat [prod(trailing)] (mirrors
  // ops/nn_ops.py _lower_layer_norm).
  std::string RunLayerNorm(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* yn = OneName(op, "Y", false);
    if (xn == nullptr || yn == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr || !IsF32(*x)) return "bad input";
    int64_t begin = IntAttr(op, "begin_norm_axis", 1);
    float eps = FloatAttr(op, "epsilon", 1e-5f);
    if (begin < 1 || begin >= static_cast<int64_t>(x->dims.size())) {
      return "bad begin_norm_axis";
    }
    int64_t rows = 1, inner = 1;
    for (int64_t d = 0; d < begin; ++d) rows *= x->dims[d];
    for (size_t d = begin; d < x->dims.size(); ++d) inner *= x->dims[d];
    const std::string* sn = OneName(op, "Scale");
    const std::string* bn = OneName(op, "Bias");
    const HostTensor* sc = sn != nullptr ? scope->Find(*sn) : nullptr;
    const HostTensor* bi = bn != nullptr ? scope->Find(*bn) : nullptr;
    if (sc != nullptr && NumElements(sc->dims) != inner) return "bad scale";
    if (bi != nullptr && NumElements(bi->dims) != inner) return "bad bias";
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    for (int64_t r = 0; r < rows; ++r) {
      const float* src = xa + r * inner;
      float* dst = oa + r * inner;
      float mean, inv;
      RowMeanInv(src, inner, eps, &mean, &inv);
      for (int64_t i = 0; i < inner; ++i) {
        float v = (src[i] - mean) * inv;
        if (sc != nullptr) v *= F32(*sc)[i];
        if (bi != nullptr) v += F32(*bi)[i];
        dst[i] = v;
      }
    }
    scope->Set(*yn, std::move(out));
    return "";
  }

  // transpose_op.cc role: general permutation via strides.
  std::string RunTranspose(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr || !IsF32(*x)) return "bad input";
    std::vector<int64_t> perm = IntsAttr(op, "axis", {});
    size_t rank = x->dims.size();
    if (perm.size() != rank) return "bad perm";
    std::vector<int64_t> odims(rank);
    for (size_t d = 0; d < rank; ++d) odims[d] = x->dims[perm[d]];
    std::vector<int64_t> xstride(rank, 1), ostride(rank, 1);
    for (int64_t d = static_cast<int64_t>(rank) - 2; d >= 0; --d) {
      xstride[d] = xstride[d + 1] * x->dims[d + 1];
      ostride[d] = ostride[d + 1] * odims[d + 1];
    }
    HostTensor out = MakeF32(odims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    int64_t total = NumElements(odims);
    for (int64_t idx = 0; idx < total; ++idx) {
      int64_t rem = idx, src = 0;
      for (size_t d = 0; d < rank; ++d) {
        int64_t coord = rem / ostride[d];
        rem -= coord * ostride[d];
        src += coord * xstride[perm[d]];
      }
      oa[idx] = xa[src];
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // sequence_mask_op.cc role: [B] (or [B, 1]) lengths -> [B, maxlen] f32.
  std::string RunSequenceMask(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Y", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "bad input";
    int64_t maxlen = IntAttr(op, "maxlen", -1);
    if (maxlen <= 0) return "needs static maxlen";
    std::vector<int64_t> lens;
    std::string err = ReadIds(*x, &lens);
    if (!err.empty()) return err;
    HostTensor out = MakeF32({static_cast<int64_t>(lens.size()), maxlen});
    float* oa = MutF32(&out);
    for (size_t b = 0; b < lens.size(); ++b) {
      for (int64_t t = 0; t < maxlen; ++t) {
        oa[b * maxlen + t] = t < lens[b] ? 1.0f : 0.0f;
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // Fused attention (ops/attention_ops.py reference semantics):
  // q,k,v [B, H, T, d]; optional Mask [B, S] validity or [B, 1|H, T, S];
  // softmax((q k^T) * sm_scale + causal/key masks) v, all f32.
  std::string RunSDPA(const OpDesc& op, Scope* scope) {
    const std::string* qn = OneName(op, "Q");
    const std::string* kn = OneName(op, "K");
    const std::string* vn = OneName(op, "V");
    const std::string* on = OneName(op, "Out", false);
    if (qn == nullptr || kn == nullptr || vn == nullptr || on == nullptr) {
      return "missing io";
    }
    const HostTensor* q = scope->Find(*qn);
    const HostTensor* k = scope->Find(*kn);
    const HostTensor* v = scope->Find(*vn);
    if (q == nullptr || k == nullptr || v == nullptr) return "bad input";
    if (!IsF32(*q) || !IsF32(*k) || !IsF32(*v)) return "non-f32";
    if (q->dims.size() != 4 || k->dims.size() != 4) return "needs [B,H,T,d]";
    if (!StrAttr(op, "seq_parallel_axis", "").empty()) {
      return "seq_parallel_axis needs the XLA path";
    }
    int64_t B = q->dims[0], H = q->dims[1], T = q->dims[2], d = q->dims[3];
    int64_t S = k->dims[2];
    // grouped-query attention: K/V carry H / kv_group heads, each
    // serving kv_group query heads (kv_group 1 = full MHA)
    int64_t g = IntAttr(op, "kv_group", 1);
    if (g < 1 || H % g != 0) return "bad kv_group";
    int64_t Hkv = H / g;
    if (k->dims[0] != B || k->dims[1] != Hkv || k->dims[3] != d) {
      return "K shape mismatch";
    }
    if (v->dims != k->dims) return "V shape mismatch";
    bool causal = IntAttr(op, "causal", 0) != 0;
    // sliding window, matching kernels/flash_attention.py _window_band:
    // causal keeps q - w < k <= q; non-causal keeps |q - k| < w
    // (window 0 = disabled)
    int64_t window = IntAttr(op, "window", 0);
    if (window < 0) return "bad window";
    float scale = FloatAttr(op, "sm_scale", 0.0f);
    if (scale == 0.0f) scale = 1.0f / std::sqrt(static_cast<float>(d));
    const std::string* mn = OneName(op, "Mask");
    const HostTensor* mask = mn != nullptr ? scope->Find(*mn) : nullptr;
    if (mask != nullptr &&
        (mask->dims.size() != 2 || mask->dims[0] != B ||
         mask->dims[1] != S)) {
      return "only [B, S] key-validity masks in the C++ path";
    }
    HostTensor out = MakeF32(q->dims);
    const float* qa = F32(*q);
    const float* ka = F32(*k);
    const float* va = F32(*v);
    const float* ma = mask != nullptr ? F32(*mask) : nullptr;
    float* oa = MutF32(&out);
    std::vector<float> s(S);
    for (int64_t b = 0; b < B; ++b) {
      for (int64_t h = 0; h < H; ++h) {
        const float* kb = ka + (b * Hkv + h / g) * S * d;
        const float* vb = va + (b * Hkv + h / g) * S * d;
        for (int64_t t = 0; t < T; ++t) {
          const float* qr = qa + ((b * H + h) * T + t) * d;
          float mx = -1e30f;
          bool any_valid = false;
          const float* mrow = ma != nullptr ? ma + b * S : nullptr;
          for (int64_t j = 0; j < S; ++j) {
            if (SdpaValid(t, j, causal, window, mrow)) {
              any_valid = true;
              float dot = 0.0f;
              for (int64_t c = 0; c < d; ++c) dot += qr[c] * kb[j * d + c];
              s[j] = dot * scale;
              if (s[j] > mx) mx = s[j];
            } else {
              s[j] = -1e30f;
            }
          }
          float* orow = oa + ((b * H + h) * T + t) * d;
          for (int64_t c = 0; c < d; ++c) orow[c] = 0.0f;
          // fully-masked rows output 0, the Pallas kernel contract
          // (docs/LONG_CONTEXT.md) — NOT the uniform average the
          // exp(-1e30 - -1e30) arithmetic would produce
          if (!any_valid) continue;
          float denom = 0.0f;
          for (int64_t j = 0; j < S; ++j) {
            s[j] = std::exp(s[j] - mx);
            denom += s[j];
          }
          if (denom <= 0.0f) denom = 1.0f;
          for (int64_t j = 0; j < S; ++j) {
            float p = s[j] / denom;
            for (int64_t c = 0; c < d; ++c) orow[c] += p * vb[j * d + c];
          }
        }
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // reduce_mean over the attrs' dim list (keep_dim supported).
  std::string RunReduceMean(const OpDesc& op, Scope* scope) {
    return RunReduce(op, scope, /*mean=*/true);
  }

  // shared reduce kernel: reduce_mean / reduce_sum differ only in the
  // final divide (reduce_op.h functor-split capability)
  std::string RunReduce(const OpDesc& op, Scope* scope, bool mean) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr || !IsF32(*x)) return "bad input";
    size_t rank = x->dims.size();
    bool keep = IntAttr(op, "keep_dim", 0) != 0;
    std::vector<bool> reduced;
    int64_t rdenom = 1;
    std::string rerr = ResolveReduce(op, x->dims, &reduced, &rdenom);
    if (!rerr.empty()) return rerr;
    std::vector<int64_t> odims;
    for (size_t d = 0; d < rank; ++d) {
      if (!reduced[d]) {
        odims.push_back(x->dims[d]);
      } else if (keep) {
        odims.push_back(1);
      }
    }
    if (odims.empty()) odims.push_back(1);
    std::vector<int64_t> xstride(rank, 1);
    for (int64_t d = static_cast<int64_t>(rank) - 2; d >= 0; --d) {
      xstride[d] = xstride[d + 1] * x->dims[d + 1];
    }
    HostTensor out = MakeF32(odims);
    float* oa = MutF32(&out);
    int64_t on_elems = NumElements(odims);
    std::fill(oa, oa + on_elems, 0.0f);
    const float* xa = F32(*x);
    int64_t total = NumElements(x->dims);
    int64_t denom = 1;
    for (size_t d = 0; d < rank; ++d) {
      if (reduced[d]) denom *= x->dims[d];
    }
    for (int64_t idx = 0; idx < total; ++idx) {
      int64_t rem = idx, oidx = 0;
      // output index folds in the non-reduced coords, row-major
      for (size_t d = 0; d < rank; ++d) {
        int64_t coord = rem / xstride[d];
        rem -= coord * xstride[d];
        if (!reduced[d]) {
          oidx = oidx * x->dims[d] + coord;
        }
      }
      oa[oidx] += xa[idx];
    }
    if (mean) {
      for (int64_t i = 0; i < on_elems; ++i) oa[i] /= denom;
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // NCHW direct convolution (conv_op.cc CPU kernel role): strides,
  // symmetric paddings, dilations, groups (depthwise = groups == C).
  std::string RunConv2d(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "Input");
    const std::string* wn = OneName(op, "Filter");
    const std::string* on = OneName(op, "Output", false);
    if (xn == nullptr || wn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* w = scope->Find(*wn);
    if (x == nullptr || w == nullptr) return "input not in scope";
    if (!IsF32(*x) || !IsF32(*w)) return "non-f32 dtype";
    if (x->dims.size() != 4 || w->dims.size() != 4) return "rank != 4";
    auto strides = IntsAttr(op, "strides", {1, 1});
    auto pads = IntsAttr(op, "paddings", {0, 0});
    auto dil = IntsAttr(op, "dilations", {1, 1});
    if (strides.size() != 2 || pads.size() != 2 || dil.size() != 2) {
      return "bad geometry attrs";
    }
    int64_t groups = IntAttr(op, "groups", 1);
    if (groups <= 0) groups = 1;
    int64_t n = x->dims[0], ci = x->dims[1], h = x->dims[2], wd = x->dims[3];
    int64_t co = w->dims[0], cig = w->dims[1], kh = w->dims[2],
            kw = w->dims[3];
    if (groups > ci || ci % groups != 0 || ci / groups != cig ||
        co < groups || co % groups != 0) {
      return "group/channel mismatch";
    }
    int64_t oh = (h + 2 * pads[0] - (dil[0] * (kh - 1) + 1)) / strides[0] + 1;
    int64_t ow = (wd + 2 * pads[1] - (dil[1] * (kw - 1) + 1)) / strides[1] + 1;
    if (oh <= 0 || ow <= 0) return "empty output";
    HostTensor out = MakeF32({n, co, oh, ow});
    const float* xa = F32(*x);
    const float* wa = F32(*w);
    float* oa = MutF32(&out);
    int64_t co_g = co / groups;
    for (int64_t b = 0; b < n; ++b) {
      for (int64_t oc = 0; oc < co; ++oc) {
        int64_t g = oc / co_g;
        for (int64_t i = 0; i < oh; ++i) {
          for (int64_t j = 0; j < ow; ++j) {
            float acc = 0.0f;
            for (int64_t icg = 0; icg < cig; ++icg) {
              int64_t ic = g * cig + icg;
              for (int64_t r = 0; r < kh; ++r) {
                int64_t yy = i * strides[0] - pads[0] + r * dil[0];
                if (yy < 0 || yy >= h) continue;
                for (int64_t s = 0; s < kw; ++s) {
                  int64_t xx = j * strides[1] - pads[1] + s * dil[1];
                  if (xx < 0 || xx >= wd) continue;
                  acc += xa[((b * ci + ic) * h + yy) * wd + xx] *
                         wa[((oc * cig + icg) * kh + r) * kw + s];
                }
              }
            }
            oa[((b * co + oc) * oh + i) * ow + j] = acc;
          }
        }
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunPool2d(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x) || x->dims.size() != 4) return "bad input";
    std::string ptype = StrAttr(op, "pooling_type", "max");
    bool global = IntAttr(op, "global_pooling", 0) != 0;
    bool exclusive = IntAttr(op, "exclusive", 1) != 0;
    bool ceil = IntAttr(op, "ceil_mode", 0) != 0;
    if (IntAttr(op, "adaptive", 0) != 0) return "adaptive unsupported";
    auto ks = IntsAttr(op, "ksize", {2, 2});
    auto st = IntsAttr(op, "strides", {1, 1});
    auto pd = IntsAttr(op, "paddings", {0, 0});
    if (ks.size() != 2 || st.size() != 2 || pd.size() != 2) {
      return "bad geometry attrs";
    }
    int64_t n = x->dims[0], c = x->dims[1], h = x->dims[2], wd = x->dims[3];
    if (global) {
      ks = {h, wd};
      st = {h, wd};
      pd = {0, 0};
      ceil = false;
    }
    int64_t oh = PoolOutDim(h, ks[0], st[0], pd[0], ceil);
    int64_t ow = PoolOutDim(wd, ks[1], st[1], pd[1], ceil);
    if (oh <= 0 || ow <= 0) return "empty output";
    HostTensor out = MakeF32({n, c, oh, ow});
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    for (int64_t b = 0; b < n; ++b) {
      for (int64_t ch = 0; ch < c; ++ch) {
        const float* plane = xa + (b * c + ch) * h * wd;
        for (int64_t i = 0; i < oh; ++i) {
          for (int64_t j = 0; j < ow; ++j) {
            float best = -INFINITY, sum = 0.0f;
            int64_t cnt = 0;
            for (int64_t r = 0; r < ks[0]; ++r) {
              int64_t yy = i * st[0] - pd[0] + r;
              if (yy < 0 || yy >= h) continue;
              for (int64_t s = 0; s < ks[1]; ++s) {
                int64_t xx = j * st[1] - pd[1] + s;
                if (xx < 0 || xx >= wd) continue;
                float v = plane[yy * wd + xx];
                best = std::max(best, v);
                sum += v;
                ++cnt;
              }
            }
            float res;
            if (ptype == "max") {
              res = cnt > 0 ? best : 0.0f;
            } else {
              int64_t denom = exclusive ? cnt : ks[0] * ks[1];
              res = denom > 0 ? sum / static_cast<float>(denom) : 0.0f;
            }
            oa[((b * c + ch) * oh + i) * ow + j] = res;
          }
        }
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // Inference-form batch norm: y = scale * (x - mean) / sqrt(var + eps)
  // + bias over channel axis 1 (batch_norm_op.cc is_test path).
  std::string RunBatchNorm(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* yn = OneName(op, "Y", false);
    if (xn == nullptr || yn == nullptr) return "missing io";
    const std::string* sn = OneName(op, "Scale");
    const std::string* bn = OneName(op, "Bias");
    const std::string* mn = OneName(op, "Mean");
    const std::string* vn = OneName(op, "Variance");
    if (sn == nullptr || bn == nullptr || mn == nullptr || vn == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* sc = scope->Find(*sn);
    const HostTensor* bi = scope->Find(*bn);
    const HostTensor* me = scope->Find(*mn);
    const HostTensor* va = scope->Find(*vn);
    if (x == nullptr || sc == nullptr || bi == nullptr || me == nullptr ||
        va == nullptr) {
      return "input not in scope";
    }
    if (!IsF32(*x) || x->dims.size() < 2) return "bad input";
    bool is_test = IntAttr(op, "is_test", 0) != 0 ||
                   IntAttr(op, "use_global_stats", 0) != 0;
    float eps = FloatAttr(op, "epsilon", 1e-5f);
    float momentum = FloatAttr(op, "momentum", 0.9f);
    if (StrAttr(op, "data_layout", "NCHW") != "NCHW") {
      return "only NCHW";
    }
    int64_t n = x->dims[0], c = x->dims[1];
    if (n <= 0 || c <= 0) return "empty input";
    if (!IsF32(*sc) || !IsF32(*bi) || !IsF32(*me) || !IsF32(*va)) {
      return "non-f32 dtype";
    }
    if (NumElements(sc->dims) < c || NumElements(bi->dims) < c ||
        NumElements(me->dims) < c || NumElements(va->dims) < c) {
      return "bn param too small";
    }
    int64_t spatial = NumElements(x->dims) / (n * c);
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    const float* sa = F32(*sc);
    const float* ba = F32(*bi);
    const float* ma = F32(*me);
    const float* vaa = F32(*va);
    // training mode: batch statistics per channel (double accumulation
    // like the XLA f32 reduce; ops/nn_ops.py _lower_batch_norm) plus
    // the running-stat momentum update and the Saved* intermediates
    // the grad op consumes
    std::vector<float> bmean(c), bvar(c);
    if (!is_test) {
      int64_t cnt = n * spatial;
      if (cnt <= 0) return "empty input";
      for (int64_t ch = 0; ch < c; ++ch) {
        double mean = 0.0, sq = 0.0;
        for (int64_t b = 0; b < n; ++b) {
          const float* src = xa + (b * c + ch) * spatial;
          for (int64_t i = 0; i < spatial; ++i) {
            mean += src[i];
            sq += static_cast<double>(src[i]) * src[i];
          }
        }
        mean /= cnt;
        bmean[ch] = static_cast<float>(mean);
        bvar[ch] = static_cast<float>(sq / cnt - mean * mean);
      }
      auto emit_vec = [&](const char* slot, const float* vals,
                          const std::vector<int64_t>& dims) {
        const std::string* nm = OneName(op, slot, false);
        if (nm == nullptr) return;
        HostTensor t2 = MakeF32(dims);
        std::copy(vals, vals + c, MutF32(&t2));
        scope->Set(*nm, std::move(t2));
      };
      std::vector<float> mout(c), vout(c);
      for (int64_t ch = 0; ch < c; ++ch) {
        mout[ch] = ma[ch] * momentum + bmean[ch] * (1.0f - momentum);
        vout[ch] = vaa[ch] * momentum + bvar[ch] * (1.0f - momentum);
      }
      emit_vec("MeanOut", mout.data(), me->dims);
      emit_vec("VarianceOut", vout.data(), va->dims);
      emit_vec("SavedMean", bmean.data(), {c});
      emit_vec("SavedVariance", bvar.data(), {c});
    }
    for (int64_t b = 0; b < n; ++b) {
      for (int64_t ch = 0; ch < c; ++ch) {
        float mu = is_test ? ma[ch] : bmean[ch];
        float vv = is_test ? vaa[ch] : bvar[ch];
        float inv = 1.0f / std::sqrt(vv + eps);
        const float* src = xa + (b * c + ch) * spatial;
        float* dst = oa + (b * c + ch) * spatial;
        for (int64_t i = 0; i < spatial; ++i) {
          dst[i] = sa[ch] * (src[i] - mu) * inv + ba[ch];
        }
      }
    }
    scope->Set(*yn, std::move(out));
    return "";
  }


  // lrn backward over the reference's -(n-1)/2 channel window:
  // out_i = x_i * mid_i^-beta, mid_i = k + alpha * sum_{j in W(i)} x_j^2
  // dx_j = g_j*mid_j^-beta
  //        - 2*alpha*beta*x_j * sum_{i: j in W(i)} g_i*x_i*mid_i^(-beta-1)
  // (scatter form: iterate i, add its contribution to every j in W(i))
  std::string RunLrnGrad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* mon = OneName(op, "MidOut");
    const std::string* ogn = OneName(op, "Out@GRAD");
    const std::string* gn = OneName(op, "X@GRAD", false);
    if (xn == nullptr || mon == nullptr || ogn == nullptr ||
        gn == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* mo = scope->Find(*mon);
    const HostTensor* og = scope->Find(*ogn);
    for (const HostTensor* tt : {x, mo, og}) {
      if (tt == nullptr) return "input not in scope";
      if (!IsF32(*tt)) return "non-f32 dtype";
    }
    if (x->dims.size() != 4 || mo->dims != x->dims ||
        og->dims != x->dims) {
      return "bad input";
    }
    int64_t n = IntAttr(op, "n", 5);
    float alpha = FloatAttr(op, "alpha", 1e-4f);
    float beta = FloatAttr(op, "beta", 0.75f);
    if (n <= 0) return "bad window";
    int64_t half = (n - 1) / 2;  // reference window, same as forward
    int64_t b = x->dims[0], c = x->dims[1], h = x->dims[2],
            wd = x->dims[3];
    int64_t hw = h * wd;
    HostTensor grad = MakeF32(x->dims);
    float* ra = MutF32(&grad);
    std::fill(ra, ra + NumElements(x->dims), 0.0f);
    const float* xa = F32(*x);
    const float* moa = F32(*mo);
    const float* ga = F32(*og);
    for (int64_t bi = 0; bi < b; ++bi) {
      for (int64_t ci = 0; ci < c; ++ci) {
        int64_t lo = std::max<int64_t>(0, ci - half);
        int64_t hi = std::min<int64_t>(c - 1, ci + (n - 1 - half));
        for (int64_t p = 0; p < hw; ++p) {
          int64_t idx = (bi * c + ci) * hw + p;
          float mid = moa[idx];
          float mb = std::pow(mid, -beta);
          float g = ga[idx];
          // direct term
          ra[idx] += g * mb;
          // scatter the cross term into every window member
          float common = 2.0f * alpha * beta * g * xa[idx] * mb / mid;
          for (int64_t cj = lo; cj <= hi; ++cj) {
            int64_t jdx = (bi * c + cj) * hw + p;
            ra[jdx] -= common * xa[jdx];
          }
        }
      }
    }
    scope->Set(*gn, std::move(grad));
    return "";
  }

  // batch_norm training backward (classic per-channel adjoint over the
  // SavedMean/SavedVariance batch stats the forward emitted):
  // dScale = sum(g*xhat), dBias = sum(g),
  // dx = inv/N * (N*g*scale - sum(g*scale) - xhat*sum(g*scale*xhat))
  std::string RunBatchNormGrad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* sn = OneName(op, "Scale");
    const std::string* smn = OneName(op, "SavedMean");
    const std::string* svn = OneName(op, "SavedVariance");
    const std::string* ygn = OneName(op, "Y@GRAD");
    if (xn == nullptr || sn == nullptr || smn == nullptr ||
        svn == nullptr || ygn == nullptr) {
      return "missing io";
    }
    // frozen-BN (use_global_stats / is_test clones used in training):
    // the stats are constants, so dx = g*scale*inv with no batch-mean
    // correction terms. SavedMean/SavedVariance hold the global stats
    // in that mode (the forward set saved = running).
    bool frozen = IntAttr(op, "is_test", 0) != 0 ||
                  IntAttr(op, "use_global_stats", 0) != 0;
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* sc = scope->Find(*sn);
    const HostTensor* sm = scope->Find(*smn);
    const HostTensor* sv = scope->Find(*svn);
    const HostTensor* yg = scope->Find(*ygn);
    for (const HostTensor* tt : {x, sc, sm, sv, yg}) {
      if (tt == nullptr) return "input not in scope";
      if (!IsF32(*tt)) return "non-f32 dtype";
    }
    if (x->dims.size() < 2 || yg->dims != x->dims) return "bad input";
    float eps = FloatAttr(op, "epsilon", 1e-5f);
    int64_t n = x->dims[0], c = x->dims[1];
    if (n <= 0 || c <= 0) return "empty input";
    if (NumElements(sc->dims) < c || NumElements(sm->dims) < c ||
        NumElements(sv->dims) < c) {
      return "bn param too small";
    }
    int64_t spatial = NumElements(x->dims) / (n * c);
    int64_t cnt = n * spatial;
    const float* xa = F32(*x);
    const float* sa = F32(*sc);
    const float* sma = F32(*sm);
    const float* sva = F32(*sv);
    const float* ga = F32(*yg);
    const std::string* xgn = OneName(op, "X@GRAD", false);
    const std::string* sgn = OneName(op, "Scale@GRAD", false);
    const std::string* bgn = OneName(op, "Bias@GRAD", false);
    HostTensor xg, sg, bg;
    float* xga = nullptr;
    float* sga = nullptr;
    float* bga = nullptr;
    if (xgn != nullptr) {
      xg = MakeF32(x->dims);
      xga = MutF32(&xg);
    }
    if (sgn != nullptr) {
      sg = MakeF32({c});
      sga = MutF32(&sg);
    }
    if (bgn != nullptr) {
      bg = MakeF32({c});
      bga = MutF32(&bg);
    }
    for (int64_t ch = 0; ch < c; ++ch) {
      float mu = sma[ch];
      float inv = 1.0f / std::sqrt(sva[ch] + eps);
      double sum_g = 0.0, sum_gx = 0.0;
      for (int64_t b = 0; b < n; ++b) {
        const float* src = xa + (b * c + ch) * spatial;
        const float* grow = ga + (b * c + ch) * spatial;
        for (int64_t i = 0; i < spatial; ++i) {
          sum_g += grow[i];
          sum_gx += static_cast<double>(grow[i]) * (src[i] - mu) * inv;
        }
      }
      if (sga != nullptr) sga[ch] = static_cast<float>(sum_gx);
      if (bga != nullptr) bga[ch] = static_cast<float>(sum_g);
      if (xga != nullptr) {
        float scale = sa[ch];
        float mean_g = static_cast<float>(sum_g / cnt);
        float mean_gx = static_cast<float>(sum_gx / cnt);
        for (int64_t b = 0; b < n; ++b) {
          const float* src = xa + (b * c + ch) * spatial;
          const float* grow = ga + (b * c + ch) * spatial;
          float* dst = xga + (b * c + ch) * spatial;
          for (int64_t i = 0; i < spatial; ++i) {
            if (frozen) {
              dst[i] = scale * inv * grow[i];
            } else {
              float xhat = (src[i] - mu) * inv;
              dst[i] = scale * inv *
                       (grow[i] - mean_g - xhat * mean_gx);
            }
          }
        }
      }
    }
    if (xgn != nullptr) scope->Set(*xgn, std::move(xg));
    if (sgn != nullptr) scope->Set(*sgn, std::move(sg));
    if (bgn != nullptr) scope->Set(*bgn, std::move(bg));
    return "";
  }

  // Logits [N, C] + integer Label [N] or [N, 1] -> Softmax + Loss [N, 1]
  // (softmax_with_cross_entropy_op.cc, hard labels).
  std::string RunSCE(const OpDesc& op, Scope* scope) {
    const std::string* ln = OneName(op, "Logits");
    const std::string* labn = OneName(op, "Label");
    const std::string* sn = OneName(op, "Softmax", false);
    const std::string* lossn = OneName(op, "Loss", false);
    if (ln == nullptr || labn == nullptr || lossn == nullptr) {
      return "missing io";
    }
    const HostTensor* logits = scope->Find(*ln);
    const HostTensor* label = scope->Find(*labn);
    if (logits == nullptr || label == nullptr) return "input not in scope";
    if (!IsF32(*logits) || logits->dims.size() != 2) return "bad logits";
    int64_t n = logits->dims[0], c = logits->dims[1];
    if (NumElements(label->dims) < n) return "label too small";
    HostTensor soft = MakeF32(logits->dims);
    HostTensor loss = MakeF32({n, 1});
    const float* la = F32(*logits);
    float* sa = MutF32(&soft);
    float* lo = MutF32(&loss);
    for (int64_t i = 0; i < n; ++i) {
      const float* row = la + i * c;
      float* srow = sa + i * c;
      float mx = row[0];
      for (int64_t j = 1; j < c; ++j) mx = std::max(mx, row[j]);
      float sum = 0.0f;
      for (int64_t j = 0; j < c; ++j) {
        srow[j] = std::exp(row[j] - mx);
        sum += srow[j];
      }
      for (int64_t j = 0; j < c; ++j) srow[j] /= sum;
      int64_t gold;
      if (label->dtype == "int64") {
        gold = reinterpret_cast<const int64_t*>(label->data.data())[i];
      } else if (label->dtype == "int32") {
        gold = reinterpret_cast<const int32_t*>(label->data.data())[i];
      } else {
        return "label dtype";
      }
      if (gold < 0 || gold >= c) return "label out of range";
      lo[i] = -std::log(std::max(srow[gold], 1e-30f));
    }
    if (sn != nullptr) scope->Set(*sn, std::move(soft));
    scope->Set(*lossn, std::move(loss));
    return "";
  }

  std::string RunReshape(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    int64_t total = NumElements(x->dims);
    std::vector<int64_t> shape;
    if (op.type == "flatten") {
      int64_t ax = IntAttr(op, "axis", 1);
      int64_t rows = 1, cols = 1;
      for (size_t d = 0; d < x->dims.size(); ++d) {
        (static_cast<int64_t>(d) < ax ? rows : cols) *= x->dims[d];
      }
      shape = {rows, cols};
    } else if (op.type == "unsqueeze") {
      // insert size-1 dims at the (normalized) target axes, like
      // jnp.expand_dims(ops/tensor_ops.py)
      auto axes = IntsAttr(op, "axes", {});
      int64_t out_rank =
          static_cast<int64_t>(x->dims.size() + axes.size());
      std::vector<int64_t> norm;
      for (int64_t a : axes) {
        norm.push_back(a < 0 ? a + out_rank : a);
      }
      shape.assign(out_rank, 0);
      for (int64_t a : norm) {
        if (a < 0 || a >= out_rank) return "axis out of range";
        if (shape[a] != 0) return "duplicate axes";
        shape[a] = 1;
      }
      size_t src = 0;
      for (int64_t i = 0; i < out_rank; ++i) {
        if (shape[i] == 0) shape[i] = x->dims[src++];
      }
      if (src != x->dims.size()) return "axes/rank mismatch";
    } else if (op.type == "squeeze") {
      auto axes = IntsAttr(op, "axes", {});
      int64_t rank = static_cast<int64_t>(x->dims.size());
      std::vector<uint8_t> drop(x->dims.size(), 0);
      if (axes.empty()) {
        for (size_t d = 0; d < x->dims.size(); ++d) {
          drop[d] = x->dims[d] == 1;
        }
      } else {
        for (int64_t a : axes) {
          a = a < 0 ? a + rank : a;
          if (a < 0 || a >= rank) return "axis out of range";
          // only size-1 axes squeeze (ops/tensor_ops.py _squeeze)
          if (x->dims[a] == 1) drop[a] = 1;
        }
      }
      for (size_t d = 0; d < x->dims.size(); ++d) {
        if (!drop[d]) shape.push_back(x->dims[d]);
      }
      if (shape.empty()) shape.push_back(1);
    } else {
      shape = IntsAttr(op, "shape", {});
      int64_t known = 1, infer = -1;
      for (size_t d = 0; d < shape.size(); ++d) {
        if (shape[d] == 0) {  // Paddle 0 = copy input dim at this position
          if (d >= x->dims.size()) return "shape mismatch";
          shape[d] = x->dims[d];
        }
        if (shape[d] == -1) {
          infer = static_cast<int64_t>(d);
        } else {
          known *= shape[d];
        }
      }
      if (infer >= 0) shape[infer] = total / (known == 0 ? 1 : known);
    }
    if (NumElements(shape) != total) return "shape mismatch";
    HostTensor out = *x;  // same bytes, new dims
    out.dims = shape;
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunMean(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x)) return "non-f32 dtype";
    int64_t total = NumElements(x->dims);
    HostTensor out = MakeF32({1});
    const float* xa = F32(*x);
    double acc = 0.0;
    for (int64_t i = 0; i < total; ++i) acc += xa[i];
    MutF32(&out)[0] = static_cast<float>(acc / (total > 0 ? total : 1));
    scope->Set(*on, std::move(out));
    return "";
  }

  // Inference dropout (dropout_op.cc is_test path): downgrade_in_infer
  // scales by (1 - p); upscale_in_train is identity.
  std::string RunDropoutTest(const OpDesc& op, Scope* scope) {
    if (IntAttr(op, "is_test", 0) == 0) {
      return "training-mode dropout unsupported (clone for_test first)";
    }
    float p = FloatAttr(op, "dropout_prob", 0.5f);
    std::string impl =
        StrAttr(op, "dropout_implementation", "downgrade_in_infer");
    float s = impl == "upscale_in_train" ? 1.0f : 1.0f - p;
    return RunUnary(op, scope, [s](float v) { return s * v; });
  }

  std::string RunMul(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* yn = OneName(op, "Y");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || yn == nullptr || on == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* y = scope->Find(*yn);
    if (x == nullptr || y == nullptr) return "input not in scope";
    if (!IsF32(*x) || !IsF32(*y)) return "non-f32 dtype";
    // x_num_col_dims semantics: flatten x to [rows, K], y to [K, cols].
    int64_t xcol = 1;
    auto it = op.attrs.find("x_num_col_dims");
    if (it != op.attrs.end()) xcol = it->second.i;
    int64_t rows = 1, k = 1;
    for (size_t d = 0; d < x->dims.size(); ++d) {
      (static_cast<int64_t>(d) < xcol ? rows : k) *= x->dims[d];
    }
    int64_t k2 = y->dims.empty() ? 1 : y->dims[0];
    int64_t cols = NumElements(y->dims) / (k2 == 0 ? 1 : k2);
    if (k != k2) return "shape mismatch";
    std::vector<int64_t> odims(x->dims.begin(), x->dims.begin() + xcol);
    odims.push_back(cols);
    HostTensor out = MakeF32(odims);
    const float* xa = F32(*x);
    const float* ya = F32(*y);
    float* oa = MutF32(&out);
    for (int64_t i = 0; i < rows; ++i) {
      for (int64_t j = 0; j < cols; ++j) {
        float acc = 0.0f;
        for (int64_t t = 0; t < k; ++t) {
          acc += xa[i * k + t] * ya[t * cols + j];
        }
        oa[i * cols + j] = acc;
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunAdd(const OpDesc& op, Scope* scope) {
    return RunBinary(op, scope, [](float a, float b) { return a + b; });
  }

  // shared elementwise-with-broadcast kernel (elementwise_op_function.h
  // role): add/sub/mul/div/min/max share the axis-aligned y broadcast
  std::string RunBinary(const OpDesc& op, Scope* scope,
                        const std::function<float(float, float)>& fn) {
    const std::string* xn = OneName(op, "X");
    const std::string* yn = OneName(op, "Y");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || yn == nullptr || on == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* y = scope->Find(*yn);
    if (x == nullptr || y == nullptr) return "input not in scope";
    if (!IsF32(*x) || !IsF32(*y)) return "non-f32 dtype";
    int64_t nx = NumElements(x->dims);
    int64_t ny = NumElements(y->dims);
    int64_t inner = 1;
    std::string berr = ResolveBroadcast(op, x->dims, y->dims, &inner);
    if (!berr.empty()) return berr;
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    const float* ya = F32(*y);
    float* oa = MutF32(&out);
    for (int64_t i = 0; i < nx; ++i) {
      oa[i] = fn(xa[i], ya[(i / inner) % ny]);
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunUnary(const OpDesc& op, Scope* scope,
                       const std::function<float(float)>& fn) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x)) return "non-f32 dtype";
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    int64_t n = NumElements(x->dims);
    for (int64_t i = 0; i < n; ++i) oa[i] = fn(xa[i]);
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunSoftmax(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x)) return "non-f32 dtype";
    if (x->dims.empty()) return "scalar softmax";
    int64_t cols = x->dims.back();
    int64_t rows = NumElements(x->dims) / cols;
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    for (int64_t i = 0; i < rows; ++i) {
      const float* row = xa + i * cols;
      float* orow = oa + i * cols;
      float mx = row[0];
      for (int64_t j = 1; j < cols; ++j) mx = std::max(mx, row[j]);
      float sum = 0.0f;
      for (int64_t j = 0; j < cols; ++j) {
        orow[j] = std::exp(row[j] - mx);
        sum += orow[j];
      }
      for (int64_t j = 0; j < cols; ++j) orow[j] /= sum;
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // Integer ids from an int32/int64/float32 tensor (feeds arrive in any
  // of the three; .npy params keep their stored width).
  static std::string ReadIds(const HostTensor& t, std::vector<int64_t>* out) {
    int64_t n = NumElements(t.dims);
    out->resize(n);
    if (t.dtype == "int32") {
      const int32_t* p = reinterpret_cast<const int32_t*>(t.data.data());
      for (int64_t i = 0; i < n; ++i) (*out)[i] = p[i];
    } else if (t.dtype == "int64") {
      const int64_t* p = reinterpret_cast<const int64_t*>(t.data.data());
      for (int64_t i = 0; i < n; ++i) (*out)[i] = p[i];
    } else if (t.dtype == "float32") {
      const float* p = reinterpret_cast<const float*>(t.data.data());
      for (int64_t i = 0; i < n; ++i) (*out)[i] = static_cast<int64_t>(p[i]);
    } else {
      return "unsupported ids dtype " + t.dtype;
    }
    return "";
  }

  // lookup_table_op.cc role: rows of W gathered by Ids; padding_idx rows
  // come back zero. Trailing singleton id dim is squeezed like the XLA
  // lowering (ops/tensor_ops.py _lower_lookup_table).
  std::string RunLookupTable(const OpDesc& op, Scope* scope) {
    const std::string* wn = OneName(op, "W");
    const std::string* idn = OneName(op, "Ids");
    const std::string* on = OneName(op, "Out", false);
    if (wn == nullptr || idn == nullptr || on == nullptr) {
      return "missing io";
    }
    const HostTensor* w = scope->Find(*wn);
    const HostTensor* ids_t = scope->Find(*idn);
    if (w == nullptr || ids_t == nullptr) return "input not in scope";
    if (!IsF32(*w) || w->dims.size() != 2) return "bad table";
    std::vector<int64_t> ids;
    std::string err = ReadIds(*ids_t, &ids);
    if (!err.empty()) return err;
    int64_t rows = w->dims[0], dim = w->dims[1];
    // padding_idx < 0 is the kNoPadding sentinel (XLA lowering only pads
    // when >= 0); trailing singleton squeezes only above rank 1, matching
    // jnp.ndim(ids) > 1 in _lower_lookup_table
    int64_t padding_idx = IntAttr(op, "padding_idx", -1);
    std::vector<int64_t> odims = ids_t->dims;
    if (odims.size() > 1 && odims.back() == 1) odims.pop_back();
    odims.push_back(dim);
    HostTensor out = MakeF32(odims);
    const float* wa = F32(*w);
    float* oa = MutF32(&out);
    for (size_t i = 0; i < ids.size(); ++i) {
      int64_t id = ids[i];
      if (padding_idx >= 0 && id == padding_idx) {
        for (int64_t j = 0; j < dim; ++j) oa[i * dim + j] = 0.0f;
        continue;
      }
      if (id < 0 || id >= rows) return "id out of range";
      for (int64_t j = 0; j < dim; ++j) oa[i * dim + j] = wa[id * dim + j];
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // sum_op.cc role: elementwise sum of N same-shaped inputs.
  std::string RunSum(const OpDesc& op, Scope* scope) {
    auto it = op.inputs.find("X");
    const std::string* on = OneName(op, "Out", false);
    if (it == op.inputs.end() || it->second.empty() || on == nullptr) {
      return "missing io";
    }
    HostTensor out;
    bool first = true;
    for (const std::string& name : it->second) {
      if (name.empty()) continue;
      const HostTensor* x = scope->Find(name);
      if (x == nullptr) return "input not in scope";
      if (!IsF32(*x)) return "non-f32 dtype";
      if (first) {
        out = MakeF32(x->dims);
        std::fill(MutF32(&out), MutF32(&out) + NumElements(out.dims), 0.0f);
        first = false;
      } else if (x->dims != out.dims) {
        return "shape mismatch";
      }
      const float* xa = F32(*x);
      float* oa = MutF32(&out);
      int64_t n = NumElements(out.dims);
      for (int64_t i = 0; i < n; ++i) oa[i] += xa[i];
    }
    if (first) return "no inputs";
    scope->Set(*on, std::move(out));
    return "";
  }

  // Per-row valid lengths: the optional Length input of the padded
  // sequence design (clamped to [0, T]); full T when absent.
  static std::string RowLengths(const OpDesc& op, Scope* scope, int64_t b,
                                int64_t t, std::vector<int64_t>* lens) {
    lens->assign(b, t);
    const std::string* ln = OneName(op, "Length");
    if (ln == nullptr) return "";
    const HostTensor* lt = scope->Find(*ln);
    if (lt == nullptr) return "Length not in scope";
    std::vector<int64_t> raw;
    std::string err = ReadIds(*lt, &raw);
    if (!err.empty()) return err;
    if (static_cast<int64_t>(raw.size()) != b) return "Length size mismatch";
    for (int64_t i = 0; i < b; ++i) {
      (*lens)[i] = std::max<int64_t>(0, std::min(raw[i], t));
    }
    return "";
  }

  // sequence_pool_op.cc role over the padded [B, T, D] layout
  // (ops/sequence_ops.py _lower_sequence_pool semantics, incl. the
  // len>=1 clamp for AVERAGE/SQRT and -1e38 fill for empty MAX rows).
  std::string RunSequencePool(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x) || x->dims.size() != 3) return "need f32 [B,T,D]";
    int64_t b = x->dims[0], t = x->dims[1], d = x->dims[2];
    std::vector<int64_t> lens;
    std::string err = RowLengths(op, scope, b, t, &lens);
    if (!err.empty()) return err;
    std::string ptype = StrAttr(op, "pooltype", "AVERAGE");
    for (char& c : ptype) c = std::toupper(c);
    if (ptype != "MAX" && ptype != "LAST" && ptype != "FIRST" &&
        ptype != "SUM" && ptype != "AVERAGE" && ptype != "SQRT") {
      return "unknown pooltype " + ptype;
    }
    HostTensor out = MakeF32({b, d});
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    for (int64_t i = 0; i < b; ++i) {
      int64_t len = lens[i];
      for (int64_t j = 0; j < d; ++j) {
        float v = 0.0f;
        if (ptype == "MAX") {
          v = -1e38f;
          for (int64_t s = 0; s < len; ++s) {
            v = std::max(v, xa[(i * t + s) * d + j]);
          }
        } else if (ptype == "LAST") {
          // zero-length rows clamp to index 0 (XLA: max(len-1, 0))
          v = xa[(i * t + std::max<int64_t>(len - 1, 0)) * d + j];
        } else if (ptype == "FIRST") {
          // FIRST ignores the mask entirely (XLA: x[:, 0])
          v = xa[(i * t + 0) * d + j];
        } else {  // SUM / AVERAGE / SQRT
          for (int64_t s = 0; s < len; ++s) v += xa[(i * t + s) * d + j];
          float denom = static_cast<float>(std::max<int64_t>(len, 1));
          if (ptype == "AVERAGE") v /= denom;
          if (ptype == "SQRT") v /= std::sqrt(denom);
        }
        oa[i * d + j] = v;
      }
    }
    scope->Set(*on, std::move(out));
    const std::string* min = OneName(op, "MaxIndex", false);
    if (min != nullptr) {
      // dummy like the XLA lowering (the grad recomputes its routing)
      HostTensor mi;
      mi.dtype = "int32";
      mi.dims = {1};
      mi.data.assign(sizeof(int32_t), 0);
      scope->Set(*min, std::move(mi));
    }
    return "";
  }

  static float Sigmoid(float v) { return 1.0f / (1.0f + std::exp(-v)); }

  static std::function<float(float)> ActFn(const std::string& name,
                                           bool* ok) {
    *ok = true;
    if (name == "sigmoid") return [](float v) { return Sigmoid(v); };
    if (name == "tanh") return [](float v) { return std::tanh(v); };
    if (name == "relu") return [](float v) { return std::max(0.0f, v); };
    if (name == "identity") return [](float v) { return v; };
    *ok = false;
    return [](float v) { return v; };
  }

  // lstm_op.cc role over the padded layout (same recurrence as
  // ops/rnn_ops.py _lower_dynamic_lstm): Input [B,T,4D] pre-projected
  // gates, Weight [D,4D] recurrent matrix, Bias [4D] (+[3D] peephole
  // diagonals), gate order i,f,c,o; masked steps carry h/c through.
  // ---- model-zoo breadth (VERDICT r3 Next #4): the ops GoogLeNet,
  // SE-ResNeXt, AlexNet, VGG, the MT model and the Transformer's full
  // logits path need beyond the CNN/transformer-encoder subset, so those
  // models serve Python-free like NativePaddlePredictor serves any
  // program (inference/api/api_impl.cc role).

  std::string RunConcat(const OpDesc& op, Scope* scope) {
    auto it = op.inputs.find("X");
    const std::string* on = OneName(op, "Out", false);
    if (it == op.inputs.end() || it->second.empty() || on == nullptr) {
      return "missing io";
    }
    std::vector<const HostTensor*> xs;
    for (const std::string& n : it->second) {
      if (n.empty()) continue;
      const HostTensor* x = scope->Find(n);
      if (x == nullptr) return "input not in scope";
      if (!IsF32(*x)) return "non-f32 dtype";
      xs.push_back(x);
    }
    if (xs.empty()) return "no inputs";
    size_t rank = xs[0]->dims.size();
    int64_t axis = IntAttr(op, "axis", 0);
    if (axis < 0) axis += rank;
    if (axis < 0 || axis >= static_cast<int64_t>(rank)) return "bad axis";
    std::vector<int64_t> odims = xs[0]->dims;
    int64_t cat = 0;
    for (const HostTensor* x : xs) {
      if (x->dims.size() != rank) return "rank mismatch";
      for (size_t d = 0; d < rank; ++d) {
        if (static_cast<int64_t>(d) != axis && x->dims[d] != odims[d]) {
          return "shape mismatch off the concat axis";
        }
      }
      cat += x->dims[axis];
    }
    odims[axis] = cat;
    // outer = product of dims before axis; copy per input its
    // (axis..end) contiguous run for each outer index
    int64_t outer = 1;
    for (int64_t d = 0; d < axis; ++d) outer *= odims[d];
    int64_t inner = 1;
    for (size_t d = axis + 1; d < rank; ++d) inner *= odims[d];
    HostTensor out = MakeF32(odims);
    float* oa = MutF32(&out);
    int64_t ostride = cat * inner;
    int64_t off = 0;
    for (const HostTensor* x : xs) {
      const float* xa = F32(*x);
      int64_t run = x->dims[axis] * inner;
      for (int64_t o = 0; o < outer; ++o) {
        std::copy(xa + o * run, xa + (o + 1) * run,
                  oa + o * ostride + off);
      }
      off += run;
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunSplit(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    auto ot = op.outputs.find("Out");
    if (xn == nullptr || ot == op.outputs.end() || ot->second.empty()) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr || !IsF32(*x)) return "bad input";
    size_t rank = x->dims.size();
    int64_t axis = IntAttr(op, "axis", 0);
    if (axis < 0) axis += rank;
    if (axis < 0 || axis >= static_cast<int64_t>(rank)) return "bad axis";
    int64_t n_out = static_cast<int64_t>(ot->second.size());
    std::vector<int64_t> sections = IntsAttr(op, "sections", {});
    if (sections.empty()) {
      int64_t num = IntAttr(op, "num", n_out);
      if (num <= 0 || x->dims[axis] % num != 0) return "bad num";
      sections.assign(num, x->dims[axis] / num);
    }
    if (static_cast<int64_t>(sections.size()) != n_out) {
      return "sections/outputs mismatch";
    }
    int64_t total = 0;
    for (int64_t s : sections) total += s;
    if (total != x->dims[axis]) return "sections do not cover the axis";
    int64_t outer = 1;
    for (int64_t d = 0; d < axis; ++d) outer *= x->dims[d];
    int64_t inner = 1;
    for (size_t d = axis + 1; d < rank; ++d) inner *= x->dims[d];
    const float* xa = F32(*x);
    int64_t xstride = x->dims[axis] * inner;
    int64_t off = 0;
    for (int64_t k = 0; k < n_out; ++k) {
      std::vector<int64_t> odims = x->dims;
      odims[axis] = sections[k];
      HostTensor out = MakeF32(odims);
      float* oa = MutF32(&out);
      int64_t run = sections[k] * inner;
      for (int64_t o = 0; o < outer; ++o) {
        std::copy(xa + o * xstride + off, xa + o * xstride + off + run,
                  oa + o * run);
      }
      off += run;
      scope->Set(ot->second[k], std::move(out));
    }
    return "";
  }

  std::string RunLrn(const OpDesc& op, Scope* scope) {
    // cross-channel local response normalization (lrn_op.cc):
    // out = x / (k + alpha * sum_{window n}(x^2))^beta, NCHW
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr || !IsF32(*x) || x->dims.size() != 4) {
      return "bad input";
    }
    int64_t n = IntAttr(op, "n", 5);
    float k = FloatAttr(op, "k", 2.0f);
    float alpha = FloatAttr(op, "alpha", 1e-4f);
    float beta = FloatAttr(op, "beta", 0.75f);
    if (n <= 0) return "bad window";
    // reference lrn_op.cc: start = -(n-1)/2 (biased toward higher
    // channels for even n); ops/nn_ops.py matches
    int64_t half = (n - 1) / 2;
    int64_t b = x->dims[0], c = x->dims[1], h = x->dims[2], w = x->dims[3];
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    // MidOut (k + alpha*acc) is the intermediate the grad op consumes
    const std::string* midn = OneName(op, "MidOut", false);
    HostTensor midt;
    float* mida = nullptr;
    if (midn != nullptr) {
      midt = MakeF32(x->dims);
      mida = MutF32(&midt);
    }
    int64_t hw = h * w;
    for (int64_t bi = 0; bi < b; ++bi) {
      for (int64_t ci = 0; ci < c; ++ci) {
        int64_t lo = std::max<int64_t>(0, ci - half);
        int64_t hi = std::min<int64_t>(c - 1, ci + (n - 1 - half));
        for (int64_t p = 0; p < hw; ++p) {
          float acc = 0.0f;
          for (int64_t cj = lo; cj <= hi; ++cj) {
            float v = xa[(bi * c + cj) * hw + p];
            acc += v * v;
          }
          float mid = k + alpha * acc;
          if (mida != nullptr) mida[(bi * c + ci) * hw + p] = mid;
          oa[(bi * c + ci) * hw + p] =
              xa[(bi * c + ci) * hw + p] / std::pow(mid, beta);
        }
      }
    }
    scope->Set(*on, std::move(out));
    if (midn != nullptr) scope->Set(*midn, std::move(midt));
    return "";
  }

  std::string RunConvTranspose2d(const OpDesc& op, Scope* scope) {
    // transposed conv (conv_transpose_op.cc role): scatter-accumulate
    // the forward-conv adjoint; filter layout [in_c, out_c/groups, kh, kw]
    const std::string* xn = OneName(op, "Input");
    const std::string* wn = OneName(op, "Filter");
    const std::string* on = OneName(op, "Output", false);
    if (xn == nullptr || wn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* w = scope->Find(*wn);
    if (x == nullptr || w == nullptr) return "input not in scope";
    if (!IsF32(*x) || !IsF32(*w)) return "non-f32 dtype";
    if (x->dims.size() != 4 || w->dims.size() != 4) return "rank != 4";
    auto strides = IntsAttr(op, "strides", {1, 1});
    auto pads = IntsAttr(op, "paddings", {0, 0});
    auto dil = IntsAttr(op, "dilations", {1, 1});
    auto osize = IntsAttr(op, "output_size", {});
    if (strides.size() != 2 || pads.size() != 2 || dil.size() != 2) {
      return "bad geometry attrs";
    }
    int64_t groups = IntAttr(op, "groups", 1);
    if (groups <= 0) groups = 1;
    int64_t n = x->dims[0], ci = x->dims[1], h = x->dims[2], wd = x->dims[3];
    int64_t wci = w->dims[0], cog = w->dims[1], kh = w->dims[2],
            kw = w->dims[3];
    if (wci != ci || ci % groups != 0) return "filter/channel mismatch";
    int64_t co = cog * groups;
    int64_t keffh = dil[0] * (kh - 1) + 1, keffw = dil[1] * (kw - 1) + 1;
    int64_t oh = (h - 1) * strides[0] - 2 * pads[0] + keffh;
    int64_t ow = (wd - 1) * strides[1] - 2 * pads[1] + keffw;
    if (osize.size() == 2) {
      // output_size picks among the stride-ambiguous candidates
      // (ops/nn_ops.py _transpose_extra_pad contract)
      if (osize[0] < oh || osize[0] >= oh + strides[0] ||
          osize[1] < ow || osize[1] >= ow + strides[1]) {
        return "output_size not reachable";
      }
      oh = osize[0];
      ow = osize[1];
    } else if (!osize.empty()) {
      return "bad output_size";
    }
    if (oh <= 0 || ow <= 0) return "empty output";
    HostTensor out = MakeF32({n, co, oh, ow});
    const float* xa = F32(*x);
    const float* wa = F32(*w);
    float* oa = MutF32(&out);
    std::fill(oa, oa + NumElements(out.dims), 0.0f);
    int64_t cig = ci / groups;
    for (int64_t b = 0; b < n; ++b) {
      for (int64_t ic = 0; ic < ci; ++ic) {
        int64_t g = ic / cig;
        for (int64_t i = 0; i < h; ++i) {
          for (int64_t j = 0; j < wd; ++j) {
            float xv = xa[((b * ci + ic) * h + i) * wd + j];
            if (xv == 0.0f) continue;
            for (int64_t ocg = 0; ocg < cog; ++ocg) {
              int64_t oc = g * cog + ocg;
              for (int64_t r = 0; r < kh; ++r) {
                int64_t yy = i * strides[0] - pads[0] + r * dil[0];
                if (yy < 0 || yy >= oh) continue;
                for (int64_t s = 0; s < kw; ++s) {
                  int64_t xx = j * strides[1] - pads[1] + s * dil[1];
                  if (xx < 0 || xx >= ow) continue;
                  oa[((b * co + oc) * oh + yy) * ow + xx] +=
                      xv * wa[((ic * cog + ocg) * kh + r) * kw + s];
                }
              }
            }
          }
        }
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunLogSoftmax(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr || !IsF32(*x)) return "bad input";
    size_t rank = x->dims.size();
    int64_t axis = IntAttr(op, "axis", -1);
    if (axis < 0) axis += rank;
    if (axis != static_cast<int64_t>(rank) - 1) {
      return "only last-axis log_softmax";
    }
    int64_t c = x->dims[rank - 1];
    int64_t rows = NumElements(x->dims) / c;
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    for (int64_t r = 0; r < rows; ++r) {
      const float* xr = xa + r * c;
      float mx = xr[0];
      for (int64_t j = 1; j < c; ++j) mx = std::max(mx, xr[j]);
      float denom = 0.0f;
      for (int64_t j = 0; j < c; ++j) denom += std::exp(xr[j] - mx);
      float lse = mx + std::log(denom);
      for (int64_t j = 0; j < c; ++j) oa[r * c + j] = xr[j] - lse;
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunPosEncoding(const OpDesc& op, Scope* scope) {
    // sinusoid position table (ops/attention_ops.py contract:
    // concat(sin, cos) halves over D): out = alpha*x + beta*table[t]
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr || !IsF32(*x) || x->dims.size() != 3) {
      return "bad input";
    }
    int64_t b = x->dims[0], t = x->dims[1], d = x->dims[2];
    if (d % 2 != 0) return "odd d_model";
    float alpha = FloatAttr(op, "alpha", 1.0f);
    float beta = FloatAttr(op, "beta", 1.0f);
    int64_t half = d / 2;
    std::vector<float> table(t * d);
    for (int64_t p = 0; p < t; ++p) {
      for (int64_t i = 0; i < half; ++i) {
        double angle = p / std::pow(
            10000.0, 2.0 * static_cast<double>(i) / d);
        table[p * d + i] = std::sin(angle);
        table[p * d + half + i] = std::cos(angle);
      }
    }
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    for (int64_t bi = 0; bi < b; ++bi) {
      for (int64_t p = 0; p < t; ++p) {
        for (int64_t j = 0; j < d; ++j) {
          oa[(bi * t + p) * d + j] =
              alpha * xa[(bi * t + p) * d + j] + beta * table[p * d + j];
        }
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunCast(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    std::string out_dtype = StrAttr(op, "out_dtype", "float32");
    int64_t total = NumElements(x->dims);
    // int -> int never goes through a float intermediate (a double
    // mangles int64 beyond 2^53)
    if ((x->dtype == "int64" || x->dtype == "int32") &&
        (out_dtype == "int64" || out_dtype == "int32")) {
      HostTensor iout;
      iout.dims = x->dims;
      iout.dtype = out_dtype;
      bool out64 = out_dtype == "int64";
      iout.data.resize(total * (out64 ? sizeof(int64_t) : sizeof(int32_t)));
      for (int64_t i = 0; i < total; ++i) {
        int64_t v = x->dtype == "int64"
            ? reinterpret_cast<const int64_t*>(x->data.data())[i]
            : reinterpret_cast<const int32_t*>(x->data.data())[i];
        if (out64) {
          reinterpret_cast<int64_t*>(iout.data.data())[i] = v;
        } else {
          reinterpret_cast<int32_t*>(iout.data.data())[i] =
              static_cast<int32_t>(v);
        }
      }
      scope->Set(*on, std::move(iout));
      return "";
    }
    // float-involved casts: read as double, write as the target
    std::vector<double> vals(total);
    if (x->dtype == "float32") {
      const float* p = F32(*x);
      for (int64_t i = 0; i < total; ++i) vals[i] = p[i];
    } else if (x->dtype == "int64") {
      const int64_t* p = reinterpret_cast<const int64_t*>(x->data.data());
      for (int64_t i = 0; i < total; ++i) {
        vals[i] = static_cast<double>(p[i]);
      }
    } else if (x->dtype == "int32") {
      const int32_t* p = reinterpret_cast<const int32_t*>(x->data.data());
      for (int64_t i = 0; i < total; ++i) vals[i] = p[i];
    } else {
      return "unsupported source dtype " + x->dtype;
    }
    HostTensor out;
    out.dims = x->dims;
    out.dtype = out_dtype;
    if (out_dtype == "float32") {
      out.data.resize(total * sizeof(float));
      float* p = reinterpret_cast<float*>(out.data.data());
      for (int64_t i = 0; i < total; ++i) {
        p[i] = static_cast<float>(vals[i]);
      }
    } else if (out_dtype == "int64") {
      out.data.resize(total * sizeof(int64_t));
      int64_t* p = reinterpret_cast<int64_t*>(out.data.data());
      for (int64_t i = 0; i < total; ++i) {
        p[i] = static_cast<int64_t>(vals[i]);
      }
    } else if (out_dtype == "int32") {
      out.data.resize(total * sizeof(int32_t));
      int32_t* p = reinterpret_cast<int32_t*>(out.data.data());
      for (int64_t i = 0; i < total; ++i) {
        p[i] = static_cast<int32_t>(vals[i]);
      }
    } else {
      return "unsupported target dtype " + out_dtype;
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunDequantizeWeight(const OpDesc& op, Scope* scope) {
    // int8-storage weight rehydration (convert_to_int8 deployment):
    // Out = int8 * step, step = scale / max_range
    const std::string* xn = OneName(op, "X");
    const std::string* sn = OneName(op, "Scale");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || sn == nullptr || on == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* sc = scope->Find(*sn);
    if (x == nullptr || sc == nullptr) return "input not in scope";
    if (x->dtype != "int8") return "weight not int8";
    if (!IsF32(*sc) || NumElements(sc->dims) < 1) return "bad scale";
    int64_t total = NumElements(x->dims);
    if (static_cast<int64_t>(x->data.size()) < total) {
      return "int8 payload shorter than shape";  // truncated/bad .npy
    }
    float step = F32(*sc)[0];
    HostTensor out = MakeF32(x->dims);
    const int8_t* xa = reinterpret_cast<const int8_t*>(x->data.data());
    float* oa = MutF32(&out);
    for (int64_t i = 0; i < total; ++i) {
      oa[i] = static_cast<float>(xa[i]) * step;
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunCrossEntropy(const OpDesc& op, Scope* scope) {
    // hard-label NLL over probabilities (cross_entropy_op.cc):
    // y = -log(max(p[label], eps)), eps matching ops/loss_ops.py
    const std::string* xn = OneName(op, "X");
    const std::string* ln = OneName(op, "Label");
    const std::string* on = OneName(op, "Y", false);
    if (xn == nullptr || ln == nullptr || on == nullptr) {
      return "missing io";
    }
    if (IntAttr(op, "soft_label", 0) != 0) return "soft_label unsupported";
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* label = scope->Find(*ln);
    if (x == nullptr || label == nullptr) return "input not in scope";
    if (!IsF32(*x) || x->dims.size() != 2) return "bad probs";
    int64_t n = x->dims[0], c = x->dims[1];
    if (NumElements(label->dims) != n) return "label count mismatch";
    std::vector<int64_t> lbl;
    std::string lerr = ReadIds(*label, &lbl);
    if (!lerr.empty()) return lerr;
    HostTensor out = MakeF32({n, 1});
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    for (int64_t i = 0; i < n; ++i) {
      if (lbl[i] < 0 || lbl[i] >= c) return "label out of range";
      oa[i] = -std::log(std::max(xa[i * c + lbl[i]], 1e-8f));
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunTopK(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    const std::string* in = OneName(op, "Indices", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr || !IsF32(*x) || x->dims.empty()) return "bad input";
    int64_t k = IntAttr(op, "k", 1);
    int64_t c = x->dims.back();
    if (k <= 0 || k > c) return "bad k";
    int64_t rows = NumElements(x->dims) / c;
    std::vector<int64_t> odims = x->dims;
    odims.back() = k;
    HostTensor vals = MakeF32(odims);
    HostTensor idx;
    idx.dtype = "int64";
    idx.dims = odims;
    idx.data.resize(rows * k * sizeof(int64_t));
    const float* xa = F32(*x);
    float* va = MutF32(&vals);
    int64_t* ia = reinterpret_cast<int64_t*>(idx.data.data());
    std::vector<int64_t> order(c);
    for (int64_t r = 0; r < rows; ++r) {
      const float* xr = xa + r * c;
      for (int64_t j = 0; j < c; ++j) order[j] = j;
      // stable partial sort: ties keep the lower index first, matching
      // jax.lax.top_k
      std::stable_sort(order.begin(), order.end(),
                       [xr](int64_t a, int64_t b2) {
                         return xr[a] > xr[b2];
                       });
      for (int64_t j = 0; j < k; ++j) {
        va[r * k + j] = xr[order[j]];
        ia[r * k + j] = order[j];
      }
    }
    scope->Set(*on, std::move(vals));
    if (in != nullptr) scope->Set(*in, std::move(idx));
    return "";
  }

  std::string RunAccuracy(const OpDesc& op, Scope* scope) {
    // hit-rate over top-k indices (accuracy_op.cc): Indices [N, k],
    // Label [N, 1] -> Accuracy [1]
    const std::string* in = OneName(op, "Indices");
    const std::string* ln = OneName(op, "Label");
    const std::string* an = OneName(op, "Accuracy", false);
    if (in == nullptr || ln == nullptr || an == nullptr) {
      return "missing io";
    }
    const HostTensor* indices = scope->Find(*in);
    const HostTensor* label = scope->Find(*ln);
    if (indices == nullptr || label == nullptr) return "input not in scope";
    if (indices->dtype != "int64" || indices->dims.size() != 2) {
      return "bad indices";
    }
    int64_t n = indices->dims[0], k = indices->dims[1];
    if (NumElements(label->dims) != n) return "label count mismatch";
    std::vector<int64_t> lbl;
    std::string lerr = ReadIds(*label, &lbl);
    if (!lerr.empty()) return lerr;
    const int64_t* ia =
        reinterpret_cast<const int64_t*>(indices->data.data());
    int64_t correct = 0;
    for (int64_t i = 0; i < n; ++i) {
      for (int64_t j = 0; j < k; ++j) {
        if (ia[i * k + j] == lbl[i]) {
          ++correct;
          break;
        }
      }
    }
    HostTensor acc = MakeF32({1});
    MutF32(&acc)[0] =
        static_cast<float>(correct) / static_cast<float>(n);
    scope->Set(*an, std::move(acc));
    const std::string* cn = OneName(op, "Correct", false);
    const std::string* tn = OneName(op, "Total", false);
    if (cn != nullptr) {
      HostTensor c32;
      c32.dtype = "int32";
      c32.dims = {1};
      c32.data.resize(sizeof(int32_t));
      *reinterpret_cast<int32_t*>(c32.data.data()) =
          static_cast<int32_t>(correct);
      scope->Set(*cn, std::move(c32));
    }
    if (tn != nullptr) {
      HostTensor t32;
      t32.dtype = "int32";
      t32.dims = {1};
      t32.data.resize(sizeof(int32_t));
      *reinterpret_cast<int32_t*>(t32.data.data()) =
          static_cast<int32_t>(n);
      scope->Set(*tn, std::move(t32));
    }
    return "";
  }

  std::string RunAttentionLstm(const OpDesc& op, Scope* scope) {
    // fused per-step attention + LSTM cell (attention_lstm_op.cc role;
    // math contract = ops/seq2seq_ops.py _lower_attention_lstm):
    //   e[b,s] = tanh(enc_proj[b,s]@wa_e + (h@Ws)@wa_s); alpha =
    //   masked softmax_s(e); context = sum_s alpha*enc_vec;
    //   gates = [h, context, x_t]@CellW + CellB -> standard cell
    const std::string* xn = OneName(op, "X");
    const std::string* evn = OneName(op, "EncoderVec");
    const std::string* epn = OneName(op, "EncoderProj");
    const std::string* h0n = OneName(op, "H0");
    const std::string* wsn = OneName(op, "StateProjW");
    const std::string* wan = OneName(op, "AttnW");
    const std::string* cwn = OneName(op, "CellW");
    const std::string* cbn = OneName(op, "CellB");
    const std::string* hn = OneName(op, "Hidden", false);
    if (xn == nullptr || evn == nullptr || epn == nullptr ||
        h0n == nullptr || wsn == nullptr || wan == nullptr ||
        cwn == nullptr || cbn == nullptr || hn == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* ev = scope->Find(*evn);
    const HostTensor* ep = scope->Find(*epn);
    const HostTensor* h0 = scope->Find(*h0n);
    const HostTensor* ws = scope->Find(*wsn);
    const HostTensor* wa = scope->Find(*wan);
    const HostTensor* cw = scope->Find(*cwn);
    const HostTensor* cb = scope->Find(*cbn);
    for (const HostTensor* t :
         {x, ev, ep, h0, ws, wa, cw, cb}) {
      if (t == nullptr) return "input not in scope";
      if (!IsF32(*t)) return "non-f32 dtype";
    }
    if (x->dims.size() != 3 || ev->dims.size() != 3 ||
        ep->dims.size() != 3 || h0->dims.size() != 2 ||
        ws->dims.size() != 2 || cw->dims.size() != 2) {
      return "bad ranks";
    }
    int64_t B = x->dims[0], T = x->dims[1], M = x->dims[2];
    int64_t S = ev->dims[1], C = ev->dims[2], D = h0->dims[1];
    if (ev->dims[0] != B || ep->dims[0] != B || ep->dims[1] != S ||
        ep->dims[2] != D || h0->dims[0] != B ||
        ws->dims[0] != D || ws->dims[1] != D ||
        NumElements(wa->dims) != 2 * D ||
        cw->dims[0] != D + C + M || cw->dims[1] != 4 * D ||
        NumElements(cb->dims) != 4 * D) {
      return "shape mismatch";
    }
    std::vector<float> c0v(B * D, 0.0f);
    const std::string* c0n = OneName(op, "C0");
    if (c0n != nullptr) {
      const HostTensor* c0 = scope->Find(*c0n);
      if (c0 == nullptr || !IsF32(*c0) ||
          NumElements(c0->dims) != B * D) {
        return "bad C0";
      }
      const float* p = F32(*c0);
      std::copy(p, p + B * D, c0v.begin());
    }
    std::vector<int64_t> enc_lens(B, S);
    const std::string* eln = OneName(op, "EncoderLen");
    if (eln != nullptr) {
      const HostTensor* el = scope->Find(*eln);
      if (el == nullptr || NumElements(el->dims) != B) {
        return "bad EncoderLen";
      }
      std::string lerr = ReadIds(*el, &enc_lens);
      if (!lerr.empty()) return lerr;
      for (int64_t i = 0; i < B; ++i) {
        enc_lens[i] = std::min<int64_t>(std::max<int64_t>(enc_lens[i], 0),
                                        S);
      }
    }
    const float* xa = F32(*x);
    const float* eva = F32(*ev);
    const float* epa = F32(*ep);
    const float* wsa = F32(*ws);
    const float* waa = F32(*wa);  // [2D]: wa_e = [:D], wa_s = [D:]
    const float* cwa = F32(*cw);
    const float* cba = F32(*cb);
    HostTensor hidden = MakeF32({B, T, D});
    float* ha = MutF32(&hidden);
    const std::string* cn = OneName(op, "Cell", false);
    const std::string* awn = OneName(op, "AttentionWeight", false);
    HostTensor cell = MakeF32({B, T, D});
    HostTensor attw = MakeF32({B, T, S});
    float* ca = MutF32(&cell);
    float* awa = MutF32(&attw);
    std::vector<float> h(B * D), c(c0v), sp(D), e(S), ctx(C),
        gates(4 * D);
    std::copy(F32(*h0), F32(*h0) + B * D, h.begin());
    for (int64_t t = 0; t < T; ++t) {
      for (int64_t b = 0; b < B; ++b) {
        const float* hrow = h.data() + b * D;
        float* crow = c.data() + b * D;
        // state_proj = h @ Ws, then its scalar read (state_proj @ wa_s)
        float sp_scalar = 0.0f;
        for (int64_t j = 0; j < D; ++j) {
          float acc = 0.0f;
          for (int64_t k2 = 0; k2 < D; ++k2) {
            acc += hrow[k2] * wsa[k2 * D + j];
          }
          sp[j] = acc;
          sp_scalar += acc * waa[D + j];
        }
        float mx = -1e30f;
        int64_t len = enc_lens[b];
        if (len <= 0) {
          // zero-length encoder row: uniform-over-padding would be a
          // silent degenerate result; emit zero weights and zero
          // context (ops/seq2seq_ops.py _attend mirrors this)
          std::fill(ctx.begin(), ctx.end(), 0.0f);
          for (int64_t s = 0; s < S; ++s) {
            awa[(b * T + t) * S + s] = 0.0f;
          }
        } else {
          for (int64_t s = 0; s < S; ++s) {
            if (s < len) {
              float dot = 0.0f;
              for (int64_t j = 0; j < D; ++j) {
                dot += epa[(b * S + s) * D + j] * waa[j];
              }
              e[s] = std::tanh(dot + sp_scalar);
              mx = std::max(mx, e[s]);
            } else {
              e[s] = -1e30f;
            }
          }
          float denom = 0.0f;
          for (int64_t s = 0; s < S; ++s) {
            e[s] = std::exp(e[s] - mx);
            denom += e[s];
          }
          if (denom <= 0.0f) denom = 1.0f;
          std::fill(ctx.begin(), ctx.end(), 0.0f);
          for (int64_t s = 0; s < S; ++s) {
            float alpha = e[s] / denom;
            awa[(b * T + t) * S + s] = alpha;
            const float* evr = eva + (b * S + s) * C;
            for (int64_t j = 0; j < C; ++j) ctx[j] += alpha * evr[j];
          }
        }
        // gates = [h, context, x_t] @ CellW + CellB
        const float* xrow = xa + (b * T + t) * M;
        for (int64_t g = 0; g < 4 * D; ++g) {
          float acc = cba[g];
          for (int64_t j = 0; j < D; ++j) {
            acc += hrow[j] * cwa[j * 4 * D + g];
          }
          for (int64_t j = 0; j < C; ++j) {
            acc += ctx[j] * cwa[(D + j) * 4 * D + g];
          }
          for (int64_t j = 0; j < M; ++j) {
            acc += xrow[j] * cwa[(D + C + j) * 4 * D + g];
          }
          gates[g] = acc;
        }
        float* hout = h.data() + b * D;
        for (int64_t k2 = 0; k2 < D; ++k2) {
          float iv = 1.0f / (1.0f + std::exp(-gates[0 * D + k2]));
          float fv = 1.0f / (1.0f + std::exp(-gates[1 * D + k2]));
          float gv = std::tanh(gates[2 * D + k2]);
          float ov = 1.0f / (1.0f + std::exp(-gates[3 * D + k2]));
          float cv = fv * crow[k2] + iv * gv;
          crow[k2] = cv;
          hout[k2] = ov * std::tanh(cv);
        }
        for (int64_t k2 = 0; k2 < D; ++k2) {
          ha[(b * T + t) * D + k2] = hout[k2];
          ca[(b * T + t) * D + k2] = crow[k2];
        }
      }
    }
    scope->Set(*hn, std::move(hidden));
    if (cn != nullptr) scope->Set(*cn, std::move(cell));
    if (awn != nullptr) scope->Set(*awn, std::move(attw));
    return "";
  }

  std::string RunDynamicGru(const OpDesc& op, Scope* scope) {
    // GRU recurrence matching ops/rnn_ops.py _lower_dynamic_gru: gates
    // g = x[:, :2D] + h @ W[:, :2D] + b[:2D]; u = act(g[:, :D]),
    // r = act(g[:, D:2D]); c = cand(x[:, 2D:] + (r*h) @ W[:, 2D:] +
    // b[2D:]); h' = u*h + (1-u)*c
    const std::string* xn = OneName(op, "Input");
    const std::string* wn = OneName(op, "Weight");
    const std::string* hn = OneName(op, "Hidden", false);
    if (xn == nullptr || wn == nullptr || hn == nullptr) return "missing io";
    if (OneName(op, "H0") != nullptr) {
      return "H0 initial state not supported";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* w = scope->Find(*wn);
    if (x == nullptr || w == nullptr) return "input not in scope";
    if (!IsF32(*x) || !IsF32(*w)) return "non-f32 dtype";
    if (x->dims.size() != 3 || w->dims.size() != 2) return "bad ranks";
    int64_t b = x->dims[0], t = x->dims[1], d = w->dims[0];
    if (x->dims[2] != 3 * d || w->dims[1] != 3 * d) return "gate dims";
    bool reverse = IntAttr(op, "is_reverse", 0) != 0;
    bool ok1 = true, ok2 = true;
    auto gate_act = ActFn(StrAttr(op, "gate_activation", "sigmoid"), &ok1);
    auto cand_act = ActFn(StrAttr(op, "activation", "tanh"), &ok2);
    if (!ok1 || !ok2) return "unsupported activation";
    const float* bias = nullptr;
    const std::string* bn = OneName(op, "Bias");
    if (bn != nullptr) {
      const HostTensor* bt = scope->Find(*bn);
      if (bt == nullptr) return "Bias not in scope";
      if (!IsF32(*bt) || NumElements(bt->dims) < 3 * d) return "bad bias";
      bias = F32(*bt);
    }
    std::vector<int64_t> lens;
    std::string err = RowLengths(op, scope, b, t, &lens);
    if (!err.empty()) return err;
    HostTensor hidden = MakeF32({b, t, d});
    const float* xa = F32(*x);
    const float* wa = F32(*w);
    float* ha = MutF32(&hidden);
    std::vector<float> h(b * d, 0.0f), g(2 * d), c(d), rh(d);
    for (int64_t step = 0; step < t; ++step) {
      int64_t s = reverse ? t - 1 - step : step;
      for (int64_t i = 0; i < b; ++i) {
        bool valid = s < lens[i];
        const float* xrow = xa + (i * t + s) * 3 * d;
        float* hrow = h.data() + i * d;
        if (valid) {
          for (int64_t j = 0; j < 2 * d; ++j) {
            float acc = xrow[j] + (bias != nullptr ? bias[j] : 0.0f);
            for (int64_t k = 0; k < d; ++k) {
              acc += hrow[k] * wa[k * 3 * d + j];
            }
            g[j] = acc;
          }
          for (int64_t k = 0; k < d; ++k) {
            rh[k] = gate_act(g[d + k]) * hrow[k];  // r * h
          }
          for (int64_t k = 0; k < d; ++k) {
            float acc = xrow[2 * d + k] +
                        (bias != nullptr ? bias[2 * d + k] : 0.0f);
            for (int64_t m = 0; m < d; ++m) {
              acc += rh[m] * wa[m * 3 * d + 2 * d + k];
            }
            c[k] = cand_act(acc);
          }
          for (int64_t k = 0; k < d; ++k) {
            float u = gate_act(g[k]);
            hrow[k] = u * hrow[k] + (1.0f - u) * c[k];
          }
        }
        for (int64_t k = 0; k < d; ++k) {
          ha[(i * t + s) * d + k] = hrow[k];
        }
      }
    }
    scope->Set(*hn, std::move(hidden));
    return "";
  }



  // reduce_{sum,mean} backward: broadcast dOut back over the reduced
  // dims (divided by the reduced count for mean) — adjoint of RunReduce
  std::string RunReduceGrad(const OpDesc& op, Scope* scope, bool mean) {
    const std::string* xn = OneName(op, "X");
    const std::string* ogn = OneName(op, "Out@GRAD");
    const std::string* gn = OneName(op, "X@GRAD", false);
    if (xn == nullptr || ogn == nullptr || gn == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* og = scope->Find(*ogn);
    if (x == nullptr || og == nullptr) return "input not in scope";
    if (!IsF32(*x) || !IsF32(*og)) return "non-f32 dtype";
    size_t rank = x->dims.size();
    std::vector<bool> reduced;
    int64_t denom = 1;
    std::string rerr = ResolveReduce(op, x->dims, &reduced, &denom);
    if (!rerr.empty()) return rerr;
    // flat index mapping: out strides over non-reduced dims only
    std::vector<int64_t> ostride(rank, 0);
    int64_t run = 1;
    for (size_t d = rank; d-- > 0;) {
      if (!reduced[d]) {
        ostride[d] = run;
        run *= x->dims[d];
      }
    }
    if (NumElements(og->dims) != run) return "dOut size mismatch";
    HostTensor grad = MakeF32(x->dims);
    const float* ga = F32(*og);
    float* ra = MutF32(&grad);
    float scale = mean ? 1.0f / static_cast<float>(denom) : 1.0f;
    std::vector<int64_t> idx(rank, 0);
    int64_t total = NumElements(x->dims);
    for (int64_t i = 0; i < total; ++i) {
      int64_t oi = 0;
      for (size_t d = 0; d < rank; ++d) oi += idx[d] * ostride[d];
      ra[i] = ga[oi] * scale;
      for (size_t d = rank; d-- > 0;) {
        if (++idx[d] < x->dims[d]) break;
        idx[d] = 0;
      }
    }
    scope->Set(*gn, std::move(grad));
    return "";
  }

  // derivative of an activation expressed via its OUTPUT value
  static std::function<float(float)> ActDeriv(const std::string& name,
                                              bool* ok) {
    *ok = true;
    if (name == "sigmoid") return [](float a) { return a * (1.0f - a); };
    if (name == "tanh") return [](float a) { return 1.0f - a * a; };
    if (name == "relu") return [](float a) { return a > 0.0f ? 1.0f : 0.0f; };
    if (name == "identity") return [](float a) { return 1.0f; };
    *ok = false;
    return [](float a) { return 0.0f; };
  }




  // Adjoint of the fused attention_lstm decoder (RunAttentionLstm):
  // per step backward through the LSTM cell (i,f,g,o; sigma/tanh) and
  // the additive-attention read (stored AttentionWeight rows are the
  // exact softmax probs; the tanh scores are recomputed). Zero-length
  // encoder rows skipped the attention forward (ctx = 0), so their
  // adjoint flows only through the cell. H0 grads supported; C0 and
  // EncoderLen-variable programs follow the forward's zero-c0
  // convention.
  std::string RunAttentionLstmGrad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* evn = OneName(op, "EncoderVec");
    const std::string* epn = OneName(op, "EncoderProj");
    const std::string* hn = OneName(op, "Hidden");
    const std::string* cn = OneName(op, "Cell");
    const std::string* awn = OneName(op, "AttentionWeight");
    const std::string* h0n = OneName(op, "H0");
    const std::string* wsn = OneName(op, "StateProjW");
    const std::string* wan = OneName(op, "AttnW");
    const std::string* cwn = OneName(op, "CellW");
    const std::string* cbn = OneName(op, "CellB");
    const std::string* hgn = OneName(op, "Hidden@GRAD");
    if (xn == nullptr || evn == nullptr || epn == nullptr ||
        hn == nullptr || cn == nullptr || awn == nullptr ||
        h0n == nullptr || wsn == nullptr || wan == nullptr ||
        cwn == nullptr || cbn == nullptr || hgn == nullptr) {
      return "missing io";
    }
    if (OneName(op, "C0") != nullptr) {
      return "C0 initial cell not supported";
    }
    // losses touching the Cell or AttentionWeight outputs would feed
    // adjoints this kernel does not propagate: refuse rather than
    // train on silently wrong gradients (RunDynamicLstmGrad handles
    // its Cell@GRAD; this fused kernel only supports Hidden losses)
    if (OneName(op, "Cell@GRAD") != nullptr) {
      return "Cell@GRAD not supported (loss through Cell)";
    }
    if (OneName(op, "AttentionWeight@GRAD") != nullptr) {
      return "AttentionWeight@GRAD not supported";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* ev = scope->Find(*evn);
    const HostTensor* ep = scope->Find(*epn);
    const HostTensor* hid = scope->Find(*hn);
    const HostTensor* cel = scope->Find(*cn);
    const HostTensor* aw = scope->Find(*awn);
    const HostTensor* h0 = scope->Find(*h0n);
    const HostTensor* ws = scope->Find(*wsn);
    const HostTensor* wat = scope->Find(*wan);
    const HostTensor* cw = scope->Find(*cwn);
    const HostTensor* cb = scope->Find(*cbn);
    const HostTensor* hg = scope->Find(*hgn);
    for (const HostTensor* tt :
         {x, ev, ep, hid, cel, aw, h0, ws, wat, cw, cb, hg}) {
      if (tt == nullptr) return "input not in scope";
      if (!IsF32(*tt)) return "non-f32 dtype";
    }
    if (x->dims.size() != 3 || ev->dims.size() != 3 ||
        ep->dims.size() != 3) {
      return "bad ranks";
    }
    int64_t B = x->dims[0], T = x->dims[1], M = x->dims[2];
    int64_t S = ev->dims[1], C = ev->dims[2];
    if (ev->dims[0] != B) return "EncoderVec batch mismatch";
    int64_t D = ws->dims.size() == 2 ? ws->dims[0] : 0;
    if (ws->dims != std::vector<int64_t>({D, D}) ||
        NumElements(wat->dims) != 2 * D ||
        cw->dims != std::vector<int64_t>({D + C + M, 4 * D}) ||
        NumElements(cb->dims) != 4 * D ||
        hid->dims != std::vector<int64_t>({B, T, D}) ||
        cel->dims != hid->dims || hg->dims != hid->dims ||
        aw->dims != std::vector<int64_t>({B, T, S}) ||
        h0->dims != std::vector<int64_t>({B, D}) ||
        ep->dims != std::vector<int64_t>({B, S, D})) {
      return "shape mismatch";
    }
    std::vector<int64_t> lens(B, S);
    const std::string* eln = OneName(op, "EncoderLen");
    if (eln != nullptr) {
      const HostTensor* lt = scope->Find(*eln);
      if (lt == nullptr) return "EncoderLen not in scope";
      std::vector<int64_t> raw;
      std::string e2 = ReadIds(*lt, &raw);
      if (!e2.empty()) return e2;
      if (static_cast<int64_t>(raw.size()) != B) return "len count";
      for (int64_t i = 0; i < B; ++i) {
        lens[i] = std::min<int64_t>(std::max<int64_t>(raw[i], 0), S);
      }
    }
    const float* xa = F32(*x);
    const float* eva = F32(*ev);
    const float* epa = F32(*ep);
    const float* ha = F32(*hid);
    const float* ca = F32(*cel);
    const float* awa = F32(*aw);
    const float* h0a = F32(*h0);
    const float* wsa = F32(*ws);
    const float* waa = F32(*wat);
    const float* cwa = F32(*cw);
    const float* cba = F32(*cb);
    const float* hga = F32(*hg);

    auto out_buf = [&](const char* slot, std::vector<int64_t> dims,
                       HostTensor* t, float** p) -> bool {
      const std::string* nm = OneName(op, slot, false);
      if (nm == nullptr) return false;
      *t = MakeF32(dims);
      *p = MutF32(t);
      std::fill(*p, *p + NumElements(dims), 0.0f);
      return true;
    };
    HostTensor xg, evg, epg, h0g, wsg, wag, cwg, cbg;
    float* xga = nullptr;
    float* evga = nullptr;
    float* epga = nullptr;
    float* h0ga = nullptr;
    float* wsga = nullptr;
    float* waga = nullptr;
    float* cwga = nullptr;
    float* cbga = nullptr;
    bool want_x = out_buf("X@GRAD", x->dims, &xg, &xga);
    bool want_ev = out_buf("EncoderVec@GRAD", ev->dims, &evg, &evga);
    bool want_ep = out_buf("EncoderProj@GRAD", ep->dims, &epg, &epga);
    bool want_h0 = out_buf("H0@GRAD", h0->dims, &h0g, &h0ga);
    bool want_ws = out_buf("StateProjW@GRAD", ws->dims, &wsg, &wsga);
    bool want_wa = out_buf("AttnW@GRAD", wat->dims, &wag, &waga);
    bool want_cw = out_buf("CellW@GRAD", cw->dims, &cwg, &cwga);
    bool want_cb = out_buf("CellB@GRAD", cb->dims, &cbg, &cbga);

    std::vector<float> dh(B * D, 0.0f), dc(B * D, 0.0f);
    std::vector<float> gates(4 * D), dgates(4 * D), ctx(C), dctx(C),
        sp(D), dsp(D), dalpha(S);
    for (int64_t t = T - 1; t >= 0; --t) {
      for (int64_t b = 0; b < B; ++b) {
        const float* hprev = t > 0 ? ha + (b * T + t - 1) * D
                                   : h0a + b * D;
        const float* cprev_row =
            t > 0 ? ca + (b * T + t - 1) * D : nullptr;
        const float* crow = ca + (b * T + t) * D;
        const float* xrow = xa + (b * T + t) * M;
        const float* arow = awa + (b * T + t) * S;
        int64_t len = lens[b];
        // recompute sp, sp_scalar and ctx (from stored alphas)
        float sp_scalar = 0.0f;
        for (int64_t j = 0; j < D; ++j) {
          float acc = 0.0f;
          for (int64_t k2 = 0; k2 < D; ++k2) {
            acc += hprev[k2] * wsa[k2 * D + j];
          }
          sp[j] = acc;
          sp_scalar += acc * waa[D + j];
        }
        for (int64_t j = 0; j < C; ++j) ctx[j] = 0.0f;
        for (int64_t s2 = 0; s2 < len; ++s2) {
          const float* evr = eva + (b * S + s2) * C;
          for (int64_t j = 0; j < C; ++j) {
            ctx[j] += arow[s2] * evr[j];
          }
        }
        // recompute cell pre-activations
        for (int64_t g2 = 0; g2 < 4 * D; ++g2) {
          float acc = cba[g2];
          for (int64_t j = 0; j < D; ++j) {
            acc += hprev[j] * cwa[j * 4 * D + g2];
          }
          for (int64_t j = 0; j < C; ++j) {
            acc += ctx[j] * cwa[(D + j) * 4 * D + g2];
          }
          for (int64_t j = 0; j < M; ++j) {
            acc += xrow[j] * cwa[(D + C + j) * 4 * D + g2];
          }
          gates[g2] = acc;
        }
        // cell backward
        float* dhr = dh.data() + b * D;
        float* dcr = dc.data() + b * D;
        const float* hg_row = hga + (b * T + t) * D;
        for (int64_t k2 = 0; k2 < D; ++k2) {
          float cpv = cprev_row != nullptr ? cprev_row[k2] : 0.0f;
          float iv = Sigmoid(gates[0 * D + k2]);
          float fv = Sigmoid(gates[1 * D + k2]);
          float gv = std::tanh(gates[2 * D + k2]);
          float ov = Sigmoid(gates[3 * D + k2]);
          float cv = crow[k2];
          float tc = std::tanh(cv);
          float dh_k = dhr[k2] + hg_row[k2];
          float dc_k = dcr[k2];
          float dov = dh_k * tc;
          float dgo = dov * ov * (1.0f - ov);
          dc_k += dh_k * ov * (1.0f - tc * tc);
          float div2 = dc_k * gv;
          float dgv = dc_k * iv;
          float dfv = dc_k * cpv;
          dgates[0 * D + k2] = div2 * iv * (1.0f - iv);
          dgates[1 * D + k2] = dfv * fv * (1.0f - fv);
          dgates[2 * D + k2] = dgv * (1.0f - gv * gv);
          dgates[3 * D + k2] = dgo;
          dcr[k2] = dc_k * fv;
        }
        if (cbga != nullptr) {
          for (int64_t g2 = 0; g2 < 4 * D; ++g2) cbga[g2] += dgates[g2];
        }
        if (cwga != nullptr) {
          for (int64_t j = 0; j < D; ++j) {
            for (int64_t g2 = 0; g2 < 4 * D; ++g2) {
              cwga[j * 4 * D + g2] += hprev[j] * dgates[g2];
            }
          }
          for (int64_t j = 0; j < C; ++j) {
            for (int64_t g2 = 0; g2 < 4 * D; ++g2) {
              cwga[(D + j) * 4 * D + g2] += ctx[j] * dgates[g2];
            }
          }
          for (int64_t j = 0; j < M; ++j) {
            for (int64_t g2 = 0; g2 < 4 * D; ++g2) {
              cwga[(D + C + j) * 4 * D + g2] += xrow[j] * dgates[g2];
            }
          }
        }
        if (xga != nullptr) {
          float* xgr = xga + (b * T + t) * M;
          for (int64_t j = 0; j < M; ++j) {
            float acc = 0.0f;
            for (int64_t g2 = 0; g2 < 4 * D; ++g2) {
              acc += cwa[(D + C + j) * 4 * D + g2] * dgates[g2];
            }
            xgr[j] += acc;
          }
        }
        for (int64_t j = 0; j < C; ++j) {
          float acc = 0.0f;
          for (int64_t g2 = 0; g2 < 4 * D; ++g2) {
            acc += cwa[(D + j) * 4 * D + g2] * dgates[g2];
          }
          dctx[j] = acc;
        }
        // dh from the cell's h_prev rows (overwrite carry)
        for (int64_t j = 0; j < D; ++j) {
          float acc = 0.0f;
          for (int64_t g2 = 0; g2 < 4 * D; ++g2) {
            acc += cwa[j * 4 * D + g2] * dgates[g2];
          }
          dhr[j] = acc;
        }
        // attention backward (skipped for zero-length rows: ctx was a
        // constant 0 there, exactly like the forward)
        if (len > 0) {
          double adot = 0.0;
          for (int64_t s2 = 0; s2 < len; ++s2) {
            const float* evr = eva + (b * S + s2) * C;
            float acc = 0.0f;
            for (int64_t j = 0; j < C; ++j) acc += dctx[j] * evr[j];
            dalpha[s2] = acc;
            adot += static_cast<double>(arow[s2]) * acc;
            if (evga != nullptr) {
              float* evgr = evga + (b * S + s2) * C;
              for (int64_t j = 0; j < C; ++j) {
                evgr[j] += arow[s2] * dctx[j];
              }
            }
          }
          float dsp_scalar = 0.0f;
          for (int64_t s2 = 0; s2 < len; ++s2) {
            // softmax adjoint, then tanh: recompute the score u_s
            float de = arow[s2] * (dalpha[s2] -
                                   static_cast<float>(adot));
            const float* epr = epa + (b * S + s2) * D;
            float dot = 0.0f;
            for (int64_t j = 0; j < D; ++j) dot += epr[j] * waa[j];
            float e2 = std::tanh(dot + sp_scalar);
            float du_s = de * (1.0f - e2 * e2);
            dsp_scalar += du_s;
            if (waga != nullptr) {
              for (int64_t j = 0; j < D; ++j) {
                waga[j] += du_s * epr[j];
              }
            }
            if (epga != nullptr) {
              float* epgr = epga + (b * S + s2) * D;
              for (int64_t j = 0; j < D; ++j) {
                epgr[j] += du_s * waa[j];
              }
            }
          }
          for (int64_t j = 0; j < D; ++j) {
            dsp[j] = dsp_scalar * waa[D + j];
            if (waga != nullptr) waga[D + j] += dsp_scalar * sp[j];
          }
          if (wsga != nullptr) {
            for (int64_t k2 = 0; k2 < D; ++k2) {
              for (int64_t j = 0; j < D; ++j) {
                wsga[k2 * D + j] += hprev[k2] * dsp[j];
              }
            }
          }
          for (int64_t k2 = 0; k2 < D; ++k2) {
            float acc = 0.0f;
            for (int64_t j = 0; j < D; ++j) {
              acc += wsa[k2 * D + j] * dsp[j];
            }
            dhr[k2] += acc;
          }
        }
        if (t == 0 && h0ga != nullptr) {
          for (int64_t j = 0; j < D; ++j) {
            h0ga[b * D + j] += dhr[j];
          }
        }
      }
    }
    if (want_x) scope->Set(*OneName(op, "X@GRAD", false), std::move(xg));
    if (want_ev) {
      scope->Set(*OneName(op, "EncoderVec@GRAD", false), std::move(evg));
    }
    if (want_ep) {
      scope->Set(*OneName(op, "EncoderProj@GRAD", false),
                 std::move(epg));
    }
    if (want_h0) {
      scope->Set(*OneName(op, "H0@GRAD", false), std::move(h0g));
    }
    if (want_ws) {
      scope->Set(*OneName(op, "StateProjW@GRAD", false), std::move(wsg));
    }
    if (want_wa) {
      scope->Set(*OneName(op, "AttnW@GRAD", false), std::move(wag));
    }
    if (want_cw) {
      scope->Set(*OneName(op, "CellW@GRAD", false), std::move(cwg));
    }
    if (want_cb) {
      scope->Set(*OneName(op, "CellB@GRAD", false), std::move(cbg));
    }
    return "";
  }

  // layer_norm backward (classic adjoint over the flattened rows the
  // forward normalizes): with yhat = (x - mu)/sigma and G = dy*gamma,
  // dx = (G - mean(G) - yhat * mean(G*yhat)) / sigma;
  // dgamma = sum_rows(dy * yhat); dbeta = sum_rows(dy)
  std::string RunLayerNormGrad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* ygn = OneName(op, "Y@GRAD");
    if (xn == nullptr || ygn == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* yg = scope->Find(*ygn);
    if (x == nullptr || yg == nullptr) return "input not in scope";
    if (!IsF32(*x) || !IsF32(*yg) || x->dims != yg->dims) {
      return "bad input";
    }
    int64_t begin = IntAttr(op, "begin_norm_axis", 1);
    float eps = FloatAttr(op, "epsilon", 1e-5f);
    if (begin < 1 || begin >= static_cast<int64_t>(x->dims.size())) {
      return "bad begin_norm_axis";
    }
    int64_t rows = 1, inner = 1;
    for (int64_t d = 0; d < begin; ++d) rows *= x->dims[d];
    for (size_t d = begin; d < x->dims.size(); ++d) inner *= x->dims[d];
    const std::string* sn = OneName(op, "Scale");
    const HostTensor* sc = sn != nullptr ? scope->Find(*sn) : nullptr;
    if (sc != nullptr && NumElements(sc->dims) != inner) {
      return "bad scale";
    }
    const std::string* xgn = OneName(op, "X@GRAD", false);
    const std::string* sgn = OneName(op, "Scale@GRAD", false);
    const std::string* bgn = OneName(op, "Bias@GRAD", false);
    if (sgn != nullptr && sc == nullptr) return "Scale@GRAD w/o Scale";
    HostTensor xg, sg, bgt;
    float* xga = nullptr;
    float* sga = nullptr;
    float* bga = nullptr;
    if (xgn != nullptr) {
      xg = MakeF32(x->dims);
      xga = MutF32(&xg);
    }
    if (sgn != nullptr) {
      sg = MakeF32({inner});
      sga = MutF32(&sg);
      std::fill(sga, sga + inner, 0.0f);
    }
    if (bgn != nullptr) {
      bgt = MakeF32({inner});
      bga = MutF32(&bgt);
      std::fill(bga, bga + inner, 0.0f);
    }
    const float* xa = F32(*x);
    const float* ga = F32(*yg);
    std::vector<float> yhat(inner), gg(inner);
    for (int64_t r = 0; r < rows; ++r) {
      const float* src = xa + r * inner;
      const float* grow = ga + r * inner;
      float mean, inv;
      RowMeanInv(src, inner, eps, &mean, &inv);
      double mg = 0.0, mgy = 0.0;
      for (int64_t i = 0; i < inner; ++i) {
        yhat[i] = (src[i] - mean) * inv;
        float gscaled = grow[i] * (sc != nullptr ? F32(*sc)[i] : 1.0f);
        gg[i] = gscaled;
        mg += gscaled;
        mgy += static_cast<double>(gscaled) * yhat[i];
        if (sga != nullptr) sga[i] += grow[i] * yhat[i];
        if (bga != nullptr) bga[i] += grow[i];
      }
      mg /= inner;
      mgy /= inner;
      if (xga != nullptr) {
        float* dst = xga + r * inner;
        for (int64_t i = 0; i < inner; ++i) {
          dst[i] = (gg[i] - static_cast<float>(mg) -
                    yhat[i] * static_cast<float>(mgy)) * inv;
        }
      }
    }
    if (xgn != nullptr) scope->Set(*xgn, std::move(xg));
    if (sgn != nullptr) scope->Set(*sgn, std::move(sg));
    if (bgn != nullptr) scope->Set(*bgn, std::move(bgt));
    return "";
  }

  // attention backward (adjoint of RunSDPA's reference math, same
  // validity predicate incl. causal/window/key-mask/GQA): per row,
  // dV_j += p_j g, dp_j = g.v_j, ds = p*(dp - sum(p*dp)),
  // dQ += scale * ds K, dK_j += scale * ds_j q. Fully-masked rows
  // contributed 0 forward and contribute 0 here.
  std::string RunSDPAGrad(const OpDesc& op, Scope* scope) {
    const std::string* qn = OneName(op, "Q");
    const std::string* kn = OneName(op, "K");
    const std::string* vn = OneName(op, "V");
    const std::string* ogn = OneName(op, "Out@GRAD");
    if (qn == nullptr || kn == nullptr || vn == nullptr ||
        ogn == nullptr) {
      return "missing io";
    }
    const HostTensor* q = scope->Find(*qn);
    const HostTensor* k = scope->Find(*kn);
    const HostTensor* v = scope->Find(*vn);
    const HostTensor* og = scope->Find(*ogn);
    for (const HostTensor* tt : {q, k, v, og}) {
      if (tt == nullptr) return "input not in scope";
      if (!IsF32(*tt)) return "non-f32";
    }
    if (q->dims.size() != 4 || k->dims.size() != 4) {
      return "needs [B,H,T,d]";
    }
    if (!StrAttr(op, "seq_parallel_axis", "").empty()) {
      return "seq_parallel_axis needs the XLA path";
    }
    int64_t B = q->dims[0], H = q->dims[1], T = q->dims[2],
            d = q->dims[3];
    int64_t S = k->dims[2];
    int64_t g = IntAttr(op, "kv_group", 1);
    if (g < 1 || H % g != 0) return "bad kv_group";
    int64_t Hkv = H / g;
    if (k->dims[0] != B || k->dims[1] != Hkv || k->dims[3] != d) {
      return "K shape mismatch";
    }
    if (v->dims != k->dims || og->dims != q->dims) {
      return "shape mismatch";
    }
    bool causal = IntAttr(op, "causal", 0) != 0;
    int64_t window = IntAttr(op, "window", 0);
    if (window < 0) return "bad window";
    float scale = FloatAttr(op, "sm_scale", 0.0f);
    if (scale == 0.0f) scale = 1.0f / std::sqrt(static_cast<float>(d));
    const std::string* mn = OneName(op, "Mask");
    const HostTensor* mask = mn != nullptr ? scope->Find(*mn) : nullptr;
    if (mask != nullptr &&
        (mask->dims.size() != 2 || mask->dims[0] != B ||
         mask->dims[1] != S)) {
      return "only [B, S] key-validity masks in the C++ path";
    }
    const std::string* qgn = OneName(op, "Q@GRAD", false);
    const std::string* kgn = OneName(op, "K@GRAD", false);
    const std::string* vgn = OneName(op, "V@GRAD", false);
    HostTensor qg, kg, vg;
    float* qga = nullptr;
    float* kga = nullptr;
    float* vga = nullptr;
    if (qgn != nullptr) {
      qg = MakeF32(q->dims);
      qga = MutF32(&qg);
      std::fill(qga, qga + NumElements(q->dims), 0.0f);
    }
    if (kgn != nullptr) {
      kg = MakeF32(k->dims);
      kga = MutF32(&kg);
      std::fill(kga, kga + NumElements(k->dims), 0.0f);
    }
    if (vgn != nullptr) {
      vg = MakeF32(v->dims);
      vga = MutF32(&vg);
      std::fill(vga, vga + NumElements(v->dims), 0.0f);
    }
    const float* qa = F32(*q);
    const float* ka = F32(*k);
    const float* va = F32(*v);
    const float* ga = F32(*og);
    const float* ma = mask != nullptr ? F32(*mask) : nullptr;
    std::vector<float> p(S), dp(S);
    for (int64_t b = 0; b < B; ++b) {
      for (int64_t h = 0; h < H; ++h) {
        const float* kb = ka + (b * Hkv + h / g) * S * d;
        const float* vb = va + (b * Hkv + h / g) * S * d;
        float* kgb = kga != nullptr ? kga + (b * Hkv + h / g) * S * d
                                    : nullptr;
        float* vgb = vga != nullptr ? vga + (b * Hkv + h / g) * S * d
                                    : nullptr;
        for (int64_t t = 0; t < T; ++t) {
          const float* qr = qa + ((b * H + h) * T + t) * d;
          const float* grow = ga + ((b * H + h) * T + t) * d;
          // recompute the softmax row with the forward's predicate
          float mx = -1e30f;
          bool any_valid = false;
          const float* mrow = ma != nullptr ? ma + b * S : nullptr;
          for (int64_t j = 0; j < S; ++j) {
            if (SdpaValid(t, j, causal, window, mrow)) {
              any_valid = true;
              float dot = 0.0f;
              for (int64_t c = 0; c < d; ++c) {
                dot += qr[c] * kb[j * d + c];
              }
              p[j] = dot * scale;
              if (p[j] > mx) mx = p[j];
            } else {
              p[j] = -1e30f;
            }
          }
          if (!any_valid) continue;  // forward emitted 0, grads are 0
          float denom = 0.0f;
          for (int64_t j = 0; j < S; ++j) {
            p[j] = std::exp(p[j] - mx);
            denom += p[j];
          }
          if (denom <= 0.0f) denom = 1.0f;
          double pdp = 0.0;
          for (int64_t j = 0; j < S; ++j) {
            p[j] /= denom;
            float acc = 0.0f;
            for (int64_t c = 0; c < d; ++c) {
              acc += grow[c] * vb[j * d + c];
            }
            dp[j] = acc;
            pdp += static_cast<double>(p[j]) * acc;
            if (vgb != nullptr) {
              for (int64_t c = 0; c < d; ++c) {
                vgb[j * d + c] += p[j] * grow[c];
              }
            }
          }
          float* qgr = qga != nullptr
                           ? qga + ((b * H + h) * T + t) * d
                           : nullptr;
          for (int64_t j = 0; j < S; ++j) {
            float ds = p[j] * (dp[j] - static_cast<float>(pdp)) * scale;
            if (ds == 0.0f) continue;
            if (qgr != nullptr) {
              for (int64_t c = 0; c < d; ++c) {
                qgr[c] += ds * kb[j * d + c];
              }
            }
            if (kgb != nullptr) {
              for (int64_t c = 0; c < d; ++c) {
                kgb[j * d + c] += ds * qr[c];
              }
            }
          }
        }
      }
    }
    if (qgn != nullptr) scope->Set(*qgn, std::move(qg));
    if (kgn != nullptr) scope->Set(*kgn, std::move(kg));
    if (vgn != nullptr) scope->Set(*vgn, std::move(vg));
    return "";
  }

  // BPTT for dynamic_gru (adjoint of RunDynamicGru's recurrence);
  // padded steps pass dh through like the LSTM grad
  std::string RunDynamicGruGrad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "Input");
    const std::string* wn = OneName(op, "Weight");
    const std::string* hn = OneName(op, "Hidden");
    const std::string* hgn = OneName(op, "Hidden@GRAD");
    if (xn == nullptr || wn == nullptr || hn == nullptr) {
      return "missing io";
    }
    if (OneName(op, "H0") != nullptr) {
      return "H0 initial state not supported";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* w = scope->Find(*wn);
    const HostTensor* hid = scope->Find(*hn);
    const HostTensor* hg = hgn != nullptr ? scope->Find(*hgn) : nullptr;
    for (const HostTensor* tt : {x, w, hid}) {
      if (tt == nullptr) return "input not in scope";
      if (!IsF32(*tt)) return "non-f32 dtype";
    }
    if (hgn != nullptr && hg == nullptr) return "input not in scope";
    if (hg != nullptr && !IsF32(*hg)) return "non-f32 dtype";
    if (x->dims.size() != 3 || w->dims.size() != 2) return "bad ranks";
    int64_t b = x->dims[0], t = x->dims[1], d = w->dims[0];
    if (x->dims[2] != 3 * d || w->dims[1] != 3 * d) return "gate dims";
    if (hid->dims != std::vector<int64_t>({b, t, d}) ||
        (hg != nullptr && hg->dims != hid->dims)) {
      return "stored state shape";
    }
    bool reverse = IntAttr(op, "is_reverse", 0) != 0;
    bool ok1 = true, ok2 = true, ok3 = true, ok4 = true;
    std::string gname = StrAttr(op, "gate_activation", "sigmoid");
    std::string cname = StrAttr(op, "activation", "tanh");
    auto gate_act = ActFn(gname, &ok1);
    auto cand_act = ActFn(cname, &ok2);
    auto gate_der = ActDeriv(gname, &ok3);
    auto cand_der = ActDeriv(cname, &ok4);
    if (!ok1 || !ok2 || !ok3 || !ok4) return "unsupported activation";
    const float* bias = nullptr;
    const std::string* bn = OneName(op, "Bias");
    if (bn != nullptr) {
      const HostTensor* bt = scope->Find(*bn);
      if (bt == nullptr) return "Bias not in scope";
      if (!IsF32(*bt) || NumElements(bt->dims) < 3 * d) return "bad bias";
      bias = F32(*bt);
    }
    std::vector<int64_t> lens;
    std::string err = RowLengths(op, scope, b, t, &lens);
    if (!err.empty()) return err;

    const float* xa = F32(*x);
    const float* wa = F32(*w);
    const float* ha = F32(*hid);
    const float* hga = hg != nullptr ? F32(*hg) : nullptr;

    const std::string* xgn = OneName(op, "Input@GRAD", false);
    const std::string* wgn = OneName(op, "Weight@GRAD", false);
    const std::string* bgn = OneName(op, "Bias@GRAD", false);
    HostTensor xg, wg, bg;
    float* xga = nullptr;
    float* wga = nullptr;
    float* bga = nullptr;
    if (xgn != nullptr) {
      xg = MakeF32(x->dims);
      xga = MutF32(&xg);
      std::fill(xga, xga + NumElements(x->dims), 0.0f);
    }
    if (wgn != nullptr) {
      wg = MakeF32(w->dims);
      wga = MutF32(&wg);
      std::fill(wga, wga + NumElements(w->dims), 0.0f);
    }
    if (bgn != nullptr) {
      bg = MakeF32({1, 3 * d});
      bga = MutF32(&bg);
      std::fill(bga, bga + 3 * d, 0.0f);
      if (bias == nullptr) return "Bias@GRAD without Bias";
    }

    std::vector<float> dh(b * d, 0.0f);
    std::vector<float> g2(2 * d), rh(d), cval(d), uval(d),
        rval(d), dg(2 * d), dcpre(d), drh(d);
    for (int64_t step = t - 1; step >= 0; --step) {
      int64_t s = reverse ? t - 1 - step : step;
      int64_t sp = reverse ? t - step : step - 1;
      for (int64_t i = 0; i < b; ++i) {
        bool valid = s < lens[i];
        float* dhr = dh.data() + i * d;
        const float* hg_row = hga != nullptr ? hga + (i * t + s) * d
                                             : nullptr;
        if (!valid) {
          if (hg_row != nullptr) {
            for (int64_t k = 0; k < d; ++k) dhr[k] += hg_row[k];
          }
          continue;
        }
        bool has_prev = step > 0;
        const float* hprev = has_prev ? ha + (i * t + sp) * d : nullptr;
        const float* xrow = xa + (i * t + s) * 3 * d;
        // recompute forward intermediates
        for (int64_t j = 0; j < 2 * d; ++j) {
          float acc = xrow[j] + (bias != nullptr ? bias[j] : 0.0f);
          if (has_prev) {
            for (int64_t k = 0; k < d; ++k) {
              acc += hprev[k] * wa[k * 3 * d + j];
            }
          }
          g2[j] = acc;
        }
        for (int64_t k = 0; k < d; ++k) {
          uval[k] = gate_act(g2[k]);
          rval[k] = gate_act(g2[d + k]);
          rh[k] = rval[k] * (has_prev ? hprev[k] : 0.0f);
        }
        for (int64_t k = 0; k < d; ++k) {
          float acc = xrow[2 * d + k] +
                      (bias != nullptr ? bias[2 * d + k] : 0.0f);
          for (int64_t m2 = 0; m2 < d; ++m2) {
            acc += rh[m2] * wa[m2 * 3 * d + 2 * d + k];
          }
          cval[k] = cand_act(acc);
        }
        // backward
        for (int64_t k = 0; k < d; ++k) {
          float hp = has_prev ? hprev[k] : 0.0f;
          float dh_k = dhr[k] + (hg_row != nullptr ? hg_row[k] : 0.0f);
          float du = dh_k * (hp - cval[k]);
          float dc = dh_k * (1.0f - uval[k]);
          dhr[k] = dh_k * uval[k];  // carry: u * dh
          dcpre[k] = dc * cand_der(cval[k]);
          dg[k] = du * gate_der(uval[k]);
        }
        // through the candidate matmul: drh, dWc, dbc, dxc
        for (int64_t m2 = 0; m2 < d; ++m2) {
          float acc = 0.0f;
          for (int64_t k = 0; k < d; ++k) {
            acc += dcpre[k] * wa[m2 * 3 * d + 2 * d + k];
            if (wga != nullptr) {
              wga[m2 * 3 * d + 2 * d + k] += rh[m2] * dcpre[k];
            }
          }
          drh[m2] = acc;
        }
        for (int64_t k = 0; k < d; ++k) {
          if (xga != nullptr) {
            xga[(i * t + s) * 3 * d + 2 * d + k] += dcpre[k];
          }
          if (bga != nullptr) bga[2 * d + k] += dcpre[k];
          float hp = has_prev ? hprev[k] : 0.0f;
          float dr = drh[k] * hp;
          dhr[k] += drh[k] * rval[k];
          dg[d + k] = dr * gate_der(rval[k]);
        }
        // through the gate matmul: dW[:, :2d], db, dx, dh_prev
        if (wga != nullptr && has_prev) {
          for (int64_t k = 0; k < d; ++k) {
            for (int64_t j = 0; j < 2 * d; ++j) {
              wga[k * 3 * d + j] += hprev[k] * dg[j];
            }
          }
        }
        for (int64_t j = 0; j < 2 * d; ++j) {
          if (xga != nullptr) xga[(i * t + s) * 3 * d + j] += dg[j];
          if (bga != nullptr) bga[j] += dg[j];
        }
        if (has_prev) {
          for (int64_t k = 0; k < d; ++k) {
            float acc = 0.0f;
            for (int64_t j = 0; j < 2 * d; ++j) {
              acc += wa[k * 3 * d + j] * dg[j];
            }
            dhr[k] += acc;
          }
        }
      }
    }
    if (xgn != nullptr) scope->Set(*xgn, std::move(xg));
    if (wgn != nullptr) scope->Set(*wgn, std::move(wg));
    if (bgn != nullptr) scope->Set(*bgn, std::move(bg));
    return "";
  }

  // BPTT for dynamic_lstm (adjoint of RunDynamicLstm's recurrence):
  // gates recomputed from Input/Weight/Bias + the stored Hidden/Cell
  // sequences (h_prev/c_prev are the PREVIOUS ITERATION index's stored
  // rows — invalid padded steps store the carried state, so the lookup
  // is uniform); padded steps pass dh/dc straight through, exactly the
  // masked-scan vjp of the XLA lowering. Peepholes included.
  std::string RunDynamicLstmGrad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "Input");
    const std::string* wn = OneName(op, "Weight");
    const std::string* hn = OneName(op, "Hidden");
    const std::string* cn = OneName(op, "Cell");
    const std::string* hgn = OneName(op, "Hidden@GRAD");
    if (xn == nullptr || wn == nullptr || hn == nullptr ||
        cn == nullptr) {
      return "missing io";
    }
    if (OneName(op, "H0") != nullptr || OneName(op, "C0") != nullptr) {
      return "H0/C0 initial state not supported";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* w = scope->Find(*wn);
    const HostTensor* hid = scope->Find(*hn);
    const HostTensor* cel = scope->Find(*cn);
    // Hidden@GRAD is optional like Cell@GRAD (a loss can touch only
    // the cell output); missing means zero incoming rows
    const HostTensor* hg = hgn != nullptr ? scope->Find(*hgn) : nullptr;
    for (const HostTensor* tt : {x, w, hid, cel}) {
      if (tt == nullptr) return "input not in scope";
      if (!IsF32(*tt)) return "non-f32 dtype";
    }
    if (hgn != nullptr && hg == nullptr) return "input not in scope";
    if (hg != nullptr && !IsF32(*hg)) return "non-f32 dtype";
    if (x->dims.size() != 3 || w->dims.size() != 2) return "bad ranks";
    int64_t b = x->dims[0], t = x->dims[1], d = w->dims[0];
    if (x->dims[2] != 4 * d || w->dims[1] != 4 * d) return "gate dims";
    if (hid->dims != std::vector<int64_t>({b, t, d}) ||
        cel->dims != hid->dims ||
        (hg != nullptr && hg->dims != hid->dims)) {
      return "stored state shape";
    }
    bool peephole = IntAttr(op, "use_peepholes", 1) != 0;
    bool reverse = IntAttr(op, "is_reverse", 0) != 0;
    bool ok1 = true, ok2 = true, ok3 = true, ok4 = true, ok5 = true,
         ok6 = true;
    std::string gname = StrAttr(op, "gate_activation", "sigmoid");
    std::string cname = StrAttr(op, "cell_activation", "tanh");
    std::string dname = StrAttr(op, "candidate_activation", "tanh");
    auto gate_act = ActFn(gname, &ok1);
    auto cell_act = ActFn(cname, &ok2);
    auto cand_act = ActFn(dname, &ok3);
    auto gate_der = ActDeriv(gname, &ok4);
    auto cell_der = ActDeriv(cname, &ok5);
    auto cand_der = ActDeriv(dname, &ok6);
    if (!ok1 || !ok2 || !ok3 || !ok4 || !ok5 || !ok6) {
      return "unsupported activation";
    }
    const float* bias = nullptr;
    const std::string* bn = OneName(op, "Bias");
    if (bn != nullptr) {
      const HostTensor* bt = scope->Find(*bn);
      if (bt == nullptr) return "Bias not in scope";
      if (!IsF32(*bt)) return "non-f32 bias";
      int64_t need = peephole ? 7 * d : 4 * d;
      if (NumElements(bt->dims) < need) return "bias too small";
      bias = F32(*bt);
    }
    const HostTensor* cg_t = nullptr;
    const std::string* cgn = OneName(op, "Cell@GRAD");
    if (cgn != nullptr) {
      cg_t = scope->Find(*cgn);
      if (cg_t != nullptr &&
          (!IsF32(*cg_t) || cg_t->dims != hid->dims)) {
        return "cell grad shape";
      }
    }
    std::vector<int64_t> lens;
    std::string err = RowLengths(op, scope, b, t, &lens);
    if (!err.empty()) return err;

    const float* xa = F32(*x);
    const float* wa = F32(*w);
    const float* ha = F32(*hid);
    const float* ca = F32(*cel);
    const float* hga = hg != nullptr ? F32(*hg) : nullptr;
    const float* cga = cg_t != nullptr ? F32(*cg_t) : nullptr;

    const std::string* xgn = OneName(op, "Input@GRAD", false);
    const std::string* wgn = OneName(op, "Weight@GRAD", false);
    const std::string* bgn = OneName(op, "Bias@GRAD", false);
    HostTensor xg, wg, bg;
    float* xga = nullptr;
    float* wga = nullptr;
    float* bga = nullptr;
    if (xgn != nullptr) {
      xg = MakeF32(x->dims);
      xga = MutF32(&xg);
      std::fill(xga, xga + NumElements(x->dims), 0.0f);
    }
    if (wgn != nullptr) {
      wg = MakeF32(w->dims);
      wga = MutF32(&wg);
      std::fill(wga, wga + NumElements(w->dims), 0.0f);
    }
    if (bgn != nullptr) {
      int64_t blen = peephole ? 7 * d : 4 * d;
      bg = MakeF32({1, blen});
      bga = MutF32(&bg);
      std::fill(bga, bga + blen, 0.0f);
      if (bias == nullptr) return "Bias@GRAD without Bias";
    }

    // iterate the forward's iteration order BACKWARD
    std::vector<float> dh(b * d, 0.0f), dc(b * d, 0.0f);
    std::vector<float> dgates(4 * d), gates(4 * d);
    for (int64_t step = t - 1; step >= 0; --step) {
      int64_t s = reverse ? t - 1 - step : step;           // data index
      int64_t sp = reverse ? t - step : step - 1;          // prev iter's
      for (int64_t i = 0; i < b; ++i) {
        bool valid = s < lens[i];
        float* dhr = dh.data() + i * d;
        float* dcr = dc.data() + i * d;
        const float* hg_row = hga != nullptr ? hga + (i * t + s) * d
                                             : nullptr;
        const float* cg_row = cga != nullptr ? cga + (i * t + s) * d
                                             : nullptr;
        if (!valid) {
          // padded step: output was the carried state, so its grad
          // joins the carried adjoints unchanged
          for (int64_t k = 0; k < d; ++k) {
            if (hg_row != nullptr) dhr[k] += hg_row[k];
            if (cg_row != nullptr) dcr[k] += cg_row[k];
          }
          continue;
        }
        bool has_prev = step > 0;
        const float* hprev = has_prev ? ha + (i * t + sp) * d : nullptr;
        const float* cprev = has_prev ? ca + (i * t + sp) * d : nullptr;
        const float* xrow = xa + (i * t + s) * 4 * d;
        const float* crow = ca + (i * t + s) * d;
        // recompute pre-activation gates exactly like the forward
        for (int64_t g = 0; g < 4 * d; ++g) {
          float acc = xrow[g] + (bias != nullptr ? bias[g] : 0.0f);
          if (has_prev) {
            for (int64_t k = 0; k < d; ++k) {
              acc += hprev[k] * wa[k * 4 * d + g];
            }
          }
          gates[g] = acc;
        }
        for (int64_t k = 0; k < d; ++k) {
          float cpv = has_prev ? cprev[k] : 0.0f;
          float gi = gates[0 * d + k];
          float gf = gates[1 * d + k];
          float gc = gates[2 * d + k];
          float go = gates[3 * d + k];
          if (peephole && bias != nullptr) {
            gi += cpv * bias[4 * d + k];
            gf += cpv * bias[5 * d + k];
          }
          float iv = gate_act(gi);
          float fv = gate_act(gf);
          float gv = cand_act(gc);
          float cv = crow[k];
          if (peephole && bias != nullptr) go += cv * bias[6 * d + k];
          float ov = gate_act(go);
          float tc = cell_act(cv);

          float dh_k = dhr[k] + (hg_row != nullptr ? hg_row[k] : 0.0f);
          float dc_k = dcr[k] + (cg_row != nullptr ? cg_row[k] : 0.0f);
          float dov = dh_k * tc;
          float dgo = dov * gate_der(ov);
          dc_k += dh_k * ov * cell_der(tc);
          if (peephole && bias != nullptr) {
            dc_k += dgo * bias[6 * d + k];
            if (bga != nullptr) bga[6 * d + k] += dgo * cv;
          }
          float div = dc_k * gv;
          float dgv = dc_k * iv;
          float dfv = dc_k * cpv;
          float dgi = div * gate_der(iv);
          float dgf = dfv * gate_der(fv);
          float dgc = dgv * cand_der(gv);
          // carried adjoints for the previous iteration step
          float dc_prev = dc_k * fv;
          if (peephole && bias != nullptr) {
            dc_prev += dgi * bias[4 * d + k] + dgf * bias[5 * d + k];
            if (bga != nullptr) {
              bga[4 * d + k] += dgi * cpv;
              bga[5 * d + k] += dgf * cpv;
            }
          }
          dcr[k] = dc_prev;
          dgates[0 * d + k] = dgi;
          dgates[1 * d + k] = dgf;
          dgates[2 * d + k] = dgc;
          dgates[3 * d + k] = dgo;
        }
        // dInput, dBias, dW, and dh for the previous iteration step
        if (xga != nullptr) {
          float* xgr = xga + (i * t + s) * 4 * d;
          for (int64_t g = 0; g < 4 * d; ++g) xgr[g] += dgates[g];
        }
        if (bga != nullptr) {
          for (int64_t g = 0; g < 4 * d; ++g) bga[g] += dgates[g];
        }
        if (wga != nullptr && has_prev) {
          for (int64_t k = 0; k < d; ++k) {
            for (int64_t g = 0; g < 4 * d; ++g) {
              wga[k * 4 * d + g] += hprev[k] * dgates[g];
            }
          }
        }
        for (int64_t k = 0; k < d; ++k) {
          float acc = 0.0f;
          for (int64_t g = 0; g < 4 * d; ++g) {
            acc += wa[k * 4 * d + g] * dgates[g];
          }
          dhr[k] = has_prev ? acc : 0.0f;
        }
      }
    }
    if (xgn != nullptr) scope->Set(*xgn, std::move(xg));
    if (wgn != nullptr) scope->Set(*wgn, std::move(wg));
    if (bgn != nullptr) scope->Set(*bgn, std::move(bg));
    return "";
  }

  std::string RunDynamicLstm(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "Input");
    const std::string* wn = OneName(op, "Weight");
    const std::string* hn = OneName(op, "Hidden", false);
    const std::string* cn = OneName(op, "Cell", false);
    if (xn == nullptr || wn == nullptr || hn == nullptr) return "missing io";
    if (OneName(op, "H0") != nullptr || OneName(op, "C0") != nullptr) {
      // zero initial state only; error rather than silently diverging
      // from the XLA lowering's H0/C0 handling
      return "H0/C0 initial state not supported";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* w = scope->Find(*wn);
    if (x == nullptr || w == nullptr) return "input not in scope";
    if (!IsF32(*x) || !IsF32(*w)) return "non-f32 dtype";
    if (x->dims.size() != 3 || w->dims.size() != 2) return "bad ranks";
    int64_t b = x->dims[0], t = x->dims[1], d = w->dims[0];
    if (x->dims[2] != 4 * d || w->dims[1] != 4 * d) return "gate dims";
    bool peephole = IntAttr(op, "use_peepholes", 1) != 0;
    bool reverse = IntAttr(op, "is_reverse", 0) != 0;
    bool ok1 = true, ok2 = true, ok3 = true;
    auto gate_act = ActFn(StrAttr(op, "gate_activation", "sigmoid"), &ok1);
    auto cell_act = ActFn(StrAttr(op, "cell_activation", "tanh"), &ok2);
    auto cand_act = ActFn(StrAttr(op, "candidate_activation", "tanh"), &ok3);
    if (!ok1 || !ok2 || !ok3) return "unsupported activation";

    const float* bias = nullptr;
    const std::string* bn = OneName(op, "Bias");
    if (bn != nullptr) {
      const HostTensor* bt = scope->Find(*bn);
      if (bt == nullptr) return "Bias not in scope";
      if (!IsF32(*bt)) return "non-f32 bias";
      int64_t need = peephole ? 7 * d : 4 * d;
      if (NumElements(bt->dims) < need) return "bias too small";
      bias = F32(*bt);
    }
    std::vector<int64_t> lens;
    std::string err = RowLengths(op, scope, b, t, &lens);
    if (!err.empty()) return err;

    HostTensor hidden = MakeF32({b, t, d});
    HostTensor cell = MakeF32({b, t, d});
    const float* xa = F32(*x);
    const float* wa = F32(*w);
    float* ha = MutF32(&hidden);
    float* ca = MutF32(&cell);
    std::vector<float> h(b * d, 0.0f), c(b * d, 0.0f), gates(4 * d);
    for (int64_t step = 0; step < t; ++step) {
      int64_t s = reverse ? t - 1 - step : step;
      for (int64_t i = 0; i < b; ++i) {
        // padded-step semantics: beyond the row's length, carry state
        // through and emit it unchanged (matches the XLA mask)
        bool valid = s < lens[i];
        const float* xrow = xa + (i * t + s) * 4 * d;
        float* hrow = h.data() + i * d;
        float* crow = c.data() + i * d;
        if (valid) {
          for (int64_t g = 0; g < 4 * d; ++g) {
            float acc = xrow[g] + (bias != nullptr ? bias[g] : 0.0f);
            for (int64_t k = 0; k < d; ++k) {
              acc += hrow[k] * wa[k * 4 * d + g];
            }
            gates[g] = acc;
          }
          for (int64_t k = 0; k < d; ++k) {
            float gi = gates[0 * d + k];
            float gf = gates[1 * d + k];
            float gc = gates[2 * d + k];
            float go = gates[3 * d + k];
            if (peephole && bias != nullptr) {
              gi += crow[k] * bias[4 * d + k];
              gf += crow[k] * bias[5 * d + k];
            }
            float iv = gate_act(gi);
            float fv = gate_act(gf);
            float cv = fv * crow[k] + iv * cand_act(gc);
            if (peephole && bias != nullptr) go += cv * bias[6 * d + k];
            float ov = gate_act(go);
            crow[k] = cv;
            hrow[k] = ov * cell_act(cv);
          }
        }
        for (int64_t k = 0; k < d; ++k) {
          ha[(i * t + s) * d + k] = hrow[k];
          ca[(i * t + s) * d + k] = crow[k];
        }
      }
    }
    scope->Set(*hn, std::move(hidden));
    if (cn != nullptr) scope->Set(*cn, std::move(cell));
    return "";
  }

  // ---- training subset --------------------------------------------------
  // Backward + update kernels for the serialized MLP training program
  // (mul/elementwise_add/relu/softmax_with_cross_entropy/mean + sgd),
  // matching the slot layout backward.py emits: grad ops read the forward
  // inputs/outputs plus Out@GRAD and write <name>@GRAD.

  std::string RunFillConstant(const OpDesc& op, Scope* scope) {
    const std::string* on = OneName(op, "Out", false);
    if (on == nullptr) return "missing io";
    if (StrAttr(op, "dtype", "float32") != "float32") return "non-f32 fill";
    HostTensor out = MakeF32(IntsAttr(op, "shape", {1}));
    float v = FloatAttr(op, "value", 0.0f);
    float* oa = MutF32(&out);
    std::fill(oa, oa + NumElements(out.dims), v);
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunUniformRandom(const OpDesc& op, Scope* scope) {
    const std::string* on = OneName(op, "Out", false);
    if (on == nullptr) return "missing io";
    HostTensor out = MakeF32(IntsAttr(op, "shape", {1}));
    float lo = FloatAttr(op, "min", -1.0f);
    float hi = FloatAttr(op, "max", 1.0f);
    uint64_t seed = static_cast<uint64_t>(IntAttr(op, "seed", 0));
    if (seed == 0) {
      // seed 0 = "op picks": mix the output name so same-shape params
      // do NOT share one stream (two equal fc layers must differ)
      seed = std::hash<std::string>{}(*on) | 1;
    }
    XorShiftRng rng(seed);
    float* oa = MutF32(&out);
    int64_t n = NumElements(out.dims);
    for (int64_t i = 0; i < n; ++i) {
      oa[i] = lo + rng.uniform() * (hi - lo);
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunMeanGrad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* ogn = OneName(op, "Out@GRAD");
    const std::string* gn = OneName(op, "X@GRAD", false);
    if (xn == nullptr || ogn == nullptr || gn == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* og = scope->Find(*ogn);
    if (x == nullptr || og == nullptr) return "input not in scope";
    if (!IsF32(*x) || !IsF32(*og)) return "non-f32 dtype";
    int64_t n = NumElements(x->dims);
    if (n == 0) return "empty input";
    float g = F32(*og)[0] / static_cast<float>(n);
    HostTensor grad = MakeF32(x->dims);
    float* ga = MutF32(&grad);
    std::fill(ga, ga + n, g);
    scope->Set(*gn, std::move(grad));
    return "";
  }

  std::string RunReluGrad(const OpDesc& op, Scope* scope) {
    // select form, not multiply: inactive units mask a NaN/Inf upstream
    // gradient to exact 0, matching jnp.where in the XLA vjp
    return RunActGradMaskFromOut(
        op, scope, [](float o) { return o > 0.0f; });
  }

  template <typename Pred>
  std::string RunActGradMaskFromOut(const OpDesc& op, Scope* scope,
                                    Pred keep) {
    const std::string* on = OneName(op, "Out");
    const std::string* ogn = OneName(op, "Out@GRAD");
    const std::string* gn = OneName(op, "X@GRAD", false);
    if (on == nullptr || ogn == nullptr || gn == nullptr) {
      return "missing io";
    }
    const HostTensor* out = scope->Find(*on);
    const HostTensor* og = scope->Find(*ogn);
    if (out == nullptr || og == nullptr) return "input not in scope";
    if (!IsF32(*out) || !IsF32(*og)) return "non-f32 dtype";
    int64_t n = NumElements(out->dims);
    if (n != NumElements(og->dims)) return "shape mismatch";
    HostTensor grad = MakeF32(out->dims);
    const float* oa = F32(*out);
    const float* ga = F32(*og);
    float* ra = MutF32(&grad);
    for (int64_t i = 0; i < n; ++i) ra[i] = keep(oa[i]) ? ga[i] : 0.0f;
    scope->Set(*gn, std::move(grad));
    return "";
  }

  std::string RunSCEGrad(const OpDesc& op, Scope* scope) {
    const std::string* sn = OneName(op, "Softmax");
    const std::string* labn = OneName(op, "Label");
    const std::string* ogn = OneName(op, "Loss@GRAD");
    const std::string* gn = OneName(op, "Logits@GRAD", false);
    if (sn == nullptr || labn == nullptr || ogn == nullptr ||
        gn == nullptr) {
      return "missing io";
    }
    const HostTensor* soft = scope->Find(*sn);
    const HostTensor* label = scope->Find(*labn);
    const HostTensor* og = scope->Find(*ogn);
    if (soft == nullptr || label == nullptr || og == nullptr) {
      return "input not in scope";
    }
    if (!IsF32(*soft) || soft->dims.size() != 2) return "bad softmax";
    int64_t n = soft->dims[0], c = soft->dims[1];
    if (NumElements(og->dims) < n) return "loss grad too small";
    HostTensor grad = MakeF32(soft->dims);
    const float* sa = F32(*soft);
    const float* ga = F32(*og);
    float* ra = MutF32(&grad);
    for (int64_t i = 0; i < n; ++i) {
      int64_t gold;
      if (label->dtype == "int64") {
        gold = reinterpret_cast<const int64_t*>(label->data.data())[i];
      } else if (label->dtype == "int32") {
        gold = reinterpret_cast<const int32_t*>(label->data.data())[i];
      } else {
        return "label dtype";
      }
      if (gold < 0 || gold >= c) return "label out of range";
      for (int64_t j = 0; j < c; ++j) {
        float d = sa[i * c + j] - (j == gold ? 1.0f : 0.0f);
        ra[i * c + j] = d * ga[i];
      }
    }
    scope->Set(*gn, std::move(grad));
    return "";
  }


  // sub/mul/div backward with the same broadcast mapping the forward
  // uses (y index = (i / inner) %% ny); dY reduces over the broadcast.
  // max/min grads stay unimplemented (tie semantics differ by backend)
  // and refuse explicitly through the unsupported-op path.
  std::string RunEwGrad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* yn = OneName(op, "Y");
    const std::string* ogn = OneName(op, "Out@GRAD");
    if (xn == nullptr || yn == nullptr || ogn == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* y = scope->Find(*yn);
    const HostTensor* og = scope->Find(*ogn);
    if (x == nullptr || y == nullptr || og == nullptr) {
      return "input not in scope";
    }
    if (!IsF32(*x) || !IsF32(*y) || !IsF32(*og)) return "non-f32 dtype";
    int64_t n = NumElements(og->dims);
    if (NumElements(x->dims) != n) return "shape mismatch";
    int64_t ny = NumElements(y->dims);
    int64_t inner = 1;
    std::string berr = ResolveBroadcast(op, x->dims, y->dims, &inner);
    if (!berr.empty()) return berr;
    int kind = op.type == "elementwise_sub_grad"
                   ? 0
                   : (op.type == "elementwise_mul_grad" ? 1 : 2);
    const float* xa = F32(*x);
    const float* ya = F32(*y);
    const float* ga = F32(*og);
    const std::string* xgn = OneName(op, "X@GRAD", false);
    if (xgn != nullptr) {
      HostTensor xg = MakeF32(x->dims);
      float* ra = MutF32(&xg);
      for (int64_t i = 0; i < n; ++i) {
        float yv = ya[ny == n ? i : (i / inner) % ny];
        float g = ga[i];
        ra[i] = kind == 0 ? g : (kind == 1 ? g * yv : g / yv);
      }
      scope->Set(*xgn, std::move(xg));
    }
    const std::string* ygn = OneName(op, "Y@GRAD", false);
    if (ygn != nullptr) {
      HostTensor yg = MakeF32(y->dims);
      float* ra = MutF32(&yg);
      std::fill(ra, ra + ny, 0.0f);
      for (int64_t i = 0; i < n; ++i) {
        int64_t yi = ny == n ? i : (i / inner) % ny;
        float yv = ya[yi];
        float g = ga[i];
        float contrib;
        if (kind == 0) {
          contrib = -g;
        } else if (kind == 1) {
          contrib = g * xa[i];
        } else {
          contrib = -g * xa[i] / (yv * yv);
        }
        ra[yi] += contrib;
      }
      scope->Set(*ygn, std::move(yg));
    }
    return "";
  }

  std::string RunAddGrad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* yn = OneName(op, "Y");
    const std::string* ogn = OneName(op, "Out@GRAD");
    if (xn == nullptr || yn == nullptr || ogn == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* y = scope->Find(*yn);
    const HostTensor* og = scope->Find(*ogn);
    if (x == nullptr || y == nullptr || og == nullptr) {
      return "input not in scope";
    }
    if (!IsF32(*y) || !IsF32(*og)) return "non-f32 dtype";
    int64_t n = NumElements(og->dims);
    const std::string* xgn = OneName(op, "X@GRAD", false);
    if (xgn != nullptr) {  // dL/dX = dL/dOut
      HostTensor xg = MakeF32(og->dims);
      std::copy(F32(*og), F32(*og) + n, MutF32(&xg));
      scope->Set(*xgn, std::move(xg));
    }
    const std::string* ygn = OneName(op, "Y@GRAD", false);
    if (ygn != nullptr) {
      // reduce dOut onto y with the SAME index mapping the forward
      // broadcast used: y element of out[i] is (i / inner) % ny
      int64_t yn_elems = NumElements(y->dims);
      int64_t inner = 1;
      std::string berr = ResolveBroadcast(op, x->dims, y->dims, &inner);
      if (!berr.empty()) return berr;
      HostTensor yg = MakeF32(y->dims);
      float* ya = MutF32(&yg);
      std::fill(ya, ya + yn_elems, 0.0f);
      const float* ga = F32(*og);
      for (int64_t i = 0; i < n; ++i) {
        ya[(i / inner) % yn_elems] += ga[i];
      }
      scope->Set(*ygn, std::move(yg));
    }
    return "";
  }

  std::string RunMulGrad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* yn = OneName(op, "Y");
    const std::string* ogn = OneName(op, "Out@GRAD");
    if (xn == nullptr || yn == nullptr || ogn == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* y = scope->Find(*yn);
    const HostTensor* og = scope->Find(*ogn);
    if (x == nullptr || y == nullptr || og == nullptr) {
      return "input not in scope";
    }
    if (!IsF32(*x) || !IsF32(*y) || !IsF32(*og)) return "non-f32 dtype";
    int64_t xcol = IntAttr(op, "x_num_col_dims", 1);
    int64_t rows = 1, k = 1;
    for (size_t d = 0; d < x->dims.size(); ++d) {
      (static_cast<int64_t>(d) < xcol ? rows : k) *= x->dims[d];
    }
    int64_t k2 = y->dims.empty() ? 1 : y->dims[0];
    int64_t cols = NumElements(y->dims) / (k2 == 0 ? 1 : k2);
    if (k != k2 || NumElements(og->dims) != rows * cols) {
      return "shape mismatch";
    }
    const float* xa = F32(*x);
    const float* ya = F32(*y);
    const float* ga = F32(*og);
    const std::string* xgn = OneName(op, "X@GRAD", false);
    if (xgn != nullptr) {  // dX = dOut . Y^T
      HostTensor xg = MakeF32(x->dims);
      float* ra = MutF32(&xg);
      for (int64_t i = 0; i < rows; ++i) {
        for (int64_t t = 0; t < k; ++t) {
          float acc = 0.0f;
          for (int64_t j = 0; j < cols; ++j) {
            acc += ga[i * cols + j] * ya[t * cols + j];
          }
          ra[i * k + t] = acc;
        }
      }
      scope->Set(*xgn, std::move(xg));
    }
    const std::string* ygn = OneName(op, "Y@GRAD", false);
    if (ygn != nullptr) {  // dY = X^T . dOut
      HostTensor yg = MakeF32(y->dims);
      float* ra = MutF32(&yg);
      for (int64_t t = 0; t < k; ++t) {
        for (int64_t j = 0; j < cols; ++j) {
          float acc = 0.0f;
          for (int64_t i = 0; i < rows; ++i) {
            acc += xa[i * k + t] * ga[i * cols + j];
          }
          ra[t * cols + j] = acc;
        }
      }
      scope->Set(*ygn, std::move(yg));
    }
    return "";
  }

  std::string RunSgd(const OpDesc& op, Scope* scope) {
    const std::string* pn = OneName(op, "Param");
    const std::string* gn = OneName(op, "Grad");
    const std::string* lrn = OneName(op, "LearningRate");
    const std::string* on = OneName(op, "ParamOut", false);
    if (pn == nullptr || gn == nullptr || lrn == nullptr || on == nullptr) {
      return "missing io";
    }
    const HostTensor* p = scope->Find(*pn);
    const HostTensor* g = scope->Find(*gn);
    const HostTensor* lr = scope->Find(*lrn);
    if (p == nullptr || g == nullptr || lr == nullptr) {
      return "input not in scope";
    }
    if (!IsF32(*p) || !IsF32(*g) || !IsF32(*lr)) return "non-f32 dtype";
    int64_t n = NumElements(p->dims);
    if (n != NumElements(g->dims)) return "shape mismatch";
    if (NumElements(lr->dims) < 1) return "empty scalar input";
    float rate = F32(*lr)[0];
    HostTensor out = MakeF32(p->dims);
    const float* pa = F32(*p);
    const float* ga = F32(*g);
    float* oa = MutF32(&out);
    for (int64_t i = 0; i < n; ++i) oa[i] = pa[i] - rate * ga[i];
    scope->Set(*on, std::move(out));
    return "";
  }


  // ops/optimizer_ops.py _lower_adam: bias-corrected lr, beta pows
  // advanced by separate scale ops the optimizer appends
  std::string RunAdam(const OpDesc& op, Scope* scope) {
    const std::string* pn = OneName(op, "Param");
    const std::string* gn = OneName(op, "Grad");
    const std::string* lrn = OneName(op, "LearningRate");
    const std::string* m1n = OneName(op, "Moment1");
    const std::string* m2n = OneName(op, "Moment2");
    const std::string* b1n = OneName(op, "Beta1Pow");
    const std::string* b2n = OneName(op, "Beta2Pow");
    const std::string* pon = OneName(op, "ParamOut", false);
    const std::string* m1on = OneName(op, "Moment1Out", false);
    const std::string* m2on = OneName(op, "Moment2Out", false);
    if (pn == nullptr || gn == nullptr || lrn == nullptr ||
        m1n == nullptr || m2n == nullptr || b1n == nullptr ||
        b2n == nullptr || pon == nullptr || m1on == nullptr ||
        m2on == nullptr) {
      return "missing io";
    }
    const HostTensor* p = scope->Find(*pn);
    const HostTensor* g = scope->Find(*gn);
    const HostTensor* lr = scope->Find(*lrn);
    const HostTensor* m1 = scope->Find(*m1n);
    const HostTensor* m2 = scope->Find(*m2n);
    const HostTensor* b1p = scope->Find(*b1n);
    const HostTensor* b2p = scope->Find(*b2n);
    for (const HostTensor* t : {p, g, lr, m1, m2, b1p, b2p}) {
      if (t == nullptr) return "input not in scope";
      if (!IsF32(*t)) return "non-f32 dtype";
    }
    int64_t n = NumElements(p->dims);
    if (NumElements(g->dims) != n || NumElements(m1->dims) != n ||
        NumElements(m2->dims) != n) {
      return "shape mismatch";
    }
    if (NumElements(lr->dims) < 1 || NumElements(b1p->dims) < 1 ||
        NumElements(b2p->dims) < 1) {
      return "empty scalar input";
    }
    float beta1 = FloatAttr(op, "beta1", 0.9f);
    float beta2 = FloatAttr(op, "beta2", 0.999f);
    float eps = FloatAttr(op, "epsilon", 1e-8f);
    float rate = F32(*lr)[0];
    float b1pow = F32(*b1p)[0];
    float b2pow = F32(*b2p)[0];
    float lr_t = rate * std::sqrt(1.0f - b2pow) / (1.0f - b1pow);
    HostTensor po = MakeF32(p->dims);
    HostTensor m1o = MakeF32(p->dims);
    HostTensor m2o = MakeF32(p->dims);
    const float* pa = F32(*p);
    const float* ga = F32(*g);
    const float* m1a = F32(*m1);
    const float* m2a = F32(*m2);
    float* poa = MutF32(&po);
    float* m1oa = MutF32(&m1o);
    float* m2oa = MutF32(&m2o);
    for (int64_t i = 0; i < n; ++i) {
      float nm1 = beta1 * m1a[i] + (1.0f - beta1) * ga[i];
      float nm2 = beta2 * m2a[i] + (1.0f - beta2) * ga[i] * ga[i];
      m1oa[i] = nm1;
      m2oa[i] = nm2;
      poa[i] = pa[i] - lr_t * nm1 / (std::sqrt(nm2) + eps);
    }
    scope->Set(*pon, std::move(po));
    scope->Set(*m1on, std::move(m1o));
    scope->Set(*m2on, std::move(m2o));
    return "";
  }

  // ops/optimizer_ops.py _lower_momentum (plain + nesterov)
  std::string RunMomentum(const OpDesc& op, Scope* scope) {
    const std::string* pn = OneName(op, "Param");
    const std::string* gn = OneName(op, "Grad");
    const std::string* vn = OneName(op, "Velocity");
    const std::string* lrn = OneName(op, "LearningRate");
    const std::string* pon = OneName(op, "ParamOut", false);
    const std::string* von = OneName(op, "VelocityOut", false);
    if (pn == nullptr || gn == nullptr || vn == nullptr ||
        lrn == nullptr || pon == nullptr || von == nullptr) {
      return "missing io";
    }
    const HostTensor* p = scope->Find(*pn);
    const HostTensor* g = scope->Find(*gn);
    const HostTensor* v = scope->Find(*vn);
    const HostTensor* lr = scope->Find(*lrn);
    for (const HostTensor* t : {p, g, v, lr}) {
      if (t == nullptr) return "input not in scope";
      if (!IsF32(*t)) return "non-f32 dtype";
    }
    int64_t n = NumElements(p->dims);
    if (NumElements(g->dims) != n || NumElements(v->dims) != n) {
      return "shape mismatch";
    }
    if (NumElements(lr->dims) < 1) return "empty scalar input";
    float mu = FloatAttr(op, "mu", 0.0f);
    bool nesterov = IntAttr(op, "use_nesterov", 0) != 0;
    float rate = F32(*lr)[0];
    HostTensor po = MakeF32(p->dims);
    HostTensor vo = MakeF32(p->dims);
    const float* pa = F32(*p);
    const float* ga = F32(*g);
    const float* va = F32(*v);
    float* poa = MutF32(&po);
    float* voa = MutF32(&vo);
    for (int64_t i = 0; i < n; ++i) {
      float nv = mu * va[i] + ga[i];
      voa[i] = nv;
      poa[i] = nesterov ? pa[i] - (ga[i] + mu * nv) * rate
                        : pa[i] - rate * nv;
    }
    scope->Set(*pon, std::move(po));
    scope->Set(*von, std::move(vo));
    return "";
  }

  // d tanh = (1 - out^2) * dOut; d sigmoid = out * (1 - out) * dOut
  std::string RunTanhGrad(const OpDesc& op, Scope* scope) {
    return RunActGradFromOut(
        op, scope, [](float o) { return 1.0f - o * o; });
  }

  std::string RunSigmoidGrad(const OpDesc& op, Scope* scope) {
    return RunActGradFromOut(
        op, scope, [](float o) { return o * (1.0f - o); });
  }


  // grads expressed in terms of the forward INPUT (square, log, ...)
  template <typename Fn>
  std::string RunActGradFromX(const OpDesc& op, Scope* scope, Fn dfn) {
    const std::string* xn = OneName(op, "X");
    const std::string* ogn = OneName(op, "Out@GRAD");
    const std::string* gn = OneName(op, "X@GRAD", false);
    if (xn == nullptr || ogn == nullptr || gn == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* og = scope->Find(*ogn);
    if (x == nullptr || og == nullptr) return "input not in scope";
    if (!IsF32(*x) || !IsF32(*og)) return "non-f32 dtype";
    int64_t n = NumElements(x->dims);
    if (n != NumElements(og->dims)) return "shape mismatch";
    HostTensor grad = MakeF32(x->dims);
    const float* xa = F32(*x);
    const float* ga = F32(*og);
    float* ra = MutF32(&grad);
    for (int64_t i = 0; i < n; ++i) ra[i] = dfn(xa[i], ga[i]);
    scope->Set(*gn, std::move(grad));
    return "";
  }

  template <typename Fn>
  std::string RunActGradFromOut(const OpDesc& op, Scope* scope, Fn dfn) {
    const std::string* on = OneName(op, "Out");
    const std::string* ogn = OneName(op, "Out@GRAD");
    const std::string* gn = OneName(op, "X@GRAD", false);
    if (on == nullptr || ogn == nullptr || gn == nullptr) {
      return "missing io";
    }
    const HostTensor* out = scope->Find(*on);
    const HostTensor* og = scope->Find(*ogn);
    if (out == nullptr || og == nullptr) return "input not in scope";
    if (!IsF32(*out) || !IsF32(*og)) return "non-f32 dtype";
    int64_t n = NumElements(out->dims);
    if (n != NumElements(og->dims)) return "shape mismatch";
    HostTensor grad = MakeF32(out->dims);
    const float* oa = F32(*out);
    const float* ga = F32(*og);
    float* ra = MutF32(&grad);
    for (int64_t i = 0; i < n; ++i) ra[i] = dfn(oa[i]) * ga[i];
    scope->Set(*gn, std::move(grad));
    return "";
  }

  // dX = (dOut - sum_j dOut_j * Out_j) * Out per row (softmax vjp)
  std::string RunSoftmaxGrad(const OpDesc& op, Scope* scope) {
    const std::string* on = OneName(op, "Out");
    const std::string* ogn = OneName(op, "Out@GRAD");
    const std::string* gn = OneName(op, "X@GRAD", false);
    if (on == nullptr || ogn == nullptr || gn == nullptr) {
      return "missing io";
    }
    const HostTensor* out = scope->Find(*on);
    const HostTensor* og = scope->Find(*ogn);
    if (out == nullptr || og == nullptr) return "input not in scope";
    if (!IsF32(*out) || !IsF32(*og) || out->dims.size() < 1) {
      return "bad input";
    }
    int64_t n = NumElements(out->dims);
    if (n != NumElements(og->dims)) return "shape mismatch";
    int64_t c = out->dims.back();
    if (c <= 0 || n % c != 0) return "bad last dim";
    HostTensor grad = MakeF32(out->dims);
    const float* oa = F32(*out);
    const float* ga = F32(*og);
    float* ra = MutF32(&grad);
    for (int64_t row = 0; row < n / c; ++row) {
      const float* orow = oa + row * c;
      const float* grow = ga + row * c;
      float dot = 0.0f;
      for (int64_t j = 0; j < c; ++j) dot += grow[j] * orow[j];
      for (int64_t j = 0; j < c; ++j) {
        ra[row * c + j] = (grow[j] - dot) * orow[j];
      }
    }
    scope->Set(*gn, std::move(grad));
    return "";
  }

  // hard-label cross_entropy: dX[i, gold] = -dY[i] / max(X[i, gold], eps)
  // (matches the forward's log(max(x, eps)) clamp, ops/loss_ops.py)
  std::string RunXentGrad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* labn = OneName(op, "Label");
    const std::string* ogn = OneName(op, "Y@GRAD");
    const std::string* gn = OneName(op, "X@GRAD", false);
    if (xn == nullptr || labn == nullptr || ogn == nullptr ||
        gn == nullptr) {
      return "missing io";
    }
    if (IntAttr(op, "soft_label", 0) != 0) return "soft_label unsupported";
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* label = scope->Find(*labn);
    const HostTensor* og = scope->Find(*ogn);
    if (x == nullptr || label == nullptr || og == nullptr) {
      return "input not in scope";
    }
    if (!IsF32(*x) || x->dims.size() != 2) return "bad input";
    int64_t n = x->dims[0], c = x->dims[1];
    if (NumElements(og->dims) < n) return "loss grad too small";
    std::vector<int64_t> gold;
    std::string lerr = ReadIds(*label, &gold);
    if (!lerr.empty()) return lerr;
    if (static_cast<int64_t>(gold.size()) != n) return "label count";
    HostTensor grad = MakeF32(x->dims);
    const float* xa = F32(*x);
    const float* ga = F32(*og);
    float* ra = MutF32(&grad);
    std::fill(ra, ra + n * c, 0.0f);
    const float kEps = 1e-8f;
    for (int64_t i = 0; i < n; ++i) {
      if (gold[i] < 0 || gold[i] >= c) return "label out of range";
      float p = xa[i * c + gold[i]];
      ra[i * c + gold[i]] = -ga[i] / (p > kEps ? p : kEps);
    }
    scope->Set(*gn, std::move(grad));
    return "";
  }



  // dX = reshape(dOut, X.shape) — pure metadata
  std::string RunReshapeGrad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* ogn = OneName(op, "Out@GRAD");
    const std::string* gn = OneName(op, "X@GRAD", false);
    if (xn == nullptr || ogn == nullptr || gn == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* og = scope->Find(*ogn);
    if (x == nullptr || og == nullptr) return "input not in scope";
    if (NumElements(x->dims) != NumElements(og->dims)) {
      return "size mismatch";
    }
    HostTensor grad = *og;
    grad.dims = x->dims;
    scope->Set(*gn, std::move(grad));
    return "";
  }

  // dX = transpose(dOut, argsort(perm)) (inverse permutation).
  // NB: this odometer-walk and RunTranspose's stride-division walk are
  // two implementations of the same permuted copy; a fix to either's
  // index math must be mirrored in the other (behavior pinned by the
  // structural-grad parity test + the fuzz transpose family).
  std::string RunTransposeGrad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* ogn = OneName(op, "Out@GRAD");
    const std::string* gn = OneName(op, "X@GRAD", false);
    if (xn == nullptr || ogn == nullptr || gn == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* og = scope->Find(*ogn);
    if (x == nullptr || og == nullptr) return "input not in scope";
    if (!IsF32(*og)) return "non-f32 dtype";
    auto perm = IntsAttr(op, "axis", {});
    size_t rank = x->dims.size();
    if (rank == 0) return "rank-0 input";
    if (perm.size() != rank || og->dims.size() != rank) {
      return "bad perm";
    }
    // the gather loop below re-derives the inverse mapping through
    // idx[perm[d]]; here we just validate perm is a permutation and
    // that dOut's dims really are x's dims permuted
    std::vector<bool> seen(rank, false);
    for (size_t d = 0; d < rank; ++d) {
      int64_t p = perm[d];
      if (p < 0 || p >= static_cast<int64_t>(rank) || seen[p]) {
        return "bad perm";
      }
      seen[p] = true;
      if (og->dims[d] != x->dims[p]) return "dOut shape mismatch";
    }
    HostTensor grad = MakeF32(x->dims);
    float* ra = MutF32(&grad);
    const float* ga = F32(*og);
    std::vector<int64_t> gstride(rank, 1);
    for (size_t d = rank - 1; d > 0; --d) {
      gstride[d - 1] = gstride[d] * og->dims[d];
    }
    std::vector<int64_t> idx(rank, 0);  // index into x/grad space
    int64_t total = NumElements(x->dims);
    for (int64_t i = 0; i < total; ++i) {
      // dOut index: out dim d corresponds to x dim perm[d], so
      // og_idx[d] = idx[perm[d]] -> flat via inverse mapping
      int64_t src = 0;
      for (size_t d = 0; d < rank; ++d) {
        src += idx[perm[d]] * gstride[d];
      }
      ra[i] = ga[src];
      for (size_t d = rank; d-- > 0;) {
        if (++idx[d] < x->dims[d]) break;
        idx[d] = 0;
      }
    }
    scope->Set(*gn, std::move(grad));
    return "";
  }

  // scatter-add of dOut rows into W@GRAD (padding_idx rows skipped —
  // the forward zeroed them, so their vjp is zero)
  std::string RunLookupTableGrad(const OpDesc& op, Scope* scope) {
    const std::string* wn = OneName(op, "W");
    const std::string* idn = OneName(op, "Ids");
    const std::string* ogn = OneName(op, "Out@GRAD");
    const std::string* gn = OneName(op, "W@GRAD", false);
    if (wn == nullptr || idn == nullptr || ogn == nullptr ||
        gn == nullptr) {
      return "missing io";
    }
    const HostTensor* w = scope->Find(*wn);
    const HostTensor* it = scope->Find(*idn);
    const HostTensor* og = scope->Find(*ogn);
    if (w == nullptr || it == nullptr || og == nullptr) {
      return "input not in scope";
    }
    if (!IsF32(*w) || !IsF32(*og) || w->dims.size() != 2) {
      return "bad input";
    }
    std::vector<int64_t> ids;
    std::string err = ReadIds(*it, &ids);
    if (!err.empty()) return err;
    int64_t rows = w->dims[0], d2 = w->dims[1];
    if (NumElements(og->dims) !=
        static_cast<int64_t>(ids.size()) * d2) {
      return "dOut shape mismatch";
    }
    int64_t pad = IntAttr(op, "padding_idx", -1);
    HostTensor grad = MakeF32(w->dims);
    float* ra = MutF32(&grad);
    std::fill(ra, ra + rows * d2, 0.0f);
    const float* ga = F32(*og);
    for (size_t i = 0; i < ids.size(); ++i) {
      int64_t r = ids[i];
      if (r < 0 || r >= rows) return "id out of range";
      if (pad >= 0 && r == pad) continue;
      for (int64_t j = 0; j < d2; ++j) {
        ra[r * d2 + j] += ga[i * d2 + j];
      }
    }
    scope->Set(*gn, std::move(grad));
    return "";
  }

  // adjoint of RunSequencePool per pooltype; MAX routes to the first
  // max among valid steps (continuous inputs make ties measure-zero)
  std::string RunSeqPoolGrad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* ogn = OneName(op, "Out@GRAD");
    const std::string* gn = OneName(op, "X@GRAD", false);
    if (xn == nullptr || ogn == nullptr || gn == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* og = scope->Find(*ogn);
    if (x == nullptr || og == nullptr) return "input not in scope";
    if (!IsF32(*x) || x->dims.size() != 3 || !IsF32(*og)) {
      return "bad input";
    }
    int64_t b = x->dims[0], t = x->dims[1], d2 = x->dims[2];
    if (og->dims != std::vector<int64_t>({b, d2})) return "dOut shape";
    std::vector<int64_t> lens;
    std::string err = RowLengths(op, scope, b, t, &lens);
    if (!err.empty()) return err;
    std::string ptype = StrAttr(op, "pooltype", "AVERAGE");
    for (char& c : ptype) c = std::toupper(c);
    if (ptype != "MAX" && ptype != "LAST" && ptype != "FIRST" &&
        ptype != "SUM" && ptype != "AVERAGE" && ptype != "SQRT") {
      return "unknown pooltype " + ptype;
    }
    HostTensor grad = MakeF32(x->dims);
    float* ra = MutF32(&grad);
    std::fill(ra, ra + b * t * d2, 0.0f);
    const float* xa = F32(*x);
    const float* ga = F32(*og);
    for (int64_t i = 0; i < b; ++i) {
      int64_t len = lens[i];
      for (int64_t j = 0; j < d2; ++j) {
        float g = ga[i * d2 + j];
        if (ptype == "MAX") {
          if (len <= 0) continue;
          int64_t best = 0;
          float bv = xa[(i * t + 0) * d2 + j];
          for (int64_t s2 = 1; s2 < len; ++s2) {
            float v = xa[(i * t + s2) * d2 + j];
            if (v > bv) {
              bv = v;
              best = s2;
            }
          }
          ra[(i * t + best) * d2 + j] += g;
        } else if (ptype == "LAST") {
          ra[(i * t + std::max<int64_t>(len - 1, 0)) * d2 + j] += g;
        } else if (ptype == "FIRST") {
          ra[(i * t + 0) * d2 + j] += g;
        } else {
          float denom = 1.0f;
          if (ptype == "AVERAGE") {
            denom = static_cast<float>(std::max<int64_t>(len, 1));
          } else if (ptype == "SQRT") {
            denom = std::sqrt(
                static_cast<float>(std::max<int64_t>(len, 1)));
          }
          for (int64_t s2 = 0; s2 < len; ++s2) {
            ra[(i * t + s2) * d2 + j] += g / denom;
          }
        }
      }
    }
    scope->Set(*gn, std::move(grad));
    return "";
  }


  // concat backward: split dOut back into the inputs' spans along axis
  std::string RunConcatGrad(const OpDesc& op, Scope* scope) {
    auto xs_it = op.inputs.find("X");
    const std::string* ogn = OneName(op, "Out@GRAD");
    auto gs_it = op.outputs.find("X@GRAD");
    if (xs_it == op.inputs.end() || ogn == nullptr ||
        gs_it == op.outputs.end()) {
      return "missing io";
    }
    const HostTensor* og = scope->Find(*ogn);
    if (og == nullptr) return "input not in scope";
    if (!IsF32(*og) || og->dims.empty()) return "bad dOut";
    size_t rank = og->dims.size();
    int64_t axis = IntAttr(op, "axis", 0);
    if (axis < 0) axis += rank;
    if (axis < 0 || axis >= static_cast<int64_t>(rank)) {
      return "axis out of range";
    }
    int64_t outer = 1, inner = 1;
    for (int64_t d = 0; d < axis; ++d) outer *= og->dims[d];
    for (size_t d = axis + 1; d < rank; ++d) inner *= og->dims[d];
    const float* ga = F32(*og);
    int64_t offset = 0;
    int64_t og_axis = og->dims[axis];
    if (xs_it->second.size() != gs_it->second.size()) {
      return "X/X@GRAD arity mismatch";
    }
    // NB: this axis-split copy mirrors RunSplit's; a fix to either's
    // span/offset math must be mirrored in the other
    for (size_t i = 0; i < xs_it->second.size(); ++i) {
      if (xs_it->second[i].empty()) {
        // forward RunConcat skips empty entries; mirror it (and keep
        // the offset accounting aligned with the forward's sum)
        continue;
      }
      const HostTensor* x = scope->Find(xs_it->second[i]);
      if (x == nullptr) return "input not in scope";
      if (x->dims.size() != rank) return "rank mismatch";
      for (size_t d = 0; d < rank; ++d) {
        if (static_cast<int64_t>(d) != axis &&
            x->dims[d] != og->dims[d]) {
          return "shape mismatch off the concat axis";
        }
      }
      int64_t span = x->dims[axis];
      if (offset + span > og_axis) return "axis spans exceed dOut";
      const std::string& gname =
          i < gs_it->second.size() ? gs_it->second[i] : std::string();
      if (!gname.empty()) {
        HostTensor grad = MakeF32(x->dims);
        float* ra = MutF32(&grad);
        for (int64_t o = 0; o < outer; ++o) {
          const float* src = ga + (o * og_axis + offset) * inner;
          std::copy(src, src + span * inner, ra + o * span * inner);
        }
        scope->Set(gname, std::move(grad));
      }
      offset += span;
    }
    if (offset != og_axis) return "axis spans do not cover dOut";
    return "";
  }

  // d(sum of inputs): copy dOut to every requested X@GRAD
  std::string RunSumGrad(const OpDesc& op, Scope* scope) {
    const std::string* ogn = OneName(op, "Out@GRAD");
    if (ogn == nullptr) return "missing io";
    const HostTensor* og = scope->Find(*ogn);
    if (og == nullptr) return "input not in scope";
    if (!IsF32(*og)) return "non-f32 dtype";
    auto it = op.outputs.find("X@GRAD");
    if (it == op.outputs.end()) return "missing io";
    for (const std::string& nme : it->second) {
      if (nme.empty()) continue;
      HostTensor copy = *og;
      scope->Set(nme, std::move(copy));
    }
    return "";
  }

  // conv2d backward: dInput (transposed conv of dOut with the filter)
  // and dFilter (correlation of Input with dOut), same geometry attrs
  // the forward kernel supports (strides/paddings/dilations/groups)
  std::string RunConv2dGrad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "Input");
    const std::string* wn = OneName(op, "Filter");
    const std::string* ogn = OneName(op, "Output@GRAD");
    if (xn == nullptr || wn == nullptr || ogn == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* w = scope->Find(*wn);
    const HostTensor* og = scope->Find(*ogn);
    if (x == nullptr || w == nullptr || og == nullptr) {
      return "input not in scope";
    }
    if (!IsF32(*x) || !IsF32(*w) || !IsF32(*og)) return "non-f32 dtype";
    if (x->dims.size() != 4 || w->dims.size() != 4 ||
        og->dims.size() != 4) {
      return "rank != 4";
    }
    auto strides = IntsAttr(op, "strides", {1, 1});
    auto pads = IntsAttr(op, "paddings", {0, 0});
    auto dil = IntsAttr(op, "dilations", {1, 1});
    if (strides.size() != 2 || pads.size() != 2 || dil.size() != 2) {
      return "bad geometry attrs";
    }
    int64_t groups = IntAttr(op, "groups", 1);
    if (groups <= 0) groups = 1;
    int64_t n = x->dims[0], ci = x->dims[1], h = x->dims[2],
            wd = x->dims[3];
    int64_t co = w->dims[0], cig = w->dims[1], kh = w->dims[2],
            kw = w->dims[3];
    if (groups > ci || ci % groups != 0 || ci / groups != cig ||
        co < groups || co % groups != 0) {
      return "group/channel mismatch";
    }
    // dOut spatial dims must match the forward geometry exactly (same
    // discipline as RunPool2dGrad): out-of-range positions would have
    // every tap bounds-skipped and mis-execute silently
    int64_t oh = (h + 2 * pads[0] - (dil[0] * (kh - 1) + 1)) /
                     strides[0] + 1;
    int64_t ow = (wd + 2 * pads[1] - (dil[1] * (kw - 1) + 1)) /
                     strides[1] + 1;
    if (og->dims != std::vector<int64_t>({n, co, oh, ow})) {
      return "dOut shape";
    }
    const float* xa = F32(*x);
    const float* wa = F32(*w);
    const float* ga = F32(*og);
    int64_t co_g = co / groups;
    const std::string* xgn = OneName(op, "Input@GRAD", false);
    const std::string* wgn = OneName(op, "Filter@GRAD", false);
    HostTensor xg, wg;
    float* xga = nullptr;
    float* wga = nullptr;
    if (xgn != nullptr) {
      xg = MakeF32(x->dims);
      xga = MutF32(&xg);
      std::fill(xga, xga + NumElements(x->dims), 0.0f);
    }
    if (wgn != nullptr) {
      wg = MakeF32(w->dims);
      wga = MutF32(&wg);
      std::fill(wga, wga + NumElements(w->dims), 0.0f);
    }
    // scatter each dOut element back through the taps the forward read:
    // one loop nest, both grads, exact adjoint of RunConv2d's gather
    for (int64_t b = 0; b < n; ++b) {
      for (int64_t oc = 0; oc < co; ++oc) {
        int64_t g = oc / co_g;
        for (int64_t i = 0; i < oh; ++i) {
          for (int64_t j = 0; j < ow; ++j) {
            float go = ga[((b * co + oc) * oh + i) * ow + j];
            if (go == 0.0f) continue;
            for (int64_t icg = 0; icg < cig; ++icg) {
              int64_t ic = g * cig + icg;
              for (int64_t r = 0; r < kh; ++r) {
                int64_t yy = i * strides[0] - pads[0] + r * dil[0];
                if (yy < 0 || yy >= h) continue;
                for (int64_t s = 0; s < kw; ++s) {
                  int64_t xx = j * strides[1] - pads[1] + s * dil[1];
                  if (xx < 0 || xx >= wd) continue;
                  int64_t xi = ((b * ci + ic) * h + yy) * wd + xx;
                  int64_t wi = ((oc * cig + icg) * kh + r) * kw + s;
                  if (xga != nullptr) xga[xi] += go * wa[wi];
                  if (wga != nullptr) wga[wi] += go * xa[xi];
                }
              }
            }
          }
        }
      }
    }
    if (xgn != nullptr) scope->Set(*xgn, std::move(xg));
    if (wgn != nullptr) scope->Set(*wgn, std::move(wg));
    return "";
  }

  // pool2d backward. max: route dOut to the argmax tap (first-max on
  // ties, matching a deterministic forward scan); avg: spread dOut over
  // the window (exclusive: only in-bounds taps share it)
  std::string RunPool2dGrad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out");
    const std::string* ogn = OneName(op, "Out@GRAD");
    const std::string* gn = OneName(op, "X@GRAD", false);
    if (xn == nullptr || on == nullptr || ogn == nullptr ||
        gn == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* og = scope->Find(*ogn);
    if (x == nullptr || og == nullptr) return "input not in scope";
    if (!IsF32(*x) || x->dims.size() != 4 || !IsF32(*og)) {
      return "bad input";
    }
    std::string ptype = StrAttr(op, "pooling_type", "max");
    bool global = IntAttr(op, "global_pooling", 0) != 0;
    bool exclusive = IntAttr(op, "exclusive", 1) != 0;
    bool ceil = IntAttr(op, "ceil_mode", 0) != 0;
    if (IntAttr(op, "adaptive", 0) != 0) return "adaptive unsupported";
    auto ks = IntsAttr(op, "ksize", {2, 2});
    auto st = IntsAttr(op, "strides", {1, 1});
    auto pd = IntsAttr(op, "paddings", {0, 0});
    if (ks.size() != 2 || st.size() != 2 || pd.size() != 2) {
      return "bad geometry attrs";
    }
    int64_t n = x->dims[0], c = x->dims[1], h = x->dims[2],
            wd = x->dims[3];
    if (global) {
      ks = {h, wd};
      st = {h, wd};
      pd = {0, 0};
      ceil = false;
    }
    int64_t oh = PoolOutDim(h, ks[0], st[0], pd[0], ceil);
    int64_t ow = PoolOutDim(wd, ks[1], st[1], pd[1], ceil);
    if (og->dims != std::vector<int64_t>({n, c, oh, ow})) {
      return "dOut shape";
    }
    HostTensor grad = MakeF32(x->dims);
    float* ra = MutF32(&grad);
    std::fill(ra, ra + NumElements(x->dims), 0.0f);
    const float* xa = F32(*x);
    const float* ga = F32(*og);
    for (int64_t b = 0; b < n; ++b) {
      for (int64_t ch = 0; ch < c; ++ch) {
        const float* plane = xa + (b * c + ch) * h * wd;
        float* gplane = ra + (b * c + ch) * h * wd;
        for (int64_t i = 0; i < oh; ++i) {
          for (int64_t j = 0; j < ow; ++j) {
            float go = ga[((b * c + ch) * oh + i) * ow + j];
            if (ptype == "max") {
              float best = -INFINITY;
              int64_t bi = -1;
              for (int64_t r = 0; r < ks[0]; ++r) {
                int64_t yy = i * st[0] - pd[0] + r;
                if (yy < 0 || yy >= h) continue;
                for (int64_t s = 0; s < ks[1]; ++s) {
                  int64_t xx = j * st[1] - pd[1] + s;
                  if (xx < 0 || xx >= wd) continue;
                  float v = plane[yy * wd + xx];
                  if (v > best) {
                    best = v;
                    bi = yy * wd + xx;
                  }
                }
              }
              if (bi >= 0) gplane[bi] += go;
            } else {
              int64_t cnt = 0;
              for (int64_t r = 0; r < ks[0]; ++r) {
                int64_t yy = i * st[0] - pd[0] + r;
                if (yy < 0 || yy >= h) continue;
                for (int64_t s = 0; s < ks[1]; ++s) {
                  int64_t xx = j * st[1] - pd[1] + s;
                  if (xx < 0 || xx >= wd) continue;
                  ++cnt;
                }
              }
              int64_t denom = exclusive ? cnt : ks[0] * ks[1];
              if (denom <= 0) continue;
              float share = go / static_cast<float>(denom);
              for (int64_t r = 0; r < ks[0]; ++r) {
                int64_t yy = i * st[0] - pd[0] + r;
                if (yy < 0 || yy >= h) continue;
                for (int64_t s = 0; s < ks[1]; ++s) {
                  int64_t xx = j * st[1] - pd[1] + s;
                  if (xx < 0 || xx >= wd) continue;
                  gplane[yy * wd + xx] += share;
                }
              }
            }
          }
        }
      }
    }
    scope->Set(*gn, std::move(grad));
    return "";
  }

  // Elementwise unary family (ops/activation_ops.py + math unaries):
  // every op maps 1:1 onto a scalar function of (x, attrs). Semantics
  // mirror the XLA lowerings exactly — incl. jnp.round's half-to-even
  // (std::nearbyint under the default rounding mode), jax.nn.softplus's
  // stable form, and jax.nn.gelu's default tanh approximation.
  static bool IsUnaryType(const std::string& t) {
    static const std::map<std::string, int>& tbl = UnaryTable();
    return tbl.count(t) != 0;
  }

  static const std::map<std::string, int>& UnaryTable() {
    static const std::map<std::string, int> tbl = {
        {"exp", 0},          {"log", 1},           {"sqrt", 2},
        {"rsqrt", 3},        {"abs", 4},           {"square", 5},
        {"reciprocal", 6},   {"floor", 7},         {"ceil", 8},
        {"round", 9},        {"sign", 10},         {"softplus", 11},
        {"softsign", 12},    {"tanh_shrink", 13},  {"logsigmoid", 14},
        {"gelu", 15},        {"sin", 16},          {"cos", 17},
        {"leaky_relu", 18},  {"elu", 19},          {"relu6", 20},
        {"pow", 21},         {"stanh", 22},        {"hard_sigmoid", 23},
        {"thresholded_relu", 24},                  {"soft_relu", 25},
        {"brelu", 26},       {"swish", 27},        {"softshrink", 28},
        {"hard_shrink", 29},
    };
    return tbl;
  }

  std::string RunUnary(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x)) return "non-f32 dtype";
    int kind = UnaryTable().at(op.type);
    float a0 = 0.0f, a1 = 0.0f;
    switch (kind) {
      case 18: a0 = FloatAttr(op, "alpha", 0.02f); break;
      case 19: a0 = FloatAttr(op, "alpha", 1.0f); break;
      case 20: a0 = FloatAttr(op, "threshold", 6.0f); break;
      case 21: a0 = FloatAttr(op, "factor", 1.0f); break;
      case 22:
        a0 = FloatAttr(op, "scale_a", 2.0f / 3.0f);
        a1 = FloatAttr(op, "scale_b", 1.7159f);
        break;
      case 23:
        a0 = FloatAttr(op, "slope", 0.2f);
        a1 = FloatAttr(op, "offset", 0.5f);
        break;
      case 24: a0 = FloatAttr(op, "threshold", 1.0f); break;
      case 25: a0 = FloatAttr(op, "threshold", 40.0f); break;
      case 26:
        a0 = FloatAttr(op, "t_min", 0.0f);
        a1 = FloatAttr(op, "t_max", 24.0f);
        break;
      case 27: a0 = FloatAttr(op, "beta", 1.0f); break;
      case 28: a0 = FloatAttr(op, "lambda", 0.5f); break;
      case 29: a0 = FloatAttr(op, "threshold", 0.5f); break;
      default: break;
    }
    auto softplus = [](float v) {
      // jax.nn.softplus's stable form
      return v > 0.0f ? v + std::log1p(std::exp(-v))
                      : std::log1p(std::exp(v));
    };
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    int64_t n = NumElements(x->dims);
    for (int64_t i = 0; i < n; ++i) {
      float v = xa[i], r;
      switch (kind) {
        case 0: r = std::exp(v); break;
        case 1: r = std::log(v); break;
        case 2: r = std::sqrt(v); break;
        case 3: r = 1.0f / std::sqrt(v); break;
        case 4: r = std::fabs(v); break;
        case 5: r = v * v; break;
        case 6: r = 1.0f / v; break;
        case 7: r = std::floor(v); break;
        case 8: r = std::ceil(v); break;
        case 9: r = static_cast<float>(std::nearbyint(v)); break;
        case 10: r = v > 0 ? 1.0f : (v < 0 ? -1.0f : 0.0f); break;
        case 11: r = softplus(v); break;
        case 12: r = v / (1.0f + std::fabs(v)); break;
        case 13: r = v - std::tanh(v); break;
        case 14: r = -softplus(-v); break;
        case 15: {
          float c = 0.7978845608028654f;  // sqrt(2/pi), tanh-approx gelu
          r = 0.5f * v * (1.0f + std::tanh(c * (v + 0.044715f * v * v * v)));
          break;
        }
        case 16: r = std::sin(v); break;
        case 17: r = std::cos(v); break;
        case 18: r = v >= 0 ? v : a0 * v; break;
        case 19: r = v > 0 ? v : a0 * (std::exp(v) - 1.0f); break;
        case 20: r = std::min(std::max(v, 0.0f), a0); break;
        case 21: r = std::pow(v, a0); break;
        case 22: r = a1 * std::tanh(v * a0); break;
        case 23: r = std::min(std::max(v * a0 + a1, 0.0f), 1.0f); break;
        case 24: r = v > a0 ? v : 0.0f; break;
        case 25: r = std::log1p(std::exp(std::min(std::max(v, -a0), a0)));
                 break;
        case 26: r = std::min(std::max(v, a0), a1); break;
        case 27: r = v / (1.0f + std::exp(-a0 * v)); break;
        case 28: {
          float m = std::fabs(v) - a0;
          r = m > 0.0f ? (v > 0 ? m : -m) : 0.0f;
          break;
        }
        case 29: r = std::fabs(v) > a0 ? v : 0.0f; break;
        default: return "unknown unary";
      }
      oa[i] = r;
    }
    scope->Set(*on, std::move(out));
    return "";
  }




  // per-(sample, group) normalization + per-channel affine
  // (ops/nn_ops.py _lower_group_norm)
  std::string RunGroupNorm(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Y", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x) || x->dims.size() < 2) return "bad input";
    int64_t groups = IntAttr(op, "groups", 1);
    float eps = FloatAttr(op, "epsilon", 1e-5f);
    int64_t n = x->dims[0], c = x->dims[1];
    if (groups <= 0 || c % groups != 0) return "bad groups";
    int64_t rest = 1;
    for (size_t d = 2; d < x->dims.size(); ++d) rest *= x->dims[d];
    int64_t cg = c / groups;
    int64_t glen = cg * rest;
    const HostTensor* scale = nullptr;
    const HostTensor* bias = nullptr;
    const std::string* sn = OneName(op, "Scale");
    const std::string* bn = OneName(op, "Bias");
    if (sn != nullptr) {
      scale = scope->Find(*sn);
      if (scale == nullptr || NumElements(scale->dims) != c) {
        return "bad scale";
      }
    }
    if (bn != nullptr) {
      bias = scope->Find(*bn);
      if (bias == nullptr || NumElements(bias->dims) != c) {
        return "bad bias";
      }
    }
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    for (int64_t b = 0; b < n; ++b) {
      for (int64_t g = 0; g < groups; ++g) {
        const float* base = xa + (b * c + g * cg) * rest;
        double mean = 0.0;
        for (int64_t i = 0; i < glen; ++i) mean += base[i];
        mean /= static_cast<double>(glen);
        double var = 0.0;
        for (int64_t i = 0; i < glen; ++i) {
          double d2 = base[i] - mean;
          var += d2 * d2;
        }
        var /= static_cast<double>(glen);
        float inv = 1.0f / std::sqrt(static_cast<float>(var) + eps);
        float* ob = oa + (b * c + g * cg) * rest;
        for (int64_t i = 0; i < glen; ++i) {
          int64_t ch = g * cg + i / rest;
          float v = (base[i] - static_cast<float>(mean)) * inv;
          if (scale != nullptr) v *= F32(*scale)[ch];
          if (bias != nullptr) v += F32(*bias)[ch];
          ob[i] = v;
        }
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // masked softmax over [batch, max_len] with optional Length
  // (ops/sequence_ops.py _lower_sequence_softmax; invalid positions 0)
  std::string RunSeqSoftmax(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x) || x->dims.size() != 2) return "bad input";
    int64_t b = x->dims[0], t = x->dims[1];
    std::vector<int64_t> lens(b, t);
    const std::string* ln = OneName(op, "Length");
    if (ln != nullptr) {
      const HostTensor* lt = scope->Find(*ln);
      if (lt == nullptr) return "length not in scope";
      std::vector<int64_t> raw;
      std::string err = ReadIds(*lt, &raw);
      if (!err.empty()) return err;
      if (static_cast<int64_t>(raw.size()) != b) return "length count";
      lens = raw;
    }
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    for (int64_t i = 0; i < b; ++i) {
      int64_t len = std::min<int64_t>(std::max<int64_t>(lens[i], 0), t);
      const float* row = xa + i * t;
      float* orow = oa + i * t;
      if (len == 0) {
        // all-masked row: softmax over all -1e38 = uniform, then
        // zeroed by the where — matches the XLA lowering exactly
        std::fill(orow, orow + t, 0.0f);
        continue;
      }
      float mx = -INFINITY;
      for (int64_t j = 0; j < len; ++j) mx = std::max(mx, row[j]);
      float denom = 0.0f;
      for (int64_t j = 0; j < len; ++j) denom += std::exp(row[j] - mx);
      for (int64_t j = 0; j < t; ++j) {
        orow[j] = j < len ? std::exp(row[j] - mx) / denom : 0.0f;
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // l2_normalize along attr axis (ops/math_ops.py norm)
  std::string RunL2Norm(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x) || x->dims.empty()) return "bad input";
    size_t rank = x->dims.size();
    int64_t axis = IntAttr(op, "axis", op.type == "norm" ? 1 : -1);
    if (axis < 0) axis += rank;
    if (axis < 0 || axis >= static_cast<int64_t>(rank)) {
      return "axis out of range";
    }
    float eps = FloatAttr(op, "epsilon", 1e-10f);
    int64_t len = x->dims[axis];
    int64_t inner = 1;
    for (size_t d = axis + 1; d < rank; ++d) inner *= x->dims[d];
    int64_t outer = NumElements(x->dims) / (len * inner);
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    for (int64_t o = 0; o < outer; ++o) {
      for (int64_t in2 = 0; in2 < inner; ++in2) {
        const float* base = xa + o * len * inner + in2;
        float* ob = oa + o * len * inner + in2;
        float acc = eps;
        for (int64_t p = 0; p < len; ++p) {
          acc += base[p * inner] * base[p * inner];
        }
        float inv = 1.0f / std::sqrt(acc);
        for (int64_t p = 0; p < len; ++p) {
          ob[p * inner] = base[p * inner] * inv;
        }
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // huber: d = Y - X; |d|<=delta -> d^2/2 else delta*(|d|-delta/2)
  std::string RunHuberLoss(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* yn = OneName(op, "Y");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || yn == nullptr || on == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* y = scope->Find(*yn);
    if (x == nullptr || y == nullptr) return "input not in scope";
    if (!IsF32(*x) || !IsF32(*y) || x->dims != y->dims) return "bad input";
    float delta = FloatAttr(op, "delta", 1.0f);
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    const float* ya = F32(*y);
    float* oa = MutF32(&out);
    int64_t n = NumElements(x->dims);
    for (int64_t i = 0; i < n; ++i) {
      float d = ya[i] - xa[i];
      float ad = std::fabs(d);
      oa[i] = ad <= delta ? 0.5f * d * d : delta * (ad - 0.5f * delta);
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunLogLoss(const OpDesc& op, Scope* scope) {
    const std::string* pn = OneName(op, "Predicted");
    const std::string* ln = OneName(op, "Labels");
    const std::string* on = OneName(op, "Loss", false);
    if (pn == nullptr || ln == nullptr || on == nullptr) {
      return "missing io";
    }
    const HostTensor* p = scope->Find(*pn);
    const HostTensor* l = scope->Find(*ln);
    if (p == nullptr || l == nullptr) return "input not in scope";
    if (!IsF32(*p) || !IsF32(*l) || p->dims != l->dims) return "bad input";
    float eps = FloatAttr(op, "epsilon", 1e-4f);
    HostTensor out = MakeF32(p->dims);
    const float* pa = F32(*p);
    const float* la = F32(*l);
    float* oa = MutF32(&out);
    int64_t n = NumElements(p->dims);
    for (int64_t i = 0; i < n; ++i) {
      oa[i] = -la[i] * std::log(pa[i] + eps) -
              (1.0f - la[i]) * std::log(1.0f - pa[i] + eps);
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // max over `groups` consecutive channels (ops/activation_ops.py
  // _maxout: reshape (n, c/g, g, h, w), max over the g axis)
  std::string RunMaxout(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x) || x->dims.size() != 4) return "bad input";
    int64_t groups = IntAttr(op, "groups", 1);
    int64_t n = x->dims[0], c = x->dims[1], h = x->dims[2],
            w = x->dims[3];
    if (groups <= 0 || c % groups != 0) return "bad groups";
    int64_t co = c / groups;
    int64_t hw = h * w;
    HostTensor out = MakeF32({n, co, h, w});
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    for (int64_t b = 0; b < n; ++b) {
      for (int64_t oc = 0; oc < co; ++oc) {
        for (int64_t p = 0; p < hw; ++p) {
          float best = -INFINITY;
          for (int64_t g = 0; g < groups; ++g) {
            best = std::max(
                best, xa[((b * c + oc * groups + g) * hw) + p]);
          }
          oa[(b * co + oc) * hw + p] = best;
        }
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // x.at[ids].set/add(updates) over dim 0 (ops/tensor_ops.py scatter)
  std::string RunScatter(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* idn = OneName(op, "Ids");
    const std::string* un = OneName(op, "Updates");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || idn == nullptr || un == nullptr ||
        on == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* it = scope->Find(*idn);
    const HostTensor* u = scope->Find(*un);
    if (x == nullptr || it == nullptr || u == nullptr) {
      return "input not in scope";
    }
    if (!IsF32(*x) || !IsF32(*u) || x->dims.empty()) return "bad input";
    std::vector<int64_t> ids;
    std::string err = ReadIds(*it, &ids);
    if (!err.empty()) return err;
    int64_t rows = x->dims[0];
    int64_t inner = NumElements(x->dims) / (rows == 0 ? 1 : rows);
    if (NumElements(u->dims) !=
        static_cast<int64_t>(ids.size()) * inner) {
      return "updates shape mismatch";
    }
    bool overwrite = IntAttr(op, "overwrite", 1) != 0;
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    std::copy(xa, xa + NumElements(x->dims), oa);
    const float* ua = F32(*u);
    for (size_t i = 0; i < ids.size(); ++i) {
      int64_t r = ids[i];
      if (r < 0 || r >= rows) return "scatter index out of range";
      for (int64_t j = 0; j < inner; ++j) {
        if (overwrite) {
          oa[r * inner + j] = ua[i * inner + j];
        } else {
          oa[r * inner + j] += ua[i * inner + j];
        }
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // jnp.argmax/argmin along attr axis, int64 out (first max on ties)
  std::string RunArgMax(const OpDesc& op, Scope* scope, bool is_min) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x) || x->dims.empty()) return "bad input";
    size_t rank = x->dims.size();
    int64_t axis = IntAttr(op, "axis", 0);
    if (axis < 0) axis += rank;
    if (axis < 0 || axis >= static_cast<int64_t>(rank)) {
      return "axis out of range";
    }
    int64_t len = x->dims[axis];
    if (len <= 0) return "empty axis";
    int64_t inner = 1;
    for (size_t d = axis + 1; d < rank; ++d) inner *= x->dims[d];
    int64_t outer = NumElements(x->dims) / (len * inner);
    std::vector<int64_t> odims;
    for (size_t d = 0; d < rank; ++d) {
      if (static_cast<int64_t>(d) != axis) odims.push_back(x->dims[d]);
    }
    if (odims.empty()) {
      // the XLA lowering returns a rank-0 scalar here; refuse rather
      // than silently emitting a different shape
      return "scalar (rank-0) output unsupported";
    }
    HostTensor out;
    out.dtype = "int64";
    out.dims = odims;
    out.data.resize(NumElements(odims) * sizeof(int64_t));
    int64_t* oa = reinterpret_cast<int64_t*>(out.data.data());
    const float* xa = F32(*x);
    for (int64_t o = 0; o < outer; ++o) {
      for (int64_t in2 = 0; in2 < inner; ++in2) {
        const float* base = xa + o * len * inner + in2;
        int64_t best = 0;
        float bv = base[0];
        for (int64_t p = 1; p < len; ++p) {
          float v = base[p * inner];
          // numpy/jnp argmax+argmin both propagate NaN: the FIRST NaN
          // wins over any number (a plain comparison would skip NaNs)
          bool take;
          if (std::isnan(bv)) {
            take = false;
          } else if (std::isnan(v)) {
            take = true;
          } else {
            take = is_min ? v < bv : v > bv;
          }
          if (take) {
            bv = v;
            best = p;
          }
        }
        oa[o * inner + in2] = best;
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunAssign(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    HostTensor out = *x;  // value copy, any dtype
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunFillZerosLike(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x)) return "non-f32 dtype";
    HostTensor out = MakeF32(x->dims);
    float* oa = MutF32(&out);
    std::fill(oa, oa + NumElements(x->dims), 0.0f);
    scope->Set(*on, std::move(out));
    return "";
  }

  // int32 shape vector (ops/tensor_ops.py shape)
  std::string RunShapeOp(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "Input");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    HostTensor out;
    out.dtype = "int32";
    out.dims = {static_cast<int64_t>(x->dims.size())};
    out.data.resize(x->dims.size() * sizeof(int32_t));
    int32_t* oa = reinterpret_cast<int32_t*>(out.data.data());
    for (size_t d = 0; d < x->dims.size(); ++d) {
      oa[d] = static_cast<int32_t>(x->dims[d]);
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // prelu modes all/channel/element (ops/activation_ops.py)
  std::string RunPrelu(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* an = OneName(op, "Alpha");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || an == nullptr || on == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* a = scope->Find(*an);
    if (x == nullptr || a == nullptr) return "input not in scope";
    if (!IsF32(*x) || !IsF32(*a)) return "non-f32 dtype";
    std::string mode = StrAttr(op, "mode", "all");
    int64_t n = NumElements(x->dims);
    int64_t na = NumElements(a->dims);
    int64_t chans = x->dims.size() > 1 ? x->dims[1] : 1;
    int64_t inner = 1;
    for (size_t d = 2; d < x->dims.size(); ++d) inner *= x->dims[d];
    int64_t batch = x->dims.empty() ? 1 : x->dims[0];
    int64_t per_sample = n / (batch == 0 ? 1 : batch);
    if (mode == "all") {
      if (na != 1) return "alpha size";
    } else if (mode == "channel") {
      if (na != chans) return "alpha size";
    } else if (mode == "element") {
      // one alpha per non-batch element, broadcast over the batch
      // (the layer creates Alpha with shape x.shape[1:])
      if (na != per_sample) return "alpha size";
    } else {
      return "unknown mode";
    }
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    const float* aa = F32(*a);
    float* oa = MutF32(&out);
    for (int64_t i = 0; i < n; ++i) {
      float v = xa[i];
      float alpha;
      if (mode == "all") {
        alpha = aa[0];
      } else if (mode == "channel") {
        alpha = aa[(i / inner) % chans];
      } else {
        alpha = aa[i % per_sample];
      }
      oa[i] = v >= 0.0f ? v : alpha * v;
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // python slice semantics per axis (ops/tensor_ops.py _lower_slice):
  // negative starts/ends wrap, then clamp to [0, dim]
  std::string RunSlice(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "Input");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x)) return "non-f32 dtype";
    auto axes = IntsAttr(op, "axes", {});
    auto starts = IntsAttr(op, "starts", {});
    auto ends = IntsAttr(op, "ends", {});
    if (axes.size() != starts.size() || axes.size() != ends.size()) {
      return "bad slice attrs";
    }
    if (x->dims.empty()) return "rank-0 input";
    size_t rank = x->dims.size();
    std::vector<int64_t> lo(rank, 0), hi = x->dims;
    for (size_t i = 0; i < axes.size(); ++i) {
      int64_t ax = axes[i];
      if (ax < 0) ax += rank;
      if (ax < 0 || ax >= static_cast<int64_t>(rank)) {
        return "slice axis out of range";
      }
      int64_t d = x->dims[ax];
      int64_t st = starts[i] < 0 ? starts[i] + d : starts[i];
      int64_t en = ends[i] < 0 ? ends[i] + d : ends[i];
      lo[ax] = std::min(std::max<int64_t>(st, 0), d);
      hi[ax] = std::min(std::max<int64_t>(en, 0), d);
      if (hi[ax] <= lo[ax]) return "empty slice";
    }
    std::vector<int64_t> odims(rank);
    for (size_t d = 0; d < rank; ++d) odims[d] = hi[d] - lo[d];
    HostTensor out = MakeF32(odims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    std::vector<int64_t> xstride(rank, 1);
    for (size_t d = rank - 1; d > 0; --d) {
      xstride[d - 1] = xstride[d] * x->dims[d];
    }
    std::vector<int64_t> idx(rank, 0);
    int64_t total = NumElements(odims);
    for (int64_t i = 0; i < total; ++i) {
      int64_t src = 0;
      for (size_t d = 0; d < rank; ++d) src += (lo[d] + idx[d]) * xstride[d];
      oa[i] = xa[src];
      for (size_t d = rank; d-- > 0;) {
        if (++idx[d] < odims[d]) break;
        idx[d] = 0;
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // rows of X at Index along dim 0 (jnp.take axis=0)
  std::string RunGather(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* in = OneName(op, "Index");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || in == nullptr || on == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* it = scope->Find(*in);
    if (x == nullptr || it == nullptr) return "input not in scope";
    if (!IsF32(*x) || x->dims.empty()) return "bad input";
    std::vector<int64_t> ids;
    std::string err = ReadIds(*it, &ids);
    if (!err.empty()) return err;
    int64_t rows = x->dims[0];
    int64_t inner = NumElements(x->dims) / (rows == 0 ? 1 : rows);
    std::vector<int64_t> odims = it->dims;
    // a trailing singleton index dim gathers whole rows, like take
    // over flat ids then reshape
    for (size_t d = 1; d < x->dims.size(); ++d) odims.push_back(x->dims[d]);
    HostTensor out = MakeF32(odims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    for (size_t i = 0; i < ids.size(); ++i) {
      int64_t r = ids[i];
      if (r < 0 || r >= rows) return "gather index out of range";
      std::copy(xa + r * inner, xa + (r + 1) * inner, oa + i * inner);
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // jnp.stack over the variadic X inputs at attr axis
  std::string RunStack(const OpDesc& op, Scope* scope) {
    auto it = op.inputs.find("X");
    const std::string* on = OneName(op, "Y", false);
    if (it == op.inputs.end() || it->second.empty() || on == nullptr) {
      return "missing io";
    }
    std::vector<const HostTensor*> xs;
    for (const std::string& nme : it->second) {
      const HostTensor* t = scope->Find(nme);
      if (t == nullptr) return "input not in scope";
      if (!IsF32(*t)) return "non-f32 dtype";
      if (!xs.empty() && t->dims != xs[0]->dims) return "shape mismatch";
      xs.push_back(t);
    }
    int64_t k = static_cast<int64_t>(xs.size());
    int64_t rank = static_cast<int64_t>(xs[0]->dims.size());
    int64_t axis = IntAttr(op, "axis", 0);
    if (axis < 0) axis += rank + 1;
    if (axis < 0 || axis > rank) return "axis out of range";
    std::vector<int64_t> odims = xs[0]->dims;
    odims.insert(odims.begin() + axis, k);
    int64_t outer = 1, inner = 1;
    for (int64_t d = 0; d < axis; ++d) outer *= xs[0]->dims[d];
    for (int64_t d = axis; d < rank; ++d) inner *= xs[0]->dims[d];
    HostTensor out = MakeF32(odims);
    float* oa = MutF32(&out);
    for (int64_t o = 0; o < outer; ++o) {
      for (int64_t j = 0; j < k; ++j) {
        const float* src = F32(*xs[j]) + o * inner;
        std::copy(src, src + inner, oa + (o * k + j) * inner);
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // jnp.pad with constant value; paddings attr is [lo0, hi0, lo1, ...]
  std::string RunPad(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x)) return "non-f32 dtype";
    auto pads = IntsAttr(op, "paddings", {});
    if (x->dims.empty()) return "rank-0 input";
    size_t rank = x->dims.size();
    if (pads.size() != 2 * rank) return "bad paddings";
    for (int64_t p : pads) {
      if (p < 0) return "negative padding";
    }
    float value = FloatAttr(op, "pad_value", 0.0f);
    std::vector<int64_t> odims(rank);
    for (size_t d = 0; d < rank; ++d) {
      odims[d] = x->dims[d] + pads[2 * d] + pads[2 * d + 1];
    }
    HostTensor out = MakeF32(odims);
    float* oa = MutF32(&out);
    int64_t total = NumElements(odims);
    std::fill(oa, oa + total, value);
    std::vector<int64_t> xstride(rank, 1), ostride(rank, 1);
    for (size_t d = rank - 1; d > 0; --d) {
      xstride[d - 1] = xstride[d] * x->dims[d];
      ostride[d - 1] = ostride[d] * odims[d];
    }
    const float* xa = F32(*x);
    std::vector<int64_t> idx(rank, 0);
    int64_t nin = NumElements(x->dims);
    for (int64_t i = 0; i < nin; ++i) {
      int64_t dst = 0;
      for (size_t d = 0; d < rank; ++d) {
        dst += (idx[d] + pads[2 * d]) * ostride[d];
      }
      oa[dst] = xa[i];
      for (size_t d = rank; d-- > 0;) {
        if (++idx[d] < x->dims[d]) break;
        idx[d] = 0;
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // jax.nn.one_hot over int ids (trailing singleton id dim squeezed,
  // like lookup_table); out-of-range ids produce all-zero rows
  std::string RunOneHot(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    std::vector<int64_t> ids;
    std::string err = ReadIds(*x, &ids);
    if (!err.empty()) return err;
    int64_t depth = IntAttr(op, "depth", 1);
    if (depth <= 0) return "bad depth";
    std::vector<int64_t> odims = x->dims;
    if (odims.size() > 1 && odims.back() == 1) odims.pop_back();
    odims.push_back(depth);
    HostTensor out = MakeF32(odims);
    float* oa = MutF32(&out);
    std::fill(oa, oa + NumElements(odims), 0.0f);
    for (size_t i = 0; i < ids.size(); ++i) {
      if (ids[i] >= 0 && ids[i] < depth) oa[i * depth + ids[i]] = 1.0f;
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // jnp.matmul with transpose_X/transpose_Y/alpha (ops/math_ops.py):
  // rank 2 or batched rank 3 (3x3 with equal batch, or 3x2 / 2x3
  // numpy-style broadcast of the rank-2 side)
  std::string RunMatmul(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* yn = OneName(op, "Y");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || yn == nullptr || on == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* y = scope->Find(*yn);
    if (x == nullptr || y == nullptr) return "input not in scope";
    if (!IsF32(*x) || !IsF32(*y)) return "non-f32 dtype";
    size_t rx = x->dims.size(), ry = y->dims.size();
    if (rx < 2 || rx > 3 || ry < 2 || ry > 3) return "rank unsupported";
    bool tx = IntAttr(op, "transpose_X", 0) != 0;
    bool ty = IntAttr(op, "transpose_Y", 0) != 0;
    float alpha = FloatAttr(op, "alpha", 1.0f);
    int64_t bx = rx == 3 ? x->dims[0] : 1;
    int64_t by = ry == 3 ? y->dims[0] : 1;
    if (bx != by && bx != 1 && by != 1) return "batch mismatch";
    int64_t batch = std::max(bx, by);
    int64_t xr = x->dims[rx - 2], xc = x->dims[rx - 1];
    int64_t yr = y->dims[ry - 2], yc = y->dims[ry - 1];
    int64_t m = tx ? xc : xr, kx = tx ? xr : xc;
    int64_t ky = ty ? yc : yr, nn = ty ? yr : yc;
    if (kx != ky) return "contraction mismatch";
    std::vector<int64_t> odims;
    if (rx == 3 || ry == 3) odims.push_back(batch);
    odims.push_back(m);
    odims.push_back(nn);
    HostTensor out = MakeF32(odims);
    float* oa = MutF32(&out);
    const float* xa = F32(*x);
    const float* ya = F32(*y);
    for (int64_t b = 0; b < batch; ++b) {
      const float* xb = xa + (bx == 1 ? 0 : b) * xr * xc;
      const float* yb = ya + (by == 1 ? 0 : b) * yr * yc;
      float* ob = oa + (rx == 3 || ry == 3 ? b : 0) * m * nn;
      for (int64_t i = 0; i < m; ++i) {
        for (int64_t j = 0; j < nn; ++j) {
          float acc = 0.0f;
          for (int64_t t = 0; t < kx; ++t) {
            float xv = tx ? xb[t * xc + i] : xb[i * xc + t];
            float yv = ty ? yb[j * yc + t] : yb[t * yc + j];
            acc += xv * yv;
          }
          ob[i * nn + j] = alpha * acc;
        }
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunClip(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x)) return "non-f32 dtype";
    float lo = FloatAttr(op, "min", 0.0f);
    float hi = FloatAttr(op, "max", 0.0f);
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    int64_t n = NumElements(x->dims);
    for (int64_t i = 0; i < n; ++i) {
      oa[i] = std::min(std::max(xa[i], lo), hi);
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // cumsum along axis with exclusive/reverse (ops/math_ops.py _cumsum)
  std::string RunCumsum(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x)) return "non-f32 dtype";
    size_t rank = x->dims.size();
    int64_t axis = IntAttr(op, "axis", -1);
    if (axis < 0) axis += rank;
    if (axis < 0 || axis >= static_cast<int64_t>(rank)) {
      return "axis out of range";
    }
    bool exclusive = IntAttr(op, "exclusive", 0) != 0;
    bool reverse = IntAttr(op, "reverse", 0) != 0;
    int64_t len = x->dims[axis];
    int64_t inner = 1;
    for (size_t d = axis + 1; d < rank; ++d) inner *= x->dims[d];
    int64_t outer = NumElements(x->dims) / (len * inner == 0 ? 1 : len * inner);
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    for (int64_t o = 0; o < outer; ++o) {
      for (int64_t in2 = 0; in2 < inner; ++in2) {
        const float* base = xa + o * len * inner + in2;
        float* ob = oa + o * len * inner + in2;
        float acc = 0.0f;
        for (int64_t p = 0; p < len; ++p) {
          int64_t q = reverse ? len - 1 - p : p;
          float v = base[q * inner];
          if (exclusive) {
            ob[q * inner] = acc;
            acc += v;
          } else {
            acc += v;
            ob[q * inner] = acc;
          }
        }
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // np.tile semantics (ops/tensor_ops.py expand): repeat each dim by
  // expand_times; a times vector longer than the input rank prepends
  // broadcast dims (numpy tile rule)
  std::string RunExpand(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x)) return "non-f32 dtype";
    auto times = IntsAttr(op, "expand_times", {});
    if (times.empty()) return "empty expand_times";
    for (int64_t t : times) {
      if (t <= 0) return "bad expand_times";
    }
    std::vector<int64_t> in_dims = x->dims;
    while (in_dims.size() < times.size()) {
      in_dims.insert(in_dims.begin(), 1);
    }
    while (times.size() < in_dims.size()) {
      times.insert(times.begin(), 1);
    }
    size_t rank = in_dims.size();
    std::vector<int64_t> out_dims(rank);
    for (size_t d = 0; d < rank; ++d) out_dims[d] = in_dims[d] * times[d];
    HostTensor out = MakeF32(out_dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    int64_t total = NumElements(out_dims);
    std::vector<int64_t> in_strides(rank, 1);
    for (size_t d = rank - 1; d > 0; --d) {
      in_strides[d - 1] = in_strides[d] * in_dims[d];
    }
    std::vector<int64_t> idx(rank, 0);
    for (int64_t i = 0; i < total; ++i) {
      int64_t src = 0;
      for (size_t d = 0; d < rank; ++d) {
        src += (idx[d] % in_dims[d]) * in_strides[d];
      }
      oa[i] = xa[src];
      for (size_t d = rank; d-- > 0;) {
        if (++idx[d] < out_dims[d]) break;
        idx[d] = 0;
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  // Switch-style MoE FFN (ops/moe_ops.py _lower_moe_ffn): softmax
  // router, top-k routing with per-expert capacity queues advanced in
  // token order (over-capacity routes dropped but still advancing the
  // queue, exactly like the XLA einsum formulation), GShard gate
  // renormalization by the SELECTED raw gates, expert FFNs, and the
  // Switch load-balancing aux loss over pre-drop top-1 assignments.
  std::string RunMoeFFN(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* gwn = OneName(op, "GateW");
    const std::string* w1n = OneName(op, "ExpertW1");
    const std::string* b1n = OneName(op, "ExpertB1");
    const std::string* w2n = OneName(op, "ExpertW2");
    const std::string* b2n = OneName(op, "ExpertB2");
    const std::string* on = OneName(op, "Out", false);
    const std::string* auxn = OneName(op, "AuxLoss", false);
    if (xn == nullptr || gwn == nullptr || w1n == nullptr ||
        b1n == nullptr || w2n == nullptr || b2n == nullptr ||
        on == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* gw = scope->Find(*gwn);
    const HostTensor* w1 = scope->Find(*w1n);
    const HostTensor* b1 = scope->Find(*b1n);
    const HostTensor* w2 = scope->Find(*w2n);
    const HostTensor* b2 = scope->Find(*b2n);
    if (x == nullptr || gw == nullptr || w1 == nullptr ||
        b1 == nullptr || w2 == nullptr || b2 == nullptr) {
      return "input not in scope";
    }
    for (const HostTensor* t : {x, gw, w1, b1, w2, b2}) {
      if (!IsF32(*t)) return "non-f32 dtype";
    }
    if (gw->dims.size() != 2 || w1->dims.size() != 3 ||
        w2->dims.size() != 3 || x->dims.empty()) {
      return "bad ranks";
    }
    int64_t d = x->dims.back();
    int64_t n = NumElements(x->dims) / (d == 0 ? 1 : d);
    int64_t e = gw->dims[1];
    int64_t hdim = w1->dims[2];
    if (gw->dims[0] != d || w1->dims[0] != e || w1->dims[1] != d ||
        w2->dims[0] != e || w2->dims[1] != hdim || w2->dims[2] != d ||
        NumElements(b1->dims) != e * hdim ||
        NumElements(b2->dims) != e * d) {
      return "weight shape mismatch";
    }
    int64_t top_k = IntAttr(op, "top_k", 1);
    float cap_factor = FloatAttr(op, "capacity_factor", 1.25f);
    std::string act = StrAttr(op, "act", "gelu");
    if (act != "gelu" && act != "relu" && act != "sigmoid" &&
        act != "tanh" && act != "identity") {
      return "unsupported activation";
    }
    if (top_k < 1) return "bad top_k";
    // double arithmetic to truncate on the same integer as the Python
    // reference's int(cap_factor * n * top_k / e) — f32 rounding can
    // land a fractional boundary on a different side
    int64_t capacity = std::max<int64_t>(
        1, static_cast<int64_t>(
               static_cast<double>(cap_factor) * static_cast<double>(n) *
               static_cast<double>(top_k) / static_cast<double>(e)));

    // optional [B, T] token validity
    std::vector<float> valid(n, 1.0f);
    bool has_mask = false;
    // NB: OneName's third arg selects inputs-vs-outputs, NOT
    // optionality — Mask is an (optional) INPUT
    const std::string* mn = OneName(op, "Mask");
    if (mn != nullptr) {
      const HostTensor* m = scope->Find(*mn);
      if (m == nullptr) return "mask not in scope";
      if (!IsF32(*m) || NumElements(m->dims) != n) return "bad mask";
      const float* ma = F32(*m);
      for (int64_t i = 0; i < n; ++i) valid[i] = ma[i] > 0 ? 1.f : 0.f;
      has_mask = true;
    }

    const float* xa = F32(*x);
    const float* ga = F32(*gw);
    // router probs [N, E]
    std::vector<float> probs(n * e);
    for (int64_t i = 0; i < n; ++i) {
      float mx = -INFINITY;
      for (int64_t j = 0; j < e; ++j) {
        float acc = 0.0f;
        for (int64_t t = 0; t < d; ++t) acc += xa[i * d + t] * ga[t * e + j];
        probs[i * e + j] = acc;
        mx = std::max(mx, acc);
      }
      float denom = 0.0f;
      for (int64_t j = 0; j < e; ++j) {
        probs[i * e + j] = std::exp(probs[i * e + j] - mx);
        denom += probs[i * e + j];
      }
      for (int64_t j = 0; j < e; ++j) {
        probs[i * e + j] = probs[i * e + j] / denom * valid[i];
      }
    }

    // top-k routing with capacity queues in token order
    std::vector<float> kept_gate(n * top_k, 0.0f);
    std::vector<float> raw_gate(n * top_k, 0.0f);
    std::vector<int64_t> route(n * top_k, -1);
    std::vector<uint8_t> used(n * e, 0);
    std::vector<int64_t> queue(e, 0);
    for (int64_t r = 0; r < top_k; ++r) {
      for (int64_t i = 0; i < n; ++i) {
        int64_t best = 0;
        float bv = -INFINITY;
        for (int64_t j = 0; j < e; ++j) {
          float v = used[i * e + j] ? 0.0f : probs[i * e + j];
          if (v > bv) {
            bv = v;
            best = j;
          }
        }
        used[i * e + best] = 1;
        raw_gate[i * top_k + r] = bv;
        if (valid[i] <= 0.0f) continue;  // no queue slot, no output
        int64_t pos = queue[best]++;
        if (pos < capacity) {
          route[i * top_k + r] = best;
          kept_gate[i * top_k + r] = bv;
        }
      }
    }
    if (top_k > 1) {
      for (int64_t i = 0; i < n; ++i) {
        float total = 1e-9f;
        for (int64_t r = 0; r < top_k; ++r) {
          total += raw_gate[i * top_k + r];
        }
        for (int64_t r = 0; r < top_k; ++r) {
          kept_gate[i * top_k + r] /= total;
        }
      }
    }

    const float* w1a = F32(*w1);
    const float* b1a = F32(*b1);
    const float* w2a = F32(*w2);
    const float* b2a = F32(*b2);
    HostTensor out = MakeF32(x->dims);
    float* oa = MutF32(&out);
    std::fill(oa, oa + n * d, 0.0f);
    std::vector<float> h(hdim);
    for (int64_t i = 0; i < n; ++i) {
      for (int64_t r = 0; r < top_k; ++r) {
        int64_t ex = route[i * top_k + r];
        float g = kept_gate[i * top_k + r];
        if (ex < 0 || g == 0.0f) continue;
        const float* ew1 = w1a + ex * d * hdim;
        const float* eb1 = b1a + ex * hdim;
        const float* ew2 = w2a + ex * hdim * d;
        const float* eb2 = b2a + ex * d;
        for (int64_t j = 0; j < hdim; ++j) {
          float acc = eb1[j];
          for (int64_t t = 0; t < d; ++t) {
            acc += xa[i * d + t] * ew1[t * hdim + j];
          }
          if (act == "relu") {
            acc = std::max(acc, 0.0f);
          } else if (act == "sigmoid") {
            acc = 1.0f / (1.0f + std::exp(-acc));
          } else if (act == "tanh") {
            acc = std::tanh(acc);
          } else if (act == "gelu") {
            // jax.nn.gelu default (approximate=True, tanh form)
            float c = 0.7978845608028654f;  // sqrt(2/pi)
            acc = 0.5f * acc *
                  (1.0f + std::tanh(c * (acc + 0.044715f * acc * acc * acc)));
          }
          h[j] = acc;
        }
        for (int64_t t = 0; t < d; ++t) {
          float acc = eb2[t];
          for (int64_t j = 0; j < hdim; ++j) {
            acc += h[j] * ew2[j * d + t];
          }
          oa[i * d + t] += g * acc;
        }
      }
    }
    scope->Set(*on, std::move(out));

    if (auxn != nullptr) {
      // E * sum_e f_e * P_e over pre-drop top-1 assignments
      std::vector<double> f(e, 0.0), p(e, 0.0);
      double denom = 0.0;
      for (int64_t i = 0; i < n; ++i) {
        denom += valid[i];
        if (has_mask && valid[i] <= 0.0f) continue;
        int64_t best = 0;
        float bv = -INFINITY;
        for (int64_t j = 0; j < e; ++j) {
          if (probs[i * e + j] > bv) {
            bv = probs[i * e + j];
            best = j;
          }
        }
        f[best] += 1.0;
        for (int64_t j = 0; j < e; ++j) p[j] += probs[i * e + j];
      }
      if (!has_mask) denom = static_cast<double>(n);
      denom = std::max(denom, 1.0);
      double aux = 0.0;
      for (int64_t j = 0; j < e; ++j) {
        aux += (f[j] / denom) * (p[j] / denom);
      }
      HostTensor at = MakeF32({1});
      MutF32(&at)[0] = static_cast<float>(aux * static_cast<double>(e));
      scope->Set(*auxn, std::move(at));
    }
    return "";
  }

  // Box-Muller over the uniform_random seed discipline (seed 0 mixes
  // the output name so same-shape params get distinct streams)
  std::string RunGaussianRandom(const OpDesc& op, Scope* scope) {
    const std::string* on = OneName(op, "Out", false);
    if (on == nullptr) return "missing io";
    HostTensor out = MakeF32(IntsAttr(op, "shape", {1}));
    float mean = FloatAttr(op, "mean", 0.0f);
    float stddev = FloatAttr(op, "std", 1.0f);
    uint64_t seed = static_cast<uint64_t>(IntAttr(op, "seed", 0));
    if (seed == 0) {
      seed = std::hash<std::string>{}(*on) | 1;
    }
    XorShiftRng rng(seed);
    float* oa = MutF32(&out);
    int64_t n = NumElements(out.dims);
    for (int64_t i = 0; i < n; i += 2) {
      float u1 = rng.uniform();
      float u2 = rng.uniform();
      if (u1 < 1e-12f) u1 = 1e-12f;
      float mag = std::sqrt(-2.0f * std::log(u1));
      oa[i] = mean + stddev * mag * std::cos(6.28318530718f * u2);
      if (i + 1 < n) {
        oa[i + 1] = mean + stddev * mag * std::sin(6.28318530718f * u2);
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  const ProgramDesc& prog_;
};

}  // namespace interp

}  // namespace ptpu
