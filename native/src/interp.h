#pragma once
// Minimal C++ CPU reference interpreter over PTPB programs.
//
// Reference parity: the NaiveExecutor + CPU-kernel path that backs the
// reference's C++ predictor (framework/naive_executor.cc,
// inference/api/api_impl.cc) and its "C++-only train/infer demo"
// (train/demo/demo_trainer.cc). On TPU the production inference path is
// the XLA-compiled executable; this interpreter is the host-side reference
// implementation used to (a) prove the C++ runtime can execute the IR end
// to end without Python and (b) cross-check XLA lowerings from C++ parity
// tests (SURVEY.md §2.9 item 7). Float32, core op subset; unsupported ops
// report an error rather than mis-executing.

#include <cmath>
#include <cstdint>
#include <functional>
#include <map>
#include <string>
#include <vector>

#include "program.h"
#include "scope.h"

namespace ptpu {

namespace interp {

inline int64_t NumElements(const std::vector<int64_t>& dims) {
  int64_t n = 1;
  for (int64_t d : dims) n *= d;
  return n;
}

inline const float* F32(const HostTensor& t) {
  return reinterpret_cast<const float*>(t.data.data());
}

inline bool IsF32(const HostTensor& t) { return t.dtype == "float32"; }

inline HostTensor MakeF32(std::vector<int64_t> dims) {
  HostTensor t;
  t.dtype = "float32";
  t.dims = std::move(dims);
  t.data.resize(NumElements(t.dims) * sizeof(float));
  return t;
}

inline float* MutF32(HostTensor* t) {
  return reinterpret_cast<float*>(t->data.data());
}

// Fetches the single input bound to `slot` (empty-name entries skipped).
inline const std::string* OneName(const OpDesc& op, const std::string& slot,
                                  bool input = true) {
  const auto& io = input ? op.inputs : op.outputs;
  auto it = io.find(slot);
  if (it == io.end()) return nullptr;
  for (const std::string& n : it->second) {
    if (!n.empty()) return &n;
  }
  return nullptr;
}

class Interpreter {
 public:
  explicit Interpreter(const ProgramDesc& prog) : prog_(prog) {}

  // Runs every op of `block` against `scope`. Returns "" on success or an
  // error description.
  std::string Run(int32_t block_idx, Scope* scope) {
    if (block_idx < 0 ||
        block_idx >= static_cast<int32_t>(prog_.blocks.size())) {
      return "bad block index";
    }
    for (const OpDesc& op : prog_.blocks[block_idx].ops) {
      std::string err = RunOp(op, scope);
      if (!err.empty()) return "op " + op.type + ": " + err;
    }
    return "";
  }

 private:
  std::string RunOp(const OpDesc& op, Scope* scope) {
    if (op.type == "feed" || op.type == "fetch") return "";  // host-managed
    if (op.type == "mul") return RunMul(op, scope);
    if (op.type == "elementwise_add") return RunAdd(op, scope);
    if (op.type == "relu") return RunUnary(op, scope, [](float v) {
      return v > 0.0f ? v : 0.0f;
    });
    if (op.type == "sigmoid") return RunUnary(op, scope, [](float v) {
      return 1.0f / (1.0f + std::exp(-v));
    });
    if (op.type == "tanh") return RunUnary(op, scope, [](float v) {
      return std::tanh(v);
    });
    if (op.type == "scale") {
      float s = 1.0f, b = 0.0f;
      auto it = op.attrs.find("scale");
      if (it != op.attrs.end()) {
        s = it->second.tag == AttrValue::kFloat
                ? static_cast<float>(it->second.f)
                : static_cast<float>(it->second.i);
      }
      it = op.attrs.find("bias");
      if (it != op.attrs.end()) {
        b = it->second.tag == AttrValue::kFloat
                ? static_cast<float>(it->second.f)
                : static_cast<float>(it->second.i);
      }
      return RunUnary(op, scope, [s, b](float v) { return s * v + b; });
    }
    if (op.type == "softmax") return RunSoftmax(op, scope);
    return "unsupported op type";
  }

  std::string RunMul(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* yn = OneName(op, "Y");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || yn == nullptr || on == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* y = scope->Find(*yn);
    if (x == nullptr || y == nullptr) return "input not in scope";
    if (!IsF32(*x) || !IsF32(*y)) return "non-f32 dtype";
    // x_num_col_dims semantics: flatten x to [rows, K], y to [K, cols].
    int64_t xcol = 1;
    auto it = op.attrs.find("x_num_col_dims");
    if (it != op.attrs.end()) xcol = it->second.i;
    int64_t rows = 1, k = 1;
    for (size_t d = 0; d < x->dims.size(); ++d) {
      (static_cast<int64_t>(d) < xcol ? rows : k) *= x->dims[d];
    }
    int64_t k2 = y->dims.empty() ? 1 : y->dims[0];
    int64_t cols = NumElements(y->dims) / (k2 == 0 ? 1 : k2);
    if (k != k2) return "shape mismatch";
    std::vector<int64_t> odims(x->dims.begin(), x->dims.begin() + xcol);
    odims.push_back(cols);
    HostTensor out = MakeF32(odims);
    const float* xa = F32(*x);
    const float* ya = F32(*y);
    float* oa = MutF32(&out);
    for (int64_t i = 0; i < rows; ++i) {
      for (int64_t j = 0; j < cols; ++j) {
        float acc = 0.0f;
        for (int64_t t = 0; t < k; ++t) {
          acc += xa[i * k + t] * ya[t * cols + j];
        }
        oa[i * cols + j] = acc;
      }
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunAdd(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* yn = OneName(op, "Y");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || yn == nullptr || on == nullptr) {
      return "missing io";
    }
    const HostTensor* x = scope->Find(*xn);
    const HostTensor* y = scope->Find(*yn);
    if (x == nullptr || y == nullptr) return "input not in scope";
    if (!IsF32(*x) || !IsF32(*y)) return "non-f32 dtype";
    // Only trailing-dim broadcast is implemented; any other axis must be
    // rejected, not mis-executed.
    auto ax_it = op.attrs.find("axis");
    if (ax_it != op.attrs.end() && ax_it->second.tag == AttrValue::kInt) {
      int64_t ax = ax_it->second.i;
      int64_t trailing = static_cast<int64_t>(x->dims.size()) -
                         static_cast<int64_t>(y->dims.size());
      if (ax != -1 && ax != trailing) return "non-trailing broadcast axis";
    }
    int64_t nx = NumElements(x->dims);
    int64_t ny = NumElements(y->dims);
    if (ny == 0 || nx % ny != 0) return "broadcast mismatch";
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    const float* ya = F32(*y);
    float* oa = MutF32(&out);
    // Trailing-dim broadcast (bias add): y repeats every ny elements.
    for (int64_t i = 0; i < nx; ++i) oa[i] = xa[i] + ya[i % ny];
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunUnary(const OpDesc& op, Scope* scope,
                       const std::function<float(float)>& fn) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x)) return "non-f32 dtype";
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    int64_t n = NumElements(x->dims);
    for (int64_t i = 0; i < n; ++i) oa[i] = fn(xa[i]);
    scope->Set(*on, std::move(out));
    return "";
  }

  std::string RunSoftmax(const OpDesc& op, Scope* scope) {
    const std::string* xn = OneName(op, "X");
    const std::string* on = OneName(op, "Out", false);
    if (xn == nullptr || on == nullptr) return "missing io";
    const HostTensor* x = scope->Find(*xn);
    if (x == nullptr) return "input not in scope";
    if (!IsF32(*x)) return "non-f32 dtype";
    if (x->dims.empty()) return "scalar softmax";
    int64_t cols = x->dims.back();
    int64_t rows = NumElements(x->dims) / cols;
    HostTensor out = MakeF32(x->dims);
    const float* xa = F32(*x);
    float* oa = MutF32(&out);
    for (int64_t i = 0; i < rows; ++i) {
      const float* row = xa + i * cols;
      float* orow = oa + i * cols;
      float mx = row[0];
      for (int64_t j = 1; j < cols; ++j) mx = std::max(mx, row[j]);
      float sum = 0.0f;
      for (int64_t j = 0; j < cols; ++j) {
        orow[j] = std::exp(row[j] - mx);
        sum += orow[j];
      }
      for (int64_t j = 0; j < cols; ++j) orow[j] /= sum;
    }
    scope->Set(*on, std::move(out));
    return "";
  }

  const ProgramDesc& prog_;
};

}  // namespace interp

}  // namespace ptpu
