#pragma once
// Bounded blocking byte-buffer queue: the LoDTensorBlockingQueue /
// BlockingQueue<T> equivalent (framework/blocking_queue.h,
// operators/reader/lod_tensor_blocking_queue.h).
//
// Python feeder threads push serialized batches; the input pipeline pops
// them for device transfer. close() wakes every waiter (the reference's
// queue close-on-epoch-end contract); reopen() resets for the next epoch.

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

namespace ptpu {

class BlockingByteQueue {
 public:
  explicit BlockingByteQueue(uint64_t capacity) : capacity_(capacity) {}

  // 0 ok, -1 closed, -2 timeout.
  int Push(const void* data, uint64_t len, int64_t timeout_ms) {
    std::unique_lock<std::mutex> lk(mu_);
    auto ready = [this] { return closed_ || items_.size() < capacity_; };
    if (!WaitFor(lk, not_full_, ready, timeout_ms)) return -2;
    if (closed_) return -1;
    const uint8_t* p = static_cast<const uint8_t*>(data);
    items_.emplace_back(p, p + len);
    not_empty_.notify_one();
    return 0;
  }

  // >0 popped size, 0 closed-and-drained, -2 timeout, -3 out buffer too
  // small (record stays queued). max_len == 0 peeks the size.
  int64_t Pop(void* out, uint64_t max_len, int64_t timeout_ms) {
    std::unique_lock<std::mutex> lk(mu_);
    auto ready = [this] { return closed_ || !items_.empty(); };
    if (!WaitFor(lk, not_empty_, ready, timeout_ms)) return -2;
    if (items_.empty()) return 0;  // closed and drained
    const std::vector<uint8_t>& front = items_.front();
    int64_t n = static_cast<int64_t>(front.size());
    if (max_len == 0) return n;  // size query
    if (static_cast<uint64_t>(n) > max_len) return -3;
    if (n != 0) std::memcpy(out, front.data(), front.size());
    items_.pop_front();
    not_full_.notify_one();
    return n;
  }

  uint64_t Size() {
    std::lock_guard<std::mutex> lk(mu_);
    return items_.size();
  }
  uint64_t Capacity() const { return capacity_; }

  void Close() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }
  // Abort: close AND discard queued items (BlockingQueue::Kill contract —
  // a reset mid-epoch must not serve stale batches).
  void Kill() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    items_.clear();
    not_empty_.notify_all();
    not_full_.notify_all();
  }
  bool IsClosed() {
    std::lock_guard<std::mutex> lk(mu_);
    return closed_;
  }
  void Reopen() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = false;
    items_.clear();
  }

 private:
  template <typename Pred>
  bool WaitFor(std::unique_lock<std::mutex>& lk, std::condition_variable& cv,
               Pred pred, int64_t timeout_ms) {
    if (timeout_ms < 0) {
      cv.wait(lk, pred);
      return true;
    }
    return cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred);
  }

  const uint64_t capacity_;
  std::mutex mu_;
  std::condition_variable not_empty_;
  std::condition_variable not_full_;
  std::deque<std::vector<uint8_t>> items_;
  bool closed_ = false;
};

}  // namespace ptpu
