// Native elastic data-dispatch master (C++17, POSIX sockets, no deps).
//
// Reference parity: go/master/service.go — SetDataset/partition (:106),
// GetTask lease + timeout (:368), TaskFinished (:411), TaskFailed requeue-
// until-failure-max (:455), snapshot/recover (:166,207). This is the
// native twin of paddle_tpu/distributed/master.py: SAME newline-JSON TCP
// protocol and SAME snapshot schema, so Python MasterClient/task_reader
// workers connect to either implementation unchanged, and either can
// recover the other's snapshot (native-checklist item 12: the reference's
// Go master maps to a C++ coordination service here).
#pragma once

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <deque>
#include <fstream>
#include <list>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "json.h"

namespace ptpu {
namespace master {

struct Task {
  int64_t task_id = 0;
  json::Array chunks;  // opaque descriptors, round-tripped verbatim
  int64_t epoch = 0;
  int64_t num_failures = 0;

  json::Value to_json() const {
    json::Object o;
    o["task_id"] = json::Value(task_id);
    o["chunks"] = json::Value(chunks);
    o["epoch"] = json::Value(epoch);
    o["num_failures"] = json::Value(num_failures);
    return json::Value(std::move(o));
  }

  static Task from_json(const json::Value& v) {
    Task t;
    t.task_id = v["task_id"].as_int();
    t.chunks = v["chunks"].as_array();
    t.epoch = v["epoch"].as_int();
    t.num_failures = v["num_failures"].as_int();
    return t;
  }
};

// Error codes shared with the Python protocol (_Errors in master.py).
inline const char* kPassBefore = "pass_before";
inline const char* kPassAfter = "pass_after";
inline const char* kNoMoreAvailable = "no_more_available";
inline const char* kAllFailed = "all_task_failed";

class MasterService {
 public:
  MasterService(int chunks_per_task, double timeout_s, int failure_max,
                std::string snapshot_path)
      : chunks_per_task_(std::max(1, chunks_per_task)),
        timeout_s_(timeout_s),
        failure_max_(failure_max),
        snapshot_path_(std::move(snapshot_path)) {
    if (!snapshot_path_.empty()) {
      std::ifstream f(snapshot_path_);
      if (f.good()) Recover(f);
    }
  }

  ~MasterService() { Close(); }

  void SetDataset(const json::Array& chunks) {
    std::lock_guard<std::mutex> lk(mu_);
    all_chunks_ = chunks;
    if (todo_.empty() && pending_.empty() && done_.empty()) {
      int64_t id = 0;
      for (size_t i = 0; i < chunks.size();
           i += static_cast<size_t>(chunks_per_task_)) {
        Task t;
        t.task_id = id++;
        size_t end = std::min(chunks.size(),
                              i + static_cast<size_t>(chunks_per_task_));
        t.chunks.assign(chunks.begin() + i, chunks.begin() + end);
        todo_.push_back(std::move(t));
      }
      Snapshot(/*force=*/true);
    }
  }

  // Lease the next task. ok=false -> err holds the protocol error code.
  bool GetTask(int64_t pass_id, Task* out, std::string* err) {
    std::lock_guard<std::mutex> lk(mu_);
    if (pass_id < cur_pass_) {
      *err = kPassBefore;
      return false;
    }
    if (pass_id > cur_pass_) {
      *err = kPassAfter;
      return false;
    }
    if (todo_.empty()) {
      *err = (done_.empty() && pending_.empty()) ? kAllFailed
                                                 : kNoMoreAvailable;
      return false;
    }
    Task t = std::move(todo_.front());
    todo_.pop_front();
    t.epoch += 1;
    *out = t;
    int64_t id = t.task_id;
    pending_[id] = {std::move(t), Clock::now() + ToDuration(timeout_s_)};
    EnsureWatcher();
    Snapshot(false);
    return true;
  }

  bool TaskFinished(int64_t task_id) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = pending_.find(task_id);
    if (it == pending_.end()) return false;
    done_.push_back(std::move(it->second.task));
    pending_.erase(it);
    bool rolled = false;
    if (todo_.empty() && pending_.empty()) {
      NextPass();
      rolled = true;
    }
    Snapshot(rolled);
    return true;
  }

  bool TaskFailed(int64_t task_id, const json::Value& epoch) {
    std::lock_guard<std::mutex> lk(mu_);
    return TaskFailedLocked(task_id, epoch);
  }

  json::Value Status() {
    std::lock_guard<std::mutex> lk(mu_);
    json::Object o;
    o["todo"] = json::Value(todo_.size());
    o["pending"] = json::Value(pending_.size());
    o["done"] = json::Value(done_.size());
    o["failed"] = json::Value(failed_.size());
    o["cur_pass"] = json::Value(cur_pass_);
    return json::Value(std::move(o));
  }

  // One request -> one response (the JSON-lines dispatch table; mirrors
  // MasterService._dispatch in master.py).
  json::Value Dispatch(const json::Value& req) {
    const std::string& method = req["method"].as_string();
    json::Object resp;
    if (method == "get_task") {
      Task t;
      std::string err;
      if (GetTask(req["pass_id"].as_int(0), &t, &err)) {
        resp["ok"] = json::Value(true);
        resp["task"] = t.to_json();
      } else {
        resp["ok"] = json::Value(false);
        resp["error"] = json::Value(err);
      }
    } else if (method == "task_finished") {
      resp["ok"] = json::Value(TaskFinished(req["task_id"].as_int()));
    } else if (method == "task_failed") {
      resp["ok"] =
          json::Value(TaskFailed(req["task_id"].as_int(), req["epoch"]));
    } else if (method == "set_dataset") {
      SetDataset(req["chunks"].as_array());
      resp["ok"] = json::Value(true);
    } else if (method == "status") {
      resp["ok"] = json::Value(true);
      resp["status"] = Status();
    } else {
      resp["ok"] = json::Value(false);
      resp["error"] = json::Value("unknown method '" + method + "'");
    }
    return json::Value(std::move(resp));
  }

  // Start the TCP endpoint; returns the bound port (0 on failure).
  int Serve(const std::string& host, int port) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return 0;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    ::inet_pton(AF_INET, host.c_str(), &addr.sin_addr);
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0 ||
        ::listen(listen_fd_, 64) != 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return 0;
    }
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return ntohs(addr.sin_port);
  }

  void Close() {
    bool was_closed = closed_.exchange(true);
    if (was_closed) return;
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (snapshot_dirty_) Snapshot(/*force=*/true);
    }
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    {
      // unblock connection threads stuck in recv() on live clients
      std::lock_guard<std::mutex> lk(mu_);
      for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    cv_.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    if (watcher_.joinable()) watcher_.join();
    for (auto& c : conn_threads_)
      if (c.th.joinable()) c.th.join();
  }

 private:
  using Clock = std::chrono::steady_clock;

  static Clock::duration ToDuration(double seconds) {
    return std::chrono::duration_cast<Clock::duration>(
        std::chrono::duration<double>(seconds));
  }

  struct Pending {
    Task task;
    Clock::time_point deadline;
  };

  bool TaskFailedLocked(int64_t task_id, const json::Value& epoch) {
    auto it = pending_.find(task_id);
    if (it == pending_.end()) return false;
    if (!epoch.is_null() && epoch.as_int() != it->second.task.epoch)
      return false;  // stale report from a previous lease
    Task t = std::move(it->second.task);
    pending_.erase(it);
    t.num_failures += 1;
    if (t.num_failures >= failure_max_) {
      failed_.push_back(std::move(t));
    } else {
      todo_.push_back(std::move(t));
    }
    if (todo_.empty() && pending_.empty() && !done_.empty()) NextPass();
    Snapshot(false);
    return true;
  }

  void NextPass() {
    cur_pass_ += 1;
    std::vector<Task> all;
    for (auto& t : done_) all.push_back(std::move(t));
    for (auto& t : failed_) all.push_back(std::move(t));
    done_.clear();
    failed_.clear();
    std::sort(all.begin(), all.end(),
              [](const Task& a, const Task& b) { return a.task_id < b.task_id; });
    todo_.clear();
    for (auto& t : all) {
      t.num_failures = 0;
      todo_.push_back(std::move(t));
    }
  }

  // -- lease timeout watcher (service.go checkTimeoutFunc) ---------------

  void EnsureWatcher() {
    if (watcher_running_) return;
    watcher_running_ = true;
    if (watcher_.joinable()) watcher_.join();
    watcher_ = std::thread([this] { WatchLoop(); });
  }

  void WatchLoop() {
    std::unique_lock<std::mutex> lk(mu_);
    while (!closed_) {
      auto now = Clock::now();
      std::vector<std::pair<int64_t, int64_t>> expired;
      for (auto& kv : pending_)
        if (kv.second.deadline <= now)
          expired.emplace_back(kv.first, kv.second.task.epoch);
      for (auto& e : expired)
        TaskFailedLocked(e.first, json::Value(e.second));
      if (pending_.empty()) break;  // watcher exits when nothing is leased
      cv_.wait_for(lk, std::min(ToDuration(timeout_s_ / 4.0),
                                ToDuration(0.25)));
    }
    watcher_running_ = false;
  }

  // -- persistence (same schema as master.py _snapshot/_recover) ---------

  void Snapshot(bool force) {
    if (snapshot_path_.empty()) return;
    auto now = Clock::now();
    if (!force && now - last_snapshot_ < ToDuration(0.5)) {
      snapshot_dirty_ = true;
      return;
    }
    last_snapshot_ = now;
    snapshot_dirty_ = false;
    json::Object state;
    json::Array todo, pending, done, failed;
    for (const auto& t : todo_) todo.push_back(t.to_json());
    for (const auto& kv : pending_) pending.push_back(kv.second.task.to_json());
    for (const auto& t : done_) done.push_back(t.to_json());
    for (const auto& t : failed_) failed.push_back(t.to_json());
    state["todo"] = json::Value(std::move(todo));
    state["pending"] = json::Value(std::move(pending));
    state["done"] = json::Value(std::move(done));
    state["failed"] = json::Value(std::move(failed));
    state["cur_pass"] = json::Value(cur_pass_);
    state["chunks"] = json::Value(all_chunks_);
    std::string tmp = snapshot_path_ + ".tmp";
    {
      std::ofstream f(tmp, std::ios::trunc);
      json::Value(std::move(state)).write(f);
    }
    std::rename(tmp.c_str(), snapshot_path_.c_str());
  }

  void Recover(std::ifstream& f) {
    std::string text((std::istreambuf_iterator<char>(f)),
                     std::istreambuf_iterator<char>());
    json::Value state = json::parse(text);
    for (const auto& v : state["todo"].as_array())
      todo_.push_back(Task::from_json(v));
    // tasks pending at crash time go back to todo (service.go:166)
    for (const auto& v : state["pending"].as_array())
      todo_.push_back(Task::from_json(v));
    for (const auto& v : state["done"].as_array())
      done_.push_back(Task::from_json(v));
    for (const auto& v : state["failed"].as_array())
      failed_.push_back(Task::from_json(v));
    cur_pass_ = state["cur_pass"].as_int();
    all_chunks_ = state["chunks"].as_array();
  }

  // -- TCP front-end (one thread per connection, JSON lines) -------------

  void AcceptLoop() {
    while (!closed_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      std::lock_guard<std::mutex> lk(mu_);
      ReapLocked();  // bound growth: join threads of closed connections
      auto done = std::make_shared<std::atomic<bool>>(false);
      conn_fds_.push_back(fd);
      conn_threads_.push_back(
          {std::thread([this, fd, done] {
             ConnLoop(fd);
             {
               std::lock_guard<std::mutex> lk2(mu_);
               conn_fds_.erase(
                   std::remove(conn_fds_.begin(), conn_fds_.end(), fd),
                   conn_fds_.end());
             }
             // last statement: after this the thread touches nothing, so
             // ReapLocked may join it while holding mu_ without deadlock
             done->store(true);
           }),
           done});
    }
  }

  void ReapLocked() {
    for (auto it = conn_threads_.begin(); it != conn_threads_.end();) {
      if (it->done->load()) {
        it->th.join();
        it = conn_threads_.erase(it);
      } else {
        ++it;
      }
    }
  }

  void ConnLoop(int fd) {
    std::string buf;
    char chunk[4096];
    while (!closed_) {
      ssize_t n = ::recv(fd, chunk, sizeof(chunk), 0);
      if (n <= 0) break;
      buf.append(chunk, static_cast<size_t>(n));
      size_t nl;
      while ((nl = buf.find('\n')) != std::string::npos) {
        std::string line = buf.substr(0, nl);
        buf.erase(0, nl + 1);
        if (line.empty()) continue;
        std::string out;
        try {
          out = Dispatch(json::parse(line)).dump();
        } catch (const std::exception& e) {
          json::Object err;
          err["ok"] = json::Value(false);
          err["error"] = json::Value(std::string(e.what()));
          out = json::Value(std::move(err)).dump();
        }
        out += '\n';
        size_t sent = 0;
        while (sent < out.size()) {
          // MSG_NOSIGNAL: a worker that died mid-request must cost one
          // connection, not a SIGPIPE that kills the whole coordinator
          ssize_t m = ::send(fd, out.data() + sent, out.size() - sent,
                             MSG_NOSIGNAL);
          if (m <= 0) {
            ::close(fd);
            return;
          }
          sent += static_cast<size_t>(m);
        }
      }
    }
    ::close(fd);
  }

  const int chunks_per_task_;
  const double timeout_s_;
  const int failure_max_;
  const std::string snapshot_path_;

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<Task> todo_;
  std::unordered_map<int64_t, Pending> pending_;
  std::vector<Task> done_;
  std::vector<Task> failed_;
  int64_t cur_pass_ = 0;
  json::Array all_chunks_;

  std::atomic<bool> closed_{false};
  bool watcher_running_ = false;
  bool snapshot_dirty_ = false;
  Clock::time_point last_snapshot_{};
  struct Conn {
    std::thread th;
    std::shared_ptr<std::atomic<bool>> done;
  };

  int listen_fd_ = -1;
  std::thread accept_thread_;
  std::thread watcher_;
  std::list<Conn> conn_threads_;
  std::vector<int> conn_fds_;
};

}  // namespace master
}  // namespace ptpu
