#pragma once
// Hierarchical host-tensor scope: Scope/Variable equivalent
// (framework/scope.h:41, variable.h:26). Name -> host tensor (dtype tag,
// dims, byte buffer); child scopes delegate lookups to parents
// (Scope::FindVar semantics) and are owned by their parent
// (Scope::NewScope/DropKids).

#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace ptpu {

struct HostTensor {
  std::string dtype;
  std::vector<int64_t> dims;
  std::vector<uint8_t> data;
};

class Scope {
 public:
  Scope() : parent_(nullptr) {}

  Scope* NewChild() {
    std::lock_guard<std::mutex> lk(mu_);
    children_.emplace_back(new Scope());
    children_.back()->parent_ = this;
    return children_.back().get();
  }

  void Set(const std::string& name, HostTensor tensor) {
    std::lock_guard<std::mutex> lk(mu_);
    vars_[name] = std::move(tensor);
  }

  // FindVar: local first, then walk parents.
  const HostTensor* Find(const std::string& name) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = vars_.find(name);
      if (it != vars_.end()) return &it->second;
    }
    return parent_ != nullptr ? parent_->Find(name) : nullptr;
  }

  bool Erase(const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    return vars_.erase(name) != 0;
  }

  uint64_t NumVars() {
    std::lock_guard<std::mutex> lk(mu_);
    return vars_.size();
  }

  std::string ListJoined() {
    std::lock_guard<std::mutex> lk(mu_);
    std::string out;
    for (const auto& kv : vars_) {
      if (!out.empty()) out.push_back('\n');
      out += kv.first;
    }
    return out;
  }

 private:
  Scope* parent_;
  std::mutex mu_;
  std::unordered_map<std::string, HostTensor> vars_;
  std::vector<std::unique_ptr<Scope>> children_;
};

}  // namespace ptpu
