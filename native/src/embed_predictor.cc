// Native predictor over the COMPILED execution path.
//
// Reference parity: inference/api/api_impl.cc:141 NativePaddlePredictor —
// a C++ serving entry point that runs the production engine, not a
// reference interpreter. Here the production engine is the whole-program
// XLA executable (core/lowering.py); this binary embeds CPython (the
// binding route this project uses instead of pybind11) and drives that
// engine in-process: load inference model -> compile once -> execute the
// XLA executable per request. The hand-written f32 interpreter
// (ptpu_demo_predictor) stays as the no-Python fallback.
//
// A direct PJRT C API client would drop the embedded interpreter too; the
// only PJRT plugin shipped on this image is libtpu (hardware the CI rig
// reaches over a tunnel), so the compiled path binds the engine instead.
//
//   ptpu_compiled_predictor <model_dir> <input.npy> <output.npy>
//                           [feed_name] [fetch_index]
//
// The embedded interpreter resolves imports via PYTHONPATH (point it at
// the repo root and the Python env's site-packages).

#include <Python.h>

#include <cstdio>
#include <cstdlib>
#include <string>

int main(int argc, char** argv) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "usage: %s <model_dir> <input.npy> <output.npy> "
                 "[feed_name] [fetch_index]\n",
                 argv[0]);
    return 2;
  }
  std::string model_dir = argv[1];
  std::string input = argv[2];
  std::string output = argv[3];
  std::string feed = argc > 4 ? argv[4] : "";
  // argv is spliced into generated Python source: the index must be an
  // actual integer and strings must not break out of the r''' literals
  long fetch_index = argc > 5 ? std::strtol(argv[5], nullptr, 10) : 0;
  for (const std::string* s : {&model_dir, &input, &output, &feed}) {
    if (s->find("'''") != std::string::npos ||
        (!s->empty() && (s->back() == '\\' || s->back() == '\''))) {
      std::fprintf(stderr,
                   "argument %s cannot contain ''' or end in a "
                   "backslash or quote\n", s->c_str());
      return 2;
    }
  }

  Py_Initialize();

  std::string script;
  script += "import jax\n";
  script += "jax.config.update('jax_platforms', 'cpu')\n";
  script += "import json, numpy as np\n";
  script += "import paddle_tpu as fluid\n";
  script += "from paddle_tpu.inference import NativeConfig, "
            "create_paddle_predictor\n";
  script += "model_dir = r'''" + model_dir + "'''\n";
  script += "feed = r'''" + feed + "'''\n";
  script += "if not feed:\n";
  script += "    meta = json.load(open(model_dir + '/__meta__.json'))\n";
  script += "    feed = meta['feed_names'][0]\n";
  script += "pred = create_paddle_predictor(\n";
  script += "    NativeConfig(model_dir=model_dir, use_tpu=False))\n";
  std::string idx = std::to_string(fetch_index);
  script += "x = np.load(r'''" + input + "''')\n";
  script += "outs = pred.run({feed: x})\n";
  script += "np.save(r'''" + output + "''', "
            "np.asarray(outs[" + idx + "]))\n";
  script += "print('ok compiled fetch shape',"
            " np.asarray(outs[" + idx + "]).shape)\n";

  int rc = PyRun_SimpleString(script.c_str());
  if (rc != 0) {
    std::fprintf(stderr, "embedded compiled predictor failed\n");
  }
  if (Py_FinalizeEx() < 0 && rc == 0) rc = 1;
  return rc == 0 ? 0 : 1;
}
