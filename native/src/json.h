// Minimal JSON value + parser/serializer (C++17, no deps) for the native
// master's wire protocol and snapshots. The protocol (one JSON object per
// line over TCP) and the snapshot schema are shared byte-compatibly with
// the Python master (paddle_tpu/distributed/master.py), so workers and
// recovery interoperate across the two implementations.
//
// Scope: the full JSON grammar except \uXXXX escapes beyond Latin-1 are
// passed through undecoded (chunk descriptors are opaque round-tripped
// values; the master never interprets them).
#pragma once

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace ptpu {
namespace json {

class Value;
using Array = std::vector<Value>;
// std::map keeps key order deterministic for snapshot diffs; the Python
// side does not depend on member order.
using Object = std::map<std::string, Value>;

class Value {
 public:
  enum class Type { Null, Bool, Int, Double, String, Array, Object };

  Value() : type_(Type::Null) {}
  Value(std::nullptr_t) : type_(Type::Null) {}  // NOLINT
  Value(bool b) : type_(Type::Bool), bool_(b) {}  // NOLINT
  Value(int v) : type_(Type::Int), int_(v) {}  // NOLINT
  Value(int64_t v) : type_(Type::Int), int_(v) {}  // NOLINT
  Value(size_t v) : type_(Type::Int), int_(static_cast<int64_t>(v)) {}  // NOLINT
  Value(double v) : type_(Type::Double), dbl_(v) {}  // NOLINT
  Value(const char* s) : type_(Type::String), str_(s) {}  // NOLINT
  Value(std::string s) : type_(Type::String), str_(std::move(s)) {}  // NOLINT
  Value(Array a)  // NOLINT
      : type_(Type::Array), arr_(std::make_shared<Array>(std::move(a))) {}
  Value(Object o)  // NOLINT
      : type_(Type::Object), obj_(std::make_shared<Object>(std::move(o))) {}

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }

  bool as_bool(bool dflt = false) const {
    return type_ == Type::Bool ? bool_ : dflt;
  }
  int64_t as_int(int64_t dflt = 0) const {
    if (type_ == Type::Int) return int_;
    if (type_ == Type::Double) return static_cast<int64_t>(dbl_);
    return dflt;
  }
  double as_double(double dflt = 0.0) const {
    if (type_ == Type::Double) return dbl_;
    if (type_ == Type::Int) return static_cast<double>(int_);
    return dflt;
  }
  const std::string& as_string() const {
    static const std::string kEmpty;
    return type_ == Type::String ? str_ : kEmpty;
  }
  const Array& as_array() const {
    static const Array kEmpty;
    return type_ == Type::Array && arr_ ? *arr_ : kEmpty;
  }
  const Object& as_object() const {
    static const Object kEmpty;
    return type_ == Type::Object && obj_ ? *obj_ : kEmpty;
  }
  Array& mutable_array() {
    if (type_ != Type::Array) *this = Value(Array{});
    return *arr_;
  }
  Object& mutable_object() {
    if (type_ != Type::Object) *this = Value(Object{});
    return *obj_;
  }

  // object convenience: v["key"] (missing -> Null value)
  const Value& operator[](const std::string& k) const {
    static const Value kNull;
    if (type_ != Type::Object || !obj_) return kNull;
    auto it = obj_->find(k);
    return it == obj_->end() ? kNull : it->second;
  }

  std::string dump() const {
    std::ostringstream os;
    write(os);
    return os.str();
  }

  void write(std::ostream& os) const {
    switch (type_) {
      case Type::Null: os << "null"; break;
      case Type::Bool: os << (bool_ ? "true" : "false"); break;
      case Type::Int: os << int_; break;
      case Type::Double: {
        if (std::isfinite(dbl_)) {
          std::ostringstream tmp;
          tmp.precision(17);
          tmp << dbl_;
          os << tmp.str();
        } else {
          os << "null";  // JSON has no inf/nan; match json.dumps(allow_nan=False) spirit
        }
        break;
      }
      case Type::String: write_string(os, str_); break;
      case Type::Array: {
        os << '[';
        bool first = true;
        for (const auto& v : *arr_) {
          if (!first) os << ", ";
          first = false;
          v.write(os);
        }
        os << ']';
        break;
      }
      case Type::Object: {
        os << '{';
        bool first = true;
        for (const auto& kv : *obj_) {
          if (!first) os << ", ";
          first = false;
          write_string(os, kv.first);
          os << ": ";
          kv.second.write(os);
        }
        os << '}';
        break;
      }
    }
  }

 private:
  static void write_string(std::ostream& os, const std::string& s) {
    os << '"';
    for (char c : s) {
      switch (c) {
        case '"': os << "\\\""; break;
        case '\\': os << "\\\\"; break;
        case '\n': os << "\\n"; break;
        case '\r': os << "\\r"; break;
        case '\t': os << "\\t"; break;
        default:
          if (static_cast<unsigned char>(c) < 0x20) {
            char buf[8];
            std::snprintf(buf, sizeof(buf), "\\u%04x", c);
            os << buf;
          } else {
            os << c;  // UTF-8 bytes pass through
          }
      }
    }
    os << '"';
  }

  Type type_;
  bool bool_ = false;
  int64_t int_ = 0;
  double dbl_ = 0.0;
  std::string str_;
  std::shared_ptr<Array> arr_;
  std::shared_ptr<Object> obj_;
};

class ParseError : public std::runtime_error {
 public:
  using std::runtime_error::runtime_error;
};

class Parser {
 public:
  explicit Parser(const std::string& text) : s_(text) {}

  Value parse() {
    Value v = value();
    skip_ws();
    if (pos_ != s_.size()) throw ParseError("trailing bytes after JSON value");
    return v;
  }

 private:
  void skip_ws() {
    while (pos_ < s_.size() && (s_[pos_] == ' ' || s_[pos_] == '\t' ||
                                s_[pos_] == '\n' || s_[pos_] == '\r'))
      ++pos_;
  }

  char peek() {
    if (pos_ >= s_.size()) throw ParseError("unexpected end of input");
    return s_[pos_];
  }

  void expect(char c) {
    if (peek() != c)
      throw ParseError(std::string("expected '") + c + "' at offset " +
                       std::to_string(pos_));
    ++pos_;
  }

  bool consume_literal(const char* lit) {
    size_t n = std::strlen(lit);
    if (s_.compare(pos_, n, lit) == 0) {
      pos_ += n;
      return true;
    }
    return false;
  }

  Value value() {
    skip_ws();
    char c = peek();
    if (c == '{') return object();
    if (c == '[') return array();
    if (c == '"') return Value(string());
    if (c == 't') {
      if (consume_literal("true")) return Value(true);
    } else if (c == 'f') {
      if (consume_literal("false")) return Value(false);
    } else if (c == 'n') {
      if (consume_literal("null")) return Value();
    } else if (c == '-' || (c >= '0' && c <= '9')) {
      return number();
    }
    throw ParseError("unexpected character at offset " + std::to_string(pos_));
  }

  Value object() {
    expect('{');
    Object o;
    skip_ws();
    if (peek() == '}') {
      ++pos_;
      return Value(std::move(o));
    }
    while (true) {
      skip_ws();
      std::string k = string();
      skip_ws();
      expect(':');
      o[std::move(k)] = value();
      skip_ws();
      if (peek() == ',') {
        ++pos_;
        continue;
      }
      expect('}');
      return Value(std::move(o));
    }
  }

  Value array() {
    expect('[');
    Array a;
    skip_ws();
    if (peek() == ']') {
      ++pos_;
      return Value(std::move(a));
    }
    while (true) {
      a.push_back(value());
      skip_ws();
      if (peek() == ',') {
        ++pos_;
        continue;
      }
      expect(']');
      return Value(std::move(a));
    }
  }

  std::string string() {
    expect('"');
    std::string out;
    while (true) {
      if (pos_ >= s_.size()) throw ParseError("unterminated string");
      char c = s_[pos_++];
      if (c == '"') return out;
      if (c != '\\') {
        out += c;
        continue;
      }
      if (pos_ >= s_.size()) throw ParseError("unterminated escape");
      char e = s_[pos_++];
      switch (e) {
        case '"': out += '"'; break;
        case '\\': out += '\\'; break;
        case '/': out += '/'; break;
        case 'b': out += '\b'; break;
        case 'f': out += '\f'; break;
        case 'n': out += '\n'; break;
        case 'r': out += '\r'; break;
        case 't': out += '\t'; break;
        case 'u': {
          unsigned code = hex4();
          // surrogate pair (Python json.dumps ensure_ascii escapes every
          // astral char this way): combine into one code point
          if (code >= 0xD800 && code <= 0xDBFF) {
            if (pos_ + 1 >= s_.size() || s_[pos_] != '\\' ||
                s_[pos_ + 1] != 'u')
              throw ParseError("unpaired high surrogate");
            pos_ += 2;
            unsigned low = hex4();
            if (low < 0xDC00 || low > 0xDFFF)
              throw ParseError("invalid low surrogate");
            code = 0x10000 + ((code - 0xD800) << 10) + (low - 0xDC00);
          } else if (code >= 0xDC00 && code <= 0xDFFF) {
            throw ParseError("unpaired low surrogate");
          }
          append_utf8(out, code);
          break;
        }
        default: throw ParseError("bad escape character");
      }
    }
  }

  unsigned hex4() {
    if (pos_ + 4 > s_.size()) throw ParseError("bad \\u escape");
    unsigned code = 0;
    for (int i = 0; i < 4; ++i) {
      char c = s_[pos_++];
      code <<= 4;
      if (c >= '0' && c <= '9')
        code |= static_cast<unsigned>(c - '0');
      else if (c >= 'a' && c <= 'f')
        code |= static_cast<unsigned>(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F')
        code |= static_cast<unsigned>(c - 'A' + 10);
      else
        throw ParseError("non-hex digit in \\u escape");
    }
    return code;
  }

  static void append_utf8(std::string& out, unsigned code) {
    if (code < 0x80) {
      out += static_cast<char>(code);
    } else if (code < 0x800) {
      out += static_cast<char>(0xC0 | (code >> 6));
      out += static_cast<char>(0x80 | (code & 0x3F));
    } else if (code < 0x10000) {
      out += static_cast<char>(0xE0 | (code >> 12));
      out += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
      out += static_cast<char>(0x80 | (code & 0x3F));
    } else {
      out += static_cast<char>(0xF0 | (code >> 18));
      out += static_cast<char>(0x80 | ((code >> 12) & 0x3F));
      out += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
      out += static_cast<char>(0x80 | (code & 0x3F));
    }
  }

  Value number() {
    size_t start = pos_;
    bool is_double = false;
    if (peek() == '-') ++pos_;
    while (pos_ < s_.size()) {
      char c = s_[pos_];
      if (c >= '0' && c <= '9') {
        ++pos_;
      } else if (c == '.' || c == 'e' || c == 'E' || c == '+' || c == '-') {
        is_double = true;
        ++pos_;
      } else {
        break;
      }
    }
    std::string tok = s_.substr(start, pos_ - start);
    if (is_double) return Value(std::stod(tok));
    try {
      return Value(static_cast<int64_t>(std::stoll(tok)));
    } catch (const std::out_of_range&) {
      return Value(std::stod(tok));
    }
  }

  const std::string& s_;
  size_t pos_ = 0;
};

inline Value parse(const std::string& text) { return Parser(text).parse(); }

}  // namespace json
}  // namespace ptpu
