#pragma once
// Minimal .npy (NumPy format v1.0/2.0) reader/writer for the C++ predictor.
// Supports C-order little-endian arrays; dtype <-> descr mapping covers the
// dtypes the framework serializes (f4/f8/i4/i8/u1). Parity role: the
// reference's C++ deserializer for saved LoDTensor files
// (framework/lod_tensor.cc DeserializeFromStream); the TPU rebuild saves
// params as .npy (paddle_tpu/io.py save_vars), so the native runtime reads
// that.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "scope.h"

namespace ptpu {
namespace npy {

inline std::string DescrToDtype(const std::string& descr) {
  if (descr == "<f4" || descr == "|f4" || descr == "=f4") return "float32";
  if (descr == "<f8") return "float64";
  if (descr == "<i4") return "int32";
  if (descr == "<i8") return "int64";
  if (descr == "|u1") return "uint8";
  if (descr == "|i1") return "int8";
  if (descr == "|b1") return "bool";
  return "";
}

inline std::string DtypeToDescr(const std::string& dtype) {
  if (dtype == "float32") return "<f4";
  if (dtype == "float64") return "<f8";
  if (dtype == "int32") return "<i4";
  if (dtype == "int64") return "<i8";
  if (dtype == "uint8") return "|u1";
  if (dtype == "int8") return "|i1";
  if (dtype == "bool") return "|b1";
  return "";
}

inline int64_t DtypeSize(const std::string& dtype) {
  if (dtype == "float32" || dtype == "int32") return 4;
  if (dtype == "float64" || dtype == "int64") return 8;
  if (dtype == "uint8" || dtype == "int8" || dtype == "bool") return 1;
  return 0;
}

// Pulls the value of a dict key out of the .npy header literal, e.g.
// key="'descr'" from "{'descr': '<f4', 'fortran_order': False, ...}".
inline std::string HeaderField(const std::string& header,
                               const std::string& key) {
  size_t at = header.find(key);
  if (at == std::string::npos) return "";
  at = header.find(':', at);
  if (at == std::string::npos) return "";
  ++at;
  while (at < header.size() && header[at] == ' ') ++at;
  size_t end = at;
  int depth = 0;
  while (end < header.size()) {
    char c = header[end];
    if (c == '(' || c == '[') ++depth;
    if (c == ')' || c == ']') --depth;
    if ((c == ',' || c == '}') && depth <= 0) break;
    ++end;
  }
  return header.substr(at, end - at);
}

inline bool Load(const std::string& path, HostTensor* out) {
  FILE* f = std::fopen(path.c_str(), "rb");
  if (f == nullptr) return false;
  uint8_t magic[8];
  if (std::fread(magic, 1, 8, f) != 8 || std::memcmp(magic, "\x93NUMPY", 6)) {
    std::fclose(f);
    return false;
  }
  uint32_t hlen = 0;
  if (magic[6] == 1) {
    uint16_t h16;
    if (std::fread(&h16, 2, 1, f) != 1) { std::fclose(f); return false; }
    hlen = h16;
  } else {
    if (std::fread(&hlen, 4, 1, f) != 1) { std::fclose(f); return false; }
  }
  std::string header(hlen, '\0');
  if (std::fread(&header[0], 1, hlen, f) != hlen) {
    std::fclose(f);
    return false;
  }
  std::string descr = HeaderField(header, "'descr'");
  // strip quotes
  while (!descr.empty() && (descr.front() == '\'' || descr.front() == '"')) {
    descr.erase(descr.begin());
  }
  while (!descr.empty() && (descr.back() == '\'' || descr.back() == '"')) {
    descr.pop_back();
  }
  if (HeaderField(header, "'fortran_order'").find("True") !=
      std::string::npos) {
    std::fclose(f);
    return false;
  }
  std::string shape = HeaderField(header, "'shape'");
  out->dims.clear();
  int64_t cur = -1;
  for (char c : shape) {
    if (c >= '0' && c <= '9') {
      cur = (cur < 0 ? 0 : cur) * 10 + (c - '0');
    } else if (cur >= 0) {
      out->dims.push_back(cur);
      cur = -1;
    }
  }
  if (cur >= 0) out->dims.push_back(cur);
  out->dtype = DescrToDtype(descr);
  if (out->dtype.empty()) {
    std::fclose(f);
    return false;
  }
  int64_t n = 1;
  for (int64_t d : out->dims) n *= d;
  out->data.resize(n * DtypeSize(out->dtype));
  bool ok = out->data.empty() ||
            std::fread(out->data.data(), 1, out->data.size(), f) ==
                out->data.size();
  std::fclose(f);
  return ok;
}

inline bool Save(const std::string& path, const HostTensor& t) {
  std::string descr = DtypeToDescr(t.dtype);
  if (descr.empty()) return false;
  std::string shape = "(";
  for (size_t i = 0; i < t.dims.size(); ++i) {
    shape += std::to_string(t.dims[i]);
    shape += ",";
    if (i + 1 < t.dims.size()) shape += " ";
  }
  shape += ")";
  std::string header = "{'descr': '" + descr +
                       "', 'fortran_order': False, 'shape': " + shape + ", }";
  // pad so magic+len+header is a multiple of 64, newline-terminated
  while ((10 + header.size() + 1) % 64 != 0) header += ' ';
  header += '\n';
  FILE* f = std::fopen(path.c_str(), "wb");
  if (f == nullptr) return false;
  uint16_t hlen = static_cast<uint16_t>(header.size());
  bool ok = std::fwrite("\x93NUMPY\x01\x00", 1, 8, f) == 8 &&
            std::fwrite(&hlen, 2, 1, f) == 1 &&
            std::fwrite(header.data(), 1, header.size(), f) == header.size() &&
            (t.data.empty() ||
             std::fwrite(t.data.data(), 1, t.data.size(), f) ==
                 t.data.size());
  std::fclose(f);
  return ok;
}

}  // namespace npy
}  // namespace ptpu
