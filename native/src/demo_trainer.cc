// C++-only TRAINING demo: load a serialized PTPB training program pair
// (startup + main with forward/backward/sgd ops), run the startup program
// to initialize parameters, then train on synthetic classification data —
// no Python in the process.
//
// Reference parity: paddle/fluid/train/demo/demo_trainer.cc (LoadProgram,
// run startup_program, loop executor.Run on the train program, read the
// loss). The XLA executor is the production path; this proves the native
// runtime executes the full training IR (forward + grads + update) end to end.
//
//   ptpu_demo_trainer <dir> <loss_var> [steps] [batch] [feed_mode]
//
// <dir> holds main.ptpb + startup.ptpb (paddle_tpu.core.program_bin
// serialize_program bytes). Feeds are fixed by the demo contract:
//   feed_mode "mlp"  (default): "img" float32 [batch, 784]
//   feed_mode "conv": "pixel" float32 [batch, 1, 28, 28]
//   feed_mode "seq":  "words" int64 [batch, 16] (two token-band
//                     classes over a 50-word vocab) + "length" int64
//                     [batch, 1] (all 16)
// plus "label" int64 [batch, 1] in every mode — the MLP, MNIST-conv
// and stacked-LSTM book models' surfaces (train/demo/demo_trainer.cc
// role).

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "interp.h"
#include "program.h"
#include "scope.h"

namespace {

std::vector<uint8_t> ReadFile(const std::string& path) {
  std::vector<uint8_t> out;
  FILE* f = std::fopen(path.c_str(), "rb");
  if (f == nullptr) return out;
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  out.resize(n > 0 ? static_cast<size_t>(n) : 0);
  if (!out.empty() && std::fread(out.data(), 1, out.size(), f) != out.size()) {
    out.clear();
  }
  std::fclose(f);
  return out;
}

bool LoadProgram(const std::string& path, ptpu::ProgramDesc* prog) {
  std::vector<uint8_t> blob = ReadFile(path);
  if (blob.empty()) return false;
  return ptpu::ParseProgram(blob.data(), blob.size(), prog);
}

using Rng = ptpu::interp::XorShiftRng;

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr,
                 "usage: %s <dir> <loss_var> [steps] [batch] "
                 "[feed_mode mlp|conv|seq]\n",
                 argv[0]);
    return 2;
  }
  std::string dir = argv[1];
  std::string loss_name = argv[2];
  int steps = argc > 3 ? std::atoi(argv[3]) : 40;
  int batch = argc > 4 ? std::atoi(argv[4]) : 32;
  std::string feed_mode = argc > 5 ? argv[5] : "mlp";
  if (feed_mode != "mlp" && feed_mode != "conv" && feed_mode != "seq") {
    std::fprintf(stderr, "unknown feed_mode %s (mlp|conv|seq)\n",
                 feed_mode.c_str());
    return 2;
  }

  ptpu::ProgramDesc main_prog, startup_prog;
  if (!LoadProgram(dir + "/main.ptpb", &main_prog) ||
      !LoadProgram(dir + "/startup.ptpb", &startup_prog)) {
    std::fprintf(stderr, "cannot load %s/{main,startup}.ptpb\n",
                 dir.c_str());
    return 1;
  }

  ptpu::Scope scope;
  ptpu::interp::Interpreter startup(startup_prog);
  std::string err = startup.Run(0, &scope);
  if (!err.empty()) {
    std::fprintf(stderr, "startup: %s\n", err.c_str());
    return 1;
  }

  // synthetic 10-class data: per-class template + noise (learnable,
  // same recipe as the Python book tests' synthetic mnist)
  const int kClasses = 10, kDim = 784;
  std::vector<float> templates(kClasses * kDim);
  Rng trng(1234);
  for (float& v : templates) v = trng.uniform();

  ptpu::interp::Interpreter trainer(main_prog);
  Rng rng(7);
  float first_loss = 0.0f, last_loss = 0.0f;
  const int kSeqLen = 16, kVocab = 50;
  for (int step = 0; step < steps; ++step) {
    if (feed_mode == "seq") {
      // two learnable classes: tokens drawn from disjoint vocab bands
      ptpu::HostTensor words;
      words.dtype = "int64";
      words.dims = {batch, kSeqLen};
      words.data.resize(static_cast<size_t>(batch) * kSeqLen *
                        sizeof(int64_t));
      int64_t* wa2 = reinterpret_cast<int64_t*>(words.data.data());
      ptpu::HostTensor lens;
      lens.dtype = "int64";
      lens.dims = {batch, 1};
      lens.data.resize(static_cast<size_t>(batch) * sizeof(int64_t));
      int64_t* la2 = reinterpret_cast<int64_t*>(lens.data.data());
      ptpu::HostTensor label;
      label.dtype = "int64";
      label.dims = {batch, 1};
      label.data.resize(static_cast<size_t>(batch) * sizeof(int64_t));
      int64_t* lb2 = reinterpret_cast<int64_t*>(label.data.data());
      for (int b2 = 0; b2 < batch; ++b2) {
        int64_t cls = static_cast<int64_t>(rng.next() % 2);
        lb2[b2] = cls;
        la2[b2] = kSeqLen;
        int64_t lo = cls == 0 ? 1 : kVocab / 2;
        int64_t band = kVocab / 2 - 1;
        for (int t2 = 0; t2 < kSeqLen; ++t2) {
          wa2[b2 * kSeqLen + t2] =
              lo + static_cast<int64_t>(rng.next() % band);
        }
      }
      scope.Set("words", std::move(words));
      scope.Set("length", std::move(lens));
      scope.Set("label", std::move(label));
    } else {
      ptpu::HostTensor img;
      img.dtype = "float32";
      if (feed_mode == "conv") {
        img.dims = {batch, 1, 28, 28};  // same 784 pixels, NCHW
      } else {
        img.dims = {batch, kDim};
      }
      img.data.resize(static_cast<size_t>(batch) * kDim * sizeof(float));
      float* ia = reinterpret_cast<float*>(img.data.data());
      ptpu::HostTensor label;
      label.dtype = "int64";
      label.dims = {batch, 1};
      label.data.resize(static_cast<size_t>(batch) * sizeof(int64_t));
      int64_t* la = reinterpret_cast<int64_t*>(label.data.data());
      for (int b = 0; b < batch; ++b) {
        int64_t cls = static_cast<int64_t>(rng.next() % kClasses);
        la[b] = cls;
        for (int d = 0; d < kDim; ++d) {
          float noise = rng.uniform();
          ia[b * kDim + d] =
              (0.75f * templates[cls * kDim + d] + 0.25f * noise) * 2.0f -
              1.0f;
        }
      }
      scope.Set(feed_mode == "conv" ? "pixel" : "img", std::move(img));
    scope.Set("label", std::move(label));
    }

    err = trainer.Run(0, &scope);
    if (!err.empty()) {
      std::fprintf(stderr, "step %d: %s\n", step, err.c_str());
      return 1;
    }
    const ptpu::HostTensor* loss = scope.Find(loss_name);
    if (loss == nullptr || loss->dtype != "float32" ||
        loss->data.size() < sizeof(float)) {
      std::fprintf(stderr, "loss var %s not produced\n",
                   loss_name.c_str());
      return 1;
    }
    float lv = reinterpret_cast<const float*>(loss->data.data())[0];
    if (!std::isfinite(lv)) {
      std::fprintf(stderr, "non-finite loss at step %d\n", step);
      return 1;
    }
    if (step == 0) first_loss = lv;
    last_loss = lv;
    std::printf("step %d loss %.6f\n", step, lv);
  }
  std::printf("first %.6f last %.6f\n", first_loss, last_loss);
  if (!(last_loss < first_loss)) {
    std::fprintf(stderr, "training did not reduce the loss\n");
    return 1;
  }
  return 0;
}
