#pragma once
// RecordIO-style chunked record file with per-record CRC32.
//
// Reference parity: the reference's recordio reader
// (operators/reader/create_recordio_file_reader_op.cc over the recordio
// library) — a simple length+checksum framing that lets the input pipeline
// detect truncated/corrupt shards instead of feeding garbage.
//
// On-disk: "PTRC" magic, then per record: u64 payload length, u32 crc32 of
// the payload, payload bytes. Little-endian throughout.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace ptpu {

// CRC-32 (IEEE 802.3), bytewise table implementation.
class Crc32 {
 public:
  Crc32() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) {
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      }
      table_[i] = c;
    }
  }
  uint32_t compute(const void* data, size_t len) const {
    const uint8_t* p = static_cast<const uint8_t*>(data);
    uint32_t c = 0xFFFFFFFFu;
    for (size_t i = 0; i < len; ++i) {
      c = table_[(c ^ p[i]) & 0xFF] ^ (c >> 8);
    }
    return c ^ 0xFFFFFFFFu;
  }

 private:
  uint32_t table_[256];
};

static const Crc32& crc32_instance() {
  static Crc32 crc;
  return crc;
}

static const char kMagic[4] = {'P', 'T', 'R', 'C'};

class RecordIOWriter {
 public:
  explicit RecordIOWriter(const std::string& path)
      : f_(std::fopen(path.c_str(), "wb")) {
    if (f_ != nullptr) {
      std::fwrite(kMagic, 1, 4, f_);
    }
  }
  bool ok() const { return f_ != nullptr; }
  bool Write(const void* data, uint64_t len) {
    if (f_ == nullptr) return false;
    uint32_t crc = crc32_instance().compute(data, len);
    return std::fwrite(&len, sizeof(len), 1, f_) == 1 &&
           std::fwrite(&crc, sizeof(crc), 1, f_) == 1 &&
           (len == 0 || std::fwrite(data, 1, len, f_) == len);
  }
  bool Close() {
    if (f_ == nullptr) return false;
    int rc = std::fclose(f_);
    f_ = nullptr;
    return rc == 0;
  }
  ~RecordIOWriter() {
    if (f_ != nullptr) std::fclose(f_);
  }

 private:
  std::FILE* f_;
};

class RecordIOReader {
 public:
  explicit RecordIOReader(const std::string& path)
      : f_(std::fopen(path.c_str(), "rb")) {
    if (f_ != nullptr) {
      char magic[4];
      if (std::fread(magic, 1, 4, f_) != 4 ||
          std::memcmp(magic, kMagic, 4) != 0) {
        std::fclose(f_);
        f_ = nullptr;
      }
    }
  }
  bool ok() const { return f_ != nullptr; }

  // Reads the next record into the internal buffer.
  // Returns payload size (>= 0; empty records are legal), -1 at EOF,
  // -2 on corruption.
  int64_t Next() {
    if (f_ == nullptr) return -2;
    uint64_t len = 0;
    uint32_t crc = 0;
    if (std::fread(&len, sizeof(len), 1, f_) != 1) return -1;  // EOF
    if (std::fread(&crc, sizeof(crc), 1, f_) != 1) return -2;
    buf_.resize(len);
    if (len != 0 && std::fread(buf_.data(), 1, len, f_) != len) return -2;
    if (crc32_instance().compute(buf_.data(), len) != crc) return -2;
    return static_cast<int64_t>(len);
  }
  const std::vector<uint8_t>& buffer() const { return buf_; }
  bool Close() {
    if (f_ == nullptr) return false;
    int rc = std::fclose(f_);
    f_ = nullptr;
    return rc == 0;
  }
  ~RecordIOReader() {
    if (f_ != nullptr) std::fclose(f_);
  }

 private:
  std::FILE* f_;
  std::vector<uint8_t> buf_;
};

}  // namespace ptpu
