// C++-only inference demo: load a saved inference model (PTPB program +
// .npy params) and run it without any Python in the process.
//
// Reference parity: paddle/fluid/train/demo/demo_trainer.cc + the C++
// predictor flow in inference/api/api_impl.cc (load ProgramDesc, load
// persistables, feed, run executor, fetch). Usage:
//
//   ptpu_demo_predictor <model_dir> <input.npy> <output.npy> [feed] [fetch]
//
// feed/fetch names default to the first entries of __meta__.json (written
// by paddle_tpu.io.save_inference_model).

#include <cstdio>
#include <dirent.h>
#include <set>
#include <string>
#include <vector>

#include "interp.h"
#include "npy.h"
#include "program.h"
#include "scope.h"

namespace {

std::vector<uint8_t> ReadFile(const std::string& path) {
  std::vector<uint8_t> out;
  FILE* f = std::fopen(path.c_str(), "rb");
  if (f == nullptr) return out;
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  out.resize(n > 0 ? static_cast<size_t>(n) : 0);
  if (!out.empty() && std::fread(out.data(), 1, out.size(), f) != out.size()) {
    out.clear();
  }
  std::fclose(f);
  return out;
}

// Extracts the first string of a JSON array field, e.g.
// First(meta, "feed_names") from {"feed_names": ["x"], ...} -> "x".
std::string FirstName(const std::string& json, const std::string& key) {
  size_t at = json.find("\"" + key + "\"");
  if (at == std::string::npos) return "";
  at = json.find('[', at);
  if (at == std::string::npos) return "";
  size_t close = json.find(']', at);
  size_t q1 = json.find('"', at);
  if (q1 == std::string::npos || (close != std::string::npos && q1 > close)) {
    return "";  // empty array
  }
  size_t q2 = json.find('"', q1 + 1);
  if (q2 == std::string::npos) return "";
  return json.substr(q1 + 1, q2 - q1 - 1);
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "usage: %s <model_dir> <input.npy> <output.npy> "
                 "[feed_name] [fetch_name]\n",
                 argv[0]);
    return 2;
  }
  std::string dir = argv[1];

  std::vector<uint8_t> blob = ReadFile(dir + "/__model__");
  if (blob.empty()) {
    std::fprintf(stderr, "cannot read %s/__model__\n", dir.c_str());
    return 1;
  }
  ptpu::ProgramDesc prog;
  if (!ptpu::ParseProgram(blob.data(), blob.size(), &prog)) {
    std::fprintf(stderr, "bad PTPB program\n");
    return 1;
  }

  std::vector<uint8_t> meta_raw = ReadFile(dir + "/__meta__.json");
  std::string meta(meta_raw.begin(), meta_raw.end());
  std::string feed_name = argc > 4 ? argv[4] : FirstName(meta, "feed_names");
  std::string fetch_name = argc > 5 ? argv[5] : FirstName(meta, "fetch_names");
  if (feed_name.empty() || fetch_name.empty()) {
    std::fprintf(stderr, "no feed/fetch names (need __meta__.json or argv)\n");
    return 1;
  }

  // names the program actually declares, to disambiguate the save_vars
  // mangling ('/' -> '__', which is not injective)
  std::set<std::string> declared;
  for (const auto& blk : prog.blocks) {
    for (const auto& v : blk.vars) declared.insert(v.name);
  }

  // load every .npy in the model dir as a parameter (save_vars layout:
  // one file per persistable, '/' mangled to '__')
  ptpu::Scope scope;
  DIR* d = opendir(dir.c_str());
  if (d == nullptr) {
    std::fprintf(stderr, "cannot open %s\n", dir.c_str());
    return 1;
  }
  int n_params = 0;
  for (dirent* e = readdir(d); e != nullptr; e = readdir(d)) {
    std::string fn = e->d_name;
    if (fn.size() < 4 || fn.substr(fn.size() - 4) != ".npy") continue;
    ptpu::HostTensor t;
    if (!ptpu::npy::Load(dir + "/" + fn, &t)) {
      std::fprintf(stderr, "bad npy: %s\n", fn.c_str());
      closedir(d);
      return 1;
    }
    std::string name = fn.substr(0, fn.size() - 4);
    if (declared.find(name) == declared.end()) {
      std::string demangled = name;
      size_t at = 0;
      while ((at = demangled.find("__", at)) != std::string::npos) {
        demangled.replace(at, 2, "/");
        ++at;
      }
      if (declared.find(demangled) != declared.end()) name = demangled;
    }
    scope.Set(name, std::move(t));
    ++n_params;
  }
  closedir(d);

  ptpu::HostTensor input;
  if (!ptpu::npy::Load(argv[2], &input)) {
    std::fprintf(stderr, "cannot read input %s\n", argv[2]);
    return 1;
  }
  scope.Set(feed_name, std::move(input));

  ptpu::interp::Interpreter interp(prog);
  std::string err = interp.Run(0, &scope);
  if (!err.empty()) {
    std::fprintf(stderr, "interpreter error: %s\n", err.c_str());
    return 1;
  }

  const ptpu::HostTensor* out = scope.Find(fetch_name);
  if (out == nullptr) {
    std::fprintf(stderr, "fetch %s not produced\n", fetch_name.c_str());
    return 1;
  }
  if (!ptpu::npy::Save(argv[3], *out)) {
    std::fprintf(stderr, "cannot write %s\n", argv[3]);
    return 1;
  }
  std::printf("ok params=%d fetch=%s dims=[", n_params, fetch_name.c_str());
  for (size_t i = 0; i < out->dims.size(); ++i) {
    std::printf("%s%lld", i ? "," : "",
                static_cast<long long>(out->dims[i]));
  }
  std::printf("]\n");
  return 0;
}
