// ptpu_master: standalone elastic data-dispatch master (go/cmd/master
// role). Serves the same newline-JSON TCP protocol as the Python
// MasterService, so paddle_tpu.distributed.MasterClient workers connect
// unchanged. Prints "LISTENING <port>" once bound (test harness contract).
//
//   ptpu_master [--host 127.0.0.1] [--port 0] [--chunks_per_task 1]
//               [--timeout_s 5.0] [--failure_max 3] [--snapshot PATH]

#include <unistd.h>

#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "master.h"

namespace {
// Self-pipe: the handler only write()s (async-signal-safe); the main
// thread, parked on read(), performs the actual Close() — which takes
// mutexes and joins threads and therefore must NOT run in a handler
// (a signal landing on a thread holding mu_ would self-deadlock).
int g_wake_pipe[2] = {-1, -1};

void HandleSignal(int) {
  char b = 1;
  ssize_t n = ::write(g_wake_pipe[1], &b, 1);
  (void)n;
}
}  // namespace

int main(int argc, char** argv) {
  std::string host = "127.0.0.1";
  std::string snapshot;
  int port = 0;
  int chunks_per_task = 1;
  double timeout_s = 5.0;
  int failure_max = 3;

  for (int i = 1; i < argc; ++i) {
    auto next = [&](const char* flag) -> const char* {
      if (i + 1 >= argc) {
        std::fprintf(stderr, "%s needs a value\n", flag);
        std::exit(2);
      }
      return argv[++i];
    };
    if (!std::strcmp(argv[i], "--host")) {
      host = next("--host");
    } else if (!std::strcmp(argv[i], "--port")) {
      port = std::atoi(next("--port"));
    } else if (!std::strcmp(argv[i], "--chunks_per_task")) {
      chunks_per_task = std::atoi(next("--chunks_per_task"));
    } else if (!std::strcmp(argv[i], "--timeout_s")) {
      timeout_s = std::atof(next("--timeout_s"));
    } else if (!std::strcmp(argv[i], "--failure_max")) {
      failure_max = std::atoi(next("--failure_max"));
    } else if (!std::strcmp(argv[i], "--snapshot")) {
      snapshot = next("--snapshot");
    } else {
      std::fprintf(stderr, "unknown flag %s\n", argv[i]);
      return 2;
    }
  }

  ptpu::master::MasterService service(chunks_per_task, timeout_s,
                                      failure_max, snapshot);
  if (::pipe(g_wake_pipe) != 0) {
    std::perror("pipe");
    return 1;
  }
  std::signal(SIGINT, HandleSignal);
  std::signal(SIGTERM, HandleSignal);
  std::signal(SIGPIPE, SIG_IGN);  // dead workers cost a connection, not us

  int bound = service.Serve(host, port);
  if (bound == 0) {
    std::fprintf(stderr, "failed to bind %s:%d\n", host.c_str(), port);
    return 1;
  }
  std::printf("LISTENING %d\n", bound);
  std::fflush(stdout);
  // serve until signalled, then shut down (and flush the snapshot) from
  // the main thread where locking is safe
  char b;
  while (::read(g_wake_pipe[0], &b, 1) < 0 && errno == EINTR) {
  }
  service.Close();
  return 0;
}
