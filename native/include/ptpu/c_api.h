/* C API for the paddle_tpu native host runtime.
 *
 * Reference parity: the host-side native infrastructure of
 * paddle/fluid — ProgramDesc IR (framework/framework.proto, program_desc.h),
 * Scope/Variable host state (framework/scope.h:41), the reader pipeline's
 * LoDTensorBlockingQueue (operators/reader/lod_tensor_blocking_queue.h) and
 * RecordIO file reader (operators/reader/create_recordio_file_reader_op.cc).
 * Device compute stays with XLA/PJRT; this library is the C++ runtime
 * around it, consumed from Python via ctypes (no pybind11 in the image).
 *
 * All functions return 0 on success, negative on error unless stated.
 * Thread-safety: queue_* and scope_* are thread-safe; reader/writer handles
 * are single-owner.
 */
#ifndef PTPU_C_API_H_
#define PTPU_C_API_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- error reporting ---- */
const char* ptpu_last_error(void); /* thread-local message for last failure */

/* ---- recordio: chunked record file with per-record CRC32 ---- */
typedef struct ptpu_recordio_writer ptpu_recordio_writer;
typedef struct ptpu_recordio_reader ptpu_recordio_reader;

ptpu_recordio_writer* ptpu_recordio_writer_open(const char* path);
int ptpu_recordio_write(ptpu_recordio_writer*, const void* data, uint64_t len);
int ptpu_recordio_writer_close(ptpu_recordio_writer*);

ptpu_recordio_reader* ptpu_recordio_reader_open(const char* path);
/* Returns record length (>= 0) and leaves the payload buffered; -1 at
 * EOF, -2 on corruption (CRC/length mismatch). */
int64_t ptpu_recordio_next(ptpu_recordio_reader*);
/* Copy the buffered record into out (size from ptpu_recordio_next). */
int ptpu_recordio_read(ptpu_recordio_reader*, void* out, uint64_t len);
int ptpu_recordio_reader_close(ptpu_recordio_reader*);

/* ---- blocking queue (LoDTensorBlockingQueue equivalent) ---- */
typedef struct ptpu_queue ptpu_queue;

ptpu_queue* ptpu_queue_create(uint64_t capacity);
/* Blocks while full unless timeout_ms >= 0 (then -2 on timeout).
 * -1 if the queue is closed. Copies the buffer. */
int ptpu_queue_push(ptpu_queue*, const void* data, uint64_t len,
                    int64_t timeout_ms);
/* Returns popped length (>0), 0 when closed-and-drained, -2 on timeout.
 * Peek size first with max_len == 0 (record stays queued). */
int64_t ptpu_queue_pop(ptpu_queue*, void* out, uint64_t max_len,
                       int64_t timeout_ms);
uint64_t ptpu_queue_size(ptpu_queue*);
uint64_t ptpu_queue_capacity(ptpu_queue*);
void ptpu_queue_close(ptpu_queue*);   /* wakes all waiters */
void ptpu_queue_kill(ptpu_queue*);    /* close + discard queued items */
int ptpu_queue_is_closed(ptpu_queue*);
void ptpu_queue_reopen(ptpu_queue*);  /* reset for a new epoch */
void ptpu_queue_destroy(ptpu_queue*);

/* ---- host tensor scope (Scope/Variable equivalent) ---- */
typedef struct ptpu_scope ptpu_scope;

ptpu_scope* ptpu_scope_create(void);
ptpu_scope* ptpu_scope_new_child(ptpu_scope*);
/* dtype: numpy-style tag string ("float32", "int64", ...). Copies data. */
int ptpu_scope_set(ptpu_scope*, const char* name, const char* dtype,
                   const int64_t* dims, int32_t ndim, const void* data,
                   uint64_t nbytes);
/* Var lookup walks parent scopes like Scope::FindVar. Returns nbytes or -1
 * if absent; fills dtype/dims/ndim metadata when pointers are non-null
 * (dims capacity must be >= 16). */
int64_t ptpu_scope_get_meta(ptpu_scope*, const char* name, char* dtype_out,
                            uint64_t dtype_cap, int64_t* dims_out,
                            int32_t* ndim_out);
int ptpu_scope_get_data(ptpu_scope*, const char* name, void* out,
                        uint64_t nbytes);
int ptpu_scope_erase(ptpu_scope*, const char* name);
uint64_t ptpu_scope_num_vars(ptpu_scope*); /* local vars only */
/* Writes local var names joined by '\n' into out; returns needed size. */
int64_t ptpu_scope_list(ptpu_scope*, char* out, uint64_t cap);
void ptpu_scope_destroy(ptpu_scope*); /* also destroys child scopes */

/* ---- PTPB program IR (core/program_bin.py twin) ---- */
typedef struct ptpu_program ptpu_program;

ptpu_program* ptpu_program_parse(const void* data, uint64_t len);
int32_t ptpu_program_num_blocks(ptpu_program*);
int32_t ptpu_program_num_ops(ptpu_program*, int32_t block);
int32_t ptpu_program_num_vars(ptpu_program*, int32_t block);
/* Returns needed size; fills out with the op type string. */
int64_t ptpu_program_op_type(ptpu_program*, int32_t block, int32_t op,
                             char* out, uint64_t cap);
/* Re-serialize (must byte-match the Python writer). Returns needed size. */
int64_t ptpu_program_serialize(ptpu_program*, void* out, uint64_t cap);
void ptpu_program_destroy(ptpu_program*);

/* ---- CPU reference interpreter (NaiveExecutor role, f32 op subset) ---- */
/* Executes every op of `block` against the scope (inputs pre-set, outputs
 * written back). 0 on success; -1 with ptpu_last_error() detail. */
int ptpu_interp_run(ptpu_program*, ptpu_scope*, int32_t block);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* PTPU_C_API_H_ */
