"""Benchmark harness: ResNet-50 training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: the reference's best committed ResNet-50 train throughput —
84.08 img/s (MKL-DNN BS256 on 2x Xeon 6148, benchmark/IntelOptimizedPaddle.md:40-46;
no GPU/Fluid ResNet numbers are committed in-tree, see BASELINE.md).
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_IMG_S = 84.08


def main():
    import jax

    # BENCH_PLATFORM=cpu forces the CPU backend (the axon TPU plugin ignores
    # JAX_PLATFORMS, and a wedged tunnel would hang device enumeration).
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    # Full ImageNet shapes on TPU; scaled-down proxy on CPU (CI smoke).
    if on_tpu:
        img, bs, steps, warmup = 224, 64, 20, 5
    else:
        img, bs, steps, warmup = 64, 16, 5, 2

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 5
    startup.random_seed = 5
    with fluid.program_guard(main_prog, startup):
        loss, feeds, extras = resnet.build(
            img_shape=(3, img, img), class_num=1000, depth=50
        )
        fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)

    place = fluid.TPUPlace() if on_tpu else fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(startup)

    rng = np.random.RandomState(0)
    x = rng.rand(bs, 3, img, img).astype(np.float32)
    y = rng.randint(0, 1000, (bs, 1)).astype(np.int64)

    for _ in range(warmup):
        exe.run(main_prog, feed={"pixel": x, "label": y}, fetch_list=[loss])

    t0 = time.perf_counter()
    for _ in range(steps):
        out = exe.run(
            main_prog, feed={"pixel": x, "label": y}, fetch_list=[loss]
        )
    # fetch already host-synced (np.asarray in executor)
    dt = time.perf_counter() - t0
    img_per_sec = steps * bs / dt

    print(
        json.dumps(
            {
                "metric": "resnet50_train_throughput"
                + ("" if on_tpu else "_cpu_proxy"),
                "value": round(img_per_sec, 2),
                "unit": "images/sec",
                "vs_baseline": round(img_per_sec / BASELINE_IMG_S, 3),
            }
        )
    )
    sys.stdout.flush()


if __name__ == "__main__":
    main()
