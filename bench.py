"""Benchmark harness: ResNet-50 training throughput + MFU on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu"}.
Baseline: the reference's best committed ResNet-50 train throughput —
84.08 img/s (MKL-DNN BS256 on 2x Xeon 6148, benchmark/IntelOptimizedPaddle.md:40-46;
no GPU/Fluid ResNet numbers are committed in-tree, see BASELINE.md).

Measurement design (BENCH_NOTES.md has the profile data behind it):
- Input comes from the in-graph ``random_data_generator`` reader op
  (reference capability: operators/reader/create_random_data_generator_op.cc)
  so the bench measures the framework's training step, not the host link —
  on this harness the TPU sits behind a tunnel with ~25 MB/s h2d, which is
  an artifact of the test rig, not of TPU hardware.
- Mixed precision: the bf16 AMP rewrite (transpiler/amp_transpiler.py) is
  on by default on TPU; master weights stay f32 (BENCH_AMP=0 disables).
- The timed loop fetches nothing per step (steps chain on device through
  donated state); one loss fetch at the end syncs the pipeline and is
  included in the timing. Finiteness of that loss is asserted.
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_IMG_S = 84.08
# ResNet-50 @224: ~4.11 GFLOP forward per image (2*MACs, conv+fc);
# fwd+bwd ~ 3x forward. Same accounting as the MFU targets in BASELINE.md.
TRAIN_GFLOP_PER_IMG = 3 * 4.11
# Peak dense bf16 matmul throughput per chip for MFU accounting.
PEAK_TFLOPS = {"tpu v5 lite": 197.0, "tpu v5e": 197.0, "tpu v4": 275.0,
               "tpu v6 lite": 918.0, "tpu v6e": 918.0}


def _peak_tflops(device):
    name = getattr(device, "device_kind", "") or ""
    for k, v in PEAK_TFLOPS.items():
        if k in name.lower():
            return v
    return None


def main():
    import jax

    # BENCH_PLATFORM=cpu forces the CPU backend (the axon TPU plugin ignores
    # JAX_PLATFORMS, and a wedged tunnel would hang device enumeration).
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import paddle_tpu as fluid
    from paddle_tpu.models import resnet
    from paddle_tpu.transpiler import rewrite_program_amp

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    # Full ImageNet shapes on TPU; scaled-down proxy on CPU (CI smoke).
    if on_tpu:
        img, bs, steps, warmup = 224, 128, 50, 10
    else:
        img, bs, steps, warmup = 64, 16, 5, 2
    use_amp = os.environ.get("BENCH_AMP", "1" if on_tpu else "0") == "1"

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 5
    startup.random_seed = 5
    with fluid.program_guard(main_prog, startup):
        pixel, label = fluid.layers.random_data_generator(
            shapes=[[bs, 3, img, img], [bs, 1]],
            dtypes=["float32", "int64"],
            int_high=999,
        )
        predict = resnet.resnet_imagenet(pixel, 1000, depth=50)
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        loss = fluid.layers.mean(cost)
        fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    if use_amp:
        rewrite_program_amp(main_prog, "bfloat16")

    place = fluid.TPUPlace() if on_tpu else fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(startup)

    # Compile + settle (first run compiles; a loss fetch syncs the queue).
    for _ in range(warmup):
        exe.run(main_prog, feed={}, fetch_list=[])
    out = exe.run(main_prog, feed={}, fetch_list=[loss])

    t0 = time.perf_counter()
    for _ in range(steps - 1):
        exe.run(main_prog, feed={}, fetch_list=[])
    out = exe.run(main_prog, feed={}, fetch_list=[loss])
    dt = time.perf_counter() - t0
    lv = float(np.ravel(np.asarray(out[0]))[0])
    assert np.isfinite(lv), "non-finite loss %r" % lv
    img_per_sec = steps * bs / dt

    peak = _peak_tflops(jax.devices()[0]) if on_tpu else None
    mfu = (
        round(img_per_sec * TRAIN_GFLOP_PER_IMG * 1e9 / (peak * 1e12), 4)
        if peak
        else None
    )

    print(
        json.dumps(
            {
                "metric": "resnet50_train_throughput"
                + ("" if on_tpu else "_cpu_proxy"),
                "value": round(img_per_sec, 2),
                "unit": "images/sec",
                "vs_baseline": round(img_per_sec / BASELINE_IMG_S, 3),
                "mfu": mfu,
            }
        )
    )
    sys.stdout.flush()


if __name__ == "__main__":
    main()
