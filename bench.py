"""Benchmark harness: ResNet-50 + Transformer training throughput and MFU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu",
"models": {...both models...}} and ALWAYS exits 0 — a wedged TPU tunnel,
a backend init failure, or a mid-run hang degrade to a CPU-proxy number
with an explicit "error" field instead of a traceback (the round-2 bench
capture was lost to exactly that failure mode).

Structure: the parent process never imports jax. It (1) probes the TPU in
a subprocess under a timeout (the axon tunnel can wedge so hard that
``jax.devices()`` blocks forever and ignores signals delivered to the
same process), (2) runs each model's bench in its own worker subprocess
(``bench.py --worker``, model/platform via env) under a timeout, and
(3) merges worker JSON into the single output line. TPU worker failure
retries that model on CPU, marked ``_cpu_proxy``.

Baseline: the reference's best committed ResNet-50 train throughput —
84.08 img/s (MKL-DNN BS256 on 2x Xeon 6148, benchmark/IntelOptimizedPaddle.md:40-46;
no GPU/Fluid ResNet numbers are committed in-tree, see BASELINE.md).

Measurement design (BENCH_NOTES.md has the profile data behind it):
- Input comes from the in-graph ``random_data_generator`` reader op
  (reference capability: operators/reader/create_random_data_generator_op.cc)
  so the bench measures the framework's training step, not the host link —
  on this harness the TPU sits behind a tunnel with ~25 MB/s h2d, which is
  an artifact of the test rig, not of TPU hardware.
- Mixed precision: the bf16 AMP rewrite (transpiler/amp_transpiler.py) is
  on by default on TPU; master weights stay f32 (BENCH_AMP=0 disables).
- The timed loop fetches nothing per step (steps chain on device through
  donated state); one loss fetch at the end syncs the pipeline and is
  included in the timing. Finiteness of that loss is asserted.
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_IMG_S = 84.08
# ResNet-50 @224 forward: 7.76 GFLOP per image at the HARDWARE convention
# (2 FLOPs per multiply-accumulate — the same convention the 197 TFLOP/s
# peak is quoted in). The widely cited "4.1 GFLOPs" counts multiply-adds
# as one op (GMACs); dividing MAC-counted work by a 2-per-MAC peak
# understated every prior ResNet MFU figure by exactly 2x (the r3 chip
# capture's 15.9% is 31.8% true MFU). Audit trail: the per-conv
# signature table from tools/hlo_cost_model.py (docs/MFU_PLAN.md) sums
# to 7.71 GF conv + 0.05 GF fc fwd on this exact model; fwd+bwd ~= 3x
# forward (dx+dw each ~= fwd). The transformer's 6N accounting below
# was already in the hardware convention, so it is unchanged.
TRAIN_GFLOP_PER_IMG = 3 * 7.76
# Peak dense bf16 matmul throughput per chip for MFU accounting.
PEAK_TFLOPS = {"tpu v5 lite": 197.0, "tpu v5e": 197.0, "tpu v4": 275.0,
               "tpu v6 lite": 918.0, "tpu v6e": 918.0}


def _peak_tflops(device):
    name = getattr(device, "device_kind", "") or ""
    for k, v in PEAK_TFLOPS.items():
        if k in name.lower():
            return v
    return None


def _timed_steps(exe, main_prog, loss, steps, warmup, feed=None):
    """Warmup + timed run. Prefers the compiled multi-step path (one
    lax.scan executable per K steps, no per-step host dispatch); falls
    back to the per-step loop if the program can't scan. Returns
    (seconds, last_loss, mode) — mode records which path actually ran so
    a silent fallback can't masquerade as a multi-step measurement."""
    feed = feed or {}
    # default per-step: measured equal on TPU (async dispatch already hides
    # per-step host cost: 2517 vs 2530 img/s) and 4x slower on XLA:CPU
    # (scan bodies lose intra-op parallelism); the capability itself is
    # tested in tests/test_multi_step.py and pays off when dispatch is
    # synchronous (multi-host barriers, very small step times)
    use_multi = os.environ.get("BENCH_MULTISTEP", "0") == "1"
    if use_multi:
        try:
            # warmup at the SAME step count: the scan executable is keyed
            # on K, so a different K would recompile inside the timing
            exe.run_multi_step(main_prog, steps, feed=feed,
                               fetch_list=[loss])
            t0 = time.perf_counter()
            out = exe.run_multi_step(main_prog, steps, feed=feed,
                                     fetch_list=[loss])
            dt = time.perf_counter() - t0
            return dt, float(np.ravel(np.asarray(out[0]))[0]), "multi-step"
        except (RuntimeError, TypeError) as e:
            # not scannable: state_out ⊄ state_in, a scan carry type
            # mismatch, or an XLA compile failure — fall back LOUDLY
            print("multi-step path failed (%s: %s); falling back to "
                  "per-step" % (type(e).__name__, e), file=sys.stderr)
    for _ in range(warmup):
        exe.run(main_prog, feed=feed, fetch_list=[])
    exe.run(main_prog, feed=feed, fetch_list=[loss])
    t0 = time.perf_counter()
    for _ in range(steps - 1):
        exe.run(main_prog, feed=feed, fetch_list=[])
    out = exe.run(main_prog, feed=feed, fetch_list=[loss])
    dt = time.perf_counter() - t0
    return dt, float(np.ravel(np.asarray(out[0]))[0]), "per-step"


def _bench_resnet(fluid, on_tpu, use_amp):
    from paddle_tpu.models import resnet
    from paddle_tpu.transpiler import rewrite_program_amp

    # Full ImageNet shapes on TPU; scaled-down proxy on CPU (CI smoke).
    if on_tpu:
        img, bs, steps, warmup = 224, 128, 50, 10
    else:
        img, bs, steps, warmup = 64, 16, 5, 2
    bs = int(os.environ.get("BENCH_BS", bs))  # batch-sweep override
    # BENCH_DATA=host feeds real numpy batches through the PyReader path
    # (h2d transfer on the timed path; BENCH_DOUBLE_BUFFER=0 disables the
    # device prefetch so the overlap win is measurable). Default "graph"
    # keeps the in-graph generator: the framework step, not the host link.
    # BENCH_UINT8=1 ships the pixels as uint8 and normalizes ON DEVICE —
    # a 4x smaller h2d transfer, the input-pipeline recipe for real TPU
    # hosts (and the fix VERDICT r2 named for the host-link-bound mode).
    host_data = os.environ.get("BENCH_DATA", "graph") == "host"
    double_buffer = os.environ.get("BENCH_DOUBLE_BUFFER", "1") == "1"
    uint8_input = os.environ.get("BENCH_UINT8", "0") == "1"

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 5
    startup.random_seed = 5
    with fluid.program_guard(main_prog, startup):
        if host_data:
            pixel = fluid.layers.data(
                name="bench_pixel", shape=[3, img, img],
                dtype="uint8" if uint8_input else "float32")
            label = fluid.layers.data(
                name="bench_label", shape=[1], dtype="int64")
            if uint8_input:
                # cast + scale to [0,1) on device; XLA fuses this into the
                # first conv's input so it costs one pass over the batch
                pixel = fluid.layers.scale(
                    fluid.layers.cast(pixel, "float32"), scale=1.0 / 255.0)
        else:
            pixel, label = fluid.layers.random_data_generator(
                shapes=[[bs, 3, img, img], [bs, 1]],
                dtypes=["float32", "int64"],
                int_high=999,
            )
        predict = resnet.resnet_imagenet(pixel, 1000, depth=50)
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        loss = fluid.layers.mean(cost)
        fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    if use_amp:
        rewrite_program_amp(main_prog, "bfloat16")

    place = fluid.TPUPlace() if on_tpu else fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(startup)
    if host_data:
        dt, lv = _host_data_steps(
            fluid, exe, main_prog, loss, steps, warmup, bs, img, place,
            double_buffer, uint8_input)
        mode = ("host-data"
                + ("+double-buffer" if double_buffer else "")
                + ("+uint8" if uint8_input else ""))
    else:
        dt, lv, mode = _timed_steps(exe, main_prog, loss, steps, warmup)
    assert np.isfinite(lv), "non-finite loss %r" % lv
    img_per_sec = steps * bs / dt
    return {
        "metric": "resnet50_train_throughput" + ("" if on_tpu else "_cpu_proxy"),
        "value": round(img_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_S, 3),
        "gflop_per_unit": TRAIN_GFLOP_PER_IMG,
        "rate": img_per_sec,
        "mode": mode,
    }


def _host_data_steps(fluid, exe, main_prog, loss, steps, warmup, bs, img,
                     place, double_buffer, uint8_input=False):
    """Timed loop fed per-step from a PyReader over pre-generated numpy
    batches: the h2d transfer is ON the timed path, so the double-buffer
    prefetch delta (and the uint8 4x-smaller-transfer delta) is what
    this mode exists to measure."""
    rng = np.random.RandomState(13)
    n_distinct = 8  # enough to defeat any transfer caching, bounded RAM

    def make_pixels():
        if uint8_input:
            return rng.randint(0, 256, (bs, 3, img, img), dtype="uint8")
        return rng.rand(bs, 3, img, img).astype("float32")

    batches = [
        {"bench_pixel": make_pixels(),
         "bench_label": rng.randint(0, 999, (bs, 1)).astype("int64")}
        for _ in range(n_distinct)
    ]

    def make_reader(n):
        def reader():
            for i in range(n):
                yield batches[i % n_distinct]
        return reader

    # dict batches bypass feed slots, so the PyReader is constructed bare
    # (py_reader() would append unused slot vars to the default program)
    from paddle_tpu.layers.io import PyReader

    pyreader = PyReader([], capacity=4, use_double_buffer=double_buffer)

    pyreader.decorate_paddle_reader(make_reader(warmup))
    pyreader.start(place=place if double_buffer else None)
    for _ in range(warmup):
        exe.run(main_prog, feed=pyreader.next_feed(), fetch_list=[])
    pyreader.reset()

    pyreader.decorate_paddle_reader(make_reader(steps))
    # clock starts BEFORE reader start in both modes: the double buffer's
    # head-start transfers are part of what the comparison measures
    t0 = time.perf_counter()
    pyreader.start(place=place if double_buffer else None)
    for _ in range(steps - 1):
        exe.run(main_prog, feed=pyreader.next_feed(), fetch_list=[])
    out = exe.run(main_prog, feed=pyreader.next_feed(), fetch_list=[loss])
    dt = time.perf_counter() - t0
    pyreader.reset()
    return dt, float(np.ravel(np.asarray(out[0]))[0])


def _bench_transformer(fluid, on_tpu, use_amp):
    """Transformer-base-ish NMT train throughput in tokens/sec (the
    BASELINE.md 'Transformer base NMT train MFU' config, single chip).
    No reference throughput number is committed in-tree (BENCH_NOTES.md),
    so vs_baseline is null; MFU is the comparable figure."""
    from paddle_tpu.models import transformer
    from paddle_tpu.transpiler import rewrite_program_amp

    if on_tpu:
        bs, seq, steps, warmup = 64, 256, 30, 5
        n_layer, n_head, d_model, d_inner = 6, 8, 512, 2048
    else:
        bs, seq, steps, warmup = 4, 32, 4, 2
        n_layer, n_head, d_model, d_inner = 2, 4, 64, 128
    vocab = 32000 if on_tpu else 500
    bs = int(os.environ.get("BENCH_BS", bs))  # batch-sweep override
    seq = int(os.environ.get("BENCH_SEQ", seq))
    # vocab override: lets the CPU proxy run the real 32k vocab head at
    # small bs/seq, which is where the CE-head lever (FLAGS_fused_ce)
    # lives — the default 500-vocab proxy is insensitive to it
    vocab = int(os.environ.get("BENCH_VOCAB", vocab))
    # compile-light fallback: fewer layers compile much faster through a
    # degraded tunnel; MFU stays a valid per-model measurement since the
    # FLOP accounting below scales with n_layer
    n_layer = int(os.environ.get("BENCH_LAYERS", n_layer))

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 7
    startup.random_seed = 7
    with fluid.program_guard(main_prog, startup):
        loss, feeds, _ = transformer.build(
            src_vocab_size=vocab, trg_vocab_size=vocab, max_length=seq,
            n_layer=n_layer, n_head=n_head, d_model=d_model,
            d_inner=d_inner, dropout=0.1,
        )
        fluid.optimizer.Adam(learning_rate=2e-4).minimize(loss)
    if use_amp:
        rewrite_program_amp(main_prog, "bfloat16")

    rng = np.random.RandomState(11)
    feed = {
        "src_word": rng.randint(1, vocab, (bs, seq)).astype("int64"),
        "src_len": np.full((bs, 1), seq, "int64"),
        "trg_word": rng.randint(1, vocab, (bs, seq)).astype("int64"),
        "trg_len": np.full((bs, 1), seq, "int64"),
        "label": rng.randint(1, vocab, (bs, seq)).astype("int64"),
    }
    feed = {k: v for k, v in feed.items()
            if any(f.name == k for f in feeds)}

    place = fluid.TPUPlace() if on_tpu else fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(startup)
    dt, lv, mode = _timed_steps(exe, main_prog, loss, steps, warmup,
                                feed=feed)
    assert np.isfinite(lv), "non-finite loss %r" % lv
    # decoder tokens/sec (standard NMT accounting); with src_len == trg_len
    # each decoder token corresponds to one src token of encoder work, so
    # charging enc+dec params per decoder token is exact, not double-counted
    tok_per_sec = steps * bs * seq / dt
    # 6N rule (2N fwd + 4N bwd) on non-embedding params; attention
    # score/context FLOPs are excluded, so MFU is slightly conservative
    n_params = (
        n_layer * (4 * d_model * d_model + 2 * d_model * d_inner)  # enc
        + n_layer * (8 * d_model * d_model + 2 * d_model * d_inner)  # dec
    )
    gflop_per_tok = 3 * 2 * n_params / 1e9
    return {
        "metric": "transformer_train_throughput" + ("" if on_tpu else "_cpu_proxy"),
        "value": round(tok_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": None,
        "gflop_per_unit": gflop_per_tok,
        "rate": tok_per_sec,
        "mode": mode,
    }


def _bench_serving(fluid, on_tpu):
    """Serving-throughput leg: the deterministic mixed-batch-size load
    from serving/loadgen.py (the SAME code path tools/serve_smoke.py
    smoke-tests) replayed through a warm BatchingServer — so the bench
    trajectory tracks requests/sec, batch occupancy and latency p50/p99
    alongside training MFU, and benchmark/budgets.json gates all three.
    """
    import shutil
    import tempfile

    from paddle_tpu.inference import NativeConfig, create_paddle_predictor
    from paddle_tpu.serving import BatchingServer, loadgen

    model_dir = tempfile.mkdtemp(prefix="bench_serving_")
    try:
        loadgen.build_demo_model(model_dir)
        predictor = create_paddle_predictor(
            NativeConfig(model_dir=model_dir, use_tpu=on_tpu))
        server = BatchingServer(predictor, max_batch=8, workers=2,
                                batch_linger_s=0.002)
        try:
            server.warmup()
            wall, ok, errors = loadgen.replay(
                server, loadgen.demo_requests(48), concurrency=4)
            assert ok == 48 and not errors, \
                "replay errors: %r" % errors[:3]
            rec = loadgen.serving_capture(server, ok, wall)
        finally:
            server.close()
    finally:
        shutil.rmtree(model_dir, ignore_errors=True)
    rec["metric"] = "serving_throughput" + ("" if on_tpu else "_cpu_proxy")
    # requests aren't FLOP-accounted: rate feeds throughput, mfu stays None
    rec["rate"] = rec["value"]
    rec["gflop_per_unit"] = 0.0
    return rec


def _bench_frontend(fluid, on_tpu):
    """Network front-end leg (serving/frontend.py): the SAME mixed
    unary load as the serving leg, but replayed over a REAL loopback
    socket through ``ServingClient``s — so the bench trajectory tracks
    wire-level requests/sec and CLIENT-side latency p50/p99 (socket,
    framing and base64 codec included), plus the stream
    time-to-first-token of the decode endpoint. ``tools/run_ci.sh net``
    smoke-tests the same path cross-process with a warm cache;
    benchmark/budgets.json gates ttft_ms / latency_ms_p99 / throughput.
    """
    import shutil
    import tempfile

    from paddle_tpu.inference import NativeConfig, create_paddle_predictor
    from paddle_tpu.models import transformer
    from paddle_tpu.serving import (
        BatchingServer,
        ServingClient,
        ServingFrontend,
        loadgen,
    )
    from paddle_tpu.serving.generation import Sampler, SlotDecodeSession

    fcfg = dict(src_vocab_size=40, trg_vocab_size=40, n_layer=1,
                n_head=2, d_inner=64)
    seq, dmodel = 16, 32
    model_dir = tempfile.mkdtemp(prefix="bench_frontend_")
    try:
        loadgen.build_demo_model(model_dir)
        predictor = create_paddle_predictor(
            NativeConfig(model_dir=model_dir, use_tpu=on_tpu))
        server = BatchingServer(predictor, max_batch=8, workers=2,
                                batch_linger_s=0.002)
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 13
        startup.random_seed = 13
        with fluid.program_guard(main, startup):
            transformer.build(dropout=0.0, label_smooth_eps=0.0,
                              max_length=seq, d_model=dmodel, **fcfg)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        sess = SlotDecodeSession(
            exe, num_slots=4, max_length=seq, d_model=dmodel,
            paged=True, page_size=4, steps=2, sampler=Sampler(seed=3),
            **fcfg)
        fe = ServingFrontend(server=server, session=sess)
        try:
            server.warmup()
            rng = np.random.RandomState(17)
            src = rng.randint(3, 40, (4, seq)).astype("int64")
            warm_cl = ServingClient(fe.address)
            warm_cl.generate_full(src[0], src_len=seq)  # decode warmup
            # wire unary replay: one connection per synchronous caller
            latencies = []
            wall, ok, errors = loadgen.replay(
                lambda: ServingClient(fe.address),
                loadgen.demo_requests(48), concurrency=4,
                latencies=latencies)
            assert ok == 48 and not errors, \
                "wire replay errors: %r" % errors[:3]
            # stream ttft: request sent -> first token chunk received.
            # Request tracing rides the stream portion: each request's
            # completed trace (fetched back over the wire) feeds the
            # ttft_breakdown split — queue wait vs prefill vs first
            # decode dispatch — beside the raw client-side ttft_ms
            from paddle_tpu.observability import tracing

            ttfts, traces = [], []
            tracing.enable(True)
            try:
                for i in range(4):
                    t0 = time.perf_counter()
                    first = []

                    def see(ev, t0=t0, first=first):
                        if ev.get("event") == "tokens" and not first:
                            first.append(time.perf_counter() - t0)

                    warm_cl.generate_full(src[i], src_len=seq,
                                          on_event=see)
                    ttfts.extend(first)
                    traces.append(warm_cl.trace())
            finally:
                tracing.enable(False)
            warm_cl.close()
            rec = loadgen.wire_capture(ok, wall, latencies, ttfts,
                                       traces=traces)
        finally:
            fe.close()
            server.close()
    finally:
        shutil.rmtree(model_dir, ignore_errors=True)
    rec["metric"] = ("frontend_throughput"
                     + ("" if on_tpu else "_cpu_proxy"))
    # wire requests aren't FLOP-accounted: rate feeds throughput only
    rec["rate"] = rec["value"]
    rec["gflop_per_unit"] = 0.0
    return rec


def _bench_decode(fluid, on_tpu):
    """Paged-decode A/B leg (ROADMAP item 3 / ragged paged attention):
    steady-state decode tokens/sec and per-token latency at MIXED slot
    lengths and LOW pool occupancy (4 requests in an 8-slot pool), the
    PR 8 dense slot decoder vs the block-paged session (page-table KV
    pool, ragged attention, steps=8 on-device token loop). The paged
    session's tokens are asserted equal to the dense oracle's inside
    the leg, so the gated speedup can never come from decoding less.
    ``predicted_hbm_bytes`` is the paged kernel's grid accounting at
    the leg's canonical mixed-length state — deterministic, gated hard:
    decode traffic must stay proportional to RESIDENT pages.

    PR 12 adds the cross-request-reuse legs: (a) a prefix-cache
    exercise (cold forced-prefix prefill, then a hit that must decode
    bit-identical — ``prefix_hit_rate``/``prefill_tokens_saved``), and
    (b) the best-of-N A/B — two sources x best-of-4 through
    ``admit_group`` (ONE encoder forward + one chunked prefill + joins
    per source, group-pooled cross K/V at ``num_groups=2``) vs eight
    UNSHARED solo admissions of the same members; both decode
    bit-identical token matrices (asserted, so ``bestofn_speedup``
    can never come from decoding less), and ``cross_kv_bytes`` is the
    grouped cross-pool footprint gated deterministically against the
    per-slot dense layout.

    PR 15 adds the BEAM A/B: ``beam_width=4`` decode with the
    zero-copy reorder (per-step parent permutation = in-graph
    page-table row gather + host refcount rebinds) vs the SAME session
    geometry under ``FLAGS_beam_reorder=reference`` (every survivor
    physically copies its parent's resident pages — the
    pre-paged-attention baseline). Both sessions share one program set
    (identical geometry, content-addressed executables) and decode
    bit-identical n-best matrices + scores (asserted), so
    ``beam_speedup`` is pure reorder mechanics. ``beam_reorder_bytes``
    is the rebind session's physically-moved reorder bytes, page-
    geometry-accounted (reorder copies — zero for pure permutations —
    plus write-page COW splits x page bytes); deterministic under
    greedy decode, gated hard: growth means reorders started copying
    or COW stopped being write-page-only.

    PR 16 adds the SPECULATIVE A/B: ``speculative={"k": 3}`` decode
    (ngram drafter, tree-attention verify — k + 1 tree nodes scored in
    ONE target dispatch) vs the SAME session under
    ``FLAGS_speculative=off`` (sequential ``steps=1`` decode, the
    bit-exactness oracle). The arm runs the prompt-lookup regime the
    drafter exists for: a briefly copy-trained model over periodic
    sources behind a forced prefix that seeds the suffix lookup (the
    drafter matches over emitted tokens + forced prefix). One session,
    one program set, a flag flip between waves; both arms decode
    bit-identical tokens (asserted), so ``speculative_speedup`` is
    pure dispatch amortization — tokens committed per target
    dispatch — and ``acceptance_rate`` is the drafter's measured
    accepted/proposed ratio over the timed wave.
    """
    from paddle_tpu.kernels import paged_attention as pk
    from paddle_tpu.models import transformer
    from paddle_tpu.serving.generation import Sampler, SlotDecodeSession

    vocab, seq, dm, n_head, S, K, ps = 50, 32, 32, 2, 8, 8, 8
    cfg = dict(src_vocab_size=vocab, trg_vocab_size=vocab, n_layer=1,
               n_head=n_head, d_inner=64)
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 7
    startup.random_seed = 7
    with fluid.program_guard(main_prog, startup):
        transformer.build(dropout=0.0, label_smooth_eps=0.0,
                          max_length=seq, d_model=dm, **cfg)
    exe = fluid.Executor(fluid.TPUPlace() if on_tpu else fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(11)
    B = 4  # half the pool stays empty: the raggedness regime
    src = rng.randint(3, vocab, (B, seq)).astype("int64")
    mixed = [seq, seq // 2, seq // 4, 3]
    src_len = np.asarray(mixed, "int64")[:, None]

    def tokens_of(out):
        # decoded tokens per row: through the first eos, else the full
        # T-1 budget (deterministic — seeded weights, greedy decode)
        total = 0
        for row in out:
            hits = np.where(row[1:] == 2)[0]
            total += (int(hits[0]) + 1) if hits.size else (seq - 1)
        return total

    def timed(sess):
        sess.generate(src, src_len)  # warm every executable
        t0 = time.perf_counter()
        out = sess.generate(src, src_len)
        return tokens_of(out), time.perf_counter() - t0, out

    dense = SlotDecodeSession(exe, num_slots=S, max_length=seq,
                              d_model=dm, **cfg)
    d_tok, d_dt, d_out = timed(dense)
    paged = SlotDecodeSession(exe, num_slots=S, max_length=seq,
                              d_model=dm, paged=True, page_size=ps,
                              steps=K, prefix_cache_pages=16, **cfg)
    p_tok, p_dt, p_out = timed(paged)
    assert np.array_equal(d_out, p_out), \
        "paged decode diverged from the dense oracle"
    d_tps = d_tok / d_dt
    p_tps = p_tok / p_dt

    # --- prefix-cache exercise (greedy => slot-independent tokens):
    # a repeated forced prefix provisions by reference; the hit MUST
    # decode bit-identical to the cold prefill that cached the pages
    pfx = [int(t) for t in src[0][: 3 * seq // 4]]
    cold = paged.generate_best_of(src[0], 1, src_len=seq,
                                  prefix_tokens=pfx)
    hit = paged.generate_best_of(src[0], 1, src_len=seq,
                                 prefix_tokens=pfx)
    assert np.array_equal(cold, hit), \
        "prefix-cache hit diverged from the cold prefill"
    pstats = paged.prefix_cache_stats()

    # --- best-of-N shared vs unshared A/B: same members, same slots,
    # same (seed, slot, position) PRNG streams — bit-identical tokens,
    # so the ratio is pure admission/prefill amortization + group-
    # pooled cross K/V
    smp = Sampler(strategy="top_k", top_k=4, temperature=0.9, seed=13)
    N = 8  # best-of-N members, filling the pool from ONE source
    src_bo = rng.randint(3, vocab, (seq,)).astype("int64")
    pfx_bo = [int(t) for t in src_bo[: 3 * seq // 4]]

    def drain(sess, slots):
        outs = {}
        while len(outs) < len(slots):
            outs.update(sess.step())
        return np.stack([outs[s] for s in slots])

    def shared_wave(sess):
        return drain(sess, sess.admit_group(
            src_bo, N, src_len=seq, prefix_tokens=pfx_bo))

    def unshared_wave(sess):
        slots = [sess.admit(src_bo, seq, prefix_tokens=pfx_bo)
                 for _ in range(N)]
        return drain(sess, slots)

    mk = lambda groups: SlotDecodeSession(  # noqa: E731
        exe, num_slots=S, max_length=seq, d_model=dm, paged=True,
        page_size=ps, steps=K, num_groups=groups, sampler=smp, **cfg)
    sh, un = mk(2), mk(S)
    shared_wave(sh)  # warm every executable (admit/join/prefill/copy)
    unshared_wave(un)
    t0 = time.perf_counter()
    sh_out = shared_wave(sh)
    sh_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    un_out = unshared_wave(un)
    un_dt = time.perf_counter() - t0
    assert np.array_equal(sh_out, un_out), \
        "shared-KV best-of-N diverged from the unshared replay"
    bo_tok = tokens_of(sh_out)
    sh_tps = bo_tok / sh_dt
    un_tps = bo_tok / un_dt

    # --- beam A/B: zero-copy rebind reorder vs the copy-reorder
    # oracle. One geometry (the oracle's transient copies need page
    # headroom, so BOTH sessions get it — identical programs, shared
    # content-addressed executables), bit-identical n-bests asserted.
    from paddle_tpu import flags as _flags

    bw = 4
    beam_pages = 1 + 2 * S * (seq // ps)  # oracle copy headroom
    src_beam = rng.randint(3, vocab, (2, seq)).astype("int64")

    def mk_beam():
        return SlotDecodeSession(
            exe, num_slots=S, max_length=seq, d_model=dm, paged=True,
            page_size=ps, beam_width=bw, num_pages=beam_pages, **cfg)

    def beam_wave(sess):
        outs = [sess.generate_beam(r, seq) for r in src_beam]
        return outs

    rb = mk_beam()
    beam_wave(rb)  # warm (admit/join/beam-step/cow-batch executables)
    rb.beam_reorder_pages = 0
    rb.cow_pairs = 0
    t0 = time.perf_counter()
    rb_out = beam_wave(rb)
    rb_dt = time.perf_counter() - t0
    rb_moved = rb.beam_reorder_pages  # MUST stay 0: pure rebinds
    rb_cow = rb.cow_pairs
    assert rb_moved == 0, (
        "rebind beam reorder physically copied %d pages" % rb_moved)
    _flags.set_flag("beam_reorder", "reference")
    try:
        ref = mk_beam()
        beam_wave(ref)  # warm (same content-addressed programs)
        ref.beam_reorder_pages = 0
        t0 = time.perf_counter()
        ref_out = beam_wave(ref)
        ref_dt = time.perf_counter() - t0
        ref_moved = ref.beam_reorder_pages
    finally:
        _flags.set_flag("beam_reorder", "rebind")
    assert ref_moved > 0, "the copy oracle never copied a page"
    for (rt, rs), (ct, cs) in zip(rb_out, ref_out):
        assert np.array_equal(rt, ct) and np.array_equal(rs, cs), \
            "rebind beam diverged from the copy-reorder oracle"
    beam_tok = sum(tokens_of(rt) for rt, _ in rb_out)
    page_bytes = 2 * cfg["n_layer"] * n_head * ps * (dm // n_head) * 4
    beam_speedup = (beam_tok / rb_dt) / (beam_tok / ref_dt)

    # --- speculative A/B (PR 16): draft-then-verify vs the sequential
    # off-oracle on the SAME session — a flag flip between waves, so
    # the ratio is pure dispatch amortization over identical tokens.
    # The n-gram drafter only pays off when the decode stream actually
    # repeats, so this arm runs the prompt-lookup regime speculative
    # decoding exists for: a briefly copy-trained model over periodic
    # sources. Training runs LAST, in its own programs — every
    # deterministic budget above was captured before a weight moved.
    tr_main, tr_startup = fluid.Program(), fluid.Program()
    tr_main.random_seed = 21
    tr_startup.random_seed = 21
    # fresh unique_name scope: the training build must mint the SAME
    # param names as the leg's first build (the names every decode
    # session binds), or Adam would train a disconnected copy
    with fluid.program_guard(tr_main, tr_startup), \
            fluid.unique_name.guard({}):
        loss, _feeds, _extras = transformer.build(
            dropout=0.0, label_smooth_eps=0.0, max_length=seq,
            d_model=dm, **cfg)
        fluid.optimizer.Adam(learning_rate=0.003).minimize(loss)
    exe.run(tr_startup)
    trng = np.random.RandomState(22)
    for _ in range(300):
        ts = trng.randint(3, vocab, (16, seq)).astype("int64")
        ttrg = np.full_like(ts, 1)
        ttrg[:, 1:] = ts[:, :-1]
        full = np.full((16, 1), seq, "int64")
        exe.run(tr_main, feed={"src_word": ts, "src_len": full,
                               "trg_word": ttrg, "trg_len": full,
                               "label": ts}, fetch_list=[loss])
    motif = trng.randint(3, vocab, (B, 4)).astype("int64")
    src_sp = np.tile(motif, (1, seq // 4))
    # two periods of forced prefix: the drafter suffix-matches over
    # emitted tokens + forced prefix, so admission seeds the lookup
    # and the first verify already speculates at full acceptance
    pfx_sp = [[int(t) for t in row[:8]] for row in src_sp]

    spec = SlotDecodeSession(
        exe, num_slots=S, max_length=seq, d_model=dm, paged=True,
        page_size=ps, steps=1,
        speculative={"k": 3, "drafter": "ngram"}, **cfg)

    def spec_wave(sess):
        return drain(sess, [sess.admit(src_sp[i], seq,
                                       prefix_tokens=pfx_sp[i])
                            for i in range(B)])

    spec_wave(spec)  # warm the draft/tree-verify set
    _flags.set_flag("speculative", "off")
    try:
        spec_wave(spec)  # warm the sequential step too
        t0 = time.perf_counter()
        off_out = spec_wave(spec)
        off_dt = time.perf_counter() - t0
    finally:
        _flags.set_flag("speculative", "on")
    p0, a0 = spec.spec_proposed, spec.spec_accepted
    t0 = time.perf_counter()
    sp_out = spec_wave(spec)
    sp_dt = time.perf_counter() - t0
    assert np.array_equal(sp_out, off_out), \
        "speculative decode diverged from the sequential off-oracle"
    sp_tok = tokens_of(sp_out)
    accept_rate = ((spec.spec_accepted - a0) / (spec.spec_proposed - p0)
                   if spec.spec_proposed > p0 else 0.0)

    acc = pk.grid_accounting(mixed + [0] * (S - B), ps, n_head,
                             dm // n_head, seq, num_groups=2,
                             n_layer=cfg["n_layer"])
    return {
        "metric": "decode_tokens_per_sec" + ("" if on_tpu
                                             else "_cpu_proxy"),
        "value": round(p_tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": None,
        "dense_tokens_per_sec": round(d_tps, 1),
        "paged_speedup": round(p_tps / d_tps, 3),
        "token_latency_ms": round(1000.0 * p_dt / p_tok, 3),
        "predicted_hbm_bytes": acc["hbm_bytes"],
        "hbm_vs_dense_ratio": round(
            acc["hbm_bytes"] / acc["dense_hbm_bytes"], 4),
        "decode_steps_per_dispatch": K,
        "pool_occupancy": B / S,
        # cross-request reuse (PR 12): best-of-4 x 2 sources, shared
        # (admit_group: 1 encoder + 1 prefill + joins per source) vs
        # unshared (8 solo admissions), bit-identical token matrices
        "bestofn_speedup": round(sh_tps / un_tps, 3),
        "bestofn_tokens_per_sec": round(sh_tps, 1),
        "prefix_hit_rate": round(pstats["hit_rate"], 3),
        "prefill_tokens_saved": pstats["tokens_saved"],
        # grouped cross-pool footprint: [G=2, H, T, dh] per layer vs
        # the per-slot dense layout — deterministic, gated
        "cross_kv_bytes": acc["cross_hbm_bytes"],
        "cross_kv_dense_bytes": acc["cross_dense_hbm_bytes"],
        # beam A/B (PR 15): rebind-vs-copy tokens/sec ratio over
        # bit-identical n-bests, and the rebind wave's physically-moved
        # bytes (reorder copies — zero — plus write-page COW splits,
        # page-geometry-accounted). ref_reorder_bytes is the oracle's
        # O(resident) traffic for scale.
        "beam_speedup": round(beam_speedup, 3),
        "beam_tokens_per_sec": round(beam_tok / rb_dt, 1),
        "beam_reorder_bytes": (rb_moved + rb_cow) * page_bytes,
        "beam_ref_reorder_bytes": ref_moved * page_bytes,
        # speculative A/B (PR 16): draft-then-verify tokens/sec over
        # the sequential steps=1 off-oracle on the SAME session
        # (bit-identical tokens asserted), plus the drafter's measured
        # acceptance over the timed wave
        "speculative_speedup": round(
            (sp_tok / sp_dt) / (sp_tok / off_dt), 3),
        "speculative_tokens_per_sec": round(sp_tok / sp_dt, 1),
        "acceptance_rate": round(accept_rate, 3),
        "rate": p_tps,
        "gflop_per_unit": 0.0,
    }


def _worker_main():
    """One model bench in this process. Prints one JSON line.

    Runs under the orchestrator's timeout, so a hang here is recoverable
    there; any exception is caught and reported as {"error": ...} with
    exit 0 so the parent gets structured data either way.
    """
    model = os.environ.get("BENCH_MODEL", "resnet50")
    try:
        import jax

        # BENCH_PLATFORM=cpu forces the CPU backend (the axon TPU plugin
        # ignores JAX_PLATFORMS; a wedged tunnel hangs device enumeration).
        if os.environ.get("BENCH_PLATFORM"):
            jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

        import paddle_tpu as fluid

        on_tpu = any(d.platform != "cpu" for d in jax.devices())
        use_amp = os.environ.get("BENCH_AMP", "1" if on_tpu else "0") == "1"
        if model == "transformer":
            result = _bench_transformer(fluid, on_tpu, use_amp)
        elif model == "serving":
            result = _bench_serving(fluid, on_tpu)
        elif model == "frontend":
            result = _bench_frontend(fluid, on_tpu)
        elif model == "decode":
            result = _bench_decode(fluid, on_tpu)
        else:
            result = _bench_resnet(fluid, on_tpu, use_amp)
        peak = _peak_tflops(jax.devices()[0]) if on_tpu else None
        rate = result.pop("rate")
        gflop = result.pop("gflop_per_unit")
        result["mfu"] = (
            round(rate * gflop * 1e9 / (peak * 1e12), 4)
            if peak and gflop else None
        )
        # compile-tax telemetry (core/exec_cache.py): cold = seconds in
        # fresh XLA compiles, warm = seconds loading cached executables.
        # With FLAGS_exec_cache_dir set to a warm dir, cold drops to ~0 —
        # the bench trajectory tracks the compile tax either way.
        from paddle_tpu.core import exec_cache

        cache = exec_cache.stats()
        result["compile_seconds_cold"] = round(
            cache["compile_seconds_cold"], 3)
        result["compile_seconds_warm"] = round(
            cache["compile_seconds_warm"], 3)
        result["exec_cache"] = {
            "enabled": cache["enabled"],
            "fresh_compiles": cache["fresh_compiles"],
            "persistent_hits": cache["persistent_hits"],
            "aot_hits": cache["aot_hits"],
        }
        # both models' gflop_per_unit now count 2 FLOPs per MAC, matching
        # the peak's convention; pre-r5 ResNet records used GMACs and
        # read 2x low (see TRAIN_GFLOP_PER_IMG note)
        result["flop_convention"] = "2-per-mac"
        # flight-recorder view (observability/telemetry.py): step-time
        # percentiles + the telemetry-side MFU estimate, present only
        # when FLAGS_telemetry=1 (default off keeps the timed loop
        # untouched — the <2% overhead acceptance gate). Best-effort in
        # its own try: an observability failure (bad FLAGS_metrics_path
        # etc.) must never discard a fully measured bench result.
        try:
            from paddle_tpu.observability import telemetry

            if telemetry.ENABLED:
                st = telemetry.step_stats(
                    peak=peak * 1e12 if peak else None)
                result["step_ms"] = {
                    "p50": round(st["p50_ms"], 3) if st["p50_ms"] else None,
                    "p95": round(st["p95_ms"], 3) if st["p95_ms"] else None,
                    "p99": round(st["p99_ms"], 3) if st["p99_ms"] else None,
                }
                result["mfu_telemetry"] = (
                    round(st["mfu"], 4) if st["mfu"] else None)
                # HBM trajectory (observability/memory.py): measured
                # ledger watermark + the planner's prediction, so
                # BENCH_*.json tracks footprint alongside MFU and
                # tools/perf_diff.py can gate regressions on it
                from paddle_tpu import profiler as _profiler

                ms = _profiler.memory_stats()
                result["peak_hbm_bytes"] = ms["measured_peak_bytes"]
                result["predicted_peak_bytes"] = ms["predicted_peak_bytes"]
                telemetry.flush()  # FLAGS_metrics_path scrape, if set
        except Exception as e:  # noqa: BLE001
            result["telemetry_error"] = "%s: %s" % (type(e).__name__, e)
    except Exception as e:  # noqa: BLE001 - report, never crash the capture
        result = {"metric": model, "error": "%s: %s" % (type(e).__name__, e)}
    else:
        result["platform"] = "tpu" if on_tpu else "cpu"
    print(json.dumps(result))
    sys.stdout.flush()


def _run_isolated(argv, timeout_s, env=None):
    """Run argv in its own process GROUP with stdout/stderr captured to
    temp files; on timeout SIGKILL the whole group. Returns (rc, stdout,
    stderr) with rc=None on timeout.

    subprocess.run(capture_output=True, timeout=...) is NOT enough here:
    on timeout it kills only the direct child and then blocks in
    communicate() until pipe EOF — a wedged axon helper process that
    inherited the pipe would hang the orchestrator forever, the very
    failure mode this file exists to prevent. Files have EOF regardless.
    """
    import signal
    import subprocess
    import tempfile

    with tempfile.TemporaryFile("w+", errors="replace") as fout, \
            tempfile.TemporaryFile("w+", errors="replace") as ferr:
        proc = subprocess.Popen(
            argv, stdout=fout, stderr=ferr, env=env, start_new_session=True,
        )
        try:
            rc = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.wait()
            rc = None
        fout.seek(0)
        ferr.seek(0)
        return rc, fout.read(), ferr.read()


def _probe_tpu(timeout_s):
    """Ask a subprocess whether a non-CPU jax backend comes up. Returns
    the device_kind string, or None (unavailable / wedged / timed out)."""
    code = (
        "import jax\n"
        "d = jax.devices()[0]\n"
        "print('BENCHPROBE|' + d.platform + '|' +"
        " (getattr(d, 'device_kind', '') or ''))\n"
    )
    try:
        rc, stdout, stderr = _run_isolated(
            [sys.executable, "-c", code], timeout_s
        )
    except Exception:
        return None
    if rc != 0:
        # keep the probe's diagnostics (tunnel/backend errors) on record
        sys.stderr.write(stderr[-4000:])
    for line in stdout.splitlines():
        if line.startswith("BENCHPROBE|"):
            _, platform, kind = line.split("|", 2)
            if platform != "cpu":
                return kind or platform
    return None


def _run_worker(model, platform, timeout_s):
    """Run one model bench in a subprocess; return (dict-or-None, err)."""
    env = dict(os.environ, BENCH_MODEL=model)
    if platform == "cpu":
        env["BENCH_PLATFORM"] = "cpu"
        # the TPU-tunnel plugin registers at interpreter start via this
        # var, and a WEDGED tunnel then hangs the first jax backend init
        # even on a CPU-only worker — exactly the fallback-path scenario
        env.pop("PALLAS_AXON_POOL_IPS", None)
    try:
        rc, stdout, stderr = _run_isolated(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            timeout_s, env=env,
        )
    except Exception as e:  # noqa: BLE001
        return None, "%s: %s" % (type(e).__name__, e)
    sys.stderr.write(stderr[-8000:])
    if rc is None:
        return None, "timeout after %ds" % timeout_s
    for line in reversed(stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                out = json.loads(line)
            except ValueError:
                continue
            if "error" in out:
                return None, out["error"]
            return out, None
    return None, "worker rc=%d, no JSON on stdout" % rc


def main():
    """Orchestrate both model benches; print ONE JSON line; exit 0."""
    def _int_env(name, default):
        try:
            return int(os.environ.get(name, default))
        except ValueError:
            errors[name] = "unparsable %r, using %d" % (
                os.environ[name], default)
            return default

    errors = {}
    probe_timeout = _int_env("BENCH_PROBE_TIMEOUT", 90)
    # 2700s default: the round-3 first window lost its Transformer capture
    # to a 1500s ceiling while the compile crawled through a degraded
    # tunnel — and the SIGKILL wedged the tunnel for the rest of the round.
    # A healthy worker finishes in ~5 min; the headroom only matters when
    # the tunnel is slow, exactly when killing it costs the window.
    worker_timeout = _int_env("BENCH_WORKER_TIMEOUT", 2700)

    forced_cpu = os.environ.get("BENCH_PLATFORM") == "cpu"
    tpu_kind = None if forced_cpu else _probe_tpu(probe_timeout)

    # single-model BENCH_MODEL (the documented knob) still works;
    # BENCH_MODELS overrides with an explicit list
    models_env = os.environ.get(
        "BENCH_MODELS",
        os.environ.get("BENCH_MODEL",
                       "resnet50,transformer,serving,frontend,decode"))
    models = {}
    for model in [m.strip() for m in models_env.split(",") if m.strip()]:
        if model not in ("resnet50", "transformer", "serving",
                         "frontend", "decode"):
            errors[model] = ("unknown model (valid: resnet50, "
                             "transformer, serving, frontend, decode)")
            continue
        result = err = None
        if tpu_kind is not None:
            result, err = _run_worker(model, "tpu", worker_timeout)
            if err:
                errors[model] = "tpu: " + err
        if result is None:
            # CPU-proxy numbers are explicitly marked by the _cpu_proxy
            # metric suffix the worker emits for non-TPU runs.
            result, err = _run_worker(model, "cpu", worker_timeout)
            if err:
                errors[model] = (errors.get(model, "") + "; cpu: " + err).strip("; ")
        if result is not None:
            models[model] = result

    primary = models.get("resnet50") or next(iter(models.values()), None)
    if primary is None:
        # no-data sentinel, named so it cannot be mistaken for a measurement
        out = {"metric": "no_result", "value": 0.0, "unit": "none",
               "vs_baseline": None, "mfu": None}
    else:
        out = dict(primary)
    out["models"] = models
    if forced_cpu:
        # requested configuration, not a failure: keep the error channel
        # clean so consumers can key degraded captures on its presence
        out["note"] = "cpu forced via BENCH_PLATFORM; values are cpu proxies"
    elif tpu_kind is None:
        errors["tpu"] = "tpu-unavailable (probe failed or timed out); " \
                        "values are cpu proxies"
        # surface the most recent on-chip captures so a degraded round
        # record still carries the hardware numbers (the tunnel wedges
        # unpredictably; BENCH_NOTES.md documents each window). Newest
        # wins PER MODEL: the best chip numbers for different models can
        # live in different capture files (r3: ResNet in _manual,
        # Transformer in _transformer).
        import glob
        import re

        def _round_of(path):
            m = re.search(r"BENCH_r(\d+)", os.path.basename(path))
            return int(m.group(1)) if m else -1

        here = os.path.dirname(os.path.abspath(__file__))
        per_model = {}
        # numeric round sort: lexicographic would put r10 before r9
        for path in sorted(
            glob.glob(os.path.join(here, "BENCH_r*_manual.json"))
            + glob.glob(os.path.join(here, "BENCH_r*_transformer.json")),
            key=lambda p: (_round_of(p), os.path.basename(p)),
        ):
            try:
                with open(path) as f:
                    cap = json.load(f)
            except (OSError, ValueError):
                continue
            tpu_models = {
                name: m for name, m in (cap.get("models") or {}).items()
                if isinstance(m, dict) and m.get("platform") == "tpu"
            }
            if not tpu_models:
                continue  # a proxy file must not pose as a TPU capture
            out["last_tpu_capture"] = cap
            out["last_tpu_capture_file"] = os.path.basename(path)
            for name, m in tpu_models.items():
                per_model[name] = dict(m, source=os.path.basename(path))
        if per_model:
            out["last_tpu_capture_models"] = per_model
    elif primary is not None and primary.get("platform") == "tpu":
        # only label the capture with the chip when the HEADLINE result
        # actually ran there — CPU-proxy retries must not masquerade as
        # chip numbers (per-model "platform" fields carry the rest)
        out["device_kind"] = tpu_kind
    elif primary is None:
        errors["tpu"] = "probe saw %s but no model produced a result" \
                        % tpu_kind
    else:
        errors["tpu"] = "probe saw %s but the primary model fell back; " \
                        "headline value is a cpu proxy" % tpu_kind
    if errors:
        out["error"] = "; ".join("%s: %s" % kv for kv in sorted(errors.items()))
    print(json.dumps(out))
    sys.stdout.flush()

    # BENCH_LEDGER=<path> (or =1 for benchmark/perf_ledger.jsonl) appends
    # this capture as one trajectory point — every measured run lands in
    # the same append-only file tools/perf_ledger.py diff gates. Strictly
    # best-effort AFTER the JSON line is out: the capture contract ("bench
    # always exits 0 with one parseable line") must survive a read-only
    # checkout or a half-broken tools/ import.
    ledger_env = os.environ.get("BENCH_LEDGER")
    if ledger_env and models:
        try:
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "tools"))
            import perf_ledger
            ledger = (perf_ledger.DEFAULT_LEDGER if ledger_env == "1"
                      else ledger_env)
            good = {name: m for name, m in models.items()
                    if isinstance(m, dict) and "error" not in m}
            if good:
                perf_ledger.append_entry(
                    ledger, good,
                    label=os.environ.get("BENCH_LEDGER_LABEL"),
                    source="bench.py")
                sys.stderr.write("bench: appended %d model(s) to %s\n"
                                 % (len(good), ledger))
        except Exception as e:
            sys.stderr.write("bench: ledger append failed (%s)\n" % e)


if __name__ == "__main__":
    if "--worker" in sys.argv[1:]:
        _worker_main()
    else:
        main()
