"""Bank an on-chip bench capture into the round's driver-format BENCH file.

VERDICT r3 Weak #5: three rounds running, the driver's end-of-round
BENCH_r{N}.json degraded to a CPU proxy while fresher chip numbers sat in
manual capture files. Fix: every successful capture immediately rewrites
``BENCH_r05_manual.json`` at the repo root in the driver's own format, so
bench.py's degraded path (which embeds the newest ``BENCH_r*_manual.json``
as ``last_tpu_capture``) and any human reader always see the latest
hardware truth.

Usage:  python tools/bank_capture.py CAPTURE.json TAG
  CAPTURE.json  a file whose last JSON line is bench.py output (driver
                format: {"metric", "value", ..., "models": {...}})
  TAG           experiment tag (e.g. transformer-default, resnet50-bs256)

Behavior:
* refuses captures with no model on platform "tpu" (CPU proxies must
  never overwrite chip numbers) — exit 3, bank untouched;
* merges TPU models into the bank's "models" map when the tag is a
  *-default tag (the driver configuration), and always records the
  capture under "experiments"[TAG] with a UTC timestamp + git rev;
* recomputes the headline (resnet50 if banked, else first model);
* commits the bank file through a private index (tools/commit_path.py),
  so the shared index is never written mid-flight (ADVICE r4: the
  check-then-add form was a TOCTOU race; a plain pathspec commit still
  contaminated the shared index).
"""

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BANK_NAME = os.environ.get("BENCH_BANK", "BENCH_r05_manual.json")
if os.path.basename(_BANK_NAME) != _BANK_NAME:
    raise SystemExit("bank_capture: BENCH_BANK must be a bare filename "
                     "(repo root), got %r" % _BANK_NAME)
BANK = os.path.join(ROOT, _BANK_NAME)


def _last_json_line(path):
    last = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line.startswith("{"):
                last = line
    if last is None:
        raise ValueError("no JSON line in %s" % path)
    return json.loads(last)


def _git(*args):
    return subprocess.run(["git", "-C", ROOT] + list(args),
                          capture_output=True, text=True)


def main():
    if len(sys.argv) != 3:
        print(__doc__, file=sys.stderr)
        return 2
    capture_path, tag = sys.argv[1], sys.argv[2]
    try:
        cap = _last_json_line(capture_path)
    except (OSError, ValueError) as e:
        print("bank_capture: unreadable capture: %s" % e, file=sys.stderr)
        return 2

    tpu_models = {
        name: m for name, m in (cap.get("models") or {}).items()
        if isinstance(m, dict) and m.get("platform") == "tpu"
    }
    # single-worker captures (bench.py --worker) have no "models" wrapper
    if not tpu_models and cap.get("platform") == "tpu" and "value" in cap:
        name = "resnet50" if "resnet" in str(cap.get("metric")) else \
            "transformer"
        tpu_models = {name: cap}
    if not tpu_models:
        print("bank_capture: no TPU-platform model in capture; refusing "
              "to bank a CPU proxy", file=sys.stderr)
        return 3

    bank = {}
    if os.path.exists(BANK):
        try:
            with open(BANK) as f:
                bank = json.load(f)
        except ValueError:
            bank = {}
    bank.setdefault("models", {})
    bank.setdefault("experiments", {})

    if tag.endswith("-default"):
        bank["models"].update(tpu_models)
    rev = _git("rev-parse", "--short", "HEAD").stdout.strip()
    bank["experiments"][tag] = {
        "models": tpu_models,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_rev": rev,
    }

    if bank["models"].get("resnet50"):
        headline, headline_from = bank["models"]["resnet50"], "resnet50-default"
    elif bank["models"]:
        name = next(iter(bank["models"]))
        headline, headline_from = bank["models"][name], name + "-default"
    else:
        # no default-config capture banked yet: promote this experiment's
        # first model so the file is never headline-less, but carry the
        # experiment tag so a bs128/seq1024 number can't masquerade as
        # the driver configuration
        headline, headline_from = next(iter(tpu_models.values())), tag
    for k in ("metric", "value", "unit", "vs_baseline", "mfu"):
        bank[k] = headline.get(k)
    bank["headline_from"] = headline_from
    bank["device_kind"] = cap.get("device_kind", bank.get("device_kind"))
    bank["banked_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    bank["git_rev"] = rev

    tmp = BANK + ".tmp"
    with open(tmp, "w") as f:
        json.dump(bank, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, BANK)
    print("banked %s -> %s" % (tag, os.path.basename(BANK)))

    # private-index commit (tools/commit_path.py): never touches the
    # shared index mid-flight, so neither direction of the interactive/
    # watcher commit race can mix files
    from commit_path import commit_path
    rc, out = commit_path(
        os.path.basename(BANK),
        "Bank on-chip capture %s into %s" % (tag, os.path.basename(BANK)))
    print(out)
    if rc != 0:
        # the bank file itself is written (what banked() checks); a
        # failed commit just rides the next commit instead
        print("bank_capture: commit failed; bank file left for the next "
              "commit", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
