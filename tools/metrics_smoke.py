"""Metrics smoke: prove the flight recorder produces a parseable scrape.

Run twice in two subprocesses sharing FLAGS_exec_cache_dir (tools/
run_ci.sh `metrics` stage does exactly that), both with FLAGS_telemetry=1
and FLAGS_metrics_path set:

    FLAGS_telemetry=1 FLAGS_metrics_path=$M FLAGS_exec_cache_dir=$D \
        python tools/metrics_smoke.py cold
    FLAGS_telemetry=1 FLAGS_metrics_path=$M FLAGS_exec_cache_dir=$D \
        python tools/metrics_smoke.py warm

Each pass trains a 3-step MLP, flushes the registry, then re-reads its
own Prometheus file with a strict line parser and asserts:

* the file parses (every non-comment line is ``name{labels} value``);
* ``paddle_tpu_steps_total`` summed over labels is nonzero;
* ``paddle_tpu_step_seconds`` histogram count matches the steps run;
* the step JSONL snapshot exists and every line json-parses;
* warm only: ``paddle_tpu_fresh_compiles_total`` is ZERO — the compile
  telemetry and the persistent executable cache agree that the second
  process paid no XLA compile.
"""

import json
import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

STEPS = 3

_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(-?[0-9.e+-]+|[+-]Inf|NaN)$")


def parse_prometheus(path):
    """{metric_name: {label_blob_or_'': float}} with strict line checks."""
    out = {}
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.rstrip("\n")
            if not line or line.startswith("#"):
                continue
            m = _LINE.match(line)
            assert m, "unparseable line %d: %r" % (lineno, line)
            name, labels, value = m.groups()
            out.setdefault(name, {})[labels or ""] = float(value)
    return out


def train_three_steps():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8])
        hid = fluid.layers.fc(x, size=16, act="relu")
        loss = fluid.layers.mean(fluid.layers.fc(hid, size=1))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": np.arange(32, dtype="float32").reshape(4, 8) / 32.0}
    for _ in range(STEPS):
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
    assert np.isfinite(np.asarray(lv)).all()


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "cold"
    metrics_path = os.environ.get("FLAGS_metrics_path")
    if not metrics_path:
        print("metrics_smoke: FLAGS_metrics_path not set", file=sys.stderr)
        return 2
    train_three_steps()

    from paddle_tpu.observability import explain, telemetry

    assert telemetry.ENABLED, "FLAGS_telemetry=1 did not enable telemetry"
    telemetry.flush()

    metrics = parse_prometheus(metrics_path)
    steps = sum(metrics.get("paddle_tpu_steps_total", {}).values())
    fresh = sum(metrics.get("paddle_tpu_fresh_compiles_total", {}).values())
    hist_count = sum(
        v for k, v in metrics.get("paddle_tpu_step_seconds_count",
                                  {}).items())
    with open(metrics_path + ".steps.jsonl") as f:
        step_lines = [json.loads(line) for line in f if line.strip()]

    print("metrics_smoke[%s]: %s" % (mode, json.dumps({
        "steps_total": steps, "fresh_compiles_total": fresh,
        "step_seconds_count": hist_count, "jsonl_records": len(step_lines),
        "explainer_events": len(explain.events()),
    })))

    # startup + 3 train steps all record; the histogram sees the same
    assert steps >= STEPS, "steps_total=%r, expected >= %d" % (steps, STEPS)
    assert hist_count == steps, (
        "histogram count %r disagrees with steps_total %r"
        % (hist_count, steps))
    assert step_lines and all("step_s" in r for r in step_lines), (
        "step JSONL snapshot missing or malformed")
    if mode == "warm":
        assert fresh == 0, (
            "warm process scrape shows %d fresh XLA compile(s); the "
            "persistent cache and the metrics disagree" % fresh)
    else:
        assert fresh > 0, "cold process scrape shows no compiles at all"
        # one explainer event per fresh trace, never more
        assert len(explain.events()) >= 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
