"""Sharding-transpiler smoke: the derived data x fsdp x tp plan is
correct, memory-proportional, and warm-startable (tools/run_ci.sh
`shard` stage).

Run twice in two subprocesses sharing FLAGS_exec_cache_dir, on the
8-virtual-device CPU mesh:

    FLAGS_exec_cache_dir=$D python tools/shard_smoke.py cold
    FLAGS_exec_cache_dir=$D python tools/shard_smoke.py warm

Each pass asserts, with ZERO hand-written tp_layout entries:

1. **Parity** — the transformer block trained on a (data=2, fsdp=2,
   tp=2) mesh via the derived plan matches the single-device loss
   trajectory step for step (tolerance 1e-4).
2. **1/N ledger bytes** — per-device param+opt_state ledger bytes under
   a 4-way fsdp x tp split stay under ~1/4 + crumbs of the replicated
   footprint (``paddle_tpu_hbm_live_bytes{device,kind}``), and the
   predicted memory plan divides by the shard factors.
3. **Warm start** (warm pass only) — the sharded executable comes back
   from the persistent exec cache with zero fresh XLA compiles.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

STEPS = 3
TOL = 1e-4


def _feeds():
    rng = np.random.RandomState(41)
    return [{"x": rng.randn(16, 8, 32).astype("float32"),
             "label": rng.randint(0, 8, (16, 1)).astype("int64")}
            for _ in range(STEPS)]


def _build():
    import __graft_entry__

    return __graft_entry__.build_tp_block_program(
        seed=23, d_model=32, d_ff=64, nclass=8)


def run_single(feeds):
    import paddle_tpu as fluid

    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    out = []
    for feed in feeds:
        lv, = exe.run(main, feed=feed, fetch_list=[loss])
        out.append(float(np.ravel(np.asarray(lv))[0]))
    return out


def run_derived(feeds):
    import paddle_tpu as fluid
    from paddle_tpu.observability import memory, telemetry
    from paddle_tpu.parallel_executor import ParallelExecutor

    telemetry.enable(True)
    memory.enable(True)
    memory.reset()
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    pe = ParallelExecutor(loss_name=loss.name, main_program=main,
                          use_tpu=False, fsdp=2, tp=2)
    out = []
    for feed in feeds:
        lv, = pe.run(fetch_list=[loss], feed=feed)
        out.append(float(np.ravel(np.asarray(lv))[0]))
    return pe, out


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "cold"
    if not os.environ.get("FLAGS_exec_cache_dir"):
        print("shard_smoke: FLAGS_exec_cache_dir not set", file=sys.stderr)
        return 2
    import jax

    if len(jax.devices()) < 8:
        print("shard_smoke: needs 8 virtual devices, found %d"
              % len(jax.devices()), file=sys.stderr)
        return 2

    import paddle_tpu as fluid  # noqa: F401  (registers flags)
    from paddle_tpu import profiler
    from paddle_tpu.observability import memory
    from paddle_tpu.parallel.sharding import plan_shard_factors

    feeds = _feeds()
    single = run_single(feeds)
    pe, derived = run_derived(feeds)

    # 1. parity, zero overrides
    np.testing.assert_allclose(single, derived, atol=TOL, rtol=TOL)
    plan = pe.sharding_plan()
    assert plan is not None and plan.sharded_params(), (
        "no params sharded — the transpiler derived nothing")
    assert not pe._sharding_overrides, "smoke must run with zero overrides"

    # 2. per-device ledger bytes: every TP weight is 4-way split
    # (fsdp x tp), so each device's param bytes must sit well under the
    # replicated footprint. Reconstruct the replicated per-device cost
    # from the plan's own byte accounting.
    by_dev = {}
    for (dev, kind, _name), b in memory._live.items():
        if kind in ("param", "opt_state") and dev != "mesh":
            by_dev[dev] = by_dev.get(dev, 0) + int(b)
    assert len(by_dev) == 8, (
        "state must be booked per device, got %s" % sorted(by_dev))
    factors = plan_shard_factors(plan)
    qkv = "tp_qkv.w"
    assert factors.get(qkv) == 4, (
        "expected %s 4-way sharded, factors=%s" % (qkv, factors))
    stats = profiler.memory_stats()
    assert stats["predicted_peak_bytes"], "memory plan did not register"
    # per-var check on the ledger itself: the qkv weight books 1/4 of
    # its logical bytes on each device label
    logical = 32 * 96 * 4  # f32 [d_model, 3*d_model]
    per_dev = [b for (dev, kind, name), b in memory._live.items()
               if name == qkv and dev != "mesh"]
    assert per_dev and all(b == logical // 4 for b in per_dev), (
        "qkv per-device ledger bytes %s != logical/4 (%d)"
        % (sorted(set(per_dev)), logical // 4))

    # 3. warm start: the sharded executable must come from the cache
    from paddle_tpu.core import exec_cache

    st = exec_cache.stats()
    summary = {
        "mode": mode,
        "mesh_axes": dict(plan.mesh_axes),
        "plan": plan.summary(),
        "losses": derived,
        "fresh_compiles": st["fresh_compiles"],
        "aot_hits": st["aot_hits"],
        "per_device_state_bytes": {d: int(b)
                                   for d, b in sorted(by_dev.items())},
        "predicted_peak_bytes": stats["predicted_peak_bytes"],
    }
    print("shard_smoke[%s]: %s" % (mode, json.dumps(summary)))
    assert st["enabled"], "exec cache did not enable from the flag"
    if mode == "cold":
        assert st["fresh_compiles"] > 0 or st["persistent_hits"] > 0, (
            "cold pass neither compiled nor hit a pre-warmed cache")
    else:
        assert st["fresh_compiles"] == 0, (
            "warm process paid %d fresh XLA compile(s) for the sharded "
            "executable; the persistent cache failed to serve it"
            % st["fresh_compiles"])
        assert st["aot_hits"] >= 1, (
            "warm process loaded no AOT images (aot_misses=%d)"
            % st["aot_misses"])
    return 0


if __name__ == "__main__":
    sys.exit(main())
