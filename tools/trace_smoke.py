"""Request-tracing smoke: prove tracing is FREE when off and COMPLETE
when on, over real sockets.

Run three times in three subprocesses sharing FLAGS_exec_cache_dir
(tools/run_ci.sh ``trace`` stage does exactly that):

    FLAGS_exec_cache_dir=$D/cache python tools/trace_smoke.py cold $D
    FLAGS_exec_cache_dir=$D/cache python tools/trace_smoke.py off  $D
    FLAGS_exec_cache_dir=$D/cache python tools/trace_smoke.py on   $D

The COLD pass builds the seeded decode transformer, warms every
executable the wire path needs, and banks the in-process token-stream
oracle (solo generations, a best-of-2 fork with a forced prefix, the
same prefix again — the cache-hit case).

The OFF pass — the control leg — replays the whole load through
``ServingClient``s over a real socket with ``FLAGS_request_tracing``
unset and asserts the zero-overhead contract: every stream
bit-identical to the cold oracle, the client minted NO trace (no trace
field ever reaches the wire), and the wire scrape reports **0 fresh
compiles** — the warm baseline the traced leg must not move.

The ON pass replays the SAME load with tracing enabled and asserts:

  * streams still bit-identical to the cold oracle (tracing observes,
    never perturbs);
  * the scrape still reports **0 fresh compiles** — the traced leg pays
    the exact compile bill the control leg did: none;
  * every request resolved a trace OVER THE WIRE (the ``trace``
    endpoint) whose span union covers >= 95% of the CLIENT-observed
    wall (root span + queue/prefill/decode/flush children);
  * the TTFT histogram carries a trace-id exemplar that resolves to a
    completed ring record over the wire;
  * ``tools/trace_view.py`` renders the flushed
    ``.traces.jsonl`` (waterfall + ``--perfetto``) and the exported
    Chrome/Perfetto JSON is structurally valid;
  * ``tools/step_breakdown.py --requests`` summarizes the same file.

The capture (``$D/trace.json``: span_coverage, fresh_compiles) gates
via ``tools/perf_diff.py --budgets benchmark/budgets.json --models
trace``.
"""

import json
import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

VOCAB, SEQ, D, S = 40, 16, 32, 4
N_STREAMS = 4
CFG = dict(src_vocab_size=VOCAB, trg_vocab_size=VOCAB, n_layer=1,
           n_head=2, d_inner=64)
COVERAGE_FLOOR = 0.95


def _build_decode_session():
    """The one seeded decode model + session every pass builds
    identically (cross-process determinism: the programs carry the
    seed, so every executable fingerprint matches the cold pass's)."""
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer
    from paddle_tpu.serving.generation import Sampler, SlotDecodeSession

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 13
    startup.random_seed = 13
    with fluid.program_guard(main, startup):
        transformer.build(dropout=0.0, label_smooth_eps=0.0,
                          max_length=SEQ, d_model=D, **CFG)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return SlotDecodeSession(
        exe, num_slots=S, max_length=SEQ, d_model=D, paged=True,
        page_size=4, steps=2, num_groups=2, prefix_cache_pages=8,
        sampler=Sampler(strategy="top_k", top_k=4, temperature=0.9,
                        seed=3), **CFG)


def _decode_load():
    """(src rows, lens, prefix) — the deterministic streaming mix."""
    rng = np.random.RandomState(17)
    src = rng.randint(3, VOCAB, (N_STREAMS + 1, SEQ)).astype("int64")
    lens = [SEQ, 5, SEQ - 1, 7, SEQ]
    prefix = [int(t) for t in src[N_STREAMS][:6]]
    return src, lens, prefix


def _scraped_fresh_compiles(text):
    for line in text.splitlines():
        if line.startswith("paddle_tpu_fresh_compiles_total "):
            return int(float(line.split()[-1]))
    raise AssertionError(
        "scrape carries no paddle_tpu_fresh_compiles_total")


def _oracle_streams(sess):
    """The in-process decode oracle both wire legs must equal
    bit-for-bit. Order matters — the wire legs replay admissions in
    this exact order, so slot assignment (and the (seed, slot,
    position) PRNG streams) line up."""
    src, lens, prefix = _decode_load()
    out = {}
    for i in range(N_STREAMS):
        out["solo_%d" % i] = sess.generate(
            src[i][None, :], [lens[i]]).tolist()
    out["bestof"] = sess.generate_best_of(
        src[N_STREAMS], 2, src_len=lens[N_STREAMS],
        prefix_tokens=prefix).tolist()
    out["prefix_hit"] = sess.generate_best_of(
        src[N_STREAMS], 2, src_len=lens[N_STREAMS],
        prefix_tokens=prefix).tolist()
    return out


def cold(workdir):
    sess = _build_decode_session()
    streams = _oracle_streams(sess)
    with open(os.path.join(workdir, "trace_oracle.json"), "w") as f:
        json.dump({"streams": streams}, f)
    print("trace_smoke[cold]: banked %d stream oracles, executables "
          "warmed" % len(streams))
    return 0


def _replay_streams(client, oracle, collect=None):
    """Replay the full streaming load, asserting bit parity per stream.
    ``collect``: optional list; (trace_id, client_wall_s) per request
    lands there — the traced leg's coverage input."""
    src, lens, prefix = _decode_load()

    def timed(key, *args, **kw):
        t0 = time.time()
        rows = client.generate_full(*args, **kw)
        wall = time.time() - t0
        assert rows.tolist() == oracle[key], (
            "wire stream %r diverged from the cold oracle" % key)
        if collect is not None:
            collect.append((client.last_trace_id, wall))

    for i in range(N_STREAMS):
        timed("solo_%d" % i, src[i], src_len=lens[i])
    timed("bestof", src[N_STREAMS], src_len=lens[N_STREAMS], n=2,
          prefix_tokens=prefix)
    timed("prefix_hit", src[N_STREAMS], src_len=lens[N_STREAMS], n=2,
          prefix_tokens=prefix)


def off(workdir):
    """The control leg: tracing off, streams bit-identical, zero fresh
    compiles, no trace field ever minted."""
    from paddle_tpu.observability import tracing
    from paddle_tpu.serving import ServingClient, ServingFrontend

    assert not tracing.ENABLED, \
        "control leg started with FLAGS_request_tracing set"
    with open(os.path.join(workdir, "trace_oracle.json")) as f:
        oracle = json.load(f)["streams"]
    sess = _build_decode_session()
    fe = ServingFrontend(session=sess)
    try:
        cl = ServingClient(fe.address)
        _replay_streams(cl, oracle)
        assert cl.last_trace_id is None, (
            "tracing-off client minted a trace id — the envelope grew "
            "a trace field on the zero-overhead path")
        fresh = _scraped_fresh_compiles(cl.metrics())
        assert fresh == 0, (
            "tracing-OFF control leg paid %d fresh compile(s)" % fresh)
        assert not tracing.completed() and not tracing.inflight_ids(), \
            "tracing-off process accumulated trace records"
        cl.close()
    finally:
        fe.close()
    with open(os.path.join(workdir, "trace_off.json"), "w") as f:
        json.dump({"fresh_compiles": fresh}, f)
    print("trace_smoke[off]: %d streams bit-identical, 0 fresh "
          "compiles, no trace minted" % len(oracle))
    return 0


def _assert_tools_render(workdir, traces_path, n_traces):
    """trace_view renders the flushed JSONL (waterfall + Perfetto) and
    step_breakdown --requests summarizes it."""
    tools = os.path.dirname(os.path.abspath(__file__))
    pf = os.path.join(workdir, "perfetto.json")
    view = subprocess.run(
        [sys.executable, os.path.join(tools, "trace_view.py"),
         traces_path, "--slowest", "3", "--perfetto", pf],
        capture_output=True, text=True)
    assert view.returncode == 0, (
        "trace_view failed on the flushed traces: %s" % view.stderr)
    assert "decode.step" in view.stdout and "coverage=" in view.stdout, \
        "trace_view waterfall missing spans/stats"
    with open(pf) as f:
        doc = json.load(f)
    events = doc.get("traceEvents")
    assert events, "perfetto export carries no traceEvents"
    for ev in events:
        assert ev["ph"] in ("X", "M") and "ts" in ev and "pid" in ev, \
            "malformed perfetto event: %r" % ev
        assert ev["ph"] != "X" or ev["dur"] >= 0, \
            "negative-duration perfetto slice: %r" % ev
    brk = subprocess.run(
        [sys.executable, os.path.join(tools, "step_breakdown.py"),
         "--requests", traces_path, "--top", "2"],
        capture_output=True, text=True)
    assert brk.returncode == 0, (
        "step_breakdown --requests failed: %s" % brk.stderr)
    summary = json.loads(brk.stdout.splitlines()[0])
    assert summary["requests"] >= n_traces, summary


def on(workdir):
    """The traced leg: same load, same bytes, same compile bill — plus
    one complete trace per request, resolvable over the wire."""
    from paddle_tpu.observability import tracing
    from paddle_tpu.serving import ServingClient, ServingFrontend
    from paddle_tpu.serving.frontend import _fe_ttft

    tracing.enable(True)
    with open(os.path.join(workdir, "trace_oracle.json")) as f:
        oracle = json.load(f)["streams"]
    with open(os.path.join(workdir, "trace_off.json")) as f:
        fresh_off = json.load(f)["fresh_compiles"]
    sess = _build_decode_session()
    fe = ServingFrontend(session=sess)
    collected = []
    try:
        cl = ServingClient(fe.address)
        _replay_streams(cl, oracle, collect=collected)
        # -- compile counters unchanged vs the control leg ------------------
        fresh = _scraped_fresh_compiles(cl.metrics())
        assert fresh == fresh_off == 0, (
            "tracing-ON leg moved the compile bill: %d fresh (control "
            "leg paid %d)" % (fresh, fresh_off))
        # -- every request: one wire-resolvable trace, >=95% coverage -------
        coverages = []
        for tid, wall in collected:
            assert tid, "traced client minted no trace id"
            rec = cl.trace(tid)
            assert rec and rec["trace_id"] == tid, (
                "trace %s unresolvable over the wire" % tid)
            union = tracing._union_seconds(rec["spans"], rec["t1"])
            cov = min(1.0, union / max(wall, 1e-9))
            coverages.append(cov)
            assert cov >= COVERAGE_FLOOR, (
                "trace %s spans cover %.4f of the client-observed "
                "%.1fms wall (< %.2f): %r"
                % (tid, cov, wall * 1e3,
                   COVERAGE_FLOOR, rec["stats"]))
            assert rec["stats"]["span_coverage"] >= COVERAGE_FLOOR, (
                "derived span_coverage below floor: %r" % rec["stats"])
        # -- histogram exemplar resolves to a ring record over the wire -----
        ex = _fe_ttft.exemplars()
        assert ex, "TTFT histogram carries no trace-id exemplar"
        ex_id = next(iter(ex.values()))["id"]
        ex_rec = cl.trace(ex_id)
        assert ex_rec and ex_rec["trace_id"] == ex_id, (
            "exemplar %s does not resolve to a completed trace" % ex_id)
        assert not tracing.inflight_ids(), (
            "open traces leaked after all streams finished: %r"
            % tracing.inflight_ids())
        cl.close()
    finally:
        fe.close()
    # -- offline tools over the flushed snapshot ----------------------------
    traces_path = os.path.join(workdir, "m.traces.jsonl")
    n = tracing.write_traces_jsonl(traces_path)
    assert n >= len(collected), (
        "ring flushed %d records for %d requests" % (n, len(collected)))
    _assert_tools_render(workdir, traces_path, len(collected))

    rec = {
        "metric": "trace_span_coverage",
        "value": round(min(coverages), 4),
        "unit": "fraction of client-observed wall",
        "vs_baseline": None,
        "span_coverage": round(min(coverages), 4),
        "fresh_compiles": fresh,
        "requests_traced": len(collected),
        "platform": "cpu",
    }
    print("trace_smoke[on]: %s" % json.dumps(rec))
    with open(os.path.join(workdir, "trace.json"), "w") as f:
        json.dump({"models": {"trace": rec}}, f)
    return 0


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else None
    workdir = sys.argv[2] if len(sys.argv) > 2 else None
    if mode not in ("cold", "off", "on") or not workdir:
        print("usage: trace_smoke.py cold|off|on <workdir>",
              file=sys.stderr)
        return 2
    if not os.environ.get("FLAGS_exec_cache_dir"):
        print("trace_smoke: FLAGS_exec_cache_dir not set",
              file=sys.stderr)
        return 2
    return {"cold": cold, "off": off, "on": on}[mode](workdir)


if __name__ == "__main__":
    sys.exit(main())
