"""Chaos smoke (CI ``chaos`` stage): kill training the way production
does, then prove recovery is exact — not approximate.

Four legs, all asserted from the parent:

1. **Preemption leg** — a TrainSession child is SIGKILLed by a seeded
   chaos kill-point mid-run (no cleanup, like a real preemption). A
   restarted child must resume from the newest COMPLETE serial and its
   loss trajectory must equal an uninterrupted reference run at the same
   total step count **bit for bit** (RNG stream restored, dropout masks
   and all).
2. **Transient-fault leg** — a child runs with injected transient
   dispatch faults under ``FLAGS_dispatch_retries``: it must complete
   successfully, ``paddle_tpu_retries_total`` must be nonzero in the
   metrics scrape, and the black box must carry the ``retry`` and
   ``chaos_fault`` flight events (a run that silently survived faults is
   an incident report, not a clean run).
3. **Corruption leg** — the parent flips bytes in the newest checkpoint;
   the next child must quarantine it (``.corrupt-`` dir kept for
   autopsy) and resume from the previous complete serial.
4. **OOM leg** — a child with retries ENABLED hits an injected
   ``oom`` fault at ``exec.dispatch`` (a RESOURCE_EXHAUSTED allocator
   death, deterministic). It must die on the FIRST attempt — zero
   retries in the scrape, no budget burned replaying a deterministic
   failure — and leave a black box whose M001 diagnostic names the
   top-3 live-buffer holders; ``tools/blackbox_dump.py`` must surface
   it with its distinct exit code (4).

The ``child`` subcommand is the training worker (also driven directly by
``tests/test_resilience.py``): a deterministic 2-layer MLP + dropout
TrainSession loop whose per-step feeds are a pure function of the step
index, so any two runs at equal step counts are comparable bit-exactly.

Usage: python tools/chaos_smoke.py            # parent, runs all legs
       python tools/chaos_smoke.py child --mode {ref|train|sigterm} \
           --ckpt-dir D --steps N --out F     # worker (internal)
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

INTERVAL_STEPS = 4


# ---------------------------------------------------------------------------
# child: the deterministic training worker
# ---------------------------------------------------------------------------

def _feed_for(step):
    import numpy as np

    r = np.random.RandomState(1000 + step)
    return {"x": r.rand(8, 4).astype("float32"),
            "y": r.rand(8, 1).astype("float32")}


def _child(args):
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.resilience import TrainSession

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], stop_gradient=False)
        y = fluid.layers.data("y", [1])
        h = fluid.layers.fc(x, 8, act="relu")
        h = fluid.layers.dropout(h, 0.3)  # RNG-dependent on purpose
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.05).minimize(loss)
    # BOTH programs get the fixed seed: the startup program's initializer
    # RNG must be process-independent too, or no two children ever agree
    main.random_seed = 17
    startup.random_seed = 17

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    sess = TrainSession(exe, args.ckpt_dir, main_program=main,
                        interval_steps=INTERVAL_STEPS)
    resumed_step = sess.step
    losses = []
    while sess.step < args.steps:
        if args.mode == "sigterm" and len(losses) == 3:
            # preemption notice to self: the session handler must finish
            # cleanly — final checkpoint, then death BY the signal
            os.kill(os.getpid(), signal.SIGTERM)
            raise SystemExit("unreachable: SIGTERM should have killed us")
        out = sess.run(feed=_feed_for(sess.step), fetch_list=[loss])
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
        # a realistic step is 100ms+ of device time; the toy CPU step is
        # sub-ms, which would give the async checkpoint writer no window
        # at all before a seeded kill lands a few steps later
        time.sleep(0.05)
    sess.close()
    with open(args.out, "w") as f:
        json.dump({
            "losses": losses,
            "final_loss": losses[-1] if losses else None,
            "resumed_step": resumed_step,
            "total_step": sess.step,
        }, f)


# ---------------------------------------------------------------------------
# parent: the three legs
# ---------------------------------------------------------------------------

def _env(chaos_spec="", **extra):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(JAX_PLATFORMS="cpu", FLAGS_chaos_spec=chaos_spec)
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _run_child(tmp, name, mode, steps, env):
    out = os.path.join(tmp, "out_%s.json" % name)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "child",
         "--mode", mode, "--ckpt-dir", os.path.join(tmp, name, "ckpt"),
         "--steps", str(steps), "--out", out],
        env=env, timeout=300)
    return proc.returncode, out


def _load(out):
    with open(out) as f:
        return json.load(f)


def _preemption_leg(tmp):
    rc, ref_out = _run_child(tmp, "ref", "ref", 12, _env())
    assert rc == 0, "reference run failed rc=%d" % rc
    ref = _load(ref_out)

    rc, _ = _run_child(tmp, "kill", "train", 12,
                       _env(chaos_spec="kill@step=7"))
    assert rc == -signal.SIGKILL, (
        "victim should die BY SIGKILL (rc=-9), got rc=%d" % rc)
    rc, out = _run_child(tmp, "kill", "train", 12, _env())
    assert rc == 0, "resumed run failed rc=%d" % rc
    res = _load(out)
    assert res["resumed_step"] > 0, "must resume from a checkpoint"
    assert res["losses"] == ref["losses"][res["resumed_step"]:], (
        "resumed trajectory diverged from the uninterrupted run:\n"
        "ref tail: %s\nresumed:  %s"
        % (ref["losses"][res["resumed_step"]:], res["losses"]))
    print("chaos preemption leg OK: SIGKILL at step 7, resumed at %d, "
          "trajectory bit-identical" % res["resumed_step"])


def _retry_leg(tmp):
    prom = os.path.join(tmp, "retry.prom")
    box = os.path.join(tmp, "retry.box.json")
    rc, out = _run_child(
        tmp, "retry", "train", 8,
        _env(chaos_spec="seed=5;compile@site=exec.dispatch,n=2",
             FLAGS_dispatch_retries=3, FLAGS_retry_backoff_s=0.01,
             FLAGS_metrics_path=prom, FLAGS_blackbox_path=box))
    assert rc == 0, (
        "run with injected transient faults + retries should SUCCEED, "
        "got rc=%d" % rc)
    res = _load(out)
    assert res["total_step"] == 8
    with open(prom) as f:
        scrape = f.read()
    retr = [line for line in scrape.splitlines()
            if line.startswith("paddle_tpu_retries_total")]
    total = sum(float(line.rsplit(None, 1)[-1]) for line in retr)
    assert total > 0, "metrics must show retries, scrape had: %r" % retr
    with open(box) as f:
        kinds = [e["kind"] for e in json.load(f)["events"]]
    assert "retry" in kinds and "chaos_fault" in kinds, kinds
    print("chaos retry leg OK: %d retries recorded, run completed, "
          "black box carries retry + chaos_fault events" % int(total))


def _corruption_leg(tmp):
    rc, _ = _run_child(tmp, "corrupt", "train", 12, _env())
    assert rc == 0
    ckpt = os.path.join(tmp, "corrupt", "ckpt")
    serials = sorted(
        int(d[len("checkpoint_"):]) for d in os.listdir(ckpt)
        if d.startswith("checkpoint_")
        and d[len("checkpoint_"):].isdigit())
    latest = serials[-1]
    victim_dir = os.path.join(ckpt, "checkpoint_%d" % latest)
    victim = next(f for f in sorted(os.listdir(victim_dir))
                  if f.endswith(".npy"))
    with open(os.path.join(victim_dir, victim), "r+b") as f:
        f.seek(-4, os.SEEK_END)
        f.write(b"\xff\xff\xff\xff")
    rc, out = _run_child(tmp, "corrupt", "train", 16, _env())
    assert rc == 0
    res = _load(out)
    assert res["resumed_step"] < latest, (
        "corrupt serial %d must be skipped, resumed at %d"
        % (latest, res["resumed_step"]))
    assert res["resumed_step"] > 0, "older complete serial must load"
    quarantined = [d for d in os.listdir(ckpt) if ".corrupt-" in d]
    assert quarantined, "corrupt serial must be quarantined for autopsy"
    print("chaos corruption leg OK: serial %d quarantined (%s), resumed "
          "from step %d" % (latest, quarantined[0], res["resumed_step"]))


def _oom_leg(tmp):
    prom = os.path.join(tmp, "oom.prom")
    box = os.path.join(tmp, "oom.box.json")
    # skip=3: startup dispatch + two clean train steps pass (populating
    # the ledger: params, opt state, feeds), the third step's dispatch
    # dies RESOURCE_EXHAUSTED — deterministic, like a real allocator OOM
    rc, _out = _run_child(
        tmp, "oom", "train", 8,
        _env(chaos_spec="oom@site=exec.dispatch,skip=3,n=1",
             FLAGS_dispatch_retries=3, FLAGS_retry_backoff_s=0.01,
             FLAGS_telemetry=1, FLAGS_metrics_path=prom,
             FLAGS_blackbox_path=box))
    assert rc > 0, (
        "an injected OOM is deterministic and never retried: the run "
        "must die by the exception (got rc=%d)" % rc)
    with open(prom) as f:
        scrape = f.read()
    retr = [line for line in scrape.splitlines()
            if line.startswith("paddle_tpu_retries_total")]
    total = sum(float(line.rsplit(None, 1)[-1]) for line in retr)
    assert total == 0, (
        "OOM must be classified never-transient — %d retry(ies) burned "
        "their budget on a deterministic death: %r" % (int(total), retr))
    with open(box) as f:
        snap = json.load(f)
    diag = snap.get("oom_diagnostic")
    assert diag and diag.get("rule") == "M001", (
        "black box must carry the M001 diagnostic, got %r" % (diag,))
    holders = diag.get("top_holders") or []
    assert len(holders) >= 3, (
        "M001 must name the top-3 live-buffer holders, got %r" % holders)
    kinds = [e["kind"] for e in snap["events"]]
    assert "chaos_fault" in kinds and "oom_diagnostic" in kinds, kinds
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "blackbox_dump.py"),
         box], stdout=subprocess.DEVNULL)
    assert proc.returncode == 4, (
        "blackbox_dump must exit 4 on an M001 dump, got %d"
        % proc.returncode)
    print("chaos oom leg OK: died first attempt, 0 retries, M001 names "
          "%s" % ", ".join(h["name"] for h in holders[:3]))


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "child":
        p = argparse.ArgumentParser()
        p.add_argument("cmd")
        p.add_argument("--mode", choices=["ref", "train", "sigterm"],
                       required=True)
        p.add_argument("--ckpt-dir", required=True)
        p.add_argument("--steps", type=int, required=True)
        p.add_argument("--out", required=True)
        _child(p.parse_args())
        return
    import tempfile

    with tempfile.TemporaryDirectory(prefix="chaos_") as tmp:
        _preemption_leg(tmp)
        _retry_leg(tmp)
        _corruption_leg(tmp)
        _oom_leg(tmp)
    print("chaos smoke OK")


if __name__ == "__main__":
    main()
