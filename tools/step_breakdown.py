"""Training-step decomposition: phase timings + per-HLO-op xprof shares.

The measurement VERDICT r2 called for behind the MFU push ("commit an
xprof/step-decomposition to BENCH_NOTES"): where does the step time go?

Two independent views, printed as JSON lines:

1. Phase timing — the model's program is compiled and timed three ways
   (forward only; forward+backward via append_backward; the full train
   step with the optimizer), so bwd and optimizer cost are the deltas.
2. ``--from-jsonl PATH`` — skip the model runs entirely and summarize an
   EXISTING telemetry snapshot (the ``<FLAGS_metrics_path>.steps.jsonl``
   a training/serving process left behind); ``--per-device`` adds the
   per-device view over the labeled step records (dispatch->ready time
   per device and the straggler ratio) that the multichip telemetry
   writes into each record; ``--memory`` adds the HBM view — per-step
   peak watermark trajectory, predicted-vs-measured peak, and the top
   ledger holders (observability/memory.py writes all three into the
   records). ``--requests PATH`` is the serving twin: the per-REQUEST
   view over a request-trace snapshot (the
   ``<FLAGS_metrics_path>.traces.jsonl`` a FLAGS_request_tracing=1
   serving process left behind) — fleet TTFT / queue / prefill /
   decode split plus the top-N slowest requests by trace id
   (``tools/trace_view.py`` renders any one of them as a waterfall).
   ``--steps PATH`` (a ``.stepprof.jsonl`` from FLAGS_step_profile=1)
   is the training twin: per-step phase split (input wait / feed /
   compile / dispatch / device / fetch / host), achieved-MFU
   percentiles, starvation fraction, and the top-N slowest steps with
   per-phase attribution and regression flags.
3. ``--xprof`` — run the full step under ``jax.profiler.trace`` and
   aggregate XLA op self-times from the xplane.pb the profiler writes.
   The xplane wire format is decoded directly (a ~60-line generic
   protobuf reader; the tensorboard_plugin_profile converter in this
   image is incompatible with its tensorflow build, and the schema —
   XPlane{name=2, lines=3, event_metadata=4} / XLine{name=2, events=4} /
   XEvent{metadata_id=1, duration_ps=3} — is stable across xprof
   versions). Top-N ops by total self time, with % of the plane.

Usage (CPU smoke / TPU real):
  BENCH_PLATFORM=cpu python tools/step_breakdown.py --model resnet50 --xprof
  python tools/step_breakdown.py --model resnet50 --steps 20 --xprof
"""

import argparse
import glob
import json
import os
import sys
import tempfile
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# xplane.pb decoding (generic protobuf wire reader; schema constants above)
# ---------------------------------------------------------------------------


def _varint(buf, i):
    v = s = 0
    while True:
        b = buf[i]
        i += 1
        v |= (b & 0x7F) << s
        if not b & 0x80:
            return v, i
        s += 7


def _fields(buf):
    i = 0
    out = []
    while i < len(buf):
        tag, i = _varint(buf, i)
        fn, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        else:
            raise ValueError("unsupported wire type %d" % wt)
        out.append((fn, wt, v))
    return out


def op_times_from_xplane(path, plane_filter=None):
    """{plane_name: {line_name: {op_name: total_self_time_ps}}} from one
    xplane.pb. Aggregation is PER LINE: a TPU device plane carries several
    XLines ("Steps", "XLA Modules", "XLA Ops", ...) whose events nest —
    summing across lines multiply-counts the same wall time and, worse,
    drowns the HLO op names in step-number events (the round-3 capture's
    "op 54: 90.7%" artifact, VERDICT r3 Weak #4)."""
    data = open(path, "rb").read()
    result = {}
    for fn, wt, plane_buf in _fields(data):
        if fn != 1 or wt != 2:  # XSpace.planes
            continue
        plane = _fields(plane_buf)
        name = next((v.decode("utf-8", "replace")
                     for f, w, v in plane if f == 2 and w == 2), "")
        if plane_filter and plane_filter not in name:
            continue
        # event metadata id -> name (map entries: key=1, value=XEventMetadata)
        md = {}
        for f, w, v in plane:
            if f != 4 or w != 2:
                continue
            entry = _fields(v)
            key = next((x for fk, _, x in entry if fk == 1), None)
            val = next((x for fk, wk, x in entry if fk == 2 and wk == 2), b"")
            try:
                emeta = _fields(val)
                ename = next((x.decode("utf-8", "replace")
                              for fk, wk, x in emeta if fk == 2 and wk == 2),
                             "")
            except (ValueError, IndexError):
                ename = ""
            if key is not None and ename:
                md[key] = ename
        # lines (XPlane.lines=3) -> events (XLine.events=4), keyed by the
        # line's name (XLine.name=2)
        lines = {}
        for f, w, v in plane:
            if f != 3 or w != 2:
                continue
            lfields = _fields(v)
            lname = next((x.decode("utf-8", "replace")
                          for lf, lw, x in lfields if lf == 2 and lw == 2),
                         "")
            times = lines.setdefault(lname or "line", defaultdict(int))
            for lf, lw, lv in lfields:
                if lf != 4 or lw != 2:
                    continue
                ev = _fields(lv)
                mid = next((x for fk, _, x in ev if fk == 1), None)
                dur = next((x for fk, _, x in ev if fk == 3), 0)
                if mid is not None:
                    times[md.get(mid, "id:%s" % mid)] += dur
        lines = {ln: dict(t) for ln, t in lines.items() if t}
        if lines:
            result[name] = lines
    return result


# ---------------------------------------------------------------------------
# phase timing
# ---------------------------------------------------------------------------


def _build(fluid, model, on_tpu, mode):
    """mode: 'fwd' | 'fwdbwd' | 'step'. Returns (main, startup, loss)."""
    from paddle_tpu.models import resnet, transformer

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        if model == "resnet50":
            img, bs = (224, 128) if on_tpu else (64, 8)
            pixel, label = fluid.layers.random_data_generator(
                shapes=[[bs, 3, img, img], [bs, 1]],
                dtypes=["float32", "int64"], int_high=999)
            pred = resnet.resnet_imagenet(pixel, 1000, depth=50)
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(input=pred, label=label))
            denom = bs
        else:
            seq, bs = (256, 64) if on_tpu else (32, 4)
            nl, nh, dm, di = (6, 8, 512, 2048) if on_tpu else (2, 4, 64, 128)
            vocab = 32000 if on_tpu else 500
            loss, feeds, _ = transformer.build(
                src_vocab_size=vocab, trg_vocab_size=vocab, max_length=seq,
                n_layer=nl, n_head=nh, d_model=dm, d_inner=di, dropout=0.1)
            denom = bs * seq
        if mode == "fwdbwd":
            # lr=0 SGD anchors the backward as live program state; a bare
            # append_backward would leave grads unread and XLA would DCE
            # the whole backward (measured: "bwd" came out free)
            fluid.optimizer.SGD(learning_rate=0.0).minimize(loss)
        elif mode == "step":
            fluid.optimizer.Momentum(
                learning_rate=0.1, momentum=0.9).minimize(loss)
    return main, startup, loss, denom


def _transformer_feed(on_tpu):
    import numpy as np

    seq, bs = (256, 64) if on_tpu else (32, 4)
    vocab = 32000 if on_tpu else 500
    rng = np.random.RandomState(11)
    return {
        "src_word": rng.randint(1, vocab, (bs, seq)).astype("int64"),
        "src_len": np.full((bs, 1), seq, "int64"),
        "trg_word": rng.randint(1, vocab, (bs, seq)).astype("int64"),
        "trg_len": np.full((bs, 1), seq, "int64"),
        "label": rng.randint(1, vocab, (bs, seq)).astype("int64"),
    }


def _time_phase(fluid, model, on_tpu, mode, steps, warmup, use_amp):
    """Phase timing via the step-telemetry JSONL snapshot: the executors
    already record per-step wall time (observability/telemetry.py), so
    this tool stopped carrying its own perf_counter loop — it runs the
    steps, dumps the snapshot, and averages the records. One instrument,
    one truth; the same numbers land in the Prometheus scrape."""
    import numpy as np
    from paddle_tpu.observability import telemetry
    from paddle_tpu.transpiler import rewrite_program_amp
    from paddle_tpu import unique_name

    unique_name.switch()
    main, startup, loss, denom = _build(fluid, model, on_tpu, mode)
    if use_amp:
        rewrite_program_amp(main, "bfloat16")
    feed = _transformer_feed(on_tpu) if model == "transformer" else {}
    telemetry.enable(True)
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.TPUPlace() if on_tpu
                             else fluid.CPUPlace())
        exe.run(startup)
        for _ in range(warmup):
            exe.run(main, feed=feed, fetch_list=[])
        exe.run(main, feed=feed, fetch_list=[loss])
        telemetry.reset()  # timed window starts here
        for _ in range(steps - 1):
            exe.run(main, feed=feed, fetch_list=[])
        out = exe.run(main, feed=feed, fetch_list=[loss])
        with tempfile.TemporaryDirectory(prefix="step_tel_") as d:
            snap = os.path.join(d, "steps.jsonl")
            n = telemetry.write_steps_jsonl(snap)
            with open(snap) as f:
                recs = [json.loads(line) for line in f if line.strip()]
        telemetry.reset()
    assert np.isfinite(float(np.ravel(np.asarray(out[0]))[0]))
    if len(recs) != steps or n != steps:
        # friendly, actionable — not a bare AssertionError traceback
        sys.exit(
            "step_breakdown: telemetry recorded %d step(s) for %d timed "
            "steps — something disabled telemetry mid-run (check that "
            "nothing calls telemetry.enable(False) or reset() while the "
            "phase loop runs)" % (len(recs), steps))
    dt = sum(r["wall_s"] for r in recs) / sum(r["steps"] for r in recs)
    return dt, denom


# ---------------------------------------------------------------------------
# offline view over an existing telemetry snapshot
# ---------------------------------------------------------------------------


def _load_steps_jsonl(path):
    """Records from a telemetry steps JSONL, or a friendly exit — a
    missing/empty snapshot is an operator mistake (telemetry was off or
    the path is wrong), not a crash."""
    if not os.path.exists(path):
        sys.exit(
            "step_breakdown: %s does not exist.\nRun the workload with "
            "FLAGS_telemetry=1 and FLAGS_metrics_path=<p> (the snapshot "
            "lands at <p>.steps.jsonl), or pass that .steps.jsonl path "
            "here." % path)
    recs = []
    with open(path) as f:
        for line in f:
            if line.strip():
                try:
                    recs.append(json.loads(line))
                except ValueError:
                    pass
    if not recs:
        sys.exit(
            "step_breakdown: %s is empty — the process wrote no step "
            "records (was FLAGS_telemetry=1? did any step complete?)"
            % path)
    return recs


def _load_traces_jsonl(path):
    """Records from a request-trace JSONL, or a friendly exit — same
    contract as ``_load_steps_jsonl``: a missing/empty snapshot means
    tracing was off or the path is wrong, not a crash."""
    if not os.path.exists(path):
        sys.exit(
            "step_breakdown: %s does not exist.\nRun the serving "
            "workload with FLAGS_request_tracing=1, FLAGS_telemetry=1 "
            "and FLAGS_metrics_path=<p> (completed traces land at "
            "<p>.traces.jsonl), or pass that .traces.jsonl path here."
            % path)
    recs = []
    with open(path) as f:
        for line in f:
            if line.strip():
                try:
                    recs.append(json.loads(line))
                except ValueError:
                    pass
    if not recs:
        sys.exit(
            "step_breakdown: %s is empty — the process completed no "
            "traced request (was FLAGS_request_tracing=1? did any "
            "request finish before the telemetry flush?)" % path)
    return recs


def _load_stepprof_jsonl(path):
    """Records from a step-profile JSONL, or a friendly exit — same
    contract as the other loaders: a missing/empty snapshot means the
    observatory was off or the path is wrong, not a crash."""
    if not os.path.exists(path):
        sys.exit(
            "step_breakdown: %s does not exist.\nRun the training "
            "workload with FLAGS_step_profile=1, FLAGS_telemetry=1 and "
            "FLAGS_metrics_path=<p> (profiled steps land at "
            "<p>.stepprof.jsonl), or pass that .stepprof.jsonl path "
            "here." % path)
    recs = []
    with open(path) as f:
        for line in f:
            if line.strip():
                try:
                    recs.append(json.loads(line))
                except ValueError:
                    pass
    if not recs:
        sys.exit(
            "step_breakdown: %s is empty — the process profiled no step "
            "(was FLAGS_step_profile=1? did any executor step complete?)"
            % path)
    return recs


# phase axis of the step observatory's records (step_profiler.PHASES)
_STEPPROF_PHASES = ("input_wait", "feed", "compile", "dispatch", "device",
                    "fetch", "host")


def _summarize_stepprof(recs, top=5):
    """The per-step training view over a step-profile snapshot: where
    did each step's wall go (phase split), achieved-MFU percentiles,
    starvation fraction, and the top-N slowest steps with per-phase
    attribution and regression flags — the training twin of
    ``--requests``."""
    timed = [r for r in recs if not r.get("dispatch_only")]
    per_step = [r["step_s"] for r in timed]
    total_wall = sum(r.get("wall_s", 0.0) for r in timed)
    phase_totals = {p: 0.0 for p in _STEPPROF_PHASES}
    for r in timed:
        for p, v in (r.get("phases") or {}).items():
            phase_totals[p] = phase_totals.get(p, 0.0) + v
    total_input = phase_totals.get("input_wait", 0.0)
    total_attr = total_wall + total_input  # wall excludes pre-step waits
    mfus = [r["achieved_mfu"] for r in timed
            if r.get("achieved_mfu") is not None]
    bounds = {}
    for r in timed:
        b = r.get("bound", "unknown")
        bounds[b] = bounds.get(b, 0) + 1
    regressions = [r for r in timed if r.get("regression")]

    def ms(v, nd=3):
        return round(v * 1e3, nd) if v is not None else None

    print(json.dumps({
        "step_records": len(recs),
        "steps": sum(int(r.get("steps", 1)) for r in timed),
        "origins": sorted({r.get("origin") for r in timed}),
        "step_ms": {"p50": ms(_percentile(per_step, 50)),
                    "p95": ms(_percentile(per_step, 95)),
                    "p99": ms(_percentile(per_step, 99))},
        "phase_split": {
            p: round(phase_totals.get(p, 0.0) / total_attr, 4)
            for p in _STEPPROF_PHASES if total_attr > 0},
        "coverage_min": (round(min(r.get("coverage", 0.0)
                                   for r in timed), 4)
                         if timed else None),
        "starvation_fraction": (round(total_input / total_attr, 4)
                                if total_attr > 0 else None),
        "achieved_mfu": {
            "p50": (round(_percentile(mfus, 50), 6) if mfus else None),
            "p95": (round(_percentile(mfus, 95), 6) if mfus else None),
        },
        "bound": bounds,
        "regressions": len(regressions),
    }))
    slowest = sorted(timed, key=lambda r: -r.get("step_s", 0.0))
    for r in slowest[:max(0, int(top))]:
        reg = r.get("regression")
        print(json.dumps({
            "slow_step": r.get("fingerprint", "")[:16] or r.get("origin"),
            "origin": r.get("origin"),
            "steps": r.get("steps", 1),
            "step_ms": ms(r.get("step_s")),
            "phases_ms": {p: ms(v) for p, v in
                          (r.get("phases") or {}).items()},
            "coverage": round(r.get("coverage", 0.0), 4),
            "achieved_mfu": r.get("achieved_mfu"),
            "predicted_ratio": r.get("predicted_ratio"),
            "bound": r.get("bound"),
            "regression": ({"kind": reg["kind"], "phase": reg["phase"]}
                           if reg else None),
        }))


def _summarize_requests(recs, top=5):
    """The per-request serving view over a trace snapshot: where did
    each request's wall time go (queue wait / prefill / decode /
    wire flush), fleet TTFT and inter-token percentiles, and the top-N
    slowest requests — the offline twin of the live ``trace`` wire
    endpoint."""
    stats = [r.get("stats") or {} for r in recs]

    def col(key):
        return [s[key] for s in stats if s.get(key) is not None]

    def ms(v, nd=3):
        return round(v * 1e3, nd) if v is not None else None

    outcomes = {}
    for r in recs:
        o = r.get("outcome", "ok")
        outcomes[o] = outcomes.get(o, 0) + 1
    print(json.dumps({
        "requests": len(recs),
        "outcomes": outcomes,
        "ttft_ms": {"p50": ms(_percentile(col("ttft_s"), 50)),
                    "p95": ms(_percentile(col("ttft_s"), 95))},
        "wall_ms": {"p50": ms(_percentile(col("wall_s"), 50)),
                    "p95": ms(_percentile(col("wall_s"), 95))},
        "split_ms_p50": {
            "queue": ms(_percentile(col("queue_s"), 50)),
            "prefill": ms(_percentile(col("prefill_s"), 50)),
            "decode": ms(_percentile(col("decode_s"), 50)),
            "flush": ms(_percentile(col("flush_s"), 50)),
        },
        "intertoken_ms": {
            "p50": round(_percentile(col("intertoken_p50_ms"), 50)
                         or 0, 3),
            "p95": round(_percentile(col("intertoken_p95_ms"), 95)
                         or 0, 3),
        },
        "tokens": sum(int(s.get("tokens", 0)) for s in stats),
        "tokens_from_spec": sum(int(s.get("tokens_from_spec", 0))
                                for s in stats),
        "page_seconds": round(sum(s.get("page_seconds", 0.0)
                                  for s in stats), 4),
        "span_coverage_min": (round(min(col("span_coverage")), 4)
                              if col("span_coverage") else None),
    }))
    slowest = sorted(recs, key=lambda r: -(r.get("stats") or {})
                     .get("wall_s", 0.0))[:max(0, int(top))]
    for r in slowest:
        s = r.get("stats") or {}
        print(json.dumps({
            "slow_request": r.get("trace_id"),
            "endpoint": r.get("endpoint"),
            "outcome": r.get("outcome"),
            "wall_ms": ms(s.get("wall_s")),
            "ttft_ms": ms(s.get("ttft_s")),
            "queue_ms": ms(s.get("queue_s")),
            "prefill_ms": ms(s.get("prefill_s")),
            "decode_ms": ms(s.get("decode_s")),
            "flush_ms": ms(s.get("flush_s")),
            "tokens": s.get("tokens"),
            "spec_fraction": s.get("spec_fraction"),
            "cow_copies": s.get("cow_copies"),
        }))


def _percentile(vals, q):
    if not vals:
        return None
    vals = sorted(vals)
    import math

    k = max(0, min(len(vals) - 1,
                   int(math.ceil(q / 100.0 * len(vals))) - 1))
    return vals[k]


def _summarize_memory(recs):
    """The HBM view over a telemetry snapshot: watermark trajectory,
    predicted-vs-measured, top holders — same friendly degradation as
    --per-device when the records carry no memory fields."""
    with_mem = [r for r in recs if r.get("peak_hbm_bytes")]
    if not with_mem:
        print(json.dumps({
            "memory": None,
            "note": "no record carries peak_hbm_bytes — the snapshot "
                    "predates the memory ledger or telemetry ran "
                    "without any executor step (the ledger is written "
                    "by Executor/ParallelExecutor runs)"}))
        return
    peaks = [r["peak_hbm_bytes"] for r in with_mem]
    preds = [r["predicted_peak_bytes"] for r in with_mem
             if r.get("predicted_peak_bytes")]
    last = with_mem[-1]
    out = {
        "records_with_memory": len(with_mem),
        "peak_hbm_mb": {
            "max": round(max(peaks) / 1e6, 3),
            "p95": round((_percentile(peaks, 95) or 0) / 1e6, 3),
            "last": round(peaks[-1] / 1e6, 3),
        },
        "predicted_peak_mb": (round(max(preds) / 1e6, 3) if preds
                              else None),
        "predicted_over_measured": (round(max(preds) / max(peaks), 3)
                                    if preds and max(peaks) else None),
        "top_holders": [
            {"name": n, "kind": k, "mb": round(b / 1e6, 3)}
            for n, k, b in (last.get("hbm_top") or [])],
    }
    print(json.dumps(out))


def _summarize_jsonl(recs, per_device=False, memory=False):
    timed = [r for r in recs if not r.get("dispatch_only")]
    per_step = [r["step_s"] for r in timed]
    print(json.dumps({
        "records": len(recs),
        "steps": sum(r.get("steps", 1) for r in recs),
        "executors": sorted({r.get("executor") for r in recs}),
        "p50_ms": round((_percentile(per_step, 50) or 0) * 1e3, 3),
        "p95_ms": round((_percentile(per_step, 95) or 0) * 1e3, 3),
        "p99_ms": round((_percentile(per_step, 99) or 0) * 1e3, 3),
        "feed_mb": round(sum(r.get("feed_bytes", 0)
                             for r in recs) / 1e6, 3),
        "fetch_mb": round(sum(r.get("fetch_bytes", 0)
                              for r in recs) / 1e6, 3),
    }))
    if memory:
        _summarize_memory(recs)
    if not per_device:
        return
    with_dev = [r for r in recs if r.get("device_times")]
    if not with_dev:
        print(json.dumps({
            "per_device": None,
            "note": "no record carries device_times — the snapshot came "
                    "from a single-device executor (per-device step "
                    "times are recorded by ParallelExecutor runs)"}))
        return
    agg = defaultdict(list)
    for r in with_dev:
        for dev, t in r["device_times"].items():
            agg[dev].append(t)
    rows = {
        dev: {"steps": len(ts),
              "mean_ms": round(sum(ts) / len(ts) * 1e3, 3),
              "max_ms": round(max(ts) * 1e3, 3)}
        for dev, ts in sorted(agg.items())
    }
    worst = [max(r["device_times"], key=r["device_times"].get)
             for r in with_dev]
    straggler = max(set(worst), key=worst.count)
    means = sorted(v["mean_ms"] for v in rows.values())
    mid = len(means) // 2
    med = means[mid] if len(means) % 2 else (
        means[mid - 1] + means[mid]) / 2.0
    print(json.dumps({
        "per_device": rows,
        "most_frequent_straggler": straggler,
        "imbalance_max_over_median": round(
            max(means) / med, 4) if med else None,
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50",
                    choices=["resnet50", "transformer"])
    ap.add_argument("--steps", default="10", metavar="N|PATH",
                    help="model-run mode: number of timed steps. With a "
                         "PATH to a step-profile JSONL "
                         "(<FLAGS_metrics_path>.stepprof.jsonl): offline "
                         "training view — phase split, achieved-MFU "
                         "percentiles, starvation fraction, top-N "
                         "slowest steps with regression flags")
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--xprof", action="store_true",
                    help="also capture + aggregate an xprof trace")
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--from-jsonl", metavar="PATH", default=None,
                    help="summarize an existing telemetry steps JSONL "
                         "instead of running the model")
    ap.add_argument("--per-device", action="store_true",
                    help="with --from-jsonl: per-device step-time table "
                         "over the labeled step records")
    ap.add_argument("--memory", action="store_true",
                    help="with --from-jsonl: peak-HBM trajectory, "
                         "predicted-vs-measured peak, top ledger holders")
    ap.add_argument("--requests", metavar="PATH", default=None,
                    help="summarize a request-trace JSONL "
                         "(<FLAGS_metrics_path>.traces.jsonl): fleet "
                         "TTFT/queue/prefill/decode split + top-N "
                         "slowest requests")
    args = ap.parse_args()

    try:
        args.steps = int(args.steps)
    except ValueError:
        # --steps <path.stepprof.jsonl>: the offline training view,
        # symmetric to --requests
        _summarize_stepprof(_load_stepprof_jsonl(args.steps),
                            top=args.top)
        return

    if args.requests:
        _summarize_requests(_load_traces_jsonl(args.requests),
                            top=args.top)
        return
    if args.from_jsonl:
        _summarize_jsonl(_load_steps_jsonl(args.from_jsonl),
                         per_device=args.per_device, memory=args.memory)
        return
    if args.memory:
        sys.exit(
            "step_breakdown: --memory reads a telemetry snapshot — pass "
            "--from-jsonl <p>.steps.jsonl (run the workload with "
            "FLAGS_telemetry=1 and FLAGS_metrics_path=<p> to produce one)")

    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    import paddle_tpu as fluid

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    use_amp = on_tpu

    phases = {}
    for mode in ("fwd", "fwdbwd", "step"):
        dt, denom = _time_phase(fluid, args.model, on_tpu, mode,
                                args.steps, args.warmup, use_amp)
        phases[mode] = dt
        print(json.dumps({"phase": mode, "ms": round(dt * 1e3, 3),
                          "per_unit_us": round(dt / denom * 1e6, 3)}))
    print(json.dumps({
        "phase": "deltas",
        "bwd_ms": round((phases["fwdbwd"] - phases["fwd"]) * 1e3, 3),
        "opt_ms": round((phases["step"] - phases["fwdbwd"]) * 1e3, 3),
        "bwd_over_fwd": round(phases["fwdbwd"] / phases["fwd"] - 1, 2),
    }))

    if not args.xprof:
        return
    from paddle_tpu.transpiler import rewrite_program_amp
    from paddle_tpu import unique_name

    unique_name.switch()
    main_p, startup, loss, _ = _build(fluid, args.model, on_tpu, "step")
    if use_amp:
        rewrite_program_amp(main_p, "bfloat16")
    feed = _transformer_feed(on_tpu) if args.model == "transformer" else {}
    with fluid.scope_guard(fluid.executor.Scope()):
        exe = fluid.Executor(fluid.TPUPlace() if on_tpu
                             else fluid.CPUPlace())
        exe.run(startup)
        for _ in range(args.warmup):
            exe.run(main_p, feed=feed, fetch_list=[])
        trace_dir = tempfile.mkdtemp(prefix="step_breakdown_")
        with jax.profiler.trace(trace_dir):
            for _ in range(args.steps):
                exe.run(main_p, feed=feed, fetch_list=[])
            exe.run(main_p, feed=feed, fetch_list=[loss])
    # device plane if present (TPU), else the host CPU plane; within a
    # plane prefer the "XLA Ops" line — that's where the per-HLO self
    # times live (the "Steps"/"XLA Modules" lines carry whole-step and
    # whole-module envelopes that would drown the op table)
    for path in glob.glob(trace_dir + "/**/*.xplane.pb", recursive=True):
        planes = op_times_from_xplane(path)
        device = {n: t for n, t in planes.items() if "CPU" not in n} or planes
        for pname, lines in sorted(device.items()):
            preferred = [ln for ln in lines if "XLA Ops" in ln] or \
                sorted(lines)
            for lname in preferred:
                times = lines[lname]
                total = sum(times.values())
                if not total:
                    continue
                top = sorted(times.items(), key=lambda kv: -kv[1])[:args.top]
                print(json.dumps({
                    "plane": pname, "line": lname,
                    "total_ms": round(total / 1e9, 3),
                    "top_ops": [
                        {"op": op, "ms": round(t / 1e9, 3),
                         "pct": round(100.0 * t / total, 1)}
                        for op, t in top
                    ]}))


if __name__ == "__main__":
    main()
