#!/usr/bin/env python
"""Render request traces: per-request waterfalls + Perfetto export.

The offline viewer over the ``<FLAGS_metrics_path>.traces.jsonl`` a
``FLAGS_request_tracing=1`` serving process leaves behind (one JSON line
per completed trace — ``observability/tracing.py``'s ring record). Three
views:

1. default — an ASCII waterfall per trace: every span on its own line,
   offset/duration in ms relative to the trace's first span, bar scaled
   to the request wall, key meta inline (tokens, cow_copies,
   prefix_hit_pages, speculative) and the derived SLO stats underneath
   (TTFT, queue/prefill/decode split, inter-token p50/p95, page-seconds,
   speculation fraction, span coverage).
2. ``--slowest N`` — only the N slowest requests by wall time (the
   "which request blew the p99" workflow: the serving histogram's bucket
   exemplar names a trace id, ``--trace`` pulls its waterfall).
3. ``--perfetto OUT`` — Chrome/Perfetto trace JSON
   (``{"traceEvents": [...]}``; load in ui.perfetto.dev or
   chrome://tracing) with one track per request.

Usage::

    python tools/trace_view.py /tmp/m.traces.jsonl
    python tools/trace_view.py /tmp/m.traces.jsonl --slowest 3
    python tools/trace_view.py /tmp/m.traces.jsonl --trace 1f2e3d4c5b6a7988
    python tools/trace_view.py /tmp/m.traces.jsonl --perfetto /tmp/t.json
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BAR_W = 40
# meta keys worth a column in the waterfall line (everything else is in
# the Perfetto export's args)
_META_KEYS = ("tokens", "cow_copies", "prefix_hit_pages", "speculative",
              "kind", "members", "batch", "force_closed")


def _load_traces_jsonl(path):
    """Trace records or a friendly exit — a missing/empty snapshot means
    tracing was off or the path is wrong, not a stack trace."""
    if not os.path.exists(path):
        sys.exit(
            "trace_view: %s does not exist.\nRun the serving workload "
            "with FLAGS_request_tracing=1, FLAGS_telemetry=1 and "
            "FLAGS_metrics_path=<p> (completed traces land at "
            "<p>.traces.jsonl), or pass that .traces.jsonl path here."
            % path)
    recs = []
    with open(path) as f:
        for line in f:
            if line.strip():
                try:
                    recs.append(json.loads(line))
                except ValueError:
                    pass
    if not recs:
        sys.exit(
            "trace_view: %s is empty — the process completed no traced "
            "request (was FLAGS_request_tracing=1? did any request "
            "finish before the telemetry flush?)" % path)
    return recs


def _fmt_meta(meta):
    parts = ["%s=%s" % (k, meta[k]) for k in _META_KEYS if k in meta]
    return (" " + " ".join(parts)) if parts else ""


def _waterfall(rec):
    """One trace's ASCII waterfall: spans sorted by start, bar position
    scaled to the request wall."""
    spans = sorted(rec.get("spans", ()), key=lambda s: s["t0"])
    stats = rec.get("stats") or {}
    if not spans:
        print("trace %s: no spans" % rec.get("trace_id"))
        return
    t_base = spans[0]["t0"]
    t_end = max(s["t1"] for s in spans if s["t1"] is not None)
    wall = max(t_end - t_base, 1e-9)
    print("trace %s  endpoint=%s origin=%s outcome=%s  wall=%.1fms "
          "spans=%d" % (rec.get("trace_id"), rec.get("endpoint"),
                        rec.get("origin"), rec.get("outcome"),
                        wall * 1e3, len(spans)))
    for sp in spans:
        t0 = sp["t0"] - t_base
        t1 = (sp["t1"] if sp["t1"] is not None else t_end) - t_base
        lo = int(round(t0 / wall * BAR_W))
        hi = max(lo + 1, int(round(t1 / wall * BAR_W)))
        bar = " " * lo + "#" * min(hi - lo, BAR_W - lo)
        print("  %-12s |%-*s| %9.3fms +%9.3fms%s"
              % (sp["name"], BAR_W, bar, (t1 - t0) * 1e3, t0 * 1e3,
                 _fmt_meta(sp.get("meta") or {})))
    line = ["  stats:"]
    for key in ("ttft_s", "queue_s", "prefill_s", "decode_s",
                "flush_s"):
        if stats.get(key) is not None:
            line.append("%s=%.3fms" % (key[:-2], stats[key] * 1e3))
    for key, fmt in (("intertoken_p50_ms", "itl_p50=%.3fms"),
                     ("intertoken_p95_ms", "itl_p95=%.3fms"),
                     ("page_seconds", "page_s=%.4f"),
                     ("spec_fraction", "spec=%.2f"),
                     ("span_coverage", "coverage=%.4f")):
        if stats.get(key) is not None:
            line.append(fmt % stats[key])
    if stats.get("tokens"):
        line.append("tokens=%d" % stats["tokens"])
    print(" ".join(line))


def _write_perfetto(recs, out_path):
    from paddle_tpu.observability import tracing

    events = []
    for row, rec in enumerate(recs):
        events.extend(tracing.perfetto_events(rec, row=row, pid=1))
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    print("trace_view: wrote %d events for %d traces -> %s"
          % (len(events), len(recs), out_path))


def main():
    ap = argparse.ArgumentParser(
        description="per-request trace waterfalls + Perfetto export")
    ap.add_argument("traces", help="path to a .traces.jsonl snapshot")
    ap.add_argument("--slowest", type=int, default=None, metavar="N",
                    help="only the N slowest requests by wall time")
    ap.add_argument("--trace", default=None, metavar="TID",
                    help="only the request with this trace id")
    ap.add_argument("--perfetto", default=None, metavar="OUT",
                    help="also write Chrome/Perfetto trace JSON here")
    args = ap.parse_args()

    recs = _load_traces_jsonl(args.traces)
    if args.trace:
        recs = [r for r in recs if r.get("trace_id") == args.trace]
        if not recs:
            sys.exit("trace_view: trace id %s not in %s (aged out of "
                     "the completed-trace ring before the flush?)"
                     % (args.trace, args.traces))
    if args.slowest is not None:
        recs = sorted(recs, key=lambda r: -(r.get("stats") or {})
                      .get("wall_s", 0.0))[:max(0, args.slowest)]
    for i, rec in enumerate(recs):
        if i:
            print()
        _waterfall(rec)
    if args.perfetto:
        _write_perfetto(recs, args.perfetto)


if __name__ == "__main__":
    main()
