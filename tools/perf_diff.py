"""Perf/memory regression sentry: diff bench / cost-model artifacts and
gate CI on the budget file.

The repo tracks its performance story in artifacts (``BENCH_*.json`` from
bench.py, cost-model JSONL from tools/hlo_cost_model.py) but until now
nothing STOPPED a PR from silently regressing step time, compile counts,
or HBM footprint. This tool is that gate, with the discipline the metrics
deserve:

* **Deterministic counters gate hard** — ``fresh_compiles`` (a +1 means
  the fingerprint cache broke for some path), ``predicted_peak_bytes``
  (the planner's number moves only when the program's liveness/shapes
  move), cost-model roofline time/bytes/flops. Any increase over the
  baseline/budget fails, no band.
* **Timings gate with a noise band** — step_ms percentiles, throughput,
  MFU, measured peak HBM (allocator jitter), compile seconds. A
  regression beyond ``--band`` (default 0.25, budgets file can override)
  fails; noise inside it passes.

Inputs: a bench JSON (the one-line ``{"models": {...}}`` capture) or an
hlo_cost_model JSONL (its ``"record": "summary"`` line). Modes compose:

  # CI perfgate: absolute ceilings/floors from the checked-in budgets
  python tools/perf_diff.py CANDIDATE.json --budgets benchmark/budgets.json

  # A/B: relative diff of two captures
  python tools/perf_diff.py CANDIDATE.json --baseline BASELINE.json

Exit codes: 0 clean, 1 regression(s), 2 unreadable/empty artifact.
"""

import argparse
import json
import os
import sys

# metric -> (direction better, gating kind). Deterministic metrics fail
# on ANY adverse move; timing metrics get the noise band.
METRICS = {
    "fresh_compiles": ("lower", "deterministic"),
    "predicted_peak_bytes": ("lower", "deterministic"),
    "predicted_hbm_bytes": ("lower", "deterministic"),
    "predicted_step_us": ("lower", "deterministic"),
    "flops": ("lower", "deterministic"),
    "peak_hbm_bytes": ("lower", "timing"),
    "step_ms_p50": ("lower", "timing"),
    "step_ms_p95": ("lower", "timing"),
    "compile_seconds_cold": ("lower", "timing"),
    "throughput": ("higher", "timing"),
    "mfu": ("higher", "timing"),
    "mfu_telemetry": ("higher", "timing"),
    # serving SLOs (tools/serve_smoke.py + bench.py serving leg)
    "latency_ms_p50": ("lower", "timing"),
    "latency_ms_p99": ("lower", "timing"),
    "batch_occupancy": ("higher", "timing"),
    # paged decode (bench.py decode leg + tools/decode_smoke.py):
    # throughput carries paged tokens/sec; the A/B ratio and per-token
    # latency gate the raggedness win itself
    "paged_speedup": ("higher", "timing"),
    "token_latency_ms": ("lower", "timing"),
    # cross-request KV reuse (PR 12): shared-vs-unshared best-of-N
    # ratio, prefix-cache effectiveness, and the grouped cross-K/V
    # pool footprint (a pure function of [G, H, T, dh] x layers —
    # deterministic: growth means cross state scales with slots again)
    "bestofn_speedup": ("higher", "timing"),
    "prefix_hit_rate": ("higher", "timing"),
    "cross_kv_bytes": ("lower", "deterministic"),
    # batched beam search over the slot pool (PR 15): rebind-vs-copy
    # reorder tokens/sec ratio (bit-identical n-bests asserted in-leg)
    # and the rebind wave's physically-moved reorder bytes (reorder
    # copies + write-page COW, page-geometry-accounted; deterministic
    # under greedy decode — growth means reorders started copying KV
    # or COW stopped being write-page-only)
    "beam_speedup": ("higher", "timing"),
    "beam_reorder_bytes": ("lower", "deterministic"),
    # speculative decoding (PR 16): draft-then-verify tokens/sec over
    # the sequential FLAGS_speculative=off oracle on the SAME session
    # (bit-identical streams asserted in-leg — the ratio can only come
    # from dispatch amortization), and the drafter's accepted/proposed
    # ratio over the timed wave (deterministic under greedy decode
    # with the leg's seeds, but gated as a timing metric so drafter
    # tuning has headroom — the floor catches lookup regressions)
    "speculative_speedup": ("higher", "timing"),
    "acceptance_rate": ("higher", "timing"),
    # serving resilience (tools/serve_chaos_smoke.py): wall seconds of
    # one synchronous decode snapshot in the restored warm process
    "snapshot_seconds": ("lower", "timing"),
    # router fleet tier (tools/router_smoke.py): end-to-end seconds of
    # one SIGKILL failover (sever detection -> banked snapshot read ->
    # ship -> quiesced restore on the survivor), and the count of
    # client streams the failover LOST (deterministic: the zero-loss
    # contract — any nonzero means a re-driven stream gapped or a
    # banked snapshot stopped covering the in-flight work)
    "migration_seconds": ("lower", "timing"),
    "lost_streams": ("lower", "deterministic"),
    # network front end (tools/frontend_smoke.py + bench.py frontend
    # leg): stream time-to-first-token over a real socket — the
    # latency_ms_* twins above carry the wire unary SLOs
    "ttft_ms": ("lower", "timing"),
    # request tracing (tools/trace_smoke.py): worst per-request span
    # coverage of the CLIENT-observed wall over real sockets — a drop
    # means some serving phase stopped being attributed
    "span_coverage": ("higher", "timing"),
    # step observatory (tools/stepprof_smoke.py + the perf ledger):
    # worst per-step phase coverage of the step wall (a drop means a
    # training phase stopped being attributed), achieved-MFU from the
    # cost-model join, input-starvation fraction, and the profiled-leg
    # wall over the off-leg control (the overhead contract)
    "phase_coverage": ("higher", "timing"),
    "achieved_mfu": ("higher", "timing"),
    "starvation_fraction": ("lower", "timing"),
    "stepprof_overhead": ("lower", "timing"),
}


def _bench_model_metrics(m):
    out = {
        "throughput": m.get("value"),
        "mfu": m.get("mfu"),
        "mfu_telemetry": m.get("mfu_telemetry"),
        "compile_seconds_cold": m.get("compile_seconds_cold"),
        "peak_hbm_bytes": m.get("peak_hbm_bytes"),
        "predicted_peak_bytes": m.get("predicted_peak_bytes"),
    }
    sm = m.get("step_ms") or {}
    out["step_ms_p50"] = sm.get("p50")
    out["step_ms_p95"] = sm.get("p95")
    out["latency_ms_p50"] = m.get("latency_ms_p50")
    out["latency_ms_p99"] = m.get("latency_ms_p99")
    out["batch_occupancy"] = m.get("batch_occupancy")
    out["paged_speedup"] = m.get("paged_speedup")
    out["token_latency_ms"] = m.get("token_latency_ms")
    out["predicted_hbm_bytes"] = m.get("predicted_hbm_bytes")
    out["bestofn_speedup"] = m.get("bestofn_speedup")
    out["prefix_hit_rate"] = m.get("prefix_hit_rate")
    out["cross_kv_bytes"] = m.get("cross_kv_bytes")
    out["beam_speedup"] = m.get("beam_speedup")
    out["beam_reorder_bytes"] = m.get("beam_reorder_bytes")
    out["speculative_speedup"] = m.get("speculative_speedup")
    out["acceptance_rate"] = m.get("acceptance_rate")
    out["snapshot_seconds"] = m.get("snapshot_seconds")
    out["migration_seconds"] = m.get("migration_seconds")
    out["lost_streams"] = m.get("lost_streams")
    out["ttft_ms"] = m.get("ttft_ms")
    out["span_coverage"] = m.get("span_coverage")
    out["phase_coverage"] = m.get("phase_coverage")
    out["achieved_mfu"] = m.get("achieved_mfu")
    out["starvation_fraction"] = m.get("starvation_fraction")
    out["stepprof_overhead"] = m.get("stepprof_overhead")
    ec = m.get("exec_cache") or {}
    out["fresh_compiles"] = ec.get("fresh_compiles",
                                   m.get("fresh_compiles"))
    return {k: v for k, v in out.items() if v is not None}


def load_artifact(path):
    """-> {model: {metric: value}} from a bench JSON or cost-model JSONL;
    SystemExit(2) with a friendly message when unusable."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        sys.exit("perf_diff: cannot read %s (%s)" % (path, e))
    records = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            pass
    if not records:
        try:
            records = [json.loads(text)]
        except ValueError:
            print("perf_diff: %s is not JSON (or JSONL)" % path)
            raise SystemExit(2)
    models = {}
    for rec in records:
        if not isinstance(rec, dict):
            continue
        if rec.get("record") == "summary":
            # hlo_cost_model JSONL: the analytic roofline — all three
            # numbers are deterministic functions of the traced program
            models[rec.get("model", "cost_model")] = {
                "predicted_step_us": rec.get("step_us_roofline_nameplate"),
                "predicted_hbm_bytes": rec.get("total_hbm_bytes"),
                "flops": rec.get("total_flops"),
            }
        elif isinstance(rec.get("models"), dict):
            for name, m in rec["models"].items():
                if isinstance(m, dict) and "error" not in m:
                    models[name] = _bench_model_metrics(m)
        elif "metric" in rec and "error" not in rec:
            # a bare worker line: one model's record
            models[rec["metric"]] = _bench_model_metrics(rec)
    models = {k: {mk: mv for mk, mv in v.items() if mv is not None}
              for k, v in models.items()}
    models = {k: v for k, v in models.items() if v}
    if not models:
        print("perf_diff: %s parsed but carries no usable model metrics "
              "(bench error capture? telemetry off?)" % path)
        raise SystemExit(2)
    return models


def _gate(metric, cand, limit, band, direction, kind, source):
    """One comparison -> (ok, effective_limit). ``limit`` is the
    baseline value or the budget ceiling/floor; timings stretch it by
    the band, deterministic metrics don't."""
    eff = float(limit)
    if kind == "timing":
        eff = eff * (1.0 + band) if direction == "lower" else \
            eff * (1.0 - band)
    ok = (cand <= eff) if direction == "lower" else (cand >= eff)
    return ok, eff


def compare(candidate, reference, band, source, results,
            require_all=False):
    """Gate every shared (model, metric) pair; append result rows.

    ``require_all`` (budget mode): a budgeted (model, metric) pair the
    candidate doesn't carry is itself a FAILURE — otherwise a PR that
    breaks the telemetry capture (metrics vanish from the artifact)
    silently weakens the gate while 'perf_diff: clean' still prints."""
    for model, cand_metrics in sorted(candidate.items()):
        ref_metrics = reference.get(model)
        if not ref_metrics:
            continue
        for metric, cand in sorted(cand_metrics.items()):
            spec = METRICS.get(metric)
            if spec is None or metric not in ref_metrics:
                continue
            direction, kind = spec
            ref = ref_metrics[metric]
            ok, eff = _gate(metric, float(cand), float(ref), band,
                            direction, kind, source)
            results.append({
                "model": model, "metric": metric, "kind": kind,
                "candidate": cand, "reference": ref,
                "effective_limit": round(eff, 6), "source": source,
                "ok": ok,
            })
    if not require_all:
        return
    for model, ref_metrics in sorted(reference.items()):
        cand_metrics = candidate.get(model)
        for metric in sorted(ref_metrics):
            if metric not in METRICS:
                continue
            if cand_metrics is None or metric not in cand_metrics:
                results.append({
                    "model": model, "metric": metric, "kind": "missing",
                    "candidate": None,
                    "reference": ref_metrics[metric],
                    "effective_limit": None, "source": source,
                    "ok": False,
                })


def budget_reference(budgets):
    """Flatten the budgets file to {model: {metric: limit}} (+ its band).
    Entries are ``{"max"|"min": value, "why": lineage}`` — the why
    strings are the audit trail for every number."""
    ref = {}
    for model, entries in (budgets.get("models") or {}).items():
        ref[model] = {}
        for metric, spec in entries.items():
            if not isinstance(spec, dict):
                ref[model][metric] = spec
                continue
            limit = spec.get("max", spec.get("min"))
            if limit is not None:
                ref[model][metric] = limit
    return ref, float(budgets.get("band", 0.25))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="diff bench/cost-model artifacts; gate on budgets")
    ap.add_argument("candidate", help="bench JSON or cost-model JSONL")
    ap.add_argument("--baseline", default=None,
                    help="reference artifact for a relative diff")
    ap.add_argument("--budgets", default=None,
                    help="benchmark/budgets.json absolute gate")
    ap.add_argument("--band", type=float, default=0.25,
                    help="noise band for timing metrics (relative mode; "
                         "the budgets file carries its own)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full result table as one JSON line")
    ap.add_argument("--models", default=None,
                    help="comma list: gate only these models (a partial "
                         "capture — e.g. the serve smoke's — isn't "
                         "failed for the models it never measured)")
    args = ap.parse_args(argv)

    if not args.baseline and not args.budgets:
        default_budgets = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "benchmark", "budgets.json")
        if os.path.exists(default_budgets):
            args.budgets = default_budgets
        else:
            ap.error("need --baseline and/or --budgets")

    candidate = load_artifact(args.candidate)
    only = None
    if args.models:
        only = {m.strip() for m in args.models.split(",") if m.strip()}
        candidate = {k: v for k, v in candidate.items() if k in only}
        if not candidate:
            print("perf_diff: candidate carries none of --models %s"
                  % sorted(only))
            raise SystemExit(2)
    results = []
    if args.baseline:
        baseline = load_artifact(args.baseline)
        if only is not None:
            baseline = {k: v for k, v in baseline.items() if k in only}
        compare(candidate, baseline, args.band, "baseline", results)
    if args.budgets:
        try:
            with open(args.budgets) as f:
                budgets = json.load(f)
        except (OSError, ValueError) as e:
            print("perf_diff: cannot read budgets %s (%s)"
                  % (args.budgets, e))
            raise SystemExit(2)
        ref, band = budget_reference(budgets)
        if only is not None:
            ref = {k: v for k, v in ref.items() if k in only}
        compare(candidate, ref, band, "budget", results,
                require_all=True)

    if not results:
        print("perf_diff: no overlapping (model, metric) pairs to gate — "
              "nothing compared, nothing proven")
        raise SystemExit(2)

    failures = [r for r in results if not r["ok"]]
    for r in results:
        mark = "FAIL" if not r["ok"] else "ok  "
        print("%s %-12s %-22s %-13s cand=%-14s %s=%-14s limit=%s"
              % (mark, r["model"], r["metric"], r["kind"],
                 r["candidate"], r["source"], r["reference"],
                 r["effective_limit"]))
    if args.json:
        print(json.dumps({"results": results,
                          "failures": len(failures)}, sort_keys=True))
    if failures:
        print("perf_diff: %d regression(s) — deterministic counters gate "
              "hard, timings beyond the noise band" % len(failures))
        raise SystemExit(1)
    print("perf_diff: clean (%d checks)" % len(results))


if __name__ == "__main__":
    main()
