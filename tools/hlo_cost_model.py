"""Offline MFU cost model: per-fused-op flops + HBM bytes from the
TPU-target lowering, no chip required (VERDICT r4 Next #1).

The bench rig's TPU sits behind a tunnel that can stay wedged for whole
rounds, so perf planning must not be hardware-gated. This tool traces
the EXACT train step bench.py times — same program builders, same
shapes, same bf16 AMP rewrite, and the TPU kernel selection (ambient
platform "tpu" picks the Pallas flash-attention path, not the CPU
reference path) — then walks the jaxpr with an XLA-style fusion-group
model:

* every matmul/conv/pallas kernel is its own group (the MXU ops XLA
  never merges with each other);
* connected chains of fusible ops (elementwise, broadcast, transpose,
  reduce, ...) merge, and a fusible chain with a single heavy consumer
  or producer folds into it (XLA's loop/input/output fusion on TPU);
* a group's HBM bytes are the values crossing its boundary, counted
  once — the perfect-fusion traffic floor;
* group time = max(flops / peak_flops, bytes / hbm_bw)  (roofline).

Output: a JSONL artifact (one record per fused group, aggregated by
signature) + a summary with predicted step time / MFU at both nameplate
peak (197 bf16 TFLOP/s, 819 GB/s HBM for v5e) and this rig's measured
observable ceiling (~36 TFLOP/s through the tunnel, BENCH_NOTES.md).
docs/MFU_PLAN.md ranks the levers this table justifies.

Reference discipline: /root/reference/tools/timeline.py:37-120 commits
the trace-analysis path; this is the same idea made chip-independent.

Usage (CPU host, tunnel-proof):
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python tools/hlo_cost_model.py --model resnet50 \
      --out docs/artifacts/hlo_cost_model_resnet50_r05.jsonl

Caveats (stated in the artifact): fusion grouping is a model of XLA's
decisions, not a readback of them; pallas_call HBM bytes are an upper
bound (grid steps whose index map revisits a block may be served from
VMEM); while_loop trip counts are unknown statically (reported with
multiplier 1). Totals are cross-checked against the analytic FLOP
accounting bench.py uses for MFU.
"""

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# nameplate v5e; the observable ceiling through this rig's tunnel is
# ~36 TFLOP/s sustained on chained 4096^3 matmuls (BENCH_NOTES.md)
PEAK_FLOPS = 197e12
OBSERVED_PEAK_FLOPS = 36e12
HBM_BW = 819e9

HEAVY = {"dot_general", "conv_general_dilated", "pallas_call",
         "sort", "scatter", "scatter-add", "top_k", "while",
         "reduce_window_max", "reduce_window_sum", "select_and_scatter_add"}

# fusible ops whose cost is one pass over their elements
_ELEMENTWISE_1 = {
    "add", "sub", "mul", "div", "max", "min", "pow", "rem", "neg", "sign",
    "abs", "floor", "ceil", "round", "exp", "log", "log1p", "expm1",
    "tanh", "logistic", "rsqrt", "sqrt", "erf", "erf_inv", "erfc",
    "integer_pow", "and", "or", "xor", "not", "shift_left",
    "shift_right_logical", "shift_right_arithmetic", "eq", "ne", "ge",
    "gt", "le", "lt", "select_n", "clamp", "nextafter", "sin", "cos",
    "atan2", "square", "is_finite", "convert_element_type", "bitcast_convert_type",
    "copy", "real", "imag", "stop_gradient",
}
_SHAPE_ONLY = {
    "reshape", "broadcast_in_dim", "transpose", "squeeze", "expand_dims",
    "slice", "dynamic_slice", "dynamic_update_slice", "concatenate",
    "pad", "rev", "iota", "gather", "split",
}
_REDUCE = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
           "reduce_and", "reduce_or", "argmax", "argmin", "cumsum",
           "cumlogsumexp", "cummax", "reduce_precision"}


def _nbytes(aval):
    try:
        return int(aval.size) * aval.dtype.itemsize
    except Exception:
        return 0


def _size(aval):
    try:
        return int(aval.size)
    except Exception:
        return 0


def _dot_flops(eqn):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    k = 1
    for d in lc:
        k *= lhs.shape[d]
    batch = 1
    for d in lb:
        batch *= lhs.shape[d]
    m = max(1, _size(lhs) // max(1, k * batch))
    n = max(1, _size(rhs) // max(1, k * batch))
    return 2 * batch * m * n * k


def _conv_flops(eqn):
    out = eqn.outvars[0].aval
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    dn = eqn.params["dimension_numbers"]
    groups = eqn.params.get("feature_group_count", 1) or 1
    # kernel spatial size x input channels per group, from the rhs spec
    rhs_shape = rhs.shape
    spatial = 1
    for d in dn.rhs_spec[2:]:
        spatial *= rhs_shape[d]
    cin_per_group = rhs_shape[dn.rhs_spec[1]]
    flops = 2 * _size(out) * cin_per_group * spatial
    # an input-dilated conv (the data-grad of a strided conv) lands a
    # real MAC only on every stride-th tap: the naive count over the
    # zero-dilated input overstates by prod(lhs_dilation)
    for d in (eqn.params.get("lhs_dilation") or ()):
        flops //= max(1, int(d))
    return flops


def eqn_flops(eqn):
    p = eqn.primitive.name
    if p == "dot_general":
        return _dot_flops(eqn)
    if p == "conv_general_dilated":
        return _conv_flops(eqn)
    if p in _ELEMENTWISE_1:
        return sum(_size(v.aval) for v in eqn.outvars)
    if p in _REDUCE:
        return sum(_size(v.aval) for v in eqn.invars)
    if p in _SHAPE_ONLY:
        return 0
    if p in ("reduce_window_max", "reduce_window_sum",
             "select_and_scatter_add"):
        win = eqn.params.get("window_dimensions", ())
        mult = 1
        for w in win:
            mult *= w
        return _size(eqn.outvars[0].aval) * mult
    if p == "sort":
        n = _size(eqn.invars[0].aval)
        return int(n * max(1, math.log2(max(2, n))))
    # default: one pass over the output
    return sum(_size(v.aval) for v in eqn.outvars)


def _subjaxprs(eqn):
    """(jaxpr, multiplier, tag) for eqns that carry inner jaxprs."""
    p = eqn.primitive.name
    params = eqn.params
    if p in ("pjit", "jit", "closed_call", "core_call", "remat",
             "checkpoint", "custom_vjp_call", "custom_jvp_call",
             "custom_vjp_call_jaxpr"):
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
            j = params.get(key)
            if j is not None:
                yield j, 1, p
                return
    if p == "scan":
        yield params["jaxpr"], int(params.get("length", 1)), "scan"
    elif p == "while":
        # trip count is dynamic: report body once, flagged in the record
        yield params["body_jaxpr"], 1, "while"
    elif p == "cond":
        branches = params.get("branches", ())
        if branches:
            # cost the most expensive branch
            yield max(branches,
                      key=lambda b: sum_flops_recursive(
                          getattr(b, "jaxpr", b))), 1, "cond"


def _is_var(v):
    return type(v).__name__ != "Literal"


def _param_key(params):
    """Hashable digest of eqn params; raises for opaque (jaxpr-carrying)
    params so callers can skip CSE for those eqns."""
    parts = []
    for k in sorted(params):
        v = params[k]
        if hasattr(v, "jaxpr") or type(v).__name__ in ("Jaxpr",
                                                       "ClosedJaxpr"):
            raise TypeError("opaque")
        parts.append((k, repr(v)))
    return tuple(parts)


def optimize_jaxpr(jaxpr, rounds=2):
    """DCE + common-subexpression elimination, approximating what XLA
    does before fusion. Needed because every grad op's lowering is built
    with jax.vjp, which RE-TRACES the forward: the raw jaxpr holds each
    forward conv/matmul twice (once from the forward op, once inside the
    grad op's vjp), and XLA's CSE collapses them — a cost model that
    counts both overstates flops ~2x (measured: 211 convs raw vs ~158
    real for ResNet-50 train). Top-level only: wrapper subjaxprs are
    rare in executor traces (ops lower inline)."""
    from jax.extend import core as jcore

    for _ in range(rounds):
        # ---- DCE (backward liveness) ----
        needed = {v for v in jaxpr.outvars if _is_var(v)}
        kept = []
        for eqn in reversed(jaxpr.eqns):
            if any(v in needed for v in eqn.outvars) \
                    or getattr(eqn, "effects", None):
                kept.append(eqn)
                for v in eqn.invars:
                    if _is_var(v):
                        needed.add(v)
        eqns = list(reversed(kept))
        # ---- CSE (value numbering) ----
        canon = {}
        table = {}
        new_eqns = []
        for eqn in eqns:
            invars = [canon.get(v, v) if _is_var(v) else v
                      for v in eqn.invars]
            if invars != list(eqn.invars):
                eqn = eqn.replace(invars=invars)
            try:
                pk = _param_key(eqn.params)
            except TypeError:
                new_eqns.append(eqn)
                continue
            key = (eqn.primitive.name, pk,
                   tuple(v if _is_var(v) else ("lit", repr(v))
                         for v in invars))
            try:
                prev = table.get(key)
            except TypeError:   # unhashable corner: keep the eqn
                new_eqns.append(eqn)
                continue
            if prev is not None:
                for mine, theirs in zip(eqn.outvars, prev):
                    canon[mine] = theirs
            else:
                table[key] = list(eqn.outvars)
                new_eqns.append(eqn)
        outvars = [canon.get(v, v) if _is_var(v) else v
                   for v in jaxpr.outvars]
        jaxpr = jcore.Jaxpr(
            jaxpr.constvars, jaxpr.invars, outvars, new_eqns,
            getattr(jaxpr, "effects", frozenset()),
            debug_info=getattr(jaxpr, "debug_info", None))
    return jaxpr


class Group(object):
    __slots__ = ("gid", "kind", "label", "flops", "eqns", "values_in",
                 "values_out", "note")

    def __init__(self, gid, kind, label):
        self.gid = gid
        self.kind = kind        # "heavy" | "fusion"
        self.label = label
        self.flops = 0
        self.eqns = 0
        self.values_in = {}     # id(var) -> bytes  (read from outside)
        self.values_out = {}    # id(var) -> bytes  (visible outside)
        self.note = ""

    def bytes_total(self):
        return sum(self.values_in.values()) + sum(self.values_out.values())


def _pallas_cost(eqn):
    """flops from the kernel jaxpr x grid product; bytes as grid x block
    transfers (upper bound: Mosaic may serve revisited blocks from VMEM)."""
    params = eqn.params
    jaxpr = params.get("jaxpr")
    gm = params.get("grid_mapping")
    grid = 1
    try:
        for g in gm.grid:
            grid *= int(g)
    except Exception:
        grid = 1
    flops = 0
    if jaxpr is not None:
        inner = getattr(jaxpr, "jaxpr", jaxpr)
        flops = sum_flops_recursive(inner) * grid
    # boundary traffic: full operands + outputs at least once; blocks
    # revisited across grid steps make this an underestimate, full-array
    # counting makes it an overestimate for pruned (windowed) kernels —
    # call it the full-tensor floor and note it.
    bts = sum(_nbytes(v.aval) for v in eqn.invars) \
        + sum(_nbytes(v.aval) for v in eqn.outvars)
    name = params.get("name") or "pallas_call"
    return name, flops, bts


def sum_flops_recursive(jaxpr):
    total = 0
    for eqn in jaxpr.eqns:
        subs = list(_subjaxprs(eqn))
        if subs:
            for j, mult, _tag in subs:
                inner = getattr(j, "jaxpr", j)
                total += sum_flops_recursive(inner) * mult
        elif eqn.primitive.name == "pallas_call":
            total += _pallas_cost(eqn)[1]
        else:
            total += eqn_flops(eqn)
    return total


def analyze(jaxpr):
    """Fusion-group the top-level jaxpr. Inner jaxprs (pjit bodies) are
    inlined into the walk; pallas/scan/while stay opaque groups."""
    groups = []
    producer = {}       # var -> group
    var_consumers = {}  # var -> count (for fold-into-consumer decisions)

    def walk_count(j):
        for eqn in j.eqns:
            for v in eqn.invars:
                if hasattr(v, "aval") and not _is_literal(v):
                    var_consumers[v] = var_consumers.get(v, 0) + 1
            for sub, _m, _t in _subjaxprs(eqn):
                inner = getattr(sub, "jaxpr", sub)
                walk_count(inner)

    def _is_literal(v):
        return type(v).__name__ == "Literal"

    def new_group(kind, label):
        g = Group(len(groups), kind, label)
        groups.append(g)
        return g

    def feed(g, eqn, mult=1):
        g.eqns += 1
        if eqn.primitive.name == "pallas_call":
            name, fl, bts = _pallas_cost(eqn)
            g.flops += fl * mult
            g.label = "pallas:" + name
            g.note = "bytes=full-tensor floor (grid revisits not modeled)"
            for v in eqn.invars:
                if not _is_literal(v) and producer.get(v) is not g:
                    g.values_in[v] = _nbytes(v.aval)
            for v in eqn.outvars:
                g.values_out[v] = _nbytes(v.aval)
                producer[v] = g
            return
        g.flops += eqn_flops(eqn) * mult
        for v in eqn.invars:
            if _is_literal(v):
                continue
            pg = producer.get(v)
            if pg is not g:
                g.values_in[v] = _nbytes(v.aval)
        for v in eqn.outvars:
            producer[v] = g
            g.values_out[v] = _nbytes(v.aval)

    def walk(j, mult=1, depth=0):
        for eqn in j.eqns:
            p = eqn.primitive.name
            subs = list(_subjaxprs(eqn))
            if subs and p not in ("scan", "while"):
                # transparent wrappers (pjit/custom_vjp/remat): inline
                for sub, m, _t in subs:
                    inner = getattr(sub, "jaxpr", sub)
                    walk(inner, mult * m, depth + 1)
                # map wrapper outputs to the producing inner groups is
                # overkill here: outputs of the wrapper are produced by
                # the last inner groups; approximate by marking them
                # produced by the newest group so downstream reads don't
                # double-count them as external reads
                if groups:
                    for v in eqn.outvars:
                        producer[v] = groups[-1]
                        groups[-1].values_out[v] = _nbytes(v.aval)
                continue
            if p in ("scan", "while"):
                g = new_group("heavy", p)
                for sub, m, _t in subs:
                    inner = getattr(sub, "jaxpr", sub)
                    g.flops += sum_flops_recursive(inner) * m * mult
                g.eqns += 1
                if p == "while":
                    g.note = "dynamic trip count; body costed once"
                for v in eqn.invars:
                    if not _is_literal(v):
                        g.values_in[v] = _nbytes(v.aval)
                for v in eqn.outvars:
                    producer[v] = g
                    g.values_out[v] = _nbytes(v.aval)
                continue
            if p in HEAVY or p == "pallas_call":
                g = new_group("heavy", p)
                feed(g, eqn, mult)
                continue
            # fusible: join the group of its largest non-literal input if
            # that group is fusible OR this is its single elementwise tail
            best, best_bytes = None, -1
            for v in eqn.invars:
                if _is_literal(v):
                    continue
                pg = producer.get(v)
                if pg is None:
                    continue
                b = _nbytes(v.aval)
                if b > best_bytes:
                    best, best_bytes = pg, b
            if best is not None and (
                    best.kind == "fusion"
                    or _single_use_tail(eqn, best, var_consumers)):
                feed(best, eqn, mult)
            else:
                g = new_group("fusion", p)
                feed(g, eqn, mult)

    def _single_use_tail(eqn, pg, consumers):
        # output fusion: fold an elementwise op into the heavy producer
        # when every value it reads from that producer has no OTHER
        # consumer (bias-add/relu after conv; scale after dot)
        for v in eqn.invars:
            if type(v).__name__ == "Literal":
                continue
            if producer.get(v) is pg and consumers.get(v, 0) > 1:
                return False
        return True

    walk_count(jaxpr)
    walk(jaxpr)

    # prune values_in entries that ended up produced in the same group
    for g in groups:
        for v in list(g.values_in):
            if producer.get(v) is g:
                del g.values_in[v]
        # outputs only count as HBM writes if someone outside reads them
        # or they escape the jaxpr; approximate: keep all (upper bound)
    return groups


def floor_model(jaxpr):
    """Perfect-fusion HBM traffic floor.

    Model: XLA fuses every fusible chain into its heavy neighbor, so the
    only HBM traffic is (a) the step's inputs read + outputs written,
    (b) every heavy op's operand reads and result writes, (c) one write
    for a fusible-produced value a heavy op consumes (the chain must
    materialize its result somewhere for a conv/dot to read it — on TPU
    conv/dot operands are materialized, not streamed). Everything an
    elementwise chain does in between is free. Real XLA sits between
    this floor and the per-chain ceiling the group table reports.

    Returns totals plus by-dtype and by-heavy-kind splits — the dtype
    split is the actionable part (f32 bytes that could be bf16).
    """
    seen_writes = set()
    by_dtype = {}
    by_kind = {}
    totals = {"bytes": 0, "flops": 0}

    def _is_literal(v):
        return type(v).__name__ == "Literal"

    def account(nbytes, dtype, kind, is_flops=False):
        totals["bytes"] += nbytes
        by_dtype[dtype] = by_dtype.get(dtype, 0) + nbytes
        k = by_kind.setdefault(kind, {"bytes": 0, "flops": 0})
        k["bytes"] += nbytes

    producer_fusible = {}

    def walk(j, mult=1):
        for eqn in j.eqns:
            p = eqn.primitive.name
            subs = list(_subjaxprs(eqn))
            if subs and p not in ("scan", "while"):
                for sub, m, _t in subs:
                    walk(getattr(sub, "jaxpr", sub), mult * m)
                continue
            heavy = p in HEAVY or p == "pallas_call" or p == "scan"
            if heavy:
                kind = p
                if p == "pallas_call":
                    name, fl, _b = _pallas_cost(eqn)
                    kind = "pallas:" + name
                    flops = fl
                elif p in ("scan", "while"):
                    flops = sum(
                        sum_flops_recursive(getattr(sub, "jaxpr", sub)) * m
                        for sub, m, _t in subs)
                else:
                    flops = eqn_flops(eqn)
                totals["flops"] += flops * mult
                by_kind.setdefault(kind, {"bytes": 0, "flops": 0})
                by_kind[kind]["flops"] += flops * mult
                for v in eqn.invars:
                    if _is_literal(v):
                        continue
                    b = _nbytes(v.aval) * mult
                    account(b, str(v.aval.dtype), kind)
                    if producer_fusible.get(v) and v not in seen_writes:
                        seen_writes.add(v)
                        account(b, str(v.aval.dtype), "chain-materialize")
                for v in eqn.outvars:
                    account(_nbytes(v.aval) * mult, str(v.aval.dtype), kind)
            else:
                for v in eqn.outvars:
                    producer_fusible[v] = True
    walk(jaxpr)
    for v in jaxpr.invars:
        account(_nbytes(v.aval), str(v.aval.dtype), "step-io")
    for v in jaxpr.outvars:
        if not type(v).__name__ == "Literal":
            account(_nbytes(v.aval), str(v.aval.dtype), "step-io")
    return totals, by_dtype, by_kind


def summarize(groups, model_flops, label):
    rows = {}
    for g in groups:
        if g.eqns == 0:
            continue
        key = (g.kind, g.label)
        r = rows.setdefault(key, {
            "kind": g.kind, "op": g.label, "count": 0, "flops": 0,
            "hbm_bytes": 0, "note": g.note})
        r["count"] += 1
        r["flops"] += g.flops
        r["hbm_bytes"] += g.bytes_total()
    out = []
    total_t_nameplate = total_t_observed = 0.0
    for r in rows.values():
        t_flops = r["flops"] / PEAK_FLOPS
        t_mem = r["hbm_bytes"] / HBM_BW
        r["roofline_us_nameplate"] = round(max(t_flops, t_mem) * 1e6, 1)
        r["roofline_us_observed"] = round(
            max(r["flops"] / OBSERVED_PEAK_FLOPS, t_mem) * 1e6, 1)
        r["bound"] = "hbm" if t_mem > t_flops else "mxu"
        r["intensity_flops_per_byte"] = round(
            r["flops"] / max(1, r["hbm_bytes"]), 1)
        total_t_nameplate += max(t_flops, t_mem)
        total_t_observed += max(r["flops"] / OBSERVED_PEAK_FLOPS, t_mem)
        out.append(r)
    out.sort(key=lambda r: -r["roofline_us_nameplate"])
    summary = {
        "record": "summary", "model": label,
        "total_flops": int(sum(r["flops"] for r in out)),
        "model_flops_analytic": int(model_flops) if model_flops else None,
        "total_hbm_bytes": int(sum(r["hbm_bytes"] for r in out)),
        "groups": sum(r["count"] for r in out),
        "step_us_roofline_nameplate": round(total_t_nameplate * 1e6, 1),
        "step_us_roofline_observed": round(total_t_observed * 1e6, 1),
        "mfu_roofline_nameplate": round(
            (model_flops or sum(r["flops"] for r in out))
            / max(1e-12, total_t_nameplate) / PEAK_FLOPS, 4),
        "mfu_roofline_observed_ceiling": round(
            (model_flops or sum(r["flops"] for r in out))
            / max(1e-12, total_t_observed) / PEAK_FLOPS, 4),
        "peaks": {"nameplate_tflops": PEAK_FLOPS / 1e12,
                  "observed_tunnel_tflops": OBSERVED_PEAK_FLOPS / 1e12,
                  "hbm_gb_s": HBM_BW / 1e9},
    }
    return out, summary


# ---------------------------------------------------------------- models

def build_resnet(fluid, bs, img):
    """Same program bench.py times (bench.py:_bench_resnet, graph data)."""
    from paddle_tpu.models import resnet
    from paddle_tpu.transpiler import rewrite_program_amp
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 5
    startup.random_seed = 5
    with fluid.program_guard(main_prog, startup):
        pixel, label = fluid.layers.random_data_generator(
            shapes=[[bs, 3, img, img], [bs, 1]],
            dtypes=["float32", "int64"], int_high=999)
        predict = resnet.resnet_imagenet(pixel, 1000, depth=50)
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        loss = fluid.layers.mean(cost)
        fluid.optimizer.Momentum(
            learning_rate=0.1, momentum=0.9).minimize(loss)
    rewrite_program_amp(main_prog, "bfloat16")
    # bench.py TRAIN_GFLOP_PER_IMG (2-FLOPs-per-MAC hardware convention);
    # conv flops scale ~(img/224)^2
    model_flops = bs * 3 * 7.76e9 * (img / 224.0) ** 2
    return main_prog, startup, {}, model_flops


def build_transformer(fluid, bs, seq):
    from paddle_tpu.models import transformer
    from paddle_tpu.transpiler import rewrite_program_amp
    import numpy as np
    n_layer, n_head, d_model, d_inner, vocab = 6, 8, 512, 2048, 32000
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 7
    startup.random_seed = 7
    with fluid.program_guard(main_prog, startup):
        loss, feeds, _ = transformer.build(
            src_vocab_size=vocab, trg_vocab_size=vocab, max_length=seq,
            n_layer=n_layer, n_head=n_head, d_model=d_model,
            d_inner=d_inner, dropout=0.1)
        fluid.optimizer.Adam(learning_rate=2e-4).minimize(loss)
    rewrite_program_amp(main_prog, "bfloat16")
    rng = np.random.RandomState(11)
    feed = {
        "src_word": rng.randint(1, vocab, (bs, seq)).astype("int64"),
        "src_len": np.full((bs, 1), seq, "int64"),
        "trg_word": rng.randint(1, vocab, (bs, seq)).astype("int64"),
        "trg_len": np.full((bs, 1), seq, "int64"),
        "label": rng.randint(1, vocab, (bs, seq)).astype("int64"),
    }
    feed = {k: v for k, v in feed.items()
            if any(f.name == k for f in feeds)}
    # bench.py's exact 6N accounting (enc + dec incl. cross-attention)
    n_params = (
        n_layer * (4 * d_model * d_model + 2 * d_model * d_inner)
        + n_layer * (8 * d_model * d_model + 2 * d_model * d_inner))
    model_flops = 6 * n_params * bs * seq
    return main_prog, startup, feed, model_flops


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50",
                    choices=["resnet50", "transformer"])
    ap.add_argument("--bs", type=int, default=None)
    ap.add_argument("--img", type=int, default=224)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--out", default=None)
    ap.add_argument("--platform", default="tpu",
                    help="lowering target the trace assumes")
    args = ap.parse_args()

    import jax
    import paddle_tpu as fluid
    from paddle_tpu.core.lowering import BlockLowerer, build_step_fn

    if args.model == "resnet50":
        bs = args.bs or 128
        program, startup, feed, model_flops = build_resnet(
            fluid, bs, args.img)
    else:
        bs = args.bs or 64
        program, startup, feed, model_flops = build_transformer(
            fluid, bs, args.seq)

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(startup)
    from paddle_tpu.executor import global_scope
    scope = global_scope()
    scope_names = exe._scope_names(scope)

    lowerer = BlockLowerer(program, 0)
    state_in, state_out = lowerer.analyze(scope_names, set(feed))
    fetch_names = []
    step = build_step_fn(program, list(feed), fetch_names, state_in,
                         state_out, platform=args.platform)

    state_avals = {}
    for n in state_in:
        v = scope.find_var(n).value
        state_avals[n] = jax.ShapeDtypeStruct(v.shape, v.dtype)
    feed_avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                  for k, v in feed.items()}
    key_aval = jax.ShapeDtypeStruct((2,), "uint32")

    closed = jax.make_jaxpr(step)(state_avals, feed_avals, key_aval)
    raw_eqns = len(closed.jaxpr.eqns)
    opt = optimize_jaxpr(closed.jaxpr)
    print("jaxpr: %d eqns raw -> %d after dce+cse" %
          (raw_eqns, len(opt.eqns)), file=sys.stderr)
    groups = analyze(opt)
    rows, summary = summarize(groups, model_flops, args.model)

    ftot, fdtype, fkind = floor_model(opt)
    floor_np = floor_obs = 0.0
    kind_rows = {}
    for kind, r in fkind.items():
        t_mem = r["bytes"] / HBM_BW
        floor_np += max(r["flops"] / PEAK_FLOPS, t_mem)
        floor_obs += max(r["flops"] / OBSERVED_PEAK_FLOPS, t_mem)
        kind_rows[kind] = {
            "flops": int(r["flops"]), "bytes": int(r["bytes"]),
            "floor_us_nameplate": round(
                max(r["flops"] / PEAK_FLOPS, t_mem) * 1e6, 1),
            "bound": "hbm" if t_mem > r["flops"] / PEAK_FLOPS else "mxu"}
    summary.update({
        "hbm_bytes_floor": int(ftot["bytes"]),
        "step_us_floor_nameplate": round(floor_np * 1e6, 1),
        "step_us_floor_observed": round(floor_obs * 1e6, 1),
        "mfu_floor_nameplate": round(
            (model_flops or ftot["flops"]) / max(1e-12, floor_np)
            / PEAK_FLOPS, 4),
        "mfu_floor_observed_ceiling": round(
            (model_flops or ftot["flops"]) / max(1e-12, floor_obs)
            / PEAK_FLOPS, 4),
        "floor_bytes_by_dtype": {k: int(v) for k, v in sorted(
            fdtype.items(), key=lambda kv: -kv[1])},
        "floor_by_kind": kind_rows,
    })

    lines = [json.dumps(summary, sort_keys=True)]
    for r in rows:
        r["record"] = "group"
        lines.append(json.dumps(r, sort_keys=True))
    text = "\n".join(lines) + "\n"
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    print(text)


if __name__ == "__main__":
    main()
