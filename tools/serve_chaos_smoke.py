"""Serving-chaos smoke: prove the decode fleet survives the machine.

    python tools/serve_chaos_smoke.py $DIR    # writes $DIR/servechaos.json

Two legs, both asserted hard (the CI ``servechaos`` stage):

* **SIGKILL-mid-decode restore.** Three subprocesses share one
  ``FLAGS_exec_cache_dir`` and build the SAME seeded model + paged
  ``SlotDecodeSession``. The *oracle* decodes a 10-request backlog
  uninterrupted (and warms the executable cache). The *victim* runs
  with a ``DecodeSnapshotManager`` (periodic async snapshots) under
  ``kill@site=serve.dispatch,step=N`` — SIGKILLed entering a seeded
  step dispatch, no cleanup, the real preemption. The *restored*
  process constructs a fresh session, restores the newest VERIFIED
  snapshot (mid-write victims quarantine/skip), pumps the remaining
  backlog to completion and must emit token streams **bit-identical**
  to the oracle's — the ``(seed, slot, position)`` PRNG contract — with
  **0 fresh compiles** scraped from its metrics registry (every
  executable, init through the multi-step scan, comes from the warm
  persistent cache). It then times one synchronous snapshot
  (``snapshot_seconds``, budget-gated).
* **Overload brownout/recovery.** An in-process ``BatchingServer``
  with the degradation machine armed is flooded past its shed
  threshold: every refusal must be a TYPED retriable ``DegradedError``
  (retry-after hint), every admitted future must complete (no wedged
  requests), and after the drain the health gauge must read healthy
  again with the brownout->shed->...->healthy transitions counted in
  the registry.

The capture lands in ``$DIR/servechaos.json`` and the stage gates it
via ``tools/perf_diff.py --budgets benchmark/budgets.json --models
servechaos`` (``fresh_compiles`` max 0 deterministic,
``snapshot_seconds`` banded).
"""

import json
import os
import re
import signal
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

VOCAB, SEQ, D, S = 40, 16, 32, 4
N_REQUESTS = 10
KILL_STEP = 6
CFG = dict(src_vocab_size=VOCAB, trg_vocab_size=VOCAB, n_layer=1,
           n_head=2, d_inner=64)


def _scrape_fresh_compiles():
    from paddle_tpu.observability import REGISTRY

    text = REGISTRY.to_prometheus()
    m = re.search(r"^paddle_tpu_fresh_compiles_total (\d+)", text,
                  re.MULTILINE)
    return int(m.group(1)) if m else 0


def _build_session():
    """The one seeded model + session every child builds identically
    (cross-process determinism: BOTH programs carry the seed, so the
    startup init and the decode sampler replay bit-for-bit)."""
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer
    from paddle_tpu.serving.generation import Sampler, SlotDecodeSession

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 13
    startup.random_seed = 13
    with fluid.program_guard(main, startup):
        transformer.build(dropout=0.0, label_smooth_eps=0.0,
                          max_length=SEQ, d_model=D, **CFG)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    sess = SlotDecodeSession(
        exe, num_slots=S, max_length=SEQ, d_model=D, paged=True,
        page_size=4, steps=2, num_groups=2, prefix_cache_pages=8,
        sampler=Sampler(strategy="top_k", top_k=4, temperature=0.9,
                        seed=3), **CFG)
    return sess


def _requests():
    rng = np.random.RandomState(17)
    src = rng.randint(3, VOCAB, (N_REQUESTS, SEQ)).astype("int64")
    lens = [SEQ, 2, SEQ - 1, 5, SEQ, 3, SEQ - 2, SEQ, 4, SEQ]
    return src, lens


def child_oracle(workdir):
    sess = _build_session()
    src, lens = _requests()
    rids = [sess.enqueue(src[i], lens[i]) for i in range(N_REQUESTS)]
    done = {}
    while len(done) < N_REQUESTS:
        done.update(sess.pump())
    with open(os.path.join(workdir, "oracle.json"), "w") as f:
        json.dump({str(r): [int(t) for t in done[r]] for r in rids}, f)
    print("oracle: decoded %d requests" % N_REQUESTS)
    return 0


def child_victim(workdir):
    from paddle_tpu.serving.snapshot import DecodeSnapshotManager

    sess = _build_session()
    mgr = DecodeSnapshotManager(  # noqa: F841 - armed via the session hook
        sess, os.path.join(workdir, "snap"), interval_steps=2)
    src, lens = _requests()
    for i in range(N_REQUESTS):
        sess.enqueue(src[i], lens[i])
    while sess._pending or sess._live:
        sess.pump()  # chaos SIGKILLs entering step dispatch KILL_STEP
    print("victim: drained WITHOUT dying — chaos never fired",
          file=sys.stderr)
    return 1


def child_restored(workdir):
    from paddle_tpu.core import exec_cache
    from paddle_tpu.serving.snapshot import DecodeSnapshotManager

    sess = _build_session()
    mgr = DecodeSnapshotManager(sess, os.path.join(workdir, "snap"))
    manifest = mgr.restore()
    assert manifest is not None, "no restorable snapshot after SIGKILL"
    done = {}
    while sess._pending or sess._live:
        done.update(sess.pump())
    # requests that FINISHED before the snapshot ride it in the result
    # bank — the restored process serves those too, so every stream of
    # the whole backlog is re-emittable after the kill
    for rid in range(N_REQUESTS):
        if rid not in done:
            tokens = sess.take_result(rid)
            if tokens is not None:
                done[rid] = tokens
    # THE acceptance numbers: the whole process — startup init, session
    # init, restore scatter, the continuation's admits and multi-step
    # scans — compiled NOTHING; every executable was an AOT cache hit
    fresh = _scrape_fresh_compiles()
    stats = exec_cache.stats()
    assert fresh == 0, (
        "restored process paid %d fresh compiles (exec_cache: %r)"
        % (fresh, stats))
    t0 = time.perf_counter()
    mgr.save()
    snap_s = time.perf_counter() - t0
    with open(os.path.join(workdir, "restored.json"), "w") as f:
        json.dump({
            "restored_serial": mgr.restored_serial,
            "fresh_compiles": fresh,
            "snapshot_seconds": snap_s,
            "tokens": {str(r): [int(t) for t in v]
                       for r, v in done.items()},
        }, f)
    print("restored: serial %s, %d requests completed post-restore, "
          "0 fresh compiles, snapshot %.3fs"
          % (mgr.restored_serial, len(done), snap_s))
    return 0


def _spawn(mode, workdir, extra_env=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", mode,
         workdir],
        env=env, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def leg_sigkill_restore(workdir):
    cache = os.path.join(workdir, "cache")
    env = {"FLAGS_exec_cache_dir": cache}
    assert _spawn("oracle", workdir, env).returncode == 0
    victim = _spawn("victim", workdir, dict(
        env, FLAGS_chaos_spec="seed=5;kill@site=serve.dispatch,step=%d"
        % KILL_STEP))
    assert victim.returncode == -signal.SIGKILL, (
        "victim exited %r, expected death by SIGKILL" % victim.returncode)
    snap_root = os.path.join(workdir, "snap")
    assert os.path.isdir(snap_root) and any(
        d.startswith("checkpoint_") for d in os.listdir(snap_root)), \
        "victim left no snapshot behind"
    assert _spawn("restored", workdir, env).returncode == 0

    with open(os.path.join(workdir, "oracle.json")) as f:
        oracle = json.load(f)
    with open(os.path.join(workdir, "restored.json")) as f:
        restored = json.load(f)
    toks = restored["tokens"]
    assert toks, "restored process completed nothing"
    for rid, stream in toks.items():
        assert stream == oracle[rid], (
            "request %s: restored tokens diverge from the oracle\n"
            "  oracle:   %r\n  restored: %r"
            % (rid, oracle[rid], stream))
    # full coverage: live/pending work re-decodes, and requests that
    # finished BEFORE the snapshot ride its result bank — the restored
    # process re-emits the ENTIRE backlog bit-identical
    missing = [r for r in range(N_REQUESTS) if str(r) not in toks]
    assert not missing, "streams missing after restore: %s" % missing
    print("servechaos: SIGKILL leg OK — %d/%d token streams re-emitted "
          "bit-identical after restore (serial %s), 0 fresh compiles"
          % (len(toks), N_REQUESTS, restored["restored_serial"]))
    return restored


def leg_overload_brownout(workdir):
    from paddle_tpu.inference import NativeConfig, create_paddle_predictor
    from paddle_tpu.observability import REGISTRY
    from paddle_tpu.serving import loadgen
    from paddle_tpu.serving.degradation import DegradedError
    from paddle_tpu.serving.server import BatchingServer

    model_dir = os.path.join(workdir, "demo_model")
    loadgen.build_demo_model(model_dir, train_steps=5)
    predictor = create_paddle_predictor(
        NativeConfig(model_dir=model_dir, use_tpu=False))
    server = BatchingServer(
        predictor, max_batch=8, workers=1, max_queue_depth=8,
        batch_linger_s=0.05,
        degradation=dict(brownout_at=0.5, shed_at=0.75,
                         recover_at=0.25, retry_after_s=0.1))
    futures, rejects = [], 0
    with server:
        for req in loadgen.demo_requests(24):
            try:
                futures.append(server.submit(req))
            except Exception as exc:  # noqa: BLE001 - asserted typed below
                assert isinstance(exc, DegradedError), (
                    "overload produced a non-typed reject: %r" % exc)
                assert exc.retry_after_s > 0
                rejects += 1
        assert rejects > 0, "the flood never tripped shed"
        for fut in futures:  # no wedged requests: everything resolves
            fut.result(timeout=60.0)
        for req in loadgen.demo_requests(4):  # post-drain: serving again
            server.run(req)
        stats = server.stats()
    assert stats["health"] == "healthy", stats["health"]
    assert stats["degraded"] == rejects
    text = REGISTRY.to_prometheus()
    assert 'paddle_tpu_serving_health{component="server"} 0' in text
    transitions = sum(
        int(float(line.split()[-1])) for line in text.splitlines()
        if line.startswith("paddle_tpu_serving_health_transitions_total"))
    assert transitions >= 2, "no brownout round trip in the scrape"
    print("servechaos: overload leg OK — %d typed retriable rejects, "
          "%d admitted futures all resolved, %d health transitions, "
          "back to healthy" % (rejects, len(futures), transitions))


def main():
    if len(sys.argv) >= 2 and sys.argv[1] == "--child":
        return {"oracle": child_oracle, "victim": child_victim,
                "restored": child_restored}[sys.argv[2]](sys.argv[3])
    if len(sys.argv) != 2:
        sys.exit("usage: serve_chaos_smoke.py OUTPUT_DIR")
    workdir = sys.argv[1]
    restored = leg_sigkill_restore(workdir)
    leg_overload_brownout(workdir)
    capture = {"models": {"servechaos": {
        "fresh_compiles": restored["fresh_compiles"],
        "snapshot_seconds": restored["snapshot_seconds"],
    }}}
    path = os.path.join(workdir, "servechaos.json")
    with open(path, "w") as f:
        json.dump(capture, f)
    print("servechaos: capture -> %s (fresh_compiles=%d, "
          "snapshot_seconds=%.3f)" % (
              path, restored["fresh_compiles"],
              restored["snapshot_seconds"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
