#!/usr/bin/env python
"""Checkpoint inspector: print and verify a checkpoint manifest.

Works on a single ``checkpoint_<serial>`` dir or a checkpoint root (then
every complete serial is listed and the newest inspected). Deliberately
jax-free — this is the tool an operator runs on a corrupt-checkpoint
page, possibly on a machine with no accelerator stack at all.

Knows all three dialects: plain training checkpoints
(resilience/checkpoint.py), the elastic sharded dialect
(elastic/reshard.py — mesh + per-shard digests + shard-byte sums), and
decode snapshots (serving/snapshot.py — slots/pages/refcounts/prefix
trie printed; ``--verify`` additionally re-checks page conservation
``free + unique-allocated == num_pages - 1`` and the refcount
accounting against the slot page lists + prefix trie).

    python tools/ckpt_inspect.py CKPT_DIR [--verify] [--json]

Exit codes:  0 ok · 1 usage/unreadable · 2 verification failed (digest
mismatch / missing file / no complete checkpoint) — the code the chaos
CI stage and restore-time tooling gate on.
"""

import argparse
import hashlib
import json
import os
import sys

MANIFEST_NAME = "__manifest__.json"


def _sha256_file(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _read_manifest(step_dir):
    try:
        with open(os.path.join(step_dir, MANIFEST_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _verify(step_dir, manifest):
    problems = []
    for name, meta in sorted(manifest.get("vars", {}).items()):
        shards = meta.get("shards")
        entries = shards if shards else [meta]
        shard_bytes = 0
        broken = False
        for ent in entries:
            fname = ent.get("file")
            if not fname:
                problems.append("no file recorded for var %r" % name)
                broken = True
                continue
            path = os.path.join(step_dir, fname)
            if not os.path.exists(path):
                problems.append("missing file for var %r: %s"
                                % (name, fname))
                broken = True
                continue
            want = ent.get("sha256")
            if want and _sha256_file(path) != want:
                problems.append("digest mismatch: var %r (%s)"
                                % (name, fname))
                broken = True
            shard_bytes += int(ent.get("bytes", 0))
        # per-var shard-byte cross-check: a dropped/truncated shard whose
        # digest still matches its (short) manifest entry would otherwise
        # reassemble silently short — reshard bugs must be diagnosable
        # OFFLINE, before a restore trips on them
        if (shards and not broken and meta.get("bytes") is not None
                and shard_bytes != int(meta["bytes"])):
            problems.append(
                "shard bytes of var %r sum to %d, manifest records %d"
                % (name, shard_bytes, int(meta["bytes"])))
    for fname in manifest.get("files", []):
        if not os.path.exists(os.path.join(step_dir, fname)):
            problems.append("missing file %s" % fname)
    return problems


def _decode_summary(ds):
    """Operator summary of a decode-snapshot manifest's dialect block
    (serving/snapshot.py): slots, pages, refcounts, prefix trie,
    backlog."""
    cfg = ds.get("config") or {}
    pool = ds.get("pool") or {}
    ref = pool.get("ref") or {}
    cache = ds.get("prefix_cache")
    beam = ds.get("beam")
    out = {
        "config": cfg,
        "steps_done": ds.get("steps_done"),
        "live_slots": sorted(int(k) for k in (ds.get("live") or {})),
        "free_slots": len(ds.get("free_slots") or []),
        "pages_free": len(pool.get("free") or []),
        "pages_allocated": len(ref),
        "pages_shared": sum(1 for c in ref.values() if int(c) > 1),
        "reserved_pages": ds.get("reserved_pages"),
        "leaked_pages": ds.get("leaked_pages"),
        "prefix_entries": (len(cache.get("entries") or [])
                           if cache else 0),
        "pending_requests": len(ds.get("pending") or []),
    }
    spec = ds.get("speculative")
    if spec or (cfg.get("speculative")):
        counters = (spec or {}).get("counters") or {}
        drafter = (spec or {}).get("drafter") or {}
        proposed = int(counters.get("proposed", 0))
        accepted = int(counters.get("accepted", 0))
        out["speculative"] = {
            "config": cfg.get("speculative"),
            "draft_params": len(drafter.get("params") or []),
            "proposed": proposed,
            "accepted": accepted,
            "dispatches": int(counters.get("dispatches", 0)),
            "acceptance_rate": (accepted / proposed
                                if proposed else None),
            "drafter": drafter.get("kind"),
            "draft_cached_slots": len(
                (drafter.get("state") or {}).get("dpos") or {}),
        }
    if beam:
        # beam bookkeeping: width, live lanes with hypothesis->slot
        # bindings, per-hypothesis scores/done (from the live map) and
        # the last parent permutation
        live = ds.get("live") or {}
        lanes = {}
        for lane, b in sorted((beam.get("lanes") or {}).items(),
                              key=lambda kv: int(kv[0])):
            slots = [int(x) for x in b.get("slots", [])]
            lanes[int(lane)] = {
                "slots": slots,
                "scores": [live.get(str(s), {}).get("score")
                           for s in slots],
                "done": [live.get(str(s), {}).get("done")
                         for s in slots],
                "last_parents": [
                    int(p) for p in (beam.get("last_parents") or {})
                    .get(str(lane), [])],
            }
        out["beam"] = {
            "width": beam.get("width"),
            "lanes": lanes,
            "free_lanes": len(beam.get("free_lanes") or []),
            "banked_results": len(beam.get("results") or []),
        }
    return out


def _decode_verify(ds, vars_meta=None):
    """Re-check the allocator laws a decode snapshot must satisfy:
    page conservation (free + unique-allocated == num_pages - 1, the
    seeded property test's invariant) and reference accounting (every
    page's refcount equals the references the slot page lists and the
    prefix trie actually hold on it). A torn/tampered dialect block
    must fail OFFLINE, before a restore builds a session on it."""
    problems = []
    cfg = ds.get("config") or {}
    pool = ds.get("pool") or {}
    num_pages = int(pool.get("num_pages", cfg.get("num_pages", 0)))
    free = [int(p) for p in pool.get("free") or []]
    ref = {int(p): int(c) for p, c in (pool.get("ref") or {}).items()}
    if len(free) + len(ref) != num_pages - 1:
        problems.append(
            "page conservation broken: %d free + %d allocated != %d "
            "(num_pages - 1)" % (len(free), len(ref), num_pages - 1))
    if set(free) & set(ref):
        problems.append("pages %s are both free and allocated"
                        % sorted(set(free) & set(ref)))
    held = {}
    for slot, pages in (ds.get("slot_pages") or {}).items():
        for p in pages:
            held[int(p)] = held.get(int(p), 0) + 1
    cache = ds.get("prefix_cache")
    for entry in (cache.get("entries") if cache else []) or []:
        page = int(entry[2])
        held[page] = held.get(page, 0) + 1
    # deliberately-LEAKED pages (failed rollback/COW dispatches keep
    # their pages allocated forever — corruption beats capacity) hold
    # refcounts with no slot/trie holder by DESIGN: they only need
    # ref >= visible holds, everything else must account exactly
    leaked = set(int(p) for p in ds.get("leaked_page_ids") or [])
    bad = sorted(
        p for p in set(held) | set(ref)
        if (ref.get(p, 0) < held.get(p, 0) if p in leaked
            else held.get(p, 0) != ref.get(p, 0)))
    if bad:
        problems.append(
            "refcount accounting broken at pages %s: slot lists + "
            "prefix trie hold %s, pool records %s (leaked: %s)"
            % (bad[:8], {p: held.get(p, 0) for p in bad[:8]},
               {p: ref.get(p, 0) for p in bad[:8]},
               sorted(leaked)[:8]))
    live_pages = sorted(int(p) for p in ds.get("live_pages") or [])
    if live_pages != sorted(ref):
        problems.append(
            "gathered live_pages %s disagree with pool refcounts %s"
            % (live_pages[:8], sorted(ref)[:8]))
    spec_cfg = cfg.get("speculative")
    if spec_cfg:
        # speculative cross-checks: the tree verifier reads every
        # RESIDENT row of a live slot through its page list, so a
        # tampered binding (a page dropped from the list, or rebound
        # while its rows are still claimed resident) must fail offline
        # even when it was laundered past the conservation and
        # refcount checks above by editing free/ref to match.
        spec = ds.get("speculative") or {}
        counters = spec.get("counters") or {}
        if int(counters.get("accepted", 0)) > int(
                counters.get("proposed", 0)):
            problems.append(
                "speculative counters tampered: accepted %d > "
                "proposed %d" % (int(counters.get("accepted", 0)),
                                 int(counters.get("proposed", 0))))
        ps = int(cfg.get("page_size") or 1)
        live = ds.get("live") or {}
        slot_pages = ds.get("slot_pages") or {}
        for slot, st in sorted(live.items(), key=lambda kv: int(kv[0])):
            pages = [int(p) for p in slot_pages.get(str(slot)) or []]
            pos = int(st.get("pos", 0))
            need = pos // ps + 1  # rows 0..pos the tree reads as base
            if len(pages) < need:
                problems.append(
                    "speculative slot %s: %d bound pages cannot back "
                    "%d resident rows (pos=%d page_size=%d) — tree "
                    "reads would hit unbound pages"
                    % (slot, len(pages), pos + 1, pos, ps))
            for page in pages[:need]:
                if ref.get(page, 0) < 1:
                    problems.append(
                        "speculative slot %s: resident page %d has no "
                        "refcount — tree-page binding is dangling"
                        % (slot, page))
        drafter = spec.get("drafter") or {}
        if drafter and drafter.get("kind") != spec_cfg.get("drafter"):
            problems.append(
                "speculative drafter state kind %r does not match "
                "config %r" % (drafter.get("kind"),
                               spec_cfg.get("drafter")))
        if drafter.get("kind") == "model" and vars_meta is not None:
            # the draft params steer acceptance timing, which binds
            # future backlog requests to slots (and slots key the
            # sampler) — a restore without them would silently change
            # the restored session's future streams
            for pname in drafter.get("params") or []:
                if ("spec_dparam__" + pname) not in vars_meta:
                    problems.append(
                        "draft param %r listed in the speculative "
                        "dialect but missing from the manifest vars"
                        % pname)
        dpos = (drafter.get("state") or {}).get("dpos") or {}
        for slot, wm in sorted(dpos.items(), key=lambda kv: int(kv[0])):
            if str(slot) not in live:
                problems.append(
                    "draft watermark on slot %s which is not live"
                    % slot)
                continue
            pos = int(live[str(slot)].get("pos", 0))
            if int(wm) > pos + 1:
                problems.append(
                    "draft watermark %d on slot %s runs past its "
                    "anchor pos %d — draft rows claim pages the "
                    "target never wrote" % (int(wm), slot, pos))
            # draft rows [0, wm) live in the draft pools through the
            # SAME page table — they need the same bound pages
            need = ((int(wm) - 1) // ps + 1) if int(wm) > 0 else 0
            pages = [int(p) for p in slot_pages.get(str(slot)) or []]
            if len(pages) < need:
                problems.append(
                    "draft watermark %d on slot %s outruns its %d "
                    "bound pages" % (int(wm), slot, len(pages)))
    beam = ds.get("beam")
    if beam:
        # beam-binding cross-check: every lane's hypothesis slots must
        # be lane-aligned, LIVE, and hold a page list the refcounts
        # above already accounted for — a lane pointing at a freed or
        # foreign slot is a torn reorder
        width = int(beam.get("width") or 0)
        live = ds.get("live") or {}
        slot_pages = ds.get("slot_pages") or {}
        seen = set()
        for lane, b in sorted((beam.get("lanes") or {}).items()):
            slots = [int(x) for x in b.get("slots", [])]
            if len(slots) != width or any(
                    s // width != int(lane) for s in slots):
                problems.append(
                    "beam lane %s slots %s are not %d aligned "
                    "hypotheses of that lane" % (lane, slots, width))
            for s in slots:
                if s in seen:
                    problems.append(
                        "slot %d bound to two beam lanes" % s)
                seen.add(s)
                if str(s) not in live:
                    problems.append(
                        "beam lane %s binds slot %d which is not "
                        "live" % (lane, s))
                if str(s) not in slot_pages:
                    problems.append(
                        "beam lane %s binds slot %d with no page "
                        "list — its refcounts are unaccounted"
                        % (lane, s))
        lanes_total = (int((ds.get("config") or {})
                           .get("num_slots", 0)) // width
                       if width else 0)
        if (width and len(beam.get("lanes") or {})
                + len(beam.get("free_lanes") or []) != lanes_total):
            problems.append(
                "beam lane conservation broken: %d live + %d free != "
                "%d lanes" % (len(beam.get("lanes") or {}),
                              len(beam.get("free_lanes") or []),
                              lanes_total))
    return problems


def _serial_dirs(root):
    out = []
    for d in sorted(os.listdir(root)):
        if not d.startswith("checkpoint_"):
            continue
        suffix = d[len("checkpoint_"):]
        if suffix.isdigit():
            out.append((int(suffix), os.path.join(root, d)))
    return sorted(out)


def _summarize(step_dir, manifest, verify):
    vars_meta = manifest.get("vars", {})
    sharding = (manifest.get("extra") or {}).get("sharding")
    info = {
        "dir": step_dir,
        "manifest_version": manifest.get("manifest_version"),
        "serial": manifest.get("serial"),
        "step": manifest.get("step"),
        "num_vars": len(vars_meta) or len(manifest.get("files", [])),
        "bytes": sum(v.get("bytes", 0) for v in vars_meta.values()),
        "rng": manifest.get("rng"),
        "has_digests": any(
            v.get("sha256") or any(s.get("sha256")
                                   for s in v.get("shards", []))
            for v in vars_meta.values()),
        # the elastic dialect (elastic/reshard.py): which mesh this
        # checkpoint was written under and which vars are shard files
        "sharding": sharding,
        "sharded_vars": sorted(n for n, v in vars_meta.items()
                               if v.get("shards")),
    }
    # the decode-snapshot dialect (serving/snapshot.py): a live
    # SlotDecodeSession image — slots/pages/refcounts/prefix trie
    decode = (manifest.get("extra") or {}).get("decode_snapshot")
    info["decode"] = _decode_summary(decode) if decode else None
    if verify:
        problems = _verify(step_dir, manifest)
        if decode:
            problems = problems + _decode_verify(decode, vars_meta)
        info["problems"] = problems
    else:
        info["problems"] = None
    return info


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="checkpoint_<n> dir or checkpoint root")
    ap.add_argument("--verify", action="store_true",
                    help="re-hash every var file against the manifest")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.path):
        print("ckpt_inspect: not a directory: %s" % args.path,
              file=sys.stderr)
        return 1
    manifest = _read_manifest(args.path)
    if manifest is not None:
        targets = [(manifest.get("serial"), args.path)]
    else:
        targets = [(s, d) for s, d in _serial_dirs(args.path)
                   if _read_manifest(d) is not None]
        if not targets:
            print("ckpt_inspect: no complete checkpoint under %s "
                  "(no readable %s)" % (args.path, MANIFEST_NAME),
                  file=sys.stderr)
            return 2
    rc = 0
    reports = []
    for serial, step_dir in targets:
        m = _read_manifest(step_dir)
        info = _summarize(step_dir, m, args.verify)
        reports.append(info)
        if info["problems"]:
            rc = 2
    if args.as_json:
        print(json.dumps(reports, indent=2, sort_keys=True))
    else:
        for info in reports:
            print("checkpoint serial=%s step=%s  vars=%d  %.1f MiB  "
                  "manifest v%s%s" % (
                      info["serial"], info["step"], info["num_vars"],
                      info["bytes"] / 1048576.0,
                      info["manifest_version"],
                      "  rng=%(base_seed)d@%(run_counter)d"
                      % info["rng"] if info["rng"] else ""))
            decode = info.get("decode")
            if decode:
                cfg = decode.get("config") or {}
                print("  decode snapshot: step %s  slots live=%s "
                      "free=%d/%d" % (
                          decode["steps_done"],
                          decode["live_slots"], decode["free_slots"],
                          cfg.get("num_slots", 0)))
                print("  pages: %d allocated (%d shared) / %d free of "
                      "%s;  reserved=%s leaked=%s" % (
                          decode["pages_allocated"],
                          decode["pages_shared"], decode["pages_free"],
                          cfg.get("num_pages"),
                          decode["reserved_pages"],
                          decode["leaked_pages"]))
                print("  prefix trie: %d entries;  pending requests: %d"
                      % (decode["prefix_entries"],
                         decode["pending_requests"]))
                spec = decode.get("speculative")
                if spec:
                    scfg = spec.get("config") or {}
                    rate = spec.get("acceptance_rate")
                    print("  speculative: k=%s drafter=%s  proposed=%d "
                          "accepted=%d (%s)  dispatches=%d  draft "
                          "cache slots=%d  draft params=%d" % (
                              scfg.get("k"), spec.get("drafter")
                              or scfg.get("drafter"),
                              spec["proposed"], spec["accepted"],
                              "%.2f accept" % rate
                              if rate is not None else "no proposals",
                              spec["dispatches"],
                              spec["draft_cached_slots"],
                              spec["draft_params"]))
                beam = decode.get("beam")
                if beam:
                    print("  beam: width=%s  lanes live=%d free=%d  "
                          "banked n-bests=%d" % (
                              beam["width"], len(beam["lanes"]),
                              beam["free_lanes"],
                              beam["banked_results"]))
                    for lane, b in sorted(beam["lanes"].items()):
                        print("    lane %s: slots=%s scores=%s "
                              "done=%s parents=%s" % (
                                  lane, b["slots"],
                                  ["%.3f" % s if s is not None
                                   else "?" for s in b["scores"]],
                                  b["done"], b["last_parents"]))
            sharding = info.get("sharding")
            if sharding:
                mesh = sharding.get("mesh_axes") or {}
                factors = sharding.get("factors") or {}
                print("  mesh: %s" % (" x ".join(
                    "%s=%d" % (a, mesh[a]) for a in sorted(mesh))
                    or "(unrecorded)"))
                print("  shard factors: %s" % (", ".join(
                    "%s/%d" % (n, factors[n]) for n in sorted(factors))
                    or "(all vars whole)"))
            if args.verify:
                if info["problems"]:
                    for p in info["problems"]:
                        print("  FAIL %s" % p)
                elif info["has_digests"]:
                    print("  verified: all digests match")
                else:
                    print("  verified: files present (v1 manifest, "
                          "no digests)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
