"""Emit the public API signature spec for paddle_tpu.

Reference parity: tools/print_signatures.py + paddle/fluid/API.spec — the
reference locks its Python surface in a golden file so accidental API breaks
fail CI. Usage:

    python tools/print_signatures.py            # print spec to stdout
    python tools/print_signatures.py --update   # rewrite API.spec

The spec line format is ``qualified.name (param, param=default, ...)`` for
functions and ``qualified.name CLASS (init params)`` for classes; defaults
are repr()s so value changes are caught, not just renames.
"""

import argparse
import importlib
import inspect
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

MODULES = [
    "paddle_tpu",
    "paddle_tpu.layers",
    "paddle_tpu.layers.nn",
    "paddle_tpu.layers.tensor",
    "paddle_tpu.layers.control_flow",
    "paddle_tpu.layers.detection",
    "paddle_tpu.layers.sequence",
    "paddle_tpu.layers.io",
    "paddle_tpu.layers.rnn",
    "paddle_tpu.layers.attention",
    "paddle_tpu.layers.loss",
    "paddle_tpu.layers.metric_op",
    "paddle_tpu.layers.nlp",
    "paddle_tpu.layers.learning_rate_scheduler",
    "paddle_tpu.optimizer",
    "paddle_tpu.backward",
    "paddle_tpu.io",
    "paddle_tpu.initializer",
    "paddle_tpu.regularizer",
    "paddle_tpu.clip",
    "paddle_tpu.metrics",
    "paddle_tpu.nets",
    "paddle_tpu.inference",
    "paddle_tpu.data_feeder",
    "paddle_tpu.profiler",
    "paddle_tpu.transpiler",
    "paddle_tpu.parallel_executor",
    "paddle_tpu.reader.decorator",
    "paddle_tpu.evaluator",
    "paddle_tpu.recordio_writer",
    "paddle_tpu.distributed.master",
    "paddle_tpu.elastic.coordinator",
    "paddle_tpu.elastic.reshard",
    "paddle_tpu.elastic.worker",
    "paddle_tpu.dataset.common",
    "paddle_tpu.core.passes",
    # VERDICT r3 Weak #6: the generated unary-activation wrappers and the
    # remaining public-class surface must be under golden protection too
    "paddle_tpu.layers.ops",
    "paddle_tpu.contrib",
    "paddle_tpu.unique_name",
    "paddle_tpu.flags",
    # the top-level fluid surface (fluid.Program, fluid.Executor, ...) is
    # re-exported from these; the package has no __all__, so the golden
    # walks the defining modules
    "paddle_tpu.framework",
    "paddle_tpu.executor",
    "paddle_tpu.core.lod",
    # PR 3: the static-analysis surface (verifier / linter / liveness)
    "paddle_tpu.analysis",
    "paddle_tpu.analysis.diagnostics",
    "paddle_tpu.analysis.verify",
    "paddle_tpu.analysis.lint",
    "paddle_tpu.analysis.liveness",
    "paddle_tpu.debugger",
    # PR 4: the failure-forensics surface (black box / watchdog / NaN
    # provenance) — incident-response APIs are surface too
    "paddle_tpu.observability.blackbox",
    "paddle_tpu.observability.watchdog",
    "paddle_tpu.observability.nan_provenance",
    # PR 5: the recovery surface (checkpoint v2 / sessions / retry /
    # chaos) — what operators script disaster drills against
    "paddle_tpu.resilience.checkpoint",
    "paddle_tpu.resilience.session",
    "paddle_tpu.resilience.retry",
    "paddle_tpu.resilience.chaos",
    # PR 6: the memory surface (live-buffer ledger / memory plan / OOM
    # forensics) — what capacity planning scripts against
    "paddle_tpu.observability.memory",
    # PR 7: the sharding-transpiler surface (derived GSPMD plans + the
    # S001 spec validator) — what distributed recipes script against
    "paddle_tpu.parallel",
    "paddle_tpu.parallel.mesh",
    "paddle_tpu.parallel.sharding",
    "paddle_tpu.analysis.shard_check",
    # PR 8: the serving surface (continuous batching server + the
    # slot-paged decode session + the load generator CI/bench share)
    "paddle_tpu.serving.server",
    "paddle_tpu.serving.generation",
    "paddle_tpu.serving.loadgen",
    # PR 13: serving resilience — decode snapshots + degradation
    "paddle_tpu.serving.snapshot",
    "paddle_tpu.serving.degradation",
    # PR 14: the network front end — socket serving plane + wire client
    "paddle_tpu.serving.frontend",
    "paddle_tpu.serving.client",
]


def _sig(obj):
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"


def iter_spec():
    for modname in MODULES:
        mod = importlib.import_module(modname)
        names = getattr(mod, "__all__", None)
        if names is None:
            names = [n for n in dir(mod) if not n.startswith("_")]
            # without __all__, only symbols defined in this module count
            names = [
                n for n in names
                if getattr(getattr(mod, n), "__module__", None) == modname
            ]
        for name in sorted(names):
            obj = getattr(mod, name, None)
            if obj is None or inspect.ismodule(obj):
                continue
            qual = "%s.%s" % (modname, name)
            if inspect.isclass(obj):
                yield "%s CLASS %s" % (qual, _sig(obj.__init__))
                # public METHODS are surface too (the reference spec
                # lists Program.clone, Executor.run, .minimize, ...):
                # a signature change in one must fail the golden test
                for mname, meth in sorted(vars(obj).items()):
                    if mname.startswith("_"):
                        continue
                    if callable(meth) or isinstance(
                            meth, (staticmethod, classmethod)):
                        fn = meth.__func__ if isinstance(
                            meth, (staticmethod, classmethod)) else meth
                        if callable(fn):
                            yield "%s.%s %s" % (qual, mname, _sig(fn))
            elif callable(obj):
                yield "%s %s" % (qual, _sig(obj))
            else:
                yield "%s CONST %r" % (qual, type(obj).__name__)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--update", action="store_true",
                        help="rewrite API.spec next to this script's repo root")
    args = parser.parse_args()
    lines = list(iter_spec())
    if args.update:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec_path = os.path.join(root, "API.spec")
        header = []
        if os.path.exists(spec_path):
            # '#' annotation lines (deliberate absences vs the reference
            # surface) survive regeneration WHEREVER they sit in the
            # file — all are gathered into the header block
            with open(spec_path) as f:
                header = [line.rstrip("\n") for line in f
                          if line.lstrip().startswith("#")]
        with open(spec_path, "w") as f:
            if header:
                f.write("\n".join(header) + "\n")
            f.write("\n".join(lines) + "\n")
        print("wrote %d signatures to API.spec" % len(lines))
    else:
        sys.stdout.write("\n".join(lines) + "\n")


if __name__ == "__main__":
    main()
