"""Append-only perf trajectory: one ledger, every measured run.

Before this tool the repo's performance story lived in scattered
artifacts — ``BENCH_*.json`` one-shots, step-profile JSONLs a training
run left behind — and comparing two PRs meant hunting both files down.
The ledger subsumes that: every capture is APPENDED as one JSON line

    {"record": "ledger", "ts": ..., "label": ..., "git": ...,
     "models": {<model>: {<bench/stepprof capture fields>}, ...}}

which is exactly the ``{"models": ...}`` shape ``tools/perf_diff.py``
already parses (later lines win per model), so the whole trajectory file
IS a valid perf_diff artifact: gate the newest entry against the
checked-in budgets, or diff it against the previous entry, with the same
deterministic-vs-banded discipline the perfgate uses. Every item-1
kernel PR lands with a measured before/after by appending to the same
file.

Sources:

* ``--stepprof <p>.stepprof.jsonl`` — a step-observatory snapshot
  (FLAGS_step_profile=1); folded to one ``stepprof`` model entry
  (step-time percentiles, worst phase coverage, achieved-MFU p50,
  starvation fraction, regression count).
* ``--bench BENCH_*.json`` — a bench.py capture; its model entries are
  carried through verbatim.

Usage:
  python tools/perf_ledger.py append --ledger benchmark/perf_ledger.jsonl \
      --stepprof /tmp/m.stepprof.jsonl --bench BENCH_CPU.json --label pr19
  python tools/perf_ledger.py show --ledger benchmark/perf_ledger.jsonl
  python tools/perf_ledger.py diff --ledger benchmark/perf_ledger.jsonl \
      [--budgets benchmark/budgets.json] [--band 0.25]

Exit codes (diff): 0 clean, 1 regression(s), 2 unusable ledger.
"""

import argparse
import json
import math
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import perf_diff  # noqa: E402  (tools/ is not a package)

DEFAULT_LEDGER = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmark", "perf_ledger.jsonl")


def _percentile(vals, q):
    if not vals:
        return None
    vals = sorted(vals)
    k = max(0, min(len(vals) - 1,
                   int(math.ceil(q / 100.0 * len(vals))) - 1))
    return vals[k]


def summarize_stepprof(recs):
    """Fold a step-observatory snapshot to one ledger model entry.
    Field names match bench.py's capture vocabulary so perf_diff's
    normalizer picks them up unchanged."""
    timed = [r for r in recs if not r.get("dispatch_only")]
    if not timed:
        return None
    per_step = [r["step_s"] for r in timed]
    mfus = [r["achieved_mfu"] for r in timed
            if r.get("achieved_mfu") is not None]
    walls = [r.get("wall_s", 0.0) for r in timed]
    waits = [(r.get("phases") or {}).get("input_wait", 0.0)
             for r in timed]
    total = sum(walls) + 0.0
    entry = {
        "metric": "stepprof",
        "records": len(timed),
        "steps": sum(int(r.get("steps", 1)) for r in timed),
        "step_ms": {
            "p50": round((_percentile(per_step, 50) or 0) * 1e3, 4),
            "p95": round((_percentile(per_step, 95) or 0) * 1e3, 4),
        },
        "phase_coverage": round(min(r.get("coverage", 0.0)
                                    for r in timed), 4),
        "starvation_fraction": (round(sum(waits) / total, 4)
                                if total > 0 else 0.0),
        "regressions": sum(1 for r in timed if r.get("regression")),
    }
    if mfus:
        entry["achieved_mfu"] = round(_percentile(mfus, 50), 8)
    return entry


def _load_jsonl(path, what):
    if not os.path.exists(path):
        sys.exit("perf_ledger: %s does not exist (%s)" % (path, what))
    recs = []
    with open(path) as f:
        for line in f:
            if line.strip():
                try:
                    recs.append(json.loads(line))
                except ValueError:
                    pass
    if not recs:
        sys.exit("perf_ledger: %s carries no records (%s)" % (path, what))
    return recs


def _git_rev():
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        ).stdout.strip() or None
    except Exception:
        return None


def append_entry(ledger, models, label=None, source=None):
    """One trajectory point: append {"record": "ledger", ...} and return
    it. The file is created on first append; the directory must exist."""
    entry = {
        "record": "ledger",
        "ts": time.time(),
        "label": label,
        "git": _git_rev(),
        "source": source,
        "models": models,
    }
    with open(ledger, "a") as f:
        f.write(json.dumps(entry, sort_keys=True) + "\n")
    return entry


def read_ledger(path):
    return [r for r in _load_jsonl(path, "ledger")
            if isinstance(r, dict) and r.get("record") == "ledger"
            and isinstance(r.get("models"), dict)]


def _entry_metrics(entry):
    """{model: {metric: value}} of one ledger entry, through perf_diff's
    normalizer — the same view the gate sees."""
    out = {}
    for name, m in entry["models"].items():
        if isinstance(m, dict) and "error" not in m:
            norm = perf_diff._bench_model_metrics(m)
            if norm:
                out[name] = norm
    return out


def cmd_append(args):
    models = {}
    if args.stepprof:
        entry = summarize_stepprof(_load_jsonl(args.stepprof, "stepprof"))
        if entry is None:
            sys.exit("perf_ledger: %s carries no timed step records"
                     % args.stepprof)
        models["stepprof"] = entry
    if args.bench:
        for rec in _load_jsonl(args.bench, "bench"):
            if isinstance(rec.get("models"), dict):
                for name, m in rec["models"].items():
                    if isinstance(m, dict) and "error" not in m:
                        models[name] = m
    if not models:
        sys.exit("perf_ledger: nothing to append — pass --stepprof "
                 "and/or --bench")
    entry = append_entry(args.ledger, models, label=args.label,
                         source=args.stepprof or args.bench)
    print(json.dumps({"appended": sorted(models),
                      "label": entry["label"], "git": entry["git"],
                      "ledger": args.ledger,
                      "entries": len(read_ledger(args.ledger))}))


def cmd_show(args):
    entries = read_ledger(args.ledger)
    for e in entries:
        for model, metrics in sorted(_entry_metrics(e).items()):
            if args.model and model != args.model:
                continue
            for metric, val in sorted(metrics.items()):
                if args.metric and metric != args.metric:
                    continue
                print("%s  %-10s %-12s %-22s %s"
                      % (time.strftime("%Y-%m-%d %H:%M:%S",
                                       time.localtime(e["ts"])),
                         (e.get("label") or e.get("git") or "-")[:10],
                         model, metric, val))


def cmd_diff(args):
    """Gate the newest ledger entry: against the previous entry that
    shares a model (relative, banded) and/or the budgets file
    (absolute) — perf_diff's compare(), perf_diff's exit codes."""
    entries = read_ledger(args.ledger)
    newest = _entry_metrics(entries[-1])
    if not newest:
        sys.exit(2)
    results = []
    # previous entry per model: the before/after every perf PR lands with
    prev = {}
    for e in entries[:-1]:
        for model, metrics in _entry_metrics(e).items():
            prev[model] = metrics  # later (still pre-newest) wins
    prev = {m: v for m, v in prev.items() if m in newest}
    if prev:
        perf_diff.compare(newest, prev, args.band, "ledger", results)
    if args.budgets:
        try:
            with open(args.budgets) as f:
                budgets = json.load(f)
        except (OSError, ValueError) as e:
            print("perf_ledger: cannot read budgets %s (%s)"
                  % (args.budgets, e))
            raise SystemExit(2)
        ref, band = perf_diff.budget_reference(budgets)
        ref = {m: v for m, v in ref.items() if m in newest}
        perf_diff.compare(newest, ref, band, "budget", results,
                          require_all=True)
    if not results:
        print("perf_ledger: nothing to gate — one entry and no budgets "
              "covering its models")
        raise SystemExit(2)
    failures = [r for r in results if not r["ok"]]
    for r in results:
        mark = "FAIL" if not r["ok"] else "ok  "
        print("%s %-12s %-22s %-13s cand=%-14s %s=%-14s limit=%s"
              % (mark, r["model"], r["metric"], r["kind"],
                 r["candidate"], r["source"], r["reference"],
                 r["effective_limit"]))
    if failures:
        print("perf_ledger: %d regression(s) vs the trajectory"
              % len(failures))
        raise SystemExit(1)
    print("perf_ledger: clean (%d checks)" % len(results))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="append-only perf trajectory over bench/stepprof "
                    "captures, gated by perf_diff")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_app = sub.add_parser("append", help="append one trajectory point")
    p_app.add_argument("--ledger", default=DEFAULT_LEDGER)
    p_app.add_argument("--stepprof", default=None,
                       help="a <p>.stepprof.jsonl snapshot to fold in")
    p_app.add_argument("--bench", default=None,
                       help="a bench.py BENCH_*.json capture to fold in")
    p_app.add_argument("--label", default=None,
                       help="trajectory label (PR id, experiment name)")
    p_show = sub.add_parser("show", help="print the trajectory")
    p_show.add_argument("--ledger", default=DEFAULT_LEDGER)
    p_show.add_argument("--model", default=None)
    p_show.add_argument("--metric", default=None)
    p_diff = sub.add_parser("diff", help="gate the newest entry")
    p_diff.add_argument("--ledger", default=DEFAULT_LEDGER)
    p_diff.add_argument("--budgets", default=None)
    p_diff.add_argument("--band", type=float, default=0.25)
    args = ap.parse_args(argv)
    {"append": cmd_append, "show": cmd_show, "diff": cmd_diff}[args.cmd](
        args)


if __name__ == "__main__":
    main()
