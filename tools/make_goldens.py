"""Generate the committed golden-output regressions (tests/golden/).

For each registry model (tests/golden_models.py) this builds the serving
slice, materializes deterministic numpy parameters, runs the XLA oracle,
and writes tests/golden/<name>.npz = {expected output + feed arrays}.
tests/test_golden_cpp.py then asserts BOTH engines still reproduce the
committed bytes: the XLA path (catches lowering/numerics drift) and the
C++ interpreter (catches native-serving drift) — the zero-egress analog
of the reference's pretrained-model inference regressions
(paddle/fluid/inference/tests/api/, inference/test.cmake).

Regenerate deliberately after an intentional model/numerics change:
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python tools/make_goldens.py
"""

import os
import sys

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as fluid
    from golden_models import GOLDEN_MODELS, build_golden

    out_dir = os.path.join(ROOT, "tests", "golden")
    os.makedirs(out_dir, exist_ok=True)
    for name in sorted(GOLDEN_MODELS):
        with fluid.scope_guard(fluid.executor.Scope()):
            pruned, feed_names, fetch, feed, exe = build_golden(name)
            (want,) = exe.run(pruned, feed=feed, fetch_list=[fetch])
        expected = np.asarray(want)
        if not np.isfinite(expected).all():
            raise RuntimeError(
                "%s: oracle produced non-finite values — refusing to "
                "commit a garbage golden (param recipe bug?)" % name)
        payload = {"expected": expected}
        payload.update({"feed_" + k: v for k, v in feed.items()})
        path = os.path.join(out_dir, name + ".npz")
        np.savez_compressed(path, **payload)
        print("%s: expected %s -> %s (%d bytes)" % (
            name, payload["expected"].shape, os.path.basename(path),
            os.path.getsize(path)))


if __name__ == "__main__":
    main()
