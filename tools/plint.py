"""plint: verify + lint saved (or golden) Programs from the command line.

The CLI front-end of paddle_tpu/analysis/ — the role the reference's
C++ validation played at graph-load time, usable offline:

    python tools/plint.py path/to/model_dir            # dir with __model__
    python tools/plint.py path/to/program.ptpb         # raw PTPB binary
    python tools/plint.py --goldens                    # all registry models
    python tools/plint.py --golden transformer         # one registry model
    python tools/plint.py model_dir --fail-on=warning  # stricter gate

Prints every diagnostic (rule id, severity, location, fix hint) and
exits nonzero when any finding sits at/above ``--fail-on`` (default
"error" — what CI's `tools/run_ci.sh lint` stage enforces over the
golden models). ``--dump`` additionally prints the annotated
program_to_code listing with verifier-flagged ops marked ``!``.
"""

import argparse
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _load_saved(path):
    """A model dir (containing __model__) or a raw PTPB file -> Program
    plus feed/fetch names when the save recorded them."""
    from paddle_tpu.core.program_bin import deserialize_program

    model_file = path
    if os.path.isdir(path):
        model_file = os.path.join(path, "__model__")
    with open(model_file, "rb") as f:
        program = deserialize_program(f.read())
    feed_names = [
        v.name for v in program.global_block().vars.values()
        if getattr(v, "is_data", False)
    ]
    return program, feed_names, None


def _build_golden(name):
    sys.path.insert(0, os.path.join(_REPO_ROOT, "tests"))
    from golden_models import GOLDEN_MODELS

    import paddle_tpu as fluid
    from paddle_tpu import unique_name

    unique_name.switch()  # deterministic names, as tools/make_goldens.py
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        feed_names, fetch, _feed = GOLDEN_MODELS[name]()
    fetch_name = fetch.name if hasattr(fetch, "name") else str(fetch)
    return main, list(feed_names), [fetch_name]


def _run_one(label, program, feed_names, fetch_names, args):
    import paddle_tpu.analysis.diagnostics as diag_mod
    import paddle_tpu.analysis.lint as lint_mod
    import paddle_tpu.analysis.verify as verify_mod

    diags = verify_mod.verify(
        program, fetch_names=fetch_names, feed_names=feed_names,
        suppress=args.suppress)
    if not args.no_lint:
        diags += lint_mod.lint(program, suppress=args.suppress)
    print(diag_mod.format_diagnostics(
        diags, header="== %s ==" % label))
    if args.dump:
        from paddle_tpu import debugger

        print(debugger.program_to_code(program, diagnostics=diags))
    failing = diag_mod.at_or_above(diags, args.fail_on)
    return len(failing)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="plint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("paths", nargs="*",
                        help="model dirs (with __model__) or .ptpb files")
    parser.add_argument("--goldens", action="store_true",
                        help="lint every tests/golden_models.py model")
    parser.add_argument("--golden", action="append", default=[],
                        help="lint one registry model by name (repeatable)")
    parser.add_argument("--fail-on", default="error",
                        choices=("info", "warning", "error"),
                        help="exit nonzero when any finding is at/above "
                             "this severity (default: error)")
    parser.add_argument("--suppress", action="append", default=[],
                        help="rule id or name to ignore (repeatable)")
    parser.add_argument("--no-lint", action="store_true",
                        help="verifier only, skip retrace-hazard lint")
    parser.add_argument("--dump", action="store_true",
                        help="print the annotated program listing")
    args = parser.parse_args(argv)

    targets = []
    for p in args.paths:
        targets.append(("load", p))
    if args.goldens:
        sys.path.insert(0, os.path.join(_REPO_ROOT, "tests"))
        from golden_models import GOLDEN_MODELS

        targets.extend(("golden", n) for n in sorted(GOLDEN_MODELS))
    targets.extend(("golden", n) for n in args.golden)
    if not targets:
        parser.error("nothing to lint: pass paths, --goldens or --golden")

    failing = 0
    for kind, name in targets:
        if kind == "load":
            program, feed_names, fetch_names = _load_saved(name)
        else:
            program, feed_names, fetch_names = _build_golden(name)
        failing += _run_one(name, program, feed_names, fetch_names, args)
    if failing:
        print("plint: %d finding(s) at/above --fail-on=%s"
              % (failing, args.fail_on))
        return 1
    print("plint: clean at --fail-on=%s (%d target(s))"
          % (args.fail_on, len(targets)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
