#!/usr/bin/env bash
# Single build-and-test driver (the paddle_build.sh role, sized to this
# repo): native C++ build + its unit tests, the Python suite on the
# 8-device virtual CPU mesh, the driver's multichip dryrun, and a CPU
# proxy of the benchmark. Runs everything by default; pass stage names
# (native|python|lint|conclint|warm|metrics|forensics|chaos|shard|serve|
# decode|servechaos|route|net|trace|stepprof|elastic|dryrun|bench|
# perfgate) to run a subset.
#
#   tools/run_ci.sh                      # everything
#   tools/run_ci.sh python               # just pytest
#   tools/run_ci.sh lint                 # verifier+linter over goldens
#   BENCH_PLATFORM= tools/run_ci.sh bench   # on a TPU host: real-chip bench
set -euo pipefail
cd "$(dirname "$0")/.."

ALL_STAGES=(native python lint conclint warm metrics forensics chaos shard
            serve decode servechaos route net trace stepprof elastic dryrun
            bench perfgate)
stages=("$@")
[ ${#stages[@]} -eq 0 ] && stages=("${ALL_STAGES[@]}")
for s in "${stages[@]}"; do
  case " ${ALL_STAGES[*]} " in
    *" $s "*) ;;
    *) echo "unknown stage '$s' (valid: ${ALL_STAGES[*]})" >&2; exit 2 ;;
  esac
done

want() {
  local s
  for s in "${stages[@]}"; do [ "$s" = "$1" ] && return 0; done
  return 1
}

if want native; then
  echo "== native build + C++ tests =="
  cmake -S native -B native/build -G Ninja >/dev/null
  cmake --build native/build >/dev/null
  ./native/build/ptpu_native_test
fi

if want python; then
  echo "== python suite (8-device virtual CPU mesh) =="
  # force-merge the device-count flag: a pre-set XLA_FLAGS would defeat
  # conftest.py's setdefault and silently shrink the mesh to 1 device
  merged="--xla_force_host_platform_device_count=8"
  for tok in ${XLA_FLAGS:-}; do
    case "$tok" in
      --xla_force_host_platform_device_count=*) ;;
      *) merged="$merged $tok" ;;
    esac
  done
  # env -u PALLAS_AXON_POOL_IPS: the TPU-tunnel plugin registers itself
  # at interpreter start when that var is set, and a WEDGED tunnel then
  # hangs the first jax backend init even under JAX_PLATFORMS=cpu —
  # CPU-only stages must not depend on tunnel health
  XLA_FLAGS="$merged" env -u PALLAS_AXON_POOL_IPS \
    python -m pytest tests/ -q
fi

if want lint; then
  echo "== program verifier + retrace-hazard lint (golden models) =="
  # every registry model must verify structurally clean; warnings print
  # but only error-severity findings (bad graphs) fail the stage
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python tools/plint.py --goldens --fail-on=error
fi

if want conclint; then
  echo "== host-plane concurrency lint + witness-armed frontend smoke =="
  # leg 1: the C-rule lint over the framework's OWN source — lock-order
  # cycles, locks held across blocking calls, untimed acquires reachable
  # from signal handlers, unnamed threads (docs/ANALYSIS.md, *Host-plane
  # concurrency*); the tree must be clean (real fix or reasoned
  # suppression) at error severity
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python tools/locklint.py paddle_tpu/ --fail-on=error
  # leg 2: the runtime twin — rerun the frontend smoke with the lock
  # witness armed (FLAGS_lock_witness=1 wraps every framework lock at
  # construction); the warm leg asserts zero lock-order cycles, zero
  # dispatch-spanning holds, and the same 0-fresh-compiles gate, proving
  # the witness itself perturbs nothing
  cldir="$(mktemp -d)"
  trap 'rm -rf "$cldir"' EXIT
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    FLAGS_exec_cache_dir="$cldir/cache" FLAGS_telemetry=1 \
    FLAGS_lock_witness=1 \
    python tools/frontend_smoke.py cold "$cldir"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    FLAGS_exec_cache_dir="$cldir/cache" FLAGS_telemetry=1 \
    FLAGS_lock_witness=1 \
    python tools/frontend_smoke.py warm "$cldir"
  rm -rf "$cldir"
  trap - EXIT
fi

if want warm; then
  echo "== warm-start smoke (persistent executable cache) =="
  # two subprocesses share one exec_cache_dir; the second must execute
  # the same tiny program with ZERO fresh XLA compiles (asserted via the
  # exec_cache stats counters inside warm_start_smoke.py)
  cache_dir="$(mktemp -d)"
  trap 'rm -rf "$cache_dir"' EXIT
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    FLAGS_exec_cache_dir="$cache_dir" \
    python tools/warm_start_smoke.py cold
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    FLAGS_exec_cache_dir="$cache_dir" \
    python tools/warm_start_smoke.py warm
  rm -rf "$cache_dir"
  trap - EXIT
fi

if want metrics; then
  echo "== metrics smoke (flight recorder scrape) =="
  # two processes share one exec cache dir; each runs a 3-step MLP with
  # telemetry on and must leave a parseable Prometheus file with nonzero
  # paddle_tpu_steps_total; the warm one additionally proves the scrape
  # shows ZERO fresh compiles (metrics_smoke.py asserts all of it)
  mdir="$(mktemp -d)"
  trap 'rm -rf "$mdir"' EXIT
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    FLAGS_telemetry=1 FLAGS_metrics_path="$mdir/cold.prom" \
    FLAGS_exec_cache_dir="$mdir/cache" \
    python tools/metrics_smoke.py cold
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    FLAGS_telemetry=1 FLAGS_metrics_path="$mdir/warm.prom" \
    FLAGS_exec_cache_dir="$mdir/cache" \
    python tools/metrics_smoke.py warm
  rm -rf "$mdir"
  trap - EXIT
fi

if want forensics; then
  echo "== forensics smoke (black box + NaN provenance) =="
  # two child processes crash on purpose: one goes NaN under
  # FLAGS_check_nan_inf (the black box must blame the exact op and
  # blackbox_dump.py must exit non-zero on it), one SIGTERMs itself
  # mid-run (must die BY the signal and still leave a readable dump)
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python tools/forensics_smoke.py
fi

if want chaos; then
  echo "== chaos smoke (crash/resume + retry + corruption) =="
  # three child legs: a SIGKILLed trainer must resume from the newest
  # COMPLETE checkpoint with a bit-identical loss trajectory; a run with
  # injected transient dispatch faults must finish with
  # paddle_tpu_retries_total > 0 and retry events in the black box; a
  # corrupted latest checkpoint must be quarantined and the previous
  # serial loaded (chaos_smoke.py asserts all of it)
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python tools/chaos_smoke.py
fi

if want shard; then
  echo "== sharding transpiler smoke (derived data x fsdp x tp plan) =="
  # two processes share one exec cache dir on the 8-virtual-device CPU
  # mesh; each proves derived-plan loss parity with the single-device
  # run (ZERO hand-written tp_layout entries) and 1/N per-device
  # param+opt_state ledger bytes under the fsdp x tp split; the second
  # must additionally execute the SHARDED executable with zero fresh
  # XLA compiles via the persistent exec cache (shard_smoke.py asserts
  # all of it)
  sdir="$(mktemp -d)"
  trap 'rm -rf "$sdir"' EXIT
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    FLAGS_exec_cache_dir="$sdir" \
    python tools/shard_smoke.py cold
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    FLAGS_exec_cache_dir="$sdir" \
    python tools/shard_smoke.py warm
  rm -rf "$sdir"
  trap - EXIT
fi

if want serve; then
  echo "== serving smoke (continuous batching, 0 steady-state compiles) =="
  # two processes share one exec cache dir: the cold pass trains + saves
  # the demo model and warms the bucket-ladder executables; the warm one
  # replays a MIXED batch-size load and must scrape ZERO fresh compiles
  # from the metrics registry, prove batched == per-request bit-for-bit,
  # and land a latency capture that perf_diff gates against the
  # committed serving budgets (p99, throughput, occupancy)
  svdir="$(mktemp -d)"
  trap 'rm -rf "$svdir"' EXIT
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    FLAGS_exec_cache_dir="$svdir/cache" FLAGS_telemetry=1 \
    python tools/serve_smoke.py cold "$svdir"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    FLAGS_exec_cache_dir="$svdir/cache" FLAGS_telemetry=1 \
    python tools/serve_smoke.py warm "$svdir"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python tools/perf_diff.py "$svdir/serve.json" \
      --budgets benchmark/budgets.json --models serving
  rm -rf "$svdir"
  trap - EXIT
fi

if want decode; then
  echo "== paged decode smoke (ragged paged attention, 0 churn compiles) =="
  # one process: churny admit/release/step over the paged slot session
  # must add ZERO fresh compiles after warmup (metrics-registry scrape +
  # exec-cache counters), decode tokens must equal the dense oracle's,
  # and the drained pool must return every KV page; a second leg churns
  # the CROSS-REQUEST reuse paths (best-of-N fork groups + forced
  # divergence/COW + prefix-cache hits + release/re-admit) asserting 0
  # fresh compiles and refcount conservation at drain; a third leg (PR
  # 15) churns staggered BEAM admissions — 0 fresh compiles at warm
  # steady state, zero pages physically moved by rebind reorders, and
  # token/score bit-equality against the FLAGS_beam_reorder=reference
  # copy oracle; a fourth leg (PR 16) churns SPECULATIVE decode —
  # draft/tree-verify/accept/reject waves add 0 fresh compiles after
  # warmup and stream bit-identical to both the dense oracle and a
  # FLAGS_speculative=off replay on the same session; then the bench
  # decode worker lands an A/B capture (paged vs dense tokens/sec at
  # mixed lengths / low occupancy, the shared-vs-unshared best-of-N
  # ratio, prefix hit rate, grouped cross-K/V bytes, beam_speedup /
  # beam_reorder_bytes from the rebind-vs-copy beam A/B, plus
  # speculative_speedup / acceptance_rate from the draft-then-verify
  # vs sequential-oracle A/B) that perf_diff gates against the
  # committed decode budgets
  dcdir="$(mktemp -d)"
  trap 'rm -rf "$dcdir"' EXIT
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu FLAGS_telemetry=1 \
    python tools/decode_smoke.py "$dcdir"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python tools/perf_diff.py "$dcdir/decode.json" \
      --budgets benchmark/budgets.json --models decode
  rm -rf "$dcdir"
  trap - EXIT
fi

if want servechaos; then
  echo "== serving chaos smoke (SIGKILL mid-decode restore + overload) =="
  # leg 1: three subprocesses share one exec cache dir — an oracle
  # decodes a backlog uninterrupted, a snapshotting victim is SIGKILLed
  # entering a seeded step dispatch, and a restored process must re-emit
  # the remaining token streams BIT-identical to the oracle's with ZERO
  # fresh compiles scraped from its metrics registry; leg 2 floods a
  # degradation-armed BatchingServer past shed and asserts only typed
  # retriable rejects, no wedged futures, and a brownout->healthy round
  # trip in the health gauge. The capture (snapshot_seconds +
  # fresh_compiles) gates against the committed servechaos budgets.
  scdir="$(mktemp -d)"
  trap 'rm -rf "$scdir"' EXIT
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu FLAGS_telemetry=1 \
    python tools/serve_chaos_smoke.py "$scdir"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python tools/perf_diff.py "$scdir/servechaos.json" \
      --budgets benchmark/budgets.json --models servechaos
  rm -rf "$scdir"
  trap - EXIT
fi

if want route; then
  echo "== router fleet smoke (SIGKILL-a-frontend failover) =="
  # an oracle subprocess decodes the whole request set and warms one
  # shared exec cache; the parent then runs a ServingRouter over TWO
  # frontend subprocesses, pins duplicate (src, prefix) pairs to one
  # member via affinity hashing (prefix hits must survive the 2-member
  # scale-out), and SIGKILLs one frontend with live slots on board —
  # every concurrent stream must still complete through the router
  # BIT-identical to the oracle (the victim's banked snapshot restores
  # on the survivor, relays re-attach and splice at (rid, seq)) with
  # ZERO lost streams and ZERO fresh compiles on the survivor. The
  # capture gates against the committed router budgets.
  rtdir="$(mktemp -d)"
  trap 'rm -rf "$rtdir"' EXIT
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu FLAGS_telemetry=1 \
    python tools/router_smoke.py "$rtdir"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python tools/perf_diff.py "$rtdir/router.json" \
      --budgets benchmark/budgets.json --models router
  rm -rf "$rtdir"
  trap - EXIT
fi

if want net; then
  echo "== network front-end smoke (wire serving plane, 0 warm compiles) =="
  # two processes share one exec cache dir: the cold leg trains the
  # demo model, warms every executable and banks the IN-PROCESS oracle
  # (predict outputs + token streams incl. a best-of-2 fork and a
  # prefix-cache hit); the warm leg binds a ServingFrontend on a real
  # socket, replays the mixed unary+streaming load through
  # ServingClients and must prove: byte-identical responses/streams vs
  # the oracle, a client killed mid-stream leaves the KV pool at
  # refcount conservation, ZERO fresh compiles in the metrics scrape
  # fetched OVER THE WIRE, and overload shed reaching the client as
  # typed retriable DegradedError with a retry-after hint. The capture
  # (requests/sec, wire p50/p99, ttft_ms) gates against the committed
  # frontend budgets.
  ndir="$(mktemp -d)"
  trap 'rm -rf "$ndir"' EXIT
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    FLAGS_exec_cache_dir="$ndir/cache" FLAGS_telemetry=1 \
    python tools/frontend_smoke.py cold "$ndir"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    FLAGS_exec_cache_dir="$ndir/cache" FLAGS_telemetry=1 \
    python tools/frontend_smoke.py warm "$ndir"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python tools/perf_diff.py "$ndir/frontend.json" \
      --budgets benchmark/budgets.json --models frontend
  rm -rf "$ndir"
  trap - EXIT
fi

if want trace; then
  echo "== request-tracing smoke (free when off, complete when on) =="
  # three processes share one exec cache dir: the cold leg warms every
  # decode executable and banks the in-process token-stream oracle; the
  # OFF leg (control) replays the load over a real socket with tracing
  # unset and must prove bit-identical streams, NO trace field on the
  # wire and 0 fresh compiles; the ON leg replays with
  # FLAGS_request_tracing=1 and must prove the streams and compile
  # counters UNCHANGED, one wire-resolvable trace per request whose
  # span union covers >=95% of the client-observed wall, a TTFT
  # histogram exemplar resolving to a ring record, and
  # trace_view/step_breakdown rendering the flushed JSONL (waterfall +
  # valid Perfetto export). The capture (span_coverage,
  # fresh_compiles) gates against the committed trace budgets.
  tdir="$(mktemp -d)"
  trap 'rm -rf "$tdir"' EXIT
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    FLAGS_exec_cache_dir="$tdir/cache" FLAGS_telemetry=1 \
    python tools/trace_smoke.py cold "$tdir"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    FLAGS_exec_cache_dir="$tdir/cache" FLAGS_telemetry=1 \
    python tools/trace_smoke.py off "$tdir"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    FLAGS_exec_cache_dir="$tdir/cache" FLAGS_telemetry=1 \
    FLAGS_request_tracing=1 \
    python tools/trace_smoke.py on "$tdir"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python tools/perf_diff.py "$tdir/trace.json" \
      --budgets benchmark/budgets.json --models trace
  rm -rf "$tdir"
  trap - EXIT
fi

if want stepprof; then
  echo "== step-observatory smoke (free when off, accountable when on) =="
  # one process, two legs over the same seeded training job: the control
  # leg (FLAGS_step_profile unset) banks every fetch and the timed walls;
  # the profiled leg replays the identical schedule and must prove
  # bit-identical fetches, ZERO fresh compiles, >=95% of every step wall
  # attributed to named phases, a finite achieved-MFU join on every
  # training record, and the offline round trip (write_stepprof_jsonl ->
  # step_breakdown --steps -> perf_ledger append/show/diff). The capture
  # (phase_coverage, fresh_compiles, achieved_mfu, stepprof_overhead)
  # gates against the committed stepprof budgets.
  spdir="$(mktemp -d)"
  trap 'rm -rf "$spdir"' EXIT
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python tools/stepprof_smoke.py "$spdir"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python tools/perf_diff.py "$spdir/stepprof.json" \
      --budgets benchmark/budgets.json --models stepprof
  rm -rf "$spdir"
  trap - EXIT
fi

if want elastic; then
  echo "== elastic smoke (fleet churn: SIGKILL -> evict -> reshard) =="
  # two worker subprocesses + an in-parent FleetCoordinator: worker 1 is
  # SIGKILLed mid-epoch and must be evicted within the lease timeout;
  # the survivor reshards its checkpoint to world 1 and its loss segment
  # must be BIT-identical to a fresh process restored from the same
  # barrier checkpoint; a re-admitted worker joins at the next
  # generation and matches the survivor exactly; the fleet gauges +
  # reshard timings must land in the metrics scrape and the final
  # sharded checkpoint must pass ckpt_inspect --verify. A second leg
  # restarts the coordinator from its snapshot mid-run: heartbeats
  # retry through it with no spurious reshape (elastic_smoke.py asserts
  # all of it)
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python tools/elastic_smoke.py
fi

if want dryrun; then
  echo "== multichip dryrun (dp+ZeRO / tp / sp / pp) =="
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
fi

if want bench; then
  # Default cpu for CI determinism (and because a wedged TPU tunnel hangs
  # device enumeration); export BENCH_PLATFORM= (empty) on a TPU host to
  # let bench.py use the real chip.
  echo "== benchmark (BENCH_PLATFORM='${BENCH_PLATFORM-cpu}') =="
  # bench.py itself always exits 0 (the driver must get a JSON capture even
  # when the TPU tunnel is wedged), so CI red-flags total failure here: the
  # line must parse and at least one model must have produced a number.
  out="$(BENCH_PLATFORM="${BENCH_PLATFORM-cpu}" python bench.py)"
  echo "$out"
  echo "$out" | BENCH_EXPECT="${BENCH_MODELS-${BENCH_MODEL-resnet50,transformer,serving,frontend,decode}}" python -c '
import json, os, sys
rec = json.loads(sys.stdin.readline())
models = rec.get("models") or {}
want = [m.strip() for m in os.environ["BENCH_EXPECT"].split(",") if m.strip()]
missing = [m for m in want if m not in models]
assert not missing, "bench missing results for %s: %s" % (
    missing, rec.get("error"))
'
fi

if want perfgate; then
  echo "== perf/memory regression gate (CPU mini-bench vs budgets) =="
  # the CPU mini-bench runs with telemetry ON so the capture carries
  # step_ms percentiles + the HBM trajectory (peak_hbm_bytes measured by
  # the live-buffer ledger, predicted_peak_bytes from the memory plan);
  # tools/perf_diff.py gates it against the checked-in budgets —
  # deterministic counters (fresh compiles, predicted peak) fail on ANY
  # increase, timings get the budgets' noise band
  gdir="$(mktemp -d)"
  trap 'rm -rf "$gdir"' EXIT
  BENCH_PLATFORM=cpu FLAGS_telemetry=1 python bench.py \
    | tail -1 > "$gdir/candidate.json"
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python tools/perf_diff.py "$gdir/candidate.json" \
      --budgets benchmark/budgets.json
  rm -rf "$gdir"
  trap - EXIT
fi

echo "CI OK"
