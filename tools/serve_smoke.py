"""Serving smoke: prove the continuous-batching server gives a warm
process a mixed-shape steady state with ZERO fresh compiles, bit-exact
batched results, and a gated p99.

Run twice in two subprocesses sharing FLAGS_exec_cache_dir (tools/
run_ci.sh `serve` stage does exactly that):

    FLAGS_exec_cache_dir=$D/cache python tools/serve_smoke.py cold $D
    FLAGS_exec_cache_dir=$D/cache python tools/serve_smoke.py warm $D

The cold pass trains + saves the demo model into $D/model, then warms
the executable cache through the server's bucket ladder and a replay.
The warm pass — new process, the model loaded from disk, only the
structural fingerprints connecting it to the cold pass — replays a
MIXED batch-size load and asserts, in order:

  * the metrics-registry scrape reports **0 fresh compiles** for the
    whole warm process (`paddle_tpu_fresh_compiles_total 0`) — every
    bucket executable came from the persistent cache;
  * batched responses are bit-identical to the per-request
    `Predictor.run` oracle (raw for on-rung row counts, pad-to-rung
    `run_reference` for the rest);
  * a capture (`$D/serve.json`) carrying requests/sec, latency
    p50/p99, and batch occupancy, which the CI stage gates via
    `tools/perf_diff.py --budgets benchmark/budgets.json
    --models serving`.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

N_REQUESTS = 48
CONCURRENCY = 4


def _make_server(model_dir, predictor=None):
    from paddle_tpu.inference import NativeConfig, create_paddle_predictor
    from paddle_tpu.serving import BatchingServer

    predictor = predictor or create_paddle_predictor(
        NativeConfig(model_dir=model_dir, use_tpu=False))
    return predictor, BatchingServer(predictor, max_batch=8, workers=2,
                                     batch_linger_s=0.002)


def _scraped_fresh_compiles():
    """The acceptance-criteria source: the metrics registry's scrape,
    not a private counter."""
    from paddle_tpu.observability import REGISTRY

    for line in REGISTRY.to_prometheus().splitlines():
        if line.startswith("paddle_tpu_fresh_compiles_total "):
            return int(float(line.split()[-1]))
    raise AssertionError("scrape carries no paddle_tpu_fresh_compiles_total")


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "cold"
    workdir = sys.argv[2] if len(sys.argv) > 2 else None
    if mode not in ("cold", "warm") or not workdir:
        print("usage: serve_smoke.py cold|warm <workdir>", file=sys.stderr)
        return 2
    if not os.environ.get("FLAGS_exec_cache_dir"):
        print("serve_smoke: FLAGS_exec_cache_dir not set", file=sys.stderr)
        return 2
    model_dir = os.path.join(workdir, "model")

    from paddle_tpu.core import exec_cache
    from paddle_tpu.observability import telemetry
    from paddle_tpu.serving import loadgen

    # the capture gates memory (predicted/measured peak) alongside the
    # SLOs, so the ledger must be on even when the flag wasn't set
    telemetry.enable(True)

    if mode == "cold":
        loadgen.build_demo_model(model_dir)
    predictor, server = _make_server(model_dir)
    try:
        server.warmup()
        wall, ok, errors = loadgen.replay(
            server, loadgen.demo_requests(N_REQUESTS),
            concurrency=CONCURRENCY)
        assert ok == N_REQUESTS and not errors, (
            "replay failed: ok=%d errors=%r" % (ok, errors[:3]))

        if mode == "warm":
            # steady state FIRST: the whole warm process — warmup
            # included — must have been served from the persistent cache
            scraped = _scraped_fresh_compiles()
            st = exec_cache.stats()
            assert scraped == 0, (
                "warm process scrape shows %d fresh compile(s) under a "
                "mixed-shape load; the bucket ladder failed its job "
                "(aot_hits=%d aot_misses=%d)"
                % (scraped, st["aot_hits"], st["aot_misses"]))
            assert st["aot_hits"] >= 1, (
                "warm process loaded no AOT images (re-traced): %r" % st)

        # bit-exact parity (rung-sized raw comparisons only add already-
        # compiled shapes, so the warm zero-compile claim stays intact)
        rungs = set(server.stats()["batch_buckets"])
        for req in loadgen.demo_requests(8, seed=23):
            got = server.run(req)
            want = server.run_reference(req)
            for g, w in zip(got, want):
                assert np.array_equal(g, w), "padded-oracle parity broke"
            if req["x"].shape[0] in rungs:
                for g, w in zip(got, predictor.run(req)):
                    assert np.array_equal(g, np.asarray(w)), (
                        "raw per-request parity broke at rung size %d"
                        % req["x"].shape[0])
        if mode == "warm":
            assert _scraped_fresh_compiles() == 0, (
                "parity replay itself recompiled — rung shapes drifted")

        rec = loadgen.serving_capture(server, ok, wall)
        from paddle_tpu import profiler

        ms = profiler.memory_stats()
        rec["predicted_peak_bytes"] = ms["predicted_peak_bytes"]
        rec["peak_hbm_bytes"] = ms["measured_peak_bytes"]
        st = exec_cache.stats()
        rec["compile_seconds_cold"] = round(st["compile_seconds_cold"], 3)
        rec["exec_cache"] = {
            "enabled": st["enabled"],
            "fresh_compiles": st["fresh_compiles"],
            "persistent_hits": st["persistent_hits"],
            "aot_hits": st["aot_hits"],
        }
        rec["platform"] = "cpu"
        print("serve_smoke[%s]: %s" % (mode, json.dumps(rec)))
        if mode == "warm":
            with open(os.path.join(workdir, "serve.json"), "w") as f:
                json.dump({"models": {"serving": rec}}, f)
    finally:
        server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
