"""locklint: the host-plane concurrency lint (C rules) from the CLI.

The static half of the concurrency analysis pair
(``paddle_tpu/analysis/concurrency.py`` is the engine,
``observability/lock_witness.py`` the runtime twin): parses the named
files/directories as ONE unit — lock identities, the acquisition-order
graph and the signal-handler call graph all span modules — and prints
every C-rule Diagnostic:

    python tools/locklint.py paddle_tpu/                 # the whole tree
    python tools/locklint.py paddle_tpu/serving/         # one subsystem
    python tools/locklint.py paddle_tpu/ --fail-on=warning
    python tools/locklint.py paddle_tpu/ --suppress C005

Exits nonzero when any finding sits at/above ``--fail-on`` (default
"error" — what CI's ``tools/run_ci.sh conclint`` stage enforces over the
triaged tree). Intentional patterns are silenced in place with
``# conclint: C00x reason=...`` — the reason string is mandatory (C000)
so the source documents every waiver.
"""

import argparse
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="locklint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("paths", nargs="+",
                        help="python files or package directories")
    parser.add_argument("--fail-on", default="error",
                        choices=("info", "warning", "error"),
                        help="exit nonzero when any finding is at/above "
                             "this severity (default: error)")
    parser.add_argument("--suppress", action="append", default=[],
                        help="rule id or name to ignore globally "
                             "(repeatable; prefer inline "
                             "'# conclint: ... reason=...' waivers)")
    parser.add_argument("--rules", action="store_true",
                        help="print the C-rule catalog and exit")
    args = parser.parse_args(argv)

    from paddle_tpu.analysis import concurrency
    import paddle_tpu.analysis.diagnostics as diag_mod

    if args.rules:
        for rule in sorted(concurrency.RULES):
            slug, sev = concurrency.RULES[rule]
            print("%s  %-8s %s" % (rule, sev, slug))
        return 0

    files = concurrency.collect_files(args.paths)
    if not files:
        parser.error("no .py files under: %s" % ", ".join(args.paths))
    diags = concurrency.lint_paths(args.paths, suppress=args.suppress)
    print(diag_mod.format_diagnostics(
        diags, header="== locklint: %d file(s) ==" % len(files)))
    failing = diag_mod.at_or_above(diags, args.fail_on)
    if failing:
        print("locklint: %d finding(s) at/above --fail-on=%s"
              % (len(failing), args.fail_on))
        return 1
    print("locklint: clean at --fail-on=%s (%d file(s))"
          % (args.fail_on, len(files)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
