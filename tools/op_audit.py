"""Operator-completeness audit: every op the reference registers vs this
registry, with the by-design mapping for each absence.

Run:  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python tools/op_audit.py
Exits non-zero if an absence appears that is neither registered here nor
in the documented by-design table below — i.e. a NEW genuine gap.
"""

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REF_OPS_DIR = "/root/reference/paddle/fluid/operators"

# absences with a documented home (COVERAGE.md "op audit" section):
BY_DESIGN = {
    # regex artifacts of the REGISTER_* macro scrape, not ops
    "act_type": "macro argument, not an op",
    "op_name": "macro argument, not an op",
    "op_type": "macro argument, not an op",
    # executor-managed pseudo-ops
    "feed": "executor feeds directly (C++ interp: host-managed)",
    "fetch": "executor fetches directly",
    "delete_var": "XLA owns buffer lifetime",
    "fake_init": "pserver-side init; no pserver (GSPMD)",
    # gRPC/NCCL distributed machinery -> GSPMD + jax.distributed
    # (docs/DISTRIBUTED_DESIGN.md)
    "send": "GSPMD collectives", "recv": "GSPMD collectives",
    "send_barrier": "GSPMD collectives", "fetch_barrier": "GSPMD",
    "listen_and_serv": "no pserver; DistributeTranspiler plan surface",
    "gen_nccl_id": "XLA collectives, no NCCL", "nccl": "XLA collectives",
    "prefetch": "sparse pserver prefetch; scoped out with rationale",
    "checkpoint_notify": "io.save_checkpoint handles checkpoints",
    "ref_by_trainer_id": "pserver machinery",
    "lookup_sparse_table": "pserver sparse table; SelectedRows covers",
    "merge_ids": "pserver sparse machinery",
    "split_ids": "pserver sparse machinery",
    "split_selected_rows": "pserver sparse machinery",
    "split_byref": "pserver sparse machinery",
    "extract_rows": "pserver sparse machinery",
    # legacy/experimental subsystems the reference itself superseded
    "parallel_do": "ParallelExecutor (GSPMD) replaces",
    "get_places": "mesh construction replaces",
    "go": "CSP experiment; n/a",
    "tensorrt_engine": "CUDA-specific; XLA is the deploy compiler",
    # While-RNN memory machinery -> lax.scan lowering design
    "rnn_memory_helper": "lax.scan carries state",
    "shrink_rnn_memory": "padded-batch design (docs/LOD_DESIGN.md)",
    "max_sequence_len": "padded-batch design",
    "split_lod_tensor": "padded/mask design (docs/LOD_DESIGN.md)",
    "merge_lod_tensor": "padded/mask design",
    # readers -> reader/decorator.py + PyReader + open_files
    "create_custom_reader": "reader combinators",
    "read": "PyReader/open_files design",
    # naming: the reference registers the DYNAMIC rnn ops under the bare
    # names; this registry uses the layer-facing names
    "lstm": "registered as dynamic_lstm",
    "lstmp": "registered as dynamic_lstmp",
    "gru": "registered as dynamic_gru",
    # conditional_block is lowered via the sub-block machinery
    "conditional_block": "ops/control_flow_ops.py cond lowering",
    # ModelAverage keeps its accumulators in optimizer state
    "average_accumulates": "optimizer.ModelAverage internal state",
}


def main():
    pat = re.compile(
        r"REGISTER_OP(?:ERATOR|_WITHOUT_GRADIENT|_CPU_KERNEL"
        r"|_CUDA_KERNEL|_KERNEL)?\s*\(\s*([a-z0-9_]+)")
    ref_ops = set()
    for root, _, files in os.walk(REF_OPS_DIR):
        for fn in files:
            if not fn.endswith((".cc", ".cu", ".h")):
                continue
            try:
                text = open(os.path.join(root, fn), errors="replace").read()
            except OSError:
                continue
            ref_ops.update(pat.findall(text))
    ref_fwd = {o for o in ref_ops if not o.endswith("_grad")}

    import paddle_tpu  # noqa: F401  (registers every op)
    from paddle_tpu.core import op_registry

    ours = set()
    for attr in dir(op_registry):
        v = getattr(op_registry, attr)
        if isinstance(v, dict) and "conv2d" in v:
            ours = set(v)
            break

    unexplained = sorted(
        o for o in ref_fwd if o not in ours and o not in BY_DESIGN)
    covered = len([o for o in ref_fwd if o in ours])
    print("reference fwd ops: %d | registered here: %d | by-design: %d "
          "| UNEXPLAINED: %d"
          % (len(ref_fwd), covered,
             len([o for o in ref_fwd if o in BY_DESIGN and o not in ours]),
             len(unexplained)))
    for o in unexplained:
        print("  UNEXPLAINED:", o)
    return 1 if unexplained else 0


if __name__ == "__main__":
    sys.exit(main())
