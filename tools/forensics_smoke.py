"""Forensics smoke (CI ``forensics`` stage): crash like production does,
then read the black box like an engineer would.

Two subprocess legs, both asserted from the parent:

1. **NaN leg** — the child runs a hand-built program whose ``log`` op
   goes non-finite under ``FLAGS_check_nan_inf=1``. The child must die
   non-zero, the black box must record the N001 diagnostic blaming the
   ``log`` op, and ``tools/blackbox_dump.py`` must exit 3 (its
   NaN-gate) on that dump.
2. **Signal leg** — the child SIGTERMs itself mid-run. The process must
   die BY the signal (not a clean exit), and the dump's last events
   must show the fatal signal arriving after the step dispatch.

Usage: python tools/forensics_smoke.py          # parent, runs both legs
       (child modes are internal)
"""

import json
import os
import signal
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _child_env(box):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(JAX_PLATFORMS="cpu", FLAGS_blackbox_path=box,
               FLAGS_check_nan_inf="1", FLAGS_nan_provenance="1")
    return env


def _build_and_run_nan():
    import numpy as np

    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        h = fluid.layers.scale(x, scale=2.0)
        y = fluid.layers.log(h)       # x contains a zero -> -inf here
        out = fluid.layers.mean(y)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": np.array([[1.0, 2.0, 0.0, 3.0]], dtype="float32")}
    exe.run(main, feed=feed, fetch_list=[out])  # raises NonFiniteError


def _run_then_sigterm():
    import numpy as np

    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4])
        out = fluid.layers.mean(fluid.layers.scale(x, scale=2.0))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.run(main, feed={"x": np.ones((2, 4), "float32")},
            fetch_list=[out])
    os.kill(os.getpid(), signal.SIGTERM)  # handler dumps, then re-raises
    raise SystemExit("unreachable: SIGTERM should have killed us")


def _read_box(box):
    with open(box) as f:
        return json.load(f)


def _nan_leg(tmp):
    box = os.path.join(tmp, "nan.box.json")
    rc = subprocess.call(
        [sys.executable, os.path.abspath(__file__), "child-nan", box],
        env=_child_env(box))
    assert rc != 0, "NaN child should have died non-zero, got rc=0"
    snap = _read_box(box)
    diag = snap.get("nan_diagnostic")
    assert diag, "black box carries no nan_diagnostic: %s" % sorted(snap)
    assert diag["rule"] == "N001" and diag["op_type"] == "log", (
        "expected N001 blaming 'log', got %r" % diag)
    # the CLI gate: exit 3 when a NaN diagnostic is recorded
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "blackbox_dump.py"),
         box], capture_output=True, text=True)
    assert proc.returncode == 3, (
        "blackbox_dump should exit 3 on a NaN dump, got %d\n%s"
        % (proc.returncode, proc.stdout + proc.stderr))
    assert "N001" in proc.stdout and "log" in proc.stdout, proc.stdout
    print("forensics nan leg OK: N001 blamed op 'log'; dump CLI exits 3")


def _signal_leg(tmp):
    box = os.path.join(tmp, "sig.box.json")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "child-signal", box],
        env=_child_env(box))
    assert proc.returncode == -signal.SIGTERM, (
        "child should die BY SIGTERM (rc=-15), got rc=%d"
        % proc.returncode)
    snap = _read_box(box)
    kinds = [e["kind"] for e in snap["events"]]
    assert "fatal_signal" in kinds and "dispatch" in kinds, kinds
    assert snap["reason"].startswith("fatal_signal"), snap["reason"]
    assert snap.get("thread_stacks"), "signal dump must carry stacks"
    print("forensics signal leg OK: SIGTERM death left a readable box")


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "child-nan":
        _build_and_run_nan()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "child-signal":
        _run_then_sigterm()
        return
    import tempfile

    with tempfile.TemporaryDirectory(prefix="forensics_") as tmp:
        _nan_leg(tmp)
        _signal_leg(tmp)
    print("forensics smoke OK")


if __name__ == "__main__":
    main()
