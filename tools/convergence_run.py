"""Convergence-on-chip proof (VERDICT r3 Next #9): train the book MNIST
conv model to its convergence threshold ON THE TPU and emit the loss
curve + final test accuracy as a committable artifact.

Reference: python/paddle/fluid/tests/book/test_recognize_digits.py trains
to a convergence threshold on real downloaded MNIST. This rig has zero
network egress, so the data is an IDX-gzip fixture written in MNIST's
real on-disk format (class templates + noise, the test_book_realdata.py
fixture recipe) and parsed by the REAL file->parser->reader pipeline
under PADDLE_TPU_DATASET=real — the synthetic in-memory fallback is
disabled, so what trains here went through the same bytes-on-disk path a
real download would. The artifact records that provenance.

Usage:  python tools/convergence_run.py            # TPU if reachable
        BENCH_PLATFORM=cpu python tools/convergence_run.py   # CPU smoke
Prints one JSON line (the artifact) and exits 0 on convergence,
1 otherwise.
"""

import hashlib
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_TRAIN, N_TEST, BS = 4096, 1024, 64
MAX_EPOCHS = 5
ACC_THRESHOLD = 0.95  # test-split accuracy (book threshold is 0.9 train)


def _batches(reader, bs):
    buf = []
    for sample in reader():
        buf.append(sample)
        if len(buf) == bs:
            yield buf
            buf = []


def main():
    data_home = tempfile.mkdtemp(prefix="convergence_mnist_")
    # DATA_HOME is read at import time: set it before paddle_tpu loads
    os.environ["PADDLE_TPU_DATA_HOME"] = data_home
    os.environ["PADDLE_TPU_DATASET"] = "real"
    # imported only after DATA_HOME is set: the package reads it at import
    from paddle_tpu.dataset.fixtures import write_mnist_idx_fixture

    write_mnist_idx_fixture(os.path.join(data_home, "mnist"), N_TRAIN, 7,
                            "train")
    write_mnist_idx_fixture(os.path.join(data_home, "mnist"), N_TEST, 8,
                            "t10k")

    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    import paddle_tpu as fluid
    import paddle_tpu.dataset as ds
    from paddle_tpu.models import mnist as mnist_model

    # repoint the md5 pins at the fixtures (the book-realdata-test
    # recipe): try_download then verifies the on-disk files and never
    # touches the (absent) network
    for attr in ("TRAIN_IMAGE", "TRAIN_LABEL", "TEST_IMAGE", "TEST_LABEL"):
        fname = getattr(ds.mnist, attr)[0]
        path = os.path.join(data_home, "mnist", fname)
        md5 = hashlib.md5(open(path, "rb").read()).hexdigest()
        setattr(ds.mnist, attr, (fname, md5))

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 1
    with fluid.program_guard(main_prog, startup):
        loss, feeds, outs = mnist_model.build()
        test_prog = main_prog.clone(for_test=True)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    acc = outs["accuracy"]

    place = fluid.TPUPlace() if on_tpu else fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(startup)

    def feed_of(batch):
        return {
            "pixel": np.stack(
                [s[0].reshape(1, 28, 28) for s in batch]),
            "label": np.asarray([[s[1]] for s in batch], "int64"),
        }

    loss_curve = []  # (global_step, loss) every 8 steps
    epochs_run = 0
    final_acc = 0.0
    t0 = time.perf_counter()
    step = 0
    for epoch in range(MAX_EPOCHS):
        for batch in _batches(ds.mnist.train(), BS):
            fetch = [loss] if step % 8 == 0 else []
            out = exe.run(main_prog, feed=feed_of(batch), fetch_list=fetch)
            if fetch:
                loss_curve.append(
                    [step, round(float(np.ravel(out[0])[0]), 5)])
            step += 1
        accs = [
            float(np.ravel(exe.run(test_prog, feed=feed_of(b),
                                   fetch_list=[acc])[0])[0])
            for b in _batches(ds.mnist.test(), BS)
        ]
        final_acc = float(np.mean(accs))
        epochs_run = epoch + 1
        if final_acc >= ACC_THRESHOLD:
            break
    wall = time.perf_counter() - t0

    artifact = {
        "model": "mnist_conv (models/mnist.py, book recognize_digits)",
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", "") or dev.platform,
        "data": "IDX-gzip fixture in MNIST's real on-disk format, parsed "
                "by the real pipeline (zero-egress rig; "
                "PADDLE_TPU_DATASET=real, synthetic fallback disabled)",
        "train_samples": N_TRAIN, "test_samples": N_TEST,
        "batch_size": BS, "epochs_run": epochs_run, "steps": step,
        "final_test_accuracy": round(final_acc, 4),
        "threshold": ACC_THRESHOLD,
        "converged": final_acc >= ACC_THRESHOLD,
        "final_train_loss": loss_curve[-1][1] if loss_curve else None,
        "wall_seconds": round(wall, 1),
        "loss_curve": loss_curve,
    }
    print(json.dumps(artifact))
    # exit 0 either way: a completed non-convergent run is still a valid
    # (negative) artifact — the JSON carries "converged"; only a crash
    # (unhandled exception) signals a capture worth discarding
    return 0


if __name__ == "__main__":
    sys.exit(main())
