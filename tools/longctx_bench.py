"""Long-context scaling evidence (VERDICT r3 Next #5).

Three hardware-independent measurements, each pinned to a claim from
docs/LONG_CONTEXT.md, emitted as JSON lines for docs/artifacts/:

1. ``reference-memory``: XLA memory analysis of reference (einsum)
   attention fwd+bwd across sequence lengths — the materialized
   [B,H,T,S] score temp grows O(T^2); this is the wall the flash path
   removes (the r3 transformer-bs128 OOM dump is its chip-side twin).
2. ``window-pruning``: wall time of the Pallas flash kernel (interpret
   mode on CPU — the same grid pruning the TPU runs) at fixed T with
   the sliding window on/off: visited k-tiles drop from T/block to
   ~window/block, so time scales O(window), not O(T).
3. ``ring-memory``: per-device temp memory of ring attention on an
   8-device virtual mesh at global seq 8*Tl vs single-device reference
   attention at the same global length — the ring never materializes
   the global score matrix (O(Tl * block) per device), which is the
   whole point of sequence parallelism.

On-chip wall-time legs (transformer-seq1024/-seq4096 + the
reference-attention control) are captured by tools/hw_window.sh.

Usage: env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \\
         XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
         python tools/longctx_bench.py
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

B, H, D = 1, 4, 64


def _temp_bytes(compiled):
    """Best-effort temp allocation size from a compiled executable."""
    try:
        ma = compiled.memory_analysis()
        return int(getattr(ma, "temp_size_in_bytes", 0))
    except Exception:
        return None


def reference_memory_sweep(fa, jax, jnp):
    for seq in (256, 1024, 4096):
        q = jnp.zeros((B, H, seq, D), jnp.float32)

        def loss(q, k, v):
            return fa.flash_attention_reference(q, k, v, causal=True).sum()

        compiled = (
            jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            .lower(q, q, q).compile()
        )
        tb = _temp_bytes(compiled)
        score_bytes = 4 * B * H * seq * seq  # one f32 [B,H,T,T] temp
        print(json.dumps({
            "bench": "reference-memory", "seq": seq,
            "temp_bytes": tb,
            "score_matrix_bytes": score_bytes,
            "claim": "reference fwd+bwd temps grow O(T^2)",
        }))


def _tiles_visited(seq, block_q, block_k, window, causal=True):
    """Count (qi, kj) tiles the kernel's ``run`` predicate computes —
    the EXACT skip rule from kernels/flash_attention.py:_flash_kernel,
    so this is the kernel's own per-query FLOP bound, not a model."""
    n_q, n_k = seq // block_q, seq // block_k
    visited = 0
    for qi in range(n_q):
        q_base = qi * block_q
        for kj in range(n_k):
            k_base = kj * block_k
            run = True
            if causal:
                run = k_base <= q_base + block_q - 1
            if window:
                run = run and (k_base + block_k - 1 > q_base - window)
                if not causal:
                    run = run and (
                        k_base - (q_base + block_q - 1) < window)
            visited += run
    return visited, n_q * n_k


def window_pruning_sweep(fa, jax, jnp):
    """Tile-visit counts under the kernel's own skip predicate, plus the
    interpret-mode parity check. Interpret-mode WALL TIME is useless
    here (measured: flat across windows — each of the 1024 grid steps
    costs ~2.5 ms of interpreter machinery, drowning the skipped
    compute), so the on-chip number comes from kernel_bench's windowed
    flash rows in the hardware window instead."""
    rng = np.random.RandomState(0)
    seq, bq, bk = 4096, 128, 128
    for window in (0, 512, 256):
        visited, total = _tiles_visited(seq, bq, bk, window)
        print(json.dumps({
            "bench": "window-tiles", "seq": seq, "window": window,
            "block": bq, "tiles_visited": visited, "tiles_total": total,
            "fraction": round(visited / total, 4),
            "claim": "computed k-tiles per query ~ window/block + 1, "
                     "so chip time is O(window) not O(T); wall-time "
                     "leg = kernel_bench flash windowed rows (chip)",
        }))
    # correctness spot-check at a small shape: windowed Pallas output
    # equals the masked reference (the pruning must drop only dead tiles)
    q = jnp.asarray(rng.randn(B, H, 256, D), jnp.float32)
    got = fa.flash_attention(q, q, q, causal=True, window=64,
                             force_pallas=True)
    want = fa.flash_attention(q, q, q, causal=True, window=64,
                              force_reference=True)
    err = float(jnp.max(jnp.abs(got - want)))
    print(json.dumps({"bench": "window-parity", "seq": 256, "window": 64,
                      "max_abs_err": err}))
    assert err < 2e-3, err


def ring_memory(fa, jax, jnp):
    from paddle_tpu.parallel.mesh import build_mesh
    from paddle_tpu.parallel.ring_attention import ring_attention

    n = min(8, len(jax.devices()))
    if n < 2:
        print(json.dumps({"bench": "ring-memory",
                          "skipped": "needs >= 2 devices"}))
        return
    tl = 512
    tg = n * tl
    mesh = build_mesh(num_devices=n, data=n)
    q = jnp.zeros((B, H, tg, D), jnp.float32)

    ring = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, axis_name="data", causal=True,
        impl="reference").sum())
    ring_tb = _temp_bytes(ring.lower(q, q, q).compile())

    full = jax.jit(lambda q, k, v: fa.flash_attention_reference(
        q, k, v, causal=True).sum())
    full_tb = _temp_bytes(full.lower(q, q, q).compile())
    print(json.dumps({
        "bench": "ring-memory", "devices": n, "seq_global": tg,
        "seq_per_device": tl,
        "ring_temp_bytes_total": ring_tb,
        "single_device_temp_bytes": full_tb,
        "ring_per_device": (ring_tb // n) if ring_tb else None,
        "claim": "ring shards the score work: per-device temps carry "
                 "[Tl, Tl] blocks, never the [Tg, Tg] matrix",
    }))


def ring_walltime_scaling(fa, jax, jnp):
    """VERDICT r4 Next #6: a committed wall-time curve that needs no
    chip. Weak scaling on the virtual mesh: fixed per-device sequence,
    device count 2/4/8, jitted fwd+bwd through the XLA ring path (NOT
    interpret mode — impl="reference" composes the per-block attention
    in XLA; only the ring schedule/ppermute structure is exercised).

    Virtual CPU devices share one physical machine, so absolute wall
    time GROWS with n (total causal work is O(Tg^2) and the compute
    pool is fixed); the honest scaling signal is time normalized by
    global work, which must stay ~flat as devices double — any
    superlinear overhead from the ring's collectives would show up as
    growth. A same-global-length single-device full-attention control
    gives the work envelope."""
    import time

    from paddle_tpu.parallel.mesh import build_mesh
    from paddle_tpu.parallel.ring_attention import ring_attention

    tl = 256  # per-device sequence (weak scaling)
    have = len(jax.devices())
    for n in (2, 4, 8):
        if n > have:
            print(json.dumps({"bench": "ring-walltime",
                              "devices": n,
                              "skipped": "only %d devices" % have}))
            continue
        tg = n * tl
        mesh = build_mesh(num_devices=n, data=n)

        def ring_loss(q, k, v):
            return ring_attention(q, k, v, mesh, axis_name="data",
                                  causal=True, impl="reference").sum()

        def full_loss(q, k, v):
            return fa.flash_attention_reference(q, k, v,
                                                causal=True).sum()

        rng = np.random.RandomState(5)
        qkv = tuple(
            jnp.asarray(rng.randn(B, H, tg, D).astype(np.float32))
            for _ in range(3))
        # pre-shard the ring's inputs to their in-computation layout so
        # the timed region measures the ring schedule, not the
        # harness's scatter/gather of unsharded arrays
        from jax.sharding import NamedSharding, PartitionSpec as P
        seq_sharded = NamedSharding(mesh, P(None, None, "data", None))
        qkv_ring = tuple(jax.device_put(a, seq_sharded) for a in qkv)

        rows = {}
        for tag, loss, args in (("ring", ring_loss, qkv_ring),
                                ("full-control", full_loss, qkv)):
            step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            out = step(*args)  # compile + warmup
            jax.block_until_ready(out)
            times = []
            for _ in range(3):
                t0 = time.perf_counter()
                jax.block_until_ready(step(*args))
                times.append(time.perf_counter() - t0)
            rows[tag] = sorted(times)[1]
        print(json.dumps({
            "bench": "ring-walltime", "devices": n,
            "seq_per_device": tl, "seq_global": tg,
            "ring_ms": round(rows["ring"] * 1e3, 2),
            "full_control_ms": round(rows["full-control"] * 1e3, 2),
            "ring_ms_per_Mwork": round(
                rows["ring"] * 1e3 / (tg * tg / 1e6), 3),
            "full_ms_per_Mwork": round(
                rows["full-control"] * 1e3 / (tg * tg / 1e6), 3),
            "claim": "normalized ring time stays ~flat as devices "
                     "double: the ring schedule adds no superlinear "
                     "collective overhead over the O(Tg^2) causal work",
        }))


def main():
    import importlib

    import jax
    import jax.numpy as jnp

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    fa = importlib.import_module("paddle_tpu.kernels.flash_attention")

    print(json.dumps({
        "host": "cpu-virtual" if jax.devices()[0].platform == "cpu"
        else str(jax.devices()[0].device_kind),
        "devices": len(jax.devices()),
    }))
    reference_memory_sweep(fa, jax, jnp)
    window_pruning_sweep(fa, jax, jnp)
    ring_memory(fa, jax, jnp)
    ring_walltime_scaling(fa, jax, jnp)


if __name__ == "__main__":
    main()
