"""Warm-start smoke: prove the persistent executable cache kills the
compile tax across process boundaries.

Run twice in two subprocesses sharing FLAGS_exec_cache_dir (tools/
run_ci.sh `warm` stage does exactly that):

    FLAGS_exec_cache_dir=$D python tools/warm_start_smoke.py cold
    FLAGS_exec_cache_dir=$D python tools/warm_start_smoke.py warm

The cold pass populates the cache (and asserts it really compiled).
The warm pass builds the SAME program from scratch — new process, new
Program/Scope objects, so only the structural fingerprint can connect it
to the cold pass's executables — and asserts ZERO fresh XLA compiles
plus at least one AOT executable image loaded. It also asserts
run_async().result() matches run() bit-for-bit while the dispatch call
returns before the fetches have materialized.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_and_run():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8])
        hid = fluid.layers.fc(x, size=16, act="relu")
        y = fluid.layers.fc(hid, size=4)
        out = fluid.layers.reduce_sum(y, dim=[1])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": np.arange(32, dtype="float32").reshape(4, 8) / 32.0}
    (sync_out,) = exe.run(main, feed=feed, fetch_list=[out])
    handle = exe.run_async(main, feed=feed, fetch_list=[out])
    (async_out,) = handle.result()
    assert np.array_equal(np.asarray(sync_out), async_out), (
        "run_async().result() diverged from run(): %r vs %r"
        % (sync_out, async_out)
    )
    return handle


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "cold"
    if not os.environ.get("FLAGS_exec_cache_dir"):
        print("warm_start_smoke: FLAGS_exec_cache_dir not set", file=sys.stderr)
        return 2
    build_and_run()
    from paddle_tpu.core import exec_cache

    st = exec_cache.stats()
    print("warm_start_smoke[%s]: %s" % (mode, json.dumps({
        k: st[k] for k in (
            "fresh_compiles", "persistent_hits", "persistent_misses",
            "aot_hits", "aot_misses", "aot_errors",
            "compile_seconds_cold", "compile_seconds_warm",
        )
    })))
    assert st["enabled"], "exec cache did not enable from the flag"
    if mode == "cold":
        assert st["fresh_compiles"] > 0 or st["persistent_hits"] > 0, (
            "cold pass neither compiled nor hit a pre-warmed cache"
        )
    else:
        assert st["fresh_compiles"] == 0, (
            "warm process paid %d fresh XLA compile(s); the persistent "
            "cache failed to serve them" % st["fresh_compiles"]
        )
        assert st["aot_hits"] >= 1, (
            "warm process loaded no AOT executable images (re-traced "
            "everything): aot_misses=%d aot_errors=%d"
            % (st["aot_misses"], st["aot_errors"])
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
