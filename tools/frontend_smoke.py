"""Network front-end smoke: prove the socket serving plane gives a warm
process a mixed unary+streaming steady state with ZERO fresh compiles,
wire token streams bit-identical to in-process decode, disconnect-safe
reclamation, and typed retriable overload rejects.

Run twice in two subprocesses sharing FLAGS_exec_cache_dir
(tools/run_ci.sh ``net`` stage does exactly that):

    FLAGS_exec_cache_dir=$D/cache python tools/frontend_smoke.py cold $D
    FLAGS_exec_cache_dir=$D/cache python tools/frontend_smoke.py warm $D

The COLD pass trains + saves the demo MLP (unary), builds the seeded
decode transformer, warms every executable the wire path will need
(bucket ladder, admit/step/prefill/join), and banks the IN-PROCESS
oracle: per-request predict outputs and token streams for the whole
mixed load — solo generations, an ``admit_group`` best-of-2 fork with a
forced prefix, and the SAME prefix again (the cache-hit case).

The WARM pass — new process, only structural fingerprints connecting it
to the cold one — binds a ``ServingFrontend`` on a real socket and
replays the same load through ``ServingClient``s, asserting in order:

  * unary replay over the wire: every response BIT-identical to the
    cold pass's oracle outputs (base64 raw-buffer framing, so this is
    byte equality, not tolerance);
  * streaming: every token stream — including the best-of-N fork and
    the prefix-cache hit — bit-identical to the cold in-process oracle,
    delivered in per-dispatch chunks (time-to-first-token measured
    client-side);
  * disconnect reclamation: a client severed mid-stream leaves the pool
    at refcount conservation (free + unique-allocated == P - 1), every
    slot free, and the next admission succeeds;
  * THE gate: the metrics scrape — fetched OVER THE WIRE via the
    ``metrics`` endpoint — reports **0 fresh compiles** for the whole
    warm process;
  * overload: a degradation-armed server flooded past shed answers the
    wire client with typed retriable ``DegradedError`` (retry-after
    hint) — and with the classified budget armed the same flood rides
    through.

The capture (``$D/frontend.json``: requests/sec, wire latency p50/p99,
ttft_ms) gates via ``tools/perf_diff.py --budgets benchmark/budgets.json
--models frontend``.
"""

import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

N_REQUESTS = 48
CONCURRENCY = 4
VOCAB, SEQ, D, S = 40, 16, 32, 4
N_STREAMS = 4
CFG = dict(src_vocab_size=VOCAB, trg_vocab_size=VOCAB, n_layer=1,
           n_head=2, d_inner=64)


def _build_decode_session():
    """The one seeded decode model + session both passes build
    identically (cross-process determinism: both programs carry the
    seed, so every executable fingerprint matches the cold pass's)."""
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer
    from paddle_tpu.serving.generation import Sampler, SlotDecodeSession

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 13
    startup.random_seed = 13
    with fluid.program_guard(main, startup):
        transformer.build(dropout=0.0, label_smooth_eps=0.0,
                          max_length=SEQ, d_model=D, **CFG)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return SlotDecodeSession(
        exe, num_slots=S, max_length=SEQ, d_model=D, paged=True,
        page_size=4, steps=2, num_groups=2, prefix_cache_pages=8,
        sampler=Sampler(strategy="top_k", top_k=4, temperature=0.9,
                        seed=3), **CFG)


def _decode_load():
    """(src rows, lens, prefix) — the deterministic streaming mix."""
    rng = np.random.RandomState(17)
    src = rng.randint(3, VOCAB, (N_STREAMS + 1, SEQ)).astype("int64")
    lens = [SEQ, 5, SEQ - 1, 7, SEQ]
    prefix = [int(t) for t in src[N_STREAMS][:6]]
    return src, lens, prefix


def _scraped_fresh_compiles(text):
    for line in text.splitlines():
        if line.startswith("paddle_tpu_fresh_compiles_total "):
            return int(float(line.split()[-1]))
    raise AssertionError(
        "scrape carries no paddle_tpu_fresh_compiles_total")


def _oracle_streams(sess):
    """The in-process decode oracle: what the wire streams must equal
    bit-for-bit. Order matters — the wire pass replays admissions in
    this exact order, so slot assignment (and the (seed, slot,
    position) PRNG streams) line up."""
    src, lens, prefix = _decode_load()
    out = {}
    for i in range(N_STREAMS):
        out["solo_%d" % i] = sess.generate(
            src[i][None, :], [lens[i]]).tolist()
    out["bestof"] = sess.generate_best_of(
        src[N_STREAMS], 2, src_len=lens[N_STREAMS],
        prefix_tokens=prefix).tolist()
    out["prefix_hit"] = sess.generate_best_of(
        src[N_STREAMS], 2, src_len=lens[N_STREAMS],
        prefix_tokens=prefix).tolist()
    return out


def cold(workdir):
    from paddle_tpu.inference import NativeConfig, create_paddle_predictor
    from paddle_tpu.serving import BatchingServer, loadgen

    model_dir = os.path.join(workdir, "model")
    loadgen.build_demo_model(model_dir)
    predictor = create_paddle_predictor(
        NativeConfig(model_dir=model_dir, use_tpu=False))
    server = BatchingServer(predictor, max_batch=8, workers=2,
                            batch_linger_s=0.002)
    try:
        server.warmup()
        predict_oracle = [
            [np.asarray(o).tolist()
             for o in server.run_reference(req)]
            for req in loadgen.demo_requests(N_REQUESTS)]
    finally:
        server.close()
    sess = _build_decode_session()
    streams = _oracle_streams(sess)
    with open(os.path.join(workdir, "oracle.json"), "w") as f:
        json.dump({"predict": predict_oracle, "streams": streams}, f)
    print("frontend_smoke[cold]: banked %d predict oracles + %d "
          "stream oracles, executables warmed"
          % (len(predict_oracle), len(streams)))
    return 0


def _assert_stream_parity(client, oracle):
    src, lens, prefix = _decode_load()
    ttfts = []

    def timed_full(*args, **kw):
        t0 = time.perf_counter()
        first = [None]

        def see(ev):
            if ev.get("event") == "tokens" and first[0] is None:
                first[0] = time.perf_counter() - t0

        rows = client.generate_full(*args, on_event=see, **kw)
        ttfts.append(first[0])
        return rows

    for i in range(N_STREAMS):
        rows = timed_full(src[i], src_len=lens[i])
        assert rows.tolist() == oracle["solo_%d" % i], (
            "wire stream %d diverged from the in-process oracle" % i)
    rows = timed_full(src[N_STREAMS], src_len=lens[N_STREAMS], n=2,
                      prefix_tokens=prefix)
    assert rows.tolist() == oracle["bestof"], \
        "wire best-of-2 fork diverged from the in-process oracle"
    rows = timed_full(src[N_STREAMS], src_len=lens[N_STREAMS], n=2,
                      prefix_tokens=prefix)
    assert rows.tolist() == oracle["prefix_hit"], \
        "wire prefix-cache-hit stream diverged from the oracle"
    return [t for t in ttfts if t is not None]


def _assert_disconnect_reclaims(fe, sess):
    from paddle_tpu.serving import ServingClient

    src, lens, _ = _decode_load()
    victim = ServingClient(fe.address)
    gen = victim.generate(src[0], src_len=SEQ)
    next(gen)
    victim.close()  # killed client: no cancel line, just a dead socket
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        if (not sess.active_slots and not sess.pending_requests
                and sess.free_slots == S and sess.pool_conserved):
            break
        time.sleep(0.02)
    assert sess.pool_conserved, (
        "conservation broken after client kill: free=%d allocated=%d "
        "P=%d" % (sess.free_pages, sess.pages_in_use, sess._P))
    assert sess.free_slots == S, (
        "slot leaked after client kill: %d of %d free"
        % (sess.free_slots, S))
    # the pool serves the very next admission
    probe = ServingClient(fe.address)
    rows = probe.generate_full(src[1], src_len=SEQ)
    assert rows.shape == (1, SEQ)
    probe.close()


def _assert_overload_typed(workdir):
    from paddle_tpu import flags
    from paddle_tpu.inference import NativeConfig, create_paddle_predictor
    from paddle_tpu.serving import (
        BatchingServer,
        DegradedError,
        ServingClient,
        ServingFrontend,
        loadgen,
    )

    predictor = create_paddle_predictor(NativeConfig(
        model_dir=os.path.join(workdir, "model"), use_tpu=False))
    server = BatchingServer(
        predictor, max_batch=8, workers=1, max_queue_depth=8,
        batch_linger_s=0.05,
        degradation=dict(brownout_at=0.5, shed_at=0.75,
                         recover_at=0.25, retry_after_s=0.1))
    rejects, okays = [], []
    with server, ServingFrontend(server=server) as fe:

        def one(req):
            cl = ServingClient(fe.address)
            try:
                cl.run(req)
                okays.append(1)
            except DegradedError as exc:
                assert exc.retry_after_s > 0, \
                    "wire DegradedError lost its retry-after hint"
                rejects.append(exc)
            finally:
                cl.close()

        threads = [threading.Thread(target=one, args=(req,))
                   for req in loadgen.demo_requests(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert rejects, "the overload flood never tripped shed"
        assert okays, "shed refused everything, including the drain"
        # the same flood with the classified budget armed rides the
        # retry-after hints through the drain: zero surfaced rejects
        flags.set_flag("dispatch_retries", 8)
        try:
            rejects2 = []
            cl = ServingClient(fe.address)
            for req in loadgen.demo_requests(8):
                try:
                    cl.run(req)
                except DegradedError as exc:
                    rejects2.append(exc)
            cl.close()
            assert not rejects2, (
                "classified retry failed to absorb shed rejects: %r"
                % rejects2[:2])
        finally:
            flags.set_flag("dispatch_retries", 0)
    return len(rejects)


def warm(workdir):
    from paddle_tpu.core import exec_cache
    from paddle_tpu.inference import NativeConfig, create_paddle_predictor
    from paddle_tpu.observability import telemetry
    from paddle_tpu.serving import (
        BatchingServer,
        ServingClient,
        ServingFrontend,
        loadgen,
    )

    telemetry.enable(True)
    with open(os.path.join(workdir, "oracle.json")) as f:
        oracle = json.load(f)
    model_dir = os.path.join(workdir, "model")
    predictor = create_paddle_predictor(
        NativeConfig(model_dir=model_dir, use_tpu=False))
    server = BatchingServer(predictor, max_batch=8, workers=2,
                            batch_linger_s=0.002)
    sess = _build_decode_session()
    fe = ServingFrontend(server=server, session=sess)
    try:
        server.warmup()
        # -- unary replay over real sockets (one client per caller) ---------
        latencies = []
        wall, ok, errors = loadgen.replay(
            lambda: ServingClient(fe.address),
            loadgen.demo_requests(N_REQUESTS), concurrency=CONCURRENCY,
            latencies=latencies)
        assert ok == N_REQUESTS and not errors, (
            "wire replay failed: ok=%d errors=%r" % (ok, errors[:3]))
        # bit-exact vs the COLD pass's in-process oracle
        checker = ServingClient(fe.address)
        for req, want in zip(loadgen.demo_requests(N_REQUESTS),
                             oracle["predict"]):
            got = checker.predict(req)
            for g, w in zip(got, want):
                assert np.array_equal(g, np.asarray(
                    w, dtype=g.dtype)), \
                    "wire predict diverged from the cold oracle"
        # -- streaming parity (incl. best-of-N fork + prefix hit) -----------
        ttfts = _assert_stream_parity(checker, oracle["streams"])
        assert ttfts, "no stream produced a first token"
        hits = sess.prefix_cache_stats()
        assert hits["hits"] >= 1, (
            "the repeated forced prefix never hit the cache: %r" % hits)
        # -- disconnect-safe reclamation ------------------------------------
        _assert_disconnect_reclaims(fe, sess)
        # -- THE gate: scrape over the wire, 0 fresh compiles ---------------
        scrape = checker.metrics()
        fresh = _scraped_fresh_compiles(scrape)
        st = exec_cache.stats()
        assert fresh == 0, (
            "warm frontend process paid %d fresh compile(s) under the "
            "mixed unary+streaming wire load (aot_hits=%d "
            "aot_misses=%d)" % (fresh, st["aot_hits"], st["aot_misses"]))
        assert st["aot_hits"] >= 1, (
            "warm process loaded no AOT images (re-traced): %r" % st)
        health = checker.health()
        assert health == {"server": "healthy", "decode": "healthy"}, \
            health
        # -- lock-witness verdict (conclint stage: FLAGS_lock_witness=1) ----
        from paddle_tpu.observability import lock_witness

        if lock_witness.ENABLED:
            wrep = lock_witness.report()
            assert not wrep["degraded"], \
                "lock witness report degraded (wedged internal lock)"
            assert wrep["registered"], \
                "witness armed but no framework lock registered through it"
            assert not wrep["cycles"], (
                "lock-order cycle(s) under the serving load: %r"
                % wrep["cycles"])
            assert not wrep["long_holds"], (
                "lock(s) held across a device dispatch: %r"
                % wrep["long_holds"])
            print("frontend_smoke[warm]: lock witness clean "
                  "(%d locks, %d order edges, 0 cycles, 0 long holds)"
                  % (len(wrep["registered"]), len(wrep["edges"])))
        checker.close()
    finally:
        fe.close()
        server.close()
    # -- overload: typed retriable rejects reach the wire client ------------
    shed_rejects = _assert_overload_typed(workdir)

    rec = loadgen.wire_capture(ok, wall, latencies, ttfts)
    from paddle_tpu import profiler

    rec["predicted_peak_bytes"] = \
        profiler.memory_stats()["predicted_peak_bytes"]
    st = exec_cache.stats()
    rec["fresh_compiles"] = fresh
    rec["compile_seconds_cold"] = round(st["compile_seconds_cold"], 3)
    rec["exec_cache"] = {
        "enabled": st["enabled"],
        "fresh_compiles": st["fresh_compiles"],
        "persistent_hits": st["persistent_hits"],
        "aot_hits": st["aot_hits"],
    }
    rec["shed_rejects"] = shed_rejects
    rec["platform"] = "cpu"
    print("frontend_smoke[warm]: %s" % json.dumps(rec))
    with open(os.path.join(workdir, "frontend.json"), "w") as f:
        json.dump({"models": {"frontend": rec}}, f)
    return 0


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else None
    workdir = sys.argv[2] if len(sys.argv) > 2 else None
    if mode not in ("cold", "warm") or not workdir:
        print("usage: frontend_smoke.py cold|warm <workdir>",
              file=sys.stderr)
        return 2
    if not os.environ.get("FLAGS_exec_cache_dir"):
        print("frontend_smoke: FLAGS_exec_cache_dir not set",
              file=sys.stderr)
        return 2
    return cold(workdir) if mode == "cold" else warm(workdir)


if __name__ == "__main__":
    sys.exit(main())
