"""Router-fleet smoke: prove the router tier survives a dead frontend.

    python tools/router_smoke.py $DIR    # writes $DIR/router.json

One leg, asserted hard (the CI ``route`` stage):

* **SIGKILL-a-frontend failover.** An *oracle* subprocess decodes the
  whole request set uninterrupted (and warms the one shared
  ``FLAGS_exec_cache_dir``). Then the parent runs a ``ServingRouter``
  and spawns TWO frontend subprocesses — each builds the SAME seeded
  model + paged ``SlotDecodeSession`` (greedy sampler: tokens are
  slot-assignment-independent, so concurrent routing stays
  oracle-comparable; SAMPLED bit-exactness across migration is pinned
  by ``tests/test_router.py``), arms a periodic
  ``DecodeSnapshotManager``, and registers as a ``RouterMember``.
  Phase 1 drives a warm set through the router including duplicate
  ``(src, prefix)`` pairs: prefix-affinity consistent hashing must pin
  each pair to ONE member so the second request HITS the prefix cache
  (``prefix_hit_rate`` surviving scale-out is the point of affinity
  routing). Phase 2 starts concurrent token streams and SIGKILLs one
  frontend mid-stream (asserted: death by SIGKILL with live slots on
  board). Every stream must still complete through the router —
  severed relays fail over, the victim's banked snapshot restores on
  the survivor, and the spliced streams are **bit-identical** to the
  oracle with **zero** lost or duplicated tokens. The survivor ends
  with **0 fresh compiles** (failover restore included — every
  executable from the warm cache).

The capture lands in ``$DIR/router.json`` and the stage gates it via
``tools/perf_diff.py --budgets benchmark/budgets.json --models
router`` (``fresh_compiles`` max 0 deterministic, ``lost_streams``
max 0 deterministic, ``migration_seconds`` banded).
"""

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

VOCAB, SEQ, D, S = 40, 16, 32, 4
CFG = dict(src_vocab_size=VOCAB, trg_vocab_size=VOCAB, n_layer=1,
           n_head=2, d_inner=64)
PREFIX_A = [5, 9, 7, 11, 6, 8]      # > page_size: a cacheable full page
PREFIX_B = [4, 6, 10, 12, 5]
# per-dispatch chaos slowdown inside the frontends: widens the
# mid-stream window so the SIGKILL provably lands on live slots
CHILD_CHAOS = "seed=5;slow@site=serve.dispatch,p=1.0,secs=0.1"


def _requests():
    """The one deterministic request set every process derives.
    Returns (warm_wave_a, warm_wave_b, streams) as lists of
    ``(oracle_index, src_row, src_len, prefix)``."""
    rng = np.random.RandomState(23)
    src = rng.randint(3, VOCAB, (10, SEQ)).astype("int64")
    warm_a = [
        (0, src[0], SEQ, PREFIX_A),
        (1, src[1], 5, None),
        (2, src[2], SEQ - 1, None),
        (3, src[3], SEQ, PREFIX_B),
    ]
    # wave B re-sends two (src, prefix) pairs VERBATIM: affinity must
    # route each to the member that already cached its prefix pages
    warm_b = [
        (4, src[0], SEQ, PREFIX_A),
        (5, src[3], SEQ, PREFIX_B),
    ]
    streams = [(6 + i, src[4 + i], SEQ, None) for i in range(6)]
    return warm_a, warm_b, streams


def _build_session():
    """The seeded model + session every child builds identically —
    GREEDY sampler (``sampler=None``): greedy tokens depend only on
    the model and the request, never on which slot/member a
    concurrently-routed request landed in."""
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer
    from paddle_tpu.serving.generation import SlotDecodeSession

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 13
    startup.random_seed = 13
    with fluid.program_guard(main, startup):
        transformer.build(dropout=0.0, label_smooth_eps=0.0,
                          max_length=SEQ, d_model=D, **CFG)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return SlotDecodeSession(
        exe, num_slots=S, max_length=SEQ, d_model=D, paged=True,
        page_size=4, steps=2, num_groups=2, prefix_cache_pages=8,
        **CFG)


def child_oracle(workdir):
    sess = _build_session()
    warm_a, warm_b, streams = _requests()
    specs = warm_a + warm_b + streams
    rids = {}
    for idx, src, length, prefix in specs:
        rids[sess.enqueue(src, length, prefix_tokens=prefix)] = idx
    done = {}
    while len(done) < len(specs):
        done.update(sess.pump())
    with open(os.path.join(workdir, "oracle.json"), "w") as f:
        json.dump({str(rids[r]): [int(t) for t in row]
                   for r, row in done.items()}, f)
    print("oracle: decoded %d requests" % len(specs))
    return 0


def child_frontend(workdir, name):
    from paddle_tpu.serving.frontend import ServingFrontend
    from paddle_tpu.serving.router import RouterMember
    from paddle_tpu.serving.snapshot import DecodeSnapshotManager

    sess = _build_session()
    mgr = DecodeSnapshotManager(
        sess, os.path.join(workdir, "snap_%s" % name), interval_steps=2)
    fe = ServingFrontend(session=sess, snapshot_manager=mgr)
    with open(os.path.join(workdir, "router.addr")) as f:
        host, port = f.read().strip().rsplit(":", 1)
    member = RouterMember(  # noqa: F841 - keeps the lease beating
        fe, (host, int(port)), worker_id="fe-%s" % name)
    ready = os.path.join(workdir, "%s.ready" % name)
    with open(ready + ".tmp", "w") as f:
        f.write("%s:%d" % (fe.address[0], fe.address[1]))
    os.rename(ready + ".tmp", ready)
    print("frontend %s: serving on %s:%d" % (name, fe.address[0],
                                             fe.address[1]))
    while True:  # parked until the parent SIGKILLs / SIGTERMs us
        time.sleep(0.2)


def _spawn_child(args, workdir, extra_env=None, wait=True):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(extra_env or {})
    cmd = [sys.executable, os.path.abspath(__file__), "--child"] + args
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if wait:
        return subprocess.run(cmd, env=env, timeout=600, cwd=cwd)
    return subprocess.Popen(cmd, env=env, cwd=cwd)


def _wait_file(path, timeout_s=120.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return f.read().strip()
        time.sleep(0.1)
    raise AssertionError("timed out waiting for %s" % path)


def _addr(text):
    host, port = text.rsplit(":", 1)
    return host, int(port)


def _scrape_fresh_compiles(text):
    m = re.search(r"^paddle_tpu_fresh_compiles_total (\d+)", text,
                  re.MULTILINE)
    return int(m.group(1)) if m else 0


def leg_fleet_failover(workdir):
    from paddle_tpu.serving.client import ServingClient
    from paddle_tpu.serving.router import ServingRouter

    cache = os.path.join(workdir, "cache")
    env = {"FLAGS_exec_cache_dir": cache}
    assert _spawn_child(["oracle", workdir], workdir, env).returncode == 0
    with open(os.path.join(workdir, "oracle.json")) as f:
        oracle = json.load(f)

    router = ServingRouter(lease_s=1.0, health_poll_s=0.25)
    procs = []
    try:
        with open(os.path.join(workdir, "router.addr"), "w") as f:
            f.write("%s:%d" % (router.address[0], router.port))
        child_env = dict(env, FLAGS_chaos_spec=CHILD_CHAOS)
        procs = [
            _spawn_child(["frontend", workdir, n], workdir, child_env,
                         wait=False)
            for n in ("a", "b")]
        fe_addr = {n: _addr(_wait_file(
            os.path.join(workdir, "%s.ready" % n))) for n in ("a", "b")}
        cl = ServingClient(router.address)
        deadline = time.monotonic() + 60.0
        while len(cl.stats()["frontends"]) < 2:
            assert time.monotonic() < deadline, "members never registered"
            time.sleep(0.1)

        # -- phase 1: warm set + prefix-affinity pinning ------------------
        warm_a, warm_b, streams = _requests()
        t0 = time.perf_counter()
        for wave in (warm_a, warm_b):
            rows, threads = {}, []
            for idx, src, length, prefix in wave:
                def run(idx=idx, src=src, length=length, prefix=prefix):
                    c = ServingClient(router.address)
                    try:
                        rows[idx] = c.generate_full(
                            src, length, prefix_tokens=prefix)[0]
                    finally:
                        c.close()
                threads.append(threading.Thread(target=run))
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120.0)
            for idx, _, _, _ in wave:
                assert idx in rows, "warm request %d never completed" % idx
                assert [int(t) for t in rows[idx]] == oracle[str(idx)], (
                    "warm request %d diverges from the oracle" % idx)
        warm_s = time.perf_counter() - t0
        lookups = hits = 0
        for n in ("a", "b"):
            c = ServingClient(fe_addr[n])
            try:
                p = c.stats()["decode"]["prefix"]
            finally:
                c.close()
            lookups += int(p["lookups"])
            hits += int(p["hits"])
        assert hits >= len(warm_b), (
            "affinity failed to pin the duplicate (src, prefix) pairs: "
            "%d hits across the fleet (lookups=%d), expected >= %d"
            % (hits, lookups, len(warm_b)))
        hit_rate = hits / float(lookups) if lookups else 0.0
        print("router: phase 1 OK — %d warm requests in %.2fs, prefix "
              "hits %d/%d (hit_rate %.2f) across 2 members"
              % (len(warm_a) + len(warm_b), warm_s, hits, lookups,
                 hit_rate))

        # -- phase 2: concurrent streams, SIGKILL one frontend ------------
        results, errors, first_tok = {}, {}, {}
        threads = []
        for idx, src, length, prefix in streams:
            first_tok[idx] = threading.Event()

            def run(idx=idx, src=src, length=length):
                c = ServingClient(router.address)

                def saw(ev):
                    if ev.get("event") == "tokens":
                        first_tok[idx].set()

                try:
                    results[idx] = c.generate_full(src, length,
                                                   on_event=saw)[0]
                except Exception as exc:  # noqa: BLE001 - asserted below
                    errors[idx] = exc
                finally:
                    c.close()
            threads.append(threading.Thread(target=run))
        for t in threads:
            t.start()
        for idx in first_tok:
            assert first_tok[idx].wait(timeout=120.0), (
                "stream %d produced no tokens" % idx)
        stats_cl = ServingClient(fe_addr["a"])
        try:
            live_on_victim = stats_cl.stats()["decode"]["active_slots"]
        finally:
            stats_cl.close()
        assert live_on_victim >= 1, (
            "victim had no live slots at the kill point — the failover "
            "would not exercise live-stream migration")
        procs[0].kill()
        assert procs[0].wait(timeout=30.0) == -signal.SIGKILL
        print("router: SIGKILLed frontend a with %d live slot(s) "
              "mid-stream" % live_on_victim)
        for t in threads:
            t.join(timeout=180.0)
            assert not t.is_alive(), "a stream never completed"
        assert not errors, (
            "streams failed after the kill: %s\n(router stats: %r)"
            % ({i: repr(e) for i, e in errors.items()}, router.stats()))
        for idx, _, _, _ in streams:
            assert idx in results, "stream %d never completed" % idx
            assert [int(t) for t in results[idx]] == oracle[str(idx)], (
                "stream %d diverges from the oracle after failover\n"
                "  oracle: %r\n  got:    %r"
                % (idx, oracle[str(idx)], [int(t) for t in results[idx]]))

        rstats = router.stats()
        assert rstats["failovers"] >= 1, "no failover ran"
        assert rstats["migrations"] >= 1, "no migration landed"
        assert rstats["lost_streams"] == 0, rstats
        assert rstats["migration_seconds"], "no migration was timed"
        migration_s = float(rstats["migration_seconds"][0])

        # the survivor — failover restore included — compiled NOTHING:
        # every executable came from the oracle-warmed persistent cache
        surv = ServingClient(fe_addr["b"])
        try:
            fresh = _scrape_fresh_compiles(surv.metrics())
            conserved = surv.stats()["decode"]["pool_conserved"]
        finally:
            surv.close()
        assert fresh == 0, (
            "survivor paid %d fresh compiles after the failover restore"
            % fresh)
        assert conserved, "survivor page pool leaked after migration"
        cl.close()
        print("router: failover leg OK — %d/%d streams bit-identical "
              "after SIGKILL (migration %.2fs), 0 lost, 0 fresh "
              "compiles on the survivor"
              % (len(results), len(streams), migration_s))
        return {"fresh_compiles": fresh, "migration_seconds": migration_s,
                "lost_streams": int(rstats["lost_streams"]),
                "prefix_hit_rate": hit_rate}
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=15.0)
                except subprocess.TimeoutExpired:
                    p.kill()
        router.close()


def main():
    if len(sys.argv) >= 2 and sys.argv[1] == "--child":
        if sys.argv[2] == "oracle":
            return child_oracle(sys.argv[3])
        return child_frontend(sys.argv[3], sys.argv[4])
    if len(sys.argv) != 2:
        sys.exit("usage: router_smoke.py OUTPUT_DIR")
    workdir = sys.argv[1]
    os.makedirs(workdir, exist_ok=True)
    numbers = leg_fleet_failover(workdir)
    capture = {"models": {"router": numbers}}
    path = os.path.join(workdir, "router.json")
    with open(path, "w") as f:
        json.dump(capture, f)
    print("router: capture -> %s (%s)" % (
        path, ", ".join("%s=%s" % (k, v)
                        for k, v in sorted(numbers.items()))))
    return 0


if __name__ == "__main__":
    sys.exit(main())
