"""Commit exactly one repo-relative path without touching the shared index.

The hardware-window watcher commits bank/artifact files while an
interactive session may be mid-commit in the same repo. Two races exist
with naive staging (ADVICE r4 + round-5 review):

* check-then-add: the watcher's ``git add`` lands between a human's
  check and commit, sweeping the watcher file into an unrelated commit;
* pathspec-commit-only fixes the watcher's own commit but still stages
  the file in the shared index, contaminating the human's NEXT commit.

Fix: build the commit in a private ``GIT_INDEX_FILE`` seeded from HEAD,
so the shared index is never written mid-flight. After the commit, the
shared index is synced (``git add`` of the now-committed path) so the
path does not appear as a staged deletion against the new HEAD; its
staged content then equals HEAD, so a concurrent commit sweeping it in
is a no-op by content.

Residual race (unavoidable with any concurrent use of one git repo): a
session that ran ``git add -A`` BEFORE this commit and commits AFTER it
snapshots the pre-bank blob and reverts the path. Nothing watcher-side
can prevent another actor committing stale staged content; interactive
sessions here stage explicit paths, never ``-A``.

Usage:  python tools/commit_path.py RELPATH MESSAGE
Exit 0 on commit or nothing-to-commit; 1 on hard git failure.
"""

import os
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _git(extra_env, *args):
    env = dict(os.environ)
    env.update(extra_env)
    return subprocess.run(["git", "-C", ROOT] + list(args),
                          capture_output=True, text=True, env=env)


def commit_path(relpath, message, retries=3):
    """Commit the working-tree state of ``relpath`` on top of HEAD.

    Plumbing-level with compare-and-swap: the commit object is built
    from a private index seeded from the OBSERVED head, and the branch
    ref only advances if it still points at that head
    (``update-ref <ref> <new> <old>``) — a concurrent interactive commit
    landing mid-flight makes the swap fail and the whole attempt retries
    against the new head, so neither side's tree can be silently
    reverted."""
    if os.path.isabs(relpath):
        return 1, "commit_path: need a repo-relative path, got %r" % relpath
    last = ""
    for _ in range(retries):
        head = _git({}, "rev-parse", "HEAD").stdout.strip()
        ref = _git({}, "symbolic-ref", "-q", "HEAD").stdout.strip() or "HEAD"
        fd, idx = tempfile.mkstemp(prefix="ptpu_index_")
        os.close(fd)
        os.remove(idx)  # git must create its own index file
        penv = {"GIT_INDEX_FILE": idx}
        try:
            r = _git(penv, "read-tree", head)
            if r.returncode:
                return 1, "read-tree failed: %s" % r.stderr.strip()
            r = _git(penv, "add", "--", relpath)
            if r.returncode:
                return 1, "add failed: %s" % r.stderr.strip()
            tree = _git(penv, "write-tree").stdout.strip()
            if not tree:
                return 1, "write-tree failed"
            base_tree = _git({}, "rev-parse",
                             head + "^{tree}").stdout.strip()
            if tree == base_tree:
                last = "nothing to commit (path matches HEAD)"
                break
            r = _git({}, "commit-tree", tree, "-p", head, "-m", message)
            if r.returncode:
                return 1, "commit-tree failed: %s" % r.stderr.strip()
            new = r.stdout.strip()
            r = _git({}, "update-ref", ref, new, head)
            if r.returncode:
                last = "head moved during commit; retrying"
                continue   # CAS failed: a concurrent commit landed
            last = "committed %s" % new[:12]
            break
        finally:
            if os.path.exists(idx):
                os.remove(idx)
    else:
        return 1, "gave up after %d CAS retries: %s" % (retries, last)
    # sync the shared index so the path isn't a staged deletion vs the
    # new HEAD; content now equals HEAD, so a concurrent commit sweeping
    # it in is a no-op by content. A failed sync (index.lock held) must
    # not pass silently: the stale staged blob would ride the next
    # interactive commit.
    import time
    for delay in (0, 2, 5, 10):
        if delay:
            time.sleep(delay)   # index.lock is typically held seconds
        r = _git({}, "add", "--", relpath)
        if r.returncode == 0:
            break
    if r.returncode:
        last += ("; WARNING: shared-index sync failed (%s) — run "
                 "`git add -- %s` before the next commit"
                 % (r.stderr.strip(), relpath))
        print(last, file=sys.stderr)
    return 0, last


def main():
    if len(sys.argv) != 3:
        print(__doc__, file=sys.stderr)
        return 2
    rc, out = commit_path(sys.argv[1], sys.argv[2])
    print(out)
    return rc


if __name__ == "__main__":
    sys.exit(main())
