"""Elastic smoke (CI ``elastic`` stage): kill a fleet the way production
does, then prove the reshape is exact — not approximate.

Two legs, all asserted from the parent (which hosts the coordinator):

1. **Churn leg** — two worker subprocesses register with a
   FleetCoordinator (min_workers=2) and train the same deterministic
   MLP over a per-worker ``ParallelExecutor`` whose planning mesh is
   sized to the fleet (fsdp=world — the repo's local-mesh stand-in for
   the global device mesh, same discipline as every multichip CPU
   test). The parent SIGKILLs worker 1 mid-epoch and asserts:

   * the coordinator **evicts it within the lease timeout** (measured
     from the kill) and bumps the membership generation;
   * the survivor reshards to world 1 and keeps training, and its
     world-1 loss segment is **bit-identical** to a fresh process
     restored from the same barrier checkpoint at world 1;
   * a **re-admitted** worker joins at the next generation, restores
     the chief's barrier serial, and both workers' world-2 segments are
     bit-identical to each other AND to a fresh restore at world 2;
   * the survivor's metrics scrape carries the fleet gauges
     (``paddle_tpu_fleet_generation``/``_size``) and
     ``paddle_tpu_reshard_seconds`` observations; the coordinator side
     counts the eviction; the final checkpoint passes
     ``tools/ckpt_inspect.py --verify`` and records the mesh.

2. **Coordinator-restart leg** — the coordinator is closed mid-run and
   restarted from its snapshot on the same port. The worker's retrying
   heartbeats (``paddle_tpu_retries_total{origin=FleetClient._call}``)
   ride out the restart, membership recovers at the SAME generation (no
   spurious reshape), and the run finishes every step.

Usage: python tools/elastic_smoke.py          # parent, runs both legs
       python tools/elastic_smoke.py child ...  # worker (internal)
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the churn leg's step budget must outlast: kill (~5s in) + lease expiry
# (2s) + the re-admitted worker's cold jax start (~5-10s), all while the
# survivor keeps stepping at ~sleep-speed — generous on purpose, the leg
# asserts segments, not totals
STEPS = 160
STEP_SLEEP = 0.15
LEASE_S = 2.0


# ---------------------------------------------------------------------------
# child: the elastic training worker
# ---------------------------------------------------------------------------


def _feed_for(step):
    import numpy as np

    r = np.random.RandomState(5000 + step)
    return {"x": r.rand(8, 16).astype("float32"),
            "y": r.rand(8, 1).astype("float32")}


def _make_build_fn(holder):
    """build_fn(world, rank): a fsdp=world planning-mesh PE over the
    first ``world`` local CPU devices. The first fc weight (16x64,
    numel 1024) clears the transpiler's shard threshold, so world>=2
    checkpoints actually exercise the shard-file dialect. The program is
    built ONCE and reused across rebuilds (unique-name discipline)."""
    import paddle_tpu as fluid
    from paddle_tpu.parallel_executor import BuildStrategy, ParallelExecutor

    def build_fn(world, rank):
        if "main" not in holder:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("x", [16], stop_gradient=False)
                y = fluid.layers.data("y", [1])
                h = fluid.layers.fc(x, 64, act="relu")
                h = fluid.layers.dropout(h, 0.3)  # RNG-dependent on purpose
                pred = fluid.layers.fc(h, 1)
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                fluid.optimizer.SGD(0.05).minimize(loss)
            main.random_seed = 23
            startup.random_seed = 23
            holder.update(main=main, startup=startup, loss=loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(holder["startup"])
        bs = BuildStrategy()
        bs.reduce_strategy = BuildStrategy.ReduceStrategy.Reduce
        pe = ParallelExecutor(
            loss_name=holder["loss"].name, main_program=holder["main"],
            build_strategy=bs, use_tpu=False, num_devices=world)
        return pe, holder["main"]

    return build_fn


def _child_elastic(args):
    import numpy as np

    from paddle_tpu.elastic.worker import ElasticTrainSession

    holder = {}
    sess = ElasticTrainSession(
        args.coordinator, args.ckpt_dir, _make_build_fn(holder),
        worker_id=args.worker_id, heartbeat_interval_s=0.3)
    losses = []
    while sess.step < args.steps:
        out = sess.run(feed=_feed_for(sess.step),
                       fetch_list=[holder["loss"]])
        # sess.step was bumped by run(): this loss belongs to step-1
        losses.append([sess.step - 1,
                       float(np.asarray(out[0]).reshape(-1)[0])])
        time.sleep(args.sleep)
    generation = sess.generation
    # leave=False: near-simultaneous finishers must not reshape each
    # other's tails — the fleet drains by lease expiry after exit
    sess.close(leave=False)
    with open(args.out, "w") as f:
        json.dump({
            "worker_id": sess.worker_id,
            "losses": losses,
            "reshapes": sess.reshapes,
            "generation": generation,
        }, f)


def _child_fixed(args):
    """Fresh-restore reference: restore ``--serial`` from a COPY of the
    checkpoint dir at a FIXED world size (no coordinator), run
    ``--steps`` more steps — the trajectory the post-reshape fleet must
    have matched bit-for-bit."""
    import numpy as np

    from paddle_tpu.elastic.reshard import ShardedCheckpointManager
    from paddle_tpu.elastic.worker import session_executor
    from paddle_tpu.resilience.session import TrainSession

    holder = {}
    pe, main = _make_build_fn(holder)(args.world, 0)
    exe = session_executor(pe)
    mgr = ShardedCheckpointManager(
        args.ckpt_dir, plan=pe.sharding_plan(), executor=exe,
        main_program=main)
    manifest = mgr.restore(serial=args.serial)
    assert manifest is not None, (
        "reference restore failed for serial %s" % args.serial)
    sess = TrainSession(exe, args.ckpt_dir, main_program=main,
                        manager=mgr, auto_resume=False,
                        interval_steps=0, interval_secs=0)
    sess.step = int(manifest["step"])
    losses = []
    for _ in range(args.steps):
        out = sess.run(feed=_feed_for(sess.step),
                       fetch_list=[holder["loss"]])
        losses.append([sess.step - 1,
                       float(np.asarray(out[0]).reshape(-1)[0])])
    sess.close(save=False)
    with open(args.out, "w") as f:
        json.dump({"losses": losses}, f)


# ---------------------------------------------------------------------------
# parent: the legs
# ---------------------------------------------------------------------------


def _env(**extra):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        FLAGS_checkpoint_max_to_keep="100",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _spawn(mode, out, extra_args, env):
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "child",
         "--mode", mode, "--out", out] + extra_args, env=env)


def _wait_member_step(co, worker_id, step, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        m = co.status()["members"].get(worker_id)
        if m and (m["step"] or 0) >= step:
            return
        time.sleep(0.1)
    raise AssertionError("worker %s never reached step %d: %s"
                         % (worker_id, step, co.status()))


def _wait_world(co, world, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if co.status()["world"] == world:
            return time.time()
        time.sleep(0.05)
    raise AssertionError("fleet never reached world=%d: %s"
                         % (world, co.status()))


def _segment(losses, lo, hi):
    """losses: [[step, value]...] -> values for lo <= step < hi."""
    return [v for s, v in losses if lo <= s < (hi if hi is not None
                                               else 1 << 60)]


def _run_fixed_reference(tmp, tag, ckpt_src, world, serial, steps):
    copy = os.path.join(tmp, "ref_ckpt_%s" % tag)
    shutil.copytree(ckpt_src, copy)
    out = os.path.join(tmp, "ref_%s.json" % tag)
    proc = _spawn("fixed", out,
                  ["--ckpt-dir", copy, "--world", str(world),
                   "--serial", str(serial), "--steps", str(steps),
                   "--sleep", "0"], _env())
    assert proc.wait(timeout=300) == 0, "fixed reference %s failed" % tag
    with open(out) as f:
        return [v for _s, v in json.load(f)["losses"]]


def _churn_leg(tmp):
    from paddle_tpu.elastic.coordinator import FleetCoordinator
    from paddle_tpu.observability.metrics_registry import REGISTRY

    co = FleetCoordinator(lease_s=LEASE_S, min_workers=2)
    host, port = co.serve()
    addr = "%s:%d" % (host, port)
    ckpt = os.path.join(tmp, "ckpt")
    prom = os.path.join(tmp, "w0.prom")

    out0 = os.path.join(tmp, "w0.json")
    out1 = os.path.join(tmp, "w1.json")
    outr = os.path.join(tmp, "w1b.json")
    common = ["--coordinator", addr, "--ckpt-dir", ckpt,
              "--steps", str(STEPS), "--sleep", str(STEP_SLEEP)]
    w0 = _spawn("elastic", out0, common + ["--worker-id", "w0"],
                _env(FLAGS_metrics_path=prom))
    w1 = _spawn("elastic", out1, common + ["--worker-id", "w1"], _env())

    # both admitted, worker 1 demonstrably training -> SIGKILL it
    _wait_member_step(co, "w1", 4, timeout=120)
    os.kill(w1.pid, signal.SIGKILL)
    t_kill = time.time()
    assert w1.wait(timeout=30) == -signal.SIGKILL

    # eviction within the lease timeout (+ watcher period slack)
    t_evict = _wait_world(co, 1, timeout=LEASE_S * 4)
    detect_s = t_evict - t_kill
    assert detect_s <= LEASE_S + 1.0, (
        "eviction took %.1fs (lease %.1fs)" % (detect_s, LEASE_S))
    gen_evict = co.status()["generation"]

    # the survivor reshards to world 1 and KEEPS TRAINING
    surv_step = (co.status()["members"].get("w0") or {}).get("step") or 0
    _wait_member_step(co, "w0", surv_step + 3, timeout=120)

    # re-admission: a fresh worker joins at the next generation
    w1b = _spawn("elastic", outr, common + ["--worker-id", "w1b"], _env())
    _wait_world(co, 2, timeout=60)
    assert co.status()["generation"] > gen_evict

    assert w0.wait(timeout=300) == 0, "survivor failed"
    assert w1b.wait(timeout=300) == 0, "re-admitted worker failed"

    with open(out0) as f:
        r0 = json.load(f)
    with open(outr) as f:
        r1b = json.load(f)

    # reshape ledger: cold start at 2, eviction to 1, rejoin to 2
    worlds = [r["world"] for r in r0["reshapes"]]
    assert worlds == [2, 1, 2], r0["reshapes"]
    assert [r["generation"] for r in r0["reshapes"]] == sorted(
        r["generation"] for r in r0["reshapes"])
    evict_re, rejoin_re = r0["reshapes"][1], r0["reshapes"][2]
    assert evict_re["serial"] == evict_re["step"]

    # --- bit-tracked loss: world-1 segment vs a fresh restore at world 1
    seg1 = _segment(r0["losses"], evict_re["step"], rejoin_re["step"])
    assert len(seg1) >= 2, "world-1 segment too short: %s" % seg1
    ref1 = _run_fixed_reference(tmp, "w1", ckpt, 1, evict_re["serial"],
                                len(seg1))
    assert seg1 == ref1, (
        "world-1 segment diverged from fresh restore:\nfleet: %s\n"
        "fresh: %s" % (seg1, ref1))

    # --- world-2 segment vs fresh restore at world 2 AND vs the rejoiner
    seg2 = _segment(r0["losses"], rejoin_re["step"], None)
    assert len(seg2) >= 2, "world-2 segment too short"
    ref2 = _run_fixed_reference(tmp, "w2", ckpt, 2, rejoin_re["serial"],
                                len(seg2))
    assert seg2 == ref2, (
        "world-2 segment diverged from fresh restore:\nfleet: %s\n"
        "fresh: %s" % (seg2, ref2))
    seg2b = _segment(r1b["losses"], rejoin_re["step"], None)
    n = min(len(seg2), len(seg2b))
    assert n >= 2 and seg2[:n] == seg2b[:n], (
        "survivor and re-admitted worker diverged:\nw0:  %s\nw1b: %s"
        % (seg2[:n], seg2b[:n]))
    assert r1b["reshapes"][0]["serial"] == rejoin_re["serial"], (
        "rejoiner restored a different serial than the chief published")

    # --- fleet metrics: worker scrape + coordinator-side counters
    with open(prom) as f:
        scrape = f.read()
    gen_lines = [line for line in scrape.splitlines()
                 if line.startswith("paddle_tpu_fleet_generation")]
    assert gen_lines and float(gen_lines[0].rsplit(None, 1)[-1]) >= 4, (
        "worker scrape must carry the generation gauge: %r" % gen_lines)
    assert any(line.startswith("paddle_tpu_fleet_size")
               for line in scrape.splitlines())
    rs = [line for line in scrape.splitlines()
          if line.startswith("paddle_tpu_reshard_seconds_count")]
    assert rs and float(rs[0].rsplit(None, 1)[-1]) >= 3, (
        "reshard timings missing from the worker scrape: %r" % rs)
    parent_scrape = REGISTRY.to_prometheus()
    ev = [line for line in parent_scrape.splitlines()
          if line.startswith("paddle_tpu_fleet_evictions_total")]
    assert ev and float(ev[0].rsplit(None, 1)[-1]) >= 1

    # --- the final checkpoint verifies offline and names its mesh
    serials = sorted(int(d.split("_")[1]) for d in os.listdir(ckpt)
                     if d.startswith("checkpoint_")
                     and d.split("_")[1].isdigit())
    final_dir = os.path.join(ckpt, "checkpoint_%d" % serials[-1])
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ckpt_inspect.py"),
         final_dir, "--verify"], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "mesh:" in proc.stdout, proc.stdout

    co.close()
    print("elastic churn leg OK: evicted in %.1fs (lease %.1fs), "
          "reshapes %s, world-1 + world-2 segments bit-identical to "
          "fresh restores (%d + %d steps), rejoiner matched serial %d"
          % (detect_s, LEASE_S, worlds, len(seg1), len(seg2),
             rejoin_re["serial"]))


def _restart_leg(tmp):
    from paddle_tpu.elastic.coordinator import FleetCoordinator

    snap = os.path.join(tmp, "fleet.json")
    co = FleetCoordinator(lease_s=LEASE_S, min_workers=1,
                          snapshot_path=snap, snapshot_interval_s=0.0)
    host, port = co.serve()
    addr = "%s:%d" % (host, port)
    out = os.path.join(tmp, "cw.json")
    prom = os.path.join(tmp, "cw.prom")
    w = _spawn("elastic", out,
               ["--coordinator", addr, "--ckpt-dir",
                os.path.join(tmp, "ckpt_restart"), "--steps", "30",
                "--sleep", "0.08", "--worker-id", "cw"],
               _env(FLAGS_metrics_path=prom))
    _wait_member_step(co, "cw", 5, timeout=120)
    gen_before = co.status()["generation"]

    # kill -restart the coordinator: workers must ride it out
    co.close()
    time.sleep(0.6)  # downtime window: heartbeats fail and retry
    co2 = FleetCoordinator(lease_s=LEASE_S, min_workers=1,
                           snapshot_path=snap, snapshot_interval_s=0.0)
    co2.serve(host=host, port=port)
    assert co2.status()["generation"] == gen_before
    assert "cw" in co2.status()["members"]

    assert w.wait(timeout=300) == 0, "worker did not survive the restart"
    with open(out) as f:
        res = json.load(f)
    # ONE build (cold start), zero reshapes: recovery at the same
    # generation must not look like churn
    assert len(res["reshapes"]) == 1, res["reshapes"]
    assert res["generation"] == gen_before
    assert len(res["losses"]) == 30
    with open(prom) as f:
        scrape = f.read()
    retr = [line for line in scrape.splitlines()
            if line.startswith("paddle_tpu_retries_total")
            and "FleetClient" in line]
    assert retr and sum(float(line.rsplit(None, 1)[-1])
                        for line in retr) >= 1, (
        "the restart window must show classified FleetClient retries: %r"
        % retr)
    co2.close()
    print("elastic restart leg OK: coordinator restarted from snapshot "
          "at generation %d, %d retries absorbed, zero spurious reshapes"
          % (gen_before, int(sum(float(line.rsplit(None, 1)[-1])
                                 for line in retr))))


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "child":
        p = argparse.ArgumentParser()
        p.add_argument("cmd")
        p.add_argument("--mode", choices=["elastic", "fixed"],
                       required=True)
        p.add_argument("--coordinator")
        p.add_argument("--ckpt-dir", required=True)
        p.add_argument("--steps", type=int, required=True)
        p.add_argument("--out", required=True)
        p.add_argument("--worker-id")
        p.add_argument("--world", type=int, default=1)
        p.add_argument("--serial", type=int, default=None)
        p.add_argument("--sleep", type=float, default=0.05)
        args = p.parse_args()
        if args.mode == "elastic":
            _child_elastic(args)
        else:
            _child_fixed(args)
        return
    import tempfile

    with tempfile.TemporaryDirectory(prefix="elastic_") as tmp:
        _churn_leg(tmp)
        _restart_leg(tmp)
    print("elastic smoke OK")


if __name__ == "__main__":
    main()
