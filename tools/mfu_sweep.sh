#!/usr/bin/env bash
# MFU push sweep (VERDICT r2 item 2): one command that captures every
# prepared experiment on the real chip the moment the tunnel is up, so a
# short hardware window is enough. Each line of output is one bench JSON
# capture tagged with the configuration that produced it.
#
#   tools/mfu_sweep.sh              # on a TPU host
#   BENCH_PLATFORM=cpu tools/mfu_sweep.sh   # CPU smoke of the harness
#
# Experiments (ResNet-50 unless stated):
#   baseline          bf16 AMP, in-graph data (the round-2 configuration)
#   fp32              AMP off (isolates the bf16 win)
#   nhwc              FLAGS_conv_nhwc=1 layout experiment
#   bs64 / bs256      batch sweep via BENCH_BS override
#   multistep         K-step lax.scan executable (dispatch amortization)
#   hostdata+db       PyReader host feeds, double buffer ON (h2d overlap)
#   hostdata-nodb     same with the prefetch off (the control)
#   hostdata-u8       uint8 pixels + on-device normalize (4x smaller h2d)
#   transformer       the second north-star model
#   transformer-*     fp32 / bs128 / reference-attention variants
#   kernels           Pallas-vs-XLA microbench (tools/kernel_bench.py)
set -uo pipefail
cd "$(dirname "$0")/.."

run() {
  local tag="$1"; shift
  echo "== $tag =="
  local out
  # per-experiment bound: one wedged worker (the axon tunnel can hang
  # in-process jax) must cost ONE capture, not every experiment after it
  out=$(env "$@" timeout -k 15 "${SWEEP_EXP_TIMEOUT:-1800}" \
    python bench.py --worker 2>"/tmp/mfu_sweep_$tag.err" | tail -1)
  if [ -n "$out" ]; then
    printf '{"experiment": "%s", "capture": %s}\n' "$tag" "$out"
  else
    # a lost capture must be visible IN the sweep record, not silently
    # absent (the hardware window may be gone before anyone rereads logs)
    printf '{"experiment": "%s", "capture": {"error": "worker produced no output; see /tmp/mfu_sweep_%s.err"}}\n' \
      "$tag" "$tag"
    tail -3 "/tmp/mfu_sweep_$tag.err" >&2
  fi
}

# SWEEP_QUICK=1 runs a 3-experiment subset (harness smoke on CPU; the
# full list is sized for the TPU, where each capture is seconds).
if [ "${SWEEP_QUICK:-0}" = "1" ]; then
  run transformer      BENCH_MODEL=transformer
  run transformer-fp32 BENCH_MODEL=transformer BENCH_AMP=0
  run nhwc-quick       BENCH_MODEL=transformer FLAGS_conv_nhwc=1
else
  run baseline      BENCH_MODEL=resnet50
  run fp32          BENCH_MODEL=resnet50 BENCH_AMP=0
  run nhwc          BENCH_MODEL=resnet50 FLAGS_conv_nhwc=1
  run bs64          BENCH_MODEL=resnet50 BENCH_BS=64
  run bs256         BENCH_MODEL=resnet50 BENCH_BS=256
  run multistep     BENCH_MODEL=resnet50 BENCH_MULTISTEP=1
  run hostdata+db   BENCH_MODEL=resnet50 BENCH_DATA=host BENCH_DOUBLE_BUFFER=1
  run hostdata-nodb BENCH_MODEL=resnet50 BENCH_DATA=host BENCH_DOUBLE_BUFFER=0
  run hostdata-u8   BENCH_MODEL=resnet50 BENCH_DATA=host BENCH_UINT8=1
  run transformer   BENCH_MODEL=transformer
  run transformer-fp32 BENCH_MODEL=transformer BENCH_AMP=0
  run transformer-bs128 BENCH_MODEL=transformer BENCH_BS=128
  run transformer-refattn BENCH_MODEL=transformer FLAGS_attention_impl=reference
  # long-context leg: seq 1024 (16x the default attention area) — the
  # regime the flash fwd+bwd kernels exist for; reference attention at
  # this size materializes 4 GiB of [B,H,T,S] scores per direction
  run transformer-seq1024 BENCH_MODEL=transformer BENCH_SEQ=1024 BENCH_BS=16
fi

echo "== kernels =="
python tools/kernel_bench.py ${BENCH_PLATFORM:+--quick}

echo "sweep done"
