#!/usr/bin/env bash
# Hardware-window watcher: poll the axon tunnel until it comes back, then
# capture everything the round still owes, in priority order (the tunnel
# wedges unpredictably — round 2 lost its bench capture to exactly that,
# and round 3's first window died mid-Transformer). Captures land in
# $HW_LOG (default /tmp/hw_window) as one JSON file per experiment.
#
#   tools/hw_window.sh            # poll forever until a window opens
#   HW_ONESHOT=1 tools/hw_window.sh   # single probe + capture (no loop)
set -u
cd "$(dirname "$0")/.."
LOG=${HW_LOG:-/tmp/hw_window}
mkdir -p "$LOG"

probe() {
  # the wedged plugin can ignore SIGTERM mid-enumeration: -k SIGKILLs
  timeout -k 10 90 python - >/dev/null 2>&1 <<'EOF'
import jax
assert jax.devices()[0].platform != "cpu"
EOF
}

capture() {
  echo "tunnel up $(date -u +%FT%TZ); capturing" | tee -a "$LOG/log"
  # Priority for THIS window reflects what the 07-31 morning window
  # already banked (BENCH_NOTES.md "second window"): the Transformer
  # driver number, the full ResNet sweep, host-data A/B, fp32 A/B and
  # the xprof breakdown are all captured. Still owed, in order:
  # 1. Pallas-vs-XLA kernel verdicts — missed in THREE windows now
  #    (crash, then sweep-tail backend loss); flag defaults depend on it
  timeout -k 30 2400 python tools/kernel_bench.py \
    >"$LOG/kernels.jsonl" 2>"$LOG/kernels.err"
  # 2. Transformer re-capture with the fixed lse layout + factored loss
  #    (the morning number predates both; direct A/B vs 102,970 tok/s)
  BENCH_MODELS=transformer BENCH_WORKER_TIMEOUT=2700 \
    python bench.py >"$LOG/transformer.json" 2>"$LOG/transformer.err"
  # 3. the reference-attention control the sweep's timeout lost
  SWEEP_QUICK=1 SWEEP_EXP_TIMEOUT=2400 timeout -k 30 7500 \
    tools/mfu_sweep.sh >"$LOG/sweep_quick.jsonl" 2>"$LOG/sweep_quick.err"
  # 4. ResNet sanity re-pin (cheap; confirms chip-side consistency)
  BENCH_MODELS=resnet50 BENCH_WORKER_TIMEOUT=2700 \
    python bench.py >"$LOG/resnet.json" 2>"$LOG/resnet.err"
  echo "capture done $(date -u +%FT%TZ)" | tee -a "$LOG/log"
}

if [ "${HW_ONESHOT:-0}" = "1" ]; then
  probe && capture
  exit 0
fi
while true; do
  if probe; then
    capture
    break
  fi
  echo "tunnel down $(date -u +%FT%TZ)" >>"$LOG/log"
  sleep 300
done
