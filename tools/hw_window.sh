#!/usr/bin/env bash
# Hardware-window watcher: poll the axon tunnel until it comes back, then
# capture everything the round still owes, in priority order (the tunnel
# wedges unpredictably — round 2 lost its bench capture to exactly that,
# and round 3's first window died mid-Transformer). Captures land in
# $HW_LOG (default /tmp/hw_window) as one JSON file per experiment.
#
#   tools/hw_window.sh            # poll forever until a window opens
#   HW_ONESHOT=1 tools/hw_window.sh   # single probe + capture (no loop)
set -u
cd "$(dirname "$0")/.."
LOG=${HW_LOG:-/tmp/hw_window}
mkdir -p "$LOG"

probe() {
  # the wedged plugin can ignore SIGTERM mid-enumeration: -k SIGKILLs
  timeout -k 10 90 python - >/dev/null 2>&1 <<'EOF'
import jax
assert jax.devices()[0].platform != "cpu"
EOF
}

capture() {
  echo "tunnel up $(date -u +%FT%TZ); capturing" | tee -a "$LOG/log"
  # 1. the missing north-star number: Transformer train on the chip
  BENCH_MODELS=transformer BENCH_WORKER_TIMEOUT=2700 \
    python bench.py >"$LOG/transformer.json" 2>"$LOG/transformer.err"
  # if the Pallas-flash compile is what hangs this rig, the reference
  # attention impl is the fallback lever (FLAGS_attention_impl)
  if ! grep -q '"platform": "tpu"' "$LOG/transformer.json"; then
    FLAGS_attention_impl=reference BENCH_MODELS=transformer \
      BENCH_WORKER_TIMEOUT=2700 python bench.py \
      >"$LOG/transformer_ref_attn.json" 2>"$LOG/transformer_ref_attn.err"
  fi
  # last resort: a compile-light 2-layer capture (valid MFU, smaller
  # model) beats no Transformer chip number at all
  if ! grep -q '"platform": "tpu"' "$LOG/transformer.json" \
      "$LOG/transformer_ref_attn.json" 2>/dev/null; then
    BENCH_LAYERS=2 BENCH_MODELS=transformer BENCH_WORKER_TIMEOUT=2700 \
      python bench.py >"$LOG/transformer_2l.json" 2>"$LOG/transformer_2l.err"
  fi
  # 2. Pallas-vs-XLA kernel verdicts (flag defaults depend on these)
  timeout -k 30 2400 python tools/kernel_bench.py \
    >"$LOG/kernels.jsonl" 2>"$LOG/kernels.err"
  # 3. per-HLO-op xprof breakdown of the ResNet step (MFU push evidence)
  timeout -k 30 2400 python tools/step_breakdown.py --model resnet50 \
    --xprof >"$LOG/breakdown.jsonl" 2>"$LOG/breakdown.err"
  # 4. the prepared MFU experiments
  timeout -k 30 7200 tools/mfu_sweep.sh \
    >"$LOG/sweep.jsonl" 2>"$LOG/sweep.err"
  echo "capture done $(date -u +%FT%TZ)" | tee -a "$LOG/log"
}

if [ "${HW_ONESHOT:-0}" = "1" ]; then
  probe && capture
  exit 0
fi
while true; do
  if probe; then
    capture
    break
  fi
  echo "tunnel down $(date -u +%FT%TZ)" >>"$LOG/log"
  sleep 300
done
