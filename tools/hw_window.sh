#!/usr/bin/env bash
# Hardware-window watcher: poll the axon tunnel until it comes back, then
# capture everything the round still owes, in priority order (the tunnel
# wedges unpredictably — round 2 lost its bench capture to exactly that,
# and round 3's first window died mid-Transformer). Captures land in
# $HW_LOG (default /tmp/hw_window) as one JSON file per experiment, and
# every successful bench capture is immediately banked into the
# driver-format BENCH_r05_manual.json + committed (tools/bank_capture.py)
# so the round-end snapshot can never be staler than the newest window
# (VERDICT r3 Weak #5).
#
# Legs are idempotent and individually tracked: a leg that already banked
# (its tag in BENCH_r05_manual.json / its artifact committed non-empty)
# is skipped, and the watcher keeps polling until EVERY leg has banked —
# a window that dies mid-capture costs the remaining legs only until the
# next window, not the round.
#
#   tools/hw_window.sh            # poll + capture until all legs banked
#   HW_ONESHOT=1 tools/hw_window.sh   # single probe + one capture pass
set -u
cd "$(dirname "$0")/.."
LOG=${HW_LOG:-/tmp/hw_window}
mkdir -p "$LOG"

# fail fast on an override bank_capture.py would reject after a 45-min
# capture: the bank file must be a bare filename at the repo root
case "${BENCH_BANK:-}" in
  */*) echo "hw_window: BENCH_BANK must be a bare filename, got '$BENCH_BANK'" >&2
       exit 2 ;;
esac

probe() {
  # the wedged plugin can ignore SIGTERM mid-enumeration: -k SIGKILLs
  timeout -k 10 90 python - >/dev/null 2>&1 <<'EOF'
import jax
assert jax.devices()[0].platform != "cpu"
EOF
}

banked() {  # has experiment tag $1 already banked?
  python - "$1" <<'EOF'
import json, os, sys
name = os.environ.get("BENCH_BANK", "BENCH_r05_manual.json")
try:
    bank = json.load(open(name))
    sys.exit(0 if sys.argv[1] in bank.get("experiments", {}) else 1)
except Exception:
    sys.exit(1)
EOF
}

# bench <tag> [ENV=VAL ...] — one bench.py capture, banked on success.
# Retirement: a leg is retired after 3 attempts that failed WITH the
# tunnel still alive (a deterministic failure like an OOM config). A
# failure where the tunnel is gone afterwards is tunnel loss, burns no
# attempt, and aborts the pass (return 2) — the next window retries.
bench() {
  local tag="$1"; shift
  banked "$tag" && return 0
  local att_file="$LOG/$tag.attempts"
  local attempts=$(cat "$att_file" 2>/dev/null || echo 0)
  if [ "$attempts" -ge 3 ]; then return 0; fi
  echo "== $tag (prior failed attempts: $attempts) $(date -u +%FT%TZ)" \
    | tee -a "$LOG/log"
  env "$@" BENCH_WORKER_TIMEOUT="${HW_BENCH_TIMEOUT:-2700}" \
    python bench.py >"$LOG/$tag.json" 2>"$LOG/$tag.err"
  python tools/bank_capture.py "$LOG/$tag.json" "$tag" \
    >>"$LOG/log" 2>&1
  local bank_rc=$?
  tail -2 "$LOG/log"
  if [ $bank_rc -eq 0 ]; then return 0; fi
  if probe; then
    echo $((attempts + 1)) >"$att_file"
    echo "$tag: failed with tunnel alive (attempt $((attempts + 1))/3)" \
      | tee -a "$LOG/log"
    return 1
  fi
  echo "$tag: tunnel lost mid-leg; no attempt burned" | tee -a "$LOG/log"
  return 2
}

# artifact <dest> <cmd...> — run a tool; keep non-empty output from a
# clean (rc=0) run only, so a timeout/crash can never overwrite a good
# artifact with a truncated one. "Done" means a non-empty $dest exists in
# the WORKING TREE (same predicate all_done uses): the commit here is
# best-effort — if the index is busy, the file still counts as captured
# and rides the next interactive/driver commit instead of re-running a
# 30-min tool. Retirement mirrors bench(): 3 tunnel-alive failures.
artifact() {
  local dest="$1"; shift
  local pend="$LOG/$(basename "$dest").commit_pending"
  if [ -s "$dest" ]; then
    # captured earlier but the commit failed: retry JUST the commit
    # instead of re-running a 30-min tool
    if [ -f "$pend" ]; then
      python tools/commit_path.py "$dest" \
        "Hardware artifact: $(basename "$dest") (window capture)" \
        >>"$LOG/log" 2>&1 && rm -f "$pend"
    fi
    return 0
  fi
  local att_file="$LOG/$(basename "$dest").attempts"
  local attempts=$(cat "$att_file" 2>/dev/null || echo 0)
  if [ "$attempts" -ge 3 ]; then return 0; fi
  echo "== artifact $dest (prior failed attempts: $attempts) $(date -u +%FT%TZ)" \
    | tee -a "$LOG/log"
  local tmp="$LOG/$(basename "$dest")"
  "$@" >"$tmp" 2>"$tmp.err"
  local rc=$?
  if [ $rc -ne 0 ] || [ ! -s "$tmp" ]; then
    echo "artifact $dest: rc=$rc, size=$(wc -c <"$tmp" 2>/dev/null || echo 0); not keeping" \
      | tee -a "$LOG/log"
    if probe; then
      echo $((attempts + 1)) >"$att_file"
      return 1
    fi
    echo "artifact $dest: tunnel lost mid-leg; no attempt burned" \
      | tee -a "$LOG/log"
    return 2
  fi
  mkdir -p "$(dirname "$dest")"
  cp "$tmp" "$dest"
  # private-index commit (tools/commit_path.py): cannot mix with a
  # concurrent interactive commit in either direction; a failed commit
  # leaves a pending marker so the next pass retries commit-only
  if ! python tools/commit_path.py "$dest" \
      "Hardware artifact: $(basename "$dest") (window capture)" \
      >>"$LOG/log" 2>&1; then
    touch "$pend"
  fi
}

capture() {
  echo "tunnel up $(date -u +%FT%TZ); capturing" | tee -a "$LOG/log"
  # Round-5 priority (VERDICT r4 Next #2 standing order; queue order from ROUND4.md). The round-3 banked
  # Transformer number predates the lse-layout fix + factored CE + flash
  # backward (+19% CPU proxy); re-capture is the round's top deliverable.
  # A leg returning 2 means the tunnel died mid-leg: abort the pass (the
  # remaining legs would each waste a worker timeout against a dead
  # tunnel) and let the poll loop wait for the next window.
  # Artifact timeouts: TERM at the ceiling, KILL only 120s later — a
  # SIGKILL mid-compile is what wedged the round-3 tunnel for hours.
  # 1. Transformer, driver default config
  bench transformer-default BENCH_MODELS=transformer; [ $? -eq 2 ] && return
  # 2. Transformer bs128 — the OOM the lse fix should have cured; bigger
  #    batch is the named MFU lever
  bench transformer-bs128 BENCH_MODELS=transformer BENCH_BS=128; [ $? -eq 2 ] && return
  # 3. long-context legs: seq1024 (flash regime) + the reference-attn
  #    control at the same shape (the O(block) claim needs the delta)
  bench transformer-seq1024 BENCH_MODELS=transformer BENCH_SEQ=1024 BENCH_BS=16; [ $? -eq 2 ] && return
  bench transformer-seq1024-refattn BENCH_MODELS=transformer \
    BENCH_SEQ=1024 BENCH_BS=16 FLAGS_attention_impl=reference; [ $? -eq 2 ] && return
  # 3b. MFU lever #1 A/B (docs/MFU_PLAN.md): fused CE head vs the
  #     composed default at the same driver config
  bench transformer-ce-fused BENCH_MODELS=transformer FLAGS_fused_ce=1; [ $? -eq 2 ] && return
  # 4. ResNet re-confirm (cheap; chip-side consistency pin)
  bench resnet50-default BENCH_MODELS=resnet50; [ $? -eq 2 ] && return
  # 5. Pallas-vs-XLA kernel verdicts — crashed in the r3 window on the
  #    pre-fix LSTM block spec (fixed in a2f4042; tests/test_tpu_lowering.py
  #    now guards the whole class); flag defaults depend on this table
  artifact docs/artifacts/kernel_bench_r05.jsonl \
    timeout -k 120 2700 python tools/kernel_bench.py; [ $? -eq 2 ] && return
  # 6. xprof per-HLO breakdown, both models (VERDICT Next #2: the MFU
  #    plan must be justified from this table)
  artifact docs/artifacts/step_breakdown_resnet50_r05.jsonl \
    timeout -k 120 2700 python tools/step_breakdown.py --model resnet50 --xprof; [ $? -eq 2 ] && return
  artifact docs/artifacts/step_breakdown_transformer_r05.jsonl \
    timeout -k 120 2700 python tools/step_breakdown.py --model transformer --xprof; [ $? -eq 2 ] && return
  # 7. convergence-on-chip proof (VERDICT Next #9): MNIST to threshold
  artifact docs/artifacts/convergence_mnist_r05.json \
    timeout -k 120 2700 python tools/convergence_run.py; [ $? -eq 2 ] && return
  # 8. seq4096 stretch leg (flash memory regime; skipped quickly if OOM)
  bench transformer-seq4096 BENCH_MODELS=transformer BENCH_SEQ=4096 BENCH_BS=4
  echo "capture pass done $(date -u +%FT%TZ)" | tee -a "$LOG/log"
}

all_done() {
  for tag in transformer-default transformer-bs128 transformer-seq1024 \
             transformer-seq1024-refattn transformer-ce-fused \
             resnet50-default transformer-seq4096; do
    if ! banked "$tag"; then
      [ "$(cat "$LOG/$tag.attempts" 2>/dev/null || echo 0)" -ge 3 ] \
        || return 1
    fi
  done
  for dest in docs/artifacts/kernel_bench_r05.jsonl \
              docs/artifacts/step_breakdown_resnet50_r05.jsonl \
              docs/artifacts/step_breakdown_transformer_r05.jsonl \
              docs/artifacts/convergence_mnist_r05.json; do
    if ! [ -s "$dest" ] \
        || [ -f "$LOG/$(basename "$dest").commit_pending" ]; then
      [ "$(cat "$LOG/$(basename "$dest").attempts" 2>/dev/null \
           || echo 0)" -ge 3 ] || return 1
    fi
  done
  return 0
}

if [ "${HW_ONESHOT:-0}" = "1" ]; then
  probe && capture
  exit 0
fi
retry_pending_commits() {  # commit retries need git, not the tunnel
  local pend
  for pend in "$LOG"/*.commit_pending; do
    [ -f "$pend" ] || continue
    local name; name="$(basename "$pend" .commit_pending)"
    local dest; dest="$(find docs/artifacts -name "$name" 2>/dev/null | head -1)"
    [ -n "$dest" ] && [ -s "$dest" ] || continue
    python tools/commit_path.py "$dest" \
      "Hardware artifact: $name (window capture)" \
      >>"$LOG/log" 2>&1 && rm -f "$pend"
  done
}

while true; do
  retry_pending_commits
  if all_done; then
    echo "all legs banked $(date -u +%FT%TZ); watcher exiting" \
      | tee -a "$LOG/log"
    break
  fi
  if probe; then
    capture
  else
    echo "tunnel down $(date -u +%FT%TZ)" >>"$LOG/log"
  fi
  sleep 300
done
