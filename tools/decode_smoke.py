"""Paged-decode smoke: prove the ragged paged-attention serving path
holds its two steady-state invariants under CHURN, then land a gated
capture.

    python tools/decode_smoke.py $DIR     # writes $DIR/decode.json

Asserted, in order:

  * **Zero fresh compiles across churn.** After one warmup wave
    (which compiles the paged session's init/admit/table executables
    and the single ``steps=K`` multi-step scan), a churny
    admit/release/step sequence — staggered admissions into freed
    slots, mixed source lengths, sequences completing mid-wave and
    recycling their pages — adds ZERO fresh compiles, read from the
    same metrics-registry scrape the serve stage trusts
    (``paddle_tpu_fresh_compiles_total``) and cross-checked against
    ``exec_cache.stats()``. The decode hot path is a fixed executable
    set; occupancy changes may never recompile it.
  * **Bit-exact churn decode.** The churned token streams equal the
    dense slot decoder's (the PR 8 oracle) for every request.
  * **Page hygiene.** After the pool drains, every page is back on the
    free list and the ``paddle_tpu_serving_kv_pages_in_use`` gauge
    reads 0.
  * **Beam churn (PR 15).** Staggered ``beam_width=4`` admissions
    through the zero-copy reorder path: per-step parent permutations
    land as in-graph table-row gathers + host refcount rebinds (ZERO
    pages physically copied — asserted), the whole staggered wave adds
    ZERO fresh compiles after one warmup wave, the token streams and
    n-best scores are BIT-identical to the copy-reorder oracle
    (``FLAGS_beam_reorder=reference`` — same geometry, same
    content-addressed executables), and the pool conserves at drain.
  * **Speculative churn (PR 16).** Staggered admissions through the
    draft-then-verify path (ngram drafter, ``k=3`` speculation tree,
    one tree-attention dispatch per verify): after one warmup wave
    that compiled the speculative executables AND the sequential
    ``FLAGS_speculative=off`` step, a churny 12-request / 4-slot wave
    adds ZERO fresh compiles, the token streams are BIT-identical to
    both the dense oracle and an off-oracle replay on the SAME
    session (flag flip, same slots — the speedup mechanism can never
    change what is decoded), the acceptance telemetry
    (``paddle_tpu_serving_speculative_*``) is published and nonzero,
    and the pool drains clean.
  * **Cross-request reuse churn (PR 12).** Best-of-N fork groups over
    a forced prefix (admit_group -> one encoder + one chunked prefill
    + joins; the top-k sampler forces member divergence, so the
    shared tail page copy-on-writes), release, re-admission of the
    SAME prefix through the prefix cache (a hit that must decode
    bit-identical to its own cold wave replay at the same slots), and
    a different source (a forced miss) — all after a warmup wave that
    compiled admit/join/prefill/copy/table/step once, adding ZERO
    fresh compiles. At drain the REFCOUNTS conserve: allocated pages
    == cache-held pages, no page is shared, and clearing the cache
    returns the free list to full.

The capture (``$DIR/decode.json``) is bench.py's decode A/B leg — the
SAME code path the BENCH trajectory tracks — and the CI ``decode``
stage gates it via ``tools/perf_diff.py --budgets
benchmark/budgets.json --models decode`` (tokens/sec, paged-vs-dense
speedup, per-token latency, grid-accounted HBM bytes).
"""

import json
import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _scrape_fresh_compiles():
    from paddle_tpu.observability import REGISTRY

    text = REGISTRY.to_prometheus()
    m = re.search(r"^paddle_tpu_fresh_compiles_total (\d+)", text,
                  re.MULTILINE)
    return int(m.group(1)) if m else None


def churn_invariants():
    import paddle_tpu as fluid
    from paddle_tpu.core import exec_cache
    from paddle_tpu.models import transformer
    from paddle_tpu.observability import REGISTRY
    from paddle_tpu.serving.generation import SlotDecodeSession

    vocab, seq, dm, S = 40, 16, 32, 4
    cfg = dict(src_vocab_size=vocab, trg_vocab_size=vocab, n_layer=1,
               n_head=2, d_inner=64)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 13
    startup.random_seed = 13
    with fluid.program_guard(main, startup):
        transformer.build(dropout=0.0, label_smooth_eps=0.0,
                          max_length=seq, d_model=dm, **cfg)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(17)
    n = 12  # 12 requests through a 4-slot pool: constant churn
    src = rng.randint(3, vocab, (n, seq)).astype("int64")
    src_len = np.asarray(
        [seq, 2, seq - 1, 5, seq, 3, seq - 2, seq, 4, seq, 2, seq],
        "int64")[:, None]

    dense = SlotDecodeSession(exe, num_slots=S, max_length=seq,
                              d_model=dm, **cfg)
    want = dense.generate(src, src_len)

    sess = SlotDecodeSession(exe, num_slots=S, max_length=seq,
                             d_model=dm, paged=True, page_size=4,
                             steps=4, **cfg)
    # warmup wave: compiles admit/table/multi-step once
    warm = sess.generate(src[:2], src_len[:2])
    np.testing.assert_array_equal(warm, want[:2])

    before_stats = exec_cache.stats()["fresh_compiles"]
    before_scrape = _scrape_fresh_compiles()
    got = sess.generate(src, src_len)  # the churny wave: 12 reqs, 4 slots
    np.testing.assert_array_equal(got, want)
    after_stats = exec_cache.stats()["fresh_compiles"]
    after_scrape = _scrape_fresh_compiles()

    assert after_stats == before_stats, (
        "churny admit/release/step paid %d fresh compiles"
        % (after_stats - before_stats))
    if before_scrape is not None:
        assert after_scrape == before_scrape, (
            "metrics scrape shows %d fresh compiles during churn"
            % (after_scrape - before_scrape))
    assert sess.pages_in_use == 0 and sess.free_slots == S

    text = REGISTRY.to_prometheus()
    assert "paddle_tpu_serving_kv_pages_in_use 0" in text, \
        "pages_in_use gauge did not return to 0"
    assert "paddle_tpu_serving_decode_tokens_per_sec" in text
    print("decode_smoke: churn OK — 0 fresh compiles over 12 requests / "
          "4 slots, tokens == dense oracle, pool drained clean")


def bestofn_prefix_churn():
    """Fork/prefix reuse under churn: groups, divergence (COW), release
    and prefix re-admission keep the zero-recompile contract and the
    allocator's conservation law."""
    import paddle_tpu as fluid
    from paddle_tpu.core import exec_cache
    from paddle_tpu.models import transformer
    from paddle_tpu.observability import REGISTRY
    from paddle_tpu.serving.generation import Sampler, SlotDecodeSession

    vocab, seq, dm, S = 40, 16, 32, 4
    cfg = dict(src_vocab_size=vocab, trg_vocab_size=vocab, n_layer=1,
               n_head=2, d_inner=64)
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 23
    startup.random_seed = 23
    with fluid.program_guard(main_prog, startup):
        transformer.build(dropout=0.0, label_smooth_eps=0.0,
                          max_length=seq, d_model=dm, **cfg)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(29)
    srcs = rng.randint(3, vocab, (3, seq)).astype("int64")
    # 7 forced tokens + bos: the first sampled write lands INSIDE the
    # shared tail page (7 % 4 != 0), so the fork's copy-on-write path
    # actually fires and its executable is part of the warmed set
    pfx = [[int(t) for t in row[:7]] for row in srcs]
    sess = SlotDecodeSession(
        exe, num_slots=S, max_length=seq, d_model=dm, paged=True,
        page_size=4, steps=2, num_groups=2, prefix_cache_pages=8,
        sampler=Sampler(strategy="top_k", top_k=4, temperature=0.9,
                        seed=3),
        **cfg)

    def wave(i, n=3):
        return sess.generate_best_of(srcs[i], n, src_len=seq,
                                     prefix_tokens=pfx[i])

    # warmup wave: compiles init/admit/join/prefill/copy/table/step
    warm = wave(0)
    assert not (np.array_equal(warm[0], warm[1])
                and np.array_equal(warm[1], warm[2])), \
        "sampled fork members never diverged — COW untested"

    before_stats = exec_cache.stats()["fresh_compiles"]
    before_scrape = _scrape_fresh_compiles()
    hits0 = sess.prefix_cache_stats()["hits"]
    wave(0)           # prefix HIT + fork + COW
    wave(1)           # different source: forced MISS + insert
    wave(1)           # ... and its hit
    wave(2, n=2)      # third source through the recycled group/pages
    wave(0)           # original prefix still cached
    assert exec_cache.stats()["fresh_compiles"] == before_stats, (
        "best-of-N / prefix churn paid %d fresh compiles"
        % (exec_cache.stats()["fresh_compiles"] - before_stats))
    after_scrape = _scrape_fresh_compiles()
    if before_scrape is not None:
        assert after_scrape == before_scrape, \
            "metrics scrape shows fresh compiles during reuse churn"
    st = sess.prefix_cache_stats()
    assert st["hits"] >= hits0 + 3 and st["tokens_saved"] > 0, st

    # refcount conservation at drain: every live reference released,
    # only the cache still holds pages; clearing it empties the pool
    assert sess.free_slots == S and sess.free_groups == 2
    assert sess.shared_pages == 0
    assert sess.pages_in_use == sess.cached_pages > 0
    sess.clear_prefix_cache()
    assert sess.pages_in_use == 0 and sess.free_pages == sess._P - 1
    text = REGISTRY.to_prometheus()
    assert "paddle_tpu_serving_kv_pages_shared 0" in text
    assert "paddle_tpu_serving_prefix_hit_rate" in text
    assert "paddle_tpu_serving_prefill_tokens_saved_total" in text
    print("decode_smoke: reuse churn OK — 0 fresh compiles across "
          "fork/COW/prefix-hit/release waves, hit rate %.2f, %d "
          "prefill tokens saved, refcounts conserved at drain"
          % (st["hit_rate"], st["tokens_saved"]))


def beam_churn():
    """Batched beam search over the slot pool (PR 15): staggered beam
    admissions through the zero-copy reorder path hold the
    zero-recompile contract, decode BIT-identical to the copy-reorder
    reference oracle (``FLAGS_beam_reorder=reference``), copy zero
    pages on pure parent permutations, and conserve the pool at
    drain."""
    import paddle_tpu as fluid
    from paddle_tpu import flags as _flags
    from paddle_tpu.core import exec_cache
    from paddle_tpu.models import transformer
    from paddle_tpu.observability import REGISTRY
    from paddle_tpu.serving.generation import SlotDecodeSession

    vocab, seq, dm, S, bw = 40, 16, 32, 8, 4
    cfg = dict(src_vocab_size=vocab, trg_vocab_size=vocab, n_layer=1,
               n_head=2, d_inner=64)
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 37
    startup.random_seed = 37
    with fluid.program_guard(main_prog, startup):
        transformer.build(dropout=0.0, label_smooth_eps=0.0,
                          max_length=seq, d_model=dm, **cfg)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(41)
    srcs = rng.randint(3, vocab, (4, seq)).astype("int64")
    # both sessions share one geometry (and therefore one
    # content-addressed program set): the oracle's transient full-copy
    # reorders need page headroom, so both get it
    pages = 1 + 2 * S * (seq // 4)

    def mk():
        return SlotDecodeSession(
            exe, num_slots=S, max_length=seq, d_model=dm, paged=True,
            page_size=4, beam_width=bw, num_pages=pages, **cfg)

    def staggered_wave(sess):
        """Two beams admitted 3 dispatches apart — the reorder, COW
        and release paths all run at mixed lane ages."""
        a = sess.admit_beam(srcs[0], seq)
        ra = sess.register_beam_owner(a)
        for _ in range(3):
            sess.step()
        b = sess.admit_beam(srcs[1], seq - 2)
        rb = sess.register_beam_owner(b)
        while sess.active_beams:
            sess.step()
        out = [sess.take_beam_result(ra), sess.take_beam_result(rb)]
        out.append(sess.generate_beam(srcs[2], seq))
        return out

    swap = mk()
    staggered_wave(swap)  # warmup: compiles the whole beam set once
    before = exec_cache.stats()["fresh_compiles"]
    before_scrape = _scrape_fresh_compiles()
    got = staggered_wave(swap)
    assert exec_cache.stats()["fresh_compiles"] == before, (
        "staggered beam churn paid %d fresh compiles"
        % (exec_cache.stats()["fresh_compiles"] - before))
    after_scrape = _scrape_fresh_compiles()
    if before_scrape is not None:
        assert after_scrape == before_scrape, \
            "metrics scrape shows fresh compiles during beam churn"
    assert swap.beam_reorder_pages == 0, (
        "rebind reorders physically copied %d pages"
        % swap.beam_reorder_pages)

    # swap-vs-copy bit equality: the copy-reorder oracle (same
    # geometry, same executables — 0 extra compiles for the mode flip)
    _flags.set_flag("beam_reorder", "reference")
    try:
        copy_sess = mk()
        ref = staggered_wave(copy_sess)
    finally:
        _flags.set_flag("beam_reorder", "rebind")
    assert copy_sess.beam_reorder_pages > 0, \
        "the copy oracle never copied a page"
    for g, r in zip(got, ref):
        gt, gs = (g["tokens"], g["scores"]) if isinstance(g, dict) else g
        rt, rs = (r["tokens"], r["scores"]) if isinstance(r, dict) else r
        np.testing.assert_array_equal(gt, rt)
        np.testing.assert_array_equal(gs, rs)

    # drain hygiene: lanes free, pool conserved, gauges current
    for sess in (swap, copy_sess):
        assert sess.pool_conserved and sess.free_beams == S // bw
        assert sess.pages_in_use == 0
    text = REGISTRY.to_prometheus()
    assert "paddle_tpu_serving_active_beams 0" in text
    assert "paddle_tpu_serving_beam_reorder_bytes_total" in text
    assert "paddle_tpu_serving_beam_cow_copies_total" in text
    print("decode_smoke: beam churn OK — 0 fresh compiles across "
          "staggered beam waves, swap == copy oracle bit-exact, 0 "
          "pages moved by rebind reorders (%d by the oracle), pool "
          "conserved at drain" % copy_sess.beam_reorder_pages)


def speculative_churn():
    """Speculative decode over the slot pool (PR 16): churny
    draft-then-verify admissions (12 requests / 4 slots, ngram
    drafter, tree-attention verify) hold the zero-recompile contract,
    decode BIT-identical to the dense oracle AND to a sequential
    off-oracle replay on the SAME session (``FLAGS_speculative=off``
    flag flip — same slots, same executables), publish nonzero
    acceptance telemetry, and drain the pool clean."""
    import paddle_tpu as fluid
    from paddle_tpu import flags as _flags
    from paddle_tpu.core import exec_cache
    from paddle_tpu.models import transformer
    from paddle_tpu.observability import REGISTRY
    from paddle_tpu.serving.generation import SlotDecodeSession

    vocab, seq, dm, S = 40, 16, 32, 4
    cfg = dict(src_vocab_size=vocab, trg_vocab_size=vocab, n_layer=1,
               n_head=2, d_inner=64)
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 43
    startup.random_seed = 43
    with fluid.program_guard(main_prog, startup):
        transformer.build(dropout=0.0, label_smooth_eps=0.0,
                          max_length=seq, d_model=dm, **cfg)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(47)
    n = 12  # 12 requests through a 4-slot pool: constant churn
    src = rng.randint(3, vocab, (n, seq)).astype("int64")
    src_len = np.asarray(
        [seq, 2, seq - 1, 5, seq, 3, seq - 2, seq, 4, seq, 2, seq],
        "int64")[:, None]

    dense = SlotDecodeSession(exe, num_slots=S, max_length=seq,
                              d_model=dm, **cfg)
    want = dense.generate(src, src_len)

    sess = SlotDecodeSession(exe, num_slots=S, max_length=seq,
                             d_model=dm, paged=True, page_size=4,
                             steps=1,
                             speculative={"k": 3, "drafter": "ngram"},
                             **cfg)
    # warmup: the speculative wave compiles the draft/tree-verify set,
    # the off wave compiles the sequential steps=1 step — BOTH paths
    # must be in the warmed set before the churn measurement
    np.testing.assert_array_equal(sess.generate(src[:2], src_len[:2]),
                                  want[:2])
    _flags.set_flag("speculative", "off")
    try:
        sess.generate(src[:2], src_len[:2])
    finally:
        _flags.set_flag("speculative", "on")

    before = exec_cache.stats()["fresh_compiles"]
    before_scrape = _scrape_fresh_compiles()
    p0, a0 = sess.spec_proposed, sess.spec_accepted
    got = sess.generate(src, src_len)  # churny speculative wave
    np.testing.assert_array_equal(got, want)
    assert sess.spec_proposed > p0 and sess.spec_dispatches > 0, \
        "the churny wave never actually speculated"
    # off-oracle replay on the SAME session: the flag flip routes the
    # same slots through the sequential step — bit parity proves the
    # speedup mechanism cannot change what is decoded
    _flags.set_flag("speculative", "off")
    try:
        off = sess.generate(src, src_len)
    finally:
        _flags.set_flag("speculative", "on")
    np.testing.assert_array_equal(got, off)
    assert exec_cache.stats()["fresh_compiles"] == before, (
        "speculative churn paid %d fresh compiles"
        % (exec_cache.stats()["fresh_compiles"] - before))
    after_scrape = _scrape_fresh_compiles()
    if before_scrape is not None:
        assert after_scrape == before_scrape, \
            "metrics scrape shows fresh compiles during speculative churn"
    assert sess.pages_in_use == 0 and sess.free_slots == S

    text = REGISTRY.to_prometheus()
    m = re.search(
        r"^paddle_tpu_serving_speculative_proposed_tokens_total (\d+)",
        text, re.MULTILINE)
    assert m and int(m.group(1)) >= sess.spec_proposed > 0, \
        "proposed-tokens counter not published"
    assert "paddle_tpu_serving_speculative_accepted_tokens_total" in text
    assert "paddle_tpu_serving_speculative_acceptance_rate" in text
    rate = ((sess.spec_accepted - a0) / (sess.spec_proposed - p0)
            if sess.spec_proposed > p0 else 0.0)
    print("decode_smoke: speculative churn OK — 0 fresh compiles over "
          "12 requests / 4 slots, tokens == dense oracle == off-oracle "
          "replay, %.2f acceptance over %d dispatches, pool drained "
          "clean" % (rate, sess.spec_dispatches))


def main():
    if len(sys.argv) != 2:
        sys.exit("usage: decode_smoke.py OUTPUT_DIR")
    out_dir = sys.argv[1]
    churn_invariants()
    bestofn_prefix_churn()
    beam_churn()
    speculative_churn()

    # the capture comes from bench.py's decode worker in its OWN
    # process — the same leg (and the same compile-count accounting)
    # the BENCH trajectory and budgets track; this process's churn
    # compiles must not pollute the worker's fresh_compiles budget
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, BENCH_MODEL="decode", BENCH_PLATFORM="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"), "--worker"],
        env=env, capture_output=True, text=True, timeout=900,
        cwd=root, check=True)
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    if "error" in rec:
        sys.exit("decode_smoke: bench worker failed: %s" % rec["error"])
    capture = {"models": {"decode": rec}}
    path = os.path.join(out_dir, "decode.json")
    with open(path, "w") as f:
        json.dump(capture, f)
    print("decode_smoke: capture -> %s (%.0f tok/s paged, %.2fx vs "
          "dense, %d fresh compiles)"
          % (path, rec["value"], rec["paged_speedup"],
             rec["exec_cache"]["fresh_compiles"]))


if __name__ == "__main__":
    main()
