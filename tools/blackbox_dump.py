"""Pretty-print a black box file (observability/blackbox.py dump).

The incident-response reader: given the JSON a crashed/hung/killed
process left behind, show what the engineer asks first — what was the
process doing (last flight events + step tail), why did it last
recompile, where was every thread (if the dump carries stacks), and did
a NaN diagnostic fire (and on which op).

Exit codes (CI-gateable, used by the ``forensics``/``chaos`` stages):
  0  dump read, no NaN/OOM diagnostic recorded
  2  file missing / unreadable / not a black box
  3  the dump records a NaN-provenance diagnostic (rule N001)
  4  the dump records an OOM diagnostic (rule M001 — top live-buffer
     holders + predicted peak; takes precedence over 3 when both exist,
     the allocator death being the step that actually killed the run)

Usage:
  python tools/blackbox_dump.py /path/box.json [--steps 10] [--events 15]
  python tools/blackbox_dump.py /path/box.json --json   # raw payload
"""

import argparse
import json
import os
import sys
import time


def _fmt_ts(ts):
    try:
        return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts))
    except Exception:
        return str(ts)


def _load(path):
    try:
        with open(path) as f:
            snap = json.load(f)
    except OSError as e:
        print("blackbox_dump: cannot read %s (%s) — did the process run "
              "with FLAGS_blackbox_path set?" % (path, e.strerror or e))
        raise SystemExit(2)
    except ValueError:
        print("blackbox_dump: %s is not valid JSON (torn write? wrong "
              "file?)" % path)
        raise SystemExit(2)
    if not isinstance(snap, dict) or "blackbox_version" not in snap:
        print("blackbox_dump: %s parses but is not a black box dump "
              "(no blackbox_version field)" % path)
        raise SystemExit(2)
    return snap


def _print_steps(snap, n):
    steps = snap.get("steps") or []
    print("\n-- last %d of %d telemetry steps --" % (min(n, len(steps)),
                                                     len(steps)))
    if not steps:
        print("  (none — FLAGS_telemetry was off or no step completed)")
    for r in steps[-n:]:
        extras = ""
        if r.get("device_times"):
            worst = max(r["device_times"], key=r["device_times"].get)
            extras = "  slowest_device=%s(%.1fms)" % (
                worst, r["device_times"][worst] * 1e3)
        print("  %s  %-10s %6.1fms  steps=%-3d feed=%dB fetch=%dB%s"
              % (_fmt_ts(r.get("ts", 0)), r.get("executor"),
                 r.get("step_s", 0) * 1e3, r.get("steps", 1),
                 r.get("feed_bytes", 0), r.get("fetch_bytes", 0), extras))


def _print_recompiles(snap):
    evs = snap.get("recompiles") or []
    print("\n-- recompiles: %d recorded --" % len(evs))
    if evs:
        last = evs[-1]
        print("  last: changed=%s mode=%s device=%s (compile #%s)"
              % (",".join(last.get("changed", [])), last.get("mode"),
                 last.get("device"), last.get("compiles_so_far")))
        for k, v in (last.get("detail") or {}).items():
            print("    %s: %s" % (k, v))
        if last.get("lint_rule"):
            print("    lint rule: %s (run tools/plint.py)"
                  % last["lint_rule"])


def _print_events(snap, n):
    evs = snap.get("events") or []
    print("\n-- last %d of %d flight events --" % (min(n, len(evs)),
                                                  len(evs)))
    for e in evs[-n:]:
        kind = e.get("kind")
        line = "  %s  %-12s" % (_fmt_ts(e.get("ts", 0)), kind)
        if kind == "dispatch":
            line += " %s fetch=%s" % (e.get("origin"),
                                      ",".join(e.get("fetch_names", [])))
        elif kind == "exception":
            line += " %s: %s: %s" % (e.get("origin"), e.get("exc_type"),
                                     (e.get("exc_message") or "")[:120])
        elif kind == "fatal_signal":
            line += " %s" % e.get("signal")
        elif kind == "watchdog_hang":
            line += " stalled=%s waited=%.1fs" % (
                ",".join(s.get("tag", "?") for s in e.get("stalled", [])),
                e.get("waited_s", 0))
        elif kind == "nan_diagnostic":
            line += " %s at block %s op %s (%s)" % (
                e.get("rule"), e.get("block_idx"), e.get("op_idx"),
                e.get("op_type"))
        elif kind == "oom_diagnostic":
            line += " %s live=%s holders=%s" % (
                e.get("rule"), e.get("live_bytes"),
                ",".join(h.get("name", "?")
                         for h in e.get("top_holders") or []))
        print(line)


def _print_stacks(snap):
    stacks = snap.get("thread_stacks")
    if not stacks:
        return
    print("\n-- thread stacks (%d threads) --" % len(stacks))
    for label, frames in sorted(stacks.items()):
        print("  [%s]" % label)
        for fr in frames[-6:]:
            for ln in fr.rstrip().splitlines():
                print("    " + ln)


def _print_nan(snap):
    d = snap.get("nan_diagnostic")
    if not d:
        return False
    print("\n-- NaN diagnostic (%s %s) --" % (d.get("rule"),
                                              d.get("name")))
    print("  %s" % d.get("message"))
    print("  location: block %s op %s (%s), vars: %s"
          % (d.get("block_idx"), d.get("op_idx"), d.get("op_type"),
             ", ".join(d.get("var_names", []))))
    if d.get("hint"):
        print("  hint: %s" % d["hint"])
    return True


def _print_oom(snap):
    d = snap.get("oom_diagnostic")
    if not d:
        return False
    print("\n-- OOM diagnostic (%s %s) --" % (d.get("rule"),
                                              d.get("name")))
    print("  %s" % d.get("message"))
    holders = d.get("top_holders") or []
    if holders:
        print("  top live-buffer holders:")
        for h in holders:
            print("    %-32s %-10s %-8s %12d bytes"
                  % (h.get("name"), h.get("kind"), h.get("device"),
                     h.get("bytes", 0)))
    if d.get("predicted_peak_bytes"):
        print("  predicted peak: %d bytes (memory plan)"
              % d["predicted_peak_bytes"])
    if d.get("live_bytes") is not None:
        print("  ledger live at death: %d bytes" % d["live_bytes"])
    if d.get("hint"):
        print("  hint: %s" % d["hint"])
    return True


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="pretty-print a paddle_tpu black box dump")
    ap.add_argument("path")
    ap.add_argument("--steps", type=int, default=10,
                    help="telemetry step records to show")
    ap.add_argument("--events", type=int, default=15,
                    help="flight events to show")
    ap.add_argument("--json", action="store_true",
                    help="print the raw JSON payload instead")
    args = ap.parse_args(argv)

    snap = _load(args.path)
    if args.json:
        json.dump(snap, sys.stdout, indent=2, sort_keys=True)
        print()
        if snap.get("oom_diagnostic"):
            return 4
        return 3 if snap.get("nan_diagnostic") else 0

    print("black box: %s" % args.path)
    print("  reason: %s" % snap.get("reason"))
    print("  when:   %s   pid: %s" % (_fmt_ts(snap.get("ts", 0)),
                                      snap.get("pid")))
    print("  argv:   %s" % " ".join(snap.get("argv", [])))
    _print_steps(snap, args.steps)
    _print_recompiles(snap)
    _print_events(snap, args.events)
    _print_stacks(snap)
    has_nan = _print_nan(snap)
    has_oom = _print_oom(snap)
    if has_oom:
        return 4
    return 3 if has_nan else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `blackbox_dump box.json | head` is normal
        os_devnull = open(os.devnull, "w")
        sys.stdout = os_devnull
        sys.exit(0)
