"""Pallas-vs-XLA kernel microbench (VERDICT r2 item 3: measure the Pallas
kernels or delete them).

For each kernel family the hand-written Pallas path is timed against the
XLA-composed lowering it replaces, at >= 3 shapes, THROUGH the op layer
(the flags/attrs users flip), so the numbers reflect what the framework
actually runs. Prints one JSON line per (kernel, shape, impl) plus a
closing summary with the per-kernel speedup and a default recommendation.

Usage (TPU host):   python tools/kernel_bench.py
CPU smoke:          BENCH_PLATFORM=cpu python tools/kernel_bench.py --quick
(on CPU the Pallas paths run in interpreter mode and are expected to lose
badly; only the TPU numbers decide flag defaults.)
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time_steps(fn, steps, warmup):
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn()
    # sync on the last value
    import numpy as np

    np.asarray(out)
    return (time.perf_counter() - t0) / steps


def _bench_rnn(fluid, op_name, flag, shapes, steps, warmup):
    import numpy as np

    rows = []
    for bs, seq, hidden in shapes:
        times = {}
        for use_pallas in (False, True):
            fluid.flags.set_flag(flag, use_pallas)
            try:
                from paddle_tpu import unique_name

                unique_name.switch()
                main, startup = fluid.Program(), fluid.Program()
                main.random_seed = 3
                startup.random_seed = 3
                with fluid.program_guard(main, startup):
                    x = fluid.layers.data(
                        name="x", shape=[seq, 4 * hidden
                                         if op_name == "dynamic_lstm"
                                         else 3 * hidden],
                        dtype="float32")
                    if op_name == "dynamic_lstm":
                        out, _ = fluid.layers.dynamic_lstm(
                            input=x, size=4 * hidden)
                    else:
                        out = fluid.layers.dynamic_gru(
                            input=x, size=hidden)
                    loss = fluid.layers.reduce_mean(out)
                with fluid.scope_guard(fluid.executor.Scope()):
                    exe = fluid.Executor(fluid.TPUPlace()
                                         if _on_tpu() else fluid.CPUPlace())
                    exe.run(startup)
                    width = (4 if op_name == "dynamic_lstm" else 3) * hidden
                    feed = {"x": np.random.RandomState(0).rand(
                        bs, seq, width).astype("float32")}
                    dt = _time_steps(
                        lambda: exe.run(main, feed=feed,
                                        fetch_list=[loss])[0],
                        steps, warmup)
                times["pallas" if use_pallas else "xla"] = dt
            finally:
                fluid.flags.set_flag(flag, False)
        row = {"kernel": op_name, "shape": [bs, seq, hidden],
               "xla_ms": round(times["xla"] * 1e3, 3),
               "pallas_ms": round(times["pallas"] * 1e3, 3),
               "speedup": round(times["xla"] / times["pallas"], 3)}
        print(json.dumps(row))
        rows.append(row)
    return rows


def _bench_flash(fluid, shapes, steps, warmup, window=0):
    """window > 0 also times the sliding-window pruned kernel vs the
    windowed reference at the same shape — the O(window) wall-time
    proof interpret mode cannot provide (tools/longctx_bench.py)."""
    import numpy as np

    rows = []
    for b, h, t, d in shapes:
        times = {}
        rng = np.random.RandomState(1)
        feed = {
            "q": rng.randn(b, h, t, d).astype("float32"),
            "k": rng.randn(b, h, t, d).astype("float32"),
            "v": rng.randn(b, h, t, d).astype("float32"),
        }
        for impl in ("reference", "pallas"):
            from paddle_tpu import unique_name

            unique_name.switch()
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                q = fluid.layers.data(name="q", shape=[h, t, d])
                kk = fluid.layers.data(name="k", shape=[h, t, d])
                v = fluid.layers.data(name="v", shape=[h, t, d])
                for var in (q, kk, v):
                    var.stop_gradient = False
                out = fluid.layers.scaled_dot_product_attention(
                    q, kk, v, causal=True, impl=impl, window=window)
                loss = fluid.layers.reduce_mean(out)
                # fwd+bwd: flash attention's win is the backward pass
                fluid.optimizer.SGD(learning_rate=0.0).minimize(
                    loss, parameter_list=[])
            with fluid.scope_guard(fluid.executor.Scope()):
                exe = fluid.Executor(fluid.TPUPlace()
                                     if _on_tpu() else fluid.CPUPlace())
                exe.run(startup)
                dt = _time_steps(
                    lambda: exe.run(main, feed=feed,
                                    fetch_list=[loss])[0],
                    steps, warmup)
            times[impl] = dt
        row = {"kernel": "flash_attention"
               + ("_w%d" % window if window else ""),
               "shape": [b, h, t, d],
               "xla_ms": round(times["reference"] * 1e3, 3),
               "pallas_ms": round(times["pallas"] * 1e3, 3),
               "speedup": round(times["reference"] / times["pallas"], 3)}
        print(json.dumps(row))
        rows.append(row)
    return rows


def _on_tpu():
    import jax

    return any(d.platform != "cpu" for d in jax.devices())


_FAMILIES = ("dynamic_lstm", "dynamic_gru", "flash_attention")


def _probe_on_tpu():
    """Ask a throwaway subprocess (timeout-bounded: a wedged tunnel hangs
    backend init) whether jax sees a non-CPU device."""
    import subprocess
    import sys

    code = ("import jax\n"
            "print('ONTPU|' + str(any(d.platform != 'cpu'"
            " for d in jax.devices())))")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=90)
    except subprocess.TimeoutExpired:
        return None
    for line in proc.stdout.splitlines():
        if line.startswith("ONTPU|"):
            return line.split("|", 1)[1] == "True"
    return None


def _orchestrate(args):
    """Run each kernel family in its OWN subprocess under a deadline:
    a crash OR a hang (the tunnel wedging mid-run — the way the first
    hardware window lost every verdict) costs one family, and rows a
    child printed before dying still reach the log and the summary."""
    import subprocess
    import sys

    all_rows = []
    for fam in _FAMILIES:
        # -u: unbuffered child stdout, so rows printed before a hang
        # survive the SIGKILL (a pipe is block-buffered by default)
        cmd = [sys.executable, "-u", os.path.abspath(__file__),
               "--family", fam]
        if args.quick:
            cmd.append("--quick")
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True,
                timeout=int(os.environ.get("KERNEL_BENCH_FAMILY_TIMEOUT",
                                           "900")))
            stderr, rc = proc.stderr, proc.returncode
            stdout = proc.stdout
        except subprocess.TimeoutExpired as e:
            stdout = (e.stdout or b"").decode() if isinstance(
                e.stdout, bytes) else (e.stdout or "")
            stderr = "family timed out (wedged backend?)"
            rc = -1
        for line in stdout.splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            print(line)
            try:
                all_rows.append(json.loads(line))
            except ValueError:
                pass
        if rc != 0:
            sys.stderr.write(stderr[-6000:] + "\n")
            print(json.dumps({"kernel": fam,
                              "error": "family rc=%s; stderr tail above"
                              % rc}))
    return all_rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="tiny shapes + few steps (CPU smoke)")
    ap.add_argument("--family", choices=_FAMILIES,
                    help="internal: run ONE family in this process")
    args = ap.parse_args()

    if args.family is None:
        all_rows = _orchestrate(args)
        _print_verdicts(all_rows)
        return

    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    import paddle_tpu as fluid

    if args.quick:
        steps, warmup = 3, 1
        rnn_shapes = [(4, 16, 32)]
        fa_shapes = [(1, 2, 128, 32)]
    else:
        steps, warmup = 20, 5
        rnn_shapes = [(32, 128, 256), (64, 256, 512), (16, 512, 1024)]
        fa_shapes = [(8, 8, 1024, 64), (4, 8, 2048, 64), (2, 8, 4096, 128)]

    # child mode: exactly one family, crash loudly (the parent records
    # the traceback from stderr and keeps the other families)
    if args.family == "dynamic_lstm":
        _bench_rnn(fluid, "dynamic_lstm", "use_pallas_lstm", rnn_shapes,
                   steps, warmup)
    elif args.family == "dynamic_gru":
        _bench_rnn(fluid, "dynamic_gru", "use_pallas_gru", rnn_shapes,
                   steps, warmup)
    else:
        _bench_flash(fluid, fa_shapes, steps, warmup)
        # sliding-window leg: same longest shape, window = seq/8 — the
        # pruned-kernel wall-time proof (longctx_bench.py tile counts
        # predict ~seq/(2*window)x on the flash side). Scaled with the
        # shape so the --quick smoke (seq 128) still exercises a window
        # that actually prunes.
        t_last = fa_shapes[-1][2]
        _bench_flash(fluid, fa_shapes[-1:], steps, warmup,
                     window=max(t_last // 8, 16))


def _print_verdicts(all_rows):
    import numpy as np

    summary = {}
    for row in all_rows:
        if "speedup" in row:
            summary.setdefault(row["kernel"], []).append(row["speedup"])
    verdicts = {
        k: {"geomean_speedup": round(
            float(np.prod(v)) ** (1.0 / len(v)), 3),
            "recommend_default": "pallas"
            if all(s > 1.05 for s in v) else "xla"}
        for k, v in summary.items()
    }
    # None = probe timed out (unknown platform): verdicts from a
    # non-TPU run must be distinguishable — only chip numbers set
    # flag defaults (module docstring)
    print(json.dumps({"on_tpu": _probe_on_tpu(),
                      "verdicts": verdicts}))


if __name__ == "__main__":
    main()
