"""Step-observatory smoke: prove the profiler is FREE when off and
ACCOUNTABLE when on, over a real training loop.

One process, two legs over the SAME seeded MLP training job (fresh
Executor per leg, so run counters and step keys line up exactly):

* **Leg A (control, FLAGS_step_profile unset)** runs single steps plus
  repeated ``run_multi_step`` dispatches, banks every fetch and the
  per-rep walls, and asserts the profiler stayed silent: no records, no
  in-flight phases.

* **Leg B (profiled)** replays the identical schedule with the
  observatory on and asserts the observe-don't-perturb contract:

    - every fetch bit-identical to the control leg;
    - **0 fresh compiles** — the profiled leg pays the exact compile
      bill the control leg already paid: none;
    - every timed step record attributes >= 95% of its wall to named
      phases (feed/compile/dispatch/device/fetch/host);
    - achieved-MFU joined from the cost model is finite on every
      record, and the bound classification is from the closed
      vocabulary;
    - the wall-clock overhead ratio (profiled / unprofiled over
      INTERLEAVED off/on multi-step pairs on the warm executable, so
      machine drift between measurements cancels) lands in the capture
      for the budget gate.

The profiled leg's ring then round-trips the offline toolchain:
``write_stepprof_jsonl`` -> ``tools/step_breakdown.py --steps`` ->
``tools/perf_ledger.py append/show/diff`` (two entries, relative gate
clean).

The capture (``$D/stepprof.json``: phase_coverage, fresh_compiles,
achieved_mfu, starvation_fraction, stepprof_overhead) gates via
``tools/perf_diff.py --budgets benchmark/budgets.json --models
stepprof``.
"""

import json
import math
import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

STEPS = 1024        # per run_multi_step dispatch: the profiler's cost is
REPS = 4            # fixed per DISPATCH (~100µs of brackets + record
SINGLES = 3         # assembly), so a real scan length amortizes it to
                    # well under the 2% budget per step
COVERAGE_FLOOR = 0.95
BOUNDS = ("compute", "bandwidth", "input", "host", "device")


def _build_mlp():
    import paddle_tpu as fluid
    from paddle_tpu import unique_name

    unique_name.switch({})
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 11
    startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [16])
        hid = fluid.layers.fc(x, size=32, act="relu")
        loss = fluid.layers.mean(fluid.layers.fc(hid, size=4))
        # small lr: ~1800 SGD steps on an unbounded toy loss must stay
        # finite, or leg parity would compare NaN against NaN
        fluid.optimizer.SGD(learning_rate=0.001).minimize(loss)
    return main, startup, loss


def _feed():
    return {"x": (np.arange(4 * 16, dtype="float32")
                  .reshape(4, 16) / 100.0)}


def _leg(exe, main, startup, loss):
    """One full schedule; -> fetches. Legs share ONE Executor
    (``run_multi_step`` executables live in the per-instance cache, so a
    fresh Executor would re-trace) and each leg rewinds the run counter:
    the step PRNG key folds it in, so identical counters mean identical
    startup init and step keys — the legs replay the exact same
    computation, executable for executable."""
    exe._run_counter = 0
    feed = _feed()
    exe.run(startup)
    fetches = []
    for _ in range(SINGLES):
        fetches.append(exe.run(main, feed=feed, fetch_list=[loss])[0])
    for _ in range(1 + REPS):
        fetches.append(
            exe.run_multi_step(main, STEPS, feed=feed,
                               fetch_list=[loss])[0])
    return fetches


def _time_overhead(exe, main, loss):
    """Profiled/unprofiled wall ratio over ADJACENT off/on multi-step
    pairs on the warm executable. Interleaving is the drift killer: the
    process speeds up over its first seconds (allocator warmup, branch
    caches), so a leg-vs-leg ratio inherits whatever the machine was
    doing minutes apart — pairing each profiled rep with an unprofiled
    neighbor cancels it. Min-of-reps on each side then drops scheduler
    jitter, which only ever ADDS time."""
    feed = _feed()
    walls_off, walls_on = [], []
    from paddle_tpu.observability import step_profiler
    try:
        for _ in range(REPS):
            for armed, walls in ((False, walls_off), (True, walls_on)):
                step_profiler.enable(armed)
                t0 = time.perf_counter()
                exe.run_multi_step(main, STEPS, feed=feed,
                                   fetch_list=[loss])
                walls.append(time.perf_counter() - t0)
    finally:
        step_profiler.enable(False)
    return min(walls_on) / max(min(walls_off), 1e-9)


def _median(vals):
    s = sorted(vals)
    return s[len(s) // 2]


def _assert_tools_round_trip(workdir, jsonl, n_timed):
    """step_breakdown reads the flushed ring; perf_ledger appends two
    trajectory points and gates the newest against the previous."""
    tools = os.path.dirname(os.path.abspath(__file__))
    brk = subprocess.run(
        [sys.executable, os.path.join(tools, "step_breakdown.py"),
         "--steps", jsonl, "--top", "2"],
        capture_output=True, text=True)
    assert brk.returncode == 0, (
        "step_breakdown --steps failed: %s" % brk.stderr)
    fleet = json.loads(brk.stdout.splitlines()[0])
    assert fleet["step_records"] >= n_timed, fleet
    assert fleet["coverage_min"] >= COVERAGE_FLOOR, fleet
    ledger = os.path.join(workdir, "ledger.jsonl")
    for label in ("smoke-a", "smoke-b"):
        app = subprocess.run(
            [sys.executable, os.path.join(tools, "perf_ledger.py"),
             "append", "--ledger", ledger, "--stepprof", jsonl,
             "--label", label],
            capture_output=True, text=True)
        assert app.returncode == 0, (
            "perf_ledger append failed: %s" % app.stderr)
    assert json.loads(app.stdout)["entries"] == 2, app.stdout
    show = subprocess.run(
        [sys.executable, os.path.join(tools, "perf_ledger.py"),
         "show", "--ledger", ledger, "--model", "stepprof"],
        capture_output=True, text=True)
    assert show.returncode == 0 and "phase_coverage" in show.stdout, (
        "perf_ledger show lost the trajectory: %s" % show.stdout)
    diff = subprocess.run(
        [sys.executable, os.path.join(tools, "perf_ledger.py"),
         "diff", "--ledger", ledger],
        capture_output=True, text=True)
    assert diff.returncode == 0, (
        "identical trajectory points must gate clean:\n%s%s"
        % (diff.stdout, diff.stderr))


def main():
    workdir = sys.argv[1] if len(sys.argv) > 1 else None
    if not workdir:
        print("usage: stepprof_smoke.py <workdir>", file=sys.stderr)
        return 2
    import paddle_tpu as fluid
    from paddle_tpu.core import exec_cache
    from paddle_tpu.observability import step_profiler

    # -- leg 0: discarded warmup --------------------------------------------
    # The first schedule's own runs create scope vars, and scope names
    # are part of the trace-cache key — so the SECOND schedule over the
    # shared global scope retraces once for startup and once for the
    # multi-step executable no matter what. One throwaway schedule
    # stabilizes the keys; legs A and B then share every executable.
    assert not step_profiler.ENABLED, \
        "control leg started with FLAGS_step_profile set"
    main, startup, loss = _build_mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    _leg(exe, main, startup, loss)

    # -- leg A: control, profiler off ---------------------------------------
    fetches_off = _leg(exe, main, startup, loss)
    assert not step_profiler.records() and not step_profiler.inflight(), \
        "profiler-off leg accumulated step records"
    compiles_off = exec_cache.stats()["fresh_compiles"]

    # -- leg B: profiled, same schedule -------------------------------------
    step_profiler.enable(True)
    step_profiler.reset()
    try:
        fetches_on = _leg(exe, main, startup, loss)
    finally:
        step_profiler.enable(False)
    fresh = exec_cache.stats()["fresh_compiles"] - compiles_off
    assert fresh == 0, (
        "profiled leg paid %d fresh compile(s) the control leg didn't"
        % fresh)
    assert len(fetches_on) == len(fetches_off)
    for i, (a, b) in enumerate(zip(fetches_off, fetches_on)):
        assert np.array_equal(a, b), (
            "fetch %d diverged between the control and profiled legs"
            % i)

    # -- the records: coverage, MFU join, classification --------------------
    recs = [r for r in step_profiler.records()
            if not r.get("dispatch_only")]
    # the startup run is profiled too: 1 + singles + warmup multi + reps
    assert len(recs) == 1 + SINGLES + 1 + REPS, (
        "expected %d step records, ring holds %d"
        % (1 + SINGLES + 1 + REPS, len(recs)))
    cov = min(r["coverage"] for r in recs)
    assert cov >= COVERAGE_FLOOR, (
        "worst step attributes only %.4f of its wall to phases: %r"
        % (cov, min(recs, key=lambda r: r["coverage"])))
    train = recs[1:]  # recs[0] is the startup run: init, ~0 FLOPs
    for r in train:
        assert r["achieved_mfu"] is not None and \
            math.isfinite(r["achieved_mfu"]) and r["achieved_mfu"] > 0, (
                "cost join produced no finite achieved-MFU: %r" % r)
    for r in recs:
        assert r["bound"] in BOUNDS, r
        assert r["starvation_fraction"] == 0.0, (
            "feed-dict job reported input starvation: %r" % r)
    assert not step_profiler.inflight(), \
        "in-flight phases leaked after the profiled leg finished"

    # -- offline round trip --------------------------------------------------
    jsonl = os.path.join(workdir, "m.stepprof.jsonl")
    n = step_profiler.write_stepprof_jsonl(jsonl)
    assert n >= len(recs), (
        "ring flushed %d records, expected >= %d" % (n, len(recs)))
    _assert_tools_round_trip(workdir, jsonl, len(recs))

    # -- overhead: interleaved off/on pairs on the warm executable ----------
    overhead = _time_overhead(exe, main, loss)
    mfu_p50 = _median(sorted(r["achieved_mfu"] for r in train))
    rec = {
        "metric": "stepprof_phase_coverage",
        "value": round(cov, 4),
        "unit": "fraction of step wall attributed to phases",
        "vs_baseline": None,
        "phase_coverage": round(cov, 4),
        "fresh_compiles": fresh,
        "achieved_mfu": round(mfu_p50, 10),
        "starvation_fraction": 0.0,
        "stepprof_overhead": round(overhead, 4),
        "step_records": len(recs),
        "steps": STEPS * (REPS + 1) + SINGLES,
        "platform": "cpu",
    }
    print("stepprof_smoke: %s" % json.dumps(rec))
    with open(os.path.join(workdir, "stepprof.json"), "w") as f:
        json.dump({"models": {"stepprof": rec}}, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
