"""Observability: the flight recorder for the XLA execution engine.

PR 1 added three cache layers plus async dispatch whose behavior was
visible only through one hand-rolled print report. This package makes the
framework self-describing in production instead:

* ``metrics_registry`` — a process-global, thread-safe registry of
  counters / gauges / fixed-bucket histograms with label support,
  exported as Prometheus text format and JSONL snapshots
  (``FLAGS_metrics_path``). The executable-cache counters
  (``core/exec_cache.py``) are absorbed via a collector, so one scrape
  carries the whole compile-tax story.
* ``telemetry`` — per-step flight data recorded by ``Executor.run`` /
  ``run_async`` / ``run_multi_step`` and ``ParallelExecutor.run``: wall
  time, feed/fetch bytes, host->device transfer time, device memory in
  use, and an MFU/roofline estimate from per-fingerprint FLOP counts.
  Surfaced through ``profiler.step_stats()`` percentiles and a
  ``StepTimer`` callback API. Switched by ``FLAGS_telemetry`` (module
  bool guard: zero overhead when off).
* ``explain`` — the recompile explainer: every fresh XLA trace logs a
  structured event naming which cache-key component changed vs. the
  nearest cached entry, so "why did it retrace" is one log line.

The failure-forensics layer (this PR) covers the moments the healthy-path
recorder can't:

* ``blackbox`` — a bounded ring of flight events (dispatches with feed
  specs/fetch lists, exceptions, notes) dumped as one JSON file on
  unhandled executor/Predictor exceptions, fatal signals, watchdog
  hangs, or demand (``FLAGS_blackbox_path``;
  ``tools/blackbox_dump.py`` pretty-prints it).
* ``watchdog`` — opt-in background hang detector: no executor/fetch
  progress within ``FLAGS_watchdog_timeout`` (default: a multiple of
  telemetry's p95 step time) dumps all thread stacks + the black box,
  then optionally aborts (``FLAGS_watchdog_abort``).
* ``nan_provenance`` — when ``FLAGS_check_nan_inf``'s on-device scan
  trips, the step is replayed per-op from a pre-step snapshot and the
  FIRST op with a non-finite output is blamed as an
  ``analysis.diagnostics.Diagnostic`` (rule N001) with a fix hint.

The memory layer (this PR) makes HBM first-class alongside time and
failures:

* ``memory`` — a live-buffer ledger the executors/feed/fetch/cache/
  checkpoint paths write (``paddle_tpu_hbm_live_bytes{device,kind}``,
  per-step ``peak_hbm_bytes`` watermarks in the telemetry records), a
  predicted-memory planner over the PR 3 liveness analysis
  (``Program.memory_plan``, ``profiler.memory_stats()`` for
  predicted-vs-measured), and OOM forensics: RESOURCE_EXHAUSTED dispatch
  deaths become rule **M001** diagnostics — never retried — whose
  black-box dump names the top holders and the predicted peak.

The training plane adds phase-level attribution:

* ``step_profiler`` — the training-step observatory
  (``FLAGS_step_profile``): every step becomes a phase-attributed
  record (input wait / feed / compile / dispatch / device / fetch /
  host residual) joined against tools/hlo_cost_model.py's fused-group
  roofline — achieved-FLOP/s, achieved-MFU, predicted-vs-achieved and
  an input/host/compute/bandwidth-bound verdict per step — plus an
  online median+MAD regression detector that names the guilty phase.
  Ring + ``<metrics_path>.stepprof.jsonl``; ``tools/step_breakdown.py
  --steps`` is the offline view, ``tools/perf_ledger.py`` the
  append-only trajectory.

The serving plane adds request-scoped attribution:

* ``tracing`` — one trace per serving request (id minted by
  ``ServingClient``, carried in the wire envelope, continued by the
  frontend and decode session): span waterfalls covering queue wait,
  admission, prefill, every decode dispatch and wire flush, with
  derived SLO stats (TTFT, inter-token distribution, page-seconds,
  speculation fraction). Completed traces land in a bounded ring +
  ``<metrics_path>.traces.jsonl``; latency histograms carry trace-id
  exemplars; blackbox dumps list in-flight ids. Switched by
  ``FLAGS_request_tracing`` (module-bool guard, telemetry's contract).

``docs/OBSERVABILITY.md`` is the operator's guide (metric catalog, how
to read the explainer, loading the merged trace in perfetto, failure
forensics, the memory ledger).
"""

from paddle_tpu.observability import blackbox  # noqa: F401
from paddle_tpu.observability import explain  # noqa: F401
from paddle_tpu.observability import memory  # noqa: F401
from paddle_tpu.observability import metrics_registry  # noqa: F401
from paddle_tpu.observability import nan_provenance  # noqa: F401
from paddle_tpu.observability import step_profiler  # noqa: F401
from paddle_tpu.observability import telemetry  # noqa: F401
from paddle_tpu.observability import tracing  # noqa: F401
from paddle_tpu.observability import watchdog  # noqa: F401
from paddle_tpu.observability.metrics_registry import REGISTRY  # noqa: F401


def _exec_cache_collector():
    """Scrape-time view of the executable-cache counters: the single
    source of truth stays core/exec_cache.py (bench.py and the warm-start
    smoke read it directly); the registry mirrors it so one Prometheus
    scrape carries compile-tax data without double bookkeeping."""
    from paddle_tpu.core import exec_cache

    st = exec_cache.stats()
    yield ("paddle_tpu_fresh_compiles_total", "counter",
           "XLA compiles no cache layer could serve",
           [({}, st["fresh_compiles"])])
    yield ("paddle_tpu_backend_compiles_total", "counter",
           "XLA backend compile calls observed (jax.monitoring)",
           [({}, st["backend_compiles"])])
    yield ("paddle_tpu_exec_cache_hits_total", "counter",
           "executable-cache hits by layer",
           [({"layer": "trace"}, st["trace_cache_hits"]),
            ({"layer": "persistent"}, st["persistent_hits"]),
            ({"layer": "aot"}, st["aot_hits"])])
    yield ("paddle_tpu_exec_cache_misses_total", "counter",
           "executable-cache misses by layer",
           [({"layer": "trace"}, st["trace_cache_misses"]),
            ({"layer": "persistent"}, st["persistent_misses"]),
            ({"layer": "aot"}, st["aot_misses"])])
    yield ("paddle_tpu_exec_cache_errors_total", "counter",
           "corrupt/incompatible persistent entries tolerated",
           [({"layer": "aot"}, st["aot_errors"])])
    yield ("paddle_tpu_compile_seconds_total", "counter",
           "wall seconds inside XLA compiles, split cold/warm",
           [({"kind": "cold"}, st["compile_seconds_cold"]),
            ({"kind": "warm"}, st["compile_seconds_warm"])])


REGISTRY.register_collector(_exec_cache_collector)
