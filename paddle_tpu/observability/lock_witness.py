"""Runtime lock witness: named locks, acquisition-order graph, dispatch
holds.

The host plane around the XLA core is ~45 lock/thread construction sites
across serving, resilience, elastic, distributed and observability. The
static linter (``analysis/concurrency.py``) checks what the source
*says*; this module checks what the process *does*: every framework lock
is built through a factory here (``make_lock``/``make_rlock``/
``make_condition``) under a stable dotted name, and with
``FLAGS_lock_witness`` armed the returned wrapper records, per thread,
which named locks were held at the moment each further lock was
acquired. Those (held -> acquired) edges accumulate into one global
order graph where a cycle means two code paths take the same pair of
locks in opposite orders — the ABBA deadlock that only fires under the
right interleave in production, caught here on ANY interleave because
the graph remembers both orders even when the holds never overlapped.

Two further checks ride the same bookkeeping:

* **dispatch holds** — ``Executor._dispatch`` calls :func:`note_dispatch`
  before handing the step to XLA; a thread that enters a device dispatch
  while holding a witnessed lock is reported (the runtime twin of the
  C002 lint rule). Locks whose contract is "serialize the dispatch"
  register with ``allow_dispatch=True`` and are exempt.
* **holder attribution** — :func:`held_by_thread` maps live thread idents
  to the named locks they hold right now; ``blackbox.thread_stacks()``
  folds it into every watchdog / fatal-signal dump, turning a "hung in
  acquire" stack into "hung in acquire of X while <thread> holds X".

Overhead contract (the house rule): ``ENABLED`` is a module bool read at
lock CONSTRUCTION time. Off (the default), every factory returns a plain
``threading.Lock``/``RLock``/``Condition`` — zero wrapper allocations,
zero per-acquire bookkeeping. Arm with ``FLAGS_lock_witness=1`` in the
environment before the subsystems under test import, or
:func:`enable` before they construct.

Reporting sinks are the standard three: the
``paddle_tpu_lock_witness_{edges,cycles_total,long_holds_total}`` metric
family, blackbox flight events (``lock_order_cycle``,
``lock_held_across_dispatch``), and the dump annotation above. The
witness's own internal lock (``_wlock``) is NEVER witnessed, is only
taken with a short timed acquire (signal-handler safety: recording
degrades to a dropped edge, never to a blocked handler), and is never
held across a metric or blackbox call (those take their own locks).
"""

import threading
import time

from paddle_tpu.observability.metrics_registry import REGISTRY

__all__ = [
    "ENABLED", "enable", "disable", "reset",
    "make_lock", "make_rlock", "make_condition",
    "note_dispatch", "held_by_thread", "report", "registered_locks",
]

ENABLED = False

# guards the graph/report structures below; deliberately plain (never
# witnessed) and only ever taken via a short timed acquire
_WLOCK_TIMEOUT = 0.2
_wlock = threading.Lock()

_edges = {}        # (held_name, acquired_name) -> count
_edge_sites = {}   # (held_name, acquired_name) -> (thread_name,) sample
_cycles = []       # [{"cycle": [names...], "thread": name}]
_cycle_keys = set()  # dedup: frozenset of the cycle's edge pairs
_long_holds = []   # [{"locks": [...], "thread": name}]
_registered = {}   # name -> construction count (lock census)

# per-thread held stack, registered globally so forensics can read OTHER
# threads' holds: ident -> the thread's own held list (entries are
# [wrapper, t_acquire, depth]; list/dict ops ride the GIL, and readers
# only snapshot names — a torn read costs one stale annotation line)
_all_held = {}

_tls = threading.local()

_edges_gauge = REGISTRY.gauge(
    "paddle_tpu_lock_witness_edges",
    "distinct (held -> acquired) lock-order edges observed since arm")
_cycles_total = REGISTRY.counter(
    "paddle_tpu_lock_witness_cycles_total",
    "lock-order cycles (potential ABBA deadlocks) detected in the "
    "acquisition-order graph")
_long_holds_total = REGISTRY.counter(
    "paddle_tpu_lock_witness_long_holds_total",
    "device dispatches entered while the dispatching thread held a "
    "witnessed lock not registered allow_dispatch")


def enable(on=True):
    """Arm the witness for locks constructed AFTER this call."""
    global ENABLED
    ENABLED = bool(on)
    return ENABLED


def disable():
    return enable(False)


def reset():
    """Drop the recorded graph and reports (tests)."""
    with _wlock:
        _edges.clear()
        _edge_sites.clear()
        del _cycles[:]
        _cycle_keys.clear()
        del _long_holds[:]
        _registered.clear()
    _edges_gauge.set(0)


# -- factories ---------------------------------------------------------------

def make_lock(name, allow_dispatch=False):
    """A named mutex: plain ``threading.Lock()`` when the witness is
    off, a recording wrapper when armed. ``allow_dispatch=True`` marks a
    lock whose CONTRACT is to be held across a device dispatch (e.g. the
    per-Predictor serialization lock) — exempt from the long-hold check,
    still in the order graph."""
    if not ENABLED:
        return threading.Lock()
    return _WitnessLock(name, threading.Lock(), allow_dispatch)


def make_rlock(name, allow_dispatch=False):
    """Named reentrant lock (same contract as :func:`make_lock`).
    Reacquisition by the owning thread records no new edges."""
    if not ENABLED:
        return threading.RLock()
    return _WitnessLock(name, threading.RLock(), allow_dispatch)


def make_condition(name, lock=None):
    """Named condition variable. When armed, the underlying mutex is a
    witnessed lock (``Condition.wait``'s release/re-acquire cycles are
    recorded like any other); pass ``lock`` to share one witnessed mutex
    between several conditions (the reader-queue pattern)."""
    if not ENABLED:
        return threading.Condition(lock)
    if lock is None:
        lock = _WitnessLock(name, threading.Lock(), False)
    return threading.Condition(lock)


# -- the wrapper -------------------------------------------------------------

class _WitnessLock(object):
    """Duck-typed threading.Lock/RLock shell that reports acquisitions.

    ``acquire`` accepts the positional ``(blocking, timeout)`` shapes the
    stdlib uses internally (``Condition._is_owned`` probes with
    ``acquire(0)``), and ``__enter__``/``__exit__`` make it a context
    manager, so it drops into every ``with lock:`` site unchanged.
    """

    __slots__ = ("name", "allow_dispatch", "_inner")

    def __init__(self, name, inner, allow_dispatch):
        self.name = name
        self.allow_dispatch = allow_dispatch
        self._inner = inner
        _registered[name] = _registered.get(name, 0) + 1

    def acquire(self, blocking=True, timeout=-1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            _note_acquired(self)
        return got

    def release(self):
        _note_released(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    # Condition(lock) support: the stdlib saves/restores through these
    # when the backing lock is an RLock; for our wrapper the plain
    # release/acquire pair keeps the bookkeeping exact.
    def _release_save(self):
        self.release()

    def _acquire_restore(self, state):
        self.acquire()

    def _is_owned(self):
        held = getattr(_tls, "held", None)
        if held:
            for e in held:
                if e[0] is self:
                    return True
        # fall back to the stdlib probe for holds recorded before the
        # witness was armed on this thread
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        return "<WitnessLock %s %s>" % (
            self.name, "locked" if self.locked() else "unlocked")


# -- bookkeeping -------------------------------------------------------------

def _held_list():
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
        _all_held[threading.get_ident()] = held
    return held


def _note_acquired(w):
    held = _held_list()
    for e in held:
        if e[0] is w:         # RLock reacquire: bump depth, no new edge
            e[2] += 1
            return
    if getattr(_tls, "busy", False):
        # witness reporting re-entered a witnessed lock (blackbox ring):
        # record nothing — a recursive report would deadlock on _wlock
        held.append([w, time.monotonic(), 1])
        return
    if held:
        _record_edges([e[0].name for e in held], w)
    held.append([w, time.monotonic(), 1])


def _note_released(w):
    held = getattr(_tls, "held", None)
    if not held:
        return
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is w:
            held[i][2] -= 1
            if held[i][2] <= 0:
                del held[i]
            return


def _record_edges(held_names, acquired):
    """Fold (held -> acquired) edges into the global graph; detect any
    cycle the new edges close. Lock discipline: graph mutation under a
    TIMED _wlock (drop the edge rather than block), reporting (metrics,
    blackbox) outside it under the thread-local busy flag."""
    new_cycles = []
    new_edge = False
    if not _wlock.acquire(timeout=_WLOCK_TIMEOUT):
        return
    try:
        tname = threading.current_thread().name
        for h in held_names:
            key = (h, acquired.name)
            if key in _edges:
                _edges[key] += 1
                continue
            _edges[key] = 1
            _edge_sites[key] = tname
            new_edge = True
            cyc = _find_cycle(acquired.name, h)
            if cyc is not None:
                ck = frozenset(zip(cyc, cyc[1:] + cyc[:1]))
                if ck not in _cycle_keys:
                    _cycle_keys.add(ck)
                    rec = {"cycle": cyc, "thread": tname}
                    _cycles.append(rec)
                    new_cycles.append(rec)
        n_edges = len(_edges)
    finally:
        _wlock.release()
    _tls.busy = True
    try:
        if new_edge:
            _edges_gauge.set(n_edges)
        for rec in new_cycles:
            _cycles_total.inc()
            from paddle_tpu.observability import blackbox

            if blackbox.ENABLED:
                blackbox.record("lock_order_cycle",
                                cycle=list(rec["cycle"]),
                                thread=rec["thread"])
    finally:
        _tls.busy = False


def _find_cycle(start, target):
    """DFS over _edges (held under _wlock by the caller): a path
    start -> ... -> target means the new (target -> start) edge closes a
    cycle; returns the node list [start, ..., target] or None."""
    succ = {}
    for (a, b) in _edges:
        succ.setdefault(a, []).append(b)
    stack = [(start, [start])]
    seen = set()
    while stack:
        node, path = stack.pop()
        if node == target:
            return path
        if node in seen:
            continue
        seen.add(node)
        for nxt in succ.get(node, ()):
            stack.append((nxt, path + [nxt]))
    return None


# -- dispatch / forensics hooks ----------------------------------------------

def note_dispatch():
    """Called by the executor immediately before handing a step to the
    device. A witnessed lock held RIGHT NOW by this thread (minus
    allow_dispatch registrations) is a hold spanning a device dispatch —
    the runtime twin of lint rule C002."""
    if not ENABLED:
        return
    held = getattr(_tls, "held", None)
    if not held:
        return
    names = [e[0].name for e in held if not e[0].allow_dispatch]
    if not names:
        return
    tname = threading.current_thread().name
    if _wlock.acquire(timeout=_WLOCK_TIMEOUT):
        try:
            _long_holds.append({"locks": names, "thread": tname})
        finally:
            _wlock.release()
    _tls.busy = True
    try:
        _long_holds_total.inc()
        from paddle_tpu.observability import blackbox

        if blackbox.ENABLED:
            blackbox.record("lock_held_across_dispatch", locks=names,
                            thread=tname)
    finally:
        _tls.busy = False


def held_by_thread():
    """ident -> [named locks held right now], live threads only. The
    blackbox dump annotation; lock-free (snapshot reads of per-thread
    lists, torn reads cost one stale line in a forensics dump)."""
    live = {t.ident for t in threading.enumerate()}
    out = {}
    for ident, held in list(_all_held.items()):
        if ident not in live:
            _all_held.pop(ident, None)   # dead thread: drop its slot
            continue
        names = [e[0].name for e in list(held)]
        if names:
            out[ident] = names
    return out


def registered_locks():
    """name -> construction count (the lock census a smoke can assert
    coverage against)."""
    with _wlock:
        return dict(_registered)


def report():
    """The witness verdict: edges, cycles, dispatch holds. What the
    witness-armed frontend smoke asserts on (zero cycles, zero long
    holds)."""
    if not _wlock.acquire(timeout=_WLOCK_TIMEOUT):
        return {"edges": {}, "cycles": [], "long_holds": [],
                "registered": {}, "degraded": True}
    try:
        return {
            "edges": {"%s -> %s" % k: v for k, v in _edges.items()},
            "cycles": [dict(c) for c in _cycles],
            "long_holds": [dict(h) for h in _long_holds],
            "registered": dict(_registered),
            "degraded": False,
        }
    finally:
        _wlock.release()


def _init_from_flags():
    from paddle_tpu import flags

    try:
        on = flags.get("lock_witness")
    except KeyError:  # pragma: no cover - flag table always has it
        on = False
    if on:
        enable()


_init_from_flags()
