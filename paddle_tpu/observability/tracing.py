"""Request-scoped distributed tracing for the serving plane.

One trace per request, minted by ``ServingClient`` (a random id plus the
client's send timestamp riding the JSON-lines envelope), continued by
``ServingFrontend`` and the decode session — so a single timeline covers
client send -> queue wait -> admission (slot pop, page acquisition,
prefix-cache hit depth) -> prefill -> every decode dispatch (tokens
committed, speculation accepted, COW copies) -> per-chunk wire flush.

The house overhead contract (telemetry.py's): ``ENABLED`` is a module
bool, flipped by ``FLAGS_request_tracing`` / :func:`enable`. Every hot
path guards on it, so OFF means one attribute read — no per-request
allocations, no wire bytes (the envelope only grows a ``trace`` field
when the CLIENT traces), no fresh-compile delta (tracing is host-side
only; it never touches a program or a feed).

Lifecycle: :func:`start` registers a :class:`Trace` in the in-flight
table (crash forensics: blackbox dumps list these ids); :func:`finish`
closes any still-open spans, derives the per-request SLO attribution
(TTFT, queue/prefill/decode split, inter-token latency distribution,
page-seconds held, tokens-from-speculation fraction, span coverage of
the client-observed wall) and banks the record in a bounded ring —
exported to ``<FLAGS_metrics_path>.traces.jsonl`` by
``telemetry.flush()`` and rendered by ``tools/trace_view.py``
(waterfall + Chrome/Perfetto JSON). Latency histograms carry the ids as
bucket exemplars, so a p99 bucket names a replayable request
(:meth:`metrics_registry.Histogram.observe` ``exemplar=``).

Preemption: a traced request's id lives in the session's
``rid -> trace_id`` binding, which rides the decode snapshot dialect —
a SIGTERM'd process's restored twin re-banks results under the ORIGINAL
ids (continuation traces carry ``origin="session"``).
"""

import json
import os
import threading
import time
from collections import deque

from paddle_tpu import flags
from paddle_tpu.observability import lock_witness
from paddle_tpu.observability.metrics_registry import (
    DECODE_BUCKETS,
    REGISTRY,
)

ENABLED = False

RING = 512  # completed traces kept for exemplar resolution / trace_view

_lock = lock_witness.make_lock("observability.tracing")
_inflight = {}                 # trace_id -> Trace
_completed = deque(maxlen=RING)

# inter-token gaps (consecutive chunk flushes of one stream), observed
# at finish() — ms-scale, hence the decode-resolution ladder
_intertoken_seconds = REGISTRY.histogram(
    "paddle_tpu_serving_intertoken_seconds",
    "gap between consecutive streamed token chunks of one traced "
    "request (observed at trace finish; DECODE_BUCKETS resolution)",
    buckets=DECODE_BUCKETS)


def enable(on=True):
    """Flip request tracing; OFF restores the untouched hot path."""
    global ENABLED
    ENABLED = bool(on)


def mint_id():
    """A fresh 16-hex-char trace id (random, not time-derived — ids
    must stay unique across the SIGTERM/restore process boundary)."""
    return os.urandom(8).hex()


class Trace(object):
    """One request's span timeline + accumulators. Mutated from both
    the handler thread (wire flush spans) and the decode worker
    (dispatch spans); list/dict mutation rides the GIL — the module
    lock only guards the in-flight/ring registries."""

    __slots__ = ("id", "origin", "endpoint", "t0", "t_client_send",
                 "spans", "marks", "acc", "baggage", "_root",
                 "_page_ts")

    def __init__(self, trace_id, endpoint, origin, t_client_send,
                 baggage):
        self.id = trace_id
        self.origin = origin
        self.endpoint = endpoint
        self.t0 = time.time()
        self.t_client_send = t_client_send
        self.spans = []
        self.marks = {}
        self.acc = {}
        self.baggage = dict(baggage) if baggage else {}
        self._root = None
        self._page_ts = None

    # -- span API -----------------------------------------------------------
    def begin(self, name, **meta):
        sp = {"name": name, "t0": time.time(), "t1": None,
              "meta": meta}
        self.spans.append(sp)
        return sp

    def end(self, sp, **meta):
        sp["t1"] = time.time()
        if meta:
            sp["meta"].update(meta)
        return sp

    def span(self, name, t0, t1, **meta):
        """Append an already-closed span (e.g. queue wait measured from
        an enqueue stamp)."""
        sp = {"name": name, "t0": float(t0), "t1": float(t1),
              "meta": meta}
        self.spans.append(sp)
        return sp

    def mark(self, name):
        """First-occurrence timestamp mark (e.g. ``first_token``)."""
        self.marks.setdefault(name, time.time())

    def bump(self, key, delta=1):
        """Accumulate a derived-stat counter (tokens, tokens_from_spec,
        cow_copies, ...)."""
        self.acc[key] = self.acc.get(key, 0) + delta

    def sample_pages(self, npages):
        """Integrate page-seconds held: called per decode dispatch and
        at release with the slot's CURRENT page count."""
        now = time.time()
        if self._page_ts is not None:
            self.acc["page_seconds"] = (
                self.acc.get("page_seconds", 0.0)
                + npages * (now - self._page_ts))
        self._page_ts = now


def start(trace_id=None, endpoint="generate", origin="frontend",
          t_client_send=None, baggage=None):
    """Register a new in-flight trace (root span opens immediately and
    closes at :func:`finish` — the whole server-side handling window is
    always covered). ``trace_id=None`` mints one."""
    tr = Trace(trace_id or mint_id(), endpoint, origin, t_client_send,
               baggage)
    tr._root = tr.begin("request", endpoint=endpoint, origin=origin)
    with _lock:
        _inflight[tr.id] = tr
    return tr


def inflight_get(trace_id):
    with _lock:
        return _inflight.get(trace_id)


def inflight_ids():
    with _lock:
        return sorted(_inflight)


def _percentile(vals, q):
    if not vals:
        return None
    vals = sorted(vals)
    k = max(0, min(len(vals) - 1,
                   int(round(q / 100.0 * len(vals) + 0.5)) - 1))
    return vals[k]


def _union_seconds(spans, t1_default):
    ivals = sorted((sp["t0"], sp["t1"] if sp["t1"] is not None
                    else t1_default) for sp in spans)
    total, hi = 0.0, None
    for a, b in ivals:
        if hi is None or a > hi:
            total += max(0.0, b - a)
            hi = b
        elif b > hi:
            total += b - hi
            hi = b
    return total


def finish(tr, outcome="ok", **meta):
    """Close the trace: force-close leaked spans (flagged in their
    meta — the cancel/disconnect tests sweep the ring for the flag),
    derive per-request stats, bank the record, drop the in-flight
    entry. Returns the record."""
    now = time.time()
    with _lock:
        _inflight.pop(tr.id, None)
    for sp in tr.spans:
        if sp["t1"] is None:
            sp["t1"] = now
            if sp is not tr._root:
                sp["meta"]["force_closed"] = True
    wall = max(now - tr.t0, 1e-9)
    client_wall = (max(now - tr.t_client_send, wall)
                   if tr.t_client_send is not None else wall)
    by_name = {}
    for sp in tr.spans:
        if sp is tr._root:
            continue
        by_name.setdefault(sp["name"], 0.0)
        by_name[sp["name"]] += sp["t1"] - sp["t0"]
    # inter-token gaps: consecutive chunk deliveries — wire flushes for
    # frontend streams, decode dispatches for in-process/session traces
    chunk_ts = sorted(sp["t1"] for sp in tr.spans
                      if sp["name"] == "wire.flush")
    if not chunk_ts:
        chunk_ts = sorted(sp["t1"] for sp in tr.spans
                          if sp["name"] == "decode.step")
    gaps = [b - a for a, b in zip(chunk_ts, chunk_ts[1:])]
    for g in gaps:
        _intertoken_seconds.observe(g, exemplar=tr.id)
    first = tr.marks.get("first_token")
    tokens = tr.acc.get("tokens", 0)
    spec = tr.acc.get("tokens_from_spec", 0)
    stats = {
        "wall_s": round(wall, 6),
        "client_wall_s": round(client_wall, 6),
        "ttft_s": (round(first - (tr.t_client_send
                                  if tr.t_client_send is not None
                                  else tr.t0), 6)
                   if first is not None else None),
        "queue_s": round(by_name.get("queue", 0.0), 6),
        "admit_s": round(by_name.get("admit", 0.0), 6),
        "prefill_s": round(by_name.get("prefill", 0.0), 6),
        "decode_s": round(by_name.get("decode.step", 0.0), 6),
        "flush_s": round(by_name.get("wire.flush", 0.0), 6),
        "intertoken_p50_ms": (round(_percentile(gaps, 50) * 1e3, 3)
                              if gaps else None),
        "intertoken_p95_ms": (round(_percentile(gaps, 95) * 1e3, 3)
                              if gaps else None),
        "intertoken_max_ms": (round(max(gaps) * 1e3, 3)
                              if gaps else None),
        "tokens": tokens,
        "tokens_from_spec": spec,
        "spec_fraction": (round(spec / float(tokens), 4)
                          if tokens else None),
        "page_seconds": round(tr.acc.get("page_seconds", 0.0), 6),
        "cow_copies": tr.acc.get("cow_copies", 0),
        # the acceptance number: fraction of the CLIENT-observed wall
        # the trace's spans account for (root span == the server-side
        # handling window; the remainder is wire + client scheduling)
        "span_coverage": round(
            min(1.0, _union_seconds(tr.spans, now) / client_wall), 4),
    }
    rec = {
        "trace_id": tr.id,
        "endpoint": tr.endpoint,
        "origin": tr.origin,
        "outcome": outcome,
        "t0": tr.t0,
        "t1": now,
        "t_client_send": tr.t_client_send,
        "stats": stats,
        "spans": tr.spans,
        "baggage": tr.baggage,
    }
    if meta:
        rec.update(meta)
    with _lock:
        _completed.append(rec)
    return rec


def get(trace_id):
    """Resolve a trace id (e.g. a histogram exemplar) to its completed
    ring record, newest first; None when it aged out."""
    with _lock:
        for rec in reversed(_completed):
            if rec["trace_id"] == trace_id:
                return rec
    return None


def completed():
    with _lock:
        return list(_completed)


def write_traces_jsonl(path):
    """One JSON line per completed trace; returns the record count."""
    with _lock:
        recs = list(_completed)
    with open(path, "w") as f:
        for rec in recs:
            f.write(json.dumps(rec) + "\n")
    return len(recs)


def perfetto_events(rec, row=0, pid=1):
    """One completed record -> Chrome/Perfetto ``traceEvents`` (complete
    'X' events, microsecond timestamps; ``row`` is the track the
    request renders on). Shared by tools/trace_view.py and the smoke's
    validity check."""
    events = [{
        "name": "trace %s" % rec["trace_id"], "ph": "M",
        "pid": pid, "tid": row, "cat": "__metadata",
        "ts": 0, "args": {"name": rec["trace_id"]},
    }]
    for sp in rec["spans"]:
        args = {"trace_id": rec["trace_id"]}
        args.update(sp.get("meta") or {})
        events.append({
            "name": sp["name"], "ph": "X", "cat": "serving",
            "pid": pid, "tid": row,
            "ts": round(sp["t0"] * 1e6, 3),
            "dur": round(max(sp["t1"] - sp["t0"], 0.0) * 1e6, 3),
            "args": args,
        })
    return events


def reset():
    """Drop every in-flight and completed trace (tests)."""
    with _lock:
        _inflight.clear()
        _completed.clear()


def _init_from_flags():
    try:
        enable(bool(flags.get("request_tracing")))
    except Exception:
        pass


_init_from_flags()
