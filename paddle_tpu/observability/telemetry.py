"""Step telemetry: per-step flight data for the executors.

``Executor.run`` / ``run_async`` / ``run_multi_step`` and
``ParallelExecutor.run`` call :func:`record_step` with wall time,
feed/fetch byte counts, host->device transfer seconds and the compiled
program's structural fingerprint. Each record lands in a bounded ring
buffer (for ``step_stats`` percentiles and the JSONL snapshot) and in the
process metrics registry (for the Prometheus scrape).

MFU: executors register analytic FLOP counts per compiled executable
(:func:`register_flops`, keyed by ``cp._exec_cache_key``; the estimate
reuses tools/hlo_cost_model.py's jaxpr walker over the exact traced step
function, run AFTER the first timed step), so ``step_stats()['mfu']`` is
sum(flops)/sum(wall)/peak over the recorded window — the
roofline-accounting discipline TPU codesign work leans on.

Overhead contract: every hook in the executors guards on the module-level
bool ``ENABLED`` (one attribute load, no dict lookups, no function call)
so the hot path with telemetry off is unchanged. ``FLAGS_telemetry=1``
turns it on at import; :func:`enable` flips it at runtime.
"""

import atexit
import collections
import threading
import time

from paddle_tpu.observability import memory as _memory
from paddle_tpu.observability import lock_witness
from paddle_tpu.observability.metrics_registry import REGISTRY

__all__ = [
    "ENABLED", "enable", "reset", "record_step", "register_flops",
    "step_stats", "step_records", "add_step_callback",
    "remove_step_callback", "StepTimer", "record_fetch_materialize",
    "flush", "estimate_flops", "device_memory_bytes", "peak_flops",
    "executable_fingerprint", "capture_step_avals",
    "register_flops_from_avals", "record_device_steps",
    "record_device_transfer", "record_pipeline_occupancy",
    "device_step_times", "device_label",
]

ENABLED = False

_RING_CAP = 4096

_lock = lock_witness.make_lock("observability.telemetry")
_records = collections.deque(maxlen=_RING_CAP)
_flops = {}              # fingerprint -> flops per step
_callbacks = []

# bf16 peak TFLOP/s per chip for MFU accounting (bench.py's table).
_PEAK_TFLOPS = {"tpu v5 lite": 197.0, "tpu v5e": 197.0, "tpu v4": 275.0,
                "tpu v6 lite": 918.0, "tpu v6e": 918.0}

# step-time buckets: 100us .. 100s (training steps span ms..minutes)
_STEP_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                 50.0, 100.0)
# async-fetch materialize: dominated by device wait + d2h transfer
_FETCH_BUCKETS = (0.00001, 0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1,
                  0.5, 1.0, 5.0, 10.0)

_steps_total = REGISTRY.counter(
    "paddle_tpu_steps_total", "program steps executed", labels=("executor",))
_step_seconds = REGISTRY.histogram(
    "paddle_tpu_step_seconds", "per-step wall time (seconds)",
    labels=("executor",), buckets=_STEP_BUCKETS)
_feed_bytes = REGISTRY.counter(
    "paddle_tpu_feed_bytes_total", "bytes fed host->device")
_fetch_bytes = REGISTRY.counter(
    "paddle_tpu_fetch_bytes_total", "bytes fetched device->host")
_h2d_seconds = REGISTRY.counter(
    "paddle_tpu_h2d_seconds_total", "wall seconds in feed transfers")
_fetch_materialize = REGISTRY.histogram(
    "paddle_tpu_fetch_materialize_seconds",
    "async-fetch dispatch-to-numpy latency", buckets=_FETCH_BUCKETS)
_device_mem = REGISTRY.gauge(
    "paddle_tpu_device_bytes_in_use",
    "device memory in use, summed over all local devices (bytes)")
# -- per-device series (the multichip incident-response surface): one
# labeled series per local device, plus a straggler ratio. All written
# only from the telemetry-guarded paths — zero cost with the flag off.
_device_mem_per = REGISTRY.gauge(
    "paddle_tpu_device_bytes_in_use_per_device",
    "device memory in use, one series per local device (bytes)",
    labels=("device",))
_device_step_seconds = REGISTRY.gauge(
    "paddle_tpu_device_step_seconds",
    "last dispatch->shard-ready latency per device (seconds)",
    labels=("device",))
_device_transfer = REGISTRY.counter(
    "paddle_tpu_device_transfer_bytes_total",
    "feed bytes landed per device (addressable shard sizes)",
    labels=("device",))
_straggler = REGISTRY.gauge(
    "paddle_tpu_device_step_imbalance",
    "straggler ratio: max/median per-device step time of the last "
    "recorded parallel step (1.0 = perfectly balanced)")
_stage_occupancy = REGISTRY.gauge(
    "paddle_tpu_pipeline_stage_occupancy",
    "fraction of schedule ticks each pipeline stage does useful work "
    "(M/(M+S-1) for a GPipe schedule)", labels=("stage",))
_hbm_peak = REGISTRY.gauge(
    "paddle_tpu_hbm_peak_bytes",
    "per-step high-water mark of ledger-tracked live bytes "
    "(observability/memory.py watermark of the last recorded step)")


def enable(on=True):
    """Flip telemetry at runtime (tests, notebooks). The flag only sets
    the import-time default. The live-buffer ledger
    (observability/memory.py) switches in lockstep — memory accounting
    is part of the same flight recorder and the same overhead contract."""
    global ENABLED
    ENABLED = bool(on)
    _memory.enable(ENABLED)
    return ENABLED


def _init_from_flags():
    from paddle_tpu import flags

    try:
        enable(flags.get("telemetry"))
    except KeyError:  # pragma: no cover - flag table always has it
        pass


def reset(flops=False):
    """Drop the ring buffer (phase-scoped measurement, e.g.
    tools/step_breakdown.py). The per-fingerprint FLOP table survives by
    default — executables register it once per compile
    (cp._telemetry_flops_done), so clearing it would leave MFU None for
    the rest of the process; pass ``flops=True`` only when also tearing
    down the compiled programs (tests)."""
    with _lock:
        _records.clear()
        if flops:
            _flops.clear()


def register_flops(fingerprint, flops):
    """Record the analytic FLOPs of one compiled step. The key must be
    per-EXECUTABLE (``cp._exec_cache_key``: structural fingerprint x feed
    specs x fetch set), not per-program: two feed shapes of one program
    do different FLOPs, and a program-level key would let the last
    compile's count misprice every other shape's steps."""
    if fingerprint and flops:
        with _lock:
            _flops[fingerprint] = float(flops)


def executable_fingerprint(cp, program=None):
    """The telemetry key for one compiled executable: its cross-process
    cache key when stamped (always, for executor-built programs), else
    the program's structural fingerprint."""
    key = getattr(cp, "_exec_cache_key", None)
    if key:
        return key
    if program is not None:
        from paddle_tpu.core.fingerprint import program_fingerprint

        return program_fingerprint(program)
    return None


def capture_step_avals(cp, state, feeds, key):
    """Aval snapshot for the deferred FLOP estimate, taken BEFORE the
    step call (which donates the mutable state buffers). One-shot per
    executable via ``cp._telemetry_flops_done``; returns None when
    already registered. Shared by Executor and ParallelExecutor."""
    if getattr(cp, "_telemetry_flops_done", False):
        return None
    cp._telemetry_flops_done = True
    import jax

    aval = jax.ShapeDtypeStruct
    return (
        {n: aval(state[n].shape, state[n].dtype)
         for n in cp.mutable_state},
        {n: aval(state[n].shape, state[n].dtype)
         for n in cp.frozen_state},
        {n: aval(v.shape, v.dtype) for n, v in feeds.items()},
        aval(key.shape, key.dtype),
    )


def register_flops_from_avals(cp, fingerprint, avals, steps=1):
    """Run the (re-trace) FLOP estimate and file it — call AFTER the
    timed step so the trace never pollutes the recorded wall time."""
    est = estimate_flops(cp.jitted, avals)
    if est:
        register_flops(fingerprint, est / max(1, steps))


def add_step_callback(fn):
    """Trainer hook: ``fn(record_dict)`` runs after every recorded step
    (loss-curve dashboards, slow-step alarms). Exceptions are swallowed —
    a broken callback must not take down training."""
    with _lock:
        if fn not in _callbacks:
            _callbacks.append(fn)


def remove_step_callback(fn):
    with _lock:
        if fn in _callbacks:
            _callbacks.remove(fn)


def device_label(d):
    """THE stable per-device metric label ('tpu:3', 'cpu:0'), matching
    the explainer's device component. Single definition — mesh.py
    re-exports it — so per-device series from telemetry, transfer and
    mesh metrics always join on the same key."""
    return "%s:%d" % (d.platform, d.id)


_device_label = device_label


def device_memory_bytes(per_device=False):
    """Bytes in use summed over ALL local devices (the old behavior
    sampled only device 0 — on a multichip mesh that under-reported by
    the device count and hid per-chip OOM pressure). ``per_device=True``
    returns a {label: bytes} dict instead. None / {} when the backend
    does not report (CPU, older runtimes)."""
    out = {}
    try:
        import jax

        for d in jax.local_devices():
            stats = d.memory_stats()
            if stats:
                out[_device_label(d)] = int(stats.get("bytes_in_use", 0))
    except Exception:
        pass
    if per_device:
        return out
    return sum(out.values()) or None


def device_step_times(arrays, t_dispatch):
    """Per-device dispatch->ready latency of one parallel step.

    Walks the first fetched/state array that has addressable shards and
    blocks on each device's shard in turn, recording the elapsed time at
    which it became ready. A healthy mesh returns near-identical times;
    a straggling chip shows up as the max. (Sequential blocking means a
    device that finished earlier than the one before it reads as that
    earlier wall — the MAX and the imbalance ratio are exact, the
    per-device floor is an upper bound. Good enough to NAME the
    straggler, which is the incident-response question.)"""
    import jax

    times = {}
    for a in arrays:
        if not isinstance(a, jax.Array):
            continue
        try:
            shards = a.addressable_shards
        except Exception:
            continue
        if len(shards) < 2:
            continue
        for sh in shards:
            label = _device_label(sh.device)
            if label not in times:
                sh.data.block_until_ready()
                times[label] = time.perf_counter() - t_dispatch
        if times:
            break
    return times


def record_device_steps(times):
    """File one parallel step's per-device ready times (seconds) into
    the labeled gauge, and refresh the straggler ratio (max/median)."""
    if not times:
        return None
    for label, t in times.items():
        _device_step_seconds.set(t, device=label)
    vals = sorted(times.values())
    mid = len(vals) // 2
    median = vals[mid] if len(vals) % 2 else (vals[mid - 1] + vals[mid]) / 2.0
    ratio = (vals[-1] / median) if median > 0 else 1.0
    _straggler.set(ratio)
    return ratio


def record_device_transfer(bytes_by_device):
    """Count feed bytes against the device that received them
    (``{label: bytes}`` — how much of the host->device transfer each
    chip actually took, the lens that catches a feed pipeline sending a
    replicated tensor it meant to shard)."""
    for label, b in (bytes_by_device or {}).items():
        if b:
            _device_transfer.inc(int(b), device=label)


def record_pipeline_occupancy(n_stages, n_micro):
    """GPipe schedule occupancy: each stage does useful work on M of the
    M+S-1 ticks. One labeled series per stage so dashboards show the
    bubble fraction next to the per-device series."""
    n_stages, n_micro = int(n_stages), int(n_micro)
    if n_stages <= 0 or n_micro <= 0:
        return None
    occ = float(n_micro) / float(n_micro + n_stages - 1)
    for s in range(n_stages):
        _stage_occupancy.set(occ, stage="%d" % s)
    return occ


def record_step(executor, wall_s, steps=1, feed_bytes=0, fetch_bytes=0,
                h2d_seconds=0.0, fingerprint=None, dispatch_only=False,
                device_times=None):
    """One executed dispatch: ``steps`` program steps in ``wall_s``
    seconds (run_multi_step dispatches K at once). ``dispatch_only``
    marks async dispatches whose wall time is host latency, NOT step
    duration — they count in ``steps_total`` but are excluded from
    ``step_stats`` percentiles and MFU (a microsecond dispatch with a
    registered FLOP count would otherwise report MFU >> 1). Callers
    guard on ``ENABLED`` themselves; calling this directly always
    records."""
    steps = max(1, int(steps))
    per_step = wall_s / steps
    rec = {
        "ts": time.time(),
        "executor": executor,
        "wall_s": wall_s,
        "steps": steps,
        "step_s": per_step,
        "feed_bytes": int(feed_bytes),
        "fetch_bytes": int(fetch_bytes),
        "h2d_seconds": h2d_seconds,
        "fingerprint": fingerprint,
        "dispatch_only": bool(dispatch_only),
    }
    if device_times:
        rec["device_times"] = {k: float(v) for k, v in device_times.items()}
        record_device_steps(device_times)
    # HBM trajectory: the ledger's per-step watermark (measured), the
    # registered plan's prediction, and the top holders — so the step
    # JSONL carries the memory story tools/step_breakdown.py --memory
    # reads offline
    peak = _memory.take_step_peak()
    if peak:
        rec["peak_hbm_bytes"] = int(peak)
        _hbm_peak.set(peak)
    pred = _memory.predicted_peak(fingerprint)
    if pred:
        rec["predicted_peak_bytes"] = int(pred)
    top = _memory.top_holders(3)
    if top:
        rec["hbm_top"] = [[h["name"], h["kind"], h["bytes"]] for h in top]
    mem_per = device_memory_bytes(per_device=True)
    if mem_per:
        for label, b in mem_per.items():
            _device_mem_per.set(b, device=label)
        rec["device_bytes_in_use"] = sum(mem_per.values())
        _device_mem.set(rec["device_bytes_in_use"])
    with _lock:
        _records.append(rec)
        callbacks = list(_callbacks)
    _steps_total.inc(steps, executor=executor)
    _step_seconds.observe(per_step, executor=executor)
    if feed_bytes:
        _feed_bytes.inc(int(feed_bytes))
    if fetch_bytes:
        _fetch_bytes.inc(int(fetch_bytes))
    if h2d_seconds:
        _h2d_seconds.inc(h2d_seconds)
    for fn in callbacks:
        try:
            fn(dict(rec))
        except Exception:
            pass
    return rec


def record_fetch_materialize(seconds):
    """FetchHandle.result() latency: dispatch -> numpy in hand."""
    _fetch_materialize.observe(seconds)


def step_records():
    with _lock:
        return [dict(r) for r in _records]


def _percentile(sorted_vals, q):
    """Nearest-rank percentile: the smallest value with at least q% of
    the sample at or below it (conservative, no interpolation)."""
    if not sorted_vals:
        return None
    import math

    k = max(0, min(len(sorted_vals) - 1,
                   int(math.ceil(q / 100.0 * len(sorted_vals))) - 1))
    return sorted_vals[k]


def peak_flops(device=None):
    """Peak FLOP/s for MFU accounting: FLAGS_peak_tflops override first,
    then the chip table keyed on device_kind; None when unknown (CPU)."""
    from paddle_tpu import flags

    try:
        override = float(flags.get("peak_tflops"))
    except (KeyError, TypeError, ValueError):
        override = 0.0
    if override > 0:
        return override * 1e12
    try:
        import jax

        device = device or jax.local_devices()[0]
        kind = (getattr(device, "device_kind", "") or "").lower()
        for k, v in _PEAK_TFLOPS.items():
            if k in kind:
                return v * 1e12
    except Exception:
        pass
    return None


def step_stats(peak=None):
    """Percentiles + MFU over the recorded window.

    Returns ``{"count", "p50_ms", "p95_ms", "p99_ms", "mean_ms",
    "total_s", "flops_per_sec", "mfu", "peak_flops"}``. ``mfu`` is
    None when no recorded step has a registered FLOP count or no peak is
    known (pass ``peak`` in FLOP/s, or set ``FLAGS_peak_tflops``).
    """
    with _lock:
        recs = list(_records)
        flops_map = dict(_flops)
    # async dispatches measure host latency, not step time: they count,
    # but their wall must not enter percentiles or MFU
    timed = [r for r in recs if not r.get("dispatch_only")]
    per_step = sorted(r["step_s"] for r in timed)
    out = {
        "count": sum(r["steps"] for r in recs),
        "p50_ms": None, "p95_ms": None, "p99_ms": None, "mean_ms": None,
        "total_s": sum(r["wall_s"] for r in recs),
        "flops_per_sec": None, "mfu": None,
        "peak_flops": peak if peak else peak_flops(),
    }
    if per_step:
        out["p50_ms"] = _percentile(per_step, 50) * 1e3
        out["p95_ms"] = _percentile(per_step, 95) * 1e3
        out["p99_ms"] = _percentile(per_step, 99) * 1e3
        out["mean_ms"] = sum(per_step) / len(per_step) * 1e3
    known = [(r, flops_map[r["fingerprint"]]) for r in timed
             if r.get("fingerprint") in flops_map]
    if known:
        total_flops = sum(f * r["steps"] for r, f in known)
        total_wall = sum(r["wall_s"] for r, _ in known)
        if total_wall > 0:
            out["flops_per_sec"] = total_flops / total_wall
            if out["peak_flops"]:
                out["mfu"] = out["flops_per_sec"] / out["peak_flops"]
    return out


class StepTimer(object):
    """Context-manager hook for trainers driving their own loop::

        with telemetry.StepTimer("trainer", feed_bytes=nbytes):
            loss = exe.run(...)

    Records one step on exit (even when the body raises, so hung-step
    forensics still see the attempt's duration)."""

    def __init__(self, executor="trainer", steps=1, feed_bytes=0,
                 fetch_bytes=0, fingerprint=None):
        self.executor = executor
        self.steps = steps
        self.feed_bytes = feed_bytes
        self.fetch_bytes = fetch_bytes
        self.fingerprint = fingerprint
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        record_step(self.executor, time.perf_counter() - self._t0,
                    steps=self.steps, feed_bytes=self.feed_bytes,
                    fetch_bytes=self.fetch_bytes,
                    fingerprint=self.fingerprint)
        return False


# -- FLOP estimation ---------------------------------------------------------

def estimate_flops(fn, args):
    """Analytic FLOPs of one call of ``fn(*args)``: trace to a jaxpr and
    walk it with tools/hlo_cost_model.py's fusion-aware counter (DCE+CSE
    first — vjp re-traces would double-count the forward). Returns None
    on any failure; this is best-effort accounting, never load-bearing."""
    try:
        import jax

        from paddle_tpu.observability import _cost_model

        closed = jax.make_jaxpr(fn)(*args)
        jaxpr = closed.jaxpr
        # jit-wrapped fns trace to a single pjit eqn; unwrap so the
        # optimizer's top-level DCE+CSE sees the real op stream
        while (len(jaxpr.eqns) == 1
               and jaxpr.eqns[0].primitive.name in ("pjit", "jit")):
            inner = jaxpr.eqns[0].params.get("jaxpr")
            if inner is None:
                break
            jaxpr = getattr(inner, "jaxpr", inner)
        mod = _cost_model.load()
        return float(mod.sum_flops_recursive(mod.optimize_jaxpr(jaxpr)))
    except Exception:
        return None


# -- export ------------------------------------------------------------------

def write_steps_jsonl(path, mode="w"):
    """One JSON line per recorded step — the snapshot format
    tools/step_breakdown.py consumes."""
    import json

    recs = step_records()
    with open(path, mode) as f:
        for r in recs:
            f.write(json.dumps(r, sort_keys=True) + "\n")
    return len(recs)


def flush(metrics_path=None):
    """Write the Prometheus scrape to ``metrics_path`` (default:
    ``FLAGS_metrics_path``), the step JSONL next to it
    (``<path>.steps.jsonl``), and — when request tracing banked any
    completed traces — the trace JSONL (``<path>.traces.jsonl``, the
    file tools/trace_view.py and step_breakdown --requests consume).
    No-op when no path is configured."""
    if metrics_path is None:
        from paddle_tpu import flags

        try:
            metrics_path = flags.get("metrics_path")
        except KeyError:  # pragma: no cover
            metrics_path = ""
    if not metrics_path:
        return None
    REGISTRY.write_prometheus(metrics_path)
    write_steps_jsonl(metrics_path + ".steps.jsonl")
    from paddle_tpu.observability import tracing

    if tracing.completed():
        tracing.write_traces_jsonl(metrics_path + ".traces.jsonl")
    from paddle_tpu.observability import step_profiler

    if step_profiler.records():
        step_profiler.write_stepprof_jsonl(
            metrics_path + ".stepprof.jsonl")
    return metrics_path


@atexit.register
def _flush_at_exit():
    try:
        flush()
    except Exception:
        pass


_init_from_flags()
