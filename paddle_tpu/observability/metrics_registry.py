"""Process-global metrics registry: counters, gauges, histograms.

The substrate every other observability piece writes to. Design points:

* Thread-safe: one registry lock guards metric creation; each metric
  guards its own label->value map (run_async donation threads, Predictor
  clone threads and the main loop all write concurrently).
* Labels are plain keyword dicts; a metric's label NAMES are fixed at
  registration (Prometheus contract), values vary per observation.
* Histograms use fixed upper bounds chosen at registration — no dynamic
  rebucketing, so ``observe`` is O(len(buckets)) with no allocation.
* Export: Prometheus text format 0.0.4 (``to_prometheus`` /
  ``write_prometheus``) and a JSONL snapshot (``write_jsonl``) for
  offline tools (tools/step_breakdown.py).
* Collectors: ``register_collector(fn)`` adds a scrape-time callback
  yielding ``(name, type, help, [(labels, value)])`` tuples — how
  counters owned elsewhere (core/exec_cache.py) appear in the scrape
  without double bookkeeping.

The reference kept nothing like this in-tree (its metrics.py is model
accuracy tracking); the design follows the TensorFlow production lesson
(Abadi et al., 2016) that the metrics substrate belongs in the framework.
"""

import json
import os
import threading
import time

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "DEFAULT_BUCKETS", "SERVING_BUCKETS", "DECODE_BUCKETS",
]

# Latency-ish default buckets (seconds): 100us .. 60s, roughly x3 steps.
DEFAULT_BUCKETS = (
    0.0001, 0.0003, 0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0,
    30.0, 60.0,
)

# Request-SLO buckets (seconds) for the serving layer: finer in the
# 0.5ms-250ms band where inference p99s live, so a histogram scrape can
# localize an SLO breach the coarse DEFAULT_BUCKETS would smear.
SERVING_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)

# Decode-resolution buckets (seconds): the streaming plane's numbers —
# inter-token gaps and per-dispatch decode latencies — live in the
# 100us-10ms band (bench decode leg: ~0.33ms/token on the CPU proxy)
# where even SERVING_BUCKETS' 0.5ms floor smears everything into two
# buckets. Sub-ms ladder below, SERVING-compatible tail above so one
# scrape still localizes an outlier stream.
DECODE_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 1.0, 5.0,
)


def _label_key(label_names, labels):
    labels = labels or {}
    extra = set(labels) - set(label_names)
    if extra:
        raise ValueError(
            "unknown label(s) %s (declared: %s)"
            % (sorted(extra), list(label_names)))
    return tuple((n, str(labels.get(n, ""))) for n in label_names)


def _fmt_value(v):
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return repr(v)
    return repr(float(v)) if isinstance(v, float) else str(v)


def _fmt_labels(pairs, extra=()):
    items = [(k, v) for k, v in pairs] + list(extra)
    if not items:
        return ""
    body = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", "\\\\").replace('"', '\\"'))
        for k, v in items)
    return "{%s}" % body


class _Metric(object):
    kind = None

    def __init__(self, name, help_text, label_names):
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        # deliberately NOT a lock_witness factory: the witness reports
        # through these very metrics — wrapping them would recurse
        self._lock = threading.Lock()
        self._values = {}  # label key tuple -> value

    def _series(self):
        """Consistent copy for export. Scalar values copy shallow; the
        Histogram override deep-copies its state dicts — the exporter
        reads count several times per series, and a concurrent observe()
        between those reads would emit a scrape where bucket{+Inf},
        _count and _sum disagree."""
        with self._lock:
            return dict(self._values)


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount=1, **labels):
        if amount < 0:
            raise ValueError("counter can only increase (got %r)" % amount)
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def value(self, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._values.get(key, 0)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._values[key] = value

    def inc(self, amount=1, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def dec(self, amount=1, **labels):
        self.inc(-amount, **labels)

    def value(self, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._values.get(key, 0)


class Histogram(_Metric):
    """Fixed-bucket cumulative histogram (Prometheus semantics)."""

    kind = "histogram"

    def __init__(self, name, help_text, label_names, buckets):
        super(Histogram, self).__init__(name, help_text, label_names)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bs

    def observe(self, value, exemplar=None, **labels):
        """``exemplar``: an opaque id (a trace id) remembered for the
        NARROWEST bucket the value lands in — last writer wins per
        bucket, so a scrape's p99 bucket names a recent replayable
        request (observability/tracing.py resolves it against the
        completed-trace ring). Exemplars ride the JSON snapshot only;
        the text exposition stays plain 0.0.4."""
        key = _label_key(self.label_names, labels)
        value = float(value)
        with self._lock:
            st = self._values.get(key)
            if st is None:
                st = {"count": 0, "sum": 0.0,
                      "buckets": [0] * len(self.buckets)}
                self._values[key] = st
            st["count"] += 1
            st["sum"] += value
            counts = st["buckets"]
            narrowest = len(self.buckets)  # +Inf overflow bucket
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
                    narrowest = min(narrowest, i)
            if exemplar is not None:
                st.setdefault("exemplars", {})[narrowest] = {
                    "id": str(exemplar), "value": value,
                    "ts": time.time()}

    def exemplars(self, **labels):
        """{bucket_index: {"id", "value", "ts"}} for one series —
        index len(buckets) is the +Inf overflow bucket."""
        key = _label_key(self.label_names, labels)
        with self._lock:
            st = self._values.get(key)
            if st is None:
                return {}
            return {i: dict(e)
                    for i, e in (st.get("exemplars") or {}).items()}

    def snapshot(self, **labels):
        key = _label_key(self.label_names, labels)
        with self._lock:
            st = self._values.get(key)
            if st is None:
                return {"count": 0, "sum": 0.0,
                        "buckets": [0] * len(self.buckets)}
            out = {"count": st["count"], "sum": st["sum"],
                   "buckets": list(st["buckets"])}
            if st.get("exemplars"):
                out["exemplars"] = {i: dict(e)
                                    for i, e in st["exemplars"].items()}
            return out

    def _series(self):
        with self._lock:
            out = {}
            for key, st in self._values.items():
                entry = {"count": st["count"], "sum": st["sum"],
                         "buckets": list(st["buckets"])}
                if st.get("exemplars"):
                    entry["exemplars"] = {
                        i: dict(e) for i, e in st["exemplars"].items()}
                out[key] = entry
            return out


class MetricsRegistry(object):
    def __init__(self):
        # plain on purpose — see the per-metric lock note above
        self._lock = threading.Lock()
        self._metrics = {}      # name -> metric, insertion-ordered
        self._order = []
        self._collectors = []

    def _register(self, cls, name, help_text, labels, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.label_names != tuple(labels):
                    raise ValueError(
                        "metric %r re-registered with a different type or "
                        "label set" % name)
                return m
            m = cls(name, help_text, tuple(labels), **kw)
            self._metrics[name] = m
            self._order.append(name)
            return m

    def counter(self, name, help_text="", labels=()):
        return self._register(Counter, name, help_text, labels)

    def gauge(self, name, help_text="", labels=()):
        return self._register(Gauge, name, help_text, labels)

    def histogram(self, name, help_text="", labels=(),
                  buckets=DEFAULT_BUCKETS):
        return self._register(Histogram, name, help_text, labels,
                              buckets=buckets)

    def register_collector(self, fn):
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def reset(self):
        """Zero every metric's series, keeping registrations alive:
        modules bind metric handles once at import (telemetry, explain,
        inference), so dropping the registration would orphan those
        handles — they would keep incrementing objects no scrape can see.
        Collectors stay."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            with m._lock:
                m._values.clear()

    # -- export --------------------------------------------------------------
    def _collected(self):
        """[(name, kind, help, [(label_pairs, value)])] from collectors."""
        with self._lock:
            collectors = list(self._collectors)
        out = []
        for fn in collectors:
            try:
                for name, kind, help_text, series in fn():
                    out.append((
                        name, kind, help_text,
                        [(tuple(sorted(lbl.items())), v)
                         for lbl, v in series]))
            except Exception:
                # a broken collector must never take down the scrape
                continue
        return out

    def to_prometheus(self):
        """Prometheus text exposition format 0.0.4."""
        lines = []
        with self._lock:
            metrics = [self._metrics[n] for n in self._order]
        for m in metrics:
            lines.append("# HELP %s %s" % (m.name, m.help or m.name))
            lines.append("# TYPE %s %s" % (m.name, m.kind))
            series = sorted(m._series().items())
            if m.kind == "histogram":
                for key, st in series:
                    cum = 0
                    for bound, c in zip(m.buckets, st["buckets"]):
                        cum = c
                        lines.append("%s_bucket%s %s" % (
                            m.name,
                            _fmt_labels(key, [("le", _fmt_value(bound))]),
                            cum))
                    lines.append("%s_bucket%s %s" % (
                        m.name, _fmt_labels(key, [("le", "+Inf")]),
                        st["count"]))
                    lines.append("%s_sum%s %s" % (
                        m.name, _fmt_labels(key), _fmt_value(st["sum"])))
                    lines.append("%s_count%s %s" % (
                        m.name, _fmt_labels(key), st["count"]))
            else:
                for key, v in series:
                    lines.append("%s%s %s" % (
                        m.name, _fmt_labels(key), _fmt_value(v)))
        for name, kind, help_text, series in self._collected():
            lines.append("# HELP %s %s" % (name, help_text or name))
            lines.append("# TYPE %s %s" % (name, kind))
            for key, v in sorted(series):
                lines.append("%s%s %s" % (name, _fmt_labels(key),
                                          _fmt_value(v)))
        return "\n".join(lines) + "\n"

    def snapshot(self):
        """One JSON-able dict of every series (registry + collectors)."""
        out = {"ts": time.time(), "metrics": {}}
        with self._lock:
            metrics = [self._metrics[n] for n in self._order]
        for m in metrics:
            series = []
            for key, v in sorted(m._series().items()):
                entry = {"labels": dict(key)}
                if m.kind == "histogram":
                    entry.update(count=v["count"], sum=v["sum"],
                                 buckets=list(v["buckets"]))
                    if v.get("exemplars"):
                        # JSON keys must be strings; bucket index keys
                        # stringify (index == len(bounds) is +Inf)
                        entry["exemplars"] = {
                            str(i): e for i, e in v["exemplars"].items()}
                else:
                    entry["value"] = v
                series.append(entry)
            rec = {"type": m.kind, "series": series}
            if m.kind == "histogram":
                rec["bucket_bounds"] = list(m.buckets)
            out["metrics"][m.name] = rec
        for name, kind, _help, series in self._collected():
            out["metrics"].setdefault(name, {"type": kind, "series": []})[
                "series"].extend(
                    {"labels": dict(key), "value": v} for key, v in series)
        return out

    def write_prometheus(self, path):
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.to_prometheus())
        os.replace(tmp, path)  # atomic: scrapers see old or new, not torn

    def write_jsonl(self, path, mode="a"):
        """Append one snapshot line (JSONL: a time series of scrapes)."""
        with open(path, mode) as f:
            f.write(json.dumps(self.snapshot(), sort_keys=True) + "\n")


REGISTRY = MetricsRegistry()
