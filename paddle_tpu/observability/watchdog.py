"""Hang watchdog: declare, diagnose, and (optionally) break a stall.

A training job that hangs on a collective (one host of a multi-host mesh
died), a fetch that never materializes (wedged TPU tunnel), a deadlocked
input pipeline — these produce NO output at all: no exception, no log
line, just burned accelerator-hours. The reference's ExceptionHolder
(framework/details/exception_holder.h) only re-raises errors its workers
DID raise; this module covers the silent case.

Design: executors/fetch paths *arm* the watchdog around potentially
blocking work and report *progress* on completion (both guarded by the
module bool ``ENABLED`` — zero overhead when off). A daemon thread wakes
every poll interval; when armed work exists and no progress has happened
within the timeout, it declares a hang ONCE per stall episode: dumps all
Python thread stacks plus the black box (observability/blackbox.py),
bumps ``paddle_tpu_watchdog_fires_total``, calls the registered
``on_hang`` callback, and — only with ``FLAGS_watchdog_abort`` — aborts
the process so a supervisor restarts it instead of leaving it wedged.

The timeout defaults to a multiple of telemetry's observed p95 step time
(a job whose steps take 50ms should scream after seconds, a 30s-step
pretrain after minutes), falling back to 300s when telemetry has no
window yet; ``FLAGS_watchdog_timeout`` pins it explicitly.
"""

import os
import threading
import time

from paddle_tpu.observability import lock_witness
from paddle_tpu.observability.metrics_registry import REGISTRY

__all__ = [
    "ENABLED", "start", "stop", "arm", "disarm", "progress",
    "effective_timeout", "is_running", "last_hang", "suspend",
    "register_on_hang", "unregister_on_hang",
]

ENABLED = False

# auto-timeout shape: max(p95 * _AUTO_MULT, _AUTO_MIN), else _AUTO_DEFAULT
_AUTO_MULT = 30.0
_AUTO_MIN = 10.0
_AUTO_DEFAULT = 300.0

_lock = lock_witness.make_lock("observability.watchdog")
_armed = {}              # token -> {"tag", "t_armed", "reported", "scale"}
_token_counter = [0]
_state = {
    "thread": None,
    "stop": None,        # threading.Event of the running thread
    "timeout": None,     # explicit override (start arg); None = flag/auto
    "on_hang": None,
    "abort": None,       # None = follow FLAGS_watchdog_abort
    "last_hang": None,
}

_on_hang_extra = []  # registered callbacks, called AFTER start()'s on_hang


def register_on_hang(fn):
    """Add a hang callback without displacing ``start(on_hang=...)``'s —
    how TrainSession banks an emergency checkpoint before
    ``FLAGS_watchdog_abort`` kills the process. Returns ``fn`` (the
    deregistration handle)."""
    with _lock:
        _on_hang_extra.append(fn)
    return fn


def unregister_on_hang(fn):
    # Timed acquire [C003]: reachable from TrainSession's SIGTERM
    # handler via close(), which may have interrupted the very thread
    # that holds _lock; a leaked callback beats a hung teardown.
    if _lock.acquire(timeout=1.0):
        try:
            try:
                _on_hang_extra.remove(fn)
            except ValueError:
                pass
        finally:
            _lock.release()


_fires = REGISTRY.counter(
    "paddle_tpu_watchdog_fires_total", "hangs declared by the watchdog")
_stalled_gauge = REGISTRY.gauge(
    "paddle_tpu_watchdog_stalled", "1 while a declared hang is unresolved")


def effective_timeout():
    """Explicit start() timeout > FLAGS_watchdog_timeout > auto from
    telemetry's p95 step time > 300s."""
    if _state["timeout"] and _state["timeout"] > 0:
        return float(_state["timeout"])
    from paddle_tpu import flags

    try:
        flag = float(flags.get("watchdog_timeout"))
    except (KeyError, TypeError, ValueError):
        flag = 0.0
    if flag > 0:
        return flag
    from paddle_tpu.observability import telemetry

    p95_ms = telemetry.step_stats().get("p95_ms")
    if p95_ms:
        return max(p95_ms / 1e3 * _AUTO_MULT, _AUTO_MIN)
    return _AUTO_DEFAULT


def arm(tag="work", scale=1):
    """Mark blocking work in flight; returns a token for :func:`disarm`.
    Callers guard on ``ENABLED``. Each token carries its own clock
    (``t_armed``): a process that sat idle for an hour is NOT instantly
    hung when the next step starts, and one wedged token cannot be
    absolved by other threads finishing their own work. ``scale``
    multiplies the timeout for THIS token — a run_multi_step dispatch of
    K steps legitimately blocks ~K times longer than the per-step p95
    the auto timeout is derived from."""
    with _lock:
        _token_counter[0] += 1
        token = _token_counter[0]
        _armed[token] = {"tag": tag, "t_armed": time.monotonic(),
                         "reported": False, "scale": max(1, int(scale))}
    return token


def disarm(token):
    """The armed work completed (or raised). Removes ONLY this token —
    a concurrent serving thread finishing its request must not reset the
    clock of another thread's wedged fetch."""
    with _lock:
        _armed.pop(token, None)
    _stalled_gauge.set(0)


def progress(token=None):
    """A liveness heartbeat without disarming. With ``token``, refresh
    that work unit's clock (multi-phase work that IS advancing); without
    one, an explicit whole-process heartbeat refreshing every armed
    token."""
    now = time.monotonic()
    with _lock:
        if token is not None:
            if token in _armed:
                _armed[token]["t_armed"] = now
                _armed[token]["reported"] = False
        else:
            for a in _armed.values():
                a["t_armed"] = now
                a["reported"] = False
    _stalled_gauge.set(0)


def last_hang():
    """The most recent hang report dict, or None (tests, post-mortems)."""
    with _lock:
        return dict(_state["last_hang"]) if _state["last_hang"] else None


_suspended = [0]


class suspend(object):
    """Context manager: no hang is declared while inside. For host work
    that is slow but provably alive — above all a fresh XLA compile,
    which can legitimately run minutes while the step-derived timeout is
    seconds (core/lowering.py wraps executable resolution in this; an
    auto-timeout tuned to 100ms steps must not abort a 60s retrace).
    On exit every armed token's clock restarts, so the suspended
    interval never counts against the work that follows."""

    def __enter__(self):
        with _lock:
            _suspended[0] += 1
        return self

    def __exit__(self, *exc):
        with _lock:
            _suspended[0] -= 1
            now = time.monotonic()
            for a in _armed.values():
                a["t_armed"] = now
        return False


def _fire(stalled, waited, timeout):
    from paddle_tpu.observability import blackbox

    report = {
        "ts": time.time(),
        "waited_s": waited,
        "timeout_s": timeout,
        "stalled": [
            {"tag": a["tag"], "armed_for_s": time.monotonic() - a["t_armed"]}
            for a in stalled
        ],
    }
    try:
        # name the stalled PHASE, not just the thread: when the step
        # observatory is on, each in-flight step's current bracket says
        # whether the hang is input wait, dispatch, device compute, ...
        from paddle_tpu.observability import step_profiler

        phases = step_profiler.inflight() if step_profiler.ENABLED else []
    except Exception:
        phases = []
    if phases:
        report["stalled_phases"] = phases
    with _lock:
        _state["last_hang"] = report
        on_hang = _state["on_hang"]
        abort = _state["abort"]
        extra_cbs = list(_on_hang_extra)
    _fires.inc()
    _stalled_gauge.set(1)
    stacks = blackbox.thread_stacks()
    blackbox.record("watchdog_hang", **{k: v for k, v in report.items()
                                        if k != "ts"})
    dump_path = blackbox.dump(
        reason="watchdog_hang", stacks=False,
        extra={"thread_stacks": stacks, "watchdog": report})
    report["dump_path"] = dump_path
    import logging

    phase_note = ""
    if report.get("stalled_phases"):
        phase_note = "; phase: " + ", ".join(
            "%s %.1fs" % (p["phase"], p["phase_age_s"])
            for p in report["stalled_phases"])
    logging.getLogger("paddle_tpu.observability.watchdog").error(
        "watchdog: no progress for %.1fs (timeout %.1fs); stalled: %s%s; "
        "black box: %s", waited, timeout,
        ", ".join(s["tag"] for s in report["stalled"]), phase_note,
        dump_path)
    for cb in [on_hang] + extra_cbs:
        if cb is None:
            continue
        try:
            cb(report)
        except Exception:
            pass
    if abort is None:
        from paddle_tpu import flags

        try:
            abort = bool(flags.get("watchdog_abort"))
        except KeyError:  # pragma: no cover
            abort = False
    if abort:
        # os.abort → SIGABRT: the blackbox signal handler already wrote
        # the dump; the supervisor sees a signal death, not a clean exit
        os.abort()


def _loop(stop_event):
    while not stop_event.wait(_poll_interval()):
        with _lock:
            if not _armed or _suspended[0]:
                continue
        timeout = effective_timeout()  # outside the lock: imports flags
        with _lock:
            # per-token aging: a hang is an ARMED unit of work older
            # than its (scale-adjusted) timeout, regardless of what
            # other threads are getting done — and each token is
            # reported ONCE per stall episode (a progress() on it
            # re-arms the report)
            now = time.monotonic()
            stalled = []
            worst = 0.0
            for a in _armed.values():
                age = now - a["t_armed"]
                worst = max(worst, age)
                if age > timeout * a["scale"] and not a["reported"]:
                    a["reported"] = True
                    stalled.append(dict(a))
        if stalled:
            _fire(stalled, worst, timeout)


def _poll_interval():
    try:
        return max(0.05, min(effective_timeout() / 4.0, 1.0))
    except Exception:
        return 1.0


def is_running():
    t = _state["thread"]
    return t is not None and t.is_alive()


def start(timeout=None, on_hang=None, abort=None):
    """Start the watchdog daemon thread (idempotent; re-calling updates
    timeout/on_hang/abort). ``timeout`` in seconds overrides the flag and
    the auto heuristic; ``abort=None`` follows ``FLAGS_watchdog_abort``."""
    global ENABLED
    with _lock:
        _state["timeout"] = timeout
        _state["on_hang"] = on_hang
        _state["abort"] = abort
    ENABLED = True
    if is_running():
        return _state["thread"]
    stop_event = threading.Event()
    t = threading.Thread(target=_loop, args=(stop_event,),
                         name="paddle-tpu-watchdog", daemon=True)
    _state["stop"] = stop_event
    _state["thread"] = t
    t.start()
    return t


def stop():
    """Stop the thread and disable the executor hooks."""
    global ENABLED
    ENABLED = False
    ev, t = _state["stop"], _state["thread"]
    if ev is not None:
        ev.set()
    if t is not None and t.is_alive():
        t.join(timeout=2.0)
    _state["thread"] = None
    _state["stop"] = None
    with _lock:
        _armed.clear()
    _stalled_gauge.set(0)


def _init_from_flags():
    from paddle_tpu import flags

    try:
        if flags.get("watchdog"):
            start()
    except KeyError:  # pragma: no cover
        pass


_init_from_flags()
