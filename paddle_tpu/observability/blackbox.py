"""Crash black box: bounded flight-event ring, dumped on failure.

PR 2's flight recorder makes the healthy path self-describing; this
module covers the moments that end in an opaque traceback or no output at
all. A bounded ring collects compact flight events as they happen
(executor dispatches with feed specs and fetch lists, exceptions, notes
from other subsystems); on an unhandled executor/Predictor exception, a
fatal signal (SIGTERM/SIGABRT), a watchdog-declared hang, or an explicit
:func:`dump`, the ring — together with the telemetry step tail, the
recompile-explainer events, the lint fold of those events, the NaN
diagnostic if one was recorded, a full flag snapshot and (optionally) all
Python thread stacks — is written to one JSON file an engineer can read
post-mortem. The reference's closest analogue is glog's FATAL stack dump
plus FLAGS_call_stack_level; the design here follows the aircraft
flight-recorder discipline the TensorFlow system paper frames as table
stakes for production training.

Overhead contract: executors guard every hook on the module-level bool
``ENABLED`` (one attribute load); with ``FLAGS_blackbox_path`` unset the
hot path is untouched and no handler is installed.
"""

import atexit
import collections
import json
import os
import sys
import threading

from paddle_tpu.observability import lock_witness
import time
import traceback

__all__ = [
    "ENABLED", "enable", "disable", "record", "record_dispatch",
    "record_exception", "record_nan_diagnostic", "record_oom_diagnostic",
    "dump", "snapshot", "thread_stacks", "events", "path",
    "install_handlers", "reset", "guard",
]

ENABLED = False

_RING_CAP = 512
_TAIL = 64           # telemetry/explainer records carried into a dump

_lock = lock_witness.make_lock("observability.blackbox")
_events = collections.deque(maxlen=_RING_CAP)
_path = [""]
_nan_diagnostic = [None]
_oom_diagnostic = [None]
_failure_dumped = [False]    # a failure dump exists: the atexit/benign
                             # dump must not overwrite the crash artifact

# Once-per-exception dump dedup marks the exception OBJECT itself:
# nested handlers (Predictor wrapping Executor, then sys.excepthook) see
# the same instance and skip the re-write. Not id()-based — CPython
# reuses a freed exception's address, and an id match would silently
# skip a NEW crash's dump. (Exceptions aren't weakref-able, so an
# attribute is the only per-object mark available.)
_DUMPED_ATTR = "_paddle_tpu_blackbox_dumped"


def _already_dumped(exc):
    return getattr(exc, _DUMPED_ATTR, False)


def _mark_dumped(exc):
    try:
        setattr(exc, _DUMPED_ATTR, True)
    except Exception:
        pass  # __slots__-only exception: a double dump beats a missing one
_handlers_installed = [False]
_prev_excepthook = [None]
_prev_signal = {}


def path():
    """The armed dump path ('' when disabled)."""
    return _path[0]


def enable(dump_path, handlers=True):
    """Arm the black box: record events, dump to ``dump_path`` on
    failure. ``handlers=True`` also chains ``sys.excepthook`` and the
    fatal-signal handlers (SIGTERM/SIGABRT) so crashes outside any
    executor still leave a dump."""
    global ENABLED
    if not dump_path:
        return disable()
    _path[0] = str(dump_path)
    ENABLED = True
    if handlers:
        install_handlers()
    return _path[0]


def disable():
    global ENABLED
    ENABLED = False
    _path[0] = ""
    return ""


def reset():
    """Drop recorded events and the NaN/OOM diagnostics (tests)."""
    with _lock:
        _events.clear()
        _nan_diagnostic[0] = None
        _oom_diagnostic[0] = None
        _failure_dumped[0] = False


def record(kind, **fields):
    """Append one compact flight event to the ring. Callers guard on
    ``ENABLED``; calling directly always records. The append is a TIMED
    acquire [C003]: record() is called straight from the preemption
    signal handlers (TrainSession, DecodeSnapshotManager), which run on
    the main thread and may have interrupted it mid-append — a blocking
    acquire there deadlocks the process short of dying. On timeout the
    event is dropped; a lost flight event beats a hung teardown."""
    ev = {"ts": time.time(), "kind": kind}
    ev.update(fields)
    if _lock.acquire(timeout=1.0):
        try:
            _events.append(ev)
        finally:
            _lock.release()
    return ev


def record_dispatch(origin, feed_specs=None, fetch_names=None,
                    fingerprint=None, **extra):
    """One executor/Predictor dispatch about to run: the event a crash
    dump's LAST entry points at when the step itself dies."""
    return record(
        "dispatch", origin=origin,
        feed_specs=sorted(
            (n, list(s), d) for n, (s, d) in (feed_specs or {}).items()),
        fetch_names=list(fetch_names or ()),
        fingerprint=str(fingerprint)[:16] if fingerprint else None,
        **extra)


def record_exception(origin, exc, dump_now=True, stacks=True):
    """An exception escaping ``origin``. Records the event always; writes
    the dump once per exception object (nested wrappers re-record but
    don't re-write). Crash dumps default to carrying thread stacks —
    the cost is paid only on the failure path."""
    ev = record(
        "exception", origin=origin,
        exc_type=type(exc).__name__,
        exc_message=str(exc)[:2000],
        traceback=traceback.format_exception(
            type(exc), exc, exc.__traceback__)[-12:],
    )
    if dump_now and ENABLED and not _already_dumped(exc):
        _mark_dumped(exc)
        dump(reason="unhandled_exception:%s" % origin, stacks=stacks)
    return ev


def record_nan_diagnostic(diag):
    """File the NaN-provenance finding (an analysis Diagnostic or its
    dict form) so dumps and tools/blackbox_dump.py can report — and CI
    can gate on — the blamed op."""
    d = diag.as_dict() if hasattr(diag, "as_dict") else dict(diag)
    with _lock:
        _nan_diagnostic[0] = d
    record("nan_diagnostic", **d)
    return d


def record_oom_diagnostic(diag, top_holders=None, predicted_peak_bytes=None,
                          live_bytes=None):
    """File the M001 OOM finding (observability/memory.py) with the
    ledger evidence — top live-buffer holders and the predicted peak —
    so the dump answers 'who held the memory' without a live process.
    tools/blackbox_dump.py exits 4 on it (distinct from NaN's 3)."""
    d = diag.as_dict() if hasattr(diag, "as_dict") else dict(diag)
    d["top_holders"] = list(top_holders or ())
    d["predicted_peak_bytes"] = predicted_peak_bytes
    d["live_bytes"] = live_bytes
    with _lock:
        _oom_diagnostic[0] = d
    record("oom_diagnostic", **d)
    return d


def events():
    with _lock:
        return [dict(e) for e in _events]


def thread_stacks():
    """Formatted stacks of every live Python thread — what the watchdog
    and fatal-signal dumps carry (sys._current_frames is the only
    in-process view of where a hung thread actually is). With the lock
    witness armed, each label carries the named locks that thread holds
    RIGHT NOW — a "hung in acquire" stack plus a "[holds: x]" peer line
    is a root cause, not a symptom."""
    names = {t.ident: t.name for t in threading.enumerate()}
    held = {}
    try:
        from paddle_tpu.observability import lock_witness

        if lock_witness.ENABLED:
            held = lock_witness.held_by_thread()
    except Exception:
        pass  # annotation must never break a crash dump
    out = {}
    for ident, frame in sys._current_frames().items():
        label = "%s(%d)" % (names.get(ident, "thread"), ident)
        if ident in held:
            label += " [holds: %s]" % ", ".join(held[ident])
        out[label] = traceback.format_stack(frame)
    return out


def _read_locked(lock, read, default, timeout):
    """Read shared state under ``lock``. ``timeout=None`` blocks (the
    normal path); otherwise a timed acquire — the SIGNAL-HANDLER path,
    which runs on the main thread between bytecodes and may have
    interrupted that very thread while it HELD the lock (non-reentrant:
    a blocking acquire would deadlock the process instead of letting it
    die). On timeout the component degrades to ``default``; a partial
    dump beats a hung teardown."""
    if timeout is None:
        # conclint: C003 reason=flow-insensitive hit — every handler-context caller passes lock_timeout (the timed branch below); this branch is the ordinary off-handler path
        with lock:
            return read()
    if lock.acquire(timeout=timeout):
        try:
            return read()
        finally:
            lock.release()
    return default


def snapshot(reason="on_demand", stacks=False, extra=None,
             lock_timeout=None):
    """The dump payload as a dict (what :func:`dump` writes). With
    ``lock_timeout`` set, every lock-guarded component is read with a
    timed acquire directly off the backing structures (signal-handler
    safety — see :func:`_read_locked`); components whose lock can't be
    taken degrade to empty."""
    from paddle_tpu import flags
    from paddle_tpu.observability import explain, telemetry

    ring, nan, oom = _read_locked(
        _lock,
        lambda: ([dict(e) for e in _events],
                 dict(_nan_diagnostic[0]) if _nan_diagnostic[0] else None,
                 dict(_oom_diagnostic[0]) if _oom_diagnostic[0] else None),
        ([], None, None), lock_timeout)
    snap = {
        "blackbox_version": 1,
        "ts": time.time(),
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "reason": reason,
        "events": ring,
        "steps": _read_locked(
            telemetry._lock,
            lambda: [dict(r) for r in telemetry._records][-_TAIL:],
            [], lock_timeout),
        "recompiles": _read_locked(
            explain._lock,
            lambda: [dict(e) for e in explain._events][-_TAIL:],
            [], lock_timeout),
        "flags": flags.all_flags(),
        "nan_diagnostic": nan,
        "oom_diagnostic": oom,
    }
    # requests in flight at crash time, by trace id (the forensics
    # question "WHOSE request died here" — resolve the ids against the
    # victim's .traces.jsonl or a surviving peer's ring)
    from paddle_tpu.observability import tracing

    snap["inflight_traces"] = _read_locked(
        tracing._lock,
        lambda: [{"trace_id": t.id, "endpoint": t.endpoint,
                  "origin": t.origin,
                  "age_s": round(time.time() - t.t0, 3),
                  "spans_open": sum(1 for sp in t.spans
                                    if sp["t1"] is None)}
                 for t in tracing._inflight.values()],
        [], lock_timeout)
    # training-plane forensics: the last phase-attributed step records,
    # plus which phase each in-flight step is stuck in RIGHT NOW (the
    # "input wait 12.3s" answer a hang dump exists to give). inflight()
    # reads a plain dict lock-free — safe even from the signal handler.
    from paddle_tpu.observability import step_profiler

    snap["step_profile"] = _read_locked(
        step_profiler._lock,
        lambda: [dict(r) for r in step_profiler._records][-_TAIL:],
        [], lock_timeout)
    try:
        snap["step_inflight"] = step_profiler.inflight()
    except Exception:
        snap["step_inflight"] = []
    try:
        # fold the live explainer log back to lint diagnostics (PR 3) so
        # the dump names the rule behind a recompile storm; skipped in
        # the timed mode (it re-acquires explain's lock internally)
        if lock_timeout is None:
            from paddle_tpu.analysis import lint_events

            snap["lint_events"] = [d.as_dict() for d in lint_events()]
        else:
            snap["lint_events"] = []
    except Exception:
        snap["lint_events"] = []
    if stacks:
        snap["thread_stacks"] = thread_stacks()
    if extra:
        snap.update(extra)
    return snap


def dump(dump_path=None, reason="on_demand", stacks=False, extra=None,
         lock_timeout=None):
    """Write the black box JSON (atomic rename so a reader never sees a
    torn file). Returns the path, or None when no path is configured.
    Never raises — a broken dump must not mask the original failure."""
    dump_path = dump_path or _path[0]
    if not dump_path:
        return None
    try:
        snap = snapshot(reason=reason, stacks=stacks, extra=extra,
                        lock_timeout=lock_timeout)
        tmp = "%s.tmp.%d" % (dump_path, os.getpid())
        with open(tmp, "w") as f:
            json.dump(snap, f, sort_keys=True, default=repr)
        os.replace(tmp, dump_path)
        if reason not in ("atexit", "on_demand"):
            _failure_dumped[0] = True
        return dump_path
    except Exception:
        return None


class guard(object):
    """The forensics shell every blocking entry point wears, in ONE
    place: arms the watchdog for the duration (unless ``arm=False`` —
    serving layers whose inner executor call already arms) and records
    any escaping exception with this origin. Class-based, slot-bound:
    one small allocation per call, no generator frames — the hot path
    with both subsystems off stays two module-bool loads::

        with blackbox.guard("Executor.run"):
            ...blocking work...
    """

    __slots__ = ("origin", "arm", "scale", "_token")

    def __init__(self, origin, arm=True, scale=1):
        self.origin = origin
        self.arm = arm
        self.scale = scale  # timeout multiplier (K-step dispatches)
        self._token = None

    def __enter__(self):
        if self.arm:
            from paddle_tpu.observability import watchdog

            if watchdog.ENABLED:
                self._token = watchdog.arm(self.origin, scale=self.scale)
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None and ENABLED:
            record_exception(self.origin, exc)
        if self._token is not None:
            from paddle_tpu.observability import watchdog

            watchdog.disarm(self._token)
        return False


# -- failure hooks -----------------------------------------------------------

def _excepthook(exc_type, exc, tb):
    try:
        if ENABLED and not _already_dumped(exc):
            _mark_dumped(exc)
            record("exception", origin="sys.excepthook",
                   exc_type=exc_type.__name__, exc_message=str(exc)[:2000],
                   traceback=traceback.format_exception(
                       exc_type, exc, tb)[-12:])
            dump(reason="unhandled_exception:sys.excepthook", stacks=True)
    finally:
        prev = _prev_excepthook[0] or sys.__excepthook__
        prev(exc_type, exc, tb)


def _signal_handler(signum, frame):
    import signal as _signal

    try:
        name = _signal.Signals(signum).name
    except Exception:
        name = str(signum)
    # This runs ON the main thread, possibly having interrupted it while
    # it held one of the observability locks — every lock here is a
    # timed acquire (see _read_locked), never a blocking one: a dump
    # with a degraded component beats a process that can no longer die
    # on SIGTERM.
    ev = {"ts": time.time(), "kind": "fatal_signal", "signal": name}
    if _lock.acquire(timeout=1.0):
        try:
            _events.append(ev)
        finally:
            _lock.release()
    dump(reason="fatal_signal:%s" % name, stacks=True, lock_timeout=1.0)
    # restore the pre-install disposition and re-raise so the process
    # still dies BY the signal (exit status and core behavior preserved —
    # supervisors keyed on "killed by SIGTERM" must not see a clean exit)
    prev = _prev_signal.get(signum, _signal.SIG_DFL)
    _signal.signal(signum, prev if callable(prev) or prev in (
        _signal.SIG_DFL, _signal.SIG_IGN) else _signal.SIG_DFL)
    os.kill(os.getpid(), signum)


def install_handlers():
    """Chain sys.excepthook and the catchable fatal signals
    (SIGTERM/SIGABRT; SIGINT is left to KeyboardInterrupt). Idempotent
    per handler: a first call from a NON-main thread (where
    signal.signal raises ValueError) chains only the excepthook and
    leaves the signals un-latched, so a later main-thread call still
    installs them — one early worker-thread enable() must not
    permanently disable fatal-signal dumps.

    Known tradeoff (any Python-level signal handler has it): the handler
    runs only when the main thread re-enters the interpreter loop, so a
    main thread wedged inside a non-interruptible C call (a dead-device
    ``block_until_ready``) neither dumps nor dies on SIGTERM — pair the
    black box with the watchdog (``FLAGS_watchdog_abort``) for hangs,
    and rely on the supervisor's SIGKILL escalation as the backstop."""
    if not _handlers_installed[0]:
        _handlers_installed[0] = True
        _prev_excepthook[0] = sys.excepthook
        sys.excepthook = _excepthook
    import signal as _signal

    for sig in (_signal.SIGTERM, _signal.SIGABRT):
        if sig in _prev_signal:
            continue  # already latched (only on success)
        try:
            _prev_signal[sig] = _signal.signal(sig, _signal_handler)
        except (ValueError, OSError):  # non-main thread / exotic platform
            pass


@atexit.register
def _dump_at_exit():
    # a process that armed the box but never crashed still leaves its
    # final flight picture (cheap; the file is tiny and atomic). NEVER
    # over a failure dump: the crash artifact — its reason line and its
    # thread stacks — must survive interpreter shutdown untouched.
    try:
        if ENABLED and not _failure_dumped[0]:
            dump(reason="atexit")
    except Exception:
        pass


def _init_from_flags():
    from paddle_tpu import flags

    try:
        p = flags.get("blackbox_path")
    except KeyError:  # pragma: no cover - flag table always has it
        p = ""
    if p:
        enable(p)


_init_from_flags()
