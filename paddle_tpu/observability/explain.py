"""Recompile explainer: "why did it retrace" as one structured log line.

Every executable-cache key is a tuple of independent components (program
structure fingerprint, feed shape/dtype specs, fetch set, scope
signature, trace-time flags, device). When a run misses every in-memory
cache layer and pays a fresh XLA trace, the executor calls
:func:`record_compile` with those components; the explainer diffs them
against the NEAREST previously-compiled entry (most components in
common) and emits a structured event naming exactly which component(s)
forced the recompile — the debugging session TensorFlow-era retrace
hunts used to cost, reduced to reading a log line.

Events go to the ``paddle_tpu.observability.explain`` logger as JSON, to
the metrics registry (``paddle_tpu_recompiles_total{changed=...}``), and
to a bounded in-process list (:func:`events`) for tests and tooling.
Always on: the cost is one dict diff per *compile*, never per step.
"""

import collections
import json
import logging
import threading
import time

from paddle_tpu.observability import lock_witness
from paddle_tpu.observability.metrics_registry import REGISTRY

__all__ = ["record_compile", "events", "reset", "COMPONENTS",
           "COMPONENT_LINT_RULES"]

logger = logging.getLogger("paddle_tpu.observability.explain")

# Diffable cache-key components, in blame-priority order: when several
# differ vs. the nearest entry, all are reported, first is the headline.
COMPONENTS = ("program", "feed_specs", "fetch_names", "scope_signature",
              "flags", "device", "mode")

# Blamed component -> the retrace-hazard lint rule(s) (analysis/lint.py)
# that statically predict that kind of miss. Events carry the ids so a
# hot recompile loop in a log names the rule to run the linter for:
#   feed_specs   churn <- L001 dynamic-feed-shape
#   program      churn <- L002 literal-scalar-attr (attr literals re-baked
#                 per step) / L003 nondeterministic-names (fingerprint
#                 drifts with unique_name counters)
#   fetch_names  churn <- L004 fetch-list-churn
COMPONENT_LINT_RULES = {
    "feed_specs": ("L001",),
    "program": ("L002", "L003"),
    "fetch_names": ("L004",),
}

_MAX_EVENTS = 512
# Bounded diff window: nearest-entry search is O(len) under the lock on
# every compile, and this module is always on — a serving process
# compiling many distinct feed shapes must not accumulate component
# dicts forever. 256 recent compiles is plenty of context to blame
# against; older ones age out (a miss against an aged-out entry reads
# as first_compile-ish blame on whichever components differ).
_MAX_ENTRIES = 256

_lock = lock_witness.make_lock("observability.explain")
_entries = collections.deque(maxlen=_MAX_ENTRIES)  # recent compile keys
_events = []     # bounded structured event log
_compile_count = [0]

_recompiles = REGISTRY.counter(
    "paddle_tpu_recompiles_total",
    "fresh XLA traces by blamed cache-key component",
    labels=("changed",))


def _canon(components):
    out = {}
    for k in COMPONENTS:
        v = components.get(k)
        if isinstance(v, (set, frozenset)):
            v = tuple(sorted(v))
        elif isinstance(v, list):
            v = tuple(v)
        out[k] = v
    return out


def _describe_change(key, old, new):
    """Human detail for the headline components; terse repr otherwise."""
    if key == "feed_specs":
        old_d, new_d = dict(old or ()), dict(new or ())
        parts = []
        for name in sorted(set(old_d) | set(new_d)):
            a, b = old_d.get(name), new_d.get(name)
            if a != b:
                parts.append("%s: %s -> %s" % (name, a, b))
        return "; ".join(parts) or "feed set changed"
    if key == "flags":
        old_d, new_d = dict(old or ()), dict(new or ())
        return "; ".join(
            "%s: %r -> %r" % (n, old_d.get(n), new_d.get(n))
            for n in sorted(set(old_d) | set(new_d))
            if old_d.get(n) != new_d.get(n))
    if key == "program":
        return "program structure changed (fingerprint %s -> %s)" % (
            str(old)[:12], str(new)[:12])
    if key == "scope_signature":
        old_s, new_s = set(old or ()), set(new or ())
        added, gone = sorted(new_s - old_s), sorted(old_s - new_s)
        bits = []
        if added:
            bits.append("vars added: %s" % ", ".join(added[:6]))
        if gone:
            bits.append("vars removed: %s" % ", ".join(gone[:6]))
        return "; ".join(bits) or "scope signature changed"
    return "%r -> %r" % (old, new)


def record_compile(components, forced=False):
    """One fresh XLA trace. ``components`` maps COMPONENTS keys to the
    new cache-key pieces; ``forced`` marks use_program_cache=False
    bypasses (nothing to blame — the caller asked). Returns the event."""
    comp = _canon(components)
    now = time.time()
    with _lock:
        nearest = None
        nearest_score = -1
        for entry in _entries:
            score = sum(1 for k in COMPONENTS if entry[k] == comp[k])
            if score > nearest_score:
                nearest, nearest_score = entry, score
        _entries.append(comp)
        _compile_count[0] += 1
        n_compiles = _compile_count[0]
    if forced:
        changed = ["forced_refresh"]
        detail = {"forced_refresh": "use_program_cache=False bypass"}
    elif nearest is None:
        changed = ["first_compile"]
        detail = {"first_compile":
                  "no prior executable in this process to compare against"}
    else:
        changed = [k for k in COMPONENTS if nearest[k] != comp[k]]
        detail = {k: _describe_change(k, nearest[k], comp[k])
                  for k in changed}
        if not changed:
            # identical key components but the in-memory registry missed:
            # an LRU eviction or a purged cache — name that, don't blame
            # the program
            changed = ["cache_evicted"]
            detail = {"cache_evicted":
                      "key matches a prior compile; the in-memory entry "
                      "was evicted or purged"}
    lint_rules = [r for c in changed
                  for r in COMPONENT_LINT_RULES.get(c, ())]
    event = {
        "event": "fresh_compile",
        "ts": now,
        "changed": changed,
        "detail": detail,
        "lint_rules": lint_rules,
        "lint_rule": lint_rules[0] if lint_rules else None,
        "program_fingerprint": str(comp.get("program"))[:16],
        "mode": comp.get("mode"),
        "device": comp.get("device"),
        "compiles_so_far": n_compiles,
    }
    with _lock:
        _events.append(event)
        del _events[:-_MAX_EVENTS]
    _recompiles.inc(changed=changed[0])
    logger.info("recompile: %s", json.dumps(event, sort_keys=True))
    return event


def events():
    """The structured event log (oldest first, bounded)."""
    with _lock:
        return [dict(e) for e in _events]


def reset():
    """Forget prior compiles and events (tests)."""
    with _lock:
        _entries.clear()
        del _events[:]
        _compile_count[0] = 0
