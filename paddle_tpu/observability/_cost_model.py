"""Loader for tools/hlo_cost_model.py (the repo's jaxpr FLOP counter).

``tools/`` is deliberately not a package — its scripts insert the repo
root on sys.path and parse argv at import-adjacent points, so a plain
``import`` from library code is wrong. This loads the module once by
file path and caches it; telemetry reuses its ``optimize_jaxpr`` /
``sum_flops_recursive`` instead of maintaining a second FLOP table.
"""

import importlib.util
import os
import threading

from paddle_tpu.observability import lock_witness

_lock = lock_witness.make_lock("observability.cost_model")
_mod = None


def load():
    global _mod
    if _mod is None:
        with _lock:
            if _mod is None:
                path = os.path.join(
                    os.path.dirname(os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__)))),
                    "tools", "hlo_cost_model.py")
                spec = importlib.util.spec_from_file_location(
                    "paddle_tpu_hlo_cost_model", path)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
                _mod = mod
    return _mod
