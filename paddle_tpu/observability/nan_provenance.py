"""NaN provenance: blame the FIRST op that produced a non-finite value.

``FLAGS_check_nan_inf``'s on-device scan (executor.py:_nan_check_start)
says *that* a step went non-finite, cheaply — one fused reduction, one
[n] bool vector to the host. It cannot say *where*: by the time the scan
trips, the NaN has flowed through the whole step. The reference checked
every op's outputs every step (operator.cc:754) — exact but ruinously
slow under XLA, where per-op sync would defeat whole-program fusion.

This module gets exactness without the steady-state cost: when the scan
trips, the executor hands over the step's *inputs* (a pre-step snapshot
of the donated mutable state, the feeds, the PRNG key — the step function
is pure, so these reproduce it bit-for-bit) and the program is replayed
HERE, op by op, eagerly, through the same registry lowerings the compiled
step traced (core/lowering.py:BlockLowerer). After each op, its outputs
are pulled to the host and checked; the first op with a non-finite output
while all its inputs were finite is the culprit. The finding is a
PR 3 :class:`analysis.diagnostics.Diagnostic` — rule ``N001``, severity
error, block/op location, involved vars, and a fix hint keyed on the op
type — so tools, tests and the black box consume it structurally.

Cost model: zero until a trip (the snapshot is one device-side copy of
the mutable state per step, only while ``FLAGS_check_nan_inf`` is on);
the replay itself is a per-op interpreter pass over one step — seconds,
paid once, on the way to an exception that was going to kill the job
anyway.
"""

import numpy as np

__all__ = ["NonFiniteError", "blame_step", "blame_multi_step",
           "RULE", "RULE_NAME"]

RULE = "N001"
RULE_NAME = "non-finite-output"

# op type -> one actionable sentence (the Diagnostic hint)
_HINTS = {
    "log": "log of a non-positive input — clip the input away from zero "
           "(e.g. x = clip(x, eps, inf)) or use a fused numerically-stable "
           "composite",
    "sqrt": "sqrt of a negative input — clip or square-then-sqrt",
    "rsqrt": "rsqrt of a non-positive input — add an epsilon inside the "
             "root (rsqrt(x + eps))",
    "elementwise_div": "division by zero — add an epsilon to the "
                       "denominator",
    "divide": "division by zero — add an epsilon to the denominator",
    "exp": "exp overflow — rescale the input or compute in log-space",
    "pow": "pow produced inf/nan — check for negative base with "
           "fractional exponent or overflow",
    "cross_entropy": "log(0) inside cross entropy — label-smooth or clip "
                     "the probabilities",
    "softmax_with_cross_entropy": "extreme logits — clip logits, lower "
                                  "the learning rate, or enable loss "
                                  "scaling under AMP",
}
_DEFAULT_HINT = ("inspect this op's inputs at the reported step; common "
                 "fixes: gradient clipping, a lower learning rate, epsilon "
                 "guards, or AMP loss scaling")


class NonFiniteError(RuntimeError):
    """The FLAGS_check_nan_inf error, upgraded with provenance: carries
    the structured :class:`Diagnostic` in ``.diagnostic`` (None when the
    replay could not localize the op). The message keeps the plain
    scanner's "NaN/Inf detected" prefix so existing handlers match."""

    def __init__(self, message, diagnostic=None):
        super(NonFiniteError, self).__init__(message)
        self.diagnostic = diagnostic


def _nonfinite_names(env, names):
    """The subset of ``names`` whose env value is a non-finite float
    array (host-syncs each checked value — replay-only path)."""
    bad = []
    for n in names:
        if not n or n not in env:
            continue
        try:
            arr = np.asarray(env[n])
        except Exception:
            continue
        if np.issubdtype(arr.dtype, np.floating) and not np.all(
                np.isfinite(arr)):
            bad.append(n)
    return bad


def _make_diagnostic(op_idx, op, bad_names, step_index=None):
    from paddle_tpu.analysis.diagnostics import Diagnostic

    where = ("" if step_index is None
             else " (step %d of the multi-step dispatch)" % step_index)
    return Diagnostic(
        RULE, RULE_NAME, "error",
        "op '%s' produced the first non-finite value%s in output(s) %s "
        "(all of its inputs were finite)"
        % (op.type, where, ", ".join(repr(n) for n in bad_names)),
        block_idx=0, op_idx=op_idx, op_type=op.type,
        var_names=tuple(bad_names),
        hint=_HINTS.get(op.type, _DEFAULT_HINT),
    )


def _input_diagnostic(bad_names, kind):
    from paddle_tpu.analysis.diagnostics import Diagnostic

    return Diagnostic(
        RULE, RULE_NAME, "error",
        "step %s already contained non-finite value(s) before any op ran: "
        "%s" % (kind, ", ".join(repr(n) for n in bad_names)),
        block_idx=0, var_names=tuple(bad_names),
        hint="the corruption happened upstream (a previous step's update "
             "or the input pipeline) — check the feed data and the prior "
             "step's optimizer update",
    )


def _replay(program, state, feeds, key, is_test, platform, step_index):
    """One eager op-by-op pass. Returns (diagnostic_or_None, final_env)."""
    from paddle_tpu.core.lowering import BlockLowerer, _AMBIENT_PLATFORM

    env = {}
    env.update(state)
    env.update(feeds)
    bad = _nonfinite_names(env, list(feeds))
    if bad:
        return _input_diagnostic(bad, "feeds"), env
    bad = _nonfinite_names(env, list(state))
    if bad:
        return _input_diagnostic(bad, "state"), env
    lowerer = BlockLowerer(program, 0, is_test=is_test)
    _AMBIENT_PLATFORM.append(platform)
    try:
        for idx, op in enumerate(lowerer.block.ops):
            lowerer.lower_op(op, env, key)
            bad = _nonfinite_names(env, op.output_arg_names())
            if bad:
                return _make_diagnostic(idx, op, bad,
                                        step_index=step_index), env
    finally:
        _AMBIENT_PLATFORM.pop()
    return None, env


def blame_step(program, state, feeds, key, is_test=False, platform=None,
               step_index=None):
    """Replay ONE step eagerly and localize the first non-finite output.

    ``state``/``feeds``/``key`` must be the step's actual inputs (the
    executor snapshots donated state before dispatch). Returns a
    Diagnostic, or None when the replay stays finite (e.g. the scan
    tripped on a value this block never touches). Never raises — a
    failed replay must not mask the original scanner error. Runs under
    ``watchdog.suspend()``: a minutes-long per-op replay on a big
    program is slow forensics, not a hang."""
    from paddle_tpu.observability import watchdog

    try:
        with watchdog.suspend():
            diag, _env = _replay(program, state, feeds, key, is_test,
                                 platform, step_index)
        return diag
    except Exception:
        return None


def blame_multi_step(program, state, feeds, key, steps, mutable_state,
                     is_test=False, platform=None):
    """Replay up to ``steps`` iterations of a run_multi_step dispatch
    (per-step key = fold_in(key, i) — ALSO for steps == 1, matching
    MultiStepProgram's scan body; mutable state chains between
    iterations) and blame the first non-finite op across them."""
    import jax

    from paddle_tpu.observability import watchdog

    state = dict(state)
    try:
        with watchdog.suspend():
            for i in range(int(steps)):
                step_key = jax.random.fold_in(key, i)
                diag, env = _replay(program, state, feeds, step_key,
                                    is_test, platform, step_index=i)
                if diag is not None:
                    return diag
                for n in mutable_state:
                    if n in env:
                        state[n] = env[n]
    except Exception:
        return None
    return None


def enrich_and_raise(base_exc, program, state, feeds, key, steps=1,
                     mutable_state=(), is_test=False, platform=None,
                     multi=False):
    """The executor's trip path: run the blame replay, file the finding
    with the black box + registry, and raise :class:`NonFiniteError`
    chained on the scanner's error. ``state`` is the pre-step snapshot
    (frozen state + copies of the donated mutable state). ``multi``
    marks a run_multi_step dispatch — the branch can't key on
    ``steps > 1`` because even steps == 1 runs through the scan body's
    ``fold_in(key, 0)``, and replaying with the raw key would diverge
    the RNG stream on programs with dropout/random ops."""
    from paddle_tpu.observability import blackbox
    from paddle_tpu.observability.metrics_registry import REGISTRY

    if multi:
        diag = blame_multi_step(program, state, feeds, key, steps,
                                mutable_state, is_test=is_test,
                                platform=platform)
    else:
        diag = blame_step(program, state, feeds, key, is_test=is_test,
                          platform=platform)
    REGISTRY.counter(
        "paddle_tpu_nan_trips_total",
        "FLAGS_check_nan_inf trips, by whether provenance localized them",
        labels=("blamed",),
    ).inc(blamed="yes" if diag is not None else "no")
    if diag is None:
        raise base_exc
    blackbox.record_nan_diagnostic(diag)
    if blackbox.ENABLED:
        blackbox.dump(reason="nan_diagnostic")
    raise NonFiniteError(
        "%s\n%s\n        hint: %s" % (str(base_exc), str(diag).split(
            "\n")[0], diag.hint),
        diagnostic=diag) from base_exc
